"""Mesh-level drivers: dense-in/dense-out distributed solves.

The user-facing layer tying DistMatrix + the shard_map kernels together —
the analogue of the reference drivers (src/posv.cc, src/gesv_nopiv path,
src/gemm.cc) run with a 2D block-cyclic distribution, with
``Matrix::fromScaLAPACK``-style construction replaced by ``from_dense``.

Note the padding contract: factorization inputs are padded with an identity
diagonal block (dist.from_dense(diag_pad_one=True)) so padded runs stay
exact — diag(A, I) factors to diag(L, I) and the pad never mixes with data.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..obs import instrument
from ..types import Diag, Op, Option, Options, Uplo, get_option
from .dist import DistMatrix, from_dense, to_dense
from .dist_chol import potrf_dist
from .dist_lu import (
    getrf_nopiv_dist,
    getrf_pp_dist,
    getrf_tntpiv_dist,
    permute_rows_dist,
)
from .dist_qr import geqrf_dist, unmqr_dist
from .dist_trsm import trsm_dist
from .summa import gemm_summa

_DEFAULT_NB = 256


def _la(opts: Optional[Options]):
    """Raw Option.Lookahead value from a driver ``opts`` mapping — the
    panel-prefetch / deferred-update pipeline depth every mesh k-loop
    consumes (comm.prefetch_bcast / comm.pipelined_factor_loop).  May be
    None (absent or explicitly unset): ``comm.la_depth`` inside each
    kernel is the single authority that maps None to the option default
    (1, as in the reference) and clamps to the trip count."""
    return get_option(opts, Option.Lookahead)


def _bi(opts: Optional[Options]):
    """Raw Option.BcastImpl value from a driver ``opts`` mapping — the
    tileBcast lowering every mesh k-loop consumes.  May be None:
    ``comm.resolve_bcast_impl`` inside each kernel is the single
    authority for the context/env/auto default chain."""
    return get_option(opts, Option.BcastImpl)


def _pi(opts: Optional[Options]):
    """Raw Option.PanelImpl value from a driver ``opts`` mapping — the
    panel-factorization lowering the factor kernels consume (fused
    Pallas panel kernels vs the XLA reference chains).  May be None:
    ``ops.pallas_ops.resolve_panel_impl`` inside each kernel is the
    single authority for the context/env/auto default chain."""
    return get_option(opts, Option.PanelImpl)


def _ui(opts: Optional[Options]):
    """Raw Option.UpdateImpl value from a driver ``opts`` mapping — the
    trailing-update lowering the summa/potrf/LU-nopiv k-loops consume
    (fused Pallas trailing-update kernels vs the XLA bulk einsums).  May
    be None: ``ops.pallas_ops.resolve_update_impl`` inside each kernel
    is the single authority for the context/env/auto default chain."""
    return get_option(opts, Option.UpdateImpl)


def _nm(opts: Optional[Options]):
    """Raw Option.NumMonitor value from a driver ``opts`` mapping — the
    in-carry numerics-gauge switch the factor kernels consume (growth /
    diagonal-margin monitoring, obs/numerics.py).  May be None:
    ``obs.numerics.resolve_num_monitor`` inside each kernel is the
    single authority for the context/env/auto default chain (auto = on
    iff the obs layer is enabled)."""
    return get_option(opts, Option.NumMonitor)


def _ckpt_every(opts: Optional[Options]):
    """Resolved Option.Checkpoint snapshot interval (int) or None (off).
    ``ft.ckpt.resolve_checkpoint`` is the single authority for the
    explicit > SLATE_TPU_CKPT env > off chain; off keeps the drivers on
    the fused kernels untouched (trace-identical, zero overhead)."""
    from ..ft.ckpt import resolve_checkpoint

    return resolve_checkpoint(get_option(opts, Option.Checkpoint, default=None))


def _ft_on(opts: Optional[Options]) -> bool:
    """True when Option.FaultTolerance selects an active ABFT policy.
    Off (the default) keeps this module on the plain kernels with zero
    overhead — results stay bitwise-identical; any active policy routes
    to the checksum-carrying variants in slate_tpu/ft/abft.py (also
    validates the option value, so a typo'd policy fails loudly here
    instead of silently running unprotected)."""
    from ..ft.policy import FtPolicy, resolve_policy

    return resolve_policy(opts) != FtPolicy.Off


def _resilience(opts: Optional[Options]):
    """(ft_on, checkpoint_every), each resolved ONCE per driver call.
    Arming FaultTolerance TOGETHER with Option.Checkpoint is rejected
    loudly: the ABFT kernels are not checkpointed yet, so the
    combination would silently drop snapshotting (and never consult
    kill faults) — fail instead of degrading."""
    ft_on = _ft_on(opts)
    every = _ckpt_every(opts)
    if ft_on and every is not None:
        raise ValueError(
            "Option.FaultTolerance and Option.Checkpoint cannot be "
            "combined (the ABFT kernels are not checkpointed yet); arm "
            "one of them"
        )
    return ft_on, every


@instrument("gemm_mesh")
def gemm_mesh(
    alpha, a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    beta=0.0, c: Optional[jax.Array] = None,
    opts: Optional[Options] = None,
) -> jax.Array:
    """Distributed C = alpha A B (+ beta C) via SUMMA (src/gemmC.cc).
    ``opts`` carries Option.Lookahead (panel-prefetch depth) and
    Option.FaultTolerance (ABFT policy; any active policy reroutes to
    the checksum-carrying SUMMA in ft/abft.py)."""
    if _ft_on(opts):
        from ..ft.abft import gemm_mesh_ft

        return gemm_mesh_ft(alpha, a, b, mesh, nb, beta, c, opts)
    ad = from_dense(a, mesh, nb)
    bd = from_dense(b, mesh, nb)
    cd = from_dense(c, mesh, nb) if c is not None else None
    return to_dense(gemm_summa(alpha, ad, bd, beta, cd, lookahead=_la(opts),
                               bcast_impl=_bi(opts), update_impl=_ui(opts)))


@instrument("potrf_mesh")
def potrf_mesh(
    a: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[DistMatrix, jax.Array]:
    """Distributed lower Cholesky; input is the full/lower Hermitian
    array.  Option.FaultTolerance reroutes to the checksum-carrying
    mesh loop (ft/abft.py)."""
    ft_on, every = _resilience(opts)
    if ft_on:
        from ..ft.abft import potrf_mesh_ft

        return potrf_mesh_ft(a, mesh, nb, opts)
    if every is not None:
        from ..ft.ckpt import potrf_ckpt

        return potrf_ckpt(
            from_dense(a, mesh, nb, diag_pad_one=True), every=every,
            bcast_impl=_bi(opts), panel_impl=_pi(opts), num_monitor=_nm(opts),
        )
    return potrf_dist(
        from_dense(a, mesh, nb, diag_pad_one=True), lookahead=_la(opts),
        bcast_impl=_bi(opts), panel_impl=_pi(opts), update_impl=_ui(opts),
        num_monitor=_nm(opts),
    )


def _posv_mesh_plain(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[jax.Array, jax.Array]:
    """The direct factor-at-data-dtype SPD solve: potrf + two trsm
    sweeps.  This is the whole solve under Option.MixedPrecision=off
    (trace-identical to the pre-mixed driver) and the fallback tier of
    the mixed ladder."""
    la, bi = _la(opts), _bi(opts)
    l, info = potrf_mesh(a, mesh, nb, opts)
    bd = from_dense(b, mesh, nb)
    y = trsm_dist(l, bd, Uplo.Lower, Op.NoTrans, lookahead=la, bcast_impl=bi)
    x = trsm_dist(l, y, Uplo.Lower, Op.ConjTrans, lookahead=la, bcast_impl=bi)
    return to_dense(x), info


@instrument("posv_mesh")
def posv_mesh(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed SPD solve (src/posv.cc).  f64 inputs route through the
    mixed-precision ladder by default (Option.MixedPrecision, default
    auto: f32 mesh factor + fused f64 refinement, GMRES-IR escalation,
    full-f64 fallback — dist_refine.py; the f32 factor consumes every
    opt the direct path would: Lookahead, BcastImpl, PanelImpl,
    FaultTolerance).  ``off`` (or any non-f64 dtype) runs the direct
    potrf + two-trsm path, trace-identical to the pre-mixed driver."""
    from .dist_refine import mixed_mesh_route

    routed = mixed_mesh_route(
        "posv", a, b, mesh, nb, opts,
        lambda: _posv_mesh_plain(a, b, mesh, nb, opts),
    )
    if routed is not None:
        return routed
    return _posv_mesh_plain(a, b, mesh, nb, opts)


@instrument("getrf_nopiv_mesh")
def getrf_nopiv_mesh(
    a: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[DistMatrix, jax.Array]:
    """Option.FaultTolerance reroutes to the checksum-carrying LU-nopiv
    mesh loop (ft/abft.py)."""
    ft_on, every = _resilience(opts)
    if ft_on:
        from ..ft.abft import getrf_nopiv_mesh_ft

        return getrf_nopiv_mesh_ft(a, mesh, nb, opts)
    if every is not None:
        from ..ft.ckpt import getrf_nopiv_ckpt

        return getrf_nopiv_ckpt(
            from_dense(a, mesh, nb, diag_pad_one=True), every=every,
            bcast_impl=_bi(opts), panel_impl=_pi(opts), num_monitor=_nm(opts),
        )
    return getrf_nopiv_dist(
        from_dense(a, mesh, nb, diag_pad_one=True), lookahead=_la(opts),
        bcast_impl=_bi(opts), panel_impl=_pi(opts), update_impl=_ui(opts),
        num_monitor=_nm(opts),
    )


@instrument("gesv_nopiv_mesh")
def gesv_nopiv_mesh(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed LU solve without pivoting (src/gesv_nopiv path). For
    general matrices use gesv_tntpiv_mesh (tournament pivoting), the RBT
    preconditioner (linalg.rbt), or the single-chip partial-pivot getrf.
    Option.FaultTolerance protects the factorization (via
    getrf_nopiv_mesh); the trsm sweeps run unprotected."""
    la, bi = _la(opts), _bi(opts)
    lu, info = getrf_nopiv_mesh(a, mesh, nb, opts)
    bd = from_dense(b, mesh, nb)
    y = trsm_dist(lu, bd, Uplo.Lower, Op.NoTrans, Diag.Unit, lookahead=la,
                  bcast_impl=bi)
    x = trsm_dist(lu, y, Uplo.Upper, Op.NoTrans, lookahead=la, bcast_impl=bi)
    return to_dense(x), info


@instrument("geqrf_mesh")
def geqrf_mesh(
    a: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
):
    """Distributed CAQR factorization (src/geqrf.cc). Returns DistQR.
    ``opts`` carries Option.BcastImpl (panel-broadcast lowering),
    Option.Checkpoint (ISSUE 13: the multi-array carry — tile stack +
    T_loc stack + tree V/T stacks — snapshots every K panel steps; off
    keeps the fused kernel untouched, trace-identical) and
    Option.NumMonitor (the in-carry reflector/τ orthogonality-loss
    gauge -> num.qr_orth_margin, through the FUSED loop and the
    checkpointed chain alike since ISSUE 15 — bitwise-equal gauges;
    off keeps the plain kernels/segment jits)."""
    every = _ckpt_every(opts)
    if every is not None:
        from ..ft.ckpt import geqrf_ckpt

        return geqrf_ckpt(from_dense(a, mesh, nb), every=every,
                          bcast_impl=_bi(opts), num_monitor=_nm(opts))
    return geqrf_dist(from_dense(a, mesh, nb), bcast_impl=_bi(opts),
                      panel_impl=_pi(opts), num_monitor=_nm(opts))


@instrument("gels_mesh")
def gels_mesh(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed least squares min ||A X - B|| for m >= n via CAQR
    (src/gels_qr.cc): X = R^-1 (Q^H B)[:n].  Returns (X, R diag info).

    The R top-square re-distribution goes through one dense round trip —
    the tile-level redistribute is the scalable path (redistribute()).
    """
    m, n = a.shape
    bi = _bi(opts)
    f = geqrf_mesh(a, mesh, nb, opts)
    bd = from_dense(b, mesh, nb)
    qb = to_dense(unmqr_dist(f, bd, Op.ConjTrans, bcast_impl=bi))[:n]
    r = jnp.triu(to_dense(f.fact)[:n, :n])
    rd = from_dense(r, mesh, nb, diag_pad_one=True)
    xd = trsm_dist(rd, from_dense(qb, mesh, nb), Uplo.Upper, Op.NoTrans,
                   bcast_impl=bi)
    rdiag = jnp.diagonal(r)
    info = jnp.where(
        jnp.any(rdiag == 0), jnp.argmax(rdiag == 0) + 1, 0
    ).astype(jnp.int32)
    return to_dense(xd), info


@instrument("heev_mesh")
def heev_mesh(
    a: jax.Array, mesh: Mesh, nb: int = 64, want_vectors: bool = True,
    distributed_solver: bool = True, opts: Optional[Options] = None,
):
    """Distributed Hermitian eigensolver (src/heev.cc with a grid): stage 1
    (he2hb, the O(n^3) reduction) and the stage-1 back-transform run on the
    mesh; the band travels as O(n nb) diagonal storage (gather_diagband,
    the analogue of he2hbGather); the band-to-tridiagonal chase runs as a
    wavefront kernel on that O(n nb) frame; the tridiagonal divide &
    conquer runs with its merge tree SHARDED over the mesh (dist_stedc —
    the reference's distributed stedc.cc/stedc_merge.cc); and the stage-2
    back-transform streams the SHARDED bulge-chase reflector family over
    Z's column shards (chase_apply_dist, reference unmtr_hb2st.cc:1-80).
    stedc_dist hands Z over ALREADY in chase_apply_dist's column-shard
    layout (dist_stedc._stedc_finale_jit), so no O(n^2) object is
    replicated anywhere in the stage-2 chain — including the driver-level
    handoffs (VERDICT r3 item 4 / r4 item 6; asserted by
    test_chase_apply_dist_memory and test_stedc_finale_memory)."""
    from ..linalg.eig import hb2st
    from ..linalg.tridiag import stedc, sterf
    from .dist_stedc import stedc_dist
    from .dist_twostage import (
        chase_apply_dist,
        gather_diagband,
        he2hb_dist,
        unmtr_he2hb_dist,
    )

    n = a.shape[0]
    cplx = jnp.issubdtype(a.dtype, jnp.complexfloating)
    every = _ckpt_every(opts)
    if every is not None:
        # Option.Checkpoint covers the O(n^3) stage-1 reduction — the
        # eig chain's preemption exposure; the later stages are O(n^2 nb)
        # or run on an O(n nb) frame (ISSUE 13)
        from ..ft.ckpt import he2hb_ckpt

        f = he2hb_ckpt(from_dense(a, mesh, nb), every=every,
                       bcast_impl=_bi(opts), num_monitor=_nm(opts))
    else:
        f = he2hb_dist(from_dense(a, mesh, nb), bcast_impl=_bi(opts),
                       num_monitor=_nm(opts))
    bandd = gather_diagband(f.band, nb)  # (n, 4nb) replicated, O(n nb)
    # the distributed two-sided update is Hermitian in exact arithmetic;
    # shave the O(eps * nsteps) rounding asymmetry before the band chase
    from ..linalg.eig import symmetrize_diagband

    bandd = symmetrize_diagband(bandd, nb)
    d, e, f2, phases = hb2st(bandd, nb, diag_storage=True)
    if not want_vectors:
        return sterf(d, e)
    if distributed_solver:
        w, ztri = stedc_dist(d, e, mesh, bcast_impl=_bi(opts))
    else:
        w, ztri = stedc(d, e)
    z = ztri.astype(a.dtype)
    if cplx:
        z = phases[:, None] * z
    z = chase_apply_dist(f2.vs, f2.taus, z, n, nb, mesh, bcast_impl=_bi(opts))
    zd = unmtr_he2hb_dist(f, from_dense(z, mesh, nb))
    return w, to_dense(zd)


@instrument("svd_mesh")
def svd_mesh(
    a: jax.Array, mesh: Mesh, nb: int = 64, want_vectors: bool = True
):
    """Distributed SVD (src/svd.cc with a grid): ge2tb and both stage-1
    back-transforms on the mesh; the band travels as O(n nb) diagonals and
    both stage-2 reflector families stream SHARDED over the eigenvector
    column shards (chase_apply_dist), as in heev_mesh."""
    from ..linalg.svd import bdsqr, tb2bd
    from .dist_twostage import (
        chase_apply_dist,
        gather_diagband,
        ge2tb_dist,
        unmbr_ge2tb_u_dist,
        unmbr_ge2tb_v_dist,
    )

    m, n = a.shape
    dtype = a.dtype
    if m < n:
        if not want_vectors:
            return svd_mesh(jnp.conj(a).T, mesh, nb, False)
        u, s, vh = svd_mesh(jnp.conj(a).T, mesh, nb, True)
        return jnp.conj(vh).T, s, jnp.conj(u).T
    f = ge2tb_dist(from_dense(a, mesh, nb))
    bandd = gather_diagband(f.band, nb)[:n]  # (n, 4nb), O(n nb) replicated
    d, e, f2, pu, pv = tb2bd(bandd, nb, diag_storage=True)
    if not want_vectors:
        return bdsqr(d, e, want_vectors=False)
    s, ub, vb = bdsqr(d, e, want_vectors=True)
    u = chase_apply_dist(f2.lvs, f2.ltaus, pu[:, None] * ub.astype(dtype), n, nb, mesh)
    u_full = jnp.zeros((m, n), dtype).at[:n].set(u)
    ud = unmbr_ge2tb_u_dist(f, from_dense(u_full, mesh, nb))
    v = chase_apply_dist(f2.rvs, f2.rtaus, pv[:, None] * vb.astype(dtype), n, nb, mesh)
    vd = unmbr_ge2tb_v_dist(f, from_dense(v, mesh, nb))
    return to_dense(ud), s, jnp.conj(to_dense(vd)).T


@instrument("her2k_mesh")
def her2k_mesh(
    alpha, a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    beta=0.0, c: Optional[jax.Array] = None, conj: bool = True,
    opts: Optional[Options] = None,
) -> jax.Array:
    """Distributed rank-2k update C = alpha A op(B) + op(alpha) B op(A)
    + beta C (conj=True: her2k, src/her2k.cc; conj=False: syr2k),
    returned FULL (both triangles).  Option.FaultTolerance reroutes to
    the checksum-carrying her2k (ft/abft.py, ISSUE 13) — the eig
    chain's dominant trailing-update op gains the same inject→detect→
    repair coverage as gemm/potrf/LU/trsm."""
    from .dist_blas3 import her2k_dist

    if _ft_on(opts):
        from ..ft.abft import her2k_mesh_ft

        return her2k_mesh_ft(alpha, a, b, mesh, nb, beta, c, conj, opts)
    ad = from_dense(a, mesh, nb)
    bd = from_dense(b, mesh, nb)
    cd = from_dense(c, mesh, nb) if c is not None else None
    out = her2k_dist(alpha, ad, bd, beta, cd, conj=conj, full=True,
                     lookahead=_la(opts), bcast_impl=_bi(opts))
    return to_dense(out)[: a.shape[0], : a.shape[0]]


@instrument("getrf_tntpiv_mesh")
def getrf_tntpiv_mesh(
    a: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[DistMatrix, jax.Array, jax.Array]:
    """Distributed tournament-pivoted LU (src/getrf_tntpiv.cc): P A = L U.
    Returns (LU, perm over the padded row space, info)."""
    return getrf_tntpiv_dist(
        from_dense(a, mesh, nb, diag_pad_one=True), lookahead=_la(opts),
        bcast_impl=_bi(opts), panel_impl=_pi(opts), num_monitor=_nm(opts),
    )


@instrument("gesv_tntpiv_mesh")
def gesv_tntpiv_mesh(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed general solve with tournament pivoting
    (src/gesv.cc with MethodLU::CALU): factor, permute B, two trsm sweeps."""
    la, bi = _la(opts), _bi(opts)
    lu, perm, info = getrf_tntpiv_mesh(a, mesh, nb, opts)
    bd = from_dense(b, mesh, nb)
    pb = permute_rows_dist(bd, perm)
    y = trsm_dist(lu, pb, Uplo.Lower, Op.NoTrans, Diag.Unit, lookahead=la,
                  bcast_impl=bi)
    x = trsm_dist(lu, y, Uplo.Upper, Op.NoTrans, lookahead=la, bcast_impl=bi)
    return to_dense(x), info


# ---------------------------------------------------------------------------
# Mixed-precision mesh solvers (src/gesv_mixed.cc:16-44, posv_mixed.cc) and
# distributed inverses (src/getri.cc, src/potri.cc).  The mixed engine —
# the fused on-device refinement loop, the Ozaki residual SUMMA, the
# distributed GMRES-IR escalation tier, and the Option.MixedPrecision
# routing behind gesv_mesh/posv_mesh — lives in dist_refine.py; the
# drivers are re-exported here so `parallel.gesv_mixed_mesh` keeps
# working.
# ---------------------------------------------------------------------------

from .dist_refine import (  # noqa: E402  (re-export; see module docstring)
    gesv_mixed_gmres_mesh,
    gesv_mixed_mesh,
    mixed_mesh_route,
    posv_mixed_gmres_mesh,
    posv_mixed_mesh,
)


@instrument("getri_mesh")
def getri_mesh(
    a: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB
) -> Tuple[jax.Array, jax.Array]:
    """Distributed inverse (src/getri.cc capability): partial-pivot factor
    then solve A X = I entirely on the mesh — the solve-against-identity
    formulation costs the same O(n^3) as the reference's trtri+trmm chain
    and reuses the pivoted trsm sweeps."""
    n = a.shape[0]
    lu, perm, info = getrf_mesh(a, mesh, nb)
    eye = jnp.eye(n, dtype=a.dtype)
    bd = from_dense(eye, mesh, nb)
    pb = permute_rows_dist(bd, perm)
    y = trsm_dist(lu, pb, Uplo.Lower, Op.NoTrans, Diag.Unit)
    x = trsm_dist(lu, y, Uplo.Upper, Op.NoTrans)
    return to_dense(x), info


@instrument("potri_mesh")
def potri_mesh(
    a: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB
) -> Tuple[jax.Array, jax.Array]:
    """Distributed SPD inverse (src/potri.cc capability): Cholesky factor,
    then A^-1 = L^-H L^-1 via two mesh trsm sweeps on the identity."""
    n = a.shape[0]
    l, info = potrf_mesh(a, mesh, nb)
    eye = jnp.eye(n, dtype=a.dtype)
    y = trsm_dist(l, from_dense(eye, mesh, nb), Uplo.Lower, Op.NoTrans)
    x = trsm_dist(l, y, Uplo.Lower, Op.ConjTrans)
    return to_dense(x), info


# ---------------------------------------------------------------------------
# Band drivers on the mesh (src/gbmm.cc, hbmm.cc, tbsm.cc, gbsv/gbtrf,
# pbsv/pbtrf on distributed band matrices).  Band storage rides the dense
# block-cyclic tile stack with the zero pattern enforced by (kl, ku)
# projection — structurally-zero tiles cost flops but not correctness; the
# bandwidth-aware k-loop skip is the scale-out refinement.
# ---------------------------------------------------------------------------


@instrument("gbmm_mesh")
def gbmm_mesh(
    alpha, a: jax.Array, kl: int, ku: int, b: jax.Array, mesh: Mesh,
    nb: int = _DEFAULT_NB, beta=0.0, c: Optional[jax.Array] = None,
    opts: Optional[Options] = None,
) -> jax.Array:
    """Distributed general-band x dense multiply (src/gbmm.cc)."""
    from ..core.matrix import band_project

    return gemm_mesh(alpha, band_project(a, kl, ku), b, mesh, nb, beta, c, opts)


@instrument("hbmm_mesh")
def hbmm_mesh(
    side, alpha, a: jax.Array, kd: int, b: jax.Array, mesh: Mesh,
    nb: int = _DEFAULT_NB, beta=0.0, c: Optional[jax.Array] = None,
    uplo: Uplo = Uplo.Lower, opts: Optional[Options] = None,
) -> jax.Array:
    """Distributed Hermitian-band x dense multiply (src/hbmm.cc)."""
    from ..core.matrix import band_project
    from .dist_blas3 import hemm_summa

    kl, ku = (kd, 0) if uplo == Uplo.Lower else (0, kd)
    ad = from_dense(band_project(a, kl, ku), mesh, nb)
    bd = from_dense(b, mesh, nb)
    cd = from_dense(c, mesh, nb) if c is not None else None
    return to_dense(hemm_summa(side, alpha, ad, bd, beta, cd, uplo=uplo,
                               lookahead=_la(opts), bcast_impl=_bi(opts)))


@instrument("tbsm_mesh")
def tbsm_mesh(
    a: jax.Array, kd: int, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    uplo: Uplo = Uplo.Lower, diag: Diag = Diag.NonUnit,
    perm: Optional[jax.Array] = None,
) -> jax.Array:
    """Distributed triangular-band solve, optionally applying LU pivots
    first (src/tbsm.cc tbsmPivots path)."""
    from ..core.matrix import band_project

    kl, ku = (kd, 0) if uplo == Uplo.Lower else (0, kd)
    ad = from_dense(band_project(a, kl, ku), mesh, nb, diag_pad_one=True)
    bd = from_dense(b, mesh, nb)
    if perm is not None:
        bd = permute_rows_dist(bd, perm)
    return to_dense(trsm_dist(ad, bd, uplo, Op.NoTrans, diag))


@instrument("pbsv_mesh")
def pbsv_mesh(
    a: jax.Array, b: jax.Array, kd: int, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed Hermitian-band solve (src/pbsv.cc/pbtrf.cc): the
    factorization k-loop only touches the tile window inside the
    bandwidth (pbtrf_band_dist) — O(n kd^2) work, tiles outside the band
    never read (Cholesky preserves the band); narrow-band inputs where
    the window equals the whole grid just degenerate to the dense
    schedule.  The triangular solves ride the dense trsm (banded L makes
    its masked flops vanish against the factor cost for skinny B)."""
    from ..core.matrix import band_project
    from .dist_chol import pbtrf_band_dist

    la, bi = _la(opts), _bi(opts)
    ab = band_project(a, kd, kd)
    ad = from_dense(ab, mesh, nb, diag_pad_one=True)
    l, info = pbtrf_band_dist(ad, kd, lookahead=la, bcast_impl=bi)
    bd = from_dense(b, mesh, nb)
    y = trsm_dist(l, bd, Uplo.Lower, Op.NoTrans, lookahead=la, bcast_impl=bi)
    x = trsm_dist(l, y, Uplo.Lower, Op.ConjTrans, lookahead=la, bcast_impl=bi)
    return to_dense(x), info


@instrument("gbsv_mesh")
def gbsv_mesh(
    a: jax.Array, b: jax.Array, kl: int, ku: int, mesh: Mesh,
    nb: int = _DEFAULT_NB, opts: Optional[Options] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed general-band solve (src/gbsv.cc/gbtrf.cc): partial-pivot
    band LU whose panel, swaps, row solve and trailing update only touch
    the band envelope (gbtrf_band_dist, U fill-in <= kl + ku under
    pivoting) — O(n (kl + nb)(kl + ku + nb)) work instead of the dense
    O(n^3)."""
    from ..core.matrix import band_project
    from .dist_lu import gbtrf_band_dist

    la, bi = _la(opts), _bi(opts)
    ab = band_project(a, kl, ku)
    ad = from_dense(ab, mesh, nb, diag_pad_one=True)
    lu, perm, info = gbtrf_band_dist(ad, kl, ku, lookahead=la, bcast_impl=bi)
    bd = from_dense(b, mesh, nb)
    pb = permute_rows_dist(bd, perm)
    y = trsm_dist(lu, pb, Uplo.Lower, Op.NoTrans, Diag.Unit, lookahead=la,
                  bcast_impl=bi)
    x = trsm_dist(lu, y, Uplo.Upper, Op.NoTrans, lookahead=la, bcast_impl=bi)
    return to_dense(x), info


@instrument("getrf_mesh")
def getrf_mesh(
    a: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[DistMatrix, jax.Array, jax.Array]:
    """Distributed partial-pivot LU — the reference's default getrf
    (src/getrf.cc:23-200): P A = L U with per-column argmax pivoting.
    Returns (LU, perm over the padded row space, info)."""
    # no pp ABFT variant exists yet (ft_on is unconsumed), but the
    # FaultTolerance x Checkpoint conflict must fail loudly here too
    _ft_on_, every = _resilience(opts)
    if every is not None:
        from ..ft.ckpt import getrf_pp_ckpt

        return getrf_pp_ckpt(
            from_dense(a, mesh, nb, diag_pad_one=True), every=every,
            bcast_impl=_bi(opts), num_monitor=_nm(opts),
        )
    return getrf_pp_dist(
        from_dense(a, mesh, nb, diag_pad_one=True), lookahead=_la(opts),
        bcast_impl=_bi(opts), panel_impl=_pi(opts), num_monitor=_nm(opts),
    )


def _gesv_mesh_plain(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[jax.Array, jax.Array]:
    """The direct factor-at-data-dtype general solve: partial-pivot
    factor, permute B, two trsm sweeps.  The whole solve under
    Option.MixedPrecision=off (trace-identical to the pre-mixed driver)
    and the fallback tier of the mixed ladder."""
    la, bi = _la(opts), _bi(opts)
    lu, perm, info = getrf_mesh(a, mesh, nb, opts)
    bd = from_dense(b, mesh, nb)
    pb = permute_rows_dist(bd, perm)
    y = trsm_dist(lu, pb, Uplo.Lower, Op.NoTrans, Diag.Unit, lookahead=la,
                  bcast_impl=bi)
    x = trsm_dist(lu, y, Uplo.Upper, Op.NoTrans, lookahead=la, bcast_impl=bi)
    return to_dense(x), info


@instrument("gesv_mesh")
def gesv_mesh(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed general solve with partial pivoting (src/gesv.cc
    default MethodLU::PartialPiv).  f64 inputs route through the
    mixed-precision ladder by default — f32 partial-pivot factor + fused
    f64 refinement, GMRES-IR escalation, full-f64 fallback
    (Option.MixedPrecision; dist_refine.py) — because on TPU the f32
    factor runs ~40x the emulated-f64 rate (BENCH_r05).
    Option.MixedPrecision=off (or non-f64 dtype) runs the direct path,
    trace-identical to the pre-mixed driver."""
    from .dist_refine import mixed_mesh_route

    routed = mixed_mesh_route(
        "gesv", a, b, mesh, nb, opts,
        lambda: _gesv_mesh_plain(a, b, mesh, nb, opts),
    )
    if routed is not None:
        return routed
    return _gesv_mesh_plain(a, b, mesh, nb, opts)
