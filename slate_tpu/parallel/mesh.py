"""Device mesh construction for the 2D block-cyclic process grid.

TPU-native analogue of the reference's MPI communicator + (p, q) grid
(BaseMatrix.hh:88-99 tileRank lambdas over ``MPI_Comm_size``).  A
``jax.sharding.Mesh`` with axes ``('p', 'q')`` plays the role of the process
grid; collectives over axis 'p' ride one ICI dimension, axis 'q' the other.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.grid import grid_2d_factor

# canonical axis names used by every distributed routine in slate_tpu
ROW_AXIS = "p"
COL_AXIS = "q"


def make_mesh(
    p: Optional[int] = None,
    q: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    order=None,
) -> Mesh:
    """Build a (p, q) mesh over ``devices`` (default: all available).

    With no arguments, picks the near-square factorization of the device
    count, matching the reference testers' default grid choice
    (test/grid_utils.hh).

    ``order`` (types.GridOrder; default Row, this package's historical
    layout) selects the ScaLAPACK-style process-grid ordering (reference
    enums.hh:130, func.hh process_2d_grid): Col places device k at grid
    position (k % p, k // p), Row at (k // q, k % q).  Ownership semantics
    are identical; only which physical device holds which block changes."""
    from ..types import GridOrder

    devs = list(devices) if devices is not None else jax.devices()
    if p is None and q is None:
        p, q = grid_2d_factor(len(devs))
    elif p is None:
        p = len(devs) // q
    elif q is None:
        q = len(devs) // p
    if p < 1 or q < 1 or p * q > len(devs):
        raise ValueError(f"mesh {p}x{q} invalid for {len(devs)} devices")
    if order == GridOrder.Col:
        grid = np.asarray(devs[: p * q]).reshape(q, p).T
    else:  # Row order — also this package's historical default layout
        grid = np.asarray(devs[: p * q]).reshape(p, q)
    return Mesh(grid, (ROW_AXIS, COL_AXIS))


def mesh_shape(mesh: Mesh) -> Tuple[int, int]:
    return mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]


def tile_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of a cyclic tile stack (mt, nt, nb, nb): dims (0, 1) over
    (p, q). Combined with ``tiling.to_cyclic`` this reproduces the
    reference's 2D block-cyclic ownership (func.hh:154)."""
    return NamedSharding(mesh, PartitionSpec(ROW_AXIS, COL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
