"""Distributed triangular solve over the block-cyclic mesh.

TPU-native analogue of ``src/trsm.cc`` / ``src/internal/internal_trsm.cc``
run on a distributed B: block forward/backward substitution where per tile
row k — diag-tile solve on the owning mesh row, broadcast of the solved RHS
row along axis 'p', broadcast of the A panel along axis 'q' (or the
transpose-gather for op != NoTrans, cf. dist_chol.py), one masked batched
einsum update.  All four (uplo, op) combinations share one kernel body with
trace-time flags.  ``trsm_dist`` is the left-side solve;
``trsm_dist_right`` mirrors it over B's tile columns for X op(A) = B
(internal_trsmA's right-side variants) — no transposing redistribution
needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..obs import instrument
from ..types import Diag, MethodTrsm, Op, Side, Uplo, select_trsm_method
from .dist import DistMatrix
from .mesh import COL_AXIS, ROW_AXIS, mesh_shape
from .comm import (
    PRECISE,
    all_gather_a,
    bcast_diag_tile,
    bcast_from_col,
    bcast_from_row,
    bcast_impl_scope,
    la_depth,
    local_indices,
    prefetch_bcast,
    psum_scatter_a,
    resolve_bcast_impl,
    route_to_block_cyclic_rows,
    shard_map_compat,
)

from typing import Optional


@instrument("trsm_dist")
def trsm_dist(
    a: DistMatrix,
    b: DistMatrix,
    uplo: Uplo = Uplo.Lower,
    op: Op = Op.NoTrans,
    diag: Diag = Diag.NonUnit,
    method: Optional[MethodTrsm] = None,
    lookahead: Optional[int] = None,
    bcast_impl: Optional[str] = None,
) -> DistMatrix:
    """Solve op(A) X = B; A triangular-distributed, B distributed. X
    overwrites B's layout (left side; alpha folded by callers).

    ``method`` picks the communication schedule (slate::trsm's MethodTrsm,
    method.hh:88-99): TrsmB broadcasts the A panel to B's owners each
    step; TrsmA keeps A's tiles stationary — the solved X row is
    replicated, A's owners compute the update partials in place, and
    psum-scatters deliver each owner exactly its own tiles (for the
    transposed ops, routed per target row by
    comm.route_to_block_cyclic_rows) — the win when B is far thinner
    than A.  All (uplo, op) combinations run the stationary schedule
    (src/trsmA.cc covers every op).  None = auto-select.

    ``lookahead`` (Option.Lookahead; None = the option default, 1): A is
    read-only here, so its per-step panels (diag tile + column/row panel)
    are prefetched ``lookahead`` steps ahead through
    ``comm.prefetch_bcast`` — the broadcast for step k + d overlaps the
    serial solve/update chain of step k.  Bitwise-identical at any
    depth."""
    p, q = mesh_shape(a.mesh)
    if b.grid != a.grid or b.nb != a.nb or b.mt != a.nt or b.m != a.n:
        raise ValueError(
            f"trsm_dist operands mismatch: A {a.m}x{a.n} nb={a.nb} grid={a.grid}, "
            f"B {b.m}x{b.n} nb={b.nb} grid={b.grid}"
        )
    a.require_diag_pad("trsm_dist")
    if method is None:
        method = select_trsm_method(Side.Left, b.mt, b.nt)
    la = la_depth(lookahead, a.nt)
    bi = resolve_bcast_impl(bcast_impl)
    from ..obs import flight as _flight

    if method == MethodTrsm.TrsmA:
        # stationary-A's psum-scatter delivery has no per-step broadcast
        # phase to fence — flight step dispatch covers TrsmB only
        xt = _trsm_a_jit(
            a.tiles, b.tiles, a.mesh, p, q, a.nt, uplo, op, diag, la, bi
        )
    elif _flight.step_dispatch_active():
        xt = _flight.trsm_steps(
            a.tiles, b.tiles, a.mesh, p, q, a.nt, uplo, op, diag, la, bi
        )
    else:
        xt = _trsm_jit(
            a.tiles, b.tiles, a.mesh, p, q, a.nt, uplo, op, diag, la, bi
        )
    return DistMatrix(tiles=xt, m=b.m, n=b.n, nb=b.nb, mesh=b.mesh)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10))
def _trsm_a_jit(at, bt, mesh, p, q, nt, uplo, op, diag, la=0, bi="psum"):
    """Stationary-A left solve, all ops (slate::trsmA, src/trsmA.cc
    semantics): per step the solved X row is all-gathered and multiplied
    against A's stationary tiles where they live — column k of A for
    op = NoTrans, row k (transposed per tile) otherwise — then the
    partials are routed to B's block-cyclic owners: a psum-scatter over
    the column axis for NoTrans, and the shared slot-scatter +
    double-psum-scatter delivery (comm.route_to_block_cyclic_rows) for
    the transposed ops, whose source row k % p differs from the
    destination rows i % p.  A never moves."""
    spec = P(ROW_AXIS, COL_AXIS)
    trans = op != Op.NoTrans
    conj = op == Op.ConjTrans
    eff_lower = (uplo == Uplo.Lower) != trans
    forward = eff_lower
    unit = diag == Diag.Unit

    def kernel(a_loc, b_loc):
        mtl, ntl, nb, _ = a_loc.shape
        mtl_b, ntl_b = b_loc.shape[0], b_loc.shape[1]
        r, c, i_log, j_log = local_indices(p, q, mtl, ntl)

        def opt(t):  # apply op to one tile (or a stack of tiles)
            t = jnp.swapaxes(t, -1, -2)
            return jnp.conj(t) if conj else t

        def fetch(s):
            # the stationary-A schedule's only read-only broadcast is the
            # diag tile; the solved-row replication is a serial chain
            k = s if forward else nt - 1 - s
            dtile = bcast_diag_tile(a_loc, k, p, q, nb)
            return opt(dtile) if trans else dtile

        def consume(s, dtile, b_loc):
            k = s if forward else nt - 1 - s
            kr, kc = k // p, k // q

            # solve X[k,:] on the owning mesh row, write back
            brow = lax.dynamic_slice_in_dim(b_loc, kr, 1, axis=0)[0]
            xrow = lax.linalg.triangular_solve(
                jnp.broadcast_to(dtile, brow.shape), brow,
                left_side=True, lower=eff_lower, transpose_a=False,
                unit_diagonal=unit,
            )
            mine_r = (r == k % p)
            b_loc = lax.dynamic_update_slice_in_dim(
                b_loc, jnp.where(mine_r, xrow, brow)[None], kr, axis=0
            )
            # replicate the solved row: every device needs it to multiply
            # against A's stationary tiles
            xrow = bcast_from_row(jnp.where(mine_r, xrow, 0), k % p)
            xfull = all_gather_a(xrow, COL_AXIS, axis=0)  # (q, ntl_b, nb, nb)

            if not trans:
                # owner-computes: only mesh column k % q holds A[:, k]
                remaining = (i_log > k) if forward else (i_log < k)
                acol = lax.dynamic_slice_in_dim(a_loc, kc, 1, axis=1)[:, 0]
                mine_c = (c == k % q)
                acol = jnp.where(remaining[:, None, None] & mine_c, acol, 0)
                part = jnp.einsum(
                    "iab,Jjbc->iJjac", acol, xfull, precision=PRECISE
                )  # (mtl, q, ntl_b, nb, nb)
                # reduce the partials over columns, scattering slice J to
                # mesh column J (each device receives only its own tiles)
                upd = psum_scatter_a(
                    part, COL_AXIS, scatter_dimension=1, tiled=False
                )
                return b_loc - upd.astype(b_loc.dtype)

            # op != NoTrans: op(A)[i, k] = op(A[k, i]) — the stationary
            # tiles are A's ROW k, held by mesh row k % p spread over the
            # columns i % q; the partial for output row i must reach mesh
            # row i % p (generally != k % p), so partials are scattered
            # into per-target-row slots and psum-scattered on both axes
            remaining = (j_log > k) if forward else (j_log < k)
            arow = lax.dynamic_slice_in_dim(a_loc, kr, 1, axis=0)[0]  # (ntl,nb,nb)
            pan = opt(arow)
            pan = jnp.where(remaining[:, None, None] & mine_r, pan, 0)
            part = jnp.einsum(
                "tab,Jjbc->tJjac", pan, xfull, precision=PRECISE
            )  # (ntl, q, ntl_b, nb, nb); slot t targets output row j_log[t]
            upd = route_to_block_cyclic_rows(part, j_log, p, mtl_b)
            return b_loc - upd.astype(b_loc.dtype)

        return prefetch_bcast(nt, la, fetch, consume, b_loc)

    with bcast_impl_scope(bi):
        return shard_map_compat(
            kernel, mesh=mesh, in_specs=(spec, spec), out_specs=spec,
            check_vma=False,
        )(at, bt)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10))
def _trsm_jit(at, bt, mesh, p, q, nt, uplo, op, diag, la=0, bi="psum"):
    spec = P(ROW_AXIS, COL_AXIS)
    trans = op != Op.NoTrans
    conj = op == Op.ConjTrans
    # effective triangle of op(A)
    eff_lower = (uplo == Uplo.Lower) != trans
    forward = eff_lower  # forward substitution iff op(A) is lower
    unit = diag == Diag.Unit

    def kernel(a_loc, b_loc):
        mtl, ntl, nb, _ = a_loc.shape
        r, c, i_log, j_log = local_indices(p, q, mtl, ntl)

        def opt(t):  # apply op to one tile (or a stack of tiles)
            t = jnp.swapaxes(t, -1, -2)
            return jnp.conj(t) if conj else t

        def fetch(s):
            # A is stationary: the diag tile and the op(A) panel of step
            # s are pure functions of a_loc, prefetchable at any depth
            k = s if forward else nt - 1 - s
            kr, kc = k // p, k // q

            dtile = bcast_diag_tile(a_loc, k, p, q, nb)
            if trans:
                dtile = opt(dtile)

            # panel of op(A)[:, k] by my local row indices, remaining side only
            remaining = (i_log > k) if forward else (i_log < k)
            if not trans:
                acol = lax.dynamic_slice_in_dim(a_loc, kc, 1, axis=1)[:, 0]
                mine_c = (c == k % q)
                pan = bcast_from_col(
                    jnp.where(remaining[:, None, None] & mine_c, acol, 0), k % q
                )
            else:
                # op(A)[i,k] = op(A[k,i]): transpose-gather of A row k
                arow = lax.dynamic_slice_in_dim(a_loc, kr, 1, axis=0)[0]
                mine_r2 = (r == k % p)
                arow = bcast_from_row(jnp.where(mine_r2, arow, 0), k % p)
                allrow = all_gather_a(arow, COL_AXIS, axis=0)  # (q,ntl,nb,nb)
                pan = opt(allrow[i_log % q, i_log // q])
                pan = jnp.where(remaining[:, None, None], pan, 0)
            return dtile, pan

        def consume(s, panels, b_loc):
            k = s if forward else nt - 1 - s
            kr = k // p
            dtile, pan = panels

            # solve X[k,:] on the owning mesh row, write back, bcast down 'p'
            brow = lax.dynamic_slice_in_dim(b_loc, kr, 1, axis=0)[0]  # (nbt,nb,nb)
            xrow = lax.linalg.triangular_solve(
                jnp.broadcast_to(dtile, brow.shape), brow,
                left_side=True, lower=eff_lower, transpose_a=False,
                unit_diagonal=unit,
            )
            mine_r = (r == k % p)
            b_loc = lax.dynamic_update_slice_in_dim(
                b_loc, jnp.where(mine_r, xrow, brow)[None], kr, axis=0
            )
            xrow = bcast_from_row(jnp.where(mine_r, xrow, 0), k % p)

            upd = jnp.einsum("iab,jbc->ijac", pan, xrow, precision=PRECISE)
            return b_loc - upd.astype(b_loc.dtype)

        return prefetch_bcast(nt, la, fetch, consume, b_loc)

    with bcast_impl_scope(bi):
        return shard_map_compat(
            kernel, mesh=mesh, in_specs=(spec, spec), out_specs=spec,
            check_vma=False,
        )(at, bt)


@instrument("trsm_dist_right")
def trsm_dist_right(
    a: DistMatrix,
    b: DistMatrix,
    uplo: Uplo = Uplo.Lower,
    op: Op = Op.NoTrans,
    diag: Diag = Diag.NonUnit,
    lookahead: Optional[int] = None,
    bcast_impl: Optional[str] = None,
) -> DistMatrix:
    """Solve X op(A) = B; A triangular-distributed (n, n), B (m, n).
    X overwrites B's layout.  ``lookahead`` prefetches A's read-only
    per-step panels, as in trsm_dist."""
    p, q = mesh_shape(a.mesh)
    if b.grid != a.grid or b.nb != a.nb or b.nt != a.nt or b.n != a.m:
        raise ValueError(
            f"trsm_dist_right operands mismatch: A {a.m}x{a.n} nb={a.nb}, "
            f"B {b.m}x{b.n} nb={b.nb}"
        )
    a.require_diag_pad("trsm_dist_right")
    xt = _trsm_right_jit(
        a.tiles, b.tiles, a.mesh, p, q, a.nt, uplo, op, diag,
        la_depth(lookahead, a.nt), resolve_bcast_impl(bcast_impl),
    )
    return DistMatrix(tiles=xt, m=b.m, n=b.n, nb=b.nb, mesh=b.mesh)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10))
def _trsm_right_jit(at, bt, mesh, p, q, nt, uplo, op, diag, la=0, bi="psum"):
    spec = P(ROW_AXIS, COL_AXIS)
    trans = op != Op.NoTrans
    conj = op == Op.ConjTrans
    eff_lower = (uplo == Uplo.Lower) != trans
    # X A = B with op(A) upper: X's leading columns close first -> forward
    forward = not eff_lower
    unit = diag == Diag.Unit

    def kernel(a_loc, b_loc):
        mtl_a, ntl_a, nb, _ = a_loc.shape
        r, c, _, j_log_b = local_indices(p, q, mtl_a, ntl_a)

        def opt(t):
            t = jnp.swapaxes(t, -1, -2)
            return jnp.conj(t) if conj else t

        def fetch(s):
            # A is stationary: diag tile + row panel of op(A) prefetch
            k = s if forward else nt - 1 - s
            kr, kc = k // p, k // q

            dtile = bcast_diag_tile(a_loc, k, p, q, nb)
            if trans:
                dtile = opt(dtile)

            # row k of op(A) restricted to the remaining columns
            remaining = (j_log_b > k) if forward else (j_log_b < k)
            if not trans:
                arow = lax.dynamic_slice_in_dim(a_loc, kr, 1, axis=0)[0]
                mine_r = (r == k % p)
                arow = bcast_from_row(jnp.where(mine_r, arow, 0), k % p)
                arow = jnp.where(remaining[:, None, None], arow, 0)
            else:
                # op(A)[k, j] = op(A[j, k]): transpose-gather of A column k
                acol = lax.dynamic_slice_in_dim(a_loc, kc, 1, axis=1)[:, 0]
                mine_c2 = (c == k % q)
                acol = bcast_from_col(jnp.where(mine_c2, acol, 0), k % q)
                allcol = all_gather_a(acol, ROW_AXIS, axis=0)  # (p,mtl,nb,nb)
                arow = opt(allcol[j_log_b % p, j_log_b // p])
                arow = jnp.where(remaining[:, None, None], arow, 0)
            return dtile, arow

        def consume(s, panels, b_loc):
            k = s if forward else nt - 1 - s
            kc = k // q
            dtile, arow = panels

            # solve X[:, k] on the owning mesh column, write back, bcast 'q'
            bcol = lax.dynamic_slice_in_dim(b_loc, kc, 1, axis=1)[:, 0]
            xcol = lax.linalg.triangular_solve(
                jnp.broadcast_to(dtile, bcol.shape), bcol,
                left_side=False, lower=eff_lower, transpose_a=False,
                unit_diagonal=unit,
            )
            mine_c = (c == k % q)
            b_loc = lax.dynamic_update_slice_in_dim(
                b_loc, jnp.where(mine_c, xcol, bcol)[:, None], kc, axis=1
            )
            xcol = bcast_from_col(jnp.where(mine_c, xcol, 0), k % q)

            upd = jnp.einsum("iab,jbc->ijac", xcol, arow, precision=PRECISE)
            return b_loc - upd.astype(b_loc.dtype)

        return prefetch_bcast(nt, la, fetch, consume, b_loc)

    with bcast_impl_scope(bi):
        return shard_map_compat(
            kernel, mesh=mesh, in_specs=(spec, spec), out_specs=spec,
            check_vma=False,
        )(at, bt)
