"""Distributed right-looking Cholesky over the block-cyclic mesh.

TPU-native analogue of ``src/potrf.cc`` (impl::potrf task DAG,
potrf.cc:91-196): per k — factor the diagonal tile, trsm the panel column,
broadcast the panel along process rows *and* columns (the symmetric
listBcastMT pattern, potrf.cc:124-134), herk the trailing matrix.

Design inversion: the OpenMP task graph + MOSI tile migration becomes ONE
``lax.fori_loop`` inside ``shard_map_compat``.  Per iteration:

- diagonal tile -> all devices via a two-hop rooted broadcast
  (comm.bcast_diag_tile; ppermute ring/doubling under Option.BcastImpl,
  masked double psum under the legacy lowering); every device factors the
  nb x nb tile redundantly (replicated flops are cheaper than a second
  broadcast — the panel is latency-bound, reference P4).
- panel trsm happens on the owning mesh column, then one rooted broadcast
  along axis 'q' gives every device the panel tiles for its row set
  (tileBcast down rows).
- the her-k update needs the panel indexed by *column* too: an all_gather
  over axis 'p' (n * nb elements — small) plus a cyclic index-map gather
  replaces the reference's transposed bcast list (potrf.cc:129-133).
- trailing update = one masked batched einsum over the local tile stack.

Static shapes: the update runs on trailing views with i/j > k masks
(SURVEY §7 "masked full-size updates"), segmented into comm.BUCKETS
statically-shrinking buckets — ~1.4x the optimal n^3/3 flops at 4
buckets (measured 1.7x step-time reduction vs the unbucketed kernel;
artifacts/README.md).  The work-optimal single-chip path is linalg.chol;
this kernel is the scaling path where the mesh amortizes the masked
flops.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from jax.sharding import PartitionSpec as P

from ..obs import instrument
from ..obs.numerics import resolve_num_monitor
from ..ops.pallas_ops import (
    chol_panel_tiles_pallas,
    chol_trailing_update_pallas,
    panel_engaged,
    panel_impl_scope,
    resolve_panel_impl,
    resolve_update_impl,
    update_engaged,
    update_impl_scope,
)
from .dist import DistMatrix
from .mesh import COL_AXIS, ROW_AXIS, mesh_shape
from .comm import (
    PRECISE,
    num_gauge_dtype,
    all_gather_a,
    audit_scope,
    bcast_diag_tile,
    bcast_from_col,
    bcast_impl_scope,
    bucket_plan,
    la_depth,
    local_indices,
    phase_scope,
    pipelined_factor_loop,
    resolve_bcast_impl,
    shard_map_compat,
)

from typing import Optional

@instrument("potrf_dist")
def potrf_dist(
    a: DistMatrix, lookahead: Optional[int] = None,
    bcast_impl: Optional[str] = None, panel_impl: Optional[str] = None,
    num_monitor: Optional[str] = None, update_impl: Optional[str] = None,
) -> Tuple[DistMatrix, jax.Array]:
    """Factor A = L L^H (lower). ``a`` holds the lower triangle (upper tile
    content ignored). Returns (L as DistMatrix, info).

    ``lookahead`` (Option.Lookahead; None = the option default, 1)
    software-pipelines the k-loop: each step's trailing herk is deferred
    into the next iteration so the panel broadcasts overlap it
    (potrf.cc:129-133's lookahead queues).  Results are bitwise-identical
    at any depth.  ``bcast_impl`` (Option.BcastImpl) picks the panel /
    diag-tile broadcast lowering — masked psum or the ppermute engine —
    also bitwise-identical.  ``panel_impl`` (Option.PanelImpl) picks the
    panel-phase lowering: ``xla`` (today's cholesky + batched-trsm chain,
    bitwise) or ``pallas`` (one fused on-chip kernel per panel; matches
    to the documented explicit-inverse tolerance class).  ``num_monitor``
    (Option.NumMonitor) threads the in-carry numerics gauges: ``on``
    accumulates the Schur-diagonal near-breakdown margin in the loop
    carry (each pivot tile's diagonal sampled right before its own panel
    factorization — a strict-schedule intermediate at ANY lookahead
    depth, so the gauge is depth-invariant) plus the final factor's diag
    min/max, reduced once at loop exit; ``off`` (and the flight
    step-dispatch path) is jaxpr-identical and records nothing.
    ``update_impl`` (Option.UpdateImpl) picks the trailing-herk lowering:
    ``xla`` (today's masked einsum bulk, jaxpr-identical) or ``pallas``
    (one fused grid dispatch per k-step, bitwise vs xla under interpret
    mode; comm bytes invariant by construction)."""
    p, q = mesh_shape(a.mesh)
    if a.mt != a.nt:
        raise ValueError("potrf_dist needs a square tile grid")
    a.require_diag_pad("potrf_dist")
    from ..obs import flight as _flight
    from ..obs import numerics as _num

    nm = resolve_num_monitor(num_monitor) == "on"
    if _flight.step_dispatch_active():
        # flight-recorder step dispatch: same arithmetic, fenced per phase
        # (the per-phase programs carry no gauges — monitoring is the
        # fused kernels' surface)
        lt, info = _flight.potrf_steps(
            a.tiles, a.mesh, p, q, a.nt, la_depth(lookahead, a.nt),
            resolve_bcast_impl(bcast_impl), resolve_panel_impl(panel_impl),
            resolve_update_impl(update_impl),
        )
    elif nm:
        lt, info, gz = _potrf_jit(
            a.tiles, a.mesh, p, q, a.nt, la_depth(lookahead, a.nt),
            resolve_bcast_impl(bcast_impl), resolve_panel_impl(panel_impl),
            resolve_update_impl(update_impl), True, a.n,
        )
        _num.record_chol_gauges("potrf", gz[0], gz[1], gz[2])
    else:
        lt, info = _potrf_jit(
            a.tiles, a.mesh, p, q, a.nt, la_depth(lookahead, a.nt),
            resolve_bcast_impl(bcast_impl), resolve_panel_impl(panel_impl),
            resolve_update_impl(update_impl), False, 0,
        )
    return DistMatrix(
        tiles=lt, m=a.m, n=a.n, nb=a.nb, mesh=a.mesh, diag_pad=True
    ), info


def _chol_panel_factor_solve(dtile, pcol, cplx):
    """Diag-tile factor + panel-column tile solves, dispatched by the
    active Option.PanelImpl scope.  XLA branch: today's ops, bitwise
    (cholesky, f32 for bf16, then one batched trsm).  Pallas branch: one
    fused kernel — column-loop factor with the inverse in VMEM scratch,
    tile solves as MXU matmuls (documented-tolerance parity)."""
    dtype = dtile.dtype
    if panel_engaged(dtype, dtile.size * dtile.dtype.itemsize):
        if dtype == jnp.bfloat16:  # no bf16 sqrt/div path worth keeping
            lkk32, solved32 = chol_panel_tiles_pallas(
                dtile.astype(jnp.float32), pcol.astype(jnp.float32)
            )
            return lkk32.astype(dtype), solved32.astype(dtype)
        return chol_panel_tiles_pallas(dtile, pcol)
    if dtype == jnp.bfloat16:
        lkk = lax.linalg.cholesky(dtile.astype(jnp.float32)).astype(dtype)
    else:
        lkk = lax.linalg.cholesky(dtile)
    lkk_h = jnp.conj(lkk).T if cplx else lkk.T
    solved = lax.linalg.triangular_solve(
        jnp.broadcast_to(lkk_h, pcol.shape), pcol,
        left_side=False, lower=False, transpose_a=False,
    )
    return lkk, solved


def _chol_panel_compute(view, k, p, q, i_log, c, cplx, roff=0, coff=0):
    """Compute half of the right-looking step-k panel phase: diag-tile
    broadcast + factor + panel-column tile solves + write-back.  Reads
    only column slot k // q - coff (refreshed by ``_chol_narrow`` when
    the update is deferred).  The factor + solve pair dispatches by
    Option.PanelImpl (_chol_panel_factor_solve).  Returns (view,
    pan_own): the owner-masked solved panel column (zeros off the owning
    mesh column), ready for the broadcast half."""
    nb = view.shape[2]
    kc = k // q - coff
    dtile = bcast_diag_tile(view, k, p, q, nb, roff, coff)
    pcol = lax.dynamic_slice_in_dim(view, kc, 1, axis=1)[:, 0]
    lkk, solved = _chol_panel_factor_solve(dtile, pcol, cplx)
    below = (i_log > k)[:, None, None]
    on_diag = (i_log == k)[:, None, None]
    newcol = jnp.where(below, solved, jnp.where(on_diag, lkk, pcol))
    mine = (c == k % q)
    view = lax.dynamic_update_slice_in_dim(
        view, jnp.where(mine, newcol, pcol)[:, None], kc, axis=1
    )
    return view, jnp.where(below & mine, newcol, 0)


def _chol_panel_bcast(pan_own, k, p, q, j_log, roff=0):
    """Broadcast half of the panel phase: one rooted broadcast along the
    column axis plus the transposed gather the herk needs (all_gather
    over 'p' + cyclic index map — the reference's transposed bcast list,
    potrf.cc:129-133).  Returns the (pan, panT) update payload."""
    pan = bcast_from_col(pan_own, k % q)
    allpan = all_gather_a(pan, ROW_AXIS, axis=0)
    # logical row j sits at local slot j // p - roff of its owner mesh
    # row j % p; columns below the view's row cut (slot < 0 would wrap)
    # are finished (j <= k) and zero
    slot = j_log // p - roff
    panT = allpan[j_log % p, jnp.maximum(slot, 0)]
    panT = jnp.where((slot >= 0)[:, None, None], panT, 0)
    return pan, panT


def _chol_narrow(view, payload, k, q, lower, cplx, coff=0):
    """Apply the deferred step-(k-1) herk to the one local column slot
    the step-k panel phase reads — same per-element products as the full
    einsum, sliced to a single j.  ``lower`` is the trailing-view lower-
    triangle tile mask (i_log >= j_log)."""
    pan_p, panT_p = payload
    kc = k // q - coff
    pT = lax.dynamic_slice_in_dim(panT_p, kc, 1, axis=0)
    upd = jnp.einsum(
        "iab,jcb->ijac", pan_p, jnp.conj(pT) if cplx else pT,
        precision=PRECISE,
    ).astype(view.dtype)
    lcol = lax.dynamic_slice_in_dim(lower, kc, 1, axis=1)
    colv = lax.dynamic_slice_in_dim(view, kc, 1, axis=1)
    return lax.dynamic_update_slice_in_dim(
        view, colv - jnp.where(lcol, upd, 0), kc, axis=1
    )


def _chol_info_dist(t_loc, i_log, j_log, nt, nb):
    """info: 1 + global index of first bad pivot (potrf.cc:253-256), 0 if
    ok.  Granularity caveat: XLA's cholesky NaN-fills the whole failing
    tile, so on failure info points at the failing *tile*'s first bad
    diagonal entry (a lower bound within nb of the exact LAPACK index)."""
    diag_tiles = (i_log[:, None] == j_log[None, :])[:, :, None]
    dvals = jnp.einsum("ijaa->ija", jnp.real(t_loc))
    bad = (~jnp.isfinite(dvals) | (dvals <= 0)) & diag_tiles
    gidx = i_log[:, None, None] * nb + jnp.arange(nb)[None, None, :] + 1
    big = nt * nb + 1
    local_info = jnp.min(jnp.where(bad, gidx, big))
    info = lax.pmin(lax.pmin(local_info, ROW_AXIS), COL_AXIS)
    return jnp.where(info >= big, 0, info).astype(jnp.int32)


def _chol_bulk(view, payload, lower, cplx, excl_kc=None):
    """The trailing herk, dispatched by the active Option.UpdateImpl
    scope.  ``excl_kc`` None: the strict/drain full update; otherwise
    exclude the column slot ``_chol_narrow`` already refreshed.  The
    pallas branch folds the lower/exclusion select into a per-tile mask
    and runs one fused grid dispatch (bitwise vs the einsum form under
    interpret mode); complex stays on the xla form."""
    pan_p, panT_p = payload
    nb = view.shape[-1]
    if not cplx and update_engaged(
        view.dtype,
        (pan_p.shape[0] + panT_p.shape[0]) * nb * nb * view.dtype.itemsize,
    ):
        mask = lower[:, :, 0, 0]
        if excl_kc is not None:
            mask = mask & (jnp.arange(lower.shape[1]) != excl_kc)[None, :]
        return chol_trailing_update_pallas(view, pan_p, panT_p, mask)
    upd = jnp.einsum(
        "iab,jcb->ijac", pan_p, jnp.conj(panT_p) if cplx else panT_p,
        precision=PRECISE,
    ).astype(view.dtype)
    mask = lower
    if excl_kc is not None:
        ntl_v = lower.shape[1]
        mask = mask & (jnp.arange(ntl_v) != excl_kc)[None, :, None, None]
    return view - jnp.where(mask, upd, 0)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6, 7, 8, 9, 10))
def _potrf_jit(at, mesh, p, q, nt, la, bi, pi, ui, nm=False, n_true=0):
    spec = P(ROW_AXIS, COL_AXIS)

    def kernel(t_loc):
        mtl, ntl, nb, _ = t_loc.shape
        dtype = t_loc.dtype
        cplx = jnp.issubdtype(dtype, jnp.complexfloating)
        r, c, _, _ = local_indices(p, q, mtl, ntl)
        rdt = num_gauge_dtype(dtype)  # Option.NumMonitor gauge carries

        def diag_probe(k, view, i_v, j_v):
            """Min Schur-complement diagonal entry of the not-yet-factored
            trailing part (logical tile >= k, true extent only) — the
            near-breakdown margin gauge.  Sampled at panel entry of step
            k, where tile (k, k)'s diagonal holds exactly the pivots the
            factor is about to take sqrt of."""
            dvals = jnp.einsum("ijaa->ija", jnp.real(view)).astype(rdt)
            gidx = i_v[:, None, None] * nb + jnp.arange(nb)[None, None, :]
            m = ((i_v[:, None] == j_v[None, :])[:, :, None]
                 & (i_v >= k)[:, None, None] & (gidx < n_true))
            return jnp.min(jnp.where(m, dvals, jnp.inf))

        def phases_on(i_log, j_log, roff, coff):
            """Panel / narrow / bulk phases of one right-looking step
            (the module-level ``_chol_*`` helpers, shared with the
            obs.flight step-dispatch drivers), restricted to a trailing
            view whose local tile (0, 0) is logical tile
            (i_log[0], j_log[0]) — the carry triple
            ``comm.pipelined_factor_loop`` schedules."""
            lower = (i_log[:, None] >= j_log[None, :])[:, :, None, None]

            def panel(k, view):
                view, pan_own = _chol_panel_compute(
                    view, k, p, q, i_log, c, cplx, roff, coff
                )
                # tag the broadcast half for the obs.schedule capture
                # (trace-time bookkeeping only; no jaxpr change)
                with phase_scope("bcast", k):
                    return view, _chol_panel_bcast(
                        pan_own, k, p, q, j_log, roff
                    )

            def narrow(k, view, payload):
                return _chol_narrow(view, payload, k, q, lower, cplx, coff)

            def bulk(k, view, payload):
                if k is None:
                    return _chol_bulk(view, payload, lower, cplx)
                return _chol_bulk(view, payload, lower, cplx, k // q - coff)

            return panel, narrow, bulk

        # Trailing-update bucketing: the masked full-size update costs ~3x
        # the optimal n^3/3; segmenting the k-range into comm.BUCKETS Python
        # buckets lets each run on a STATICALLY smaller trailing view
        # (finished tile rows/cols are sliced off between buckets), cutting
        # the masked flops to ~0.47x of full at 4 buckets (~1.4x optimal).
        # The reference gets the same effect from its shrinking task DAG
        # (potrf.cc:94).  Lookahead (Option.Lookahead) pipelines within
        # each bucket: the deferred update drains at the bucket boundary
        # before the view is re-sliced.

        margin = jnp.asarray(jnp.inf, rdt)
        for k0, k1, s0r, s0c in bucket_plan(nt, p, q):
            view = t_loc[s0r:, s0c:]
            i_log_v = r + (s0r + jnp.arange(mtl - s0r)) * p
            j_log_v = c + (s0c + jnp.arange(ntl - s0c)) * q
            panel, narrow, bulk = phases_on(i_log_v, j_log_v, s0r, s0c)
            zero_pl = (
                jnp.zeros((mtl - s0r, nb, nb), dtype),
                jnp.zeros((ntl - s0c, nb, nb), dtype),
            )
            if nm:
                # thread the margin gauge through the pipelined loop's
                # carry: probe at panel ENTRY (each pivot tile's column
                # was just refreshed by ``narrow``, so its sample is the
                # strict-schedule Schur diagonal at every depth); zero
                # extra collectives — the scalar rides the carry
                def panel_nm(k, st, panel=panel, i_v=i_log_v, j_v=j_log_v):
                    view, g = st
                    g = jnp.minimum(g, diag_probe(k, view, i_v, j_v))
                    view, pl = panel(k, view)
                    return (view, g), pl

                def narrow_nm(k, st, pl, narrow=narrow):
                    return (narrow(k, st[0], pl), st[1])

                def bulk_nm(k, st, pl, bulk=bulk):
                    return (bulk(k, st[0], pl), st[1])

                view, margin = pipelined_factor_loop(
                    k0, k1, la, panel_nm, narrow_nm, bulk_nm,
                    (view, margin), zero_pl
                )
            else:
                view = pipelined_factor_loop(
                    k0, k1, la, panel, narrow, bulk, view, zero_pl
                )
            t_loc = t_loc.at[s0r:, s0c:].set(view)

        _, _, i_log, j_log = local_indices(p, q, mtl, ntl)
        info = _chol_info_dist(t_loc, i_log, j_log, nt, nb)
        if nm:
            # final factor diag extrema + the carried margin, reduced once
            # at loop exit through the same unaudited pmin/pmax class the
            # info computation uses (no audited wire bytes)
            dvals = jnp.einsum("ijaa->ija", jnp.real(t_loc)).astype(rdt)
            gidx = i_log[:, None, None] * nb + jnp.arange(nb)[None, None, :]
            dm = (i_log[:, None] == j_log[None, :])[:, :, None] & (gidx < n_true)
            lmin = jnp.min(jnp.where(dm, dvals, jnp.inf))
            lmax = jnp.max(jnp.where(dm, dvals, -jnp.inf))

            def allr(x, op):
                return op(op(x, ROW_AXIS), COL_AXIS)

            gauges = jnp.stack([
                allr(margin, lax.pmin), allr(lmin, lax.pmin),
                allr(lmax, lax.pmax),
            ])
            return t_loc, info[None, None], gauges[None, None]
        return t_loc, info[None, None]

    out_specs = (spec, P(ROW_AXIS, COL_AXIS))
    if nm:
        out_specs = out_specs + (P(ROW_AXIS, COL_AXIS),)
    with bcast_impl_scope(bi), panel_impl_scope(pi), update_impl_scope(ui):
        out = shard_map_compat(
            kernel,
            mesh=mesh,
            in_specs=(spec,),
            out_specs=out_specs,
            check_vma=False,
        )(at)
    if nm:
        lt, info, gz = out
        return lt, jnp.max(info), gz[0, 0]
    lt, info = out
    return lt, jnp.max(info)


@instrument("pbtrf_band_dist")
def pbtrf_band_dist(
    a: DistMatrix, kd: int, lookahead: Optional[int] = None,
    bcast_impl: Optional[str] = None,
) -> Tuple[DistMatrix, jax.Array]:
    """Band Cholesky on the mesh at band cost (src/pbtrf.cc): the k-loop
    only ever touches the O(wd^2) tile window inside the bandwidth —
    tiles outside kd are never read or written (VERDICT r5 item 8), so
    total work is O(n (kd + nb)^2) (the nb term is tile granularity) and
    per-step communication O(wd nb^2) instead of the dense kernel's
    O(n^2)-class step.  ``a`` holds the lower triangle with bandwidth kd
    scalars (Cholesky preserves the band)."""
    p, q = mesh_shape(a.mesh)
    if a.mt != a.nt:
        raise ValueError("pbtrf_band_dist needs a square tile grid")
    a.require_diag_pad("pbtrf_band_dist")
    nb = a.nb
    # last tile row touched by column k*nb..k*nb+nb-1 under bandwidth kd
    wd = min(((nb - 1) + kd) // nb + 1, a.nt)
    lt, info = _pbtrf_band_jit(
        a.tiles, a.mesh, p, q, a.nt, wd, la_depth(lookahead, a.nt),
        resolve_bcast_impl(bcast_impl),
    )
    return DistMatrix(
        tiles=lt, m=a.m, n=a.n, nb=a.nb, mesh=a.mesh, diag_pad=True
    ), info


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6, 7))
def _pbtrf_band_jit(at, mesh, p, q, nt, wd, la, bi):
    spec = P(ROW_AXIS, COL_AXIS)

    def kernel(t_loc):
        mtl, ntl, nb, _ = t_loc.shape
        # local slots covering any wd-row/col window (clamped: a wide band
        # degenerates to the dense schedule)
        wlr = min(-(-wd // p) + 1, mtl)
        wlc = min(-(-wd // q) + 1, ntl)
        dtype = t_loc.dtype
        cplx = jnp.issubdtype(dtype, jnp.complexfloating)
        r, c, i_log, j_log = local_indices(p, q, mtl, ntl)
        zero = jnp.zeros((), jnp.int32)

        def srow(k):
            """Local row-slot base covering logical tile rows [k, k+wd)."""
            return jnp.asarray(
                jnp.clip((k - r + p - 1) // p, 0, mtl - wlr), jnp.int32
            )

        def scol(k):
            return jnp.asarray(
                jnp.clip((k - c + q - 1) // q, 0, ntl - wlc), jnp.int32
            )

        def panel(k, t_loc):
            """Diag factor + windowed column trsm + panel broadcasts of
            step k.  The deferred-update payload carries its own window
            offsets (the band window slides with k, unlike the dense
            kernel's bucket-fixed view)."""
            kc = jnp.asarray(k // q, jnp.int32)
            dtile = bcast_diag_tile(t_loc, k, p, q, nb)
            lkk = lax.linalg.cholesky(
                dtile.astype(jnp.float32) if dtype == jnp.bfloat16 else dtile
            ).astype(dtype)
            s_r = srow(k)
            i_win = r + (s_r + jnp.arange(wlr)) * p
            colwin = lax.dynamic_slice(t_loc, (s_r, kc, zero, zero), (wlr, 1, nb, nb))[:, 0]
            lkk_h = jnp.conj(lkk).T if cplx else lkk.T
            solved = lax.linalg.triangular_solve(
                jnp.broadcast_to(lkk_h, colwin.shape), colwin,
                left_side=False, lower=False, transpose_a=False,
            )
            below = (i_win > k)[:, None, None]
            on_diag = (i_win == k)[:, None, None]
            newcol = jnp.where(below, solved, jnp.where(on_diag, lkk, colwin))
            mine = c == k % q
            t_loc = lax.dynamic_update_slice(
                t_loc, jnp.where(mine, newcol, colwin)[:, None], (s_r, kc, zero, zero)
            )
            pan = bcast_from_col(jnp.where(below & mine, newcol, 0), k % q)
            allpan = all_gather_a(pan, ROW_AXIS, axis=0)  # (p, wlr, nb, nb)

            s_c = scol(k)
            j_win = c + (s_c + jnp.arange(wlc)) * q
            slot0 = jnp.clip((k - jnp.arange(p) + p - 1) // p, 0, mtl - wlr)
            idx = j_win // p - slot0[j_win % p]
            valid = (idx >= 0) & (idx < wlr) & (j_win > k)
            panT = allpan[j_win % p, jnp.clip(idx, 0, wlr - 1)]
            panT = jnp.where(valid[:, None, None], panT, 0)
            return t_loc, (pan, panT, s_r, s_c)

        def narrow(k, t_loc, payload):
            """Refresh what panel(k) reads — the column-slot-k piece of
            the deferred step-(k-1) window update."""
            pan_p, panT_p, s_r_p, s_c_p = payload
            kc = jnp.asarray(k // q, jnp.int32)
            i_win_p = r + (s_r_p + jnp.arange(wlr)) * p
            jcol = c + q * kc  # logical column of my slot kc
            oc = kc - s_c_p  # column's offset inside the pending window
            in_win = (oc >= 0) & (oc < wlc)
            pT = lax.dynamic_slice_in_dim(
                panT_p, jnp.clip(oc, 0, wlc - 1), 1, axis=0
            )
            upd = jnp.einsum(
                "iab,jcb->ijac", pan_p, jnp.conj(pT) if cplx else pT,
                precision=PRECISE,
            ).astype(dtype)
            mask = (in_win & (i_win_p >= jcol))[:, None, None, None]
            colv = lax.dynamic_slice(
                t_loc, (s_r_p, kc, zero, zero), (wlr, 1, nb, nb)
            )
            return lax.dynamic_update_slice(
                t_loc, colv - jnp.where(mask, upd, 0), (s_r_p, kc, zero, zero)
            )

        def bulk(k, t_loc, payload):
            """The windowed trailing update at the payload's own offsets;
            k = None applies everywhere (strict/drain), otherwise the
            column slot narrow(k) refreshed is excluded."""
            pan_p, panT_p, s_r_p, s_c_p = payload
            i_win_p = r + (s_r_p + jnp.arange(wlr)) * p
            j_win_p = c + (s_c_p + jnp.arange(wlc)) * q
            upd = jnp.einsum(
                "iab,jcb->ijac", pan_p, jnp.conj(panT_p) if cplx else panT_p,
                precision=PRECISE,
            ).astype(dtype)
            mask = (i_win_p[:, None] >= j_win_p[None, :])[:, :, None, None]
            if k is not None:
                kc = jnp.asarray(k // q, jnp.int32)
                mask = mask & ((s_c_p + jnp.arange(wlc)) != kc)[None, :, None, None]
            win = lax.dynamic_slice(
                t_loc, (s_r_p, s_c_p, zero, zero), (wlr, wlc, nb, nb)
            )
            win = win - jnp.where(mask, upd, 0)
            return lax.dynamic_update_slice(t_loc, win, (s_r_p, s_c_p, zero, zero))

        zero_pl = (
            jnp.zeros((wlr, nb, nb), dtype),
            jnp.zeros((wlc, nb, nb), dtype),
            zero,
            zero,
        )
        t_loc = pipelined_factor_loop(
            0, nt, la, panel, narrow, bulk, t_loc, zero_pl
        )

        _, _, i_l, j_l = local_indices(p, q, mtl, ntl)
        diag_tiles = (i_l[:, None] == j_l[None, :])[:, :, None]
        dvals = jnp.einsum("ijaa->ija", jnp.real(t_loc))
        bad = (~jnp.isfinite(dvals) | (dvals <= 0)) & diag_tiles
        gidx = i_l[:, None, None] * nb + jnp.arange(nb)[None, None, :] + 1
        big = nt * nb + 1
        local_info = jnp.min(jnp.where(bad, gidx, big))
        info = lax.pmin(lax.pmin(local_info, ROW_AXIS), COL_AXIS)
        info = jnp.where(info >= big, 0, info).astype(jnp.int32)
        return t_loc, info[None, None]

    with bcast_impl_scope(bi):
        lt, info = shard_map_compat(
            kernel,
            mesh=mesh,
            in_specs=(spec,),
            out_specs=(spec, P(ROW_AXIS, COL_AXIS)),
            check_vma=False,
        )(at)
    return lt, jnp.max(info)
