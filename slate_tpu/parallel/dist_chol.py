"""Distributed right-looking Cholesky over the block-cyclic mesh.

TPU-native analogue of ``src/potrf.cc`` (impl::potrf task DAG,
potrf.cc:91-196): per k — factor the diagonal tile, trsm the panel column,
broadcast the panel along process rows *and* columns (the symmetric
listBcastMT pattern, potrf.cc:124-134), herk the trailing matrix.

Design inversion: the OpenMP task graph + MOSI tile migration becomes ONE
``lax.fori_loop`` inside ``shard_map``.  Per iteration:

- diagonal tile -> all devices via two masked psums; every device factors the
  nb x nb tile redundantly (replicated flops are cheaper than a second
  broadcast — the panel is latency-bound, reference P4).
- panel trsm happens on the owning mesh column, then one psum over axis 'q'
  gives every device the panel tiles for its row set (tileBcast down rows).
- the her-k update needs the panel indexed by *column* too: an all_gather
  over axis 'p' (n * nb elements — small) plus a cyclic index-map gather
  replaces the reference's transposed bcast list (potrf.cc:129-133).
- trailing update = one masked batched einsum over the local tile stack.

Static shapes: the update runs full-size every step with i/j > k masks
(SURVEY §7 "masked full-size updates"); work is 3x the optimal n^3/3 but
perfectly load-balanced and compiles to O(1) program size.  The
work-optimal single-chip path is linalg.chol; this kernel is the scaling
path where the mesh amortizes the masked flops.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from jax.sharding import PartitionSpec as P

from .dist import DistMatrix
from .mesh import COL_AXIS, ROW_AXIS, mesh_shape
from .comm import (
    PRECISE,
    bcast_diag_tile,
    bcast_from_col,
    bcast_from_row,
    local_indices,
    shard_map,
)

def potrf_dist(a: DistMatrix) -> Tuple[DistMatrix, jax.Array]:
    """Factor A = L L^H (lower). ``a`` holds the lower triangle (upper tile
    content ignored). Returns (L as DistMatrix, info)."""
    p, q = mesh_shape(a.mesh)
    if a.mt != a.nt:
        raise ValueError("potrf_dist needs a square tile grid")
    a.require_diag_pad("potrf_dist")
    lt, info = _potrf_jit(a.tiles, a.mesh, p, q, a.nt)
    return DistMatrix(
        tiles=lt, m=a.m, n=a.n, nb=a.nb, mesh=a.mesh, diag_pad=True
    ), info


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _potrf_jit(at, mesh, p, q, nt):
    spec = P(ROW_AXIS, COL_AXIS)

    def kernel(t_loc):
        mtl, ntl, nb, _ = t_loc.shape
        dtype = t_loc.dtype
        cplx = jnp.issubdtype(dtype, jnp.complexfloating)
        r, c, i_log, j_log = local_indices(p, q, mtl, ntl)

        def step(k, t_loc):
            kc = k // q
            # ---- diagonal tile to everyone, factored redundantly ----
            lkk = lax.linalg.cholesky(bcast_diag_tile(t_loc, k, p, q, nb))

            # ---- panel trsm on owning column:  L[i,k] lkk^H = A[i,k] ----
            pcol = lax.dynamic_slice_in_dim(t_loc, kc, 1, axis=1)[:, 0]  # (mtl,nb,nb)
            lkk_h = jnp.conj(lkk).T if cplx else lkk.T
            solved = lax.linalg.triangular_solve(
                jnp.broadcast_to(lkk_h, pcol.shape), pcol,
                left_side=False, lower=False, transpose_a=False,
            )
            below = (i_log > k)[:, None, None]
            on_diag = (i_log == k)[:, None, None]
            newcol = jnp.where(below, solved, jnp.where(on_diag, lkk, pcol))
            mine = (c == k % q)
            t_loc = lax.dynamic_update_slice_in_dim(
                t_loc,
                jnp.where(mine, newcol, pcol)[:, None],
                kc,
                axis=1,
            )

            # ---- broadcast panel along rows (tileBcast, potrf.cc:124) ----
            pan = bcast_from_col(jnp.where(below & mine, newcol, 0), k % q)

            # ---- transposed panel by column index (all_gather over 'p') ----
            allpan = lax.all_gather(pan, ROW_AXIS, axis=0)  # (p, mtl, nb, nb)
            panT = allpan[j_log % p, j_log // p]  # (ntl, nb, nb); zero for j<=k

            # ---- trailing herk: A[i,j] -= L[i,k] L[j,k]^H for i>=j>k ----
            upd = jnp.einsum(
                "iab,jcb->ijac", pan, jnp.conj(panT) if cplx else panT,
                precision=PRECISE,
            ).astype(dtype)
            lower = (i_log[:, None] >= j_log[None, :])[:, :, None, None]
            return t_loc - jnp.where(lower, upd, 0)

        t_loc = lax.fori_loop(0, nt, step, t_loc)
        # info: 1 + global index of first bad pivot (potrf.cc:253-256), 0 if
        # ok.  Granularity caveat: XLA's cholesky NaN-fills the whole failing
        # tile, so on failure info points at the failing *tile*'s first bad
        # diagonal entry (a lower bound within nb of the exact LAPACK index).
        diag_tiles = (i_log[:, None] == j_log[None, :])[:, :, None]
        dvals = jnp.einsum("ijaa->ija", jnp.real(t_loc))
        bad = (~jnp.isfinite(dvals) | (dvals <= 0)) & diag_tiles
        gidx = i_log[:, None, None] * nb + jnp.arange(nb)[None, None, :] + 1
        big = nt * nb + 1
        local_info = jnp.min(jnp.where(bad, gidx, big))
        info = lax.pmin(lax.pmin(local_info, ROW_AXIS), COL_AXIS)
        info = jnp.where(info >= big, 0, info).astype(jnp.int32)
        return t_loc, info[None, None]

    lt, info = shard_map(
        kernel,
        mesh=mesh,
        in_specs=(spec,),
        out_specs=(spec, P(ROW_AXIS, COL_AXIS)),
        check_vma=False,
    )(at)
    return lt, jnp.max(info)
