"""Mixed-precision distributed solves: the fused f32-factor + f64-refine
engine behind the default mesh ``gesv``/``posv`` (ISSUE 8, SURVEY §2.4/§2.5
P8 at mesh scale).

The reference ships ``gesv_mixed``/``posv_mixed`` (f32 factor, f64
refinement, gesv_mixed.cc:16-44) as its high-performance solve tier.  On
TPU the gap is not a tier, it is the product: f64 getrf measures ~52 GF/s
against ~2 TF/s for f32 (BENCH_r05), so refinement is how a distributed
f64 solve should run by default.  Three pieces live here:

- ``_ir_posv_jit`` / ``_ir_gesv_jit``: classic iterative refinement as ONE
  jitted on-device program — a ``lax.while_loop`` whose carry is the
  distributed solution/residual tile stacks plus the mesh-reduced norms,
  with the f32 triangular solves, the f64 (or Ozaki int8) residual SUMMA
  and the Inf-norm reductions all inlined in the loop body.  Zero host
  round-trips per iteration; the only readback is the final
  (x, iters, converged) at the driver.  (The predecessor ran a Python
  loop calling ``float(norm_dist(...))`` twice per step — one host sync
  per refinement iteration, and no opts threading at all.)
- ``Option.ResidualImpl``: the residual ``b - A x`` computed either by the
  plain f64 SUMMA (XLA's emulated f32-pair arithmetic on TPU) or by the
  Ozaki split-integer SUMMA (``summa.gemm_summa_ozaki`` — the int8 digit
  planes of A and X ride the unchanged broadcast schedule at
  slice_count/8 x the f64 panel bytes and run on the integer MXU).
- ``gesv_mixed_gmres_mesh`` / ``posv_mixed_gmres_mesh``: distributed
  left-preconditioned restarted GMRES — ``linalg.refine._gmres``'s
  static-shape Arnoldi with the operator application (SUMMA matvec) and
  the f32-factor preconditioner (mesh trsm sweeps) running on DistMatrix
  operands — the escalation tier between IR and the full-f64 fallback.

Routing (``Option.MixedPrecision``, resolve chain explicit >
``use_mixed`` context > ``SLATE_TPU_MIXED`` env > ``auto``): ``off`` keeps
``gesv_mesh``/``posv_mesh`` trace-identical to the direct f64 path;
``ir``/``gmres`` pin one tier; ``auto`` (the default) runs the ladder
IR -> GMRES-IR -> full-f64 fallback for real f64 inputs.  Convergence is
the reference's gate (refine.py): ||r|| <= ||x|| * ||A|| * eps * sqrt(n).
Every tier threads ``opts`` end-to-end, so the f32 factor gets ring
broadcasts (Option.BcastImpl), lookahead pipelining, fused Pallas panels
(Option.PanelImpl) and ABFT (Option.FaultTolerance) exactly like a direct
factor call.
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from ..linalg.refine import gate_cte, ir_count, ir_gauge
from ..obs import instrument
from ..types import (
    MethodGemm,
    Norm,
    Op,
    Option,
    Options,
    Uplo,
    Diag,
    get_option,
)
from .comm import resolve_bcast_impl
from .dist import DistMatrix, from_dense, padded_tiles, to_dense
from .dist_aux import norm_dist
from .dist_lu import permute_rows_dist
from .dist_trsm import trsm_dist
from .mesh import mesh_shape
from .summa import OzakiSplit, gemm_summa, gemm_summa_ozaki, \
    ozaki_presplit_cached

_DEFAULT_NB = 256

# ---------------------------------------------------------------------------
# Option.MixedPrecision / Option.ResidualImpl resolution (the
# comm.resolve_bcast_impl pattern: explicit > context > env > auto)
# ---------------------------------------------------------------------------

MIXED_MODES = ("off", "ir", "gmres", "auto")
MIXED_ENV = "SLATE_TPU_MIXED"
_MIXED_DEFAULT = [None]

RESIDUAL_IMPLS = ("f64", "ozaki", "auto")
RESIDUAL_ENV = "SLATE_TPU_RESIDUAL_IMPL"


def resolve_mixed(opts: Optional[Options] = None) -> str:
    """Resolved Option.MixedPrecision mode: explicit option >
    ``use_mixed`` context > ``SLATE_TPU_MIXED`` env > ``auto``."""
    mode = get_option(opts, Option.MixedPrecision)
    if mode is None:
        mode = _MIXED_DEFAULT[-1]
    if mode is None:
        mode = os.environ.get(MIXED_ENV) or "auto"
    mode = str(mode)
    if mode not in MIXED_MODES:
        raise ValueError(
            f"unknown mixed-precision mode {mode!r}; expected one of {MIXED_MODES}"
        )
    return mode


@contextlib.contextmanager
def use_mixed(mode: str):
    """Session-default mixed-precision mode for drivers called inside
    (tests / CI sweeps); an explicit Option.MixedPrecision still wins."""
    if mode not in MIXED_MODES:
        raise ValueError(
            f"unknown mixed-precision mode {mode!r}; expected one of {MIXED_MODES}"
        )
    _MIXED_DEFAULT.append(mode)
    try:
        yield
    finally:
        _MIXED_DEFAULT.pop()


def resolve_residual_impl(opts: Optional[Options] = None) -> str:
    """Resolved Option.ResidualImpl: explicit option >
    ``SLATE_TPU_RESIDUAL_IMPL`` env > auto (ozaki on a real TPU backend —
    where the int8 MXU is the fast path — f64 elsewhere)."""
    impl = get_option(opts, Option.ResidualImpl)
    if impl is None:
        impl = os.environ.get(RESIDUAL_ENV) or "auto"
    impl = str(impl)
    if impl not in RESIDUAL_IMPLS:
        raise ValueError(
            f"unknown residual impl {impl!r}; expected one of {RESIDUAL_IMPLS}"
        )
    if impl == "auto":
        from ..ops.matmul import _tpu_is_default

        return "ozaki" if _tpu_is_default() else "f64"
    return impl


def _la(opts):
    return get_option(opts, Option.Lookahead)


def _max_iter(opts, max_iter=None) -> int:
    if max_iter is not None:
        return int(max_iter)
    return int(get_option(opts, Option.MaxIterations, 30))


def _astype_dist(d: DistMatrix, dtype) -> DistMatrix:
    return DistMatrix(tiles=d.tiles.astype(dtype), m=d.m, n=d.n, nb=d.nb,
                      mesh=d.mesh, diag_pad=d.diag_pad)


def _require_f64(a: jax.Array, who: str) -> None:
    if a.dtype != jnp.float64:
        raise TypeError(
            f"{who} is the f32-factor + f64-refine path and requires float64 "
            f"input, got {a.dtype}; complex/f32 solves use the direct drivers"
        )


def residual_comm_bytes(
    mt: int, ntb: int, kt: int, nb: int, p: int, q: int,
    bcast_impl: Optional[str] = None, residual_impl: str = "f64",
    n_slices: int = 9,
) -> int:
    """Analytic audited comm bytes of ONE residual SUMMA over the
    refinement loop's operands (A (mt x kt tiles) against X (kt x ntb
    tiles)): the plain GemmC broadcast volume with the per-impl factor of
    tests/test_comm_audit.py, times the payload itemsize — 8 B/elem for
    the f64 panels, ``n_slices`` B/elem for the int8 digit planes (the
    slice-count x plain-volume factor).  Used for the ``ir.*`` metrics
    and proven against the traced audit in tests/test_mixed_mesh.py."""
    itemsize = n_slices if residual_impl == "ozaki" else 8
    mtl, ntl = mt // p, ntb // q
    a_bytes = mtl * nb * nb * itemsize
    b_bytes = ntl * nb * nb * itemsize
    if resolve_bcast_impl(bcast_impl) == "psum":
        return kt * (a_bytes + b_bytes)
    return kt * ((q - 1) * a_bytes + (p - 1) * b_bytes)


# ---------------------------------------------------------------------------
# The fused refinement program: lax.while_loop over distributed tiles with
# mesh-reduced norms in the carry; donated RHS buffer; zero host syncs.
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6))
def _inf_norm_pair_jit(rt, xt, mesh, p, q, m_true, n_true):
    """Inf-norms of TWO same-shape tile stacks in ONE shard_map kernel —
    the refinement loop's (||r||, ||x||) carry update.  One kernel call
    per iteration keeps the mesh-reduction count minimal AND gives the
    trace-time comm audit a record per collective call site (a second
    ``_norm_jit`` call would be a jit-cache hit: eqns in the loop body,
    no audit records — the slate_lint loop-audit contract)."""
    from jax.sharding import PartitionSpec as P

    from .comm import local_indices, psum_a, shard_map_compat
    from .mesh import COL_AXIS, ROW_AXIS

    spec = P(ROW_AXIS, COL_AXIS)

    def kernel(r_loc, x_loc):
        mtl, ntl, nb, _ = r_loc.shape
        _r, _c, i_log, j_log = local_indices(p, q, mtl, ntl)
        gr = i_log[:, None, None, None] * nb + jnp.arange(nb)[None, None, :, None]
        gc = j_log[None, :, None, None] * nb + jnp.arange(nb)[None, None, None, :]
        mask = (gr < m_true) & (gc < n_true)
        st = jnp.stack([r_loc, x_loc])            # (2, mtl, ntl, nb, nb)
        absa = jnp.where(mask[None], jnp.abs(st), 0)
        rowsums = jnp.sum(absa, axis=(2, 4))      # (2, mtl, nb)
        rowsums = psum_a(rowsums, COL_AXIS)
        out = jnp.max(rowsums, axis=(1, 2))       # (2,)
        out = lax.pmax(out, ROW_AXIS)
        out = lax.pmax(out, COL_AXIS)
        return out[None, None]

    out = shard_map_compat(
        kernel, mesh=mesh, in_specs=(spec, spec), out_specs=P(ROW_AXIS, COL_AXIS),
        check_vma=False,
    )(rt, xt)
    return out[0, 0, 0], out[0, 0, 1]


def _ir_common(ad: DistMatrix, bd: DistMatrix, lo_solve, info,
               max_iter: int, la, bi: str, ri: str, nm: bool = False,
               qa=None, ea=None):
    """Shared refinement body over a factored low-precision solve.

    ``lo_solve(rd) -> DistMatrix`` applies the f32 factor to a distributed
    RHS and returns the f64 upcast.  Returns (x_tiles, r_tiles, iters,
    converged, rnorm, xnorm) — all device values; a failed factor
    (info != 0) skips the loop and NaN-fills x so misuse fails loudly.
    ``nm`` (Option.NumMonitor resolved) additionally carries a fixed-size
    (max_iter + 1, 2) history buffer of the per-iteration (||r||, ||x||)
    pair through the while_loop — the convergence TRAJECTORY, read back
    once at exit (rows never reached stay NaN); ``nm=False`` is
    jaxpr-identical to the unmonitored program and returns no buffer.

    Loop structure: the initial f32 solve IS the first ``lax.while_loop``
    trip (carry starts at x = 0, r = b, it = -1), so every distributed
    kernel — the f32 triangular sweeps, the residual SUMMA, the fused
    norm pair — has exactly ONE call site, inside the loop body.  That is
    both the audit contract (a second call site would be a jit-cache hit:
    counted eqns with no records) and what keeps the traced program
    minimal.  ``iters`` keeps the reference semantics: the number of
    CORRECTION steps after the initial solve (0 = converged at once)."""
    from .comm import audit_scope, phase_scope

    dtype = ad.tiles.dtype
    n = ad.m
    p, q = mesh_shape(ad.mesh)
    anorm = norm_dist(Norm.Inf, ad)
    cte = gate_cte(anorm, n, dtype)
    ok = info == 0

    def wrap(t, like):
        return DistMatrix(tiles=t, m=like.m, n=like.n, nb=like.nb,
                          mesh=like.mesh, diag_pad=like.diag_pad)

    def residual(x_t):
        if ri == "ozaki":
            # A's digit planes ride in as loop-invariant operands
            # (ozaki_presplit): the stationary operand is split ONCE per
            # request — and, through the buffer-identity cache, once per
            # OPERATOR — instead of once per refinement iteration
            split = None if qa is None else OzakiSplit(qa=qa, ea=ea)
            return gemm_summa_ozaki(-1.0, ad, wrap(x_t, bd), 1.0, bd,
                                    lookahead=la, bcast_impl=bi,
                                    a_split=split).tiles
        return gemm_summa(-1.0, ad, wrap(x_t, bd), 1.0, bd,
                          method=MethodGemm.GemmC, lookahead=la,
                          bcast_impl=bi).tiles

    def cond(state):
        it, done = state[4], state[5]
        return ok & (~done) & (it < max_iter)

    def body(state):
        x_t, r_t, _rn, _xn, it, _done = state[:6]
        with phase_scope("correct"):
            d = lo_solve(wrap(r_t, bd)).tiles
        x_t = x_t + d
        with phase_scope("residual"):
            r_t = residual(x_t)
        rn, xn = _inf_norm_pair_jit(r_t, x_t, ad.mesh, p, q, bd.m, bd.n)
        out = (x_t, r_t, rn, xn, it + 1, rn <= xn * cte)
        if nm:
            # trajectory buffer rides the carry: row it+1 (the trip the
            # initial solve counts as trip 0) gets this trip's norm pair
            hist = lax.dynamic_update_slice_in_dim(
                state[6], jnp.stack([rn, xn])[None], it + 1, axis=0)
            out = out + (hist,)
        return out

    # audit_scope(max_iter + 1): the while trip count is dynamic, so the
    # trace-time comm audit records the refinement loop's collectives at
    # the worst-case multiplicity (the lint loop-audit contract; the ir.*
    # metrics scale the per-iteration volume by the MEASURED iters)
    rdt = jnp.real(jnp.zeros((), dtype)).dtype
    init = (jnp.zeros_like(bd.tiles), bd.tiles, jnp.asarray(jnp.inf, rdt),
            jnp.zeros((), rdt), jnp.int32(-1), jnp.zeros((), bool))
    if nm:
        init = init + (jnp.full((max_iter + 1, 2), jnp.nan, rdt),)
    with audit_scope(max_iter + 1):
        out = lax.while_loop(cond, body, init)
    x_t, r_t, rn, xn, iters, done = out[:6]
    x_t = jnp.where(ok, x_t, jnp.full_like(x_t, jnp.nan))
    if nm:
        return x_t, r_t, iters, done & ok, rn, xn, out[6]
    return x_t, r_t, iters, done & ok, rn, xn


@functools.partial(
    jax.jit,
    static_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14),
    donate_argnums=(1,),
)
def _ir_posv_jit(at, bt, lt, info, mesh, p, q, m, nrhs, nb,
                 max_iter, la, bi, ri, nm=False, qa=None, ea=None):
    ad = DistMatrix(tiles=at, m=m, n=m, nb=nb, mesh=mesh, diag_pad=True)
    bd = DistMatrix(tiles=bt, m=m, n=nrhs, nb=nb, mesh=mesh, diag_pad=False)
    ld = DistMatrix(tiles=lt, m=m, n=m, nb=nb, mesh=mesh, diag_pad=True)

    def lo_solve(rd: DistMatrix) -> DistMatrix:
        r32 = _astype_dist(rd, jnp.float32)
        y = trsm_dist(ld, r32, Uplo.Lower, Op.NoTrans, lookahead=la,
                      bcast_impl=bi)
        x = trsm_dist(ld, y, Uplo.Lower, Op.ConjTrans, lookahead=la,
                      bcast_impl=bi)
        return _astype_dist(x, at.dtype)

    return _ir_common(ad, bd, lo_solve, info, max_iter, la, bi, ri, nm,
                      qa, ea)


@functools.partial(
    jax.jit,
    static_argnums=(5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
    donate_argnums=(1,),
)
def _ir_gesv_jit(at, bt, lut, perm, info, mesh, p, q, m, nrhs, nb,
                 max_iter, la, bi, ri, nm=False, qa=None, ea=None):
    ad = DistMatrix(tiles=at, m=m, n=m, nb=nb, mesh=mesh, diag_pad=True)
    bd = DistMatrix(tiles=bt, m=m, n=nrhs, nb=nb, mesh=mesh, diag_pad=False)
    lud = DistMatrix(tiles=lut, m=m, n=m, nb=nb, mesh=mesh, diag_pad=True)

    def lo_solve(rd: DistMatrix) -> DistMatrix:
        r32 = _astype_dist(rd, jnp.float32)
        pr = permute_rows_dist(r32, perm)
        y = trsm_dist(lud, pr, Uplo.Lower, Op.NoTrans, Diag.Unit,
                      lookahead=la, bcast_impl=bi)
        x = trsm_dist(lud, y, Uplo.Upper, Op.NoTrans, lookahead=la,
                      bcast_impl=bi)
        return _astype_dist(x, at.dtype)

    return _ir_common(ad, bd, lo_solve, info, max_iter, la, bi, ri, nm,
                      qa, ea)


def _factor_f32(kind: str, a: jax.Array, mesh: Mesh, nb: int, opts):
    """The f32 mesh factor with ``opts`` threaded end-to-end: the factor
    drivers consume Option.Lookahead, Option.BcastImpl, Option.PanelImpl
    and Option.FaultTolerance exactly as a direct f32 call would (the
    whole point of the rebuild — the old facade factored bare)."""
    from .drivers import getrf_mesh, potrf_mesh

    a32 = a.astype(jnp.float32)
    if kind == "posv":
        l, info = potrf_mesh(a32, mesh, nb, opts)
        return l, None, info
    lu, perm, info = getrf_mesh(a32, mesh, nb, opts)
    return lu, perm, info


# stationary-operator prefactor memo (the serving case: ONE operator,
# a stream of right-hand sides): keyed on the dense operand's buffer
# identity + the factor-relevant config, holding a strong reference to
# the key array so its id cannot be recycled while the entry lives.
# Each entry holds the dense A, the distributed f64 A and its f32
# factor (~2.5 matrix copies), so residency is bounded two ways: the
# entry cap, and a per-operand byte ceiling — a large one-shot solve
# near the HBM ceiling must NOT have its buffers pinned by a serving
# cache it never asked for (the 256-4096 serving bins all fit under
# the default 256 MiB; SLATE_TPU_PREFACTOR_CACHE_MAX_BYTES overrides,
# 0 disables the memo entirely).
_PREFACTOR_MEMO: dict = {}
_PREFACTOR_ORDER: list = []
_PREFACTOR_CAP = 4
_PREFACTOR_MAX_BYTES_ENV = "SLATE_TPU_PREFACTOR_CACHE_MAX_BYTES"


def _prefactor_max_bytes() -> int:
    try:
        return int(float(os.environ.get(_PREFACTOR_MAX_BYTES_ENV, "") or
                         (1 << 28)))
    except ValueError:
        return 1 << 28


def clear_prefactor_cache() -> None:
    _PREFACTOR_MEMO.clear()
    _PREFACTOR_ORDER.clear()


def _prefactor_cached(kind: str, a: jax.Array, mesh: Mesh, nb: int, opts):
    """``_prefactor`` memoized on ``id(a)``: repeated routed solves
    against the SAME dense operand object (the stationary-A serving
    stream) reuse the f32 factor, the distributed f64 A — and, through
    ``ozaki_presplit_cached`` keying on the reused ad.tiles buffer, the
    Ozaki digit planes — instead of re-running the O(n^3) factor per
    request.  Tracers bypass the memo (host caching is runtime-only)."""
    if isinstance(a, jax.core.Tracer) or a.nbytes > _prefactor_max_bytes():
        return _prefactor(kind, a, mesh, nb, opts)
    from ..serve.cache import options_signature

    key = (id(a), kind, id(mesh), nb, options_signature(opts))
    hit = _PREFACTOR_MEMO.get(key)
    if hit is not None and hit[0] is a:
        return hit[1]
    pre = _prefactor(kind, a, mesh, nb, opts)
    _PREFACTOR_MEMO[key] = (a, pre)
    _PREFACTOR_ORDER.append(key)
    while len(_PREFACTOR_ORDER) > _PREFACTOR_CAP:
        _PREFACTOR_MEMO.pop(_PREFACTOR_ORDER.pop(0), None)
    return pre


def _prefactor(kind: str, a: jax.Array, mesh: Mesh, nb: int, opts):
    """(fact, perm, info, ad): the f32 factor plus the distributed f64 A.
    Computed once per routed solve and SHARED down the ladder — the
    GMRES escalation tier preconditions with the exact factor the IR
    tier just computed, never re-running the O(n^3) factorization on
    the (ill-conditioned, i.e. slowest) inputs that escalate."""
    if kind == "posv":
        # the potrf contract reads only the lower triangle (upper tile
        # ignored — dist_chol.potrf_dist), so lower-only storage is a
        # valid posv input; the refinement residual reads BOTH triangles,
        # so mirror the lower one first (refine.posv_mixed_array's
        # symmetrize at mesh scale; real f64 only, no conjugation).  For
        # a full symmetric array this is the bitwise identity.
        a = jnp.tril(a) + jnp.tril(a, -1).T
    fact, perm, info = _factor_f32(kind, a, mesh, nb, opts)
    ad = from_dense(a, mesh, nb, diag_pad_one=True)
    return fact, perm, info, ad


def _mixed_ir_solve(kind: str, a: jax.Array, b: jax.Array, mesh: Mesh,
                    nb: int, max_iter, opts, pre=None):
    """Factor + fused refinement; returns (x_dense, iters, converged,
    rnorm, xnorm, info, resid_bytes_per_iter, history) with
    iters/converged still on device.  ``history`` is the carried
    (||r||, ||x||) trajectory buffer under Option.NumMonitor=on, else
    None (the monitored program is a distinct static variant; off is
    jaxpr-identical to the pre-monitoring kernel)."""
    from ..obs import flight as _flight
    from ..obs import numerics as _num

    p, q = mesh_shape(mesh)
    la = _la(opts)
    bi = resolve_bcast_impl(get_option(opts, Option.BcastImpl))
    ri = resolve_residual_impl(opts)
    mi = _max_iter(opts, max_iter)
    nm = _num.resolve_num_monitor(_num.monitor_from_opts(opts)) == "on"
    fact, perm, info, ad = pre if pre is not None else _prefactor_cached(
        kind, a, mesh, nb, opts)
    bd = from_dense(b, mesh, nb)
    # the step-level flight recorder cannot descend into a fused
    # while_loop (its per-phase dispatches are host-driven); the factor
    # above records normally, the refinement runs as the one fused program
    # stationary-A digit planes: split once per operator (buffer-identity
    # cached) instead of once per refinement ITERATION — the planes enter
    # the fused program as loop-invariant operands (summa.ozaki_presplit)
    split = ozaki_presplit_cached(ad) if ri == "ozaki" else None
    qa, ea = (split.qa, split.ea) if split is not None else (None, None)
    with _flight.no_flight():
        if kind == "posv":
            out = _ir_posv_jit(
                ad.tiles, bd.tiles, fact.tiles, info, mesh, p, q, ad.m,
                bd.n, nb, mi, la, bi, ri, nm, qa, ea,
            )
        else:
            out = _ir_gesv_jit(
                ad.tiles, bd.tiles, fact.tiles, perm, info, mesh, p, q,
                ad.m, bd.n, nb, mi, la, bi, ri, nm, qa, ea,
            )
    x_t, _r_t, iters, conv, rn, xn = out[:6]
    hist = out[6] if nm else None
    xd = DistMatrix(tiles=x_t, m=bd.m, n=bd.n, nb=nb, mesh=mesh)
    per_iter = float(residual_comm_bytes(
        ad.tiles.shape[0], bd.tiles.shape[1], ad.nt, nb, p, q, bi, ri))
    return to_dense(xd), iters, conv, rn, xn, info, per_iter, hist


@instrument("posv_mixed_mesh")
def posv_mixed_mesh(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    max_iter: Optional[int] = None, opts: Optional[Options] = None,
    pre=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Distributed SPD solve, f32 mesh factor + fused f64 mesh refinement
    (src/posv_mixed.cc).  Returns (x, iters, info); iters = -1 means the
    refinement did not converge (or the factor failed — x is then
    NaN-filled) and the caller should escalate (GMRES-IR / full f64).
    ``a`` holds the lower triangle (upper ignored, the potrf_mesh
    contract — the residual gemm reads the lower triangle mirrored; see
    ``_prefactor``).  ``pre`` is the routing ladder's shared
    ``_prefactor`` result (internal)."""
    _require_f64(a, "posv_mixed_mesh")
    x, raw_iters, conv, rn, xn, info, per_iter, hist = _mixed_ir_solve(
        "posv", a, b, mesh, nb, max_iter, opts, pre
    )
    iters = jnp.where(conv, raw_iters, -1).astype(jnp.int32)
    _record_ir("posv", iters, raw_iters, rn, xn, per_iter, hist)
    return x, iters, jnp.asarray(info, jnp.int32)


@instrument("gesv_mixed_mesh")
def gesv_mixed_mesh(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    max_iter: Optional[int] = None, opts: Optional[Options] = None,
    pre=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Distributed general solve, f32 partial-pivot mesh factor + fused
    f64 mesh refinement (src/gesv_mixed.cc:16-44).  Returns
    (x, iters, info); see posv_mixed_mesh."""
    _require_f64(a, "gesv_mixed_mesh")
    x, raw_iters, conv, rn, xn, info, per_iter, hist = _mixed_ir_solve(
        "gesv", a, b, mesh, nb, max_iter, opts, pre
    )
    iters = jnp.where(conv, raw_iters, -1).astype(jnp.int32)
    _record_ir("gesv", iters, raw_iters, rn, xn, per_iter, hist)
    return x, iters, jnp.asarray(info, jnp.int32)


def _record_ir(kind: str, iters, raw_iters, rnorm, xnorm, per_iter,
               hist=None) -> None:
    """The ir.* observability surface (always-on, like the ft.* counters):
    per-solve gauges + the totals obs.report gates.  One host readback —
    the final (iters, norms) the drivers return anyway.  Under tracing
    (slate_lint's make_jaxpr over the registry) the values are tracers and
    the readback is skipped — metrics are a runtime surface.

    ``raw_iters`` is the pre-convergence-masking trip counter: the loop
    ran raw_iters + 1 residual SUMMAs (-1 = failed factor, loop never
    entered), so the residual comm bytes scale by the MEASURED trips."""
    try:
        it = int(iters)
        raw = int(raw_iters)
    except (jax.errors.TracerIntegerConversionError,
            jax.errors.ConcretizationTypeError):
        return
    ir_count("ir.solves", kind)
    ir_gauge("ir.iters", max(it, 0), kind)
    ir_gauge("ir.rnorm", float(rnorm), kind)
    ir_gauge("ir.xnorm", float(xnorm), kind)
    ir_count("ir.iters_total", kind, max(it, 0))
    ir_count("ir.residual_gemm_bytes", kind, per_iter * (raw + 1))
    if it >= 0:
        ir_count("ir.converged", kind)
    if hist is not None:
        # the carried (||r||, ||x||) trajectory (Option.NumMonitor=on):
        # lands as the ir.residual_history gauge series so a stalling-
        # but-eventually-converging solve is distinguishable from a
        # healthy one in the RunReport (ISSUE 10 satellite)
        from ..obs import numerics as _num

        _num.record_ir_history(kind, hist, raw)


# ---------------------------------------------------------------------------
# Distributed GMRES-IR (src/gesv_mixed_gmres.cc at mesh scale): the
# refine._gmres Arnoldi with DistMatrix operator/preconditioner application.
# The Krylov basis is an O(n (restart+1)) replicated buffer; the O(n^2)
# work (matvec, triangular sweeps) runs distributed.
# ---------------------------------------------------------------------------


def _vec_to_tiles(v, m, nb, p, q, mt, ntv):
    """Dense (m,) vector -> the cyclic tile stack of an (m, 1) DistMatrix
    (traceable: pure reshape/permutation, no host round trip)."""
    from ..core.tiling import to_cyclic, to_tiles

    x = jnp.zeros((mt * nb, ntv * nb), v.dtype).at[: v.shape[0], 0].set(v)
    return to_cyclic(to_tiles(x, nb), p, q)


def _tiles_to_vec(t, m, p, q):
    from ..core.tiling import from_cyclic, from_tiles

    return from_tiles(from_cyclic(t, p, q), m, 1)[:, 0]


def _gmres_dist(pm_resid, b, restart: int, tol, max_restarts: int):
    """Left-preconditioned restarted GMRES with the distributed operator
    applied at exactly ONE call site.

    ``pm_resid(v, c) -> M^-1 (c - A v)`` is the preconditioned-residual
    verb (the mesh trsm sweeps + SUMMA matvec).  The flat inner loop
    j = 0..restart folds the per-restart initial residual into the
    Arnoldi recurrence: j = 0 evaluates ``pm_resid(x, b)`` (the restart's
    TRUE preconditioned residual — normalized into V[0] and, crucially,
    the convergence measurement); j >= 1 evaluates ``-pm_resid(V[j-1],
    0) = M^-1 A V[j-1]`` (the next Krylov vector).  One call site means
    one copy of the distributed kernels in the traced program — the
    jit-cache/audit contract ``refine._gmres``'s three call sites cannot
    satisfy.

    Stopping is on MEASURED residuals only: with an f32 preconditioner
    the in-cycle least-squares estimate ||beta e1 - H y|| is
    systematically optimistic (Arnoldi orthogonality decays at eps32, so
    the estimate can read 1e-16 while the true residual sits at 1e-7 —
    observed), so each restart first measures ||M^-1 (b - A x)|| and the
    loop stops when THAT meets tol.  A converged solve pays exactly one
    extra matvec: the measuring cycle's j >= 1 steps and its update are
    gated off by ``lax.cond``/masking once beta <= tol.  Runs
    max_restarts + 1 cycles so the final update gets measured; a solve
    still unconverged at the budget reports the last measured rnorm
    (conservative: its final update is unmeasured)."""
    from ..ops.matmul import matmul

    n = b.shape[0]
    dtype = b.dtype
    m = restart
    rdt = jnp.real(b).dtype

    def restart_body(i, carry):
        x, rnorm, stop = carry

        def do(x):
            V0 = jnp.zeros((m + 1, n), dtype)
            H0 = jnp.zeros((m + 1, m), dtype)

            def inner(j, st):
                V, H, beta = st
                is0 = j == 0
                # once the j=0 measurement converged, later j skip the
                # operator entirely (the cond's false branch is free)
                active = is0 | (beta > tol)
                jm1 = jnp.maximum(j - 1, 0)
                u = jnp.where(is0, x, V[jm1])
                c = jnp.where(is0, b, jnp.zeros_like(b))
                out = lax.cond(active, lambda uc: pm_resid(*uc),
                               lambda uc: jnp.zeros_like(b), (u, c))
                r0 = out                        # j=0: M^-1 (b - A x)
                w = -out                        # j>=1: M^-1 A V[j-1]
                # j = 0: normalize the residual into V[0]
                b0 = jnp.linalg.norm(r0)
                v0 = r0 / jnp.where(b0 == 0, 1, b0)
                # j >= 1: modified Gram-Schmidt against rows <= j-1
                h = matmul(jnp.conj(V), w[:, None])[:, 0]
                h = h * (jnp.arange(m + 1) <= j - 1).astype(dtype)
                wg = w - matmul(h[None, :], V)[0]
                hn = jnp.linalg.norm(wg)
                vj = wg / jnp.where(hn == 0, 1, hn)
                V = V.at[j].set(jnp.where(is0, v0, jnp.where(active, vj, V[j])))
                Hupd = H.at[:, jm1].set(h + 0).at[j, jm1].set(hn.astype(dtype))
                H = jnp.where(is0 | ~active, H, Hupd)
                return V, H, jnp.where(is0, b0.astype(rdt), beta)

            V, H, beta = lax.fori_loop(
                0, m + 1, inner, (V0, H0, jnp.zeros((), rdt))
            )
            improve = beta > tol
            e1 = jnp.zeros(m + 1, dtype).at[0].set(beta.astype(dtype))
            y = jnp.linalg.lstsq(H, e1)[0]
            upd = matmul(y[None, :], V[:m])[0]
            x = x + jnp.where(improve, upd, jnp.zeros_like(upd))
            return x, beta, ~improve  # stop once a measurement meets tol

        return lax.cond(~stop, do, lambda xx: (xx, rnorm, stop), x)

    x, rnorm, _stop = lax.fori_loop(
        0, max_restarts + 1, restart_body,
        (jnp.zeros_like(b), jnp.asarray(jnp.inf, rdt), jnp.zeros((), bool)),
    )
    return x, rnorm


def _gmres_mesh_common(ad, fact_solve, bcol, restart, max_restarts, la, bi):
    """Left-preconditioned restarted GMRES on one RHS column with the
    operator and preconditioner applied on the mesh."""
    m = ad.m
    p, q = mesh_shape(ad.mesh)
    mt, ntv = ad.tiles.shape[0], padded_tiles(1, ad.nb, ad.mesh)
    dtype = ad.tiles.dtype

    def wrap(t):
        return DistMatrix(tiles=t, m=m, n=1, nb=ad.nb, mesh=ad.mesh)

    def pm_resid(v, c):
        # M^-1 (c - A v): SUMMA matvec + f32 factor sweeps, fused so the
        # whole distributed pipeline is one call site (see _gmres_dist)
        xd = wrap(_vec_to_tiles(v, m, ad.nb, p, q, mt, ntv))
        cd = wrap(_vec_to_tiles(c, m, ad.nb, p, q, mt, ntv))
        rd = gemm_summa(-1.0, ad, xd, 1.0, cd, method=MethodGemm.GemmC,
                        lookahead=la, bcast_impl=bi)
        out = fact_solve(rd)
        return _tiles_to_vec(out.tiles, m, p, q).astype(dtype)

    eps = jnp.finfo(dtype).eps
    tol = (eps * jnp.sqrt(jnp.asarray(float(m), dtype))
           * jnp.linalg.norm(bcol)).astype(dtype)
    from .comm import audit_scope

    # worst-case trip product of the restart x Arnoldi loops: the single
    # pm_resid call site sits inside both fori bodies — max_restarts + 1
    # cycles (the +1 is the final measuring cycle) of restart + 1 inner
    # steps — so the trace-time comm audit records its collectives at
    # the (dynamically unknowable) upper bound, the lint loop-audit
    # contract for dynamic-trip loops
    with audit_scope((max_restarts + 1) * (restart + 1)):
        x, rnorm = _gmres_dist(pm_resid, bcol, restart, tol, max_restarts)
    return x, rnorm, rnorm <= tol


@functools.partial(jax.jit, static_argnums=tuple(range(4, 13)))
def _gmres_posv_jit(at, bcol, lt, info, mesh, p, q, m, nb,
                    restart, max_restarts, la=None, bi="auto"):
    ad = DistMatrix(tiles=at, m=m, n=m, nb=nb, mesh=mesh, diag_pad=True)
    ld = DistMatrix(tiles=lt, m=m, n=m, nb=nb, mesh=mesh, diag_pad=True)

    def fact_solve(rd):
        r32 = _astype_dist(rd, jnp.float32)
        y = trsm_dist(ld, r32, Uplo.Lower, Op.NoTrans, lookahead=la,
                      bcast_impl=bi)
        return trsm_dist(ld, y, Uplo.Lower, Op.ConjTrans, lookahead=la,
                         bcast_impl=bi)

    x, rnorm, conv = _gmres_mesh_common(ad, fact_solve, bcol, restart,
                                        max_restarts, la, bi)
    bad = info != 0
    return jnp.where(bad, jnp.nan, x), rnorm, conv & ~bad


@functools.partial(jax.jit, static_argnums=tuple(range(5, 14)))
def _gmres_gesv_jit(at, bcol, lut, perm, info, mesh, p, q, m, nb,
                    restart, max_restarts, la=None, bi="auto"):
    ad = DistMatrix(tiles=at, m=m, n=m, nb=nb, mesh=mesh, diag_pad=True)
    lud = DistMatrix(tiles=lut, m=m, n=m, nb=nb, mesh=mesh, diag_pad=True)

    def fact_solve(rd):
        r32 = _astype_dist(rd, jnp.float32)
        pr = permute_rows_dist(r32, perm)
        y = trsm_dist(lud, pr, Uplo.Lower, Op.NoTrans, Diag.Unit,
                      lookahead=la, bcast_impl=bi)
        return trsm_dist(lud, y, Uplo.Upper, Op.NoTrans, lookahead=la,
                         bcast_impl=bi)

    x, rnorm, conv = _gmres_mesh_common(ad, fact_solve, bcol, restart,
                                        max_restarts, la, bi)
    bad = info != 0
    return jnp.where(bad, jnp.nan, x), rnorm, conv & ~bad


def _mixed_gmres_solve(kind: str, a, b, mesh, nb, opts, restart, pre=None):
    """Factor + per-column distributed GMRES.  Returns (x, rnorm,
    converged_all, info); the column loop reuses one compiled program.
    ``pre`` is the routing ladder's shared ``_prefactor`` result."""
    from ..obs import flight as _flight

    p, q = mesh_shape(mesh)
    la = _la(opts)
    bi = resolve_bcast_impl(get_option(opts, Option.BcastImpl))
    max_restarts = _max_iter(opts, None)
    from .comm import audit_scope

    fact, perm, info, ad = pre if pre is not None else _prefactor_cached(
        kind, a, mesh, nb, opts)
    b2 = b if b.ndim == 2 else b[:, None]
    cols, rnorms, convs = [], [], []
    # columns after the first are jit-cache hits (one compiled program);
    # the scope keeps the trace-time audit honest about the total volume
    with _flight.no_flight(), audit_scope(b2.shape[1]):
        for j in range(b2.shape[1]):
            if kind == "posv":
                x, rn, cv = _gmres_posv_jit(
                    ad.tiles, b2[:, j], fact.tiles, info, mesh, p, q, ad.m,
                    nb, restart, max_restarts, la, bi,
                )
            else:
                x, rn, cv = _gmres_gesv_jit(
                    ad.tiles, b2[:, j], fact.tiles, perm, info, mesh, p, q,
                    ad.m, nb, restart, max_restarts, la, bi,
                )
            cols.append(x)
            rnorms.append(rn)
            convs.append(cv)
    x = jnp.stack(cols, axis=1) if b.ndim == 2 else cols[0]
    rnorm = jnp.max(jnp.stack(rnorms))
    conv = jnp.all(jnp.stack(convs))
    if not isinstance(conv, jax.core.Tracer):  # metrics are a runtime
        ir_count("ir.gmres_solves", kind)      # surface (see _record_ir)
    return x, rnorm, conv, info


@instrument("posv_mixed_gmres_mesh")
def posv_mixed_gmres_mesh(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None, restart: int = 30,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Distributed GMRES-IR SPD solve (src/posv_mixed_gmres.cc at mesh
    scale): f32 mesh Cholesky preconditioning f64 restarted GMRES.
    Returns (x, rnorm, info); converged when rnorm <= eps*sqrt(n)*||b||
    per column (the refine.py tolerance)."""
    _require_f64(a, "posv_mixed_gmres_mesh")
    x, rnorm, _conv, info = _mixed_gmres_solve("posv", a, b, mesh, nb, opts,
                                               restart)
    return x, rnorm, jnp.asarray(info, jnp.int32)


@instrument("gesv_mixed_gmres_mesh")
def gesv_mixed_gmres_mesh(
    a: jax.Array, b: jax.Array, mesh: Mesh, nb: int = _DEFAULT_NB,
    opts: Optional[Options] = None, restart: int = 30,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Distributed GMRES-IR general solve (src/gesv_mixed_gmres.cc at
    mesh scale): f32 partial-pivot LU preconditioning f64 restarted
    GMRES.  Returns (x, rnorm, info)."""
    _require_f64(a, "gesv_mixed_gmres_mesh")
    x, rnorm, _conv, info = _mixed_gmres_solve("gesv", a, b, mesh, nb, opts,
                                               restart)
    return x, rnorm, jnp.asarray(info, jnp.int32)


# ---------------------------------------------------------------------------
# Default routing: the Option.MixedPrecision ladder behind gesv_mesh /
# posv_mesh.  IR -> GMRES-IR -> full-f64 fallback; each readback is one
# host sync BETWEEN programs (never inside a loop).
# ---------------------------------------------------------------------------


def _route_health(kind, pre, opts) -> bool:
    """The measured-health entry-tier decision for ``MixedPrecision=auto``
    under Option.NumMonitor=on: read the monitored f32 factor's in-carry
    gauges (element growth / Cholesky diagonal margin — already recorded
    by the factor kernel), run the distributed Hager-Higham condition
    estimate over the factored tiles (dist_aux.gecondest_dist /
    pocondest_dist: ~2*iters+1 single-column mesh trsm solve pairs), and
    return True when the input sits in the IR-cannot-converge regime so
    the ladder enters at GMRES-IR."""
    from ..obs import numerics as _num
    from .dist_aux import gecondest_dist, pocondest_dist

    fact, perm, info, ad = pre
    try:
        if int(info) != 0:
            return False  # failed factor: the existing NaN/fallback path
    except (jax.errors.TracerIntegerConversionError,
            jax.errors.ConcretizationTypeError):
        return False
    la = _la(opts)
    bi = get_option(opts, Option.BcastImpl)
    gauges = _num.last_gauges("potrf" if kind == "posv" else "getrf_pp")
    anorm = norm_dist(Norm.One, ad)
    if kind == "posv":
        rcond = pocondest_dist(fact, anorm, lookahead=la, bcast_impl=bi)
    else:
        rcond = gecondest_dist(fact, perm, anorm, lookahead=la,
                               bcast_impl=bi)
    if _num.route_entry_tier(kind, gauges, float(rcond)):
        _num.record_routed_gmres(kind)
        return True
    return False


def mixed_mesh_route(kind, a, b, mesh, nb, opts, plain_fn):
    """Route an f64 ``gesv_mesh``/``posv_mesh`` call through the mixed
    ladder per the resolved Option.MixedPrecision.  Returns (x, info), or
    None when the direct path should run (mode off, non-f64 dtype, a
    non-2D RHS, or TRACED operands) — all decided before any tracing, so
    the direct path's jaxpr is untouched (asserted in
    tests/test_mixed_mesh.py).

    The ladder is host-DRIVEN by design: each tier is a fused on-device
    program, but the tier-to-tier decision (converged? escalate?) is one
    scalar readback between programs.  Under an outer jit/vmap/make_jaxpr
    there is no host between programs, so traced calls keep the direct
    f64 path — which is also exactly the pre-mixed trace semantics of
    the public drivers (a user jitting gesv_mesh gets the same jaxpr as
    before this routing existed; the mixed tiers are reachable under
    jit via the explicit ``*_mixed_mesh`` drivers' fused programs).

    Health-aware entry tier (ISSUE 10): under Option.NumMonitor=on (auto
    = on when the obs layer is enabled) the f32 factor runs MONITORED —
    its element-growth / diagonal-margin gauges ride the k-loop carry —
    and ``auto`` mode additionally runs a distributed Hager-Higham
    condition estimate over the just-computed factor (a handful of mesh
    trsm solves on one column, no O(n^3)).  Pathological health —
    growth above numerics.GROWTH_THRESHOLD or cond(A) above
    numerics.CONDEST_THRESHOLD, the regime where classic IR on an f32
    factor is known to stall (Carson & Higham 2018) — skips the IR tier
    entirely and enters at GMRES-IR (``num.routed_gmres``), instead of
    burning max_iter refinement iterations to learn the same fact."""
    mode = resolve_mixed(opts)
    if (mode == "off" or getattr(a, "dtype", None) != jnp.float64
            or getattr(b, "ndim", 0) != 2
            or isinstance(a, jax.core.Tracer)
            or isinstance(b, jax.core.Tracer)):
        return None
    from ..obs import driver_span
    from ..obs import numerics as _num

    nm_on = _num.resolve_num_monitor(_num.monitor_from_opts(opts)) == "on"
    if nm_on:
        # pin the resolved mode into the opts every tier consumes, so the
        # f32 factor's k-loop carries the gauges the router reads
        opts = dict(opts or {})
        opts[Option.NumMonitor] = "on"
    drv = posv_mixed_mesh if kind == "posv" else gesv_mixed_mesh
    with driver_span(f"{kind}_mixed", mode=mode) as sp:
        # one f32 factor for the whole ladder: the GMRES tier
        # preconditions with the exact factor the IR tier refined on.
        # Clear the op's last-gauge entry first so the router only ever
        # reads THIS factor's health — a factor path that records no
        # gauges (e.g. Option.FaultTolerance routes to the ABFT kernels,
        # which carry no monitor) yields an empty dict and the routing
        # decision falls back to the condest alone
        if nm_on:
            _num.clear_last("potrf" if kind == "posv" else "getrf_pp")
        pre = _prefactor_cached(kind, a, mesh, nb, opts)
        skip_ir = False
        if nm_on and mode == "auto":
            with sp.phase("health"):
                skip_ir = _route_health(kind, pre, opts)
        if mode in ("ir", "auto") and not skip_ir:
            with sp.phase("ir"):
                x, iters, info = drv(a, b, mesh, nb, opts=opts, pre=pre)
            if int(info) == 0 and int(iters) >= 0:
                return x, info
        if mode in ("gmres", "auto"):
            if mode == "auto" and not skip_ir:
                # gmres-pinned runs it as tier 1 and a health-routed
                # entry (num.routed_gmres) is a ROUTE, not an escalation
                # — only an IR tier that actually ran and failed counts
                ir_count("ir.escalated_gmres", kind)
            with sp.phase("gmres"):
                x, rnorm, conv, info = _mixed_gmres_solve(
                    kind, a, b, mesh, nb, opts, restart=30, pre=pre
                )
            if int(info) == 0 and bool(conv):
                return x, info
        if not get_option(opts, Option.UseFallbackSolver, True):
            # the caller opted out of the f64 fallback: surface the best
            # mixed-tier result (NaN x / info != 0 on a failed factor)
            return x, info
        ir_count("ir.fallback", kind)
        with sp.phase("fallback"):
            return plain_fn()
