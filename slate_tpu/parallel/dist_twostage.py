"""Distributed stage-1 two-stage reductions: he2hb and ge2tb on the mesh.

TPU-native analogue of the reference's distributed stage-1 kernels
(``src/he2hb.cc:207-604``: panel QR on the grid column + distributed
two-sided block update via he2hb_{hemm,her2k,trmm,gemm} internal ops, and
``src/ge2tb.cc``: alternating distributed QR/LQ panels).  Stage 2 (the
band -> tridiagonal / bidiagonal bulge chase) stays a single-program
wavefront kernel (linalg.eig.hb2st / linalg.svd.tb2bd) on the gathered
band — the band is (n, nb), tiny next to the O(n^2) matrix, which matches
the reference's placement of hb2st/tb2bd on the node that owns the band.

Design: the panel factorization is REPLICATED, the trailing update is
DISTRIBUTED.  Per panel k every device receives the full (m, nb) panel
column (one masked psum along 'q' + one all_gather along 'p', m * nb
elements) and runs the same offset-pivot panel QR — the panel is O(m nb^2)
flops, negligible next to the O(n^2 nb) trailing update, and replicating
it deletes the reference's panel-rank round trips (he2hb.cc:238-287).
The two-sided update B -= W V^H + V W^H runs on the local tile stacks
with W/V sliced by each device's global row/column ids: Y = A V is a
local flat gemm + psum over 'q', the W~ = Y T - 1/2 V (T^H V^H Y T)
algebra is replicated (m x nb), and the rank-2nb update is two local
outer products.  Reflectors are stored SHARDED by mesh row ('p') so the
distributed back-transform (unmtr_he2hb on a DistMatrix of eigenvectors)
runs with one psum per panel and no reflector gathers.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..linalg.eig import _he2hb_panel_count
from ..obs import instrument
from ..obs.numerics import resolve_num_monitor
from ..linalg.qr import _larft_v, _panel_qr_offset
from .comm import (PRECISE, all_gather_a, audit_scope, bcast_from_col,
                   bcast_from_row, bcast_impl_scope, local_indices,
                   num_gauge_dtype, phase_scope, psum_a, resolve_bcast_impl,
                   shard_map_compat)
from .dist import DistMatrix
from .dist_qr import _qr_orth_loss
from .mesh import COL_AXIS, ROW_AXIS, mesh_shape


def _to_global_rows(x_loc: jax.Array, nparts: int, nb: int, axis_name: str):
    """All-gather a per-device row slice (cyclic tile order) into the full
    GLOBAL flat row order: gathered slot r holds logical tiles {i : i %
    nparts == r} at slot i // nparts, so a (slot, r) transpose linearizes
    to logical tile index i = slot * nparts + r."""
    mfl, w = x_loc.shape
    mtl = mfl // nb
    ag = all_gather_a(x_loc, axis_name, axis=0)  # (nparts, mfl, w)
    ag = ag.reshape(nparts, mtl, nb, w).transpose(1, 0, 2, 3)
    return ag.reshape(mtl * nparts * nb, w)


class DistTwoStage(NamedTuple):
    """Stage-1 factors: reflectors sharded along one mesh axis, compact-WY
    accumulators replicated."""

    band: DistMatrix
    vq: jax.Array  # (K, p * mfl, nb) — global rows, sharded over 'p'
    tq: jax.Array  # (K, nb, nb) replicated
    vl: jax.Array  # ge2tb only: (K, q * nfl, nb) — A-cols, sharded over 'q'
    tl: jax.Array  # ge2tb only: (K, nb, nb)


# ---------------------------------------------------------------------------
# he2hb: full Hermitian -> band over the mesh (src/he2hb.cc)
# ---------------------------------------------------------------------------


@instrument("he2hb_dist")
def he2hb_dist(a: DistMatrix, bcast_impl=None,
               num_monitor=None) -> DistTwoStage:
    """Reduce the full Hermitian DistMatrix (both triangles stored) to a
    Hermitian band of bandwidth nb; Q panels sharded over mesh rows.

    ``bcast_impl`` (Option.BcastImpl) picks the panel-broadcast lowering
    (ISSUE 15: the he2hb panel column now rides the rooted engine like
    geqrf's — bitwise-identical across lowerings).  ``num_monitor``
    (Option.NumMonitor): ``on`` carries the per-panel reflector/τ
    orthogonality-loss proxy — the first eig-chain gauge — as a running
    max through the k-loop; the panel QR is REPLICATED, so the gauge is
    collective-free and lands as ``num.he2hb_orth_margin``.  ``off`` is
    jaxpr-IDENTICAL."""
    from ..obs import flight as _flight
    from ..obs import numerics as _num

    p, q = mesh_shape(a.mesh)
    if a.m != a.n:
        raise ValueError("he2hb_dist needs a square matrix")
    nsteps = _he2hb_panel_count(a.n, a.nb)
    bi = resolve_bcast_impl(bcast_impl)
    nm = resolve_num_monitor(num_monitor) == "on"
    if _flight.step_dispatch_active() and nsteps:
        # flight-recorder step dispatch: same arithmetic, fenced per
        # phase (no gauges — monitoring is the fused kernel's surface)
        bt, vs, ts = _flight.he2hb_steps(
            a.tiles, a.mesh, p, q, a.n, a.nb, nsteps, bi)
    elif nm:
        bt, vs, ts, g = _he2hb_jit(a.tiles, a.mesh, p, q, a.n, a.nb,
                                   nsteps, bi, True)
        _num.record_he2hb_orth("he2hb", g)
    else:
        bt, vs, ts = _he2hb_jit(a.tiles, a.mesh, p, q, a.n, a.nb, nsteps,
                                bi, False)
    band = DistMatrix(tiles=bt, m=a.m, n=a.n, nb=a.nb, mesh=a.mesh)
    return DistTwoStage(band, vs, ts, vs[:0], ts[:0])


def _he2hb_fetch(k, a, p, q, nb):
    """Step k's full panel column in global row order, replicated: one
    rooted column broadcast + one row all_gather (the he2hb bcast
    phase's comm-audit volume).  Module-level (the dist_chol/_lu
    phase-helper contract) so the fused loop, the checkpointed segments,
    and the flight recorder's per-step dispatches share one
    arithmetic."""
    mfl, nfl = a.shape
    mtl, ntl = mfl // nb, nfl // nb
    _r, c, _il, _jl = local_indices(p, q, mtl, ntl)
    kc = k // q
    mine_c = c == k % q
    pcol = lax.dynamic_slice(a, (0, kc * nb), (mfl, nb))
    pcol = bcast_from_col(jnp.where(mine_c, pcol, 0), k % q)
    return _to_global_rows(pcol, p, nb, ROW_AXIS)


def _he2hb_panel(k, gpan, n_true, nb):
    """Step k's REPLICATED offset panel QR + compact-WY T of the gathered
    column — every device computes the same (R, V, T), so anything
    derived from them (e.g. the orthogonality-loss gauge) is
    collective-free."""
    mglob = gpan.shape[0]
    grows = jnp.arange(mglob)
    c0 = k * nb + nb
    masked = jnp.where(((grows >= c0) & (grows < n_true))[:, None], gpan, 0)
    r_a, v, tau = _panel_qr_offset(masked, c0)
    return r_a, v, _larft_v(v, tau)


def _he2hb_update(k, carry, gpan, pan, p, q, n_true, nb):
    """The remainder of the strict-schedule he2hb step: write R + its
    mirror into the band column/row, then the distributed two-sided
    trailing update A -= W~ V^H + V W~^H (one psum over 'q' + one row
    all_gather)."""
    a, vqs, tqs = carry
    r_a, v, t = pan
    mfl, nfl = a.shape
    mtl, ntl = mfl // nb, nfl // nb
    dtype = a.dtype
    r, c, i_log, j_log = local_indices(p, q, mtl, ntl)
    rg = (i_log[:, None] * nb + jnp.arange(nb)[None, :]).reshape(-1)
    cg = (j_log[:, None] * nb + jnp.arange(nb)[None, :]).reshape(-1)
    mglob = mtl * p * nb
    grows = jnp.arange(mglob)
    j0 = k * nb
    c0 = j0 + nb
    kc, kr = k // q, k // p
    mine_c, mine_r = c == k % q, r == k % p

    # write [history above c0 | R; 0] into the panel column + mirror
    newpan = jnp.where((grows >= c0)[:, None], r_a, gpan)
    a = jnp.where(
        mine_c,
        lax.dynamic_update_slice(a, newpan[rg], (0, kc * nb)),
        a,
    )
    rowblk = lax.dynamic_slice(a, (kr * nb, 0), (nb, nfl))
    # mask the cg gather explicitly: on meshes where padded global
    # cols exceed padded global rows, cg indexes past newpan's rows
    # and JAX clamps silently — zero those tiles so pad stays zero
    cg_ok = (cg < mglob)[:, None]
    mirr = jnp.conj(jnp.where(cg_ok, newpan[jnp.minimum(cg, mglob - 1)], 0)).T
    rowblk_new = jnp.where((cg >= c0)[None, :], mirr, rowblk)
    a = jnp.where(
        mine_r,
        lax.dynamic_update_slice(a, rowblk_new, (kr * nb, 0)),
        a,
    )

    # two-sided trailing update (he2hb.cc:207-604 algebra):
    # Y = A V (local gemm + psum over 'q'), W~ replicated, then
    # A -= W~ V^H + V W~^H on the local stack
    v_rows = v[rg]
    v_cols = jnp.where(cg_ok, v[jnp.minimum(cg, mglob - 1)], 0)
    y_part = jnp.einsum("rc,ci->ri", a, v_cols, precision=PRECISE)
    y = psum_a(y_part, COL_AXIS)
    y = jnp.where((rg >= c0)[:, None], y, 0).astype(dtype)
    yg = _to_global_rows(y, p, nb, ROW_AXIS)
    wmat = jnp.einsum("ri,ij->rj", yg, t, precision=PRECISE)
    x = jnp.einsum(
        "ji,jk->ik", jnp.conj(t),
        jnp.einsum("ri,rj->ij", jnp.conj(v), wmat, precision=PRECISE),
        precision=PRECISE,
    )
    wt = (wmat - 0.5 * jnp.einsum("ri,ij->rj", v, x, precision=PRECISE)).astype(dtype)
    wt_rows = wt[rg]
    wt_cols = jnp.where(cg_ok, wt[jnp.minimum(cg, mglob - 1)], 0)
    upd = jnp.einsum("ri,ci->rc", wt_rows, jnp.conj(v_cols), precision=PRECISE)
    upd = upd + jnp.einsum(
        "ri,ci->rc", v_rows, jnp.conj(wt_cols), precision=PRECISE
    )
    a = a - upd.astype(dtype)
    return a, vqs.at[k].set(v[rg]), tqs.at[k].set(t)


def _he2hb_step(k, carry, p, q, n_true, nb, nm=False):
    """One he2hb panel + two-sided trailing update of the strict schedule
    on the full local FLAT view (carry = (a_flat, vq stack, tq stack)) —
    the composition of the module-level phase helpers above, with
    ``phase_scope`` tags (trace-time bookkeeping only, no jaxpr change)
    so one ``sched_audit`` trace of the fused kernel yields the
    per-phase schedule the flight recorder's ``ScheduleModel`` consumes.

    Module-level so the fused ``_he2hb_jit`` loop and the checkpointed
    segment chain (``ft/ckpt._he2hb_seg_jit``) run the IDENTICAL
    per-element arithmetic — chained segments reproduce the fused kernel
    bitwise at any boundary set (the dist_chol/_lu step-helper
    contract).

    ``nm=True`` additionally returns this step's reflector/τ
    orthogonality-loss scalar (``dist_qr._qr_orth_loss`` on the
    REPLICATED panel factors — collective-free); the default leaves the
    computation, and hence the unmonitored jaxpr, untouched."""
    with phase_scope("bcast", k):
        gpan = _he2hb_fetch(k, carry[0], p, q, nb)
    with phase_scope("panel", k):
        pan = _he2hb_panel(k, gpan, n_true, nb)
    with phase_scope("bulk", k):
        out = _he2hb_update(k, carry, gpan, pan, p, q, n_true, nb)
    if nm:
        return out, _qr_orth_loss(pan[1], pan[2],
                                  num_gauge_dtype(carry[0].dtype))
    return out


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6, 7, 8))
def _he2hb_jit(at, mesh, p, q, n_true, nb, nsteps, bi="psum", nm=False):
    spec = P(ROW_AXIS, COL_AXIS)

    def kernel(t_loc):
        mtl, ntl, _, _ = t_loc.shape
        dtype = t_loc.dtype
        mfl, nfl = mtl * nb, ntl * nb
        a = jnp.transpose(t_loc, (0, 2, 1, 3)).reshape(mfl, nfl)

        vqs0 = jnp.zeros((max(nsteps, 1), mfl, nb), dtype)
        tqs0 = jnp.zeros((max(nsteps, 1), nb, nb), dtype)
        if not nm:
            def step(k, carry):
                return _he2hb_step(k, carry, p, q, n_true, nb)

            if nsteps:
                with audit_scope(nsteps):
                    a2, vqs, tqs = lax.fori_loop(0, nsteps, step,
                                                 (a, vqs0, tqs0))
            else:
                a2, vqs, tqs = a, vqs0, tqs0
            t_out = jnp.transpose(a2.reshape(mtl, nb, ntl, nb), (0, 2, 1, 3))
            return t_out, vqs, tqs

        # monitored loop (ISSUE 15): the per-panel orthogonality-loss
        # proxy rides the carry as a running max.  The panel factors are
        # REPLICATED (every device ran the same gathered-column QR), so
        # the gauge needs no reduction at all — collective-free, audited
        # wire bytes unchanged.
        rdt = num_gauge_dtype(dtype)

        def step_nm(k, carry):
            *st3, gg = carry
            out3, loss = _he2hb_step(k, tuple(st3), p, q, n_true, nb,
                                     nm=True)
            return out3 + (jnp.maximum(gg, loss),)

        g0 = jnp.zeros((), rdt)
        if nsteps:
            with audit_scope(nsteps):
                a2, vqs, tqs, gg = lax.fori_loop(
                    0, nsteps, step_nm, (a, vqs0, tqs0, g0))
        else:
            a2, vqs, tqs, gg = a, vqs0, tqs0, g0
        t_out = jnp.transpose(a2.reshape(mtl, nb, ntl, nb), (0, 2, 1, 3))
        return t_out, vqs, tqs, gg

    out_specs = (spec, P(None, ROW_AXIS), P())
    if nm:
        out_specs = out_specs + (P(),)
    with bcast_impl_scope(bi):
        return shard_map_compat(
            kernel,
            mesh=mesh,
            in_specs=(spec,),
            out_specs=out_specs,
            check_vma=False,
        )(at)


@instrument("unmtr_he2hb_dist")
def unmtr_he2hb_dist(f: DistTwoStage, z: DistMatrix, adjoint: bool = False) -> DistMatrix:
    """Z <- Q Z (or Q^H Z) for the distributed stage-1 Q: one psum along
    'p' per panel, reflectors consumed from their sharded storage
    (src/unmtr_he2hb.cc)."""
    p, q = mesh_shape(z.mesh)
    if f.band.mt != z.mt or f.band.nb != z.nb:
        raise ValueError("unmtr_he2hb_dist operand mismatch")
    zt = _apply_row_panels_jit(f.vq, f.tq, z.tiles, z.mesh, p, q, adjoint)
    return DistMatrix(tiles=zt, m=z.m, n=z.n, nb=z.nb, mesh=z.mesh)


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _apply_row_panels_jit(vqs, tqs, zt, mesh, p, q, adjoint):
    spec = P(ROW_AXIS, COL_AXIS)
    nsteps = vqs.shape[0]

    def kernel(vq_loc, tq, z_loc):
        mtl, ntl, nb, _ = z_loc.shape
        mfl, nfl = mtl * nb, ntl * nb
        z = jnp.transpose(z_loc, (0, 2, 1, 3)).reshape(mfl, nfl)
        dtype = z.dtype

        def body(i, z):
            k = i if adjoint else nsteps - 1 - i
            v = vq_loc[k]
            t = jnp.conj(tq[k]).T if adjoint else tq[k]
            w1 = psum_a(
                jnp.einsum("ri,rc->ic", jnp.conj(v), z, precision=PRECISE),
                ROW_AXIS,
            )
            upd = jnp.einsum("ri,ij,jc->rc", v, t, w1, precision=PRECISE)
            return z - upd.astype(dtype)

        with audit_scope(nsteps):
            z = lax.fori_loop(0, nsteps, body, z)
        return jnp.transpose(z.reshape(mtl, nb, ntl, nb), (0, 2, 1, 3))

    return shard_map_compat(
        kernel,
        mesh=mesh,
        in_specs=(P(None, ROW_AXIS), P(), spec),
        out_specs=spec,
        check_vma=False,
    )(vqs, tqs, zt)


# ---------------------------------------------------------------------------
# ge2tb: general -> upper triangular band over the mesh (src/ge2tb.cc)
# ---------------------------------------------------------------------------


@instrument("ge2tb_dist")
def ge2tb_dist(a: DistMatrix) -> DistTwoStage:
    """Reduce a general (m >= n) DistMatrix to an upper triangular band of
    bandwidth nb via alternating distributed QR/LQ panels; U-side
    reflectors sharded over 'p', V-side over 'q'."""
    p, q = mesh_shape(a.mesh)
    if a.m < a.n:
        raise ValueError(f"ge2tb_dist requires m >= n, got {a.m}x{a.n}")
    nblocks = -(-a.n // a.nb)
    bt, vqs, tqs, vls, tls = _ge2tb_jit(
        a.tiles, a.mesh, p, q, a.m, a.n, a.nb, nblocks
    )
    band = DistMatrix(tiles=bt, m=a.m, n=a.n, nb=a.nb, mesh=a.mesh)
    return DistTwoStage(band, vqs, tqs, vls, tls)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6, 7))
def _ge2tb_jit(at, mesh, p, q, m_true, n_true, nb, nblocks):
    spec = P(ROW_AXIS, COL_AXIS)

    def kernel(t_loc):
        mtl, ntl, _, _ = t_loc.shape
        dtype = t_loc.dtype
        r, c, i_log, j_log = local_indices(p, q, mtl, ntl)
        mfl, nfl = mtl * nb, ntl * nb
        rg = (i_log[:, None] * nb + jnp.arange(nb)[None, :]).reshape(-1)
        cg = (j_log[:, None] * nb + jnp.arange(nb)[None, :]).reshape(-1)
        a = jnp.transpose(t_loc, (0, 2, 1, 3)).reshape(mfl, nfl)
        grows = jnp.arange(mtl * p * nb)
        gcols = jnp.arange(ntl * q * nb)

        def step(k, carry):
            a, vqs, tqs, vls, tls = carry
            j0 = k * nb
            j1 = j0 + nb
            kc, kr = k // q, k // p
            mine_c, mine_r = c == k % q, r == k % p

            # ---- QR panel: eliminate below-diagonal of block column k ----
            pcol = lax.dynamic_slice(a, (0, kc * nb), (mfl, nb))
            pcol = bcast_from_col(jnp.where(mine_c, pcol, 0), k % q)
            gpan = _to_global_rows(pcol, p, nb, ROW_AXIS)
            masked = jnp.where(((grows >= j0) & (grows < m_true))[:, None], gpan, 0)
            r_a, vq, tauq = _panel_qr_offset(masked, j0)
            tq = _larft_v(vq, tauq)
            # left trailing update on cols >= j1: A -= Vq T^H (Vq^H A)
            vq_rows = vq[rg]
            w1 = psum_a(
                jnp.einsum("ri,rc->ic", jnp.conj(vq_rows), a, precision=PRECISE),
                ROW_AXIS,
            )
            upd = jnp.einsum(
                "ri,ij,jc->rc", vq_rows, jnp.conj(tq).T, w1, precision=PRECISE
            ).astype(dtype)
            a = a - jnp.where((cg >= j1)[None, :], upd, 0)
            newpan = jnp.where((grows >= j0)[:, None], r_a, gpan)
            a = jnp.where(
                mine_c,
                lax.dynamic_update_slice(a, newpan[rg], (0, kc * nb)),
                a,
            )

            # ---- LQ panel on block row k (QR of its conj transpose) ----
            lq_active = j1 < n_true - 1
            rowblk = lax.dynamic_slice(a, (kr * nb, 0), (nb, nfl))
            rowb = bcast_from_row(jnp.where(mine_r, rowblk, 0), k % p)
            # to global col order: gather the (nfl, nb) transpose by cols
            growb = _to_global_rows(jnp.conj(rowb).T, q, nb, COL_AXIS)  # (nglob, nb)
            maskedh = jnp.where(
                ((gcols >= j1) & lq_active)[:, None], growb, 0
            )
            l_a, vl, taul = _panel_qr_offset(maskedh, j1)
            tl = _larft_v(vl, taul)
            vl = vl * jnp.asarray(lq_active, dtype)
            tl = tl * jnp.asarray(lq_active, dtype)
            # right trailing update on rows >= j1: A -= (A Vl) Tl Vl^H
            vl_cols = vl[cg]
            w2 = psum_a(
                jnp.einsum("rc,ci->ri", a, vl_cols, precision=PRECISE), COL_AXIS
            )
            upd2 = jnp.einsum(
                "ri,ij,cj->rc", w2, tl, jnp.conj(vl_cols), precision=PRECISE
            ).astype(dtype)
            a = a - jnp.where((rg >= j1)[:, None], upd2, 0)
            newrow = jnp.where(
                ((cg >= j1) & lq_active)[None, :], jnp.conj(l_a[cg]).T, rowblk
            )
            a = jnp.where(
                mine_r,
                lax.dynamic_update_slice(a, newrow, (kr * nb, 0)),
                a,
            )
            return (
                a,
                vqs.at[k].set(vq[rg]),
                tqs.at[k].set(tq),
                vls.at[k].set(vl[cg]),
                tls.at[k].set(tl),
            )

        vqs0 = jnp.zeros((nblocks, mfl, nb), dtype)
        tqs0 = jnp.zeros((nblocks, nb, nb), dtype)
        vls0 = jnp.zeros((nblocks, nfl, nb), dtype)
        tls0 = jnp.zeros((nblocks, nb, nb), dtype)
        with audit_scope(nblocks):
            a, vqs, tqs, vls, tls = lax.fori_loop(
                0, nblocks, step, (a, vqs0, tqs0, vls0, tls0)
            )
        t_out = jnp.transpose(a.reshape(mtl, nb, ntl, nb), (0, 2, 1, 3))
        return t_out, vqs, tqs, vls, tls

    return shard_map_compat(
        kernel,
        mesh=mesh,
        in_specs=(spec,),
        out_specs=(spec, P(None, ROW_AXIS), P(), P(None, COL_AXIS), P()),
        check_vma=False,
    )(at)


@instrument("unmbr_ge2tb_u_dist")
def unmbr_ge2tb_u_dist(f: DistTwoStage, z: DistMatrix, adjoint: bool = False) -> DistMatrix:
    """Z <- Q Z for the stage-1 U factor (src/unmbr_ge2tb.cc U side) —
    identical panel-apply loop to unmtr_he2hb_dist."""
    p, q = mesh_shape(z.mesh)
    if f.band.mt != z.mt or f.band.nb != z.nb:
        raise ValueError("unmbr_ge2tb_u_dist operand mismatch")
    zt = _apply_row_panels_jit(f.vq, f.tq, z.tiles, z.mesh, p, q, adjoint)
    return DistMatrix(tiles=zt, m=z.m, n=z.n, nb=z.nb, mesh=z.mesh)


@instrument("unmbr_ge2tb_v_dist")
def unmbr_ge2tb_v_dist(f: DistTwoStage, z: DistMatrix) -> DistMatrix:
    """Z <- P Z for the stage-1 V factor: the reflectors live in A's
    COLUMN space (sharded over 'q') while Z's rows are sharded over 'p',
    so each panel is re-gathered to global order (n * nb elements) and
    sliced by Z's row ids — one all_gather + one psum per panel."""
    p, q = mesh_shape(z.mesh)
    if f.band.nt * f.band.nb != z.mt * z.nb or f.band.nb != z.nb:
        raise ValueError("unmbr_ge2tb_v_dist operand mismatch")
    zt = _apply_col_panels_jit(f.vl, f.tl, z.tiles, z.mesh, p, q)
    return DistMatrix(tiles=zt, m=z.m, n=z.n, nb=z.nb, mesh=z.mesh)


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _apply_col_panels_jit(vls, tls, zt, mesh, p, q):
    spec = P(ROW_AXIS, COL_AXIS)
    nsteps = vls.shape[0]

    def kernel(vl_loc, tl, z_loc):
        mtl, ntl, nb, _ = z_loc.shape
        mfl, nfl = mtl * nb, ntl * nb
        z = jnp.transpose(z_loc, (0, 2, 1, 3)).reshape(mfl, nfl)
        dtype = z.dtype
        _, _, i_log, _ = local_indices(p, q, mtl, ntl)
        rg = (i_log[:, None] * nb + jnp.arange(nb)[None, :]).reshape(-1)

        def body(i, z):
            k = nsteps - 1 - i
            gvl = _to_global_rows(vl_loc[k], q, nb, COL_AXIS)
            v = gvl[jnp.minimum(rg, gvl.shape[0] - 1)]
            v = jnp.where((rg < gvl.shape[0])[:, None], v, 0)
            w1 = psum_a(
                jnp.einsum("ri,rc->ic", jnp.conj(v), z, precision=PRECISE),
                ROW_AXIS,
            )
            upd = jnp.einsum("ri,ij,jc->rc", v, tl[k], w1, precision=PRECISE)
            return z - upd.astype(dtype)

        with audit_scope(nsteps):
            z = lax.fori_loop(0, nsteps, body, z)
        return jnp.transpose(z.reshape(mtl, nb, ntl, nb), (0, 2, 1, 3))

    return shard_map_compat(
        kernel,
        mesh=mesh,
        in_specs=(P(None, COL_AXIS), P(), spec),
        out_specs=spec,
        check_vma=False,
    )(vls, tls, zt)


# ---------------------------------------------------------------------------
# Stage 2 distribution (VERDICT r3 item 4, reference src/unmtr_hb2st.cc):
# the band travels as O(n w) diagonals, the bulge-chase reflector family is
# SHARDED over all p*q devices, and the back-transform streams one sweep
# block at a time to Z's column shards — no O(n^2) replication anywhere in
# the stage-2 chain.
# ---------------------------------------------------------------------------


def gather_diagband(band: DistMatrix, w: int) -> jax.Array:
    """Diagonal-band storage (n, 4w) of the distributed band matrix,
    replicated (O(n w) bytes — the analogue of the reference's he2hbGather
    to the rank that runs hb2st, HermitianBandMatrix.hh:305).  Each device
    scatters its local tiles' near-diagonal elements into the diagonal
    frame, then one psum over both mesh axes."""
    p, q = mesh_shape(band.mesh)
    return _gather_diagband_jit(band.tiles, band.mesh, p, q, band.nb, w)[: band.m]


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def _gather_diagband_jit(tiles, mesh, p, q, nb, w):
    D = 4 * w

    def kernel(t_loc):
        mtl, ntl, _, _ = t_loc.shape
        _, _, i_log, j_log = local_indices(p, q, mtl, ntl)
        a = jnp.arange(nb)
        # per local tile (ti, tj): element (x, y) lands at global row
        # i_log[ti]*nb + x, diagonal offset (j_log[tj]-i_log[ti])*nb + y - x
        gi0 = (i_log[:, None] * nb + a[None, :]).reshape(-1)  # (mtl*nb,)
        dd = (
            (j_log[None, :, None, None] - i_log[:, None, None, None]) * nb
            + a[None, None, None, :]
            - a[None, None, :, None]
            + 2 * w
        )  # (mtl, ntl, nb, nb)
        ok = (dd >= 0) & (dd < D)
        vals = jnp.where(ok, t_loc, 0)
        out = jnp.zeros((mtl * p * nb, D), t_loc.dtype)
        rows = jnp.broadcast_to(
            gi0[:, None, None], (mtl * nb, ntl, nb)
        )  # row id per (flat row, tile col, y)
        flat_rows = rows.reshape(-1)
        flat_dd = jnp.clip(dd, 0, D - 1).transpose(0, 2, 1, 3).reshape(-1)
        out = out.at[flat_rows, flat_dd].add(
            vals.transpose(0, 2, 1, 3).reshape(-1), mode="drop"
        )
        return psum_a(out, (ROW_AXIS, COL_AXIS))

    return shard_map_compat(
        kernel,
        mesh=mesh,
        in_specs=(P(ROW_AXIS, COL_AXIS),),
        out_specs=P(),
        check_vma=False,
    )(tiles)


@instrument("chase_apply_dist")
def chase_apply_dist(vs, taus, z, n: int, w: int, mesh,
                     bcast_impl=None) -> jax.Array:
    """Z <- U Z for a bulge-chase reflector basis with Z column-sharded
    over ALL p*q devices and the (sweep, hop) family sharded by sweep
    blocks — the distributed unmtr_hb2st / unmbr_tb2bd (reference
    src/unmtr_hb2st.cc:1-80).  Block b travels from its linearized owner
    (r, c) = (b // q, b % q) as a TWO-HOP rooted broadcast — along the
    row axis from mesh row r, then along the column axis from mesh
    column c (the ``bcast_diag_tile`` pattern; formerly a waived
    tuple-axis masked psum) — lowered per ``bcast_impl``
    (Option.BcastImpl: ppermute ring/doubling at half the all-reduce
    bytes, or the legacy masked psum), O(n^2/p) per step either way, and
    applied locally to my column shard via the offset
    _chase_sweep_apply; peak per-device memory is O(n^2 / (p q)), never
    the O(n^2) of the replicated form (asserted by
    tests/test_parallel.py::test_chase_apply_dist_memory)."""
    p, q = mesh_shape(mesh)
    nparts = p * q
    nsweeps, max_hops, wv = vs.shape
    assert wv == w
    blk = -(-nsweeps // nparts)
    vs_p = jnp.pad(vs, ((0, blk * nparts - nsweeps), (0, 0), (0, 0)))
    ta_p = jnp.pad(taus, ((0, blk * nparts - nsweeps), (0, 0)))
    ncols = z.shape[1]
    cpad = (-ncols) % nparts
    zp = jnp.pad(z, ((0, 0), (0, cpad)))
    out = _chase_apply_dist_jit(vs_p, ta_p, zp, mesh, p, q, n, w, blk,
                                resolve_bcast_impl(bcast_impl))
    return out[:, :ncols]


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6, 7, 8, 9))
def _chase_apply_dist_jit(vs, taus, z, mesh, p, q, n, w, blk, bi="auto"):
    from ..linalg.eig import _chase_sweep_apply

    nparts = p * q
    both = (ROW_AXIS, COL_AXIS)

    def kernel(vs_loc, ta_loc, z_loc):
        def body(b, z_loc):
            src = nparts - 1 - b  # reverse chronological block order
            # two-hop rooted broadcast from the linearized owner: hop 1
            # delivers mesh row (src // q)'s local block down each
            # column, hop 2 roots at mesh column (src % q) — every
            # device then holds device (src // q, src % q)'s exact bytes
            # (bitwise what the masked tuple-axis psum summed out of
            # zeros, at half the wire bytes under the engine lowerings)
            vs_b = bcast_from_col(bcast_from_row(vs_loc, src // q), src % q)
            ta_b = bcast_from_col(bcast_from_row(ta_loc, src // q), src % q)
            return _chase_sweep_apply(vs_b, ta_b, z_loc, n, w, False, j0=src * blk)

        with audit_scope(nparts):
            return lax.fori_loop(0, nparts, body, z_loc)

    with bcast_impl_scope(bi):
        return shard_map_compat(
            kernel,
            mesh=mesh,
            in_specs=(P(both), P(both), P(None, both)),
            out_specs=P(None, both),
            check_vma=False,
        )(vs, taus, z)
