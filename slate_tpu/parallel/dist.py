"""DistMatrix: 2D block-cyclic distributed tile-stack matrix.

TPU-native analogue of the reference's distributed ``slate::Matrix``
(BaseMatrix.hh:40 + MatrixStorage.hh:158): the global (m, n) matrix is split
into nb x nb tiles, tile (i, j) is owned by process (i % p, j % q)
(func.hh:154), and algorithms move tiles with broadcasts/reductions.

Here the tile map is one dense array of shape (mt, nt, nb, nb) stored in
*cyclic order* (tiling.to_cyclic) and sharded over a ``Mesh(('p','q'))`` with
``PartitionSpec('p','q')`` — device (r, c) then holds exactly the tiles
{(i, j) : i % p == r, j % q == c}, reproducing block-cyclic ownership with
zero bookkeeping.  Tile communication is XLA collectives over ICI inside
``shard_map`` kernels (summa.py, dist_chol.py, dist_lu.py): the reference's
``tileBcast`` along a process row/column becomes a masked ``psum`` over one
mesh axis (BaseMatrix.hh:1917 -> lax.psum), ``listReduce`` becomes ``psum``
proper, and MOSI/lifetime/tag machinery (MatrixStorage.hh) vanishes.

Tile-grid padding: mt and nt are rounded up to multiples of lcm(p, q) so
that every device holds the same local count (static shapes).  Pad tiles are
zero; ``diag_pad_one`` additionally sets the padded diagonal to 1 so that
factorizations (potrf/getrf) act as identity on the pad block —
diag(A, I) = diag(L, I) diag(L, I)^H — keeping padded runs exact.  The
``diag_pad`` flag records this so factorization kernels can refuse inputs
whose pad diagonal is zero (which would NaN-poison the trailing updates).
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ..core.grid import num_tiles
from ..core.tiling import from_cyclic, from_tiles, to_cyclic, to_tiles
from .mesh import COL_AXIS, ROW_AXIS, mesh_shape, tile_sharding


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class DistMatrix:
    """Block-cyclic distributed matrix: cyclic-ordered tile stack + metadata."""

    tiles: jax.Array  # (mt, nt, nb, nb) in cyclic storage order, sharded
    m: int  # logical rows
    n: int  # logical cols
    nb: int
    mesh: Mesh
    diag_pad: bool = False  # True if padded diagonal is identity (or no pad)

    def tree_flatten(self):
        return (self.tiles,), (self.m, self.n, self.nb, self.mesh, self.diag_pad)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (tiles,) = children
        m, n, nb, mesh, diag_pad = aux
        return cls(tiles=tiles, m=m, n=n, nb=nb, mesh=mesh, diag_pad=diag_pad)

    @property
    def mt(self) -> int:
        return self.tiles.shape[0]

    @property
    def nt(self) -> int:
        return self.tiles.shape[1]

    @property
    def grid(self) -> Tuple[int, int]:
        return mesh_shape(self.mesh)

    @property
    def dtype(self):
        return self.tiles.dtype

    def require_diag_pad(self, who: str) -> None:
        """Factorization/solve kernels call this: a zero pad diagonal would
        NaN-poison their triangular solves (see module docstring)."""
        if not self.diag_pad:
            raise ValueError(
                f"{who} needs an identity-padded diagonal; build the operand "
                "with from_dense(..., diag_pad_one=True)"
            )


def _pad_grid(mesh: Mesh) -> int:
    p, q = mesh_shape(mesh)
    return math.lcm(p, q)


def padded_tiles(extent: int, nb: int, mesh: Mesh) -> int:
    """Tile count along one dim after rounding up to the mesh lcm."""
    return _round_up(max(1, num_tiles(extent, nb)), _pad_grid(mesh))


def from_dense(
    a: jax.Array, mesh: Mesh, nb: int, diag_pad_one: bool = False
) -> DistMatrix:
    """Distribute a dense (m, n) array over ``mesh`` block-cyclically.

    Analogue of Matrix::fromLAPACK + insertLocalTiles + tile scatter
    (Matrix.hh:58-112); on TPU it is a reshape + permutation + device_put.
    """
    m, n = a.shape
    mt = padded_tiles(m, nb, mesh)
    nt = padded_tiles(n, nb, mesh)
    mp, np_ = mt * nb, nt * nb
    a = jnp.pad(a, ((0, mp - m), (0, np_ - n)))
    if diag_pad_one:
        d = jnp.arange(min(m, n), min(mp, np_))
        a = a.at[d, d].set(1)
    t = to_cyclic(to_tiles(a, nb), *mesh_shape(mesh))
    t = jax.device_put(t, tile_sharding(mesh))
    no_pad = mp == m and np_ == n
    return DistMatrix(
        tiles=t, m=m, n=n, nb=nb, mesh=mesh, diag_pad=diag_pad_one or no_pad
    )


def to_dense(d: DistMatrix) -> jax.Array:
    """Gather back to a logically-ordered dense (m, n) array."""
    t = from_cyclic(d.tiles, *mesh_shape(d.mesh))
    return from_tiles(t, d.m, d.n)


def empty_like(d: DistMatrix, m: Optional[int] = None, n: Optional[int] = None) -> DistMatrix:
    m = d.m if m is None else m
    n = d.n if n is None else n
    mt = padded_tiles(m, d.nb, d.mesh)
    nt = padded_tiles(n, d.nb, d.mesh)
    t = jnp.zeros((mt, nt, d.nb, d.nb), d.dtype)
    t = jax.device_put(t, tile_sharding(d.mesh))
    return DistMatrix(tiles=t, m=m, n=n, nb=d.nb, mesh=d.mesh)


# ---------------------------------------------------------------------------
# Non-uniform block sizes (func.hh:39-203 parity; exercised by ref ex13)
# ---------------------------------------------------------------------------


def from_dense_nonuniform(
    a: jax.Array,
    mesh: Mesh,
    row_sizes,
    col_sizes,
) -> DistMatrix:
    """Distribute with PER-INDEX tile sizes (reference func.hh non-uniform
    block-size lambdas, ex13): non-uniform tile (i, j) of size
    (row_sizes[i], col_sizes[j]) keeps the reference's ownership rule
    (i % p, j % q) and is embedded top-left into a uniform
    max(sizes)-square padded tile — the TPU-idiomatic canonicalization
    (static shapes; XLA cannot trace ragged tiles).  The zero embedding is
    exact for multiply-class ops and norms: gemm's tile products align
    because row k of B and column k of A pad identically; factorizations
    require uniform tiling (interior pad would make diag tiles singular) —
    ``redistribute_nonuniform`` retiles onto a uniform nb for those.

    Returns a DistMatrix with nb = max of all sizes and the logical
    (m, n) = sums of sizes; recover the dense array with
    ``to_dense_nonuniform(d, row_sizes, col_sizes)``."""
    import numpy as _np

    row_sizes = [int(x) for x in row_sizes]
    col_sizes = [int(x) for x in col_sizes]
    m, n = a.shape
    if sum(row_sizes) != m or sum(col_sizes) != n:
        raise ValueError(
            f"non-uniform sizes must tile the matrix exactly: "
            f"sum(rows)={sum(row_sizes)} vs m={m}, sum(cols)={sum(col_sizes)} vs n={n}"
        )
    nb = max(row_sizes + col_sizes)
    mt = _round_up(max(1, len(row_sizes)), _pad_grid(mesh))
    nt = _round_up(max(1, len(col_sizes)), _pad_grid(mesh))
    roff = _np.concatenate([[0], _np.cumsum(row_sizes)])
    coff = _np.concatenate([[0], _np.cumsum(col_sizes)])
    # assemble on host (one device transfer), not per-tile .at[].set
    th = _np.zeros((mt, nt, nb, nb), _np.asarray(a).dtype)
    ah = _np.asarray(a)
    for i, mb in enumerate(row_sizes):
        for j, nbj in enumerate(col_sizes):
            th[i, j, :mb, :nbj] = ah[roff[i] : roff[i] + mb, coff[j] : coff[j] + nbj]
    t = to_cyclic(jnp.asarray(th), *mesh_shape(mesh))
    t = jax.device_put(t, tile_sharding(mesh))
    return DistMatrix(tiles=t, m=m, n=n, nb=nb, mesh=mesh, diag_pad=False)


def to_dense_nonuniform(d: DistMatrix, row_sizes, col_sizes) -> jax.Array:
    """Gather a from_dense_nonuniform matrix back to dense (m, n)."""
    import numpy as _np

    row_sizes = [int(x) for x in row_sizes]
    col_sizes = [int(x) for x in col_sizes]
    t = from_cyclic(d.tiles, *mesh_shape(d.mesh))
    roff = _np.concatenate([[0], _np.cumsum(row_sizes)])
    coff = _np.concatenate([[0], _np.cumsum(col_sizes)])
    out = jnp.zeros((d.m, d.n), d.dtype)
    for i, mb in enumerate(row_sizes):
        for j, nbj in enumerate(col_sizes):
            out = out.at[roff[i] : roff[i] + mb, coff[j] : coff[j] + nbj].set(
                t[i, j, :mb, :nbj]
            )
    return out


REDIST_IMPLS = ("auto", "eager", "shardmap")


def redistribute(
    d: DistMatrix, mesh: Mesh, nb: Optional[int] = None,
    impl: Optional[str] = None,
) -> DistMatrix:
    """Re-distribute between layouts (src/redistribute.cc analogue),
    entirely on device.  Two lowerings, selected by ``impl``:

    - ``eager``: the cyclic-order permutation + one device_put that XLA
      lowers to collective traffic — no host round trip (the reference
      moves tiles with point-to-point MPI, redistribute.cc:20).  Caveat:
      the permutation materializes a replicated intermediate (one full
      tile grid per device).
    - ``shardmap``: the ppermute ring all-to-all exchange — each device
      circulates its own 1/(p*q) source block around the linearized mesh
      ring (Cannon-style: q-1 column rotations per row step, p-1 row
      steps) and gathers the tiles it owns under the DESTINATION layout,
      so per-device residency stays at one source + one destination
      block.  Audited like any broadcast (``redistribute_wire_bytes`` is
      the analytic link-byte total, proven in tests/test_comm_audit.py);
      bitwise-identical to the eager path (moves exact bytes).  Requires
      an unchanged ``nb`` and a target mesh that re-arranges exactly the
      source mesh's devices.
    - ``auto`` (None, the default): shardmap when eligible, else eager.

    Pad-tile diagonal contract: a ``diag_pad`` source KEEPS its identity
    pad through any reshape — freshly grown pad tiles get their diagonal
    set to 1 (both lowerings), and an nb retile re-establishes it via
    ``from_dense(diag_pad_one=True)`` — so redistributed factorization
    operands stay factorizable (the round-trip bug class pinned by
    tests/test_parallel.py::test_redistribute_roundtrip_bitwise)."""
    nb2 = nb or d.nb
    impl = impl or "auto"
    if impl not in REDIST_IMPLS:
        raise ValueError(
            f"unknown redistribute impl {impl!r}; expected one of "
            f"{REDIST_IMPLS}"
        )
    p2, q2 = mesh_shape(mesh)
    if nb2 == d.nb and impl != "eager":
        if (p2, q2) == mesh_shape(d.mesh) and bool(
            (mesh.devices == d.mesh.devices).all()
        ):
            return d  # identical layout: nothing moves
        cmap = _shardmap_coord_map(d.mesh, mesh)
        if cmap is not None:
            return _redistribute_shardmap(d, mesh, cmap)
        if impl == "shardmap":
            raise ValueError(
                "shardmap redistribute needs the target mesh to re-arrange "
                "exactly the source mesh's devices; use impl='eager'/'auto'"
            )
    elif impl == "shardmap":
        raise ValueError(
            "shardmap redistribute cannot retile (nb change); use "
            "impl='eager'/'auto'"
        )
    if nb2 == d.nb:
        # pure ownership change: logical tile grid is unchanged
        t_log = from_cyclic(d.tiles, *mesh_shape(d.mesh))
        mt2 = padded_tiles(d.m, nb2, mesh)
        nt2 = padded_tiles(d.n, nb2, mesh)
        mt, nt = t_log.shape[:2]
        if (mt2, nt2) != (mt, nt):  # pad/crop the tile grid for the new lcm
            t_log = jnp.pad(
                t_log[: min(mt, mt2), : min(nt, nt2)],
                ((0, max(0, mt2 - mt)), (0, max(0, nt2 - nt)), (0, 0), (0, 0)),
            )
            start, stop = fresh_pad_diag_range(mt, nt, mt2, nt2)
            if d.diag_pad and stop > start:
                fresh = jnp.arange(start, stop)
                t_log = t_log.at[fresh, fresh].set(
                    jnp.eye(nb2, dtype=d.dtype))
        t2 = to_cyclic(t_log, p2, q2)
        t2 = jax.device_put(t2, tile_sharding(mesh))
        no_pad2 = mt2 * nb2 == d.m and nt2 * nb2 == d.n
        return DistMatrix(
            tiles=t2, m=d.m, n=d.n, nb=nb2, mesh=mesh,
            diag_pad=no_pad2 or d.diag_pad,
        )
    # nb change: retile through a device-resident (sharded) dense view,
    # re-establishing the identity pad diagonal when the source had one
    dense = from_tiles(from_cyclic(d.tiles, *mesh_shape(d.mesh)), d.m, d.n)
    return from_dense(dense, mesh, nb2, diag_pad_one=d.diag_pad)


def _shardmap_coord_map(mesh1: Mesh, mesh2: Mesh):
    """(r1, c1) -> (r2, c2) device-identity map between two meshes, or
    None when ``mesh2`` is not a re-arrangement of exactly ``mesh1``'s
    devices (the shardmap-eligibility test)."""
    import numpy as _np

    d1, d2 = mesh1.devices, mesh2.devices
    if d1.size != d2.size:
        return None
    pos2 = {dev: rc for rc, dev in _np.ndenumerate(d2)}
    cmap = []
    for r in range(d1.shape[0]):
        row = []
        for c in range(d1.shape[1]):
            got = pos2.get(d1[r, c])
            if got is None:
                return None
            row.append((int(got[0]), int(got[1])))
        cmap.append(tuple(row))
    return tuple(cmap)


def fresh_pad_diag_range(mt1: int, nt1: int, mt2: int, nt2: int):
    """Tile indices [start, stop) whose (t, t) pad tile is FRESH to a
    tile grid grown from (mt1, nt1) to (mt2, nt2): the source covers
    diagonal tiles below min(mt1, nt1); a diag_pad source needs every
    fresh one set to the identity (the from_dense(diag_pad_one=True)
    contract — their global diagonal indices all sit past min(m, n)).
    ONE source for the contract: the eager/shardmap redistribute
    lowerings and ft.elastic's host relayout all consume this."""
    return min(mt1, nt1), min(mt2, nt2)


def redistribute_wire_bytes(src_tiles_shape, p: int, q: int,
                            itemsize: int) -> int:
    """Analytic audited link bytes of the shardmap redistribution of a
    (mt, nt, nb, nb) cyclic stack off a (p, q) mesh: the ring schedule
    rotates each device's source block p*(q-1) times along the column
    axis (q link pairs per hop under comm.ppermute_a's convention) and
    p-1 times along the row axis (p pairs per hop).  The formula is the
    comm-audit acceptance bound (tests/test_comm_audit.py)."""
    mt, nt, nb, _ = src_tiles_shape
    block = (mt // p) * (nt // q) * nb * nb * itemsize
    return block * (p * (q - 1) * q + (p - 1) * p)


def _redist_shardmap_fn(at, mesh1, p1, q1, dims, cmap, diag_pad):
    """The ring-exchange program over the SOURCE mesh.  ``dims`` =
    (p2, q2, mt1, nt1, mt2, nt2, nb); ``cmap`` maps each source
    coordinate to the destination-mesh coordinate of the SAME physical
    device, so each device computes exactly the block it owns under the
    destination layout — the output reassembles onto the target mesh
    with zero further movement (_redistribute_shardmap).  Unjitted form
    so the comm-audit volume test traces it directly;
    ``_redist_shardmap_jit`` is the dispatch path."""
    p2, q2, mt1, nt1, mt2, nt2, nb = dims
    mtl2, ntl2 = mt2 // p2, nt2 // q2
    spec = P(ROW_AXIS, COL_AXIS)
    from .comm import ppermute_a, shard_map_compat

    r2m = jnp.asarray([[rc[0] for rc in row] for row in cmap])
    c2m = jnp.asarray([[rc[1] for rc in row] for row in cmap])

    def kernel(t_loc):
        mtl1, ntl1 = t_loc.shape[0], t_loc.shape[1]
        dtype = t_loc.dtype
        r1 = lax.axis_index(ROW_AXIS)
        c1 = lax.axis_index(COL_AXIS)
        r2 = r2m[r1, c1]
        c2 = c2m[r1, c1]
        # logical tile indices of MY destination slots (block-cyclic on
        # the target grid)
        i2 = r2 + jnp.arange(mtl2) * p2
        j2 = c2 + jnp.arange(ntl2) * q2
        dest = jnp.zeros((mtl2, ntl2, nb, nb), dtype)
        pad0, pad1 = fresh_pad_diag_range(mt1, nt1, mt2, nt2)
        if diag_pad and pad1 > pad0:
            # fresh pad tiles carry the identity diagonal; i2 == j2
            # already bounds the index below pad1 = min(mt2, nt2)
            fresh = ((i2[:, None] == j2[None, :])
                     & (i2[:, None] >= pad0))
            dest = jnp.where(
                fresh[:, :, None, None], jnp.eye(nb, dtype=dtype)[None, None],
                dest,
            )
        buf = t_loc
        off_p = off_q = 0
        for idx in range(p1 * q1):
            # buf currently holds the source block of coordinate (rs, cs)
            rs = (r1 + off_p) % p1
            cs = (c1 + off_q) % q1
            take_i = (i2 % p1 == rs) & (i2 < mt1)
            take_j = (j2 % q1 == cs) & (j2 < nt1)
            src_i = jnp.clip(i2 // p1, 0, mtl1 - 1)
            src_j = jnp.clip(j2 // q1, 0, ntl1 - 1)
            g = buf[src_i][:, src_j]
            m = (take_i[:, None] & take_j[None, :])[:, :, None, None]
            dest = jnp.where(m, g, dest)
            if idx == p1 * q1 - 1:
                break  # last block consumed: no trailing rotation
            if (idx + 1) % q1 == 0:
                buf = ppermute_a(buf, ROW_AXIS,
                                 [((i + 1) % p1, i) for i in range(p1)])
                off_p += 1
            else:
                buf = ppermute_a(buf, COL_AXIS,
                                 [((i + 1) % q1, i) for i in range(q1)])
                off_q += 1
        return dest

    return shard_map_compat(
        kernel, mesh=mesh1, in_specs=(spec,), out_specs=spec,
        check_vma=False,
    )(at)


_redist_shardmap_jit = functools.partial(
    jax.jit, static_argnums=(1, 2, 3, 4, 5, 6)
)(_redist_shardmap_fn)


def _redistribute_shardmap(d: DistMatrix, mesh: Mesh, cmap) -> DistMatrix:
    p1, q1 = mesh_shape(d.mesh)
    p2, q2 = mesh_shape(mesh)
    mt1, nt1 = d.tiles.shape[0], d.tiles.shape[1]
    mt2 = padded_tiles(d.m, d.nb, mesh)
    nt2 = padded_tiles(d.n, d.nb, mesh)
    dims = (p2, q2, mt1, nt1, mt2, nt2, d.nb)
    out = _redist_shardmap_jit(d.tiles, d.mesh, p1, q1, dims, cmap,
                               d.diag_pad)
    # each device already holds exactly its destination-layout block;
    # reassemble the shards under the TARGET mesh's sharding — a
    # metadata-level rebind, zero further data movement
    sh2 = tile_sharding(mesh)
    shards = {s.device: s.data for s in out.addressable_shards}
    arrs = [shards[dev] for dev in
            sh2.addressable_devices_indices_map(
                (mt2, nt2, d.nb, d.nb)).keys()]
    t2 = jax.make_array_from_single_device_arrays(
        (mt2, nt2, d.nb, d.nb), sh2, arrs)
    no_pad2 = mt2 * d.nb == d.m and nt2 * d.nb == d.n
    return DistMatrix(
        tiles=t2, m=d.m, n=d.n, nb=d.nb, mesh=mesh,
        diag_pad=no_pad2 or d.diag_pad,
    )


def redistribute_nonuniform(
    d: DistMatrix, row_sizes, col_sizes, nb: Optional[int] = None,
    diag_pad_one: bool = False,
) -> DistMatrix:
    """Re-distribute a ``from_dense_nonuniform`` matrix onto a UNIFORM
    nb tiling of the same mesh — the bridge that lets every factorization
    run on non-uniformly tiled input (reference ex13 runs algorithms on
    func.hh:39-78 non-uniform distributions; here the uniform retile is
    the algorithm-facing canonical form because interior tile padding
    would make diagonal tiles singular).  Entirely device-resident: the
    per-tile unpad/reassembly works on global (sharded) arrays, the
    analogue of redistribute.cc's tile-by-tile MPI moves.  Pass
    ``diag_pad_one=True`` when the result feeds a factorization (the
    from_dense padding contract)."""
    dense = to_dense_nonuniform(d, row_sizes, col_sizes)
    return from_dense(dense, d.mesh, nb or d.nb, diag_pad_one=diag_pad_one)
