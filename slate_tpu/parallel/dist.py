"""DistMatrix: 2D block-cyclic distributed tile-stack matrix.

TPU-native analogue of the reference's distributed ``slate::Matrix``
(BaseMatrix.hh:40 + MatrixStorage.hh:158): the global (m, n) matrix is split
into nb x nb tiles, tile (i, j) is owned by process (i % p, j % q)
(func.hh:154), and algorithms move tiles with broadcasts/reductions.

Here the tile map is one dense array of shape (mt, nt, nb, nb) stored in
*cyclic order* (tiling.to_cyclic) and sharded over a ``Mesh(('p','q'))`` with
``PartitionSpec('p','q')`` — device (r, c) then holds exactly the tiles
{(i, j) : i % p == r, j % q == c}, reproducing block-cyclic ownership with
zero bookkeeping.  Tile communication is XLA collectives over ICI inside
``shard_map`` kernels (summa.py, dist_chol.py, dist_lu.py): the reference's
``tileBcast`` along a process row/column becomes a masked ``psum`` over one
mesh axis (BaseMatrix.hh:1917 -> lax.psum), ``listReduce`` becomes ``psum``
proper, and MOSI/lifetime/tag machinery (MatrixStorage.hh) vanishes.

Tile-grid padding: mt and nt are rounded up to multiples of lcm(p, q) so
that every device holds the same local count (static shapes).  Pad tiles are
zero; ``diag_pad_one`` additionally sets the padded diagonal to 1 so that
factorizations (potrf/getrf) act as identity on the pad block —
diag(A, I) = diag(L, I) diag(L, I)^H — keeping padded runs exact.  The
``diag_pad`` flag records this so factorization kernels can refuse inputs
whose pad diagonal is zero (which would NaN-poison the trailing updates).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..core.grid import num_tiles
from ..core.tiling import from_cyclic, from_tiles, to_cyclic, to_tiles
from .mesh import mesh_shape, tile_sharding


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class DistMatrix:
    """Block-cyclic distributed matrix: cyclic-ordered tile stack + metadata."""

    tiles: jax.Array  # (mt, nt, nb, nb) in cyclic storage order, sharded
    m: int  # logical rows
    n: int  # logical cols
    nb: int
    mesh: Mesh
    diag_pad: bool = False  # True if padded diagonal is identity (or no pad)

    def tree_flatten(self):
        return (self.tiles,), (self.m, self.n, self.nb, self.mesh, self.diag_pad)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (tiles,) = children
        m, n, nb, mesh, diag_pad = aux
        return cls(tiles=tiles, m=m, n=n, nb=nb, mesh=mesh, diag_pad=diag_pad)

    @property
    def mt(self) -> int:
        return self.tiles.shape[0]

    @property
    def nt(self) -> int:
        return self.tiles.shape[1]

    @property
    def grid(self) -> Tuple[int, int]:
        return mesh_shape(self.mesh)

    @property
    def dtype(self):
        return self.tiles.dtype

    def require_diag_pad(self, who: str) -> None:
        """Factorization/solve kernels call this: a zero pad diagonal would
        NaN-poison their triangular solves (see module docstring)."""
        if not self.diag_pad:
            raise ValueError(
                f"{who} needs an identity-padded diagonal; build the operand "
                "with from_dense(..., diag_pad_one=True)"
            )


def _pad_grid(mesh: Mesh) -> int:
    p, q = mesh_shape(mesh)
    return math.lcm(p, q)


def padded_tiles(extent: int, nb: int, mesh: Mesh) -> int:
    """Tile count along one dim after rounding up to the mesh lcm."""
    return _round_up(max(1, num_tiles(extent, nb)), _pad_grid(mesh))


def from_dense(
    a: jax.Array, mesh: Mesh, nb: int, diag_pad_one: bool = False
) -> DistMatrix:
    """Distribute a dense (m, n) array over ``mesh`` block-cyclically.

    Analogue of Matrix::fromLAPACK + insertLocalTiles + tile scatter
    (Matrix.hh:58-112); on TPU it is a reshape + permutation + device_put.
    """
    m, n = a.shape
    mt = padded_tiles(m, nb, mesh)
    nt = padded_tiles(n, nb, mesh)
    mp, np_ = mt * nb, nt * nb
    a = jnp.pad(a, ((0, mp - m), (0, np_ - n)))
    if diag_pad_one:
        d = jnp.arange(min(m, n), min(mp, np_))
        a = a.at[d, d].set(1)
    t = to_cyclic(to_tiles(a, nb), *mesh_shape(mesh))
    t = jax.device_put(t, tile_sharding(mesh))
    no_pad = mp == m and np_ == n
    return DistMatrix(
        tiles=t, m=m, n=n, nb=nb, mesh=mesh, diag_pad=diag_pad_one or no_pad
    )


def to_dense(d: DistMatrix) -> jax.Array:
    """Gather back to a logically-ordered dense (m, n) array."""
    t = from_cyclic(d.tiles, *mesh_shape(d.mesh))
    return from_tiles(t, d.m, d.n)


def empty_like(d: DistMatrix, m: Optional[int] = None, n: Optional[int] = None) -> DistMatrix:
    m = d.m if m is None else m
    n = d.n if n is None else n
    mt = padded_tiles(m, d.nb, d.mesh)
    nt = padded_tiles(n, d.nb, d.mesh)
    t = jnp.zeros((mt, nt, d.nb, d.nb), d.dtype)
    t = jax.device_put(t, tile_sharding(d.mesh))
    return DistMatrix(tiles=t, m=m, n=n, nb=d.nb, mesh=d.mesh)


# ---------------------------------------------------------------------------
# Non-uniform block sizes (func.hh:39-203 parity; exercised by ref ex13)
# ---------------------------------------------------------------------------


def from_dense_nonuniform(
    a: jax.Array,
    mesh: Mesh,
    row_sizes,
    col_sizes,
) -> DistMatrix:
    """Distribute with PER-INDEX tile sizes (reference func.hh non-uniform
    block-size lambdas, ex13): non-uniform tile (i, j) of size
    (row_sizes[i], col_sizes[j]) keeps the reference's ownership rule
    (i % p, j % q) and is embedded top-left into a uniform
    max(sizes)-square padded tile — the TPU-idiomatic canonicalization
    (static shapes; XLA cannot trace ragged tiles).  The zero embedding is
    exact for multiply-class ops and norms: gemm's tile products align
    because row k of B and column k of A pad identically; factorizations
    require uniform tiling (interior pad would make diag tiles singular) —
    ``redistribute_nonuniform`` retiles onto a uniform nb for those.

    Returns a DistMatrix with nb = max of all sizes and the logical
    (m, n) = sums of sizes; recover the dense array with
    ``to_dense_nonuniform(d, row_sizes, col_sizes)``."""
    import numpy as _np

    row_sizes = [int(x) for x in row_sizes]
    col_sizes = [int(x) for x in col_sizes]
    m, n = a.shape
    if sum(row_sizes) != m or sum(col_sizes) != n:
        raise ValueError(
            f"non-uniform sizes must tile the matrix exactly: "
            f"sum(rows)={sum(row_sizes)} vs m={m}, sum(cols)={sum(col_sizes)} vs n={n}"
        )
    nb = max(row_sizes + col_sizes)
    mt = _round_up(max(1, len(row_sizes)), _pad_grid(mesh))
    nt = _round_up(max(1, len(col_sizes)), _pad_grid(mesh))
    roff = _np.concatenate([[0], _np.cumsum(row_sizes)])
    coff = _np.concatenate([[0], _np.cumsum(col_sizes)])
    # assemble on host (one device transfer), not per-tile .at[].set
    th = _np.zeros((mt, nt, nb, nb), _np.asarray(a).dtype)
    ah = _np.asarray(a)
    for i, mb in enumerate(row_sizes):
        for j, nbj in enumerate(col_sizes):
            th[i, j, :mb, :nbj] = ah[roff[i] : roff[i] + mb, coff[j] : coff[j] + nbj]
    t = to_cyclic(jnp.asarray(th), *mesh_shape(mesh))
    t = jax.device_put(t, tile_sharding(mesh))
    return DistMatrix(tiles=t, m=m, n=n, nb=nb, mesh=mesh, diag_pad=False)


def to_dense_nonuniform(d: DistMatrix, row_sizes, col_sizes) -> jax.Array:
    """Gather a from_dense_nonuniform matrix back to dense (m, n)."""
    import numpy as _np

    row_sizes = [int(x) for x in row_sizes]
    col_sizes = [int(x) for x in col_sizes]
    t = from_cyclic(d.tiles, *mesh_shape(d.mesh))
    roff = _np.concatenate([[0], _np.cumsum(row_sizes)])
    coff = _np.concatenate([[0], _np.cumsum(col_sizes)])
    out = jnp.zeros((d.m, d.n), d.dtype)
    for i, mb in enumerate(row_sizes):
        for j, nbj in enumerate(col_sizes):
            out = out.at[roff[i] : roff[i] + mb, coff[j] : coff[j] + nbj].set(
                t[i, j, :mb, :nbj]
            )
    return out


def redistribute(d: DistMatrix, mesh: Mesh, nb: Optional[int] = None) -> DistMatrix:
    """Re-distribute between layouts (src/redistribute.cc analogue),
    entirely on device: the cyclic-order permutation + one device_put that
    XLA lowers to collective traffic — no host round trip (the reference
    moves tiles with point-to-point MPI, redistribute.cc:20).  Caveat: the
    eager permutation materializes a replicated intermediate (one full
    tile grid per device); a shard_map all-to-all exchange that keeps
    per-device memory at 1/(p*q) is a further optimization."""
    nb2 = nb or d.nb
    p2, q2 = mesh_shape(mesh)
    if nb2 == d.nb:
        # pure ownership change: logical tile grid is unchanged
        t_log = from_cyclic(d.tiles, *mesh_shape(d.mesh))
        mt2 = padded_tiles(d.m, nb2, mesh)
        nt2 = padded_tiles(d.n, nb2, mesh)
        mt, nt = t_log.shape[:2]
        if (mt2, nt2) != (mt, nt):  # pad/crop the tile grid for the new lcm
            t_log = jnp.pad(
                t_log[: min(mt, mt2), : min(nt, nt2)],
                ((0, max(0, mt2 - mt)), (0, max(0, nt2 - nt)), (0, 0), (0, 0)),
            )
        t2 = to_cyclic(t_log, p2, q2)
        t2 = jax.device_put(t2, tile_sharding(mesh))
        # growing the grid adds zero pad tiles whose diagonal is 0; a
        # layout with no pad at all is trivially diag-padded (from_dense's
        # no_pad rule)
        no_pad2 = mt2 * nb2 == d.m and nt2 * nb2 == d.n
        keep_pad = no_pad2 or (d.diag_pad and mt2 <= mt and nt2 <= nt)
        return DistMatrix(
            tiles=t2, m=d.m, n=d.n, nb=nb2, mesh=mesh, diag_pad=keep_pad
        )
    # nb change: retile through a device-resident (sharded) dense view
    dense = from_tiles(from_cyclic(d.tiles, *mesh_shape(d.mesh)), d.m, d.n)
    return from_dense(dense, mesh, nb2)


def redistribute_nonuniform(
    d: DistMatrix, row_sizes, col_sizes, nb: Optional[int] = None,
    diag_pad_one: bool = False,
) -> DistMatrix:
    """Re-distribute a ``from_dense_nonuniform`` matrix onto a UNIFORM
    nb tiling of the same mesh — the bridge that lets every factorization
    run on non-uniformly tiled input (reference ex13 runs algorithms on
    func.hh:39-78 non-uniform distributions; here the uniform retile is
    the algorithm-facing canonical form because interior tile padding
    would make diagonal tiles singular).  Entirely device-resident: the
    per-tile unpad/reassembly works on global (sharded) arrays, the
    analogue of redistribute.cc's tile-by-tile MPI moves.  Pass
    ``diag_pad_one=True`` when the result feeds a factorization (the
    from_dense padding contract)."""
    dense = to_dense_nonuniform(d, row_sizes, col_sizes)
    return from_dense(dense, d.mesh, nb or d.nb, diag_pad_one=diag_pad_one)
