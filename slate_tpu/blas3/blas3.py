"""Level-3 BLAS drivers.

TPU-native analogues of the reference drivers ``src/{gemm,gemmA,gemmC,hemm,
symm,herk,syrk,her2k,syr2k,trmm,trsm,trsmA,trsmB,gbmm,hbmm,tbsm}.cc`` and the
internal ops ``src/internal/internal_{gemm,hemm,herk,...}.cc``.

Design inversion: the reference builds an OpenMP task DAG per driver (SUMMA
k-loop with lookahead broadcast pipeline, gemmC.cc:78-192; tile batches to
cuBLAS, internal_gemm.cc:383-700).  Under XLA the whole driver is ONE traced
program — the k-loop pipeline, tile batching, H2D staging and comm/compute
overlap are produced by the compiler from a single ``matmul`` on (possibly
sharded) arrays.  What survives from the reference is the *math semantics*
(uplo/op/diag handling, rank-k update symmetry, band shapes), which lives
here, and the distributed SUMMA schedule, which lives in
``slate_tpu.parallel.summa`` for explicitly-sharded meshes.

Triangular solve / multiply use recursive blocking (split at a power-of-two
boundary, recurse, stitch with ``matmul``): exact-flop algorithms whose O(log
n) distinct subproblem shapes keep XLA compile time bounded — the TPU-native
replacement for the reference's dynamic task scheduling over k-varying
trailing shapes.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core.matrix import (
    BandMatrix,
    BaseMatrix,
    HermitianMatrix,
    Matrix,
    SymmetricMatrix,
    TriangularMatrix,
    band_project,
    symmetrize,
    tri_project,
)
from ..ops.matmul import matmul
from ..types import Diag, Op, Option, Options, Precision, Side, SlateError, Uplo, get_option

ArrayLike = Union[jax.Array, BaseMatrix]

# base-case size for recursive triangular algorithms; one MXU-sized block
_NB = 256


def _arr(x: ArrayLike) -> jax.Array:
    return x.array if isinstance(x, BaseMatrix) else jnp.asarray(x)


def _mul_prec(opts: Optional[Options]) -> Precision:
    """Precision tier for multiply-class drivers (gemm/hemm/trmm/...).

    Default: Highest for every dtype — the reference always runs
    full-precision vendor GEMM (internal_gemm.cc:634), so f32 callers of
    the drop-in API get SGEMM-class (2^-24) accuracy, not single-pass
    bf16.  The faster reduced-accuracy tiers (Fast ~2^-8, High ~2^-16 on
    f32 data) are explicit opt-ins via Option.Precision."""
    p = get_option(opts, Option.Precision, None) if opts else None
    if p is not None:
        return Precision(p)  # coerce "fast"-style string values to the enum
    return Precision.Highest


def _wrap_like(c: ArrayLike, data: jax.Array):
    if isinstance(c, BaseMatrix):
        if c.op != Op.NoTrans:
            # store back through the view: data is logical (m,n)
            und = data.T if c.op == Op.Trans else jnp.conj(data).T
            return replace(c, data=und)
        return replace(c, data=data)
    return data


# ---------------------------------------------------------------------------
# gemm family (src/gemm.cc, gemmA.cc, gemmC.cc)
# ---------------------------------------------------------------------------


def gemm_array(
    alpha, a: jax.Array, b: jax.Array, beta, c: jax.Array,
    precision: Optional[Precision] = None,
) -> jax.Array:
    """C := alpha*A@B + beta*C on plain arrays."""
    ab = matmul(a, b, precision=precision)
    return alpha * ab.astype(c.dtype) + beta * c


def gemm(alpha, a: ArrayLike, b: ArrayLike, beta, c: ArrayLike, opts: Optional[Options] = None):
    """slate::gemm (src/gemm.cc:72). Method selection (gemmA vs gemmC,
    method.hh:35-45) is a scheduling choice the XLA partitioner makes from
    shardings; semantics are identical, so one entry point suffices."""
    aa, bb = _arr(a), _arr(b)
    return _wrap_like(c, gemm_array(alpha, aa, bb, beta, _arr(c), precision=_mul_prec(opts)))


def _side_mul(
    side: Side, alpha, afull: jax.Array, b: jax.Array, beta, c: jax.Array,
    precision: Optional[Precision] = None,
) -> jax.Array:
    prod = matmul(afull, b, precision=precision) if side == Side.Left else matmul(b, afull, precision=precision)
    return alpha * prod.astype(c.dtype) + beta * c


def hemm(side: Side, alpha, a: ArrayLike, b: ArrayLike, beta, c: ArrayLike, opts: Optional[Options] = None):
    """slate::hemm (src/hemm.cc): C := alpha*A*B + beta*C, A Hermitian."""
    am = a if isinstance(a, BaseMatrix) else HermitianMatrix.from_array(a, Uplo.Lower)
    afull = symmetrize(am.data, am.uplo, conj=True)
    bb = _arr(b)
    return _wrap_like(c, _side_mul(side, alpha, afull, bb, beta, _arr(c), precision=_mul_prec(opts)))


def symm(side: Side, alpha, a: ArrayLike, b: ArrayLike, beta, c: ArrayLike, opts: Optional[Options] = None):
    """slate::symm (src/symm.cc): A symmetric (not conjugated)."""
    am = a if isinstance(a, BaseMatrix) else SymmetricMatrix.from_array(a, Uplo.Lower)
    afull = symmetrize(am.data, am.uplo, conj=False)
    bb = _arr(b)
    return _wrap_like(c, _side_mul(side, alpha, afull, bb, beta, _arr(c), precision=_mul_prec(opts)))


def _rank_k_update(alpha, a: jax.Array, beta, c: ArrayLike, uplo: Uplo, conj: bool, two_sided_b: Optional[jax.Array] = None, precision: Optional[Precision] = None):
    cm = c if isinstance(c, BaseMatrix) else None
    cdata = cm.data if cm is not None else jnp.asarray(c)
    at = jnp.conj(a).T if conj else a.T
    if two_sided_b is None:
        upd = matmul(a, at, precision=precision)
        new = alpha * upd.astype(cdata.dtype)
    else:
        bt = jnp.conj(two_sided_b).T if conj else two_sided_b.T
        upd1 = matmul(a, bt, precision=precision)
        upd2 = matmul(two_sided_b, at, precision=precision)
        new = alpha * upd1.astype(cdata.dtype) + (jnp.conj(alpha) if conj else alpha) * upd2.astype(cdata.dtype)
    full = new + beta * (symmetrize(cdata, uplo, conj) if cm is not None else cdata)
    stored = tri_project(full, uplo)
    out = stored + tri_project(cdata, _other(uplo), Diag.NonUnit) - jnp.diag(jnp.diagonal(cdata)).astype(cdata.dtype)
    # keep only the uplo triangle updated; the other stays untouched
    if cm is not None:
        return replace(cm, data=out)
    return out


def _other(uplo: Uplo) -> Uplo:
    return Uplo.Upper if uplo == Uplo.Lower else Uplo.Lower


def herk(alpha, a: ArrayLike, beta, c: ArrayLike, uplo: Optional[Uplo] = None, opts: Optional[Options] = None):
    """slate::herk (src/herk.cc): C := alpha*A*A^H + beta*C, C Hermitian."""
    u = uplo or (c.uplo if isinstance(c, BaseMatrix) else Uplo.Lower)
    aa = _arr(a)
    return _rank_k_update(alpha, aa, beta, c, u, conj=True, precision=_mul_prec(opts))


def syrk(alpha, a: ArrayLike, beta, c: ArrayLike, uplo: Optional[Uplo] = None, opts: Optional[Options] = None):
    """slate::syrk: C := alpha*A*A^T + beta*C, C symmetric."""
    u = uplo or (c.uplo if isinstance(c, BaseMatrix) else Uplo.Lower)
    aa = _arr(a)
    return _rank_k_update(alpha, aa, beta, c, u, conj=False, precision=_mul_prec(opts))


def her2k(alpha, a: ArrayLike, b: ArrayLike, beta, c: ArrayLike, uplo: Optional[Uplo] = None, opts: Optional[Options] = None):
    """slate::her2k: C := alpha*A*B^H + conj(alpha)*B*A^H + beta*C."""
    u = uplo or (c.uplo if isinstance(c, BaseMatrix) else Uplo.Lower)
    aa = _arr(a)
    return _rank_k_update(alpha, aa, beta, c, u, conj=True, two_sided_b=_arr(b), precision=_mul_prec(opts))


def syr2k(alpha, a: ArrayLike, b: ArrayLike, beta, c: ArrayLike, uplo: Optional[Uplo] = None, opts: Optional[Options] = None):
    u = uplo or (c.uplo if isinstance(c, BaseMatrix) else Uplo.Lower)
    aa = _arr(a)
    return _rank_k_update(alpha, aa, beta, c, u, conj=False, two_sided_b=_arr(b), precision=_mul_prec(opts))


# ---------------------------------------------------------------------------
# trmm / trsm — recursive blocked (src/trmm.cc, src/trsm.cc, trsmA/trsmB)
# ---------------------------------------------------------------------------


def _tri_full(a: jax.Array, uplo: Uplo, diag: Diag) -> jax.Array:
    return tri_project(a, uplo, diag)


# below this size the dense-masked multiply (full matmul on the projected
# triangle) beats the recursion's extra launches
_TRMM_DENSE_MAX = 1024


def _trmm_ll(a: jax.Array, b: jax.Array, diag: Diag, precision) -> jax.Array:
    """B := L B, recursive blocked (half the flops of the dense-masked
    form — the reference's tile kernels likewise skip the zero triangle,
    internal_trmm.cc; VERDICT r2 weak item 8)."""
    n = a.shape[0]
    if n <= _TRMM_DENSE_MAX:
        return matmul(_tri_full(a, Uplo.Lower, diag), b, precision=precision).astype(b.dtype)
    h = _split(n)
    top = _trmm_ll(a[:h, :h], b[:h], diag, precision)
    bot = matmul(a[h:, :h], b[:h], precision=precision).astype(b.dtype)
    bot = bot + _trmm_ll(a[h:, h:], b[h:], diag, precision)
    return jnp.concatenate([top, bot], axis=0)


def _trmm_lu(a: jax.Array, b: jax.Array, diag: Diag, precision) -> jax.Array:
    """B := U B, recursive blocked."""
    n = a.shape[0]
    if n <= _TRMM_DENSE_MAX:
        return matmul(_tri_full(a, Uplo.Upper, diag), b, precision=precision).astype(b.dtype)
    h = _split(n)
    top = _trmm_lu(a[:h, :h], b[:h], diag, precision)
    top = top + matmul(a[:h, h:], b[h:], precision=precision).astype(b.dtype)
    bot = _trmm_lu(a[h:, h:], b[h:], diag, precision)
    return jnp.concatenate([top, bot], axis=0)


def trmm_array(
    side: Side, uplo: Uplo, op: Op, diag: Diag, alpha, a: jax.Array, b: jax.Array,
    precision: Optional[Precision] = None,
) -> jax.Array:
    """B := alpha * op(A) * B (or B*op(A)), A triangular (src/trmm.cc).

    All eight (side, uplo, op) combinations reduce to the two left-notrans
    recursions via transposition, mirroring trsm_array's routing."""
    if side == Side.Right:
        # B op(A) = (op(A)^T B^T)^T
        if op == Op.NoTrans:
            out = trmm_array(Side.Left, uplo, Op.Trans, diag, alpha, a, b.T, precision)
        elif op == Op.Trans:
            out = trmm_array(Side.Left, uplo, Op.NoTrans, diag, alpha, a, b.T, precision)
        else:  # ConjTrans: B A^H = (conj(A) B^T)^T
            out = trmm_array(Side.Left, uplo, Op.NoTrans, diag, alpha, jnp.conj(a), b.T, precision)
        return out.T
    if op == Op.Trans:
        return trmm_array(Side.Left, _other(uplo), Op.NoTrans, diag, alpha, a.T, b, precision)
    if op == Op.ConjTrans:
        return trmm_array(Side.Left, _other(uplo), Op.NoTrans, diag, alpha, jnp.conj(a).T, b, precision)
    core = _trmm_ll if uplo == Uplo.Lower else _trmm_lu
    return alpha * core(a, jnp.asarray(b), diag, precision)


def trmm(side: Side, alpha, a: ArrayLike, b: ArrayLike, opts: Optional[Options] = None):
    am = a if isinstance(a, BaseMatrix) else TriangularMatrix.from_array(a, Uplo.Lower)
    bb = _arr(b)
    out = trmm_array(side, am.uplo, am.op, am.diag, alpha, am.data, bb, precision=_mul_prec(opts))
    return _wrap_like(b, out)


def _trsm_left_lower_notrans(a: jax.Array, b: jax.Array, diag: Diag) -> jax.Array:
    """Solve L X = B, L lower triangular, recursive blocked."""
    n = a.shape[0]
    if n <= _NB:
        if b.shape[1] > n:
            # wide RHS: XLA's triangular_solve runs ~10x below the MXU
            # matmul rate there (and far worse under f64 emulation), so
            # invert the small triangle against eye (an n-wide solve) and
            # ride one gemm — the same explicit-inverse panel trade as
            # chol._potrf_scan, O(eps * cond(L11)) on a base block
            eye = jnp.eye(n, dtype=a.dtype)
            linv = jax.lax.linalg.triangular_solve(
                a, eye, left_side=True, lower=True, transpose_a=False,
                unit_diagonal=(diag == Diag.Unit),
            )
            return matmul(linv, b).astype(b.dtype)
        return jax.lax.linalg.triangular_solve(
            a, b, left_side=True, lower=True, transpose_a=False,
            unit_diagonal=(diag == Diag.Unit),
        )
    h = _split(n)
    a11, a21, a22 = a[:h, :h], a[h:, :h], a[h:, h:]
    x1 = _trsm_left_lower_notrans(a11, b[:h], diag)
    rhs2 = b[h:] - matmul(a21, x1).astype(b.dtype)
    x2 = _trsm_left_lower_notrans(a22, rhs2, diag)
    return jnp.concatenate([x1, x2], axis=0)


def split_pow2(n: int, base: int) -> int:
    """Largest power-of-two multiple of ``base`` below n — the shared split
    policy for all recursive blocked algorithms (keeps the set of distinct
    recursive shapes O(log n) for XLA compile caching)."""
    h = base
    while h * 2 < n:
        h *= 2
    return h


def _split(n: int) -> int:
    return split_pow2(n, _NB)


def trsm_array(
    side: Side, uplo: Uplo, op: Op, diag: Diag, alpha, a: jax.Array, b: jax.Array
) -> jax.Array:
    """Solve op(A) X = alpha B / X op(A) = alpha B (src/trsm.cc).

    All eight (side, uplo, op) combinations reduce to the left-lower-notrans
    recursion via transposition identities, mirroring how the reference
    routes trsm variants through one internal kernel (internal_trsm.cc)."""
    b = jnp.asarray(b) * alpha
    if side == Side.Right:
        # X * op(A) = B  <=>  op(A)^T X^T = B^T
        if op == Op.NoTrans:  # A^T X^T = B^T: left solve with op=Trans
            out = trsm_array(Side.Left, uplo, Op.Trans, diag, 1.0, a, b.T)
        elif op == Op.Trans:  # A X^T = B^T
            out = trsm_array(Side.Left, uplo, Op.NoTrans, diag, 1.0, a, b.T)
        else:  # conj(A) X^T = B^T
            out = trsm_array(Side.Left, uplo, Op.NoTrans, diag, 1.0, jnp.conj(a), b.T)
        return out.T
    if op == Op.Trans:
        return trsm_array(Side.Left, _other(uplo), Op.NoTrans, diag, 1.0, a.T, b)
    if op == Op.ConjTrans:
        return trsm_array(Side.Left, _other(uplo), Op.NoTrans, diag, 1.0, jnp.conj(a).T, b)
    if uplo == Uplo.Upper:
        # U X = B: flip to lower by reversing indices
        rev = (slice(None, None, -1),)
        a_fl = a[::-1, ::-1]
        b_fl = b[::-1]
        x = _trsm_left_lower_notrans(a_fl, b_fl, diag)
        return x[::-1]
    return _trsm_left_lower_notrans(a, b, diag)


def trsm(side: Side, alpha, a: ArrayLike, b: ArrayLike,
         opts: Optional[Options] = None):
    """slate::trsm driver over matrix views.  ``opts`` is accepted for
    option symmetry with the other drivers; Option.Lookahead is a mesh
    scheduling knob (parallel.dist_trsm consumes it) — the single-chip
    recursive solve has no broadcast loop to pipeline."""
    am = a if isinstance(a, BaseMatrix) else TriangularMatrix.from_array(a, Uplo.Lower)
    out = trsm_array(side, am.uplo, am.op, am.diag, alpha, am.data, _arr(b))
    return _wrap_like(b, out)


# ---------------------------------------------------------------------------
# band (src/gbmm.cc, hbmm.cc, tbsm.cc)
# ---------------------------------------------------------------------------


def gbmm(alpha, a: ArrayLike, b: ArrayLike, beta, c: ArrayLike, opts: Optional[Options] = None):
    """slate::gbmm: general band * dense. Band stored dense-masked; XLA sees
    the zero pattern only through (kl, ku) metadata at the driver level."""
    am = a if isinstance(a, BaseMatrix) else None
    ad = band_project(_arr(a), am.kl, am.ku) if am is not None and am.kl is not None else _arr(a)
    bb = _arr(b)
    return _wrap_like(c, gemm_array(alpha, ad, bb, beta, _arr(c), precision=_mul_prec(opts)))


def hbmm(side: Side, alpha, a: ArrayLike, b: ArrayLike, beta, c: ArrayLike, opts: Optional[Options] = None):
    """slate::hbmm: Hermitian band * dense."""
    am = a if isinstance(a, BaseMatrix) else None
    if am is not None and am.kl is not None:
        kd = am.kl if am.uplo == Uplo.Lower else am.ku
        stored = band_project(am.data, am.kl, am.ku)
        afull = symmetrize(stored, am.uplo, conj=True)
    else:
        afull = symmetrize(_arr(a), Uplo.Lower, conj=True)
    bb = _arr(b)
    return _wrap_like(c, _side_mul(side, alpha, afull, bb, beta, _arr(c), precision=_mul_prec(opts)))


def tbsm(side: Side, alpha, a: ArrayLike, b: ArrayLike, pivots: Optional[jax.Array] = None):
    """slate::tbsm: triangular-band solve, optionally applying LU pivots
    first (src/tbsm.cc tbsmPivots path)."""
    am = a if isinstance(a, BaseMatrix) else TriangularMatrix.from_array(a, Uplo.Lower)
    bd = _arr(b)
    if pivots is not None:
        bd = _apply_pivots(bd, pivots, forward=True)
    out = trsm_array(side, am.uplo, am.op, am.diag, alpha, am.data, bd)
    return _wrap_like(b, out)


def _apply_pivots(b: jax.Array, pivots: jax.Array, forward: bool) -> jax.Array:
    """Sequential row interchanges, LAPACK laswp-style."""

    def body(i, acc):
        p = pivots[i]
        ri, rp = acc[i], acc[p]
        acc = acc.at[i].set(rp)
        acc = acc.at[p].set(ri)
        return acc

    n = pivots.shape[0]
    if forward:
        return jax.lax.fori_loop(0, n, body, b)

    def body_rev(t, acc):
        return body(n - 1 - t, acc)

    return jax.lax.fori_loop(0, n, body_rev, b)
