"""Persistent executable cache: one compiled program per request class.

Today every request that reaches a driver with a fresh options dict can
re-trace; at serving rates that is the difference between MXU-bound and
compiler-bound.  The cache pins ONE jitted program per ``CacheKey`` —
``(op, shape signature, dtype, batch, mesh, resolved Options)`` — so
steady-state traffic hits exactly the programs warmed at startup and
performs ZERO retraces (transfer-guard style: asserted by trace
counters, not hoped).

Layering: this is the HOST half (key -> traced program identity); the
DISK half is JAX's persistent compilation cache, which
``enable_persistent_compilation_cache`` turns on so a restarted server
re-loads compiled binaries instead of re-running XLA.  Note the PR 10
finding: cache-DESERIALIZED executables report an empty
``memory_analysis``, which is why the mem gates (obs/memory.py) force
their measuring compile to bypass the compilation cache — that bypass is
orthogonal to this layer and stays intact (tests/test_mem.py).

Trace counting: the cached program's Python body increments the key's
trace counter — the body only runs when JAX actually traces, so the
counter IS the retrace count (a cache hit at the jit layer never
re-enters Python).  ``ExecutableCache.assert_steady`` turns that into
the CI-facing zero-retrace assertion.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax

from .metrics import serve_count

CACHE_DIR_ENV = "SLATE_TPU_SERVE_CACHE_DIR"


class CacheKey(NamedTuple):
    """The request-class identity every compiled program is pinned to."""

    op: str            # driver name ("posv", "gesv", "gemm", "potrf", ...)
    shape: Tuple       # problem shape signature, e.g. ((8, 512, 512), (8, 512, 1))
    dtype: str         # operand dtype ("float64", ...)
    batch: int         # stack depth B (1 = single problem)
    mesh: str          # mesh descriptor ("none" = single-chip stacked path)
    opts: Tuple        # sorted resolved-option items, e.g. (("bcast_impl", "ring"),)


def options_signature(opts: Optional[Dict]) -> Tuple:
    """Canonical hashable form of a resolved Options mapping (enum keys
    and values collapse to their .value strings)."""
    if not opts:
        return ()
    items = []
    for k, v in opts.items():
        kk = getattr(k, "value", k)
        vv = getattr(v, "value", v)
        items.append((str(kk), vv))
    return tuple(sorted(items))


def mesh_signature(mesh) -> str:
    if mesh is None:
        return "none"
    shape = dict(mesh.shape)
    plat = mesh.devices.flat[0].platform
    return f"{plat}:" + "x".join(str(shape[a]) for a in mesh.axis_names)


def make_key(op: str, args: Tuple[jax.Array, ...], batch: int = 1,
             mesh=None, opts: Optional[Dict] = None) -> CacheKey:
    return CacheKey(
        op=op,
        shape=tuple(tuple(a.shape) for a in args),
        dtype=str(args[0].dtype),
        batch=batch,
        mesh=mesh_signature(mesh),
        opts=options_signature(opts),
    )


class ExecutableCache:
    """Key -> pinned jitted program, with trace accounting."""

    def __init__(self) -> None:
        self._programs: Dict[CacheKey, Callable] = {}
        self._trace_counts: Dict[CacheKey, int] = {}
        self._pinned: set = set()

    def __len__(self) -> int:
        return len(self._programs)

    def contains(self, key: CacheKey) -> bool:
        """Pure membership probe (no counter side effects): the request
        tracer reads it to attribute a lookup as hit vs miss BEFORE
        ``get_or_build`` performs (and counts) the real lookup."""
        return key in self._programs

    def get_or_build(self, key: CacheKey, build: Callable[[], Callable]):
        """The request path: a hit returns the pinned program; a miss
        builds the pure array->array function via ``build()``, wraps it
        in a trace-counting jit, and pins it under ``key``."""
        prog = self._programs.get(key)
        if prog is not None:
            serve_count("cache_hits")
            return prog
        serve_count("cache_misses")
        fn = build()

        def traced(*args):
            # body runs at TRACE time only: this is the retrace counter
            self._trace_counts[key] = self._trace_counts.get(key, 0) + 1
            serve_count("traces")
            return fn(*args)

        prog = jax.jit(traced)
        self._programs[key] = prog
        self._trace_counts.setdefault(key, 0)
        return prog

    def warmup(self, key: CacheKey, build: Callable[[], Callable],
               example_args: Tuple) -> None:
        """Compile ``key`` ahead of traffic: trace + compile + execute
        once on representative operands, so the first real request is a
        pure execution (and, with the persistent compilation cache on, a
        restarted server pays deserialization instead of XLA)."""
        prog = self.get_or_build(key, build)
        jax.block_until_ready(prog(*example_args))
        serve_count("warmups")
        self._pinned.add(key)

    def pin(self, key: CacheKey) -> None:
        self._pinned.add(key)

    def trace_count(self, key: CacheKey) -> int:
        return self._trace_counts.get(key, 0)

    def total_traces(self) -> int:
        return sum(self._trace_counts.values())

    def assert_steady(self, before: Optional[Dict[CacheKey, int]] = None) -> None:
        """Zero-retrace assertion: every known key has been traced at
        most once (or exactly its count in the ``before`` snapshot —
        take one with ``snapshot_traces`` after warm-up, run traffic,
        then assert nothing re-traced)."""
        ref = before if before is not None else {}
        for key, count in self._trace_counts.items():
            want = ref.get(key, 1)
            if count > want:
                raise AssertionError(
                    f"serve cache retraced {key.op} {key.shape} "
                    f"{count - want} time(s) past steady state — the key "
                    "is not capturing everything the trace depends on")

    def snapshot_traces(self) -> Dict[CacheKey, int]:
        return dict(self._trace_counts)

    def clear_unpinned(self) -> None:
        for key in list(self._programs):
            if key not in self._pinned:
                del self._programs[key]
                self._trace_counts.pop(key, None)

    def clear(self) -> None:
        self._programs.clear()
        self._trace_counts.clear()
        self._pinned.clear()


# The process-wide cache the Router and smoke use; tests may build their
# own isolated instances.
executable_cache = ExecutableCache()


def enable_persistent_compilation_cache(path: Optional[str] = None) -> str:
    """Turn on JAX's disk compilation cache under ``path`` (default
    ``$SLATE_TPU_SERVE_CACHE_DIR`` or ``~/.cache/slate_tpu_serve``) so
    compiled executables survive process restarts.  A directory already
    configured (e.g. the test suite's .jax_cache) is left alone."""
    current = jax.config.jax_compilation_cache_dir
    if current:
        return current
    path = path or os.environ.get(CACHE_DIR_ENV) or os.path.expanduser(
        "~/.cache/slate_tpu_serve")
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return path
