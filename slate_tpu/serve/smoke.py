"""Serve smoke: the CI acceptance run for the serving runtime.

Asserts, on the 8-device CPU mesh harness:

(a) **Batched throughput**: the stacked batch driver solves a flood of
    B same-shaped SPD problems >= 3x faster (solves/s) than the Python
    loop of one-at-a-time solves through today's request path (the mesh
    driver, warm executables — per-request dispatch of a 512-sized
    problem is exactly what the serving layer exists to replace), and
    every batched solution is BITWISE equal to the single-problem
    kernel's.
(b) **Zero steady-state retraces**: after warm-up, a stream of batches
    through the executable cache performs no retraces (trace-counter
    asserted, transfer-guard style).
(c) **Ragged packing**: pack -> solve -> unpack returns exactly the
    per-(padded-)problem solutions.
(d) **Tuned table**: the committed artifact loads, validates, and the
    request path resolves unset options through it (explicit still
    wins).
(e) **Request-level SLA** (ISSUE 14): a deterministic request stream
    through the Router must leave a nonempty latency histogram per
    accuracy class with p50 <= p95 <= p99, attribute every request to
    EXACTLY one terminal outcome (totals == request count), export a
    Perfetto-valid request timeline, and the ``serve.stats``
    Prometheus text must carry the surface.

Emits ``serve.report.json`` (RunReport schema, ``serve`` counter
section + headline values) and ``serve_sla.report.json`` (the SLA
phase's own RunReport: latency quantiles/counts + outcome rates) for
the CI regression gates — machine-dependent rates carry a ``_runtime_``
infix and the latency quantiles a ``latency…_s`` shape so the
committed-artifact checks can ``--ignore 'serve.*_runtime_*'`` /
``--ignore '*latency*_s'`` while the deterministic shape/count/rate
keys gate tight.

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m slate_tpu.serve.smoke [--out artifacts/serve] [--n 512]
        [--batch 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def measure_throughput(mesh, n: int = 512, batch: int = 8, nrhs: int = 1,
                       reps: int = 3, loop_reps: int = 2) -> dict:
    """Warm solves/s of the stacked batch driver vs the one-at-a-time
    mesh-driver loop on B SPD problems — the serving headline.  Returns
    rates + the bitwise-parity flag (also reused by bench.py's
    ``serve_batched_solves_per_s`` / ``serve_vs_loop_speedup`` extras)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..linalg.chol import posv_array
    from ..parallel.drivers import posv_mesh
    from ..types import Option
    from .batch import posv_batched
    from .cache import executable_cache, make_key

    rng = np.random.default_rng(0)
    g = rng.standard_normal((batch, n, n))
    spd = jnp.asarray(np.einsum("bij,bkj->bik", g, g) / n
                      + 2 * np.eye(n)[None])
    b = jnp.asarray(rng.standard_normal((batch, n, nrhs)))

    # today's request path: one mesh dispatch per problem (direct f64
    # driver — deterministic work per request, no refinement iteration
    # count in the denominator)
    opts = {Option.MixedPrecision: "off"}
    loop_nb = 64
    jax.block_until_ready(posv_mesh(spd[0], b[0], mesh, loop_nb, opts)[0])
    t0 = time.perf_counter()
    for _ in range(loop_reps):
        outs = [posv_mesh(spd[i], b[i], mesh, loop_nb, opts)[0]
                for i in range(batch)]
        jax.block_until_ready(outs)
    loop_s = (time.perf_counter() - t0) / loop_reps

    # the serving path: ONE compiled program over the stack, through the
    # executable cache (warmup compiles + pins it)
    key = make_key("posv_batched", (spd, b), batch=batch, mesh=None)
    executable_cache.warmup(key, lambda: posv_batched, (spd, b))
    prog = executable_cache.get_or_build(key, lambda: posv_batched)
    t0 = time.perf_counter()
    for _ in range(reps):
        xs, info = prog(spd, b)
        jax.block_until_ready(xs)
    bat_s = (time.perf_counter() - t0) / reps

    # bitwise parity vs the single-problem kernel AS DISPATCHED (jitted
    # — eager concrete calls can take form-dispatch branches a traced
    # program cannot, so the jitted program is the per-problem identity)
    single = jax.jit(lambda aa, bb: posv_array(aa, bb)[0])
    bitwise = all(
        np.array_equal(np.asarray(xs[i]), np.asarray(single(spd[i], b[i])))
        for i in range(batch))
    return {
        "n": n, "batch": batch, "key": key,
        "loop_solves_per_s": batch / loop_s,
        "batched_solves_per_s": batch / bat_s,
        "speedup": loop_s / bat_s,
        "bitwise": bitwise,
        "info_ok": bool(np.all(np.asarray(info) == 0)),
    }


def run_sla_phase(out_dir: str, failures: list) -> dict:
    """(e) Request-level SLA observability (ISSUE 14): drive a
    deterministic meshless request stream through the Router — both
    condest accuracy classes plus an admission reject — then assert the
    trace/SLA contracts and emit ``serve_sla.report.json`` + the
    Perfetto request timeline.  Meshless on purpose: the stream is
    broadcast-impl-independent, so the ring CI re-run reproduces the
    gated counts exactly."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..obs import REGISTRY, perfetto, report
    from ..types import SlateError
    from . import trace as serve_trace
    from .router import Router
    from .stats import prometheus_text, stats_snapshot

    rng = np.random.default_rng(3)
    n = 48
    router = Router(bins=(64,), hbm_budget=1 << 30)
    traces_before = len(serve_trace.finished_traces())
    requests = 0

    def spd(sz):
        g = rng.standard_normal((sz, sz))
        return jnp.asarray(g @ g.T / sz + 2 * np.eye(sz))

    b = jnp.asarray(rng.standard_normal((n, 2)))
    # friendly gesv x2 + posv x3 + hostile gesv x2 (prescribed spectrum,
    # cond 1e9 >> CONDEST_THRESHOLD)
    for _ in range(2):
        good = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n))
        router.solve("gesv", good, b)
        requests += 1
    for _ in range(3):
        router.solve("posv", spd(n), b)
        requests += 1
    q1, _ = np.linalg.qr(rng.standard_normal((n, n)))
    q2, _ = np.linalg.qr(rng.standard_normal((n, n)))
    sing = np.logspace(0, -9, n)
    for _ in range(2):
        router.solve("gesv", jnp.asarray(q1 @ np.diag(sing) @ q2), b)
        requests += 1
    # one admission reject: a router whose modeled HBM budget admits
    # nothing terminates the request as reject_admission
    tiny = Router(bins=(64,), hbm_budget=10_000)
    try:
        tiny.solve("posv", spd(n), b)
        failures.append("SLA phase: 10kB-budget router admitted an n=48 "
                        "solve — admission model broken")
    except SlateError:
        pass
    requests += 1

    traces = serve_trace.finished_traces()[traces_before:]
    # every request terminated with exactly one outcome
    if len(traces) != requests:
        failures.append(f"SLA phase: {requests} requests produced "
                        f"{len(traces)} finished traces")
    if any(t.outcome is None for t in traces):
        failures.append("SLA phase: a finished trace has no terminal "
                        "outcome")
    sla = serve_trace.sla_values()
    total_outcomes = sum(v for k, v in sla.items()
                         if k.startswith("outcome_")
                         and not k.startswith("outcome_rate_"))
    if total_outcomes != requests:
        failures.append(
            f"SLA phase: outcome attribution totals {total_outcomes} != "
            f"request count {requests} — a request is unattributed or "
            "double-attributed")
    # nonempty latency histogram per accuracy class, p50 <= p95 <= p99
    for op, klass in (("gesv", "friendly"), ("gesv", "hostile"),
                      ("posv", "friendly")):
        if sla.get(f"latency_count_{op}_{klass}", 0) <= 0:
            failures.append(f"SLA phase: empty latency histogram for "
                            f"({op}, {klass})")
            continue
        p50 = sla[f"latency_p50_{op}_{klass}_s"]
        p95 = sla[f"latency_p95_{op}_{klass}_s"]
        p99 = sla[f"latency_p99_{op}_{klass}_s"]
        if not (0 <= p50 <= p95 <= p99):
            failures.append(f"SLA phase: quantiles not monotone for "
                            f"({op}, {klass}): {p50} / {p95} / {p99}")
    # export surfaces: Perfetto request timeline + Prometheus text
    trace_path = os.path.join(out_dir, "serve_requests.trace.json")
    perfetto.write_request_trace(trace_path, traces)
    with open(trace_path) as f:
        errs = perfetto.validate_chrome_trace(json.load(f))
    if errs:
        failures.append(f"SLA phase: request timeline invalid: {errs[:3]}")
    text = prometheus_text(stats_snapshot())
    for needle in ("slate_tpu_serve_requests", "slate_tpu_serve_latency_s",
                   'quantile="0.99"'):
        if needle not in text:
            failures.append(f"SLA phase: {needle!r} missing from the "
                            "Prometheus export")
    sla_rep_path = os.path.join(out_dir, "serve_sla.report.json")
    report.write_report(
        sla_rep_path, name="serve_sla",
        config={"n": n, "bins": "64", "driver": "router_meshless"},
        values={"serve.sla_requests": float(requests),
                "serve.sla_traces": float(len(traces))})
    with open(sla_rep_path) as f:
        errs = report.validate_report(json.load(f))
    if errs:
        failures.append(f"SLA RunReport schema: {errs}")
    return {"requests": requests, "traces": len(traces),
            "report": sla_rep_path, "trace": trace_path}


def run_smoke(out_dir: str, n: int = 512, batch: int = 8) -> int:
    import jax

    jax.config.update("jax_enable_x64", True)  # f64 serving classes
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices("cpu")
    if len(devs) < 8:
        print(f"serve.smoke: need 8 CPU devices, have {len(devs)} — set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8")
        return 2

    from .. import obs
    from ..linalg.chol import posv_array
    from ..obs import report
    from ..parallel import make_mesh
    from ..types import Option
    from . import metrics as serve_metrics
    from .batch import pack_block_diag, unpack_block_diag
    from .cache import executable_cache
    from .table import load_tuned_table, resolve_request_options

    obs.reset()
    obs.enable()
    serve_metrics.reset()
    executable_cache.clear()
    mesh = make_mesh(2, 4, devices=devs[:8])
    failures = []

    # (a) batched throughput + bitwise parity ------------------------------
    thr = measure_throughput(mesh, n=n, batch=batch)
    print(f"serve.smoke: loop {thr['loop_solves_per_s']:.2f} solves/s, "
          f"batched {thr['batched_solves_per_s']:.2f} solves/s "
          f"({thr['speedup']:.1f}x, B={batch}, n={n})")
    if thr["speedup"] < 3.0:
        failures.append(
            f"batched speedup {thr['speedup']:.2f}x < 3x the one-at-a-time "
            "loop — the serving headline regressed")
    if not thr["bitwise"]:
        failures.append("batched solutions are not bitwise-equal to the "
                        "single-problem kernel")
    if not thr["info_ok"]:
        failures.append("batched factorization reported nonzero info")

    # (b) steady state: more traffic, zero retraces ------------------------
    before = executable_cache.snapshot_traces()
    rng = np.random.default_rng(1)
    prog = None
    for _ in range(5):
        g = rng.standard_normal((batch, n, n))
        spd = jnp.asarray(np.einsum("bij,bkj->bik", g, g) / n
                          + 2 * np.eye(n)[None])
        bb = jnp.asarray(rng.standard_normal((batch, n, 1)))
        from .batch import posv_batched
        from .cache import make_key

        key = make_key("posv_batched", (spd, bb), batch=batch, mesh=None)
        prog = executable_cache.get_or_build(key, lambda: posv_batched)
        jax.block_until_ready(prog(spd, bb)[0])
    try:
        executable_cache.assert_steady(before)
    except AssertionError as e:
        failures.append(str(e))

    # (c) ragged packing round trip: pack -> solve -> unpack is EXACT in
    # the non-interaction sense — each problem's unpacked solution is
    # bitwise what it would be packed ALONE (co-packed operands only
    # ever contribute structural zeros), and matches the per-problem
    # unpadded solve to factorization accuracy
    sizes = [48, 33, 64]
    m = 64
    k = len(sizes)
    ops_, rhs_ = [], []
    for sz in sizes:
        g = rng.standard_normal((sz, sz))
        ops_.append(jnp.asarray(g @ g.T / sz + 2 * np.eye(sz)))
        rhs_.append(jnp.asarray(rng.standard_normal((sz, 2))))
    a_pack, b_pack = pack_block_diag(ops_, m, rhs_)
    x_pack, _f, info = posv_array(a_pack, b_pack)
    got = unpack_block_diag(x_pack, sizes, m, [2] * k)
    pack_ok = int(info) == 0
    for i, sz in enumerate(sizes):
        solo_a, solo_b = pack_block_diag(
            [ops_[j] if j == i else jnp.eye(m, dtype=a_pack.dtype)
             for j in range(k)],
            m,
            [rhs_[j] if j == i else jnp.zeros((m, 2), a_pack.dtype)
             for j in range(k)])
        ref = unpack_block_diag(posv_array(solo_a, solo_b)[0], sizes, m,
                                [2] * k)[i]
        if not np.array_equal(np.asarray(got[i]), np.asarray(ref)):
            pack_ok = False
        lone = posv_array(ops_[i], rhs_[i])[0]
        if not np.allclose(np.asarray(got[i]), np.asarray(lone),
                           rtol=1e-10, atol=1e-10):
            pack_ok = False
    if not pack_ok:
        failures.append("block-diagonal pack -> solve -> unpack lost "
                        "per-problem exactness (blocks interacted)")

    # (d) tuned table: committed artifact + resolution ---------------------
    table = load_tuned_table()
    tuned_entries = len(table["entries"]) if table else 0
    if table is None:
        failures.append("committed tuned table missing or invalid "
                        "(artifacts/serve/tuned.json)")
    else:
        merged = resolve_request_options(None, "potrf", 96, "float64", (2, 4))
        env_pin = os.environ.get("SLATE_TPU_BCAST_IMPL")
        if Option.Lookahead not in merged:
            failures.append("tuned table did not resolve an unset Lookahead")
        if env_pin and merged.get(Option.BcastImpl) is not None:
            failures.append("tuned tier overrode the environment BcastImpl "
                            "pin — precedence chain broken")
        explicit = resolve_request_options(
            {Option.Lookahead: 0}, "potrf", 96, "float64", (2, 4))
        if explicit.get(Option.Lookahead) != 0:
            failures.append("explicit option lost to the tuned table")

    # (e) request-level SLA observability (ISSUE 14) -----------------------
    os.makedirs(out_dir, exist_ok=True)
    sla = run_sla_phase(out_dir, failures)

    # report ----------------------------------------------------------------
    rep_path = os.path.join(out_dir, "serve.report.json")
    values = {
        # machine-dependent rates: _runtime_ infix => CI gate --ignore's
        "serve.posv_runtime_loop_solves_per_s": thr["loop_solves_per_s"],
        "serve.posv_runtime_batched_solves_per_s": thr["batched_solves_per_s"],
        "serve.posv_runtime_speedup": thr["speedup"],
        # deterministic at fixed workload: gate tight
        "serve.cache_programs": float(len(executable_cache)),
        "serve.batched_bitwise_ok": float(thr["bitwise"]),
        "serve.pack_roundtrip_ok": float(pack_ok),
        "serve.tuned_entries": float(tuned_entries),
    }
    report.write_report(
        rep_path, name="serve_smoke",
        config={"n": n, "batch": batch, "grid": "2x4",
                "driver": "posv_batched"},
        values=values)
    with open(rep_path) as f:
        rep = json.load(f)
    errs = report.validate_report(rep)
    if errs:
        failures.append(f"RunReport schema: {errs}")
    serve_sec = rep.get("serve") or {}
    if serve_sec.get("traces", 0) <= 0:
        failures.append("serve counter section missing trace counts — "
                        "obs.report is not folding serve.* in")
    if serve_sec.get("cache_misses", 0) > serve_sec.get("traces", 0):
        failures.append("cache misses exceed traces — a built program "
                        "never traced?")

    if failures:
        print(f"serve.smoke: FAILED with {len(failures)} problem(s):")
        for msg in failures:
            print(f"  FAIL {msg}")
        return 1
    print(f"serve.smoke: OK — {thr['speedup']:.1f}x batched speedup, "
          f"{int(serve_sec['traces'])} trace(s) over "
          f"{len(executable_cache)} program(s), 0 retraces, "
          f"{sla['requests']} SLA request(s) fully attributed, report "
          f"{rep_path} + {sla['report']}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m slate_tpu.serve.smoke")
    ap.add_argument("--out", default=os.path.join("artifacts", "serve"))
    ap.add_argument("--n", type=int, default=512)
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args(argv)
    return run_smoke(args.out, args.n, args.batch)


if __name__ == "__main__":
    sys.exit(main())
