"""Serving runtime: batched small-problem drivers, a persistent
executable cache, and an autotuned schedule table (ISSUE 11).

The reference SLATE is built for one big factorization at a time; the
serving workload is the opposite — floods of 256–4096-sized solves where
the one-at-a-time mesh dispatch leaves the hardware idle between
requests.  This package is the throughput layer over ``api.py`` /
``parallel/drivers.py``:

- ``batch``: stacked batch drivers (one compiled program factors a
  stack of B same-shaped problems, bitwise-equal per problem to the
  single-problem kernels) plus block-diagonal packing that bins ragged
  sizes into a few canonical shapes (pad-to-bin, pack k problems into
  one block-diagonal operand, unpack solutions).
- ``cache``: the persistent executable cache keyed on
  ``(op, shape, dtype, batch, mesh, resolved Options)``, layered over
  JAX's persistent compilation cache, with warm-up/pin APIs and
  trace-count assertions (steady-state traffic performs ZERO retraces).
- ``table`` / ``tune``: the autotuned schedule table.  ``python -m
  slate_tpu.serve.tune`` sweeps (BcastImpl, Lookahead, nb, stationary
  variant) per cache key using the flight recorder's measured
  ``sched.*`` metrics as the objective and persists the winners as a
  versioned artifact (``artifacts/serve/tuned.json``); the request path
  resolves unset Options through the table (explicit > context > env >
  tuned > auto — the Option.BcastImpl resolution-chain idiom extended
  by one tier).
- ``router``: admission control via ``MemoryModel.predict_max_n``,
  accuracy-class dispatch via cached condition estimates (cheap
  nopiv+IR for friendly operators, pp+GMRES-IR above
  ``numerics.CONDEST_THRESHOLD`` — the Carson–Higham regime boundary),
  then dispatch through the executable cache.
- ``trace`` / ``stats``: request-level observability (ISSUE 14).  With
  the obs layer on, every Router request carries a ``RequestTrace``
  across admission → classify → cache → factor/solve → the degradation
  ladder, terminated with exactly one outcome; latencies land in
  (op, class, outcome)-tagged histograms reduced to the gated
  ``serve.latency_{p50,p95,p99}_*`` + outcome-rate SLA surface.
  ``python -m slate_tpu.serve.stats`` exports Prometheus text + JSON;
  ``obs.perfetto.request_trace_events`` renders request timelines.
- ``python -m slate_tpu.serve.smoke`` is the CI acceptance run; the
  ``serve.*`` counters land in every RunReport and gate via
  ``obs.report --check`` like the ft/ir/mem/num sections.
- ``queue`` / ``budget`` / ``controller`` / ``service``: the async
  service layer (ISSUE 19).  ``BatchQueue`` coalesces a concurrent
  request stream into batch windows (B requests or T seconds, binned
  on the cache-key identity) over per-tenant HBM budget accounts
  (``BudgetLedger``, ``reject_budget``) with weighted deficit-round-
  robin dequeue; ``ServiceController`` closes the SLA loop (hysteresis
  latches moving (B, T) and the precision-tier entry point off the
  PR 14 p95/outcome-rate surface); ``python -m slate_tpu.serve.service``
  is the stdlib-http front door and ``python -m
  slate_tpu.serve.queue_smoke`` the CI acceptance run.
"""

from .batch import (  # noqa: F401
    gemm_batched,
    gesv_batched,
    pack_block_diag,
    pad_to_bin,
    posv_batched,
    potrf_batched,
    unpack_block_diag,
)
from .budget import BudgetLedger, request_cost  # noqa: F401
from .cache import CacheKey, ExecutableCache, executable_cache  # noqa: F401
from .controller import Hysteresis, ServiceController  # noqa: F401
from .metrics import serve_counter_values  # noqa: F401
from .queue import BatchQueue, ManualClock, queue_stats  # noqa: F401
from .router import Router  # noqa: F401
from .trace import RequestTrace, finished_traces  # noqa: F401
from .table import (  # noqa: F401
    load_tuned_table,
    resolve_request_options,
    use_tuned_table,
)

__all__ = [
    "BatchQueue",
    "BudgetLedger",
    "CacheKey",
    "ExecutableCache",
    "executable_cache",
    "Hysteresis",
    "ManualClock",
    "Router",
    "ServiceController",
    "queue_stats",
    "request_cost",
    "gemm_batched",
    "gesv_batched",
    "posv_batched",
    "potrf_batched",
    "pack_block_diag",
    "pad_to_bin",
    "unpack_block_diag",
    "serve_counter_values",
    "RequestTrace",
    "finished_traces",
    "load_tuned_table",
    "resolve_request_options",
    "use_tuned_table",
]
