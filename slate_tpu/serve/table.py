"""The autotuned schedule table: persistence + request-path resolution.

``python -m slate_tpu.serve.tune`` measures (BcastImpl, Lookahead, nb,
stationary variant) sweeps per cache key with the flight recorder's
``sched.*`` metrics as the objective and writes the winners here as a
versioned committed artifact (``artifacts/serve/tuned.json``).  The
request path then resolves UNSET schedule options through the table:

    explicit option > context manager > environment > tuned > auto

i.e. the existing Option.BcastImpl resolution-chain idiom extended by
one tier — the table only ever speaks when every older tier is silent,
so a user pin (or a CI sweep's env override) always wins.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Dict, Optional, Tuple

from ..types import MethodGemm, Option, Options, get_option
from .metrics import serve_count

TUNED_SCHEMA = "slate_tpu.serve.tuned_table"
TUNED_VERSION = 1
TUNED_ENV = "SLATE_TPU_SERVE_TUNED"  # path override for the table file
AUTOTUNE_ENV = "SLATE_TPU_AUTOTUNE"  # "0" disables the tuned tier

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_TABLE_PATH = os.path.join(_REPO_ROOT, "artifacts", "serve",
                                  "tuned.json")

# session override (use_tuned_table): a loaded table dict, None (= pin
# "no table"), or _UNSET (no override active — fall through to files)
_UNSET = object()
_TABLE_CTX: list = [_UNSET]
_TABLE_FILE_CACHE: Dict[str, Dict] = {}


def entry_key(op: str, n: int, dtype: str, grid: Tuple[int, int]) -> str:
    """The table's row identity — matches the executable-cache key's
    schedule-relevant coordinates (batch rides the shape, not the
    schedule; nb is a TUNABLE, so it lives in the entry, not the key)."""
    return f"{op}|n={n}|dtype={dtype}|grid={grid[0]}x{grid[1]}"


def validate_table(doc: Any) -> list:
    errs = []
    if not isinstance(doc, dict):
        return ["tuned table must be an object"]
    if doc.get("schema") != TUNED_SCHEMA:
        errs.append(f"schema must be {TUNED_SCHEMA!r}, got {doc.get('schema')!r}")
    if not isinstance(doc.get("version"), int):
        errs.append("version must be an int")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        errs.append("entries must map key -> winning options")
        return errs
    for key, e in entries.items():
        if not isinstance(e, dict):
            errs.append(f"{key}: entry must be an object")
            continue
        for field, typ in (("bcast_impl", str), ("lookahead", int),
                           ("nb", int)):
            if field in e and not isinstance(e[field], typ):
                errs.append(f"{key}: {field} must be {typ.__name__}")
    return errs


def load_tuned_table(path: Optional[str] = None) -> Optional[Dict]:
    """The active table: session context > explicit path >
    $SLATE_TPU_SERVE_TUNED > the committed artifact.  Returns None when
    nothing is available (the resolution chain then just skips the
    tuned tier)."""
    if _TABLE_CTX[-1] is not _UNSET:
        return _TABLE_CTX[-1]
    path = path or os.environ.get(TUNED_ENV) or DEFAULT_TABLE_PATH
    if path in _TABLE_FILE_CACHE:
        return _TABLE_FILE_CACHE[path]
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if validate_table(doc):
        return None
    _TABLE_FILE_CACHE[path] = doc
    return doc


@contextlib.contextmanager
def use_tuned_table(table: Optional[Dict]):
    """Pin a table dict (or None to disable) for calls inside — the
    testing/sweep hook, same shape as comm.use_bcast_impl."""
    if table is not None:
        errs = validate_table(table)
        if errs:
            raise ValueError(f"invalid tuned table: {errs}")
    _TABLE_CTX.append(table)
    try:
        yield
    finally:
        _TABLE_CTX.pop()


def clear_table_cache() -> None:
    _TABLE_FILE_CACHE.clear()


def lookup(op: str, n: int, dtype: str, grid: Tuple[int, int],
           table: Optional[Dict] = None) -> Optional[Dict]:
    """The winning entry for a request class: exact n first, then the
    nearest tuned n at the same (op, dtype, grid) — serving bins are
    coarse, and a 96-tuned schedule is the best prior for 128."""
    doc = table if table is not None else load_tuned_table()
    if doc is None:
        return None
    entries = doc.get("entries", {})
    exact = entries.get(entry_key(op, n, dtype, grid))
    if exact is not None:
        return exact
    prefix = f"{op}|n="
    suffix = f"|dtype={dtype}|grid={grid[0]}x{grid[1]}"
    best, best_dist = None, None
    for key, e in entries.items():
        if not (key.startswith(prefix) and key.endswith(suffix)):
            continue
        try:
            kn = int(key[len(prefix):-len(suffix)])
        except ValueError:
            continue
        # a schedule tuned at kn is only a credible prior within ~2x of
        # the request size: an nb/depth winner at n=96 says nothing
        # about n=4096, and silence (-> the auto chain) beats a wild
        # extrapolation
        if not (n / 2 <= kn <= n * 2):
            continue
        dist = abs(kn - n)
        if best_dist is None or dist < best_dist:
            best, best_dist = e, dist
    return best


def autotune_enabled(opts: Optional[Options] = None) -> bool:
    """Option.AutoTune resolution: explicit > $SLATE_TPU_AUTOTUNE > on."""
    explicit = get_option(opts, Option.AutoTune)
    if explicit is not None:
        return str(getattr(explicit, "value", explicit)).lower() not in (
            "off", "0", "false")
    return os.environ.get(AUTOTUNE_ENV, "1") not in ("0", "off", "false")


def _bcast_tier_silent() -> bool:
    """True when neither the use_bcast_impl context nor the
    SLATE_TPU_BCAST_IMPL environment pins a lowering — the only state in
    which the tuned tier may speak for Option.BcastImpl."""
    from ..parallel.comm import BCAST_IMPL_ENV, _IMPL_DEFAULT

    return _IMPL_DEFAULT[-1] is None and not os.environ.get(BCAST_IMPL_ENV)


def _raw(opts: Optional[Options], key: Option):
    """Presence-only option lookup: None means genuinely UNSET (unlike
    types.get_option, which falls back to the option's default — the
    tuned tier must slot in BEFORE that default, not after)."""
    if not opts:
        return None
    if key in opts:
        return opts[key]
    if key.value in opts:
        return opts[key.value]
    return None


def resolve_request_options(
    opts: Optional[Options], op: str, n: int, dtype: str,
    grid: Tuple[int, int], table: Optional[Dict] = None,
) -> Dict:
    """Fill a request's UNSET schedule options from the tuned table.

    Returns a plain dict Options mapping: the caller's explicit options
    verbatim, plus — only where every older tier (explicit > context >
    env) is silent — the tuned winners for (op, n, dtype, grid).  With
    no table (or Option.AutoTune off) the input passes through and the
    per-option default chains behave exactly as before (auto)."""
    merged: Dict = dict(opts) if opts else {}
    if not autotune_enabled(opts):
        return merged
    entry = lookup(op, n, dtype, grid, table)
    if entry is None:
        return merged
    used = False
    if (_raw(merged, Option.BcastImpl) is None
            and "bcast_impl" in entry and _bcast_tier_silent()):
        merged[Option.BcastImpl] = entry["bcast_impl"]
        used = True
    if _raw(merged, Option.Lookahead) is None and "lookahead" in entry:
        merged[Option.Lookahead] = int(entry["lookahead"])
        used = True
    if _raw(merged, Option.BlockSize) is None and "nb" in entry:
        merged[Option.BlockSize] = int(entry["nb"])
        used = True
    if (op == "gemm" and _raw(merged, Option.MethodGemm) is None
            and "method" in entry):
        merged[Option.MethodGemm] = MethodGemm(entry["method"])
        used = True
    if used:
        serve_count("tuned_resolutions")
    return merged


def write_table(path: str, entries: Dict[str, Dict],
                config: Optional[Dict] = None) -> str:
    """Persist a tuned table as the versioned committed artifact."""
    import time

    from ..obs.report import _env_info

    doc = {
        "schema": TUNED_SCHEMA,
        "version": TUNED_VERSION,
        "created_unix": time.time(),
        "env": _env_info(),
        "config": dict(config or {}),
        "entries": entries,
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    clear_table_cache()
    return path
