"""Per-tenant HBM budgets for the batch-window queue (ISSUE 19).

The Router's admission bound (``MemoryModel.predict_max_n`` +
``admit_batch``) protects the DEVICE: no single dispatch may exceed the
modeled HBM budget.  It says nothing about WHO is consuming it — one
tenant's n=16384 burst passes per-request admission and still evicts
everyone else's working set.  The ledger here is the tenant dimension of
that bound: every queued-or-in-flight request holds a modeled-byte
reservation against its tenant's budget, and a submit that would push
the tenant past its budget is refused BEFORE it enters a window
(``reject_budget`` in the RequestTrace taxonomy — the fair-share twin
of ``reject_admission``).

The modeled cost of one request is the same closed form
``Router.admit_batch`` applies to a whole stacked dispatch
(~3.5 copies of the binned operand: operand + factor + solution + XLA
temps for the mapped body), prorated to one problem — the ledger and
the device bound price a request identically, so a stream that is
tenant-admissible is also device-admissible once windows cap at B.

Weights live here too: the ledger is the ONE place the queue's deficit
round-robin reads a tenant's fair share from, so budget and weight are
declared together (``BudgetLedger(budgets=..., weights=...)``).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

# matches Router.admit_batch's aggregate-residency model: the whole
# stack lives at once, ~3.5 copies per problem
REQUEST_RESIDENCY_FACTOR = 3.5


def request_cost(m: int, itemsize: int) -> int:
    """Modeled HBM residency of ONE bin-padded request inside a stacked
    dispatch (the per-problem share of Router.admit_batch's bound)."""
    return int(REQUEST_RESIDENCY_FACTOR * m * m * itemsize)


class TenantAccount:
    """One tenant's ledger row: budget, fair-share weight, the live
    reservation total, and its high-water mark (the smoke's no-tenant-
    over-budget assertion reads ``peak``)."""

    __slots__ = ("tenant", "budget", "weight", "reserved", "peak")

    def __init__(self, tenant: str, budget: int, weight: float) -> None:
        self.tenant = tenant
        self.budget = int(budget)
        self.weight = float(weight)
        self.reserved = 0
        self.peak = 0

    def headroom(self) -> int:
        return self.budget - self.reserved


class BudgetLedger:
    """Thread-safe per-tenant reservation ledger.

    Tenants not named in ``budgets`` get ``default_budget`` (default:
    the device HBM budget under the memmodel safety factor — one tenant
    alone may use the whole device; the ledger only bites once budgets
    are declared tighter).  ``try_reserve`` is the queue's admission
    probe: False means the submit must be refused as ``reject_budget``
    — the ledger itself never raises and never counts, so policy
    (reject vs backpressure) stays in the queue."""

    def __init__(self, budgets: Optional[Dict[str, int]] = None,
                 weights: Optional[Dict[str, float]] = None,
                 default_budget: Optional[int] = None,
                 default_weight: float = 1.0) -> None:
        from ..obs import memmodel

        self._default_budget = int(
            default_budget if default_budget is not None
            else memmodel.hbm_budget() * memmodel.HBM_SAFETY)
        self._default_weight = float(default_weight)
        self._declared_budgets = dict(budgets or {})
        self._declared_weights = {t: float(v)
                                  for t, v in (weights or {}).items()}
        for t, v in [("<default>", self._default_weight),
                     *self._declared_weights.items()]:
            if not v > 0.0:   # also catches NaN
                raise ValueError(
                    f"budget: DRR weight for tenant {t!r} must be > 0, "
                    f"got {v!r} — a non-positive weight never accrues "
                    "deficit and would stall the dequeue rotation")
        self._accounts: Dict[str, TenantAccount] = {}
        self._lock = threading.Lock()

    def account(self, tenant: str) -> TenantAccount:
        with self._lock:
            acct = self._accounts.get(tenant)
            if acct is None:
                acct = self._accounts[tenant] = TenantAccount(
                    tenant,
                    self._declared_budgets.get(tenant, self._default_budget),
                    self._declared_weights.get(tenant, self._default_weight))
            return acct

    def weight(self, tenant: str) -> float:
        return self.account(tenant).weight

    def headroom(self, tenant: str) -> int:
        return self.account(tenant).headroom()

    def try_reserve(self, tenant: str, cost: int) -> bool:
        """Reserve ``cost`` modeled bytes against ``tenant``'s budget;
        False (nothing reserved) when the tenant would go over."""
        acct = self.account(tenant)
        with self._lock:
            if acct.reserved + cost > acct.budget:
                return False
            acct.reserved += cost
            acct.peak = max(acct.peak, acct.reserved)
            return True

    def release(self, tenant: str, cost: int) -> None:
        acct = self.account(tenant)
        with self._lock:
            acct.reserved = max(0, acct.reserved - cost)

    def snapshot(self) -> Dict[str, dict]:
        """Per-tenant ledger view for the ``/queue.json`` scrape and the
        ``serve.queue_budget_headroom_bytes`` gauges."""
        with self._lock:
            return {
                name: {
                    "budget_bytes": acct.budget,
                    "reserved_bytes": acct.reserved,
                    "headroom_bytes": acct.headroom(),
                    "peak_bytes": acct.peak,
                    "weight": acct.weight,
                }
                for name, acct in sorted(self._accounts.items())
            }
