"""Queue smoke: the CI acceptance run for the service layer (ISSUE 19).

Drives a deterministic 64-request two-tenant stream through the
batch-window queue on an injectable ManualClock — every scheduling
decision in this run is about NUMBERS, never about how fast CI ran —
and asserts:

(a) **Windowed throughput**: the stream coalesces into at most
    ceil(N/B) dispatched batch programs, with ZERO steady-state
    retraces (trace-counter asserted after the first window), and every
    served solution BITWISE equal to one-at-a-time dispatch through a
    fresh reference Router — the queue is host-side scheduling only.
(b) **Fair-share dequeue**: an oversubscribed window dequeues by
    weighted deficit round robin — no pending tenant is shut out of a
    closed window (starvation freedom), service stays within one
    max-weight round of the weight ratio, and FIFO holds within a
    tenant.  No tenant's reservation ledger ever exceeded its budget.
(c) **Budget rejections**: a tenant submitting past its HBM budget is
    refused as the ``reject_budget`` terminal (counted, exactly-one-
    terminal), other tenants are untouched, and drained windows restore
    the tenant's headroom.
(d) **Admission memo**: a steady-state 100-request admission stream
    across two Routers computes the MemoryModel closed form EXACTLY
    once per (op, nb, grid, dtype, budget) key
    (``serve.max_n_computes``).
(e) **Control loop**: a seeded p95 latency spike trips the controller's
    hysteresis latch exactly once (no flapping under a sustained
    square-wave input), the actuation moves the (B, T) window knobs,
    and the ``controller`` event lands on the telemetry bus.
(f) **Packed dispatch**: a ragged posv window in ``dispatch="packed"``
    mode runs as ONE block-diagonal program whose unpacked solutions
    match the solo kernel to factorization accuracy.

Meshless ON PURPOSE: the stream is broadcast-impl-independent, so the
``SLATE_TPU_BCAST_IMPL=ring`` CI re-run reproduces every gated count
exactly.  Emits ``serve_queue.report.json`` (RunReport schema; the
``serve`` counter section rides in automatically) gated by
``obs.report --check --ignore '*latency*_s'``.

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m slate_tpu.serve.queue_smoke [--out artifacts/serve]
"""

from __future__ import annotations

import argparse
import os
import sys


def _spd(rng, n):
    import jax.numpy as jnp
    import numpy as np

    g = rng.standard_normal((n, n))
    return jnp.asarray(g @ g.T / n + 2 * np.eye(n))


def run_stream_phase(failures: list) -> dict:
    """(a)+(b): the 64-request two-tenant stream + the oversubscribed
    DRR window."""
    import numpy as np
    import jax.numpy as jnp

    from .cache import ExecutableCache
    from .metrics import serve_counts
    from .queue import BatchQueue, ManualClock
    from .router import Router

    rng = np.random.default_rng(19)
    n, total, batch = 32, 64, 8
    clk = ManualClock()
    qcache = ExecutableCache()
    router = Router(bins=(n,), hbm_budget=1 << 30, cache=qcache)
    q = BatchQueue(router, max_batch=batch, window_s=0.005, clock=clk,
                   budgets={"acme": 1 << 30, "zeta": 1 << 30},
                   weights={"acme": 2.0, "zeta": 1.0}, name="smoke")
    probs = [(_spd(rng, n), jnp.asarray(rng.standard_normal((n,))))
             for _ in range(total)]
    tenants = ["acme" if i % 2 == 0 else "zeta" for i in range(total)]
    c0 = serve_counts()
    tickets = []
    snapshot = None
    for i, ((a, b), tenant) in enumerate(zip(probs, tenants)):
        tickets.append(q.submit("posv", a, b, tenant=tenant))
        if i == batch - 1:
            # first window just closed by B-fill: its program is the
            # steady state — everything after must be ZERO retraces
            snapshot = qcache.snapshot_traces()
    q.drain()
    c1 = serve_counts()
    windows = c1["queue_windows"] - c0["queue_windows"]
    if windows > -(-total // batch):
        failures.append(
            f"stream phase: {total} requests dispatched {windows:.0f} "
            f"windows > ceil(N/B) = {-(-total // batch)} — windows are "
            "fragmenting")
    if c1["queue_dispatched"] - c0["queue_dispatched"] != total:
        failures.append("stream phase: dispatched count != submitted count")
    try:
        qcache.assert_steady(snapshot)
    except AssertionError as e:
        failures.append(f"stream phase: steady-state retrace: {e}")
    if any(not t.done() for t in tickets):
        failures.append("stream phase: a ticket never resolved")

    # bitwise parity vs one-at-a-time dispatch through a fresh Router
    # (the service layer is host-side scheduling ONLY)
    ref = Router(bins=(n,), hbm_budget=1 << 30, cache=ExecutableCache())
    bitwise = all(
        np.array_equal(np.asarray(t.result()),
                       np.asarray(ref.solve("posv", a, b, tenant=tn)))
        for t, (a, b), tn in zip(tickets, probs, tenants))
    if not bitwise:
        failures.append("stream phase: queued solutions are not bitwise-"
                        "equal to one-at-a-time Router dispatch")

    # no tenant's ledger ever exceeded its budget
    for tenant in ("acme", "zeta"):
        acct = q.ledger.account(tenant)
        if not 0 < acct.peak <= acct.budget:
            failures.append(f"stream phase: tenant {tenant} peak "
                            f"{acct.peak} outside (0, budget]")

    # (b) oversubscribe ONE window (12 pending, B=8) and dequeue by DRR
    q.max_batch = 16  # let the window fill past the dispatch size...
    over = [(_spd(rng, n), jnp.asarray(rng.standard_normal((n,))),
             "acme" if i % 2 == 0 else "zeta") for i in range(12)]
    otk = [q.submit("posv", a, b, tenant=t) for a, b, t in over]
    q.max_batch = 8   # ...then close it at B=8: 12 pending, 8 slots
    clk.advance(0.01)
    q.pump()          # the contended close: 8 of 12 dequeue by DRR
    clk.advance(0.01)
    q.pump()          # the leftover window's fresh deadline expires
    first = q.dispatch_log[-2]  # the contended close
    sel = first["tickets"]
    if len(sel) != 8:
        failures.append(f"DRR phase: contended close selected {len(sel)} "
                        "!= 8")
    by_tenant = {t: sum(1 for _s, tt in sel if tt == t)
                 for t in ("acme", "zeta")}
    # starvation freedom: both pending tenants appear in the close
    if min(by_tenant.values()) < 1:
        failures.append(f"DRR phase: a pending tenant was starved out of "
                        f"the close ({by_tenant})")
    # one-max-weight-round fairness: with weights 2:1 over 8 slots the
    # fair split is (16/3, 8/3); within one round means zeta >= 2 and
    # acme >= 4
    if by_tenant["acme"] < 4 or by_tenant["zeta"] < 2:
        failures.append(f"DRR phase: selection {by_tenant} further than "
                        "one max-weight round from the 2:1 weight ratio")
    # FIFO within tenant, across the whole oversubscribed dispatch order
    served_order = [s for entry in q.dispatch_log[-2:]
                    for s in entry["tickets"]]
    for tenant in ("acme", "zeta"):
        seqs = [s for s, tt in served_order if tt == tenant]
        if seqs != sorted(seqs):
            failures.append(f"DRR phase: FIFO broken within {tenant}: "
                            f"{seqs}")
    if any(not t.done() for t in otk):
        failures.append("DRR phase: leftover tickets never dispatched")
    q.close()
    return {"requests": total, "windows": windows, "bitwise": bitwise,
            "drr_split": by_tenant}


def run_budget_phase(failures: list) -> dict:
    """(c): per-tenant budget rejection + headroom restoration."""
    import jax.numpy as jnp
    import numpy as np

    from ..types import SlateError
    from . import trace as serve_trace
    from .cache import ExecutableCache
    from .metrics import serve_counts
    from .queue import BatchQueue, ManualClock
    from .router import Router

    rng = np.random.default_rng(23)
    n = 32
    clk = ManualClock()
    router = Router(bins=(n,), hbm_budget=1 << 30, cache=ExecutableCache())
    # cost of one binned f64 request: 3.5 * 32 * 32 * 8 = 28_672 bytes
    # -> a 100 kB budget admits exactly 3 in flight
    q = BatchQueue(router, max_batch=8, window_s=0.005, clock=clk,
                   budgets={"burst": 100_000}, name="smoke_budget")
    c0 = serve_counts()
    t0 = len(serve_trace.finished_traces())
    accepted, rejected = 0, 0
    for _ in range(5):
        a, b = _spd(rng, n), jnp.asarray(rng.standard_normal((n,)))
        try:
            q.submit("posv", a, b, tenant="burst")
            accepted += 1
        except SlateError:
            rejected += 1
    # an unaffected tenant keeps its default (device-sized) budget
    q.submit("posv", _spd(rng, n),
             jnp.asarray(rng.standard_normal((n,))), tenant="calm")
    c1 = serve_counts()
    if (accepted, rejected) != (3, 2):
        failures.append(f"budget phase: expected 3 accepts + 2 rejects at "
                        f"a 100kB budget, got {accepted}+{rejected}")
    if c1["queue_budget_rejects"] - c0["queue_budget_rejects"] != rejected:
        failures.append("budget phase: serve.queue_budget_rejects did not "
                        "count the refusals")
    rej_traces = [t for t in serve_trace.finished_traces()[t0:]
                  if t.outcome == "reject_budget"]
    if len(rej_traces) != rejected:
        failures.append(f"budget phase: {rejected} refusals produced "
                        f"{len(rej_traces)} reject_budget terminals")
    # a submit past the bin vocabulary is the OTHER reject taxon
    try:
        q.submit("posv", _spd(rng, 64),
                 jnp.asarray(rng.standard_normal((64,))), tenant="burst")
        failures.append("budget phase: an over-bin submit was admitted")
    except SlateError:
        pass
    clk.advance(0.01)
    q.pump()
    if q.ledger.account("burst").reserved != 0:
        failures.append("budget phase: drained windows did not restore "
                        "the tenant's headroom")
    q.close()
    return {"accepted": accepted, "rejected": rejected}


def run_memo_phase(failures: list) -> dict:
    """(d): the admission memo computes each MemoryModel key once over a
    steady-state 100-request stream (across Router instances)."""
    from .metrics import serve_counts
    from .router import Router

    # a budget value no other phase uses -> a FRESH process-global key
    budget = 987_654_321
    c0 = serve_counts()
    r1 = Router(bins=(32,), hbm_budget=budget)
    r2 = Router(bins=(32,), hbm_budget=budget)
    for _ in range(50):
        r1.admit("posv", 32)
        r2.admit("posv", 32)
    computes = serve_counts()["max_n_computes"] - c0["max_n_computes"]
    if computes != 1:
        failures.append(
            f"memo phase: 100 admissions across 2 routers evaluated the "
            f"MemoryModel closed form {computes:.0f} times (want exactly "
            "1 per (op, nb, grid, dtype, budget) key)")
    return {"computes": computes}


def run_controller_phase(failures: list) -> dict:
    """(e): a seeded latency spike trips the SLA control loop exactly
    once — hysteresis + cooldown prove it cannot flap."""
    from ..obs import REGISTRY, live as obs_live
    from .cache import ExecutableCache
    from .controller import ServiceController
    from .metrics import serve_counts
    from .queue import BatchQueue, ManualClock
    from .router import Router

    router = Router(bins=(32,), hbm_budget=1 << 30,
                    cache=ExecutableCache())
    q = BatchQueue(router, max_batch=8, window_s=0.005,
                   clock=ManualClock(), name="smoke_ctrl")
    # failure latch deliberately out of reach: the earlier phases SEEDED
    # reject outcomes into the global SLA surface, and this phase is
    # about the latency latch alone
    ctrl = ServiceController(q, slo_p95_s=0.25, arm=2, cooldown=2,
                             failure_rate_hi=0.9, failure_rate_lo=0.0)
    base = (q.max_batch, q.window_s)
    c0 = serve_counts()
    # the spike: enough 2 s observations to own the pooled p95
    for _ in range(32):
        REGISTRY.observe("serve.latency_s", 2.0, op="posv",
                         klass="friendly", outcome="served")
    if ctrl.signals()["p95_s"] < 1.0:
        failures.append("controller phase: seeded spike did not surface "
                        "in the p95 signal")
    acted = []
    for _ in range(6):  # sustained square-wave input
        acted += ctrl.step()
    trips = serve_counts()["controller_actuations"] - \
        c0["controller_actuations"]
    if trips != 1:
        failures.append(f"controller phase: sustained spike produced "
                        f"{trips:.0f} actuations (hysteresis should latch "
                        "after exactly 1)")
    if not acted or acted[0]["action"] != "shrink_window":
        failures.append(f"controller phase: expected a shrink_window "
                        f"actuation, got {[a['action'] for a in acted]}")
    if (q.max_batch, q.window_s) == base or q.window_s >= base[1]:
        failures.append("controller phase: the actuation did not move "
                        "the (B, T) window knobs")
    if not any(e["kind"] == "controller"
               for e in obs_live.BUS.events()):
        failures.append("controller phase: no controller event on the "
                        "telemetry bus")
    q.close()
    return {"trips": trips,
            "actions": [a["action"] for a in acted]}


def run_packed_phase(failures: list) -> dict:
    """(f): a ragged posv window in packed mode runs as ONE
    block-diagonal program."""
    import jax.numpy as jnp
    import numpy as np

    from ..linalg.chol import posv_array
    from . import trace as serve_trace
    from .cache import ExecutableCache
    from .metrics import serve_counts
    from .queue import BatchQueue, ManualClock
    from .router import Router

    rng = np.random.default_rng(29)
    clk = ManualClock()
    router = Router(bins=(32,), hbm_budget=1 << 30,
                    cache=ExecutableCache())
    q = BatchQueue(router, max_batch=8, window_s=0.005, clock=clk,
                   dispatch="packed", name="smoke_packed")
    sizes = (20, 28, 32)
    probs = [(_spd(rng, sz), jnp.asarray(rng.standard_normal((sz, 1))))
             for sz in sizes]
    c0 = serve_counts()
    t0 = len(serve_trace.finished_traces())
    tks = [q.submit("posv", a, b) for a, b in probs]
    clk.advance(0.01)
    q.pump()
    c1 = serve_counts()
    if c1["queue_packed_dispatches"] - c0["queue_packed_dispatches"] != 1:
        failures.append("packed phase: 3 ragged requests did not dispatch "
                        "as ONE packed program")
    ok = True
    for tk, (a, b) in zip(tks, probs):
        ref, _f, info = posv_array(a, b)
        if int(info) != 0 or not np.allclose(
                np.asarray(tk.result()), np.asarray(ref),
                rtol=1e-9, atol=1e-9):
            ok = False
    if not ok:
        failures.append("packed phase: unpacked solutions drifted from "
                        "the solo kernel past factorization accuracy")
    outcomes = [t.outcome for t in serve_trace.finished_traces()[t0:]]
    if outcomes != ["served"] * len(sizes):
        failures.append(f"packed phase: outcomes {outcomes} != all served")
    q.close()
    return {"packed_ok": ok}


def run_smoke(out_dir: str) -> int:
    import jax

    jax.config.update("jax_enable_x64", True)  # f64 serving classes

    from .. import obs
    # import the bus up front: phase (e) asserts the controller event
    # reaches it (producers probe sys.modules, so it must be loaded)
    from ..obs import live as _obs_live  # noqa: F401
    from ..obs import report
    from . import metrics as serve_metrics
    from .cache import executable_cache

    obs.reset()
    obs.enable()
    serve_metrics.reset()
    executable_cache.clear()
    failures: list = []

    stream = run_stream_phase(failures)
    budget = run_budget_phase(failures)
    memo = run_memo_phase(failures)
    ctrl = run_controller_phase(failures)
    packed = run_packed_phase(failures)

    os.makedirs(out_dir, exist_ok=True)
    rep_path = os.path.join(out_dir, "serve_queue.report.json")
    # every value below is deterministic under the ManualClock workload
    # (no *_runtime_* keys needed); the wall-clock latency quantiles the
    # serve section carries are the CI gate's --ignore '*latency*_s'
    report.write_report(
        rep_path, name="serve_queue",
        config={"n": 32, "batch": 8, "window_s": 0.005,
                "driver": "batch_queue_meshless", "clock": "manual"},
        values={
            "serve.queue_stream_requests": float(stream["requests"]),
            "serve.queue_stream_windows": float(stream["windows"]),
            "serve.queue_stream_bitwise_ok": float(stream["bitwise"]),
            "serve.queue_drr_acme": float(stream["drr_split"]["acme"]),
            "serve.queue_drr_zeta": float(stream["drr_split"]["zeta"]),
            "serve.queue_budget_accepts": float(budget["accepted"]),
            "serve.queue_budget_rejections": float(budget["rejected"]),
            "serve.queue_memo_computes": float(memo["computes"]),
            "serve.queue_controller_trips": float(ctrl["trips"]),
            "serve.queue_packed_ok": float(packed["packed_ok"]),
        })
    import json

    with open(rep_path) as f:
        rep = json.load(f)
    errs = report.validate_report(rep)
    if errs:
        failures.append(f"RunReport schema: {errs}")
    serve_sec = rep.get("serve") or {}
    if serve_sec.get("queue_submitted", 0) <= 0:
        failures.append("serve section missing queue counters — "
                        "obs.report is not folding serve.queue_* in")

    if failures:
        print(f"serve.queue_smoke: FAILED with {len(failures)} problem(s):")
        for msg in failures:
            print(f"  FAIL {msg}")
        return 1
    print(f"serve.queue_smoke: OK — {stream['requests']} requests in "
          f"{stream['windows']:.0f} windows (0 retraces, bitwise parity), "
          f"DRR split {stream['drr_split']}, "
          f"{budget['rejected']} budget reject(s), 1 memo compute, "
          f"{ctrl['trips']:.0f} controller trip, packed dispatch OK, "
          f"report {rep_path}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m slate_tpu.serve.queue_smoke")
    ap.add_argument("--out", default=os.path.join("artifacts", "serve"))
    args = ap.parse_args(argv)
    return run_smoke(args.out)


if __name__ == "__main__":
    sys.exit(main())
