"""Autotuner: sweep schedule knobs per cache key, persist the winners.

``python -m slate_tpu.serve.tune`` runs the flight recorder (obs/flight)
over every (BcastImpl, Lookahead depth, nb) combination for the swept
ops and picks each key's winner by MEASURED step-level schedule metrics
— ``sched.critical_path_s`` as the primary objective (the quantity a
request's latency is made of), ``sched.exposed_comm_s`` as the
tie-break (less exposed communication generalizes better to real ICI
than a CPU-harness wall-clock tie).  For gemm the stationary variant
(GemmA vs GemmC) is additionally timed at a thin-output serving shape,
where the |B|-replication schedule can undercut the k-loop.

The winning table is written as the versioned committed artifact
``artifacts/serve/tuned.json`` (serve/table.py schema); the request
path resolves unset Options through it (explicit > context > env >
tuned > auto).

Usage::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m slate_tpu.serve.tune [--out artifacts/serve/tuned.json]
        [--ops summa,potrf,getrf_nopiv] [--n 96] [--quick]
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict, List, Optional, Tuple

from .table import DEFAULT_TABLE_PATH, entry_key, write_table

SWEEP_IMPLS = ("doubling", "ring", "psum")
SWEEP_DEPTHS = {"summa": (0, 1, 2), "potrf": (0, 1), "getrf_nopiv": (0, 1)}
SWEEP_NB = (8, 16)


def _objective(values: Dict[str, float]) -> Tuple[float, float]:
    return (values["sched.critical_path_s"], values["sched.exposed_comm_s"])


def sweep_op(op: str, n: int, mesh, nbs=SWEEP_NB, impls=SWEEP_IMPLS,
             depths: Optional[Tuple[int, ...]] = None,
             log=print) -> Tuple[Dict, List[Dict]]:
    """All (nb, impl, depth) flights for one op; returns (winner entry,
    full sweep log).  Each flight is a complete step-dispatch run: the
    measured per-(impl, depth) overlap/critical-path numbers PRs 7's
    recorder gates are exactly the tuner's objective."""
    from ..obs.flight import run_flight

    depths = depths if depths is not None else SWEEP_DEPTHS[op]
    swept: List[Dict] = []
    best = None
    for nb in nbs:
        for impl in impls:
            for depth in depths:
                t0 = time.time()
                rep = run_flight(op, n=n, nb=nb, depth=depth,
                                 bcast_impl=impl, mesh=mesh)
                row = {
                    "nb": nb, "bcast_impl": impl, "lookahead": depth,
                    "critical_path_s": rep["values"]["sched.critical_path_s"],
                    "overlap_eff": rep["values"]["sched.overlap_eff"],
                    "exposed_comm_s": rep["values"]["sched.exposed_comm_s"],
                    "resid": rep["values"]["resid"],
                    "sweep_s": round(time.time() - t0, 2),
                }
                swept.append(row)
                log(f"  {op} nb={nb} impl={impl:>8} depth={depth}: "
                    f"crit={row['critical_path_s'] * 1e3:8.2f} ms "
                    f"overlap={row['overlap_eff']:.3f} "
                    f"exposed={row['exposed_comm_s'] * 1e3:8.2f} ms")
                if best is None or _objective(rep["values"]) < _objective(
                        {"sched.critical_path_s": best["critical_path_s"],
                         "sched.exposed_comm_s": best["exposed_comm_s"]}):
                    best = row
    entry = {
        "bcast_impl": best["bcast_impl"],
        "lookahead": int(best["lookahead"]),
        "nb": int(best["nb"]),
        "objective": {
            "critical_path_s": best["critical_path_s"],
            "overlap_eff": best["overlap_eff"],
            "exposed_comm_s": best["exposed_comm_s"],
        },
    }
    return entry, swept


def time_gemm_method(n: int, nb: int, mesh, reps: int = 3) -> Dict[str, float]:
    """Stationary-variant timing at the thin-output serving shape
    (n x n times n x 2nb): GemmA replicates the thin B and reduces C;
    GemmC loops broadcasts of A panels.  The flight recorder cannot
    arbitrate this (GemmA has no k-loop to record), so the variant
    tunable is decided by warm wall-clock on the serving mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..parallel.dist import from_dense
    from ..parallel.summa import gemm_summa
    from ..types import MethodGemm

    rng = np.random.default_rng(0)
    ad = from_dense(jnp.asarray(rng.standard_normal((n, n))), mesh, nb)
    bd = from_dense(jnp.asarray(rng.standard_normal((n, 2 * nb))), mesh, nb)
    out = {}
    for method in (MethodGemm.GemmA, MethodGemm.GemmC):
        run = lambda: gemm_summa(1.0, ad, bd, method=method)
        jax.block_until_ready(run().tiles)  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(run().tiles)
        out[method.value] = (time.perf_counter() - t0) / reps
    return out


def run_tune(out: str, ops: List[str], n: int, quick: bool = False,
             log=print) -> int:
    import jax

    devs = jax.devices("cpu")
    if len(devs) < 8:
        log("serve.tune: need 8 CPU devices — set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
        return 2
    from ..parallel import make_mesh
    from ..parallel.mesh import mesh_shape

    mesh = make_mesh(2, 4, devices=devs[:8])
    grid = mesh_shape(mesh)
    nbs = (SWEEP_NB[0],) if quick else SWEEP_NB
    entries: Dict[str, Dict] = {}
    sweeps: Dict[str, List[Dict]] = {}
    for op in ops:
        log(f"serve.tune: sweeping {op} (n={n}, grid={grid[0]}x{grid[1]})")
        entry, swept = sweep_op(op, n, mesh, nbs=nbs, log=log)
        if op == "summa":
            times = time_gemm_method(n, entry["nb"], mesh)
            entry["method"] = min(times, key=times.get)
            entry["method_runtime_s"] = {k: round(v, 6)
                                         for k, v in times.items()}
            key_op = "gemm"
        else:
            key_op = {"potrf": "potrf", "getrf_nopiv": "gesv"}.get(op, op)
        key = entry_key(key_op, n, "float64", grid)
        entries[key] = entry
        sweeps[key] = swept
        # factor winners serve the solve verbs built on them too
        if op == "potrf":
            entries[entry_key("posv", n, "float64", grid)] = dict(entry)
    path = write_table(out, entries, config={
        "n": n, "grid": f"{grid[0]}x{grid[1]}", "ops": ops,
        "impls": list(SWEEP_IMPLS), "nbs": list(nbs), "quick": quick,
        "objective": "min sched.critical_path_s, tie-break "
                     "sched.exposed_comm_s (obs.flight measured)",
    })
    log(f"serve.tune: wrote {len(entries)} entries to {path}")
    for key, entry in sorted(entries.items()):
        log(f"  {key}: impl={entry['bcast_impl']} depth={entry['lookahead']} "
            f"nb={entry['nb']}" + (f" method={entry['method']}"
                                   if "method" in entry else ""))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m slate_tpu.serve.tune",
                                 description=__doc__)
    ap.add_argument("--out", default=DEFAULT_TABLE_PATH)
    ap.add_argument("--ops", default="summa,potrf,getrf_nopiv",
                    help="comma-separated flight ops to sweep")
    ap.add_argument("--n", type=int, default=96)
    ap.add_argument("--quick", action="store_true",
                    help="single nb, for fast re-tunes")
    args = ap.parse_args(argv)
    return run_tune(args.out, [o for o in args.ops.split(",") if o],
                    args.n, args.quick)


if __name__ == "__main__":
    sys.exit(main())
