"""serve.* counters: the serving layer's observability surface.

Mirrors the ft.*/ir.*/mem.*/num.* counter sections (ft/policy.py,
linalg/refine.py): a plain always-on dict that ``obs.report.make_report``
folds into every RunReport as the ``serve`` section, so cache-hygiene
regressions (retraces creeping back into the steady state, the batched
path silently falling back to one-at-a-time dispatch) gate in CI exactly
like perf regressions.  ``*_runtime_*``-infixed report VALUES are the
machine-dependent keys the CI gate ``--ignore``s; everything here is a
deterministic count under a fixed workload and gates tight.
"""

from __future__ import annotations

import re
from typing import Dict


def _sanitize_key(name: str) -> str:
    """Report/Prometheus-safe metric-name fragment (tag values like
    dtype strings can carry characters the flat key space cannot)."""
    return re.sub(r"[^A-Za-z0-9_]", "_", name)

_ZEROS: Dict[str, float] = {
    # request router
    "requests": 0.0,           # single problems entering the serve layer
    "batches": 0.0,            # compiled batch programs dispatched
    "batched_solves": 0.0,     # problems solved through batch programs
    "packed_problems": 0.0,    # ragged problems packed block-diagonally
    "admission_rejects": 0.0,  # requests over the HBM admission bound
    #   (also: preempted-and-unresumable requests rejected instead of
    #   being served NaNs — the router's graceful-degradation endpoint)
    "retries": 0.0,            # transient FtError -> one Recompute retry
    "resumes": 0.0,            # preempted request resumed from checkpoint
    "class_friendly": 0.0,     # condest-keyed cheap-path dispatches
    "class_hostile": 0.0,      # condest-keyed GMRES-IR dispatches
    # executable cache
    "cache_hits": 0.0,         # key already held a compiled program
    "cache_misses": 0.0,       # key built (and traced) a new program
    "traces": 0.0,             # actual tracer executions of cached programs
    "warmups": 0.0,            # programs compiled ahead of traffic
    # schedule-table resolution
    "tuned_resolutions": 0.0,  # options filled from the tuned table
    # stationary-operator caches (the serving twins)
    "condest_cache_hits": 0.0,   # condest served from a factor's memo
    "ozaki_presplits": 0.0,      # digit-plane splits computed
    "ozaki_presplit_hits": 0.0,  # splits served from the operand cache
    # batch-window queue + budgets + control loop (ISSUE 19)
    "queue_submitted": 0.0,      # requests admitted into the batch queue
    "queue_windows": 0.0,        # batch windows closed (dispatched)
    "queue_window_full": 0.0,    # windows closed by B-fill
    "queue_window_expired": 0.0, # windows closed by T-expiry (or drain)
    "queue_dispatched": 0.0,     # requests dispatched out of closed windows
    "queue_packed_dispatches": 0.0,  # windows dispatched block-diagonally
    "queue_budget_rejects": 0.0, # submits refused by a tenant's HBM budget
    "queue_pump_errors": 0.0,    # non-settling exceptions the service
    #   worker survived (anything past the SlateError batch-abort path)
    "controller_actuations": 0.0,  # SLA control-loop knob movements
    "max_n_computes": 0.0,       # MemoryModel closed-form evaluations
    #   (admission memo misses — a steady-state request stream must
    #   compute each (op, nb, grid, dtype, budget) key exactly once)
}

_COUNTS: Dict[str, float] = dict(_ZEROS)


def serve_count(name: str, n: float = 1.0) -> None:
    """Bump one serve.* counter (and its obs-registry twin when the obs
    layer is enabled, so counts also land tagged in metric snapshots)."""
    if name not in _COUNTS:
        raise KeyError(f"unknown serve counter {name!r}")
    _COUNTS[name] += n
    from ..obs import REGISTRY, enabled

    if enabled():
        REGISTRY.counter_add(f"serve.{name}", n)


def serve_counts() -> Dict[str, float]:
    """Plain snapshot of the flat counters (no SLA merge) — what the
    scheduler tests and the queue smoke diff across phases."""
    return dict(_COUNTS)


def serve_counter_values() -> Dict[str, float]:
    """Snapshot for RunReports (obs.report.make_report's ``serve``
    section): the flat counters plus the request-level SLA reduction
    (ISSUE 14) — per-(op, class) latency quantiles/counts and outcome
    attribution totals/rates from serve/trace.py.  An idle run (no
    request terminated) contributes nothing beyond the counter zeros,
    so the all-zero section keeps staying out of the report-gate
    comparison surface."""
    out = dict(_COUNTS)
    from . import trace as _trace

    out.update(_trace.sla_values())
    return out


def reset() -> None:
    _COUNTS.clear()
    _COUNTS.update(_ZEROS)
    from . import trace as _trace

    _trace.reset()
