"""Metrics export surface: Prometheus-style text + JSON snapshots of the
serving registry (ISSUE 14; canonical implementation moved to
obs/live.py in ISSUE 17).

This module keeps the historical import path and CLI, delegating to
``slate_tpu.obs.live`` — there is ONE Prometheus formatter and ONE
source for family naming, shared by this offline/embedding surface and
the live scrape endpoint (``python -m slate_tpu.obs.live``).

The library half is ``stats_snapshot()`` / ``prometheus_text()`` — a
server embedding the Router exposes its scrape endpoint by returning
``prometheus_text()`` from a handler; counters map to Prometheus
counters, gauges to gauges, and histograms to summary-style series with
``quantile="0.5|0.95|0.99"`` labels from the first-class reservoir
quantiles (obs/metrics.py).

The CLI::

    python -m slate_tpu.serve.stats [REPORT.json] [--json OUT] [--demo]

- with a RunReport argument, formats THAT report's ``serve`` section +
  metric series (the offline view of a committed artifact — CI runs it
  over the fresh SLA report as a format smoke);
- without one, snapshots the LIVE registry of this process (``--demo``
  first drives a tiny batched workload through the Router so a bare
  invocation shows a populated surface);
- ``--json`` additionally writes the machine-readable snapshot.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..obs.live import (  # noqa: F401
    _PREFIX,
    _SCRAPE_PREFIXES,
    _fmt_tags,
    prometheus_text,
    sanitize_key as _sanitize_key,
    snapshot_from_report,
    stats_snapshot,
    validate_prometheus_text,
)


def _run_demo() -> None:
    """Tiny meshless Router workload so a bare CLI run shows a populated
    surface (small n — the point is the export format, not the solve)."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from .. import obs
    from .router import Router

    obs.enable()
    rng = np.random.default_rng(0)
    router = Router(bins=(32,), hbm_budget=1 << 30)
    n = 32
    for seed in range(3):
        g = rng.standard_normal((n, n))
        a = jnp.asarray(g @ g.T / n + 2 * np.eye(n))
        b = jnp.asarray(rng.standard_normal((n, 2)))
        router.solve("posv", a, b)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m slate_tpu.serve.stats", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("report", nargs="?",
                    help="RunReport JSON to format instead of the live "
                         "registry")
    ap.add_argument("--json", metavar="PATH",
                    help="also write the JSON snapshot to PATH")
    ap.add_argument("--demo", action="store_true",
                    help="drive a tiny Router workload first (live mode)")
    args = ap.parse_args(argv)

    if args.report:
        try:
            with open(args.report) as f:
                rep = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"serve.stats: cannot read report: {e}", file=sys.stderr)
            return 2
        snap = snapshot_from_report(rep)
    else:
        if args.demo:
            _run_demo()
        snap = stats_snapshot()

    text = prometheus_text(snap)
    sys.stdout.write(text)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(snap, f, indent=1)
        print(f"# snapshot written to {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
