"""Metrics export surface: Prometheus-style text + JSON snapshots of the
serving registry (ISSUE 14).

The library half is ``stats_snapshot()`` / ``prometheus_text()`` — a
server embedding the Router exposes its scrape endpoint by returning
``prometheus_text()`` from a handler; counters map to Prometheus
counters, gauges to gauges, and histograms to summary-style series with
``quantile="0.5|0.95|0.99"`` labels from the first-class reservoir
quantiles (obs/metrics.py).

The CLI::

    python -m slate_tpu.serve.stats [REPORT.json] [--json OUT] [--demo]

- with a RunReport argument, formats THAT report's ``serve`` section +
  metric series (the offline view of a committed artifact — CI runs it
  over the fresh SLA report as a format smoke);
- without one, snapshots the LIVE registry of this process (``--demo``
  first drives a tiny batched workload through the Router so a bare
  invocation shows a populated surface);
- ``--json`` additionally writes the machine-readable snapshot.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from .metrics import _sanitize_key, serve_counter_values

_PREFIX = "slate_tpu_serve"


# metric-name prefixes one scrape surfaces (ISSUE 15): the serving
# counters/latencies plus the schedule (sched.*), accuracy-health
# (num.*), and refinement-trajectory (ir.*) families — latency,
# schedule, and health together in one exposition
_SCRAPE_PREFIXES = ("serve.", "sched.", "num.", "ir.")


def stats_snapshot() -> dict:
    """JSON-able snapshot of the live serving surface: the serve.*
    counter section (with the SLA reduction merged in), the exact
    outcome-attribution totals, the num.* accuracy-health totals, and
    every ``serve.``/``sched.``/``num.``/``ir.``-named metric series in
    the shared registry."""
    from ..obs import REGISTRY
    from ..obs import numerics as _numerics
    from . import trace as _trace

    snap = REGISTRY.snapshot()
    scrape_metrics = {
        kind: [e for e in entries
               if str(e.get("name", "")).startswith(_SCRAPE_PREFIXES)]
        for kind, entries in snap.items()
    }
    # the num section (the RunReport twin): all-zero (nothing monitored
    # this process) stays out, exactly like the report surface
    num = _numerics.num_counter_values()
    return {
        "serve": serve_counter_values(),
        "sla": _trace.sla_values(),
        "num": (num if any(num.values()) else {}),
        "finished_requests": len(_trace.finished_traces()),
        "metrics": scrape_metrics,
    }


def _fmt_tags(tags: Dict[str, str], extra: Optional[Dict[str, str]] = None
              ) -> str:
    items = dict(tags or {})
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{_sanitize_key(k)}="{v}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


def prometheus_text(snapshot: Optional[dict] = None) -> str:
    """Prometheus exposition-format text of a ``stats_snapshot()``
    (taken live when not given).  Rows are grouped per metric NAME with
    exactly one ``# TYPE`` header each — multiple tag sets of one
    metric (the (op, klass, outcome) latency series) are one metric
    family to Prometheus, and a repeated TYPE line is a parse error."""
    snap = snapshot if snapshot is not None else stats_snapshot()
    # family name -> (kind, [sample rows]); insertion-ordered
    families: Dict[str, tuple] = {}

    def emit(name: str, kind: str, rows) -> None:
        fam = families.setdefault(name, (kind, []))
        fam[1].extend(rows)

    # flat serve counters (+ merged SLA keys): the RunReport serve section
    for key, val in sorted((snap.get("serve") or {}).items()):
        name = f"{_PREFIX}_{_sanitize_key(key)}"
        emit(name, "gauge" if "latency" in key or "rate" in key
             else "counter", [f"{name} {val:.10g}"])
    # flat num.* accuracy-health totals (ISSUE 15): worst-case gauges are
    # gauges, event totals counters — the RunReport num section's scrape
    for key, val in sorted((snap.get("num") or {}).items()):
        name = f"slate_tpu_num_{_sanitize_key(key)}"
        kind = ("gauge" if any(t in key for t in ("_max", "_min", "margin",
                                                  "cond", "_s"))
                else "counter")
        emit(name, kind, [f"{name} {val:.10g}"])
    # flat sched.* keys (a formatted FlightReport's values — the offline
    # schedule surface; live registries carry sched series below instead)
    for key, val in sorted((snap.get("sched") or {}).items()):
        name = f"slate_tpu_{_sanitize_key(key)}"
        emit(name, "gauge", [f"{name} {val:.10g}"])
    # registry series (tagged counters/gauges/histograms)
    m = snap.get("metrics") or {}
    for e in m.get("counters", []):
        name = f"slate_tpu_{_sanitize_key(e['name'])}_total"
        emit(name, "counter",
             [f"{name}{_fmt_tags(e.get('tags'))} {e['value']:.10g}"])
    for e in m.get("gauges", []):
        name = f"slate_tpu_{_sanitize_key(e['name'])}"
        emit(name, "gauge",
             [f"{name}{_fmt_tags(e.get('tags'))} {e['value']:.10g}"])
    for e in m.get("histograms", []):
        name = f"slate_tpu_{_sanitize_key(e['name'])}"
        rows = [
            f"{name}_count{_fmt_tags(e.get('tags'))} {e['count']}",
            f"{name}_sum{_fmt_tags(e.get('tags'))} {e['sum']:.10g}",
        ]
        for label, qkey in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            qv = e.get(qkey)
            if qv is not None:
                rows.append(
                    f"{name}{_fmt_tags(e.get('tags'), {'quantile': label})}"
                    f" {qv:.10g}")
        emit(name, "summary", rows)
    lines: List[str] = []
    for name, (kind, rows) in families.items():
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(rows)
    return "\n".join(lines) + "\n"


def snapshot_from_report(rep: dict) -> dict:
    """Rebuild the stats surface from a committed RunReport or
    FlightReport (the offline twin of the live snapshot): the serve
    section plus the num section and any ``num.*``/``sched.*`` headline
    values (numwatch / flight artifacts format through the same
    exposition — ISSUE 15)."""
    metrics = rep.get("metrics") or {}
    values = rep.get("values") or {}
    num = dict(rep.get("num") or {})
    num.update({k[len("num."):]: v for k, v in values.items()
                if isinstance(v, (int, float)) and k.startswith("num.")})
    sched = {k: v for k, v in values.items()
             if isinstance(v, (int, float)) and k.startswith("sched.")}
    return {
        "serve": dict(rep.get("serve") or {}),
        "sla": {k: v for k, v in (rep.get("serve") or {}).items()
                if k.startswith(("latency_", "outcome_"))},
        "num": num,
        "sched": sched,
        "finished_requests": None,
        "metrics": {
            kind: [e for e in metrics.get(kind, [])
                   if str(e.get("name", "")).startswith(_SCRAPE_PREFIXES)]
            for kind in ("counters", "gauges", "histograms")
        },
    }


def _run_demo() -> None:
    """Tiny meshless Router workload so a bare CLI run shows a populated
    surface (small n — the point is the export format, not the solve)."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from .. import obs
    from .router import Router

    obs.enable()
    rng = np.random.default_rng(0)
    router = Router(bins=(32,), hbm_budget=1 << 30)
    n = 32
    for seed in range(3):
        g = rng.standard_normal((n, n))
        a = jnp.asarray(g @ g.T / n + 2 * np.eye(n))
        b = jnp.asarray(rng.standard_normal((n, 2)))
        router.solve("posv", a, b)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m slate_tpu.serve.stats", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("report", nargs="?",
                    help="RunReport JSON to format instead of the live "
                         "registry")
    ap.add_argument("--json", metavar="PATH",
                    help="also write the JSON snapshot to PATH")
    ap.add_argument("--demo", action="store_true",
                    help="drive a tiny Router workload first (live mode)")
    args = ap.parse_args(argv)

    if args.report:
        try:
            with open(args.report) as f:
                rep = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"serve.stats: cannot read report: {e}", file=sys.stderr)
            return 2
        snap = snapshot_from_report(rep)
    else:
        if args.demo:
            _run_demo()
        snap = stats_snapshot()

    text = prometheus_text(snap)
    sys.stdout.write(text)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(snap, f, indent=1)
        print(f"# snapshot written to {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
