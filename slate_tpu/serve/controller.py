"""ServiceController: the SLA control loop over the batch-window queue
(ISSUE 19 tentpole, part c).

PR 14 built the measurement half — per-(op, class) latency quantiles and
outcome rates reduced from live RequestTraces (``trace.sla_values``).
This module closes the loop: a periodic ``step()`` reads that SLA
surface plus the queue's own depth, and actuates the three serving
knobs the measurements are ABOUT:

- **(B, T) window shaping.**  Sustained queue depth over ``depth_hi``
  widens the window (bigger B, longer T — amortize the backlog into
  fewer, fuller programs); drained depth under ``depth_lo`` restores
  the baseline (stop taxing latency for throughput nobody needs).
- **Latency guard.**  p95 over the SLO shrinks T toward its floor —
  the window wait is the one latency term the service layer itself
  adds, so it is the first thing to give back.
- **Precision-tier entry point.**  A sustained failure-outcome tail
  (failed_info / reject_residual rates) escalates ``Router.tier_map``
  so friendly-classified operators ENTER at the robust pp+GMRES-IR
  tier (the Carson–Higham regime boundary is evidently misjudging this
  traffic); a clean tail releases back to the condest-keyed ladder.

Every latch is a **hysteresis** pair (trip threshold > release
threshold, arm streaks, cooldown ticks) so one noisy scrape cannot
flap a knob — an actuation requires ``arm`` consecutive out-of-band
observations and a quiet cooldown.  Every actuation counts
``serve.controller_actuations``, updates the ``serve.queue_window_*``
gauges, and publishes a ``controller`` event on the telemetry bus, so
a dashboard (or the queue smoke) can replay exactly when and why each
knob moved.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import obs
from ..obs import REGISTRY
from . import trace as rtrace
from .metrics import serve_count


class Hysteresis:
    """Two-threshold latch with arming streaks and a post-actuation
    cooldown.  ``observe(value)`` returns "trip" the tick the value has
    been >= ``hi`` for ``arm`` consecutive ticks (latch closed),
    "release" symmetrically at <= ``lo``, else None.  While the latch
    is closed further highs return None (no repeated actuation), and
    ``cooldown`` ticks must pass after any actuation before the next —
    the control loop cannot flap even on a square-wave input."""

    def __init__(self, hi: float, lo: float, arm: int = 2,
                 cooldown: int = 3) -> None:
        if lo > hi:
            raise ValueError(f"hysteresis lo {lo} > hi {hi}")
        self.hi = float(hi)
        self.lo = float(lo)
        self.arm = int(arm)
        self.cooldown = int(cooldown)
        self.tripped = False
        self._hi_streak = 0
        self._lo_streak = 0
        self._cool = 0

    def observe(self, value: float) -> Optional[str]:
        if value >= self.hi:
            self._hi_streak += 1
            self._lo_streak = 0
        elif value <= self.lo:
            self._lo_streak += 1
            self._hi_streak = 0
        else:
            self._hi_streak = 0
            self._lo_streak = 0
        if self._cool > 0:
            self._cool -= 1
            return None
        if not self.tripped and self._hi_streak >= self.arm:
            self.tripped = True
            self._cool = self.cooldown
            self._hi_streak = 0
            return "trip"
        if self.tripped and self._lo_streak >= self.arm:
            self.tripped = False
            self._cool = self.cooldown
            self._lo_streak = 0
            return "release"
        return None


class ServiceController:
    """The control loop.  Call ``step()`` periodically (the service
    worker thread does; tests and the smoke drive it directly)."""

    def __init__(self, queue, *,
                 slo_p95_s: float = 0.25,
                 depth_hi: Optional[float] = None,
                 depth_lo: Optional[float] = None,
                 failure_rate_hi: float = 0.05,
                 failure_rate_lo: float = 0.005,
                 arm: int = 2, cooldown: int = 3,
                 widen_factor: float = 2.0,
                 min_window_s: float = 0.001) -> None:
        self.queue = queue
        self.router = queue.router
        self.slo_p95_s = float(slo_p95_s)
        # baseline (B, T): what release restores
        self._base_batch = int(queue.max_batch)
        self._base_window_s = float(queue.window_s)
        self.widen_factor = float(widen_factor)
        self.min_window_s = float(min_window_s)
        dhi = depth_hi if depth_hi is not None else 2.0 * queue.max_batch
        dlo = depth_lo if depth_lo is not None else 0.5 * queue.max_batch
        self.depth_latch = Hysteresis(dhi, dlo, arm=arm, cooldown=cooldown)
        # latency is binary vs the SLO: hi = breach, lo = within 80%
        self.latency_latch = Hysteresis(
            self.slo_p95_s, 0.8 * self.slo_p95_s, arm=arm,
            cooldown=cooldown)
        self.failure_latch = Hysteresis(
            failure_rate_hi, failure_rate_lo, arm=arm, cooldown=cooldown)
        self.ticks = 0
        self.actuations: List[dict] = []

    # -- signal extraction -------------------------------------------------

    def signals(self) -> Dict[str, float]:
        """The three scalars the latches consume, reduced from the live
        SLA surface (worst-case across (op, class) cells — the SLO is a
        service promise, not a per-cell one) plus the queue's depth."""
        sla = rtrace.sla_values()
        p95 = max((v for k, v in sla.items()
                   if k.startswith("latency_p95_")), default=0.0)
        # every non-served outcome rate is failure tail (rejects and
        # failures alike — all of them are broken promises to a caller)
        fail = sum(v for k, v in sla.items()
                   if k.startswith("outcome_rate_")
                   and not k.startswith("outcome_rate_served"))
        return {"depth": float(self.queue.depth()), "p95_s": p95,
                "failure_rate": fail}

    # -- the loop ----------------------------------------------------------

    def step(self) -> List[dict]:
        """One control tick: observe, latch, actuate.  Returns the list
        of actuations this tick (usually empty)."""
        self.ticks += 1
        sig = self.signals()
        acted: List[dict] = []

        edge = self.depth_latch.observe(sig["depth"])
        if edge == "trip":
            acted.append(self._actuate(
                "widen_window", sig,
                batch=int(self._base_batch * self.widen_factor),
                window_s=self._base_window_s * self.widen_factor))
        elif edge == "release":
            acted.append(self._actuate(
                "restore_window", sig, batch=self._base_batch,
                window_s=self._base_window_s))

        edge = self.latency_latch.observe(sig["p95_s"])
        if edge == "trip":
            acted.append(self._actuate(
                "shrink_window", sig, batch=self.queue.max_batch,
                window_s=max(self.min_window_s,
                             self._base_window_s / self.widen_factor)))
        elif edge == "release":
            acted.append(self._actuate(
                "restore_window", sig, batch=self.queue.max_batch,
                window_s=self._base_window_s))

        edge = self.failure_latch.observe(sig["failure_rate"])
        if edge == "trip":
            acted.append(self._actuate("escalate_tier", sig,
                                       tier={"friendly": "hostile"}))
        elif edge == "release":
            acted.append(self._actuate("release_tier", sig, tier={}))
        return acted

    def _actuate(self, action: str, sig: Dict[str, float], *,
                 batch: Optional[int] = None,
                 window_s: Optional[float] = None,
                 tier: Optional[Dict[str, str]] = None) -> dict:
        if batch is not None:
            self.queue.max_batch = int(batch)
        if window_s is not None:
            self.queue.window_s = float(window_s)
        if tier is not None:
            self.router.tier_map = dict(tier)
        serve_count("controller_actuations")
        rec = {"tick": self.ticks, "action": action,
               "batch": self.queue.max_batch,
               "window_s": self.queue.window_s,
               "tier_map": dict(self.router.tier_map),
               "signals": dict(sig)}
        self.actuations.append(rec)
        if obs.enabled():
            REGISTRY.gauge_set("serve.queue_window_batch",
                               float(self.queue.max_batch),
                               queue=self.queue.name)
            REGISTRY.gauge_set("serve.queue_window_s",
                               float(self.queue.window_s),
                               queue=self.queue.name)
        self._publish(rec)
        return rec

    def _publish(self, rec: dict) -> None:
        import sys as _sys

        _live = _sys.modules.get(
            __package__.rsplit(".", 1)[0] + ".obs.live")
        if _live is not None:
            _live.publish("controller", rec)
