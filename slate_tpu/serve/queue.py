"""Batch-window admission queue: the scheduler between a socket and the
Router (ISSUE 19 tentpole).

PR 11 built the throughput mechanism — ONE compiled program factors a
stack of B same-shaped problems ~25x faster than B one-at-a-time
dispatches — but the Router is synchronous: a caller must already HOLD
B compatible requests to collect the win.  Under real concurrent
traffic nobody does; this module is the piece that manufactures those
batches from a stream of single requests:

- **Batch windows.**  ``submit`` bins each request by the Router-
  compatible window key ``(op, shape bin, nrhs, dtype, accuracy
  class)`` — the same identity the executable cache keys compiled
  programs on, derived through ``Router.effective_class`` so one
  window always lands in ONE stacked (or block-diagonally packed)
  program.  A window closes when it holds B requests (B-fill) or when
  T seconds pass (``pump`` observes the deadline), whichever first.
- **Deterministic clock.**  Every scheduling decision reads
  ``clock.now()`` — inject a ``ManualClock`` and B-fill vs T-expiry,
  FIFO order, DRR rounds and starvation bounds are all testable
  without wall time (tests/test_service_queue.py).
- **Per-tenant budgets + weighted deficit round robin.**  Submits
  reserve modeled HBM bytes against the tenant's ``BudgetLedger``
  account (``reject_budget`` when over — one tenant's n=16384 burst
  cannot OOM the device), and an oversubscribed window dequeues by
  weighted DRR over the PR 17 tenant dimension: each round grants
  every pending tenant ``weight`` worth of deficit, so any tenant's
  service lag is bounded by one max-weight round and a saturating
  adversary cannot starve anyone (FIFO holds within a tenant).
- **Observability.**  Depth / open windows / per-tenant deficit and
  budget headroom land as ``serve.queue_*`` gauges in the shared
  registry (the obs.live scrape surfaces them, plus ``/queue.json``),
  window closes publish ``queue`` events on the telemetry bus, and
  every admitted request carries its RequestTrace from SUBMIT (the
  latency SLA covers the window wait; the ``queue`` phase records it).

``stacked_body`` / ``packed_mesh_body`` expose the exact program bodies
a closed window dispatches — the contract-matrix cells
(``posv_batched_queue`` / ``posv_packed_queue`` in analysis/registry.py)
prove they are byte-identical to the service-off Router dispatch.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..obs import REGISTRY
from ..types import SlateError
from . import trace as rtrace
from .batch import bin_for, pack_block_diag, unpack_block_diag
from .budget import BudgetLedger, request_cost
from .cache import make_key
from .metrics import serve_count


# ---------------------------------------------------------------------------
# clocks
# ---------------------------------------------------------------------------


class ManualClock:
    """Deterministic injectable clock: tests and the queue smoke advance
    time explicitly, so every window close is a decision about NUMBERS,
    never about how fast the suite ran."""

    def __init__(self, t: float = 0.0) -> None:
        self._t = float(t)

    def now(self) -> float:
        return self._t

    def advance(self, dt: float) -> float:
        self._t += float(dt)
        return self._t


class MonotonicClock:
    """The wall-clock twin (``python -m slate_tpu.serve.service``)."""

    now = staticmethod(time.monotonic)


# ---------------------------------------------------------------------------
# tickets and windows
# ---------------------------------------------------------------------------

# process-wide ticket numbering: next() on a count() is atomic under
# the GIL, so concurrent submits on DIFFERENT queues (each under its
# own instance lock) still get unique seqs
_TICKET_SEQ = itertools.count(1)


class Ticket:
    """One submitted request's handle: resolves to the solution once its
    window dispatched (or to the dispatch error)."""

    __slots__ = ("seq", "op", "n", "bin", "nrhs", "tenant", "tenant_key",
                 "cost", "trace", "submitted_at", "state", "_result",
                 "_error")

    def __init__(self, seq, op, n, m, nrhs, tenant, tenant_key, cost,
                 trace, submitted_at) -> None:
        self.seq = seq
        self.op = op
        self.n = n
        self.bin = m
        self.nrhs = nrhs
        self.tenant = tenant
        self.tenant_key = tenant_key
        self.cost = cost
        self.trace = trace
        self.submitted_at = submitted_at
        self.state = "queued"   # -> "done" | "failed"
        self._result = None
        self._error: Optional[Exception] = None

    def done(self) -> bool:
        return self.state != "queued"

    def result(self):
        if self.state == "queued":
            raise SlateError(
                f"queue: request #{self.seq} not dispatched yet — pump() "
                "the queue (or wait on the service worker)")
        if self._error is not None:
            raise self._error
        return self._result

    def wait(self, timeout: Optional[float] = None, poll_s: float = 0.002):
        """Block (wall time) until dispatched — the service front-end's
        request thread parks here while the worker pumps."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self.done():
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"queue: request #{self.seq} still queued after "
                    f"{timeout}s")
            time.sleep(poll_s)
        return self.result()


class _Window:
    """One open batch window: per-tenant FIFO sub-queues of compatible
    requests (tenant order = first-arrival order, the DRR rotation)."""

    __slots__ = ("key", "opened_at", "deadline", "entries", "count")

    def __init__(self, key, opened_at: float, deadline: float) -> None:
        self.key = key
        self.opened_at = opened_at
        self.deadline = deadline
        # tenant_key -> deque[(ticket, a, b)]
        self.entries: "OrderedDict[str, deque]" = OrderedDict()
        self.count = 0

    def add(self, tenant_key: str, entry) -> None:
        self.entries.setdefault(tenant_key, deque()).append(entry)
        self.count += 1

    def depth(self) -> int:
        return self.count


# ---------------------------------------------------------------------------
# the queue
# ---------------------------------------------------------------------------

# live queues by name — the obs.live ``/queue.json`` + ``/healthz``
# scrape probes this through sys.modules (zero cost for processes that
# never import the service layer)
_ACTIVE: "OrderedDict[str, BatchQueue]" = OrderedDict()

_DEFAULT_TENANT = "default"


class BatchQueue:
    """The async admission queue over one Router (see module doc)."""

    def __init__(self, router, *, max_batch: int = 8,
                 window_s: float = 0.005,
                 ledger: Optional[BudgetLedger] = None,
                 budgets: Optional[Dict[str, int]] = None,
                 weights: Optional[Dict[str, float]] = None,
                 clock=None, dispatch: str = "stacked",
                 name: str = "default") -> None:
        if dispatch not in ("stacked", "packed"):
            raise ValueError(f"unknown dispatch mode {dispatch!r}")
        self.router = router
        # the ServiceController's two window knobs — mutated live
        self.max_batch = int(max_batch)
        self.window_s = float(window_s)
        self.ledger = ledger if ledger is not None else BudgetLedger(
            budgets, weights)
        self.clock = clock if clock is not None else MonotonicClock()
        self.dispatch_mode = dispatch
        self.name = name
        self._windows: "OrderedDict[Tuple, _Window]" = OrderedDict()
        self._deficit: Dict[str, float] = {}
        self._lock = threading.RLock()
        self.dispatch_log: List[dict] = []   # last _LOG_CAP window closes
        self._LOG_CAP = 64
        self.submitted = 0
        self.dispatched = 0
        _ACTIVE[name] = self

    def close(self) -> None:
        """Deregister from the live-scrape surface (windows still open
        are the caller's to drain first)."""
        if _ACTIVE.get(self.name) is self:
            del _ACTIVE[self.name]

    # -- admission ---------------------------------------------------------

    def _reject_admission(self, op, n, dtype, tenant, msg):
        """Terminal ``reject_admission`` at submit: count, open-and-
        finish the request's trace, raise."""
        serve_count("admission_rejects")
        tr = rtrace.new_trace(op, n, self.router.nb, dtype, tenant=tenant)
        rtrace.finish(tr, "reject_admission")
        raise SlateError(msg)

    def submit(self, op: str, a, b, tenant: Optional[str] = None) -> Ticket:
        """Admit one request into its batch window.  Raises SlateError
        (terminal ``reject_admission`` / ``reject_budget`` on the
        request's trace) when the request is malformed (non-square
        operand, rhs row count mismatch), exceeds the bin vocabulary,
        or is over its tenant's HBM budget; otherwise returns a Ticket
        that resolves when the window dispatches.  Shape validation
        lives HERE, at admission, because a malformed request that
        entered a shared window would abort every co-batched sibling
        (and, unguarded, the pump worker) at stack/pad time."""
        dtype = str(a.dtype)
        tenant_key = tenant if tenant is not None else _DEFAULT_TENANT
        shape_a = tuple(getattr(a, "shape", ()))
        if a.ndim != 2 or shape_a[0] != shape_a[1]:
            self._reject_admission(
                op, int(shape_a[0]) if shape_a else 0, dtype, tenant,
                f"queue: operand must be a square matrix, got shape "
                f"{shape_a}")
        n = a.shape[0]
        if b.ndim not in (1, 2) or b.shape[0] != n:
            self._reject_admission(
                op, n, dtype, tenant,
                f"queue: rhs shape {tuple(b.shape)} incompatible with "
                f"operand n={n} (want ({n},) or ({n}, nrhs))")
        m = bin_for(n, self.router.bins)
        if m is None:
            self._reject_admission(
                op, n, dtype, tenant,
                f"queue: n={n} exceeds the largest serving bin "
                f"{self.router.bins[-1]}")
        cost = request_cost(m, a.dtype.itemsize)
        if not self.ledger.try_reserve(tenant_key, cost):
            serve_count("queue_budget_rejects")
            tr = rtrace.new_trace(op, n, self.router.nb, dtype,
                                  tenant=tenant)
            rtrace.finish(tr, "reject_budget")
            self._publish("budget_reject", {
                "tenant": tenant_key, "op": op, "n": n,
                "cost_bytes": cost,
                "headroom_bytes": self.ledger.headroom(tenant_key)})
            raise SlateError(
                f"queue: tenant {tenant_key!r} over its HBM budget — "
                f"request needs ~{cost / 2**20:.1f} MiB modeled, "
                f"headroom {self.ledger.headroom(tenant_key) / 2**20:.1f} "
                "MiB")
        serve_count("queue_submitted")
        tr = rtrace.new_trace(op, n, self.router.nb, dtype, tenant=tenant)
        nrhs = b.shape[1] if b.ndim == 2 else 1
        klass = self.router.effective_class(op, a)
        key = (op, klass, m, nrhs, dtype)
        now = self.clock.now()
        with self._lock:
            tk = Ticket(next(_TICKET_SEQ), op, n, m, nrhs, tenant,
                        tenant_key, cost, tr, now)
            w = self._windows.get(key)
            if w is None:
                w = self._windows[key] = _Window(
                    key, opened_at=now, deadline=now + self.window_s)
            w.add(tenant_key, (tk, a, b))
            self._deficit.setdefault(tenant_key, 0.0)
            self.submitted += 1
            ready = w.depth() >= self.max_batch
        if ready:
            self._close_key(key, "full")
        self._update_gauges()
        return tk

    # -- scheduling --------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return sum(w.depth() for w in self._windows.values())

    def pump(self) -> int:
        """Close every window that is due — past its T deadline, or at/
        over B (the controller may shrink B under an open window).
        Returns the number of requests dispatched.  A dispatch error
        propagates after its window's tickets/traces/reservations are
        settled (the Router's batch-abort contract)."""
        total = 0
        while True:
            now = self.clock.now()
            with self._lock:
                due = [(k, ("full" if w.depth() >= self.max_batch
                            else "expired"))
                       for k, w in self._windows.items()
                       if w.depth() >= self.max_batch or now >= w.deadline]
            if not due:
                break
            for key, cause in due:
                total += self._close_key(key, cause)
        self._update_gauges()
        return total

    def drain(self) -> int:
        """Close EVERY open window now, deadlines notwithstanding
        (shutdown / end-of-stream)."""
        total = 0
        while True:
            with self._lock:
                keys = list(self._windows)
            if not keys:
                break
            for key in keys:
                total += self._close_key(key, "expired")
        self._update_gauges()
        return total

    def _close_key(self, key, cause: str) -> int:
        with self._lock:
            w = self._windows.pop(key, None)
            if w is None:
                return 0
            pending_at_close = {t: len(q) for t, q in w.entries.items()}
            selected = self._drr_select(w, self.max_batch)
            if w.depth() > 0:
                # oversubscribed: the remainder opens a FRESH window (a
                # new T deadline — it queued behind a full round, not
                # behind a lost one)
                now = self.clock.now()
                w.opened_at = now
                w.deadline = now + self.window_s
                self._windows[key] = w
        serve_count("queue_windows")
        serve_count("queue_window_full" if cause == "full"
                    else "queue_window_expired")
        self.dispatch_log.append({
            "key": _key_str(key), "cause": cause,
            "tickets": [(tk.seq, tk.tenant_key) for tk, _a, _b in selected],
            "pending_at_close": pending_at_close,
        })
        del self.dispatch_log[:-self._LOG_CAP]
        self._dispatch(key, selected)
        return len(selected)

    def _drr_select(self, w: _Window, k: int) -> List[tuple]:
        """Weighted deficit round robin over the window's tenants: each
        round grants every pending tenant ``weight`` deficit and serves
        whole requests (cost 1) while deficit lasts — so within one
        round every tenant with weight >= 1 is served, and a tenant's
        service lag is bounded by one max-weight round.  Deficit resets
        only once a tenant has drained from EVERY open window (credit
        accrued here is not forfeited by a sibling window's close) and
        never banks across idle periods; FIFO holds within a tenant by
        construction.  A full rotation that serves nothing (possible
        only if the ledger yields degenerate weights at runtime —
        construction validates > 0) force-serves the head-of-line
        tenant into deficit debt, so selection always terminates
        instead of spinning the dispatching thread."""
        selected: List[tuple] = []
        while len(selected) < k and w.entries:
            progressed = False
            for tenant_key in list(w.entries.keys()):
                if len(selected) >= k:
                    break
                q = w.entries.get(tenant_key)
                if not q:
                    continue
                self._deficit[tenant_key] = (
                    self._deficit.get(tenant_key, 0.0)
                    + self.ledger.weight(tenant_key))
                while (self._deficit[tenant_key] >= 1.0 and q
                       and len(selected) < k):
                    self._take(w, tenant_key, q, selected)
                    progressed = True
                if not q:
                    self._drop_subqueue(w, tenant_key)
            if not progressed and w.entries and len(selected) < k:
                tenant_key = next(iter(w.entries))
                q = w.entries[tenant_key]
                self._take(w, tenant_key, q, selected)
                if not q:
                    self._drop_subqueue(w, tenant_key)
        return selected

    def _take(self, w: _Window, tenant_key: str, q, selected) -> None:
        selected.append(q.popleft())
        w.count -= 1
        self._deficit[tenant_key] = (
            self._deficit.get(tenant_key, 0.0) - 1.0)

    def _drop_subqueue(self, w: _Window, tenant_key: str) -> None:
        """The tenant's sub-queue in this window drained; forget its
        deficit only if no OTHER open window still holds its entries
        (``_drr_select`` runs under the lock with the closing window
        already popped from ``_windows``)."""
        del w.entries[tenant_key]
        if not any(w2.entries.get(tenant_key)
                   for w2 in self._windows.values()):
            self._deficit[tenant_key] = 0.0

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, key, entries: List[tuple]) -> None:
        if not entries:
            return
        op = key[0]
        tickets = [tk for tk, _a, _b in entries]
        now = self.clock.now()
        for tk in tickets:
            # zero-length marker phase: the window wait, measured on the
            # queue's own clock (the trace's wall-clock latency already
            # spans submit -> terminal because the trace opened at submit)
            with rtrace.phase(tk.trace, "queue",
                              wait_s=now - tk.submitted_at):
                pass
        try:
            if self.dispatch_mode == "packed" and op == "posv":
                out = self._dispatch_packed(key, entries)
            else:
                out = self.router.solve_batch(
                    [(op, a, b) for _tk, a, b in entries],
                    tenants=[tk.tenant for tk in tickets],
                    traces=[tk.trace for tk in tickets])
            for tk, x in zip(tickets, out):
                tk._result = x
                tk.state = "done"
        except Exception as e:
            for tk in tickets:
                if tk.state == "queued":
                    tk._error = e
                    tk.state = "failed"
            raise
        finally:
            serve_count("queue_dispatched", len(entries))
            for tk in tickets:
                self.ledger.release(tk.tenant_key, tk.cost)
            self._publish("window", {
                "queue": self.name, "key": _key_str(key),
                "count": len(entries),
                "tenants": sorted({tk.tenant_key for tk in tickets})})

    def _dispatch_packed(self, key, entries: List[tuple]) -> List:
        """Block-diagonal packed dispatch: the window's k problems pack
        into ONE operand and one compiled program through the executable
        cache (posv only — block-diagonal of SPD is SPD).  Per-problem
        solutions are exact in the non-interaction sense (co-packed
        blocks only contribute structural zeros); the bitwise-vs-Router
        guarantee lives on the stacked path."""
        import jax
        import numpy as np

        _op, _klass, m, _nrhs, _dtype = key
        tickets = [tk for tk, _a, _b in entries]
        traces = [tk.trace for tk in tickets]
        ops_ = [a for _tk, a, _b in entries]
        rhs_ = [(b if b.ndim == 2 else b[:, None]) for _tk, _a, b in entries]
        serve_count("queue_packed_dispatches")
        # pack_block_diag itself counts serve.packed_problems (runtime)
        a_pack, b_pack = pack_block_diag(ops_, m, rhs_)
        if self.router.mesh is not None:
            body, _merged = packed_mesh_body(
                self.router.mesh, a_pack.shape[0], str(a_pack.dtype),
                self.router.opts or None)
            pkey = make_key("posv_packed", (a_pack, b_pack),
                            batch=len(entries), mesh=self.router.mesh)
        else:
            body = _packed_single_body()
            pkey = make_key("posv_packed", (a_pack, b_pack),
                            batch=len(entries), mesh=None)
        live = any(tr is not None for tr in traces)
        hit = self.router.cache.contains(pkey) if live else False
        with rtrace.phase_all(traces, "cache_lookup",
                              result="hit" if hit else "miss"):
            prog = self.router.cache.get_or_build(pkey, lambda: body)
        with rtrace.phase_all(traces, "solve"):
            with obs.driver_span("serve.dispatch", op="posv_packed",
                                 batch=len(entries)):
                x_pack, info = prog(a_pack, b_pack)
                if live:
                    jax.block_until_ready(x_pack)
        serve_count("batches")
        serve_count("batched_solves", len(entries))
        if int(np.asarray(info).max()) != 0:
            for tr in traces:
                rtrace.finish(tr, "failed_info")
            raise SlateError(
                "queue: packed posv dispatch reported nonzero info — "
                "an operand in the window is not SPD")
        xs = unpack_block_diag(x_pack, [tk.n for tk in tickets], m,
                               [r.shape[1] for r in rhs_])
        out = []
        for tk, x, b_orig in zip(tickets, xs,
                                 (b for _tk, _a, b in entries)):
            out.append(x[:, 0] if b_orig.ndim == 1 else x)
            rtrace.finish(tk.trace)
        return out

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """JSON-able live view: the ``/queue.json`` scrape body."""
        with self._lock:
            windows = [{
                "key": _key_str(k),
                "depth": w.depth(),
                "opened_at": w.opened_at,
                "deadline": w.deadline,
            } for k, w in self._windows.items()]
            deficits = dict(self._deficit)
        tenants = self.ledger.snapshot()
        for name, d in deficits.items():
            tenants.setdefault(name, {})["deficit"] = d
        return {
            "depth": sum(w["depth"] for w in windows),
            "open_windows": len(windows),
            "windows": windows,
            "max_batch": self.max_batch,
            "window_s": self.window_s,
            "dispatch": self.dispatch_mode,
            "submitted": self.submitted,
            "tenants": tenants,
        }

    def _update_gauges(self) -> None:
        if not obs.enabled():
            return
        REGISTRY.gauge_set("serve.queue_depth", float(self.depth()),
                           queue=self.name)
        with self._lock:
            REGISTRY.gauge_set("serve.queue_open_windows",
                               float(len(self._windows)), queue=self.name)
            deficits = dict(self._deficit)
        for tenant_key, d in deficits.items():
            REGISTRY.gauge_set("serve.queue_tenant_deficit", float(d),
                               queue=self.name, tenant=tenant_key)
            REGISTRY.gauge_set(
                "serve.queue_budget_headroom_bytes",
                float(self.ledger.headroom(tenant_key)),
                queue=self.name, tenant=tenant_key)

    def _publish(self, event: str, data: dict) -> None:
        import sys as _sys

        _live = _sys.modules.get(
            __package__.rsplit(".", 1)[0] + ".obs.live")
        if _live is not None:
            _live.publish("queue", dict(data, event=event))


def _key_str(key) -> str:
    op, klass, m, nrhs, dtype = key
    return f"{op}/{klass}/n{m}/rhs{nrhs}/{dtype}"


def queue_stats() -> dict:
    """Every live queue's stats, keyed by queue name — the obs.live
    ``/queue.json`` body (and the ``/healthz`` liveness line).  The
    scrape runs on its own thread while queues open/close; snapshot the
    registry so a concurrent ``BatchQueue.__init__``/``close`` cannot
    resize the dict mid-iteration."""
    return {"queues": {name: q.stats()
                       for name, q in list(_ACTIVE.items())}}


# ---------------------------------------------------------------------------
# dispatched program bodies (the contract-matrix surface)
# ---------------------------------------------------------------------------


def stacked_body(op: str, klass: str):
    """The pure stacked program a closed window dispatches for
    ``(op, klass)`` — BY CONSTRUCTION the Router's own batched body
    (the queue is host-side scheduling; with the service layer off the
    dispatch is byte-identical, proven as the ``posv_batched_queue``
    contract cell)."""
    from .router import _build_batched

    return _build_batched(op, klass)


def packed_mesh_body(mesh, n_packed: int, dtype: str, opts=None):
    """The pure packed-operand mesh solve body the packed dispatch jits
    through the executable cache, with option resolution identical to
    ``batch.posv_packed_mesh`` (explicit > context > env > tuned >
    auto) — so the queue's packed program is byte-identical to the
    direct packed path (the ``posv_packed_queue`` contract cell).
    Returns ``(body, merged_options)``."""
    from ..parallel.drivers import posv_mesh
    from ..parallel.mesh import mesh_shape
    from ..types import Option, get_option
    from .table import resolve_request_options

    merged = resolve_request_options(opts, "posv", n_packed, dtype,
                                     mesh_shape(mesh))
    nb = int(get_option(merged, Option.BlockSize, default=64))

    def packed(a, b):
        return posv_mesh(a, b, mesh, nb, merged)

    return packed, merged


def _packed_single_body():
    """Single-chip packed body: one posv over the block-diagonal
    operand (info is the packed factor's scalar)."""
    from ..linalg.chol import posv_array

    def packed(a, b):
        x, _f, info = posv_array(a, b)
        return x, info

    return packed
