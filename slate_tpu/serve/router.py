"""serve.Router: admission -> accuracy class -> cached batched dispatch.

The thin request layer tying the serving pieces to the observability
stack PRs 7–10 built:

- **Admission** rides ``MemoryModel.predict_max_n``: a request whose
  modeled residency exceeds the per-request HBM budget is rejected
  before any pod time is burned (``serve.admission_rejects``).
- **Accuracy class** rides the cached condition estimate (the Carson &
  Higham three-precision regime boundary already encoded in
  ``numerics.CONDEST_THRESHOLD``): friendly general operators dispatch
  the cheap no-pivot f32 factor + iterative refinement; operators whose
  condest crosses the threshold dispatch partial pivoting + GMRES-IR
  (the stall regime where classic IR on a cheap factor diverges).  The
  estimate is memoized per operand buffer, so a stationary operator
  pays the Hager–Higham probe loop once across its request stream.
- **Dispatch** goes through the executable cache: same-shaped requests
  stack into one compiled batch program (serve/batch.py).  The stacked
  single-chip programs have no schedule knobs, so tuned options are
  NOT folded into their cache keys (a re-tuned table must not re-key
  programs it cannot affect); the autotuned table's consumers are the
  mesh request paths (batch.posv_packed_mesh resolves explicit >
  context > env > tuned > auto into nb/BcastImpl/Lookahead).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..types import Norm, Options, SlateError
from . import trace as rtrace
from .batch import DEFAULT_BINS, bin_for, pad_rhs_to_bin, pad_to_bin, \
    record_batch_size
from .cache import ExecutableCache, executable_cache, make_key
from .metrics import serve_count


class _BufferMemo:
    """Small LRU keyed on operand buffer identity (id()), holding a
    strong reference to the key array so the id cannot be recycled
    while the entry lives — the stationary-operator cache pattern
    (condest, digit planes).  Capped: serving traffic rotates through a
    handful of stationary operators, not thousands."""

    def __init__(self, cap: int = 16) -> None:
        self._cap = cap
        self._entries: OrderedDict = OrderedDict()

    def get(self, arr, extra=()) -> Optional[object]:
        key = (id(arr),) + tuple(extra)
        hit = self._entries.get(key)
        if hit is None:
            return None
        ref, value = hit
        if ref is not arr:  # id recycled across a dropped entry
            del self._entries[key]
            return None
        self._entries.move_to_end(key)
        return value

    def put(self, arr, value, extra=()) -> None:
        key = (id(arr),) + tuple(extra)
        self._entries[key] = (arr, value)
        self._entries.move_to_end(key)
        while len(self._entries) > self._cap:
            self._entries.popitem(last=False)

    def clear(self) -> None:
        self._entries.clear()


# Process-wide admission memo (ISSUE 19 satellite): the MemoryModel
# closed forms are pure in (model op, nb, grid, dtype, budget), so the
# hot dequeue path must not re-evaluate them per Router instance —
# every actual evaluation counts ``serve.max_n_computes`` (the queue
# smoke asserts a steady-state 100-request stream computes each key
# exactly once, however many Routers the service layer builds).
_MAX_N_MEMO: Dict[Tuple, int] = {}


class Router:
    """Synchronous request router over the batched drivers.

    ``solve_batch`` is the serving entry: a list of (op, a, b) requests
    is admitted, classified, binned into canonical shapes, stacked, and
    dispatched through the executable cache — steady-state traffic of a
    bounded shape vocabulary touches a handful of compiled programs and
    never re-traces."""

    def __init__(self, mesh=None, nb: int = 64,
                 bins: Sequence[int] = DEFAULT_BINS,
                 hbm_budget: Optional[int] = None,
                 cache: Optional[ExecutableCache] = None,
                 opts: Optional[Options] = None) -> None:
        from ..obs import memmodel

        self.mesh = mesh
        self.nb = nb
        self.bins = tuple(sorted(bins))
        self.cache = cache if cache is not None else executable_cache
        self.opts = dict(opts) if opts else {}
        self._budget = hbm_budget if hbm_budget is not None else int(
            memmodel.hbm_budget() * memmodel.HBM_SAFETY)
        self._max_n: Dict[str, int] = {}
        self._condest_memo = _BufferMemo()
        # precision-tier entry point per accuracy class (ISSUE 19): the
        # ServiceController's escalation knob.  Empty = identity; e.g.
        # {"friendly": "hostile"} makes friendly-classified operators
        # ENTER at the pp+GMRES-IR tier (the Carson–Higham robust
        # regime) instead of the cheap nopiv+IR tier.
        self.tier_map: Dict[str, str] = {}

    # -- admission ---------------------------------------------------------

    def max_n(self, op: str) -> int:
        """Largest admissible n for ``op`` under the HBM budget (modeled
        per-device peak, memmodel.predict_max_n; memoized process-wide
        per (model op, nb, grid, dtype, budget) with a per-instance L1
        — the hot dequeue path never re-evaluates a closed form)."""
        from ..obs import memmodel

        got = self._max_n.get(op)
        if got is None:
            # QR/eig requests carry their own models (ISSUE 15): the
            # multi-array aux outputs (T_loc/tree stacks, reflector/WY
            # stacks) made the old getrf_nopiv fallback over-admit them
            model_op = {"posv": "potrf", "potrf": "potrf",
                        "gemm": "summa", "summa": "summa",
                        "geqrf": "geqrf", "gels": "geqrf",
                        "heev": "he2hb", "he2hb": "he2hb"}.get(
                            op, "getrf_nopiv")
            grid = ((1, 1) if self.mesh is None
                    else tuple(self.mesh.devices.shape))
            key = (model_op, max(self.nb, 8), grid, "float64", self._budget)
            got = _MAX_N_MEMO.get(key)
            if got is None:
                serve_count("max_n_computes")
                got = memmodel.predict_max_n(
                    self._budget, op=model_op, nb=max(self.nb, 8),
                    grid=grid, dtype="float64")
                _MAX_N_MEMO[key] = got
            self._max_n[op] = got
        return got

    def admit(self, op: str, n: int) -> None:
        if n > self.max_n(op):
            serve_count("admission_rejects")
            raise SlateError(
                f"serve admission: {op} n={n} exceeds modeled HBM budget "
                f"(max admissible n={self.max_n(op)}, budget "
                f"{self._budget / 2**30:.2f} GiB)")

    def admit_batch(self, op: str, m: int, count: int, itemsize: int) -> None:
        """Aggregate residency check for one stacked dispatch: the whole
        (count, m, m) operand stack + RHS/solution + factor transients
        live at once in the single program (per-problem admission bounds
        one problem, not the stack).  ~3.5 stack copies covers operand +
        factor + solution + XLA temps for the mapped bodies."""
        agg = 3.5 * count * m * m * itemsize
        if agg > self._budget:
            serve_count("admission_rejects")
            raise SlateError(
                f"serve admission: batch of {count} x {op} n={m} needs "
                f"~{agg / 2**30:.2f} GiB aggregate, over the "
                f"{self._budget / 2**30:.2f} GiB budget — split the batch")

    # -- accuracy class ----------------------------------------------------

    def classify(self, op: str, a: jax.Array) -> str:
        """"friendly" | "hostile" per the cached reciprocal condition
        estimate.  The f32 probe factor is cheap (it is also the factor
        the friendly path would reuse conceptually); a stationary
        operator's estimate is memoized on its buffer identity, so a
        million-solve request stream pays the probe loop once."""
        from ..linalg import norms
        from ..obs.numerics import CONDEST_THRESHOLD

        if not jnp.issubdtype(a.dtype, jnp.floating) or a.dtype != jnp.float64:
            return "friendly"  # accuracy ladder is the f64 story
        cached = self._condest_memo.get(a, (op,))
        if cached is None:
            from ..linalg.lu import getrf_array

            anorm = jnp.abs(a).sum(axis=0).max()  # one-norm
            f = getrf_array(a.astype(jnp.float32))
            rcond = norms.gecondest(Norm.One, f, anorm)
            cached = float(rcond)
            self._condest_memo.put(a, cached, (op,))
        else:
            serve_count("condest_cache_hits")
        cond = (1.0 / cached) if cached > 0 else float("inf")
        hostile = cond > CONDEST_THRESHOLD
        serve_count("class_hostile" if hostile else "class_friendly")
        return "hostile" if hostile else "friendly"

    def effective_class(self, op: str, a: jax.Array) -> str:
        """The accuracy class ``solve_batch`` will dispatch ``(op, a)``
        under — condest classification (memoized, so the batch-window
        queue probing it at submit time and the dispatch re-deriving it
        pay the Hager–Higham loop once) composed with the controller's
        ``tier_map`` entry-point override.  The queue's window key uses
        this so one window always lands in one stacked program."""
        if op == "gesv" and not self._mesh_resilient(op):
            klass = self.classify(op, a)
        else:
            klass = "friendly"
        return self.tier_map.get(klass, klass)

    # -- dispatch ----------------------------------------------------------

    def _key_for(self, op: str, variant: str,
                 args: Tuple[jax.Array, ...], batch: int):
        # the ONE source of the stacked-program cache key (the request
        # tracer's hit/miss probe must agree with the lookup by
        # construction).  The stacked single-chip programs have NO
        # schedule knobs (no broadcasts, no k-loop pipelining), so tuned
        # options are deliberately NOT folded into their cache keys — a
        # re-tuned table must not re-key (and re-trace) programs it
        # cannot affect.  The tuned tier's consumers are the mesh paths
        # (batch.posv_packed_mesh resolves it into nb/BcastImpl/
        # Lookahead for the packed solve).
        return make_key(f"{op}_{variant}", args, batch=batch, mesh=None)

    def solve_batch(self, requests: Sequence[Tuple[str, jax.Array, jax.Array]],
                    tenants: Optional[Sequence[Optional[str]]] = None,
                    traces: Optional[List] = None) -> List[jax.Array]:
        """Serve a list of (op, a, b) requests (op in {"posv", "gesv"}).
        Returns per-request solutions in order.  Same-class requests
        sharing a bin run as ONE stacked compiled program (ragged sizes
        identity-pad to the bin; the padded rows solve an appended
        identity system and never touch data rows).

        ``tenants`` optionally names the submitting tenant per request
        (ISSUE 17): with the obs layer on, every metric, span, sample
        and gauge recorded under that request's phases carries the
        tenant tag (and the request's trace_id on event records); with
        obs off the argument is inert — no trace, no context, no tag.

        With the obs layer enabled, every request carries a
        ``RequestTrace`` (serve/trace.py) across its whole lifecycle —
        admission → classify → cache lookup → solve (plus the mesh
        path's factor/solve/degradation phases) — terminated with
        exactly one outcome; disabled, the tracer allocates nothing and
        the dispatch below is byte-identical.  A failure anywhere
        aborts the WHOLE call, so on the error path every still-open
        sibling trace terminates as ``reject_batch_abort`` (the request
        that actually failed already carries its own outcome) — the
        exactly-one-terminal contract holds for every request on every
        exit.

        ``traces`` optionally hands in pre-created RequestTraces (the
        batch-window queue opens a request's trace at SUBMIT time, so
        its latency covers the window wait); entries left ``None`` get
        a fresh trace per the obs-on/off contract, and the batch-abort
        sweep covers handed-in traces identically."""
        trs: List[Optional[rtrace.RequestTrace]] = (
            list(traces) if traces is not None else [None] * len(requests))
        try:
            return self._solve_batch_inner(requests, trs, tenants)
        except Exception:
            for tr in trs:
                if tr is not None and tr.outcome is None:
                    tr.finish("reject_batch_abort")
            raise

    def _solve_batch_inner(self, requests, traces, tenants=None):
        groups: Dict[Tuple, List[int]] = {}
        padded: List[Optional[Tuple[jax.Array, jax.Array]]] = [None] * len(requests)
        for i, (op, a, b) in enumerate(requests):
            serve_count("requests")
            n = a.shape[0]
            tr = traces[i]
            if tr is None:
                tr = traces[i] = rtrace.new_trace(
                    op, n, self.nb, str(a.dtype),
                    tenant=tenants[i] if tenants else None)
            try:
                with rtrace.phase(tr, "admission"):
                    m = bin_for(n, self.bins)
                    if m is None:
                        serve_count("admission_rejects")
                        raise SlateError(
                            f"serve: n={n} exceeds the largest bin "
                            f"{self.bins[-1]}")
                    # the program runs at the PADDED bin size
                    self.admit(op, m)
            except SlateError:
                rtrace.finish(tr, "reject_admission")
                raise
            if tr is not None:
                tr.bin = m
            # the resilient mesh path has its own dispatch (pp for gesv)
            # and never consumes the accuracy class — skip the condest
            # probe instead of paying it for a discarded label
            if op == "gesv" and not self._mesh_resilient(op):
                with rtrace.phase(tr, "classify"):
                    klass = self.classify(op, a)
            else:
                klass = "friendly"
            # the controller's precision-tier entry-point override
            # (ISSUE 19): an escalated class dispatches the robust tier
            # even for operators the condest probe called friendly
            klass = self.tier_map.get(klass, klass)
            if tr is not None:
                tr.klass = klass
            bd = b if b.ndim == 2 else b[:, None]
            padded[i] = (pad_to_bin(a, m), pad_rhs_to_bin(bd, m))
            groups.setdefault(
                (op, klass, m, bd.shape[1], str(a.dtype)), []).append(i)

        out: List[Optional[jax.Array]] = [None] * len(requests)
        for (op, klass, m, nrhs, _dt), idxs in groups.items():
            trs = [traces[i] for i in idxs]
            for tr in trs:
                if tr is not None:
                    tr.batch = len(idxs)
            a_stack = jnp.stack([padded[i][0] for i in idxs])
            b_stack = jnp.stack([padded[i][1] for i in idxs])
            try:
                self.admit_batch(op, m, len(idxs), a_stack.dtype.itemsize)
            except SlateError:
                for tr in trs:
                    rtrace.finish(tr, "reject_admission")
                raise
            record_batch_size(op, len(idxs))
            if self._mesh_resilient(op):
                xs, info = self._solve_group_mesh(op, a_stack, b_stack, trs)
            else:
                key = self._key_for(op, klass, (a_stack, b_stack),
                                    len(idxs))
                live = any(tr is not None for tr in trs)
                # the membership probe exists only for the tracer's
                # hit/miss label; untraced dispatch skips it
                hit = self.cache.contains(key) if live else False
                with rtrace.phase_all(trs, "cache_lookup",
                                      result="hit" if hit else "miss"):
                    prog = self.cache.get_or_build(
                        key, lambda op=op, klass=klass: _build_batched(
                            op, klass))
                with rtrace.phase_all(trs, "solve"):
                    # the dispatch itself runs inside a driver span
                    # (ISSUE 17): with obs on, the batched path gets a
                    # span record (and its depth-0 memory sample)
                    # carrying the ambient trace_id/tenant — the join
                    # point the unified Perfetto export correlates the
                    # request track against; with obs off this is the
                    # shared null span and dispatch is untouched
                    with obs.driver_span("serve.dispatch", op=op,
                                         klass=klass, batch=len(idxs)):
                        xs, info = prog(a_stack, b_stack)
                        if live:
                            # fence so the span (and the SLA latency)
                            # covers the execution, not just the
                            # dispatch — the untraced path keeps JAX's
                            # async semantics
                            jax.block_until_ready(xs)
            serve_count("batches")
            serve_count("batched_solves", len(idxs))
            infos = np.asarray(info)
            bad = [idxs[j] for j, v in enumerate(infos) if v != 0]
            if bad:
                for j, i in enumerate(idxs):
                    if infos[j] != 0:
                        rtrace.finish(traces[i], "failed_info")
                # never silently serve a failed factorization's output
                raise SlateError(
                    f"serve: {op} batch reported nonzero info for request "
                    f"indices {bad} — operand(s) not factorizable in the "
                    f"{klass} class")
            for j, i in enumerate(idxs):
                n = requests[i][1].shape[0]
                xi = xs[j, :n]
                out[i] = xi[:, 0] if requests[i][2].ndim == 1 else xi
                rtrace.finish(traces[i])  # note-attributed served terminal
        return out  # type: ignore[return-value]

    def solve(self, op: str, a: jax.Array, b: jax.Array,
              tenant: Optional[str] = None) -> jax.Array:
        """One request through the full policy (a batch of one)."""
        return self.solve_batch([(op, a, b)],
                                tenants=[tenant] if tenant else None)[0]

    # -- graceful degradation (ISSUE 12 satellite) -------------------------
    #
    # When the router is armed with a resilience policy
    # (Option.FaultTolerance and/or Option.Checkpoint in its opts) and a
    # mesh, requests dispatch through the protected mesh drivers instead
    # of the stacked single-chip programs, and the router absorbs their
    # failure modes instead of surfacing them raw:
    #
    # - a transient FtError retries ONCE under FtPolicy.Recompute
    #   (``serve.retries``) before surfacing — a one-shot SDC costs one
    #   recompute, not a failed request;
    # - a Preempted factorization resumes from its checkpoint on the
    #   router's mesh (``serve.resumes``);
    # - a preempted-and-UNRESUMABLE request (killed before the first
    #   snapshot, or re-killed on resume) is admission-REJECTED
    #   (``serve.admission_rejects``) with a structured error — never
    #   served NaNs.

    def _ckpt_every(self):
        from ..ft.ckpt import resolve_checkpoint
        from ..types import Option, get_option

        # get_option, not dict.get: Options accepts string keys too
        return resolve_checkpoint(
            get_option(self.opts, Option.Checkpoint, default=None))

    def _mesh_resilient(self, op: str) -> bool:
        if self.mesh is None or op not in ("posv", "gesv"):
            return False
        from ..ft.policy import FtPolicy, resolve_policy

        return (resolve_policy(self.opts) != FtPolicy.Off
                or self._ckpt_every() is not None)

    def _solve_group_mesh(self, op: str, a_stack, b_stack, trs=None):
        xs, infos = [], []
        for i in range(a_stack.shape[0]):
            tr = trs[i] if trs is not None else None
            x, info = self._solve_one_mesh(op, a_stack[i], b_stack[i], tr)
            xs.append(x)
            infos.append(jnp.asarray(info, jnp.int32))
        return jnp.stack(xs), jnp.stack(infos)

    def _solve_one_mesh(self, op: str, a, b, tr=None):
        try:
            return self._solve_one_mesh_inner(op, a, b, tr)
        except Exception:
            # an error escaping THIS request's own dispatch (e.g. a
            # second FtError after the one retry, or an abort raised
            # inside a retry) is this request's failure, not a sibling's
            # — terminate it with its own cause so solve_batch's
            # batch-abort sweep only ever labels true bystanders
            if tr is not None and tr.outcome is None:
                tr.finish("failed_error")
            raise

    def _solve_one_mesh_inner(self, op: str, a, b, tr=None):
        from ..ft import ckpt as _ckpt
        from ..ft.policy import FtError, FtPolicy, resolve_policy

        from ..obs.numerics import GrowthAbort

        pol = resolve_policy(self.opts)
        try:
            return self._guard(op, a, b, *self._factor_solve_mesh(
                op, a, b, pol, tr), tr=tr)
        except _ckpt.Preempted as e:
            if e.checkpoint is None:
                serve_count("admission_rejects")
                rtrace.finish(tr, "reject_unresumable")
                raise SlateError(
                    f"serve: {op} request preempted at step {e.killed_at} "
                    "before its first checkpoint — rejected (unresumable), "
                    "not served NaNs") from e
            serve_count("resumes")
            rtrace.note(tr, "resume")
            try:
                with rtrace.phase(tr, "resume", killed_at=e.killed_at,
                                  from_step=e.checkpoint.step):
                    resumed = self._resume_solve(op, b, e.checkpoint, tr)
                return self._guard(op, a, b, *resumed, tr=tr)
            except _ckpt.Preempted as e2:
                serve_count("admission_rejects")
                rtrace.finish(tr, "reject_unresumable")
                raise SlateError(
                    f"serve: {op} request re-preempted on resume at step "
                    f"{e2.killed_at} — rejected") from e2
            except GrowthAbort:
                # the RESUMED no-pivot factor kept policing the gauge
                # (Checkpoint.growth_abort) and aborted: same escalation
                # as the uninterrupted abort — one pivoted retry
                serve_count("retries")
                rtrace.note(tr, "growth_retry")
                with rtrace.phase(tr, "retry", cause="growth_abort"):
                    retried = self._factor_solve_pp(op, a, b, tr=tr)
                return self._guard(op, a, b, *retried, tr=tr)
        except FtError:
            # transient-SDC class: one retry under the recompute policy;
            # a second FtError (persistent corruption) surfaces raw
            serve_count("retries")
            rtrace.note(tr, "ft_retry")
            with rtrace.phase(tr, "retry", cause="ft_error"):
                retried = self._factor_solve_mesh(
                    op, a, b, FtPolicy.Recompute, tr)
            return self._guard(op, a, b, *retried, tr=tr)

    def _guard(self, op: str, a, b, x, info, tr=None):
        """The resilient mesh path bypasses the batched drivers'
        condest-keyed accuracy ladder (the ABFT LU is no-pivot), so no
        solution leaves unvalidated: one residual check rejects a
        silently-inaccurate solve instead of serving it."""
        if int(info) != 0:
            return x, info  # caller surfaces nonzero info itself
        n = a.shape[0]
        eps = float(jnp.finfo(a.dtype).eps)
        scale = float(jnp.max(jnp.abs(a))) * max(
            float(jnp.max(jnp.abs(x))), 1.0) * n
        resid = float(jnp.max(jnp.abs(a @ x - b)))
        if not np.isfinite(resid) or resid > 1e6 * n * eps * max(scale, 1.0):
            serve_count("admission_rejects")
            rtrace.finish(tr, "reject_residual")
            raise SlateError(
                f"serve: {op} resilient-path solution failed the residual "
                f"gate (|Ax-b| max {resid:.3g}) — rejected, not served")
        return x, info

    def _resil_opts(self):
        """Raw schedule/monitor options the resilient mesh path forwards
        (the drivers' _la/_bi/_pi/_nm idiom — armed options must thread
        end-to-end, not silently drop to defaults)."""
        from ..types import Option, get_option

        return (get_option(self.opts, Option.Lookahead),
                get_option(self.opts, Option.BcastImpl),
                get_option(self.opts, Option.PanelImpl),
                get_option(self.opts, Option.NumMonitor))

    def _factor_solve_mesh(self, op: str, a, b, pol, tr=None):
        from ..ft.ckpt import getrf_pp_ckpt, potrf_ckpt
        from ..ft.policy import FtPolicy
        from ..parallel.dist import from_dense

        every = self._ckpt_every()
        la, bi, pi, nm = self._resil_opts()
        if pol != FtPolicy.Off:
            if every is not None:
                raise SlateError(
                    "serve: Option.FaultTolerance and Option.Checkpoint "
                    "cannot be combined (the ABFT kernels are not "
                    "checkpointed yet); arm one of them")
            from ..ft import abft

            with rtrace.phase(tr, "factor", method="abft", policy=str(pol)):
                if op == "posv":
                    l, info, _rep = abft.potrf_ft(
                        a, self.mesh, self.nb, policy=pol, lookahead=la,
                        bcast_impl=bi, panel_impl=pi)
                else:
                    # the only ABFT LU is no-pivot — _guard validates the
                    # solution it produces
                    l, info, _rep = abft.getrf_nopiv_ft(
                        a, self.mesh, self.nb, policy=pol, lookahead=la,
                        bcast_impl=bi, panel_impl=pi)
            return self._trsm_solve(op, l, b, tr=tr), info
        d = from_dense(a, self.mesh, self.nb, diag_pad_one=True)
        if op == "posv":
            with rtrace.phase(tr, "factor", method="potrf_ckpt"):
                l, info = potrf_ckpt(d, every=every, bcast_impl=bi,
                                     panel_impl=pi, num_monitor=nm)
            return self._trsm_solve(op, l, b, tr=tr), info
        # gesv on the checkpointed path: with NumMonitor armed, try the
        # cheap no-pivot factor first — the FRIENDLY accuracy class the
        # batched router already serves (PR 11's condest-keyed nopiv+IR
        # dispatch), here policed by the segment chain's in-carry growth
        # gauge instead of a condest probe: element growth crossing
        # GROWTH_THRESHOLD ABORTS the factor mid-k-loop
        # (obs.numerics.GrowthAbort, ISSUE 13 satellite: never complete
        # a garbage factor) and the router consumes that as exactly one
        # retry with partial pivoting (``serve.retries``).  Served
        # growth below the threshold bounds the nopiv backward error at
        # ~GROWTH_THRESHOLD·eps64 ≈ 2e-10 — the friendly-class bar —
        # and _guard's residual gate backstops every served solution.
        # The class mix is observable: gauge-policed nopiv serves count
        # ``serve.class_friendly``, pp serves ``serve.class_hostile``.
        # Unmonitored requests keep partial pivoting outright — no
        # class downgrade without the gauge that polices it.
        from ..obs.numerics import GrowthAbort, resolve_num_monitor

        if resolve_num_monitor(nm) == "on":
            from ..ft.ckpt import getrf_nopiv_ckpt

            try:
                with rtrace.phase(tr, "factor", method="nopiv_ckpt"):
                    lu, info = getrf_nopiv_ckpt(
                        d, every=every, bcast_impl=bi, panel_impl=pi,
                        num_monitor=nm)
                serve_count("class_friendly")
                return self._trsm_solve(op, lu, b, tr=tr), info
            except GrowthAbort:
                serve_count("retries")
                rtrace.note(tr, "growth_retry")
                with rtrace.phase(tr, "retry", cause="growth_abort"):
                    return self._factor_solve_pp(op, b_dense=b, d=d, tr=tr)
        return self._factor_solve_pp(op, b_dense=b, d=d, tr=tr)

    def _factor_solve_pp(self, op: str, a=None, b_dense=None, d=None,
                         tr=None):
        """The pivoted gesv tier (shared by the growth-abort retry paths:
        the initial attempt hands over its DistMatrix, the resumed-abort
        path re-encodes from the dense operand)."""
        from ..ft.ckpt import getrf_pp_ckpt
        from ..parallel.dist import from_dense

        _la, bi, _pi, nm = self._resil_opts()
        if d is None:
            d = from_dense(a, self.mesh, self.nb, diag_pad_one=True)
        with rtrace.phase(tr, "factor", method="pp_ckpt"):
            lu, perm, info = getrf_pp_ckpt(d, every=self._ckpt_every(),
                                           bcast_impl=bi, num_monitor=nm)
        serve_count("class_hostile")
        return self._trsm_solve(op, lu, b_dense, perm=perm, tr=tr), info

    def _resume_solve(self, op: str, b, checkpoint, tr=None):
        from ..ft import elastic

        _la, bi, pi, _nm = self._resil_opts()
        with rtrace.phase(tr, "factor", method="elastic_resume"):
            out = elastic.resume(checkpoint, self.mesh, bcast_impl=bi,
                                 panel_impl=pi)
        if len(out) == 3:  # getrf_pp: (LU, perm, info)
            lu, perm, info = out
            return self._trsm_solve(op, lu, b, perm=perm, tr=tr), info
        l, info = out
        return self._trsm_solve(op, l, b, tr=tr), info

    def _trsm_solve(self, op: str, l, b, perm=None, tr=None):
        from ..parallel.dist import from_dense, to_dense
        from ..parallel.dist_lu import permute_rows_dist
        from ..parallel.dist_trsm import trsm_dist
        from ..types import Diag, Op, Uplo

        la, bi, _pi, _nm = self._resil_opts()
        with rtrace.phase(tr, "solve"):
            bd = from_dense(b, self.mesh, self.nb)
            if perm is not None:
                bd = permute_rows_dist(bd, perm)
            if op == "posv":
                y = trsm_dist(l, bd, Uplo.Lower, Op.NoTrans, lookahead=la,
                              bcast_impl=bi)
                x = trsm_dist(l, y, Uplo.Lower, Op.ConjTrans, lookahead=la,
                              bcast_impl=bi)
            else:
                y = trsm_dist(l, bd, Uplo.Lower, Op.NoTrans, Diag.Unit,
                              lookahead=la, bcast_impl=bi)
                x = trsm_dist(l, y, Uplo.Upper, Op.NoTrans, lookahead=la,
                              bcast_impl=bi)
            out = to_dense(x)[: b.shape[0]]
            if tr is not None:
                jax.block_until_ready(out)  # honest span/SLA end time
        return out

    # -- QR (least-squares) tier -------------------------------------------

    def gels(self, a: jax.Array, b: jax.Array,
             tenant: Optional[str] = None) -> jax.Array:
        """Serve one least-squares request min ||A x - b|| through the
        mesh CAQR tier (requires a mesh; m >= n).  With
        Option.NumMonitor armed the factor's recorded reflector/τ
        consistency loss (the ``num.qr_orth_margin`` gauge — recorded
        since ISSUE 15, acted on here) is policed against
        ``obs.numerics.ORTH_THRESHOLD``: a factor past the bound is NOT
        served raw — the router retries ONCE with a
        re-orthogonalization pass ("twice is enough": a second CAQR
        over the explicitly-formed Q, both triangular factors folded
        into the solve), counted as one ``serve.retries`` with its own
        degradation note (``orth_retry``).  Unmonitored requests keep
        the single-pass factor — no degradation action without the
        gauge that polices it (the growth-abort rule)."""
        from ..obs import numerics as _num
        from ..parallel.dist import from_dense, to_dense
        from ..parallel.dist_qr import geqrf_dist, unmqr_dist
        from ..types import Op

        if self.mesh is None:
            raise SlateError("serve: the gels tier requires a mesh")
        serve_count("requests")
        m, n = a.shape
        tr = rtrace.new_trace("gels", m, self.nb, str(a.dtype),
                              tenant=tenant)
        try:
            with rtrace.phase(tr, "admission"):
                self.admit("gels", m)
        except SlateError:
            rtrace.finish(tr, "reject_admission")
            raise
        try:
            _la, bi, pi, nm = self._resil_opts()
            monitored = _num.resolve_num_monitor(nm) == "on"
            if monitored:
                _num.clear_last("geqrf")  # police THIS factor's gauge
            bcol = b if b.ndim == 2 else b[:, None]
            with rtrace.phase(tr, "factor", method="geqrf_dist"):
                f1 = geqrf_dist(from_dense(a, self.mesh, self.nb),
                                bcast_impl=bi, panel_impl=pi,
                                num_monitor=nm)
            if monitored and _num.orth_exceeded("geqrf"):
                serve_count("retries")
                rtrace.note(tr, "orth_retry")
                with rtrace.phase(tr, "retry", cause="orth_loss"):
                    # Q1 = Q2 R2 re-orthogonalizes the computed basis, so
                    # A = Q2 (R2 R1): solve R2 z = Q2ᴴ b, then R1 x = z
                    eye = jnp.eye(m, n, dtype=a.dtype)
                    q1 = to_dense(unmqr_dist(
                        f1, from_dense(eye, self.mesh, self.nb),
                        Op.NoTrans, bcast_impl=bi))[:, :n]
                    f2 = geqrf_dist(from_dense(q1, self.mesh, self.nb),
                                    bcast_impl=bi, panel_impl=pi,
                                    num_monitor=nm)
                    qb = to_dense(unmqr_dist(
                        f2, from_dense(bcol, self.mesh, self.nb),
                        Op.ConjTrans, bcast_impl=bi))[:n]
                    z, info2 = self._rsolve(f2, qb, n, bi)
                    x, info1 = self._rsolve(f1, z, n, bi)
                    info = jnp.where(info1 != 0, info1, info2)
            else:
                with rtrace.phase(tr, "solve"):
                    qb = to_dense(unmqr_dist(
                        f1, from_dense(bcol, self.mesh, self.nb),
                        Op.ConjTrans, bcast_impl=bi))[:n]
                    x, info = self._rsolve(f1, qb, n, bi)
            if int(info) != 0:
                rtrace.finish(tr, "failed_info")
                raise SlateError(
                    f"serve: gels factor reported info={int(info)} — "
                    "R diagonal exactly zero (rank-deficient operand)")
            jax.block_until_ready(x)  # honest span/SLA end time
            rtrace.finish(tr)
            return x[:, 0] if b.ndim == 1 else x
        except Exception:
            if tr is not None and tr.outcome is None:
                tr.finish("failed_error")
            raise

    def _rsolve(self, f, y, n: int, bi):
        """x = R^{-1} y from CAQR factors: the R top square goes through
        one dense triu round trip (the gels_mesh composition) into an
        upper trsm sweep.  info flags an exactly-zero R diagonal."""
        from ..parallel.dist import from_dense, to_dense
        from ..parallel.dist_trsm import trsm_dist
        from ..types import Op, Uplo

        r = jnp.triu(to_dense(f.fact)[:n, :n])
        rd = from_dense(r, self.mesh, self.nb, diag_pad_one=True)
        xd = trsm_dist(rd, from_dense(y, self.mesh, self.nb), Uplo.Upper,
                       Op.NoTrans, bcast_impl=bi)
        rdiag = jnp.diagonal(r)
        info = jnp.where(
            jnp.any(rdiag == 0), jnp.argmax(rdiag == 0) + 1, 0
        ).astype(jnp.int32)
        return to_dense(xd)[:n], info


def _build_batched(op: str, variant: str):
    """The pure stacked solve body for one (op, accuracy-class) pair —
    what the executable cache jits and pins."""
    from jax import lax

    if op == "posv":
        from ..linalg.chol import posv_array

        def posv(a, b):
            def one(ab):
                x, _f, info = posv_array(ab[0], ab[1])
                return x, info

            return lax.map(one, (a, b))

        return posv
    if op != "gesv":
        raise ValueError(f"router has no batched driver for {op!r}")
    if variant == "hostile":
        # pp + GMRES-IR: the escalation class for operators past the
        # Carson–Higham IR stall boundary
        from ..linalg.refine import gesv_mixed_gmres_array

        def hostile(a, b):
            def one(ab):
                x, _resid = gesv_mixed_gmres_array(ab[0], ab[1])
                # GMRES-IR has no LAPACK info; a non-finite solution is
                # the observable factor/convergence failure signal
                ok = jnp.all(jnp.isfinite(x))
                return x, jnp.where(ok, 0, 1).astype(jnp.int32)

            return lax.map(one, (a, b))

        return hostile
    from ..linalg.lu import gesv_array, getrf_nopiv_array, getrs_array
    from ..linalg.refine import _fallback, _refine_loop

    def friendly(a, b):
        # cheap class: f32 no-pivot factor + f64 IR, full-solve fallback
        # (the pivot-free factor is the fast tier no-pivoting safety
        # analysis forbids for hostile operators — which is exactly why
        # the condest class gate sits in front of it)
        def one(ab):
            a1, b1 = ab
            f32 = getrf_nopiv_array(a1.astype(jnp.float32))
            solve = lambda r: getrs_array(f32, r.astype(jnp.float32))
            x, iters, done = _refine_loop(a1, b1, solve, 30)
            x, _iters, info = _fallback(
                done, x, iters,
                lambda: (lambda o: (o[0], o[1].info))(gesv_array(a1, b1)))
            return x, info

        return lax.map(one, (a, b))

    return friendly
