"""serve.Router: admission -> accuracy class -> cached batched dispatch.

The thin request layer tying the serving pieces to the observability
stack PRs 7–10 built:

- **Admission** rides ``MemoryModel.predict_max_n``: a request whose
  modeled residency exceeds the per-request HBM budget is rejected
  before any pod time is burned (``serve.admission_rejects``).
- **Accuracy class** rides the cached condition estimate (the Carson &
  Higham three-precision regime boundary already encoded in
  ``numerics.CONDEST_THRESHOLD``): friendly general operators dispatch
  the cheap no-pivot f32 factor + iterative refinement; operators whose
  condest crosses the threshold dispatch partial pivoting + GMRES-IR
  (the stall regime where classic IR on a cheap factor diverges).  The
  estimate is memoized per operand buffer, so a stationary operator
  pays the Hager–Higham probe loop once across its request stream.
- **Dispatch** goes through the executable cache: same-shaped requests
  stack into one compiled batch program (serve/batch.py).  The stacked
  single-chip programs have no schedule knobs, so tuned options are
  NOT folded into their cache keys (a re-tuned table must not re-key
  programs it cannot affect); the autotuned table's consumers are the
  mesh request paths (batch.posv_packed_mesh resolves explicit >
  context > env > tuned > auto into nb/BcastImpl/Lookahead).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..types import Norm, Options, SlateError
from .batch import DEFAULT_BINS, bin_for, pad_rhs_to_bin, pad_to_bin
from .cache import ExecutableCache, executable_cache, make_key
from .metrics import serve_count


class _BufferMemo:
    """Small LRU keyed on operand buffer identity (id()), holding a
    strong reference to the key array so the id cannot be recycled
    while the entry lives — the stationary-operator cache pattern
    (condest, digit planes).  Capped: serving traffic rotates through a
    handful of stationary operators, not thousands."""

    def __init__(self, cap: int = 16) -> None:
        self._cap = cap
        self._entries: OrderedDict = OrderedDict()

    def get(self, arr, extra=()) -> Optional[object]:
        key = (id(arr),) + tuple(extra)
        hit = self._entries.get(key)
        if hit is None:
            return None
        ref, value = hit
        if ref is not arr:  # id recycled across a dropped entry
            del self._entries[key]
            return None
        self._entries.move_to_end(key)
        return value

    def put(self, arr, value, extra=()) -> None:
        key = (id(arr),) + tuple(extra)
        self._entries[key] = (arr, value)
        self._entries.move_to_end(key)
        while len(self._entries) > self._cap:
            self._entries.popitem(last=False)

    def clear(self) -> None:
        self._entries.clear()


class Router:
    """Synchronous request router over the batched drivers.

    ``solve_batch`` is the serving entry: a list of (op, a, b) requests
    is admitted, classified, binned into canonical shapes, stacked, and
    dispatched through the executable cache — steady-state traffic of a
    bounded shape vocabulary touches a handful of compiled programs and
    never re-traces."""

    def __init__(self, mesh=None, nb: int = 64,
                 bins: Sequence[int] = DEFAULT_BINS,
                 hbm_budget: Optional[int] = None,
                 cache: Optional[ExecutableCache] = None,
                 opts: Optional[Options] = None) -> None:
        from ..obs import memmodel

        self.mesh = mesh
        self.nb = nb
        self.bins = tuple(sorted(bins))
        self.cache = cache if cache is not None else executable_cache
        self.opts = dict(opts) if opts else {}
        self._budget = hbm_budget if hbm_budget is not None else int(
            memmodel.hbm_budget() * memmodel.HBM_SAFETY)
        self._max_n: Dict[str, int] = {}
        self._condest_memo = _BufferMemo()

    # -- admission ---------------------------------------------------------

    def max_n(self, op: str) -> int:
        """Largest admissible n for ``op`` under the HBM budget (modeled
        per-device peak, memmodel.predict_max_n; cached per op)."""
        from ..obs import memmodel

        got = self._max_n.get(op)
        if got is None:
            model_op = {"posv": "potrf", "potrf": "potrf",
                        "gemm": "summa", "summa": "summa"}.get(
                            op, "getrf_nopiv")
            grid = ((1, 1) if self.mesh is None
                    else tuple(self.mesh.devices.shape))
            got = memmodel.predict_max_n(
                self._budget, op=model_op, nb=max(self.nb, 8), grid=grid,
                dtype="float64")
            self._max_n[op] = got
        return got

    def admit(self, op: str, n: int) -> None:
        if n > self.max_n(op):
            serve_count("admission_rejects")
            raise SlateError(
                f"serve admission: {op} n={n} exceeds modeled HBM budget "
                f"(max admissible n={self.max_n(op)}, budget "
                f"{self._budget / 2**30:.2f} GiB)")

    def admit_batch(self, op: str, m: int, count: int, itemsize: int) -> None:
        """Aggregate residency check for one stacked dispatch: the whole
        (count, m, m) operand stack + RHS/solution + factor transients
        live at once in the single program (per-problem admission bounds
        one problem, not the stack).  ~3.5 stack copies covers operand +
        factor + solution + XLA temps for the mapped bodies."""
        agg = 3.5 * count * m * m * itemsize
        if agg > self._budget:
            serve_count("admission_rejects")
            raise SlateError(
                f"serve admission: batch of {count} x {op} n={m} needs "
                f"~{agg / 2**30:.2f} GiB aggregate, over the "
                f"{self._budget / 2**30:.2f} GiB budget — split the batch")

    # -- accuracy class ----------------------------------------------------

    def classify(self, op: str, a: jax.Array) -> str:
        """"friendly" | "hostile" per the cached reciprocal condition
        estimate.  The f32 probe factor is cheap (it is also the factor
        the friendly path would reuse conceptually); a stationary
        operator's estimate is memoized on its buffer identity, so a
        million-solve request stream pays the probe loop once."""
        from ..linalg import norms
        from ..obs.numerics import CONDEST_THRESHOLD

        if not jnp.issubdtype(a.dtype, jnp.floating) or a.dtype != jnp.float64:
            return "friendly"  # accuracy ladder is the f64 story
        cached = self._condest_memo.get(a, (op,))
        if cached is None:
            from ..linalg.lu import getrf_array

            anorm = jnp.abs(a).sum(axis=0).max()  # one-norm
            f = getrf_array(a.astype(jnp.float32))
            rcond = norms.gecondest(Norm.One, f, anorm)
            cached = float(rcond)
            self._condest_memo.put(a, cached, (op,))
        else:
            serve_count("condest_cache_hits")
        cond = (1.0 / cached) if cached > 0 else float("inf")
        hostile = cond > CONDEST_THRESHOLD
        serve_count("class_hostile" if hostile else "class_friendly")
        return "hostile" if hostile else "friendly"

    # -- dispatch ----------------------------------------------------------

    def _program(self, op: str, variant: str, args: Tuple[jax.Array, ...],
                 batch: int):
        # the stacked single-chip programs have NO schedule knobs (no
        # broadcasts, no k-loop pipelining), so tuned options are
        # deliberately NOT folded into their cache keys — a re-tuned
        # table must not re-key (and re-trace) programs it cannot
        # affect.  The tuned tier's consumers are the mesh paths
        # (batch.posv_packed_mesh resolves it into nb/BcastImpl/
        # Lookahead for the packed solve).
        key = make_key(f"{op}_{variant}", args, batch=batch, mesh=None)
        return self.cache.get_or_build(key, lambda: _build_batched(
            op, variant)), key

    def solve_batch(self, requests: Sequence[Tuple[str, jax.Array, jax.Array]]
                    ) -> List[jax.Array]:
        """Serve a list of (op, a, b) requests (op in {"posv", "gesv"}).
        Returns per-request solutions in order.  Same-class requests
        sharing a bin run as ONE stacked compiled program (ragged sizes
        identity-pad to the bin; the padded rows solve an appended
        identity system and never touch data rows)."""
        groups: Dict[Tuple, List[int]] = {}
        padded: List[Optional[Tuple[jax.Array, jax.Array]]] = [None] * len(requests)
        for i, (op, a, b) in enumerate(requests):
            serve_count("requests")
            n = a.shape[0]
            m = bin_for(n, self.bins)
            if m is None:
                serve_count("admission_rejects")
                raise SlateError(f"serve: n={n} exceeds the largest bin "
                                 f"{self.bins[-1]}")
            self.admit(op, m)  # the program runs at the PADDED bin size
            klass = self.classify(op, a) if op == "gesv" else "friendly"
            bd = b if b.ndim == 2 else b[:, None]
            padded[i] = (pad_to_bin(a, m), pad_rhs_to_bin(bd, m))
            groups.setdefault(
                (op, klass, m, bd.shape[1], str(a.dtype)), []).append(i)

        out: List[Optional[jax.Array]] = [None] * len(requests)
        for (op, klass, m, nrhs, _dt), idxs in groups.items():
            a_stack = jnp.stack([padded[i][0] for i in idxs])
            b_stack = jnp.stack([padded[i][1] for i in idxs])
            self.admit_batch(op, m, len(idxs), a_stack.dtype.itemsize)
            prog, _key = self._program(op, klass, (a_stack, b_stack),
                                       batch=len(idxs))
            xs, info = prog(a_stack, b_stack)
            serve_count("batches")
            serve_count("batched_solves", len(idxs))
            bad = [idxs[j] for j, v in enumerate(np.asarray(info)) if v != 0]
            if bad:
                # never silently serve a failed factorization's output
                raise SlateError(
                    f"serve: {op} batch reported nonzero info for request "
                    f"indices {bad} — operand(s) not factorizable in the "
                    f"{klass} class")
            for j, i in enumerate(idxs):
                n = requests[i][1].shape[0]
                xi = xs[j, :n]
                out[i] = xi[:, 0] if requests[i][2].ndim == 1 else xi
        return out  # type: ignore[return-value]

    def solve(self, op: str, a: jax.Array, b: jax.Array) -> jax.Array:
        """One request through the full policy (a batch of one)."""
        return self.solve_batch([(op, a, b)])[0]


def _build_batched(op: str, variant: str):
    """The pure stacked solve body for one (op, accuracy-class) pair —
    what the executable cache jits and pins."""
    from jax import lax

    if op == "posv":
        from ..linalg.chol import posv_array

        def posv(a, b):
            def one(ab):
                x, _f, info = posv_array(ab[0], ab[1])
                return x, info

            return lax.map(one, (a, b))

        return posv
    if op != "gesv":
        raise ValueError(f"router has no batched driver for {op!r}")
    if variant == "hostile":
        # pp + GMRES-IR: the escalation class for operators past the
        # Carson–Higham IR stall boundary
        from ..linalg.refine import gesv_mixed_gmres_array

        def hostile(a, b):
            def one(ab):
                x, _resid = gesv_mixed_gmres_array(ab[0], ab[1])
                # GMRES-IR has no LAPACK info; a non-finite solution is
                # the observable factor/convergence failure signal
                ok = jnp.all(jnp.isfinite(x))
                return x, jnp.where(ok, 0, 1).astype(jnp.int32)

            return lax.map(one, (a, b))

        return hostile
    from ..linalg.lu import gesv_array, getrf_nopiv_array, getrs_array
    from ..linalg.refine import _fallback, _refine_loop

    def friendly(a, b):
        # cheap class: f32 no-pivot factor + f64 IR, full-solve fallback
        # (the pivot-free factor is the fast tier no-pivoting safety
        # analysis forbids for hostile operators — which is exactly why
        # the condest class gate sits in front of it)
        def one(ab):
            a1, b1 = ab
            f32 = getrf_nopiv_array(a1.astype(jnp.float32))
            solve = lambda r: getrs_array(f32, r.astype(jnp.float32))
            x, iters, done = _refine_loop(a1, b1, solve, 30)
            x, _iters, info = _fallback(
                done, x, iters,
                lambda: (lambda o: (o[0], o[1].info))(gesv_array(a1, b1)))
            return x, info

        return lax.map(one, (a, b))

    return friendly
