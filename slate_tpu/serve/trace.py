"""Request-level serving traces: the end-to-end lifecycle record of one
request through the Router (ISSUE 14).

The obs stack already answers "is the schedule right" (``sched.*``),
"does it fit" (``mem.*``) and "is the answer right" (``num.*``); this
module answers "what happened to request 4711" — the Dapper-style span
record of one request's whole path: queue/admission → condest
classification → executable-cache lookup (hit/miss) → factor →
solve/refine → the PR 12/13 degradation ladder (FtError retry,
Preempted resume, GrowthAbort pivoted retry, structured reject).

Contracts:

- **Exactly one terminal outcome per request.**  ``finish`` is a
  single-shot: a second terminal is a programming error and raises.
  The outcome taxonomy (``TERMINALS``) attributes every exit to one
  cause — a request that retried AND resumed terminates under the LAST
  degradation that carried it home.
- **Disabled mode stays honest.**  ``new_trace`` returns ``None`` while
  the obs layer is off: ZERO trace allocations, and every Router call
  site guards with ``if tr is not None`` (the module-level ``phase`` /
  ``note`` / ``finish`` helpers do it once), so the dispatch path is
  byte-identical to the untraced router (asserted in tests/test_serve.py).
- **The metric surface is the shared registry.**  ``finish`` observes
  the request latency into the ``serve.latency_s`` histogram tagged by
  (op, request class, outcome) — obs/metrics.py histograms now carry
  first-class reservoir quantiles — and ``sla_values()`` reduces the
  live registry to the flat ``latency_{p50,p95,p99}_*`` +
  outcome-count/rate keys that land in the RunReport ``serve`` section
  (serve/metrics.py merges them), gated by ``obs.report --check`` with
  the wall-clock ``*latency*_s`` keys ``--ignore``d.

Export surfaces: ``obs.perfetto.request_trace_events`` renders finished
traces as one Perfetto track per accuracy class with flow arrows
retry→resume→final; ``python -m slate_tpu.serve.stats`` emits a
Prometheus-style text + JSON snapshot of the live registry.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..obs import REGISTRY, enabled
from ..obs import context as _obs_context

# terminal outcomes: every request ends in EXACTLY one of these
TERMINALS = (
    "served",                # clean dispatch, no degradation consumed
    "served_retry",          # transient FtError -> one Recompute retry
    "served_resume",         # Preempted -> resumed from its checkpoint
    "served_growth_retry",   # GrowthAbort -> one pivoted (pp) retry
    "reject_admission",      # over the HBM/bin admission bound
    "reject_budget",         # over the submitting TENANT's HBM budget
    #   (the batch-window queue's fair-share ledger, ISSUE 19 — the
    #   global admission bound above is the whole-device twin)
    "reject_unresumable",    # preempted with no (or a re-killed) snapshot
    "reject_residual",       # resilient-path residual gate refused it
    "reject_batch_abort",    # a sibling/other-group failure aborted the
    #   batch before this request's own dispatch concluded (the Router
    #   raises for the whole solve_batch call; the cause lives on the
    #   request that actually failed)
    "failed_info",           # factorization reported nonzero info
    "failed_error",          # the request's OWN dispatch raised past the
    #   degradation ladder (persistent SDC after the one retry, an abort
    #   inside a retry, an unexpected error)
)

# degradation notes -> the served-terminal they map to (the LAST note
# names the cause that actually carried the request home)
_NOTE_TERMINAL = {
    "ft_retry": "served_retry",
    "resume": "served_resume",
    "growth_retry": "served_growth_retry",
}

_IDS = itertools.count(1)
_lock = threading.Lock()
_FINISHED: List["RequestTrace"] = []
_FINISHED_CAP = 4096
# (op, klass, outcome) -> count; the exact outcome-attribution totals
# (histogram reservoirs estimate quantiles; these counts are exact)
_OUTCOME_COUNTS: Dict[Tuple[str, str, str], float] = {}


class RequestTrace:
    """One request's lifecycle: identity (rid/op/n/nb/dtype), the
    condest-keyed accuracy class, nesting phase spans, degradation
    notes, and the single terminal outcome."""

    __slots__ = ("rid", "op", "n", "nb", "dtype", "klass", "bin", "batch",
                 "t0", "t1", "phases", "notes", "outcome", "_stack",
                 "trace_id", "tenant")

    def __init__(self, op: str, n: int, nb: int, dtype: str,
                 tenant: Optional[str] = None) -> None:
        self.rid = next(_IDS)
        # the correlation id every surface below joins on (ISSUE 17):
        # assigned ONCE here, so degradation-ladder retries/resumes —
        # which re-dispatch under this same trace — keep one trace_id
        # across dispatches, while a batch-abort bystander (its own
        # RequestTrace) gets its own
        self.trace_id = _obs_context.new_trace_id()
        self.tenant = tenant
        self.op = op
        self.n = int(n)
        self.nb = int(nb)
        self.dtype = dtype
        self.klass: Optional[str] = None
        self.bin: Optional[int] = None
        self.batch: int = 1
        self.t0 = time.perf_counter()
        self.t1 = 0.0
        self.phases: List[dict] = []   # {name, t0, t1, depth, parent, meta}
        self.notes: List[str] = []     # degradation events, in order
        self.outcome: Optional[str] = None
        self._stack: List[str] = []

    @contextlib.contextmanager
    def phase(self, name: str, **meta):
        """Open one nesting phase span (records on exit, so children
        append before their parents — containment is by interval +
        ``parent`` name)."""
        rec = {"name": name, "t0": time.perf_counter(), "t1": 0.0,
               "depth": len(self._stack),
               "parent": self._stack[-1] if self._stack else None,
               "meta": dict(meta)}
        self._stack.append(name)
        # every surface beneath this phase (driver spans, flight
        # StepEvents, mem samples, num gauges) reads the ambient
        # TraceContext at its own record points — this phase boundary is
        # the ONE propagation choke point (ISSUE 17)
        ctx = _obs_context.TraceContext(
            self.trace_id, tenant=self.tenant, klass=self.klass,
            rid=self.rid, op=self.op)
        try:
            with _obs_context.use_context(ctx):
                yield rec
        finally:
            self._stack.pop()
            rec["t1"] = time.perf_counter()
            self.phases.append(rec)
            # unconditional: a trace only exists because obs was on at
            # admission, and flipping obs off mid-request must not
            # desynchronize the phase/latency surfaces from the exact
            # outcome counts.  The tenant tag joins only when a tenant
            # was declared, so tenant-less request streams keep their
            # exact historical tag sets (and the committed SLA artifact
            # its exact series).
            tt = {"tenant": self.tenant} if self.tenant else {}
            REGISTRY.observe("serve.phase_s", rec["t1"] - rec["t0"],
                             op=self.op, phase=name, **tt)

    def note(self, kind: str) -> None:
        """Record one degradation event (ft_retry / resume /
        growth_retry) — ``terminal()`` attributes the served outcome to
        the last one."""
        if kind not in _NOTE_TERMINAL:
            raise ValueError(f"unknown degradation note {kind!r}")
        self.notes.append(kind)

    def terminal(self) -> str:
        """The served-terminal this request's notes attribute it to."""
        return _NOTE_TERMINAL[self.notes[-1]] if self.notes else "served"

    def finish(self, outcome: str) -> None:
        """Set THE terminal outcome (single-shot), observe the request
        latency tagged (op, class, outcome), and retire the trace to the
        finished stream."""
        if self.outcome is not None:
            raise RuntimeError(
                f"request {self.rid} ({self.op}) already terminal "
                f"({self.outcome!r}); a second outcome {outcome!r} would "
                "double-attribute it")
        if outcome not in TERMINALS:
            raise ValueError(f"unknown terminal outcome {outcome!r}; "
                             f"expected one of {TERMINALS}")
        self.outcome = outcome
        self.t1 = time.perf_counter()
        klass = self.klass or "friendly"
        with _lock:
            key = (self.op, klass, outcome)
            _OUTCOME_COUNTS[key] = _OUTCOME_COUNTS.get(key, 0.0) + 1.0
            _FINISHED.append(self)
            if len(_FINISHED) > _FINISHED_CAP:
                del _FINISHED[0]
        # unconditional (not re-gated on enabled()): the trace exists
        # because obs was on when the request entered, and the latency
        # histogram MUST stay in lockstep with the exact outcome counts
        # above — an obs.disable() racing a request in flight must not
        # leave outcome totals exceeding latency counts
        tt = {"tenant": self.tenant} if self.tenant else {}
        REGISTRY.observe("serve.latency_s", self.t1 - self.t0,
                         op=self.op, klass=klass, outcome=outcome, **tt)
        REGISTRY.counter_add("serve.outcomes", 1.0, op=self.op,
                             klass=klass, outcome=outcome, **tt)
        # live telemetry bus (ISSUE 17): publish the terminated request
        # when the bus module is loaded (sys.modules probe — zero cost
        # for runs that never imported obs.live)
        import sys as _sys

        _live = _sys.modules.get(
            __package__.rsplit(".", 1)[0] + ".obs.live")
        if _live is not None:
            _live.publish("request", {
                "rid": self.rid, "trace_id": self.trace_id,
                "tenant": self.tenant, "op": self.op, "n": self.n,
                "klass": klass, "outcome": outcome,
                "latency_s": self.t1 - self.t0,
                "notes": list(self.notes),
            })


# ---------------------------------------------------------------------------
# None-safe call-site helpers: the Router threads Optional[RequestTrace]
# and these keep the disabled path one `is None` test per site
# ---------------------------------------------------------------------------


def new_trace(op: str, n: int, nb: int, dtype: str,
              tenant: Optional[str] = None) -> Optional[RequestTrace]:
    """A live trace while the obs layer is enabled, else None — the
    zero-allocation disabled contract (which also means NO TraceContext
    is ever entered with obs off: the context spine is invisible to the
    disabled dispatch path)."""
    if not enabled():
        return None
    return RequestTrace(op, n, nb, dtype, tenant=tenant)


def phase(tr: Optional[RequestTrace], name: str, **meta):
    return tr.phase(name, **meta) if tr is not None \
        else contextlib.nullcontext()


@contextlib.contextmanager
def phase_all(trs, name: str, **meta):
    """One phase span opened on every live trace of a stacked group (the
    group shares the compiled dispatch, so it shares the span times)."""
    with contextlib.ExitStack() as stack:
        for tr in trs:
            if tr is not None:
                stack.enter_context(tr.phase(name, **meta))
        yield


def note(tr: Optional[RequestTrace], kind: str) -> None:
    if tr is not None:
        tr.note(kind)


def finish(tr: Optional[RequestTrace], outcome: Optional[str] = None) -> None:
    """Terminate ``tr`` with ``outcome`` (default: the note-attributed
    served terminal)."""
    if tr is not None:
        tr.finish(outcome if outcome is not None else tr.terminal())


def finished_traces() -> List[RequestTrace]:
    with _lock:
        return list(_FINISHED)


def reset() -> None:
    with _lock:
        _FINISHED.clear()
        _OUTCOME_COUNTS.clear()


# ---------------------------------------------------------------------------
# SLA reduction: live registry -> flat RunReport serve-section keys
# ---------------------------------------------------------------------------


def sla_values() -> Dict[str, float]:
    """Reduce the request-latency histograms + exact outcome counts to
    the flat SLA surface of the RunReport ``serve`` section:

    - ``latency_{p50,p95,p99}_{op}_{klass}_s``: reservoir quantiles
      pooled over every outcome of one (op, accuracy class) — wall-clock
      keys, ``--ignore``d by the CI gate (``*latency*_s``);
    - ``latency_count_{op}_{klass}``: observation counts — machine-
      independent under a fixed request stream, gate tight;
    - ``outcome_{outcome}`` / ``outcome_rate_{outcome}``: exact
      attribution totals and their share of all terminated requests —
      the shape/rate keys the gate holds tight.

    Empty (no request terminated) -> {} so an idle run's serve section
    stays exactly the counter zeros."""
    from .metrics import _sanitize_key as _san

    with _lock:
        counts = dict(_OUTCOME_COUNTS)
    vals: Dict[str, float] = {}
    # exact outcome attribution totals + rates
    by_outcome: Dict[str, float] = {}
    for (_op, _kl, outc), c in counts.items():
        by_outcome[outc] = by_outcome.get(outc, 0.0) + c
    total = sum(by_outcome.values())
    for outc, c in sorted(by_outcome.items()):
        vals[f"outcome_{outc}"] = c
        vals[f"outcome_rate_{outc}"] = c / total
    # pooled per-(op, klass) latency quantiles over all outcomes
    from ..obs.metrics import quantile_of

    pools: Dict[Tuple[str, str], dict] = {}
    for series in REGISTRY.histogram_series("serve.latency_s"):
        tags = series["tags"]
        key = (tags.get("op", "?"), tags.get("klass", "?"))
        pool = pools.setdefault(
            key, {"count": 0, "samples": [],
                  "min": float("inf"), "max": float("-inf")})
        pool["count"] += series["count"]
        pool["samples"].extend(series["samples"])
        pool["min"] = min(pool["min"], series["min"])
        pool["max"] = max(pool["max"], series["max"])
    for (op, klass), pool in sorted(pools.items()):
        stem = _san(f"{op}_{klass}")
        vals[f"latency_count_{stem}"] = float(pool["count"])
        for label, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            qv = quantile_of(pool["samples"], q, pool["min"], pool["max"])
            if qv is not None:
                vals[f"latency_{label}_{stem}_s"] = qv
    return vals
