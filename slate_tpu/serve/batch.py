"""Batched small-problem drivers + block-diagonal ragged packing.

The serving workload is a flood of same-shaped small solves; running them
one at a time pays a full dispatch (and, on the mesh, a full shard_map
round) per problem.  The batch drivers here run a STACK of B problems as
one compiled program: the per-problem body is ``lax.map`` over the exact
single-problem kernels (linalg/chol.py, linalg/lu.py, blas3), so batched
results are BITWISE identical to per-problem solves — slicing a stacked
operand and mapping the same kernel reproduces the single trace per
element (vmap is deliberately NOT used: batching the blocked kernels'
dot_generals changes reduction kernels, which breaks bitwise parity and
measured slower on the k-loop-heavy bodies).

``vmap`` over the shard_map mesh kernels is not viable (and the mesh
dispatch is exactly the per-request overhead serving must avoid for
256–4096-sized problems), so the mesh path batches by PACKING instead:
``pack_block_diag`` bins ragged sizes into a few canonical shapes
(pad-to-bin with an identity diagonal, the ``from_dense(diag_pad_one)``
contract) and packs k problems into one block-diagonal operand — one
mesh factorization then factors all k at once, and ``unpack_block_diag``
recovers per-problem solutions.  The blocks never mix: co-packed
operands only ever contribute structural zeros to each other's rows, so
each unpacked solution is BITWISE what the same problem yields packed
alone (asserted in serve.smoke / tests/test_serve.py), and matches the
unpadded per-problem solve to factorization accuracy.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..types import MethodLU, Options, Uplo
from .metrics import serve_count

# Canonical serving bins (the steady-state traffic shape classes): a
# request of size n runs at the smallest bin >= n.  2048/4096 stay listed
# even though CPU smoke never exercises them — the bin set IS the cache
# key vocabulary.
DEFAULT_BINS: Tuple[int, ...] = (128, 256, 512, 1024, 2048, 4096)


def record_batch_size(op: str, count: int) -> None:
    """Observe one dispatched batch's size into the ``serve.batch_size``
    histogram (ISSUE 14: the batching-efficiency distribution beside the
    latency SLA — a p50 batch size of 1 under heavy traffic means the
    binning vocabulary is fragmenting the stream).  No-op while the obs
    layer is off."""
    from ..obs import REGISTRY, enabled

    if enabled():
        REGISTRY.observe("serve.batch_size", float(count), op=op)


# ---------------------------------------------------------------------------
# Stacked batch drivers (bitwise per-problem)
# ---------------------------------------------------------------------------


def posv_batched(a: jax.Array, b: jax.Array):
    """Stacked SPD solve: ``a`` (B, n, n) lower-referenced, ``b``
    (B, n, nrhs).  Returns (x (B, n, nrhs), info (B,)) — row i bitwise
    equals ``chol.posv_array(a[i], b[i])``."""
    from ..linalg.chol import posv_array

    def one(ab):
        x, _f, info = posv_array(ab[0], ab[1], Uplo.Lower)
        return x, info

    return lax.map(one, (a, b))


def potrf_batched(a: jax.Array):
    """Stacked lower Cholesky: (B, n, n) -> (l (B, n, n), info (B,))."""
    from ..linalg.chol import potrf_array

    return lax.map(lambda x: potrf_array(x, Uplo.Lower), a)


def gesv_batched(a: jax.Array, b: jax.Array,
                 method: MethodLU = MethodLU.PartialPiv):
    """Stacked general solve: returns (x (B, n, nrhs), info (B,)) — row i
    bitwise equals ``lu.gesv_array(a[i], b[i], method)``."""
    from ..linalg.lu import gesv_array

    def one(ab):
        x, f = gesv_array(ab[0], ab[1], method)
        return x, f.info

    return lax.map(one, (a, b))


def gemm_batched(alpha, a: jax.Array, b: jax.Array, beta=0.0,
                 c: Optional[jax.Array] = None):
    """Stacked C = alpha A B + beta C over (B, m, k) x (B, k, n)."""
    from ..blas3.blas3 import gemm_array

    if c is None:
        c = jnp.zeros(a.shape[:2] + (b.shape[2],), a.dtype)
    return lax.map(lambda abc: gemm_array(alpha, abc[0], abc[1], beta,
                                          abc[2]), (a, b, c))


BATCHED_DRIVERS = {
    "posv": posv_batched,
    "gesv": gesv_batched,
    "potrf": potrf_batched,
    "gemm": gemm_batched,
}


# ---------------------------------------------------------------------------
# Ragged-size binning + block-diagonal packing
# ---------------------------------------------------------------------------


def bin_for(n: int, bins: Sequence[int] = DEFAULT_BINS) -> Optional[int]:
    """Smallest canonical bin >= n, or None when n exceeds every bin
    (too big to serve through the small-problem path)."""
    for m in sorted(bins):
        if n <= m:
            return int(m)
    return None


def pad_to_bin(a: jax.Array, m: int, factorizable: bool = True) -> jax.Array:
    """Pad an (n, n) operand to (m, m).  ``factorizable`` pads the new
    diagonal with the identity (the ``from_dense(diag_pad_one=True)``
    contract: diag(A, I) factors to diag(L, I) with the pad never mixing
    into data rows); gemm-style operands pad with zeros."""
    n = a.shape[0]
    if n == m:
        return a
    if n > m:
        raise ValueError(f"operand of size {n} exceeds bin {m}")
    out = jnp.zeros((m, m), a.dtype)
    out = out.at[:n, :n].set(a)
    if factorizable:
        out = out.at[jnp.arange(n, m), jnp.arange(n, m)].set(1.0)
    return out


def pad_rhs_to_bin(b: jax.Array, m: int) -> jax.Array:
    """Zero-pad an (n, nrhs) right-hand side to (m, nrhs)."""
    n = b.shape[0]
    if n == m:
        return b
    return jnp.zeros((m,) + b.shape[1:], b.dtype).at[:n].set(b)


def pack_block_diag(
    operands: Sequence[jax.Array], m: int,
    rhs: Optional[Sequence[jax.Array]] = None,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Pack k ragged operands (each n_i <= m) into ONE (k*m, k*m)
    block-diagonal matrix (each block identity-padded to the bin) and,
    when given, stack their right-hand sides into one (k*m, nrhs) RHS.
    One factorization of the packed operand factors all k problems; the
    blocks never interact (their cross terms are structural zeros, and
    partial pivoting cannot select a zero row over a diagonal-1 pad)."""
    k = len(operands)
    dtype = operands[0].dtype
    a = jnp.zeros((k * m, k * m), dtype)
    for i, op in enumerate(operands):
        a = a.at[i * m:(i + 1) * m, i * m:(i + 1) * m].set(
            pad_to_bin(jnp.asarray(op), m))
    if not isinstance(a, jax.core.Tracer):
        # runtime-only counter (the ir.*/num.* convention): a traced
        # packer must not inflate the gated serve section per trace
        serve_count("packed_problems", k)
    if rhs is None:
        return a, None
    nrhs = max(r.shape[1] for r in rhs)
    b = jnp.zeros((k * m, nrhs), dtype)
    for i, r in enumerate(rhs):
        b = b.at[i * m:i * m + r.shape[0], :r.shape[1]].set(jnp.asarray(r))
    return a, b


def unpack_block_diag(
    x: jax.Array, sizes: Sequence[int], m: int,
    nrhs: Optional[Sequence[int]] = None,
) -> List[jax.Array]:
    """Slice per-problem solutions back out of a packed solve's (k*m,
    nrhs) solution stack: block i's rows are [i*m, i*m + sizes[i])."""
    out = []
    for i, n in enumerate(sizes):
        xi = x[i * m:i * m + n]
        if nrhs is not None:
            xi = xi[:, :nrhs[i]]
        out.append(xi)
    return out


def posv_packed_mesh(
    operands: Sequence[jax.Array], rhs: Sequence[jax.Array], mesh,
    nb: Optional[int] = None, bins: Sequence[int] = DEFAULT_BINS,
    opts: Optional[Options] = None,
) -> Tuple[List[jax.Array], jax.Array]:
    """Ragged SPD solves through ONE mesh factorization: bin to the
    largest requested size class, pack block-diagonally, run posv_mesh
    once, unpack.  The mesh-scale twin of the stacked drivers — use it
    when the packed size is big enough to want the 2D grid.

    This IS a serving request path, so unset schedule options resolve
    through the autotuned table (explicit > context > env > tuned >
    auto; serve/table.py): the tuned ``nb`` becomes the mesh tile size
    when ``nb`` is None, and tuned BcastImpl/Lookahead ride ``opts``
    into the mesh k-loops.  Returns (per-problem solutions, info)."""
    from ..parallel.drivers import posv_mesh
    from ..parallel.mesh import mesh_shape
    from ..types import Option, get_option
    from .table import resolve_request_options

    m = bin_for(max(op.shape[0] for op in operands), bins)
    if m is None:
        raise ValueError("packed operand exceeds the largest serving bin")
    record_batch_size("posv_packed", len(operands))
    a, b = pack_block_diag(operands, m, rhs)
    merged = resolve_request_options(
        opts, "posv", a.shape[0], str(a.dtype), mesh_shape(mesh))
    if nb is None:
        nb = int(get_option(merged, Option.BlockSize, default=64))
    x, info = posv_mesh(a, b, mesh, nb, merged)
    return unpack_block_diag(x, [op.shape[0] for op in operands], m,
                             [r.shape[1] for r in rhs]), info
