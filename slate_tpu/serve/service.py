"""The serving front door: ``python -m slate_tpu.serve.service``.

Wires the ISSUE 19 pieces into one long-running process:

- a **Router** (the PR 11 admission → class → cached-dispatch policy),
- a **BatchQueue** in front of it (batch windows, per-tenant HBM
  budgets, weighted-DRR dequeue — serve/queue.py), pumped by a worker
  thread on the wall clock,
- a **ServiceController** stepping the SLA control loop
  (serve/controller.py) between pumps,
- a stdlib-http front end: ``POST /solve`` submits one request (JSON
  ``{"op", "a", "b", "tenant"}``) and blocks its connection thread on
  the ticket — concurrent callers' requests coalesce into shared batch
  windows, which is the entire point — plus ``GET /queue.json`` /
  ``/healthz`` / ``/metrics`` delegating to the obs.live surface.

Deliberately stdlib-only (``http.server``, like obs/live.py): the
repo's serving story must not grow a web-framework dependency to be
demonstrable.  A real deployment would put this behind a proper ASGI
gateway; every piece below the HTTP skin (queue, ledger, controller)
is transport-agnostic and is what such a gateway would drive.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import traceback
from typing import Dict, Optional

from ..types import SlateError
from .budget import BudgetLedger
from .controller import ServiceController
from .metrics import serve_count
from .queue import BatchQueue
from .router import Router


class Service:
    """Queue + worker + controller around one Router."""

    def __init__(self, router: Optional[Router] = None, *,
                 max_batch: int = 8, window_s: float = 0.005,
                 budgets: Optional[Dict[str, int]] = None,
                 weights: Optional[Dict[str, float]] = None,
                 dispatch: str = "stacked",
                 controller_every: int = 8,
                 request_timeout_s: float = 60.0,
                 name: str = "service", **controller_kw) -> None:
        self.router = router if router is not None else Router()
        self.queue = BatchQueue(
            self.router, max_batch=max_batch, window_s=window_s,
            ledger=BudgetLedger(budgets, weights), dispatch=dispatch,
            name=name)
        self.controller = ServiceController(self.queue, **controller_kw)
        self.request_timeout_s = float(request_timeout_s)
        self._controller_every = int(controller_every)
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._worker is not None:
            return
        self._stop.clear()
        self._worker = threading.Thread(
            target=self._run, name="slate-serve-worker", daemon=True)
        self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
            self._worker = None
        self.queue.drain()
        self.queue.close()

    def _run(self) -> None:
        ticks = 0
        while not self._stop.is_set():
            try:
                self.queue.pump()
            except SlateError:
                # a failed window already settled its tickets/traces —
                # the worker must outlive any one bad operand
                pass
            except Exception:
                # ANY other escape (a malformed operand that slipped
                # admission, a backend error) must not kill the worker:
                # a dead pump hangs every queued and future request
                # until its ticket timeout — a one-request DoS
                serve_count("queue_pump_errors")
                traceback.print_exc(file=sys.stderr)
            ticks += 1
            if ticks % self._controller_every == 0:
                try:
                    self.controller.step()
                except Exception:
                    serve_count("queue_pump_errors")
                    traceback.print_exc(file=sys.stderr)
            # park for a fraction of the window so T-expiry is observed
            # promptly without spinning
            self._stop.wait(min(self.queue.window_s / 4.0, 0.002))

    # -- request entry -----------------------------------------------------

    def solve(self, op: str, a, b, tenant: Optional[str] = None):
        """Submit one request and block until its window dispatched (the
        per-connection path; concurrent callers share windows)."""
        ticket = self.queue.submit(op, a, b, tenant=tenant)
        return ticket.wait(timeout=self.request_timeout_s)


# ---------------------------------------------------------------------------
# the HTTP skin
# ---------------------------------------------------------------------------


def _make_handler(service: Service):
    from http.server import BaseHTTPRequestHandler

    import jax.numpy as jnp

    from ..obs import live as _live

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def _send(self, code: int, ctype: str, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, doc: dict) -> None:
            self._send(code, "application/json",
                       json.dumps(doc, default=str).encode())

        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path == "/queue.json":
                self._send_json(200, _live.queue_snapshot())
            elif self.path == "/healthz":
                qs = _live.queue_snapshot()["queues"]
                body = "ok\nqueues {} depth {} open_windows {}\n".format(
                    len(qs),
                    sum(s.get("depth", 0) for s in qs.values()),
                    sum(s.get("open_windows", 0) for s in qs.values()))
                self._send(200, "text/plain", body.encode())
            elif self.path in ("/metrics", "/"):
                self._send(200, "text/plain; version=0.0.4",
                           _live.prometheus_text().encode())
            else:
                self._send(404, "text/plain", b"not found\n")

        def do_POST(self):  # noqa: N802 (http.server API)
            if self.path != "/solve":
                self._send(404, "text/plain", b"not found\n")
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                doc = json.loads(self.rfile.read(length).decode())
                op = doc["op"]
                a = jnp.asarray(doc["a"], dtype=jnp.float64)
                b = jnp.asarray(doc["b"], dtype=jnp.float64)
                tenant = doc.get("tenant")
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._send_json(400, {"error": f"bad request: {e}"})
                return
            try:
                x = service.solve(op, a, b, tenant=tenant)
            except SlateError as e:
                # budget refusals are the retry-later class; everything
                # else in the SlateError taxonomy is the caller's operand
                code = 429 if "budget" in str(e) else 422
                self._send_json(code, {"error": str(e)})
                return
            except TimeoutError as e:
                self._send_json(504, {"error": str(e)})
                return
            self._send_json(200, {"x": jnp.asarray(x).tolist(),
                                  "tenant": tenant})

    return Handler


def start_http(service: Service, port: int = 0, host: str = "127.0.0.1"):
    """Serve the front end on a daemon thread; returns ``(server,
    thread, port)`` — the obs.live ``start_server`` contract."""
    from http.server import ThreadingHTTPServer

    srv = ThreadingHTTPServer((host, port), _make_handler(service))
    srv.daemon_threads = True
    th = threading.Thread(target=srv.serve_forever,
                          name="slate-serve-http", daemon=True)
    th.start()
    return srv, th, srv.server_address[1]


def _parse_kv(pairs, cast):
    out = {}
    for item in pairs or ():
        name, _, val = item.partition("=")
        if not name or not val:
            raise SystemExit(f"expected TENANT=VALUE, got {item!r}")
        out[name] = cast(val)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m slate_tpu.serve.service", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--port", type=int, default=9465,
                    help="front-end port (default 9465; 0 = ephemeral)")
    ap.add_argument("--max-batch", type=int, default=8,
                    help="batch-window fill target B (default 8)")
    ap.add_argument("--window-ms", type=float, default=5.0,
                    help="batch-window deadline T in ms (default 5)")
    ap.add_argument("--budget", action="append", metavar="TENANT=BYTES",
                    help="per-tenant HBM budget (repeatable)")
    ap.add_argument("--weight", action="append", metavar="TENANT=W",
                    help="per-tenant DRR weight (repeatable)")
    ap.add_argument("--dispatch", choices=("stacked", "packed"),
                    default="stacked")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_enable_x64", True)  # f64 serving classes
    from .. import obs
    from ..obs import live as _live, span as _span

    obs.enable()
    _span.enable()
    try:
        service = Service(
            max_batch=args.max_batch, window_s=args.window_ms / 1000.0,
            budgets=_parse_kv(args.budget, int),
            weights=_parse_kv(args.weight, float),
            dispatch=args.dispatch)
    except ValueError as e:   # e.g. --weight t=0
        raise SystemExit(str(e))
    service.start()
    srv, th, port = start_http(service, args.port)
    print(f"slate_tpu.serve.service: POST /solve, GET /queue.json "
          f"/healthz /metrics on http://127.0.0.1:{port} "
          f"(B={args.max_batch}, T={args.window_ms}ms)", file=sys.stderr)
    try:
        th.join()
    except KeyboardInterrupt:
        srv.shutdown()
        service.stop()
    return 0


if __name__ == "__main__":
    # run as ``__main__``, re-enter through the canonical import so the
    # queue registry / bus keyed on real module names see ONE instance
    # (the obs.live idiom)
    from slate_tpu.serve import service as _canonical

    sys.exit(_canonical.main())
