"""slate_tpu — TPU-native distributed dense linear algebra.

A from-scratch JAX/XLA/Pallas framework with the capabilities of SLATE
(the ECP dense linear algebra library; reference at /root/reference,
public umbrella header include/slate/slate.hh).  Tile-level compute runs as
XLA/Pallas kernels on HBM-resident arrays; distribution is jax.sharding over
a TPU mesh with XLA collectives over ICI replacing MPI.

Public surface mirrors slate.hh: matrix types, level-3 BLAS, linear system
solvers (Cholesky / LU with four pivoting strategies / mixed precision /
symmetric-indefinite / band), QR/LQ least squares, two-stage eigensolvers and
SVD, norms and condition estimators, plus a simplified verb API
(simplified_api.hh analog) in ``slate_tpu.api``.
"""

from .types import (
    Diag,
    GridOrder,
    Layout,
    MethodEig,
    MethodGels,
    MethodGemm,
    MethodHemm,
    MethodLU,
    MethodSVD,
    MethodTrsm,
    Norm,
    NormScope,
    Op,
    Option,
    Pivot,
    Side,
    SlateError,
    Target,
    Uplo,
)
from .core import (
    BandMatrix,
    BaseMatrix,
    HermitianBandMatrix,
    HermitianMatrix,
    Matrix,
    SymmetricMatrix,
    TrapezoidMatrix,
    TriangularBandMatrix,
    TriangularMatrix,
)
from .blas3 import (
    gbmm,
    gemm,
    hbmm,
    hemm,
    her2k,
    herk,
    symm,
    syr2k,
    syrk,
    tbsm,
    trmm,
    trsm,
)

from . import api, ft, linalg, obs, ops, parallel
from .linalg import (
    bdsqr,
    gecondest,
    gels_array,
    geqrf_array,
    gesv_array,
    getrf_array,
    heev_array,
    hegv_array,
    hesv_array,
    norm,
    pocondest,
    posv_array,
    potrf_array,
    stedc,
    steqr,
    sterf,
    svd_array,
    trcondest,
)

__version__ = "0.1.0"
