"""SVD: two-stage reduction ge2tb -> tb2bd -> bidiagonal solve + lifts.

Analogues of the reference chain (SURVEY §3.5, src/svd.cc:215-330):
``src/ge2tb.cc`` (general -> upper triangular band via alternating QR/LQ
block panels), ``src/tb2bd.cc`` (band -> bidiagonal bulge chasing),
LAPACK ``bdsqr`` (bidiagonal SVD), back-transforms ``src/unmbr_ge2tb.cc`` /
``src/unmbr_tb2bd.cc``.

TPU design:
- ge2tb is all BLAS-3 (panel geqrf/gelqf + compact-WY applications on the
  MXU), mirroring the reference's GPU-capable stage 1.
- tb2bd is the sequential bulge chase: nested (sweep, hop) fori_loops, one
  right + one left Householder per hop on static 3w windows (cf. eig.hb2st).
- the bidiagonal solve is formulated TPU-natively through the Golub-Kahan
  tridiagonal embedding: T_GK = perfect-shuffle of [[0, B],[B^H, 0]] is a
  real symmetric tridiagonal with zero diagonal and off-diagonals
  (d_0, e_0, d_1, e_1, ...), whose positive eigenpairs are (sigma_i,
  (u_i, v_i) interleaved / sqrt 2) — solved by the stedc divide & conquer
  (tridiag.py) whose merge matmuls ride the MXU, replacing the reference's
  sequential LAPACK bdsqr QR iteration.
"""

from __future__ import annotations

from ..obs import instrument

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.matmul import matmul
from .eig import _larfg_masked
from .tridiag import _STEDC_STAGE_ABOVE, stedc, stedc_staged, sterf

Array = jax.Array

_SVD_NB = 32


class Ge2tbFactors(NamedTuple):
    """Band + stage-1 reflectors (reference U/V T-matrix families,
    ge2tb.cc:60-100).  Reflectors are stacked in GLOBAL coordinates
    (``vq[k]`` zero above row k*nb; ``vl[k]`` zero above column (k+1)*nb,
    stored as column vectors of A^H) so the whole reduction and both
    back-transforms trace as single fori_loop programs."""

    band: Array  # (m, n) with upper-band content (bandwidth nb above diag)
    vq: Array  # (K, mp2, nb) left (U-side) reflectors
    tq: Array  # (K, nb, nb)
    vl: Array  # (K, np2, nb) right (V-side) reflectors (zeros = dead panel)
    tl: Array  # (K, nb, nb)
    nb: int


def ge2tb(a: Array, nb: int = _SVD_NB, segments: int = 1) -> Ge2tbFactors:
    """General (m >= n) -> upper triangular band, alternating QR/LQ panels.

    One lax.fori_loop over block columns with static shapes: per step an
    offset-pivot panel QR of the (masked) full-height block column, a
    global masked compact-WY application to the trailing columns, then the
    mirrored LQ step on the block row (via QR of its conjugate transpose).
    LQ steps that would destroy the final band (remaining width <= 1) are
    masked to identity, matching the unrolled form's skip.

    ``segments > 1`` runs the block loop as that many donated jit
    programs over k-ranges (call EAGERLY to benefit) — the chip escape
    hatch for sizes where one program's serial step chain outruns the
    TPU worker's watchdog (cf. eig._wavefront_chase_segmented).
    """
    from .qr import _larft_v, _panel_qr_offset

    m, n = a.shape
    if m < n:
        raise ValueError(f"ge2tb requires m >= n, got {a.shape}")
    nblocks = -(-n // nb)
    mp2 = max(m, (nblocks + 1) * nb)
    np2 = max(n, (nblocks + 1) * nb)
    ap = jnp.pad(a, ((0, mp2 - m), (0, np2 - n)))
    rows = jnp.arange(mp2)
    cols = jnp.arange(np2)

    def body(k, carry):
        ap, vqs, tqs, vls, tls = carry
        j0 = k * nb
        j1 = j0 + nb
        # ---- QR panel: eliminate below-diagonal of block column k
        colblk = jax.lax.dynamic_slice(ap, (0, j0), (mp2, nb))
        masked = jnp.where((rows >= j0)[:, None], colblk, 0)
        r_a, vq, tauq = _panel_qr_offset(masked, j0)
        tq = _larft_v(vq, tauq)
        # apply Q^H to trailing columns (>= j1) before writing R back
        w1 = matmul(jnp.conj(vq).T, ap)
        upd = matmul(vq, matmul(jnp.conj(tq).T, w1)).astype(ap.dtype)
        ap = ap - upd * (cols >= j1)[None, :].astype(ap.dtype)
        newcols = jnp.where((rows >= j0)[:, None], r_a, colblk)
        ap = jax.lax.dynamic_update_slice(ap, newcols, (0, j0))
        # ---- LQ panel on block row k: eliminate right of the superdiagonal
        # block, via QR of the conj-transposed row block
        lq_active = j1 < n - 1
        rowblk = jax.lax.dynamic_slice(ap, (j0, 0), (nb, np2))
        rowblkh = jnp.conj(rowblk).T  # (np2, nb)
        maskedh = jnp.where((cols >= j1)[:, None] & lq_active, rowblkh, 0)
        l_a, vl, taul = _panel_qr_offset(maskedh, j1)
        tl = _larft_v(vl, taul)
        vl = vl * jnp.asarray(lq_active, ap.dtype)
        tl = tl * jnp.asarray(lq_active, ap.dtype)
        # apply from the right to rows >= j1: A <- A (I - Vl Tl Vl^H)
        w2 = matmul(ap, vl)
        upd = matmul(matmul(w2, tl), jnp.conj(vl).T).astype(ap.dtype)
        ap = ap - upd * (rows >= j1)[:, None].astype(ap.dtype)
        newrows = jnp.where(
            ((cols >= j1) & lq_active)[None, :], jnp.conj(l_a).T, rowblk
        )
        ap = jax.lax.dynamic_update_slice(ap, newrows, (j0, 0))
        return (
            ap,
            vqs.at[k].set(vq),
            tqs.at[k].set(tq),
            vls.at[k].set(vl),
            tls.at[k].set(tl),
        )

    carry0 = (
        ap,
        jnp.zeros((nblocks, mp2, nb), a.dtype),
        jnp.zeros((nblocks, nb, nb), a.dtype),
        jnp.zeros((nblocks, np2, nb), a.dtype),
        jnp.zeros((nblocks, nb, nb), a.dtype),
    )
    if segments <= 1:
        ap, vqs, tqs, vls, tls = jax.lax.fori_loop(0, nblocks, body, carry0)
    else:
        import functools

        # lo/hi stay DYNAMIC so every segment reuses one compiled program
        # (cf. _chase_apply_staged's j0; ragged tails included)
        @functools.partial(jax.jit, donate_argnums=0)
        def seg(carry, lo, hi):
            return jax.lax.fori_loop(lo, hi, body, carry)

        bounds = [nblocks * i // segments for i in range(segments)] + [nblocks]
        carry = carry0
        for i in range(segments):
            if bounds[i] < bounds[i + 1]:
                carry = seg(carry, bounds[i], bounds[i + 1])
        ap, vqs, tqs, vls, tls = carry
    return Ge2tbFactors(ap[:m, :n], vqs, tqs, vls, tls, nb)


def unmbr_ge2tb_u(f: Ge2tbFactors, c: Array) -> Array:
    """C <- Q C for the stage-1 left factor (unmbr_ge2tb U side)."""
    nsteps, mp2, _ = f.vq.shape
    n = c.shape[0]
    cp = jnp.pad(c, ((0, mp2 - n),) + ((0, 0),) * (c.ndim - 1))

    def body(i, cp):
        k = nsteps - 1 - i
        v, t = f.vq[k], f.tq[k]
        return cp - matmul(v, matmul(t, matmul(jnp.conj(v).T, cp))).astype(cp.dtype)

    cp = jax.lax.fori_loop(0, nsteps, body, cp)
    return cp[:n]


def unmbr_ge2tb_v(f: Ge2tbFactors, c: Array) -> Array:
    """C <- P C for the stage-1 right factor (V side; P from the LQ
    panels, applied as left ops on V columns).  Dead panels carry zero
    reflectors and apply as identity."""
    nsteps, np2, _ = f.vl.shape
    n = c.shape[0]
    cp = jnp.pad(c, ((0, np2 - n),) + ((0, 0),) * (c.ndim - 1))

    def body(i, cp):
        k = nsteps - 1 - i
        v, t = f.vl[k], f.tl[k]
        return cp - matmul(v, matmul(t, matmul(jnp.conj(v).T, cp))).astype(cp.dtype)

    cp = jax.lax.fori_loop(0, nsteps, body, cp)
    return cp[:n]


# ---------------------------------------------------------------------------
# Stage 2: band -> bidiagonal (src/tb2bd.cc)
# ---------------------------------------------------------------------------


class Tb2bdFactors(NamedTuple):
    """Bulge-chase reflectors: left (U-side) and right (V-side) per
    (sweep, hop)."""

    lvs: Array  # (nsweeps, max_hops, w)
    ltaus: Array
    rvs: Array
    rtaus: Array
    w: int
    n: int


def tb2bd(band: Array, w: int = _SVD_NB, segments: int = 1, diag_storage: bool = False):
    """Upper-band (bandwidth w) square matrix (or its diagonal-band
    storage (n, 4w) when ``diag_storage``) -> upper bidiagonal (d, e),
    plus reflectors.  Chases each row's out-of-band tail down the band with
    alternating right/left Householders.

    Wavefront pipelining (reference P7, tb2bd.cc): the schedule and
    gather/scatter harness are eig._wavefront_chase_band; per hop the in-block
    update is one right Householder eliminating a row tail followed by one
    left Householder eliminating the created column bulge."""
    from .eig import _chase_frame, _wavefront_chase_segmented

    n = band.shape[0]
    dtype = band.dtype
    cplx = jnp.issubdtype(dtype, jnp.complexfloating)
    pad = 4 * w
    ba = _chase_frame(band, w, pad, diag_storage)
    nsweeps = max(n - 1, 1)
    max_hops = max(1, -(-(n - 1) // w))
    lvs = jnp.zeros((nsweeps, max_hops, w), dtype)
    ltaus = jnp.zeros((nsweeps, max_hops), dtype)
    rvs = jnp.zeros((nsweeps, max_hops, w), dtype)
    rtaus = jnp.zeros((nsweeps, max_hops), dtype)

    # idx0 = in-block row whose tail the right reflector eliminates: the
    # first hop of a sweep reads row j (= c0-1), later hops row c0-w
    def one(block, ri, na):
        # --- right Householder: W <- W G, G s.t. (x G)[1:] = 0 ---
        xr = lax.dynamic_slice(block, (ri, w), (1, w))[0]
        vr, taur = _larfg_masked(jnp.conj(xr), na)
        colb = block[:, w : 2 * w]
        colb = colb - jnp.conj(taur) * jnp.outer(
            matmul(colb, vr[:, None])[:, 0], jnp.conj(vr)
        )
        block = block.at[:, w : 2 * w].set(colb)
        # --- left Householder: eliminate column c0 below diag ---
        xl = block[w : 2 * w, w]
        vl, taul = _larfg_masked(xl, na)
        mid = block[w : 2 * w, :]
        mid = mid - taul * jnp.outer(vl, matmul(jnp.conj(vl)[None, :], mid)[0])
        block = block.at[w : 2 * w, :].set(mid)
        return block, vr, taur, vl, taul

    if n > 1:
        ba, rvs, rtaus, lvs, ltaus = _wavefront_chase_segmented(
            ba, n, w, nsweeps, max_hops, one, (rvs, rtaus, lvs, ltaus), segments
        )
    d = ba[pad : pad + n, 2 * w]
    e = ba[pad : pad + n - 1, 2 * w + 1] if n > 1 else jnp.zeros((0,), dtype)
    f = Tb2bdFactors(lvs, ltaus, rvs, rtaus, w, n)

    # phase-normalize to a real nonnegative bidiagonal: B' = Pu^H B Pv
    if cplx:
        def phase_step(carry, de):
            pu_prev_irrelevant, pv_i = carry
            di, ei = de
            s_d = di * pv_i
            pu_i = jnp.where(jnp.abs(s_d) == 0, 1.0 + 0j, s_d / jnp.abs(s_d))
            s_e = jnp.conj(pu_i) * ei
            pv_n = jnp.where(jnp.abs(s_e) == 0, 1.0 + 0j, jnp.conj(s_e / jnp.abs(s_e)))
            return (pu_i, pv_n), (pu_i, pv_i)

        e_ext = jnp.concatenate([e, jnp.zeros((1,), dtype)])
        (_, _), (pu, pv) = lax.scan(
            phase_step, (jnp.ones((), dtype), jnp.ones((), dtype)), (d, e_ext)
        )
        d_r = jnp.real(jnp.conj(pu) * d * pv)
        e_r = jnp.real(jnp.conj(pu[:-1]) * e * pv[1:]) if n > 1 else jnp.zeros((0,), jnp.real(d).dtype)
    else:
        pu = jnp.ones((n,), dtype)
        pv = jnp.ones((n,), dtype)
        d_r = jnp.real(d)
        e_r = jnp.real(e)
    return d_r, e_r, f, pu, pv


def unmbr_tb2bd_u(f: Tb2bdFactors, z: Array) -> Array:
    """Z <- (stage-2 left basis) Z: H_i^H applied reverse-chronologically."""
    return _apply_chase(f, z, left=True)


def unmbr_tb2bd_v(f: Tb2bdFactors, z: Array) -> Array:
    """Z <- (stage-2 right basis) Z: G_i applied reverse-chronologically."""
    return _apply_chase(f, z, left=False)


def _apply_chase(f: Tb2bdFactors, z: Array, left: bool) -> Array:
    """Batched sweep application (eig._chase_sweep_apply): left basis
    applies H^H (conj tau); right applies G = I - conj(tau) v v^H — the
    same coefficient, so both share the adjoint=False path."""
    from .eig import _chase_sweep_apply

    vs = f.lvs if left else f.rvs
    taus = f.ltaus if left else f.rtaus
    return _chase_sweep_apply(vs, taus, z, f.n, f.w, adjoint=False)


# ---------------------------------------------------------------------------
# Bidiagonal SVD via the Golub-Kahan tridiagonal (bdsqr equivalent)
# ---------------------------------------------------------------------------


def bdsqr(d: Array, e: Array, want_vectors: bool = True):
    """SVD of the real upper bidiagonal (d, e).  Returns (s descending,
    U, V) or just s.  Golub-Kahan embedding + stedc (module docstring).

    Accuracy note: values and residuals are machine precision; U/V
    orthogonality degrades as ~eps/sigma for singular values near zero
    (the +/-sigma GK eigenpairs nearly collide).  Matches the capability
    envelope of normal-equation-free dense SVD; callers needing orthonormal
    null-space bases should re-orthogonalize the trailing block."""
    n = d.shape[0]
    rdt = d.dtype
    if n == 1:
        s = jnp.abs(d)
        sgn = jnp.where(d[0] >= 0, 1.0, -1.0)
        if not want_vectors:
            return s
        return s, sgn * jnp.ones((1, 1), rdt), jnp.ones((1, 1), rdt)
    gk_e = jnp.zeros((2 * n - 1,), rdt)
    gk_e = gk_e.at[0::2].set(d)
    if n > 1:
        gk_e = gk_e.at[1::2].set(e)
    gk_d = jnp.zeros((2 * n,), rdt)
    if not want_vectors:
        w = sterf(gk_d, gk_e)
        return jnp.flip(jnp.maximum(w[n:], 0.0))
    if 2 * n > _STEDC_STAGE_ABOVE:
        # level-staged dispatch (no-op under an outer jit, where the
        # stages inline; call bdsqr eagerly to benefit — svd_staged does)
        w, z = stedc_staged(gk_d, gk_e)
    else:
        w, z = stedc(gk_d, gk_e)
    # positive eigenvalues ascending are the last n; descend for SVD order
    sel = jnp.arange(2 * n - 1, n - 1, -1)
    s = jnp.maximum(w[sel], 0.0)
    zq = z[:, sel] * jnp.sqrt(jnp.asarray(2.0, rdt))
    # perfect shuffle: rows 0,2,4,... are V components, 1,3,5,... are U
    v = zq[0::2, :]
    u = zq[1::2, :]
    return s, u, v


# ---------------------------------------------------------------------------
# Driver (src/svd.cc)
# ---------------------------------------------------------------------------


def svd_staged(a: Array, want_vectors: bool = True, nb: int = _SVD_NB):
    """svd with each phase as its own XLA program (cf. eig.heev_staged:
    one fused program for ge2tb | tb2bd | solve exceeds the TPU runtime's
    per-program ceiling near n = 8192, while each phase alone is fine)."""
    m, n = a.shape
    if m < n:
        if not want_vectors:
            return svd_staged(jnp.conj(a).T, False, nb)
        u, s, vh = svd_staged(jnp.conj(a).T, True, nb)
        return jnp.conj(vh).T, s, jnp.conj(u).T
    from .eig import _chase_segments

    segs = _chase_segments(n)
    if segs > 1:  # segmented ge2tb must dispatch eagerly
        f1 = ge2tb(a, nb, segments=segs)
    else:
        f1 = jax.jit(ge2tb, static_argnums=1)(a, nb)
    band = f1.band[:n, :n]
    if segs > 1:  # segmented chase must dispatch eagerly
        d, e, f2, pu, pv = tb2bd(band, nb, segments=segs)
    else:
        d, e, f2, pu, pv = jax.jit(tb2bd, static_argnums=(1, 2))(band, nb)
    if not want_vectors:
        return jax.jit(bdsqr, static_argnums=2)(d, e, False)
    from .eig import _chase_apply_staged

    if 2 * n > _STEDC_STAGE_ABOVE:
        # eager: bdsqr internally level-stages its stedc at this scale
        s, ub, vb = bdsqr(d, e)
    else:
        s, ub, vb = jax.jit(bdsqr)(d, e)
    dtype = a.dtype
    # sweep-block staged applies (the fused apply outruns the worker
    # watchdog at 16384)
    u = _chase_apply_staged(f2.lvs, f2.ltaus, pu[:, None] * ub.astype(dtype), n, nb, False)
    u_full = jnp.zeros((m, n), dtype).at[:n].set(u)
    u_full = jax.jit(unmbr_ge2tb_u)(f1, u_full)
    v = _chase_apply_staged(f2.rvs, f2.rtaus, pv[:, None] * vb.astype(dtype), n, nb, False)
    v = jax.jit(unmbr_ge2tb_v)(f1, v)
    return u_full, s, jnp.conj(v).T


@instrument("svd_array")
def svd_array(
    a: Array,
    want_vectors: bool = True,
    nb: int = _SVD_NB,
):
    """Singular value decomposition (slate::svd): returns s (descending)
    or (U_thin, s, Vh_thin)."""
    m, n = a.shape
    dtype = a.dtype
    if m < n:
        # work on A^H and swap factors
        if not want_vectors:
            return svd_array(jnp.conj(a).T, False, nb)
        u, s, vh = svd_array(jnp.conj(a).T, True, nb)
        return jnp.conj(vh).T, s, jnp.conj(u).T
    f1 = ge2tb(a, nb)
    band = f1.band[:n, :n]
    d, e, f2, pu, pv = tb2bd(band, nb)
    if not want_vectors:
        return bdsqr(d, e, want_vectors=False)
    s, ub, vb = bdsqr(d, e, want_vectors=True)
    k = n
    # lift U: phases, stage-2 left, embed to m rows, stage-1 Q panels
    u = ub.astype(dtype)
    u = pu[:, None] * u
    u = unmbr_tb2bd_u(f2, u)
    u_full = jnp.zeros((m, k), dtype).at[:n].set(u)
    u_full = unmbr_ge2tb_u(f1, u_full)
    # lift V: phases, stage-2 right, stage-1 LQ panels
    v = vb.astype(dtype)
    v = pv[:, None] * v
    v = unmbr_tb2bd_v(f2, v)
    v = unmbr_ge2tb_v(f1, v)
    return u_full, s, jnp.conj(v).T
