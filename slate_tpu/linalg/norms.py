"""Norm drivers and condition estimators.

Analogues of ``src/norm.cc`` (one/inf/max/fro over every matrix type, with
``NormScope::{Matrix,Rows,Columns}``), ``src/colNorms.cc``, and the
condition estimators ``src/gecondest.cc`` / ``src/pocondest.cc`` /
``src/trcondest.cc`` built on the Higham-Tisseur 1-norm estimator
(``src/internal/internal_norm1est.cc``).

The reference computes per-tile partial norms then MPI_Allreduce's
(internal_genorm.cc + norm.cc); under XLA the whole reduction is one fused
program (and on a sharded array GSPMD inserts the all-reduce over ICI).
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

from ..core.matrix import (
    BandMatrix,
    BaseMatrix,
    HermitianBandMatrix,
    HermitianMatrix,
    Matrix,
    SymmetricMatrix,
    TrapezoidMatrix,
    TriangularBandMatrix,
    TriangularMatrix,
)
from ..ops import tile_ops
from ..types import Diag, Norm, NormScope, Op, Uplo

ArrayLike = Union[jax.Array, BaseMatrix]


def norm(norm_type: Norm, a: ArrayLike, scope: NormScope = NormScope.Matrix) -> jax.Array:
    """slate::norm (src/norm.cc): dispatch on matrix type."""
    if isinstance(a, HermitianBandMatrix):
        kd = a.kl if a.uplo == Uplo.Lower else a.ku
        return tile_ops.hbnorm(norm_type, a.data, a.uplo, kd)
    if isinstance(a, TriangularBandMatrix):
        # band content is already band-projected in storage; triangle norm
        return tile_ops.trnorm(norm_type, a.data, a.uplo, a.diag)
    if isinstance(a, BandMatrix):
        return tile_ops.gbnorm(norm_type, a.data, a.kl, a.ku)
    if isinstance(a, (HermitianMatrix, SymmetricMatrix)):
        return tile_ops.henorm(norm_type, a.data, a.uplo)
    if isinstance(a, (TriangularMatrix, TrapezoidMatrix)):
        return tile_ops.trnorm(norm_type, a.data, a.uplo, a.diag)
    ad = a.array if isinstance(a, BaseMatrix) else jnp.asarray(a)
    return tile_ops.genorm(norm_type, ad, scope)


def col_norms(a: ArrayLike) -> jax.Array:
    """slate::colNorms (src/colNorms.cc): per-column max-abs."""
    ad = a.array if isinstance(a, BaseMatrix) else jnp.asarray(a)
    return tile_ops.col_norms(ad)


# ---------------------------------------------------------------------------
# Higham-Tisseur 1-norm estimator (internal_norm1est.cc; LAPACK xLACN2)
# ---------------------------------------------------------------------------


def norm1est(
    solve: Callable[[jax.Array], jax.Array],
    solve_h: Callable[[jax.Array], jax.Array],
    n: int,
    dtype=jnp.float64,
    iters: int = 5,
) -> jax.Array:
    """Estimate ||M||_1 given only products y = M x (``solve``) and
    z = M^H x (``solve_h``) — used with M = A^-1 for condition numbers.

    The LAPACK xLACN2 power iteration on the 1-norm dual, with the final
    alternating-sign probe; runs the fixed LAPACK itmax (5) without early
    exit (convergence masking keeps shapes static under jit)."""
    cplx = jnp.issubdtype(dtype, jnp.complexfloating)

    def sign_of(y):
        if cplx:
            ay = jnp.abs(y)
            return jnp.where(ay == 0, 1.0 + 0j, y / jnp.where(ay == 0, 1, ay)).astype(dtype)
        return jnp.where(y >= 0, 1.0, -1.0).astype(dtype)

    x = jnp.full((n,), 1.0 / n, dtype)
    est = jnp.zeros((), jnp.float64)
    for _ in range(iters):
        y = solve(x)
        est = jnp.maximum(est, jnp.sum(jnp.abs(y)).astype(jnp.float64))
        z = solve_h(sign_of(y))
        j = jnp.argmax(jnp.abs(z))
        x = jnp.zeros((n,), dtype).at[j].set(1.0)
    # alternating-sign safeguard vector (xLACN2 final stage)
    v = ((-1.0) ** jnp.arange(n)).astype(dtype) * (1.0 + jnp.arange(n) / max(n - 1, 1)).astype(dtype)
    y = solve(v)
    alt = 2.0 * jnp.sum(jnp.abs(y)).astype(jnp.float64) / (3.0 * n)
    return jnp.maximum(est, alt)


def _recondest(anorm, ainv_norm):
    """1/cond = 1/(||A|| * ||A^-1||), guarded like the reference
    (gecondest.cc returns 0 on overflow)."""
    denom = anorm * ainv_norm
    return jnp.where(denom > 0, 1.0 / denom, jnp.zeros_like(denom))


def gecondest(norm_type: Norm, lu_factors, anorm) -> jax.Array:
    """slate::gecondest: reciprocal condition estimate from LU factors.
    Inf-norm routes through A^H like the reference (norm1est on A^-H)."""
    from .lu import getrs_array

    n = lu_factors.lu.shape[0]
    dtype = lu_factors.lu.dtype
    fwd = lambda x: getrs_array(lu_factors, x[:, None])[:, 0]
    adj = lambda x: getrs_array(lu_factors, x[:, None], Op.ConjTrans)[:, 0]
    if norm_type == Norm.One:
        ainv = norm1est(fwd, adj, n, dtype)
    elif norm_type == Norm.Inf:
        ainv = norm1est(adj, fwd, n, dtype)  # ||A^-1||_inf = ||A^-H||_1
    else:
        raise ValueError("gecondest: only One/Inf norms (gecondest.cc)")
    return _recondest(jnp.asarray(anorm, jnp.float64), ainv)


def pocondest(norm_type: Norm, factor, anorm) -> jax.Array:
    """slate::pocondest: SPD reciprocal condition from the Cholesky factor."""
    from .chol import potrs_array

    f = factor.data if isinstance(factor, BaseMatrix) else jnp.asarray(factor)
    uplo = factor.uplo if isinstance(factor, BaseMatrix) else Uplo.Lower
    n = f.shape[0]
    solve = lambda x: potrs_array(f, x[:, None], uplo)[:, 0]
    ainv = norm1est(solve, solve, n, f.dtype)  # A^-1 Hermitian: 1 == inf norm
    return _recondest(jnp.asarray(anorm, jnp.float64), ainv)


def trcondest(norm_type: Norm, a: ArrayLike, anorm=None) -> jax.Array:
    """slate::trcondest: triangular reciprocal condition estimate."""
    from ..blas3.blas3 import trsm_array
    from ..types import Side

    am = a if isinstance(a, BaseMatrix) else TriangularMatrix.from_array(jnp.asarray(a), Uplo.Lower)
    n = am.data.shape[0]
    if anorm is None:
        anorm = tile_ops.trnorm(norm_type if norm_type in (Norm.One, Norm.Inf) else Norm.One, am.data, am.uplo, am.diag)
    fwd = lambda x: trsm_array(Side.Left, am.uplo, Op.NoTrans, am.diag, 1.0, am.data, x[:, None])[:, 0]
    adj = lambda x: trsm_array(Side.Left, am.uplo, Op.ConjTrans, am.diag, 1.0, am.data, x[:, None])[:, 0]
    if norm_type == Norm.Inf:
        ainv = norm1est(adj, fwd, n, am.data.dtype)
    else:
        ainv = norm1est(fwd, adj, n, am.data.dtype)
    return _recondest(jnp.asarray(anorm, jnp.float64), ainv)
