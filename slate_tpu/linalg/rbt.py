"""Random Butterfly Transform LU (gesv_rbt).

Analogue of ``src/gesv_rbt.cc``, ``src/gerbt.cc``,
``src/internal/internal_gerbt.cc`` and ``internal_rbt_generate.cc``: multiply
A by depth-d random butterfly matrices on both sides so that pivoting becomes
unnecessary with high probability, factor with no-pivot LU, and clean up with
iterative refinement — SLATE's pivoting-free fast path, and an excellent TPU
fit (butterflies are O(d n^2) elementwise ops that XLA fuses; no row swaps at
all).

A depth-1 butterfly is B = (1/sqrt(2)) [[R0, R1], [R0, -R1]] with random
diagonal R0, R1; depth-d applies independent butterflies to nested halves.
U^T A V with U, V random butterflies; solve A x = b as
x = V (U^T A V)^-1 U^T b.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.matmul import matmul
from ..types import Option, Options, get_option

Array = jax.Array
_SQRT1_2 = 0.7071067811865476


def _rand_diag(key, n: int, dtype) -> Array:
    """Reference generates entries exp(r/10) with r uniform in [-0.5, 0.5]
    (internal_rbt_generate.cc) — near-1 positive scalings."""
    r = jax.random.uniform(key, (n,), jnp.float64 if dtype != jnp.float32 else jnp.float32, -0.05, 0.05)
    return jnp.exp(r).astype(dtype)


def generate_butterfly(key, n: int, depth: int, dtype) -> Array:
    """Random diagonals packed as (depth, n); level l acts on blocks of size
    n / 2^l (n must be divisible by 2^depth; drivers pad)."""
    keys = jax.random.split(key, depth)
    return jnp.stack([_rand_diag(k, n, dtype) for k in keys])


def _apply_level(x: Array, d: Array, block: int, trans: bool) -> Array:
    """Apply one butterfly level to rows of x: for each block pair
    (top, bot) of size block/2:  top' = r0*top + r1*bot, bot' = r0*top - r1*bot
    (times 1/sqrt2).  trans applies B^T, which for this symmetric-signed form
    swaps where the diagonals multiply."""
    n = x.shape[0]
    h = block // 2
    xb = x.reshape(n // block, block, -1)
    r = d.reshape(n // block, block)
    r0, r1 = r[:, :h], r[:, h:]
    top, bot = xb[:, :h], xb[:, h:]
    if not trans:
        # B @ x with B = [[R0, R1], [R0, -R1]] / sqrt2
        new_top = r0[..., None] * top + r1[..., None] * bot
        new_bot = r0[..., None] * top - r1[..., None] * bot
    else:
        # B^T @ x = [[R0, R0], [R1, -R1]] / sqrt2 @ x
        new_top = r0[..., None] * (top + bot)
        new_bot = r1[..., None] * (top - bot)
    out = jnp.concatenate([new_top, new_bot], axis=1) * jnp.asarray(_SQRT1_2, x.dtype)
    return out.reshape(n, -1)


def apply_butterfly(x: Array, diags: Array, trans: bool) -> Array:
    """x := W^(T) x for a depth-d butterfly W (internal_gerbt.cc).  W is the
    product level_0 @ level_1 @ ... (coarsest first)."""
    n = x.shape[0]
    depth = diags.shape[0]
    squeeze = x.ndim == 1
    if squeeze:
        x = x[:, None]
    # W = L0 @ L1 @ ... @ L_{d-1} (coarsest first): W x applies finest level
    # first; W^T x applies coarsest first
    levels = range(depth) if trans else range(depth - 1, -1, -1)
    for l in levels:
        block = n // (2**l)
        x = _apply_level(x, diags[l], block, trans)
    return x[:, 0] if squeeze else x


def _pad_pow2(n: int, depth: int) -> int:
    mult = 2**depth
    return ((n + mult - 1) // mult) * mult


def gerbt_array(a: Array, key=None, depth: int = 2) -> Tuple[Array, Array, Array, int]:
    """Two-sided transform: returns (U^T A V, u_diags, v_diags, padded_n).
    A is padded with an identity block so n divides 2^depth
    (gesv_rbt pads to tile multiples similarly).

    ``key=None`` draws fresh entropy per call, matching the reference's
    stateful RNG (internal_rbt_generate.cc): RBT's no-pivot safety is
    probabilistic, so a retry must see new butterflies.  Pass an explicit
    key for reproducibility."""
    if key is None:
        import numpy as _np

        key = jax.random.PRNGKey(int(_np.random.SeedSequence().entropy % (2**31)))
    n = a.shape[0]
    np_ = _pad_pow2(n, depth)
    if np_ != n:
        a = jnp.pad(a, ((0, np_ - n), (0, np_ - n)))
        a = a.at[jnp.arange(n, np_), jnp.arange(n, np_)].set(1)
    ku, kv = jax.random.split(key)
    ud = generate_butterfly(ku, np_, depth, a.dtype)
    vd = generate_butterfly(kv, np_, depth, a.dtype)
    # U^T A V = U^T (A V): apply V to columns via (V^T A^T)^T
    av = apply_butterfly(a.T, vd, trans=True).T  # A V  (V symmetric-signed: A V = (V^T A^T)^T)
    uav = apply_butterfly(av, ud, trans=True)  # U^T (A V)
    return uav, ud, vd, np_


class RBTFactors(NamedTuple):
    """Reusable gesv_rbt factorization: LU of the *transformed* matrix plus
    the butterflies needed to solve against the ORIGINAL A.  Returned
    instead of bare LUFactors because lu_factors.lu factors U^T A V, not A —
    reusing it through getrs_array would be silently wrong (the reference's
    gesv_rbt likewise keeps the butterflies with the factors,
    src/gesv_rbt.cc)."""

    lu_factors: object  # LUFactors of U^T A V
    ud: Array
    vd: Array
    n: int
    npad: int

    @property
    def info(self):
        return self.lu_factors.info

    def solve(self, b: Array) -> Array:
        """x = V (U^T A V)^-1 U^T b for the original A (src/gesv_rbt.cc
        solve path)."""
        from .lu import getrs_array

        squeeze = b.ndim == 1
        rhs = b[:, None] if squeeze else b
        rp = jnp.pad(rhs, ((0, self.npad - self.n), (0, 0)))
        y = apply_butterfly(rp, self.ud, trans=True)  # U^T b
        z = getrs_array(self.lu_factors, y)
        x = apply_butterfly(z, self.vd, trans=False)  # V z
        x = x[: self.n]
        return x[:, 0] if squeeze else x


def gesv_rbt_array(a: Array, b: Array, opts: Optional[Options] = None, key=None):
    """slate::gesv_rbt (src/gesv_rbt.cc): transform, no-pivot LU, solve,
    one step of iterative refinement in working precision.  Returns
    (x, RBTFactors); reuse factors via RBTFactors.solve, NOT getrs_array."""
    from .lu import getrf_nopiv_array

    depth = get_option(opts, Option.Depth, 2)
    n = a.shape[0]
    squeeze = b.ndim == 1
    bd = b[:, None] if squeeze else b
    uav, ud, vd, np_ = gerbt_array(a, key=key, depth=depth)
    rf = RBTFactors(getrf_nopiv_array(uav), ud, vd, n, np_)

    x = rf.solve(bd)
    # one refinement step guards the no-pivot growth (gesv_rbt refines via
    # gesv_mixed-style loop; a single correction suffices at working prec)
    r = bd - matmul(a, x).astype(bd.dtype)
    x = x + rf.solve(r)
    return (x[:, 0] if squeeze else x), rf
