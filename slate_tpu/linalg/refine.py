"""Mixed-precision iterative refinement: classic IR and GMRES-IR.

Analogues of ``src/{gesv_mixed,gesv_mixed_gmres,posv_mixed,
posv_mixed_gmres}.cc``.  The reference factors in FP32 and refines in FP64
(gesv_mixed.cc:16-44); that maps *natively* onto TPU where f32 (and bf16)
matmuls ride the MXU at full rate while f64 is emulated — mixed precision is
the performance path, not an option, so these drivers are first-class here.

Generic over a (factor, solve) pair so LU and Cholesky share the loop; the
convergence gate mirrors the reference: stop when the residual satisfies
``||r|| <= ||x|| * ||A|| * eps * sqrt(n) * stesp`` and fall back to the full
high-precision solver after max_iter failures when UseFallbackSolver is set.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.matrix import symmetrize
from ..ops.matmul import matmul
from ..ops.tile_ops import genorm
from ..types import Norm, Option, Options, Uplo, get_option

Array = jax.Array


def gate_cte(anorm, n: int, dtype, tol_factor: float = 1.0):
    """The refinement convergence constant: the loop stops when
    ``||r|| <= ||x|| * cte`` with ``cte = ||A|| * eps * sqrt(n)`` — the
    reference's gesv_mixed.cc gate.  The single definition shared by the
    single-chip loop below and the fused mesh refinement
    (parallel/dist_refine.py), so the accuracy contract cannot drift."""
    eps = jnp.finfo(dtype).eps
    return anorm * eps * jnp.sqrt(jnp.asarray(float(n), dtype)) * tol_factor


# -- ir.* observability counters (the ft.policy pattern: always-on, cheap,
#    landed in every RunReport as the ``ir`` section) ------------------------

_IR_COUNTERS = (
    "ir.solves", "ir.converged", "ir.iters_total", "ir.gmres_solves",
    "ir.escalated_gmres", "ir.fallback", "ir.residual_gemm_bytes",
)


def _registry():
    from ..obs import REGISTRY

    return REGISTRY


def ir_count(name: str, op: str, n: float = 1.0) -> None:
    """Bump one ``ir.*`` counter, tagged by op (gesv/posv)."""
    _registry().counter_add(name, n, op=op)


def ir_gauge(name: str, value: float, op: str) -> None:
    _registry().gauge_set(name, float(value), op=op)


def ir_counter_values() -> dict:
    """Totals of every ``ir.*`` counter across op tags — the RunReport
    ``ir`` section (obs.report.make_report reads this), gated by
    ``obs.report --check`` like the ft.* outcome totals."""
    snap = _registry().snapshot()
    out = {name.split("ir.", 1)[1]: 0.0 for name in _IR_COUNTERS}
    for entry in snap.get("counters", []):
        if entry["name"] in _IR_COUNTERS:
            out[entry["name"].split("ir.", 1)[1]] += float(entry["value"])
    return out


class RefineResult(NamedTuple):
    """Result of a mixed-precision refined solve (ADVICE r4: the public
    return grew from 3 to 4 fields in round 4; the NamedTuple documents the
    arity in one place and keeps positional unpacking explicit).

    ``iters`` is -1 when the fallback full-precision solver produced ``x``;
    ``info`` is then that factorization's LAPACK code."""

    x: Array
    iters: Array
    converged: Array
    info: Array


def _refine_loop(
    a_hi: Array,
    b: Array,
    lo_solve: Callable[[Array], Array],
    max_iter: int,
    tol_factor: float = 1.0,
) -> Tuple[Array, Array, Array]:
    """Classic iterative refinement. Returns (x, iters, converged)."""
    n = a_hi.shape[0]
    anorm = genorm(Norm.Inf, a_hi)
    cte = gate_cte(anorm, n, a_hi.dtype, tol_factor)

    x = lo_solve(b).astype(a_hi.dtype)

    def cond(state):
        x, r, it, done = state
        return (~done) & (it < max_iter)

    def body(state):
        x, r, it, _ = state
        d = lo_solve(r).astype(a_hi.dtype)
        x = x + d
        r = b - matmul(a_hi, x).astype(b.dtype)
        xnorm = genorm(Norm.Inf, x)
        rnorm = genorm(Norm.Inf, r)
        done = rnorm <= xnorm * cte
        return x, r, it + 1, done

    r0 = b - matmul(a_hi, x).astype(b.dtype)
    done0 = genorm(Norm.Inf, r0) <= genorm(Norm.Inf, x) * cte
    x, r, iters, done = jax.lax.while_loop(cond, body, (x, r0, jnp.int32(0), done0))
    return x, iters, done


def _fallback(done, x, iters, full_solve):
    """Run the full high-precision solver only on non-convergence.  Eagerly,
    ``bool(done)`` is concrete and the expensive path is skipped entirely;
    under jit it falls back to lax.cond (one branch *executes*).
    ``full_solve`` returns (x, info); the converged path reports info 0
    (the f32 factor succeeded and the refinement met its gate)."""
    zero = jnp.zeros((), jnp.int32)
    try:
        if bool(done):
            return x, iters, zero
        xf, info = full_solve()
        return xf, jnp.asarray(-1, iters.dtype), jnp.asarray(info, jnp.int32)
    except jax.errors.TracerBoolConversionError:
        return jax.lax.cond(
            done,
            lambda: (x, iters, zero),
            lambda: (lambda out: (out[0], jnp.asarray(-1, iters.dtype),
                                  jnp.asarray(out[1], jnp.int32)))(full_solve()),
        )


def gesv_mixed_array(
    a: Array, b: Array, opts: Optional[Options] = None
) -> RefineResult:
    """FP32-factor + high-precision-refine LU solve (src/gesv_mixed.cc).
    Returns RefineResult(x, iters, converged, info); on non-convergence
    with fallback enabled the result is the full-precision solve, iters =
    -1, and info is that factorization's LAPACK code (first zero pivot
    index)."""
    from .lu import gesv_array, getrf_array, getrs_array

    lo_dtype = jnp.complex64 if jnp.issubdtype(a.dtype, jnp.complexfloating) else jnp.float32
    max_iter = get_option(opts, Option.MaxIterations, 30)
    f32 = getrf_array(a.astype(lo_dtype))
    solve = lambda rhs: getrs_array(f32, rhs.astype(lo_dtype))
    x, iters, done = _refine_loop(a, b, solve, max_iter)
    info = jnp.zeros((), jnp.int32)
    if get_option(opts, Option.UseFallbackSolver, True):
        x, iters, info = _fallback(
            done, x, iters, lambda: (lambda o: (o[0], o[1].info))(gesv_array(a, b))
        )
    return RefineResult(x, iters, done, info)


def posv_mixed_array(
    a: Array, b: Array, uplo: Uplo = Uplo.Lower, opts: Optional[Options] = None
) -> RefineResult:
    """src/posv_mixed.cc analogue.  Returns RefineResult(x, iters,
    converged, info)."""
    from .chol import posv_array, potrf_array, potrs_array

    lo_dtype = jnp.complex64 if jnp.issubdtype(a.dtype, jnp.complexfloating) else jnp.float32
    max_iter = get_option(opts, Option.MaxIterations, 30)
    f32, _ = potrf_array(a.astype(lo_dtype), uplo)
    solve = lambda rhs: potrs_array(f32, rhs.astype(lo_dtype), uplo)
    conj = jnp.issubdtype(a.dtype, jnp.complexfloating)
    a_full = symmetrize(a, uplo, conj=conj)
    x, iters, done = _refine_loop(a_full, b, solve, max_iter)
    info = jnp.zeros((), jnp.int32)
    if get_option(opts, Option.UseFallbackSolver, True):
        x, iters, info = _fallback(
            done, x, iters, lambda: (lambda o: (o[0], o[2]))(posv_array(a, b, uplo))
        )
    return RefineResult(x, iters, done, info)


# ---------------------------------------------------------------------------
# GMRES-IR (src/gesv_mixed_gmres.cc, 409 LoC; posv_mixed_gmres.cc)
# ---------------------------------------------------------------------------


def _gmres(
    matvec: Callable[[Array], Array],
    precond: Callable[[Array], Array],
    b: Array,
    x0: Array,
    restart: int,
    tol: Array,
    max_restarts: int,
) -> Tuple[Array, Array]:
    """Left-preconditioned restarted GMRES on a single RHS vector.

    Static-shape Arnoldi: the Krylov basis lives in a fixed (restart+1, n)
    buffer inside ``lax.fori_loop`` — the XLA-friendly form of the
    reference's dynamic rotation loop (gesv_mixed_gmres.cc)."""
    n = b.shape[0]
    dtype = b.dtype
    m = restart

    def restart_body(rs, carry):
        x, _ = carry
        r = precond(b - matvec(x))
        beta = jnp.linalg.norm(r)
        v0 = r / jnp.where(beta == 0, 1, beta)
        V = jnp.zeros((m + 1, n), dtype).at[0].set(v0)
        H = jnp.zeros((m + 1, m), dtype)

        def arnoldi(j, vh):
            V, H = vh
            w = precond(matvec(V[j]))
            # modified Gram-Schmidt against all m+1 rows (rows > j are zero)
            h = matmul(jnp.conj(V), w[:, None])[:, 0]
            mask = (jnp.arange(m + 1) <= j).astype(dtype)
            h = h * mask
            w = w - matmul(h[None, :], V)[0]
            hn = jnp.linalg.norm(w)
            H = H.at[:, j].set(h + 0).at[j + 1, j].set(hn.astype(dtype))
            V = V.at[j + 1].set(w / jnp.where(hn == 0, 1, hn))
            return V, H

        V, H = jax.lax.fori_loop(0, m, arnoldi, (V, H))
        # solve least squares min || beta e1 - H y ||
        e1 = jnp.zeros(m + 1, dtype).at[0].set(beta.astype(dtype))
        y = jnp.linalg.lstsq(H, e1)[0]
        x = x + matmul(y[None, :], V[:m])[0]
        rnorm = jnp.linalg.norm(precond(b - matvec(x)))
        return x, rnorm

    # while_loop, not fori_loop + cond: under the multi-RHS vmap a
    # batched-predicate cond lowers to both-branches-execute + select,
    # so converged columns would keep paying full Arnoldi cycles for all
    # max_restarts trips.  A while_loop's batched cond is ANY-lane: the
    # batch stops at the SLOWEST column's cycle count, and unbatched
    # semantics are unchanged (loop while unconverged, at most
    # max_restarts cycles).
    def cont(c):
        i, _x, rn = c
        return (i < max_restarts) & (rn > tol)

    def step(c):
        i, x, rn = c
        x, rn = restart_body(i, (x, rn))
        return i + 1, x, rn

    _, x, rnorm = jax.lax.while_loop(
        cont, step,
        (jnp.int32(0), x0, jnp.asarray(jnp.inf, jnp.real(b).dtype)),
    )
    return x, rnorm


def gesv_mixed_gmres_array(
    a: Array, b: Array, opts: Optional[Options] = None, restart: int = 30
) -> Tuple[Array, Array]:
    """GMRES-IR: low-precision LU as preconditioner for high-precision GMRES
    (src/gesv_mixed_gmres.cc). b may be (n,) or (n, 1). Returns (x, resid)."""
    from .lu import getrf_array, getrs_array

    lo_dtype = jnp.complex64 if jnp.issubdtype(a.dtype, jnp.complexfloating) else jnp.float32
    f = getrf_array(a.astype(lo_dtype))
    precond = lambda v: getrs_array(f, v.astype(lo_dtype)[:, None])[:, 0].astype(a.dtype)
    matvec = lambda v: matmul(a, v[:, None])[:, 0].astype(a.dtype)
    return _gmres_multi_rhs(
        a, b, matvec, precond, restart, get_option(opts, Option.MaxIterations, 30)
    )


def posv_mixed_gmres_array(
    a: Array, b: Array, uplo: Uplo = Uplo.Lower, opts: Optional[Options] = None, restart: int = 30
) -> Tuple[Array, Array]:
    """src/posv_mixed_gmres.cc analogue."""
    from .chol import potrf_array, potrs_array

    lo_dtype = jnp.complex64 if jnp.issubdtype(a.dtype, jnp.complexfloating) else jnp.float32
    conj = jnp.issubdtype(a.dtype, jnp.complexfloating)
    a_full = symmetrize(a, uplo, conj=conj)
    f, _ = potrf_array(a.astype(lo_dtype), uplo)
    precond = lambda v: potrs_array(f, v.astype(lo_dtype)[:, None], uplo)[:, 0].astype(a.dtype)
    matvec = lambda v: matmul(a_full, v[:, None])[:, 0].astype(a.dtype)
    return _gmres_multi_rhs(
        a, b, matvec, precond, restart, get_option(opts, Option.MaxIterations, 30)
    )


def _gmres_multi_rhs(a, b, matvec, precond, restart, max_restarts):
    """Solve each RHS column with _gmres; returns (x like b, worst resid).

    The columns are independent Krylov solves with identical static
    shapes, so the single-RHS solver is ``vmap``ped over them — ONE
    compiled program for any B width (the predecessor re-traced ``_gmres``
    per column in a Python loop: B with 30 columns compiled 30 copies of
    the whole Arnoldi program)."""
    eps = jnp.finfo(a.dtype).eps
    rdtype = jnp.real(a).dtype
    scale = jnp.sqrt(jnp.asarray(float(a.shape[0]), rdtype)) * eps

    def one(bv):
        tol = (scale * jnp.linalg.norm(bv)).astype(rdtype)
        return _gmres(matvec, precond, bv, jnp.zeros_like(bv), restart, tol, max_restarts)

    if b.ndim == 1:
        return one(b)
    x, rnorms = jax.vmap(one, in_axes=1, out_axes=(1, 0))(b)
    return x, jnp.max(rnorms)
