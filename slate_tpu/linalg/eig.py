"""Two-stage Hermitian eigensolver: he2hb -> hb2st -> tridiag -> back.

Analogues of the reference chain (SURVEY §3.5): ``src/he2hb.cc`` (full ->
band, GPU-capable panel QR + two-sided block update), ``src/hb2st.cc``
(band -> tridiagonal bulge chasing, pipelined sweeps), tridiagonal solvers
(see tridiag.py), and the back-transforms ``src/unmtr_hb2st.cc`` /
``src/unmtr_he2hb.cc``; drivers ``src/heev.cc``, ``src/hegv.cc``,
``src/hegst.cc``.

TPU design:
- Stage 1 (he2hb) is ALL BLAS-3: per block column, a panel ``geqrf`` then a
  symmetric two-sided compact-WY update B' = B - W~ V^H - V W~^H (two
  MXU-sized gemms per step) — the SBR structure the reference builds with
  he2hb_{hemm,her2k,trmm,gemm} internal ops (he2hb.cc:207-604).
- Stage 2 (hb2st) is the sequential bulge chase, run as nested fori_loops
  over (sweep, hop) with static 3w-wide windows on a padded array — the
  reference's single-node pipelined taskloop (hb2st.cc:170-281) collapses to
  a masked two-kernel-per-hop loop; per-hop work is O(w^2) so the whole
  stage is O(n^2 w).
- Complex off-diagonals are phase-rotated to a real tridiagonal at the end
  (LAPACK hbtrd convention) with the phases folded into the back-transform.
"""

from __future__ import annotations

from ..obs import instrument

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.matrix import symmetrize
from ..ops.matmul import matmul
from ..types import MethodEig, Uplo

from .tridiag import stedc, steqr, sterf

Array = jax.Array

_EIG_NB = 32  # stage-1 band width (reference nb; hb2st window size)


class He2hbFactors(NamedTuple):
    """Band matrix + stacked compact-WY reflectors (he2hb's V/T storage,
    reference T matrix family he2hb.cc:60-80).  ``v[k]`` holds panel k's
    explicit reflectors in GLOBAL row coordinates (zeros above the panel's
    pivot rows), padded to a common height — one fixed shape so the whole
    reduction traces as a single fori_loop program."""

    band: Array  # (n, n) full Hermitian array with bandwidth-nb content
    v: Array  # (K, np2, nb) global-coordinate reflectors
    t: Array  # (K, nb, nb) per-panel WY accumulators
    nb: int


def _he2hb_panel_count(n: int, nb: int) -> int:
    k = 0
    while (k + 1) * nb < n - 1:
        k += 1
    return k


def he2hb(a: Array, nb: int = _EIG_NB) -> He2hbFactors:
    """Full Hermitian -> Hermitian band (bandwidth nb), Q stored per panel.

    One lax.fori_loop over panels with static shapes (O(1) program size in
    n): per step, an offset-pivot panel QR of the full-height block column,
    scatter of [R; 0] + its mirror into the band, and the global masked
    two-sided compact-WY update B' = B - W V^H - V W^H (the SBR structure
    the reference builds with he2hb_{hemm,her2k,trmm,gemm} internal ops,
    he2hb.cc:207-604).
    """
    from .qr import _larft_v, _panel_qr_offset

    n = a.shape[0]
    cplx = jnp.issubdtype(a.dtype, jnp.complexfloating)
    a = symmetrize(a, Uplo.Lower, conj=cplx)
    nsteps = _he2hb_panel_count(n, nb)
    np2 = max(n, (nsteps + 1) * nb)  # padding so panel slices never clamp
    if nsteps == 0:
        return He2hbFactors(
            a, jnp.zeros((0, np2, nb), a.dtype), jnp.zeros((0, nb, nb), a.dtype), nb
        )
    ap = jnp.pad(a, ((0, np2 - n), (0, np2 - n)))
    rows = jnp.arange(np2)

    def body(k, carry):
        ap, vs, ts = carry
        j0 = k * nb
        c0 = j0 + nb
        colblk = jax.lax.dynamic_slice(ap, (0, j0), (np2, nb))
        masked = jnp.where((rows >= c0)[:, None], colblk, 0)
        r_a, v, tau = _panel_qr_offset(masked, c0)
        t = _larft_v(v, tau)
        # panel columns <- history above c0, [R; 0] below; mirror row block
        newcols = jnp.where((rows >= c0)[:, None], r_a, colblk)
        ap = jax.lax.dynamic_update_slice(ap, newcols, (0, j0))
        rowblk = jax.lax.dynamic_slice(ap, (j0, 0), (nb, np2))
        rowblk = jnp.where((rows >= c0)[None, :], jnp.conj(newcols).T, rowblk)
        ap = jax.lax.dynamic_update_slice(ap, rowblk, (j0, 0))
        # two-sided trailing update, global masked: v is zero above c0 so
        # the update touches only the trailing block
        y = matmul(ap, v).astype(ap.dtype)
        y = jnp.where((rows >= c0)[:, None], y, 0)
        wmat = matmul(y, t).astype(ap.dtype)
        x = matmul(jnp.conj(t).T, matmul(jnp.conj(v).T, wmat)).astype(ap.dtype)
        wt = wmat - 0.5 * matmul(v, x).astype(ap.dtype)
        ap = (
            ap
            - matmul(wt, jnp.conj(v).T).astype(ap.dtype)
            - matmul(v, jnp.conj(wt).T).astype(ap.dtype)
        )
        ap = 0.5 * (ap + (jnp.conj(ap).T if cplx else ap.T))
        return ap, vs.at[k].set(v), ts.at[k].set(t)

    vs0 = jnp.zeros((nsteps, np2, nb), a.dtype)
    ts0 = jnp.zeros((nsteps, nb, nb), a.dtype)
    ap, vs, ts = jax.lax.fori_loop(0, nsteps, body, (ap, vs0, ts0))
    return He2hbFactors(ap[:n, :n], vs, ts, nb)


def unmtr_he2hb(f: He2hbFactors, c: Array) -> Array:
    """C <- Q C with Q = Q_0 Q_1 ... (src/unmtr_he2hb.cc): applied
    right-to-left so eigenvectors of the band matrix lift to the original.
    V is stored globally (zeros above each panel), so the update touches
    only the rows below the panel with no dynamic slicing."""
    nsteps, np2, _ = f.v.shape
    n = c.shape[0]
    cp = jnp.pad(c, ((0, np2 - n),) + ((0, 0),) * (c.ndim - 1))

    def body(i, cp):
        k = nsteps - 1 - i
        v, t = f.v[k], f.t[k]
        upd = matmul(v, matmul(t, matmul(jnp.conj(v).T, cp))).astype(cp.dtype)
        return cp - upd

    if nsteps:  # zero-panel case (n <= nb+1): Q is the identity
        cp = jax.lax.fori_loop(0, nsteps, body, cp)
    return cp[:n]


# ---------------------------------------------------------------------------
# Stage 2: band -> tridiagonal bulge chasing (src/hb2st.cc)
# ---------------------------------------------------------------------------


def _larfg_masked(x: Array, nactive) -> Tuple[Array, Array]:
    """Householder of x (length w) restricted to its first ``nactive``
    entries: H = I - tau v v^H with v[0] = 1, H x = beta e1.  Complex-safe
    (LAPACK larfg); identity (tau = 0) when nothing to eliminate."""
    w = x.shape[0]
    dtype = x.dtype
    cplx = jnp.issubdtype(dtype, jnp.complexfloating)
    idx = jnp.arange(w)
    mask = idx < nactive
    x = jnp.where(mask, x, 0)
    alpha = x[0]
    tailnorm2 = jnp.sum(jnp.abs(x) ** 2) - jnp.abs(alpha) ** 2
    if cplx:
        degenerate = (tailnorm2 <= 0) & (jnp.imag(alpha) == 0)
    else:
        degenerate = tailnorm2 <= 0
    norm = jnp.sqrt(jnp.abs(alpha) ** 2 + tailnorm2)
    re_a = jnp.real(alpha)
    sgn = jnp.where(re_a >= 0, 1.0, -1.0)
    beta = (-sgn * norm).astype(jnp.real(x).dtype)  # real by construction
    denom = alpha - beta.astype(dtype)
    denom = jnp.where(denom == 0, 1, denom)
    v = jnp.where(idx == 0, jnp.ones((), dtype), x / denom)
    v = jnp.where(mask, v, 0)
    tau_full = ((beta.astype(dtype) - alpha) / beta.astype(dtype))
    tau = jnp.where(degenerate | (nactive <= 1) | (beta == 0), jnp.zeros((), dtype), jnp.conj(tau_full))
    v = jnp.where((nactive <= 1), jnp.zeros_like(v).at[0].set(1), v)
    return v, tau


class Hb2stFactors(NamedTuple):
    """Bulge-chase reflectors: vs[j, t] (length w, v[0]=1) + taus[j, t]."""

    vs: Array  # (n-1, max_hops, w)
    taus: Array  # (n-1, max_hops)
    w: int
    n: int


def _dense_to_diagband(a: Array, w: int, pad: int) -> Array:
    """Dense (n, n) -> diagonal-band storage (n + 2*pad, 4w) with
    ba[i, dd] = A[i - pad, i - pad + dd - 2w] (zero outside the band or the
    matrix).  4w diagonals (j - i in [-2w, 2w)) cover the working set of
    both bulge chases: each hop writes only block rows/cols [w, 2w) of its
    3w window, so every written offset satisfies |j - i| <= 2w - 1 —
    strictly inside the frame for hb2st (band w + bulge w, both triangles
    kept) AND tb2bd (lower bulge, upper fill).  128 lanes at w = 32."""
    n = a.shape[0]
    D = 4 * w
    i = jnp.arange(n)[:, None]
    j = i + jnp.arange(D)[None, :] - 2 * w
    ok = (j >= 0) & (j < n)
    vals = jnp.where(ok, a[i, jnp.clip(j, 0, n - 1)], 0)
    return jnp.zeros((n + 2 * pad, D), a.dtype).at[pad : pad + n].set(vals)


def _chase_frame(band: Array, w: int, pad: int, diag_storage: bool) -> Array:
    """The (n + 2*pad, 4w) working frame for a bulge chase, from either a
    dense (n, n) band matrix or prebuilt diagonal storage (n, 4w).  Owned
    here so the two chase entry points (hb2st, svd.tb2bd) share one
    prelude."""
    if diag_storage:
        if band.shape[1] != 4 * w:
            raise ValueError(f"diag storage needs (n, {4 * w}), got {band.shape}")
        n = band.shape[0]
        return jnp.zeros((n + 2 * pad, 4 * w), band.dtype).at[pad : pad + n].set(band)
    return _dense_to_diagband(band, w, pad)


def symmetrize_diagband(bandd: Array, w: int) -> Array:
    """Hermitian-average a diagonal-band frame (n, 4w): element (i, dd)
    holds A[i, i+o] (o = dd - 2w); its mirror conj(A[i+o, i]) lives at
    frame position (i+o, 2w - o).  Keeps the frame-layout knowledge next
    to _dense_to_diagband; used by the mesh drivers to shave the
    O(eps * nsteps) rounding asymmetry of the distributed two-sided
    update before the chase."""
    n, D = bandd.shape
    assert D == 4 * w, (bandd.shape, w)
    cplx = jnp.issubdtype(bandd.dtype, jnp.complexfloating)
    o = jnp.arange(D) - 2 * w
    src_r = jnp.arange(n)[:, None] + o[None, :]
    src_c = 2 * w - o
    ok = (src_r >= 0) & (src_r < n) & ((src_c >= 0) & (src_c < D))[None, :]
    g = bandd[jnp.clip(src_r, 0, n - 1), jnp.clip(src_c, 0, D - 1)[None, :]]
    return 0.5 * (bandd + jnp.where(ok, jnp.conj(g) if cplx else g, bandd))


def _wavefront_chase_band(
    ba, n, w, nsweeps, max_hops, one, facs, s_lo=None, s_hi=None
):
    """Band-storage wavefront chase.

    Schedule: hop (sweep j, hop t) touches only the 3w x 3w diagonal block
    at r0 = j + 1 + t*w, and two hops conflict iff their r0 differ by
    < 3w.  Scheduling hop (j, t) at time s = 4j + t places concurrent hops
    exactly 4w-1 >= 3w apart (disjoint) and executes every conflicting
    pair in sequential order, so a chase runs in ~4n batched steps instead
    of nsweeps * max_hops serial hops.  ``one`` receives (block, idx0,
    nact) — idx0 the in-block row/column of the vector being eliminated
    (w-1 on a sweep's first hop, else 0) — and returns (block,
    *per_hop_factors); idle wavefront slots park on the dummy rows
    [0, 3w) inside the pad (live windows start >= 3w+1) with identity
    updates (nact = 0 -> tau = 0), and their factor rows are dropped via
    an out-of-bounds scatter index.

    Storage (the round-4 rework): the matrix lives in diagonal-band
    storage (N, 4w) instead of a full (N, N) array — the loop carry drops
    from O(n^2) (285 MB at n = 8192 f32) to O(n w) (4 MB), so the ~4n
    serial steps stop being HBM-copy-bound.  Each step gathers
    K row slabs (3w, 4w), shears them into dense (3w, 3w) windows for the
    vmapped ``one`` update, shears back, and scatters.  Entries of a slab
    row outside its 3w window (band columns left of the window) are
    preserved by the shear-back mask.

    The shears run as PAD + FLATTEN + STRIDED-RESHAPE moves, not as
    take_along_axis and not as matmuls: element-wise gathers execute on
    the TPU scalar unit at ~30 ms per step for these shapes (measured on
    chip, round 5) — slow enough that the worker's long-program watchdog
    killed every chase past ~1500 steps — and a one-hot einsum shear is
    fast but NOT bit-exact (XLA's dgemm reassociation adds a few ulp of
    noise per hop, which the chase's eliminated-entry bookkeeping
    amplifies catastrophically; observed as O(1) singular-value errors).
    A row shift by r is index algebra: padding each length-D row to
    width W and reading the flat buffer at offset 2w with row stride
    W - 1 realigns every row's band columns to block columns in one
    reshape — exact data movement, zero flops.  This is the TPU answer
    to the reference's cache-resident pipelined taskloop
    (hb2st.cc:170-281): the working set FITS fast memory and every
    reshape is a layout move."""
    D = 4 * w
    k_slots = max_hops // 4 + 1
    islot = jnp.arange(k_slots)
    w3 = 3 * w
    pad = 4 * w
    rr = jnp.arange(w3)
    cidx = rr[:, None] + jnp.arange(D)[None, :] - 2 * w  # (3w, D) block col per (r, dd)
    ok_s = (cidx >= 0) & (cidx < w3)

    def shear_in(slabs):
        """block[k, r, c] = slab[k, r, c - r + 2w], 0 outside [0, D).

        Pad rows to width W = 5w; in the flat row-major buffer the wanted
        entry sits at r*W + (c - r + 2w) = 2w + r*(W - 1) + c, so a
        reshape with row stride W - 1 starting at offset 2w IS the shear;
        out-of-band reads land in a neighbor row's zero padding."""
        K = slabs.shape[0]
        W = 5 * w
        p = jnp.concatenate([slabs, jnp.zeros((K, w3, W - D), slabs.dtype)], axis=2)
        flat = p.reshape(K, w3 * W)
        return flat[:, 2 * w : 2 * w + w3 * (W - 1)].reshape(K, w3, W - 1)[:, :, :w3]

    def shear_out(blocks):
        """raw[k, r, d] = block[k, r, r + d - 2w] (junk outside [0, 3w),
        masked by ok_s after).  Same trick with the opposite shift: pad
        rows to width W2 = 5w, prepend 2w zeros, read with row stride
        W2 + 1."""
        K = blocks.shape[0]
        W2 = 5 * w
        p = jnp.concatenate([blocks, jnp.zeros((K, w3, W2 - w3), blocks.dtype)], axis=2)
        flat = jnp.concatenate(
            [jnp.zeros((K, 2 * w), blocks.dtype), p.reshape(K, w3 * W2),
             jnp.zeros((K, w), blocks.dtype)], axis=1,
        )
        return flat[:, : w3 * (W2 + 1)].reshape(K, w3, W2 + 1)[:, :, :D]

    def step_body(s, carry):
        ba, *fs = carry
        j = s // 4 - islot
        t = s - 4 * j
        r0 = j + 1 + t * w
        valid = (j >= 0) & (j < nsweeps) & (t < max_hops) & (r0 <= n - 1)
        nact = jnp.where(valid, jnp.clip(n - r0, 0, w), 0)
        b0 = jnp.where(valid, pad + r0 - w, 0)
        slabs = jax.vmap(lambda b: lax.dynamic_slice(ba, (b, 0), (w3, D)))(b0)
        blocks = shear_in(slabs)
        idx0 = jnp.where(t == 0, w - 1, 0)
        blocks, *vals = jax.vmap(one)(blocks, idx0, nact)
        # band columns outside the 3w window keep their slab values
        newslabs = jnp.where(ok_s[None], shear_out(blocks), slabs)

        def put(i, ba):
            return lax.dynamic_update_slice(ba, newslabs[i], (b0[i], 0))

        ba = lax.fori_loop(0, k_slots, put, ba)
        jw = jnp.where(valid, j, fs[0].shape[0])  # out-of-bounds -> dropped
        tw = jnp.where(valid, t, 0)
        fs = [f.at[jw, tw].set(v, mode="drop") for f, v in zip(fs, vals)]
        return (ba, *fs)

    nsteps = 4 * (nsweeps - 1) + max_hops
    return lax.fori_loop(s_lo if s_lo is not None else 0,
                         s_hi if s_hi is not None else nsteps,
                         step_body, (ba, *facs))


# Empirical worker per-program ceiling: the fused wavefront chase faults
# past this n; segmented dispatch (below) is the escape hatch.
_CHASE_SEGMENT_ABOVE = 8192


def _chase_segments(n: int) -> int:
    """Auto segment count for the staged drivers: 1 (fused) at or below
    the validated ceiling, else ~one segment per 4096 rows."""
    return 1 if n <= _CHASE_SEGMENT_ABOVE else max(2, n // 4096)


def _wavefront_chase_segmented(ba, n, w, nsweeps, max_hops, one, facs, segments):
    """Run the chase as ``segments`` jitted programs over step ranges,
    state carried on device — bit-identical to the fused form (same
    step_body, same order).  Keeps the step-count formula in ONE place for
    both the eig (hb2st) and svd (tb2bd) chases."""
    if segments <= 1:
        return _wavefront_chase_band(ba, n, w, nsweeps, max_hops, one, facs)
    nsteps = 4 * (nsweeps - 1) + max_hops
    bounds = [nsteps * i // segments for i in range(segments)] + [nsteps]

    @functools.partial(jax.jit, static_argnames=("lo", "hi"))
    def _seg(ba, facs, lo, hi):
        out = _wavefront_chase_band(ba, n, w, nsweeps, max_hops, one, facs, lo, hi)
        return out[0], tuple(out[1:])

    facs = tuple(facs)
    for i in range(segments):
        ba, facs = _seg(ba, facs, bounds[i], bounds[i + 1])
    return (ba, *facs)


def hb2st(band: Array, w: int = _EIG_NB, segments: int = 1, diag_storage: bool = False):
    """Hermitian band (bandwidth w, dense storage — or diagonal-band
    storage (n, 4w) when ``diag_storage``, as built by _dense_to_diagband /
    parallel.dist_twostage.gather_diagband) -> real tridiagonal (d, e) +
    reflectors for the back-transform.  Returns (d, e_real, factors,
    phases); eigvec lifting: z_band = phases * unmtr_hb2st(factors,
    z_tridiag).

    Wavefront pipelining (reference P7, hb2st.cc:170-281 taskloop): see
    _wavefront_chase_band for the schedule; per hop the in-block update is one
    left Householder on rows [r0, r0+w) and its mirrored right
    application."""
    n = band.shape[0]
    dtype = band.dtype
    cplx = jnp.issubdtype(dtype, jnp.complexfloating)
    pad = 4 * w
    ba = _chase_frame(band, w, pad, diag_storage)
    max_hops = max(1, -(-(n - 1) // w))
    nsweeps = max(n - 2, 1)
    vs = jnp.zeros((max(n - 1, 1), max_hops, w), dtype)
    taus = jnp.zeros((max(n - 1, 1), max_hops), dtype)

    # in-block column of the vector being eliminated (idx0): the first hop
    # of a sweep reads column j (= r0-1), later hops column r0-w
    def one(block, ci, na):
        x = lax.dynamic_slice(block, (w, ci), (w, 1))[:, 0]
        v, tau = _larfg_masked(x, na)
        # left: H applied to rows [r0, r0+w) (block rows [w, 2w))
        mid = block[w : 2 * w, :]
        mid = mid - tau * jnp.outer(v, matmul(jnp.conj(v)[None, :], mid)[0])
        block = block.at[w : 2 * w, :].set(mid)
        # right: A H^H on cols [r0, r0+w) (block cols [w, 2w))
        colb = block[:, w : 2 * w]
        colb = colb - jnp.conj(tau) * jnp.outer(
            matmul(colb, v[:, None])[:, 0], jnp.conj(v)
        )
        block = block.at[:, w : 2 * w].set(colb)
        return block, v, tau

    if n > 2:
        # segments > 1: one jitted program per step range (call hb2st
        # EAGERLY to benefit) — the scale escape hatch for chases whose
        # single program exceeds the worker's limits (cf. stedc_staged)
        ba, vs, taus = _wavefront_chase_segmented(
            ba, n, w, nsweeps, max_hops, one, (vs, taus), segments
        )
    d = jnp.real(ba[pad : pad + n, 2 * w])
    e = ba[pad + 1 : pad + n, 2 * w - 1]  # A[i, i-1], i = 1..n-1
    if cplx:
        # phase-rotate to a real tridiagonal: T_real = P^H T P
        ae = jnp.abs(e)
        s = jnp.where(ae == 0, 1.0 + 0j, e / jnp.where(ae == 0, 1, ae))
        phases = jnp.concatenate(
            [jnp.ones((1,), dtype), jnp.cumprod(s)]
        )  # z_band = P z_real with p_{k+1} = p_k * (e_k/|e_k|), e_k = A[k+1,k]
        e_real = ae
    else:
        phases = jnp.ones((n,), dtype)
        e_real = e
    return d, e_real, Hb2stFactors(vs, taus, w, n), phases


def _chase_sweep_apply(
    vs: Array, taus: Array, z: Array, n: int, w: int, adjoint: bool, j0: int = 0
) -> Array:
    """Apply a bulge-chase reflector family to Z, one batched sweep at a
    time.  Within one sweep j the hops touch DISJOINT w-row slabs of Z
    (rows j+1+t*w for t = 0..max_hops-1 tile [j+1, j+1+max_hops*w)
    contiguously), so a whole sweep is one batched rank-1 update on a
    (max_hops, w, nrhs) reshape — serial depth n instead of n^2/w.

    adjoint=False applies the basis U = H_1^H H_2^H ... (reflectors
    conj-transposed, reverse chronological order); adjoint=True applies
    U^H (reflectors as-is, chronological order).  ``j0`` offsets the
    family's sweep indices (vs[jj] is global sweep j0 + jj) so a BLOCK of
    sweeps can be applied — the streamed distributed back-transform
    (parallel.dist_twostage.chase_apply_dist) feeds one sharded block at a
    time."""
    nsweeps, max_hops = vs.shape[0], vs.shape[1]
    nrhs = z.shape[1]
    span = max_hops * w
    zp = jnp.zeros((n + span, nrhs), z.dtype)
    zp = zp.at[:n].set(z)

    def sweep_body(jj, zp):
        jl = jj if adjoint else (nsweeps - 1) - jj  # local family row
        j = j0 + jl  # global sweep index (slab position in Z)
        # hop order within a sweep is irrelevant (disjoint rows)
        slab = lax.dynamic_slice(zp, (j + 1, 0), (span, nrhs))
        slab = slab.reshape(max_hops, w, nrhs)
        vj = lax.dynamic_slice(vs, (jl, 0, 0), (1, max_hops, w))[0].astype(z.dtype)
        tj = lax.dynamic_slice(taus, (jl, 0), (1, max_hops))[0].astype(z.dtype)
        cj = tj if adjoint else jnp.conj(tj)
        coef = jnp.einsum("hw,hwr->hr", jnp.conj(vj), slab,
                          precision=lax.Precision.HIGHEST)
        slab = slab - cj[:, None, None] * vj[:, :, None] * coef[:, None, :]
        return lax.dynamic_update_slice(zp, slab.reshape(span, nrhs), (j + 1, 0))

    if n > 1:
        zp = lax.fori_loop(0, nsweeps, sweep_body, zp)
    return zp[:n]


def unmtr_hb2st(f: Hb2stFactors, z: Array) -> Array:
    """Z <- Q Z for the stage-2 Q (src/unmtr_hb2st.cc): the basis is
    U = H_1^H H_2^H ... (A_tri = U^H A U), so U Z applies conj-transposed
    reflectors last-to-first, one batched sweep at a time."""
    return _chase_sweep_apply(f.vs, f.taus, z, f.n, f.w, adjoint=False)


# ~4k sweeps per apply program at the 8192^2 reference size keeps each
# dispatch well under the worker's long-program watchdog (measured ~5.4 ms
# per sweep there; one 16384-sweep apply ran minutes and was killed).  The
# per-sweep cost scales with the touched area (span x ncols ~ n x ncols),
# so the block size shrinks proportionally at larger problems.
_APPLY_SEG_SWEEPS = 4096
_APPLY_REF_AREA = 8192 * 8192
_APPLY_MIN_BLOCK = 256  # dispatch-overhead floor

# module-level jit: a fresh ``jax.jit(_chase_sweep_apply, ...)`` wrapper
# per call owns a fresh cache, so every _chase_apply_staged invocation
# re-traced (and re-compiled on cache-miss backends) even for identical
# shapes — ADVICE r5.  One shared wrapper makes repeat applies cache hits.
_chase_sweep_apply_jit = jax.jit(_chase_sweep_apply, static_argnums=(3, 4, 5))


def _chase_apply_staged(vs, taus, z, n: int, w: int, adjoint: bool) -> Array:
    """Apply a bulge-chase reflector family to Z in SWEEP-BLOCK programs
    (eager staged dispatch, cf. _wavefront_chase_segmented): at n = 16384
    the single-program apply runs minutes of serial sweeps and the TPU
    worker's watchdog kills it; area-scaled blocks of sweeps each
    dispatch as one jit (identical shapes -> one compile), applied in the
    order the factored form requires — descending block index for
    adjoint=False (U = H_1^H H_2^H ... applies last reflectors first),
    ascending for adjoint=True."""
    nsweeps = vs.shape[0]
    area = max(1, n * z.shape[1])
    per_block = max(
        _APPLY_MIN_BLOCK, int(_APPLY_SEG_SWEEPS * _APPLY_REF_AREA / area)
    )
    nseg = max(1, -(-nsweeps // per_block))
    if nseg == 1:
        return _chase_sweep_apply_jit(vs, taus, z, n, w, adjoint)
    # equal-size blocks within 1 (at most two distinct compiled shapes)
    bounds = [nsweeps * i // nseg for i in range(nseg)] + [nsweeps]
    order = range(nseg) if adjoint else range(nseg - 1, -1, -1)
    for i in order:
        b0, b1 = bounds[i], bounds[i + 1]
        z = _chase_sweep_apply_jit(vs[b0:b1], taus[b0:b1], z, n, w, adjoint, b0)
    return z


# ---------------------------------------------------------------------------
# Drivers: heev / hegst / hegv (src/heev.cc, hegst.cc, hegv.cc)
# ---------------------------------------------------------------------------


@instrument("heev_array")
def heev_array(
    a: Array,
    want_vectors: bool = True,
    method: MethodEig = MethodEig.DC,
    nb: int = _EIG_NB,
):
    """Hermitian eigen-decomposition (src/heev.cc): two-stage reduction,
    tridiagonal solve (DC default / QR iteration), two back-transforms.
    Returns w ascending (and Z if want_vectors)."""
    n = a.shape[0]
    dtype = a.dtype
    cplx = jnp.issubdtype(dtype, jnp.complexfloating)
    if n == 1:
        w = jnp.real(a[0, 0])[None]
        return (w, jnp.ones((1, 1), dtype)) if want_vectors else w
    f1 = he2hb(a, nb)
    d, e, f2, phases = hb2st(f1.band, nb)
    if not want_vectors:
        return sterf(d, e)
    if method == MethodEig.DC:
        w, ztri = stedc(d, e)
    else:
        w, ztri = steqr(d, e)
    z = ztri.astype(dtype)
    if cplx:
        z = phases[:, None] * z
    z = unmtr_hb2st(f2, z)
    z = unmtr_he2hb(f1, z)
    return w, z


def heev_staged(
    a: Array,
    want_vectors: bool = True,
    method: MethodEig = MethodEig.DC,
    nb: int = _EIG_NB,
):
    """heev with each phase dispatched as its OWN XLA program (jit per
    stage) rather than one fused program.  Numerically identical to
    heev_array; use it at large n: the reference's heev is likewise a
    sequence of phase barriers (he2hb | hb2st | solver | back-transforms,
    src/heev.cc), and a single fused program for all phases exceeds the
    TPU runtime's per-program ceiling near n = 8192 (worker kernel fault;
    each phase alone runs fine — tools/northstar_sweep.py finding)."""
    from .tridiag import stedc_vals as _vals

    n = a.shape[0]
    if n == 1:
        return heev_array(a, want_vectors, method, nb)
    f1 = jax.jit(he2hb, static_argnums=1)(a, nb)
    segs = _chase_segments(n)
    if segs > 1:  # segmented chase must dispatch eagerly
        d, e, f2, phases = hb2st(f1.band, nb, segments=segs)
    else:
        d, e, f2, phases = jax.jit(hb2st, static_argnums=(1, 2))(f1.band, nb)
    if not want_vectors:
        return jax.jit(_vals)(d, e)
    if method == MethodEig.DC:
        from .tridiag import _STEDC_STAGE_ABOVE, stedc_staged

        if n > _STEDC_STAGE_ABOVE:
            w, ztri = stedc_staged(d, e)  # one dispatch per merge level
        else:
            w, ztri = jax.jit(stedc)(d, e)
    else:
        w, ztri = jax.jit(steqr)(d, e)
    z = ztri.astype(a.dtype)
    if jnp.issubdtype(a.dtype, jnp.complexfloating):
        z = phases[:, None] * z
    # sweep-block staged apply (the fused apply outruns the worker
    # watchdog at 16384); factor-tuple ints (n, w) are static
    z = _chase_apply_staged(f2.vs, f2.taus, z, n, nb, False)
    z = jax.jit(unmtr_he2hb)(He2hbFactors(f1.band, f1.v, f1.t, nb), z)
    return w, z


def hegst_array(a: Array, l: Array, itype: int = 1) -> Array:
    """Reduce generalized to standard form (src/hegst.cc) given B = L L^H:
    itype 1: C = L^-1 A L^-H  (for A x = lambda B x)
    itype 2/3: C = L^H A L    (for A B x = lambda x / B A x = lambda x)."""
    from ..blas3.blas3 import trsm_array, trmm_array
    from ..types import Diag, Op, Side

    cplx = jnp.issubdtype(a.dtype, jnp.complexfloating)
    a = symmetrize(a, Uplo.Lower, conj=cplx)
    if itype == 1:
        y = trsm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, 1.0, l, a)
        return trsm_array(Side.Right, Uplo.Lower, Op.ConjTrans, Diag.NonUnit, 1.0, l, y)
    y = trmm_array(Side.Left, Uplo.Lower, Op.ConjTrans, Diag.NonUnit, 1.0, l, a)
    return trmm_array(Side.Right, Uplo.Lower, Op.NoTrans, Diag.NonUnit, 1.0, l, y)


def hegv_array(
    a: Array,
    b: Array,
    itype: int = 1,
    want_vectors: bool = True,
    method: MethodEig = MethodEig.DC,
):
    """Generalized Hermitian eig (src/hegv.cc): potrf(B) -> hegst -> heev ->
    back-solve.  Returns (w, X, info) with X the B-orthonormal eigvecs."""
    from ..blas3.blas3 import trsm_array, trmm_array
    from ..types import Diag, Op, Side
    from .chol import potrf_array

    l, info = potrf_array(b, Uplo.Lower)
    c = hegst_array(a, l, itype)
    if not want_vectors:
        return heev_array(c, want_vectors=False, method=method), None, info
    w, z = heev_array(c, want_vectors=True, method=method)
    # Back-transform (hegv.cc:100-105): itype 1 and 2 both have y = L^H x,
    # so x = L^-H y (trsm); only itype 3 (B A x = lambda x) has x = L y.
    if itype in (1, 2):
        x = trsm_array(Side.Left, Uplo.Lower, Op.ConjTrans, Diag.NonUnit, 1.0, l, z)
    else:
        x = trmm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, 1.0, l, z)
    return w, x, info
