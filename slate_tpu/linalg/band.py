"""Windowed band factorizations: O(n band^2) work instead of dense O(n^3).

TPU-native analogues of ``src/pbtrf.cc`` / ``src/gbtrf.cc`` (+ solves):
the reference walks the band tile-by-tile so each step touches only the
O(band) trailing window; here each step is one iteration of a
``lax.fori_loop`` over SLAB storage — the band is packed into per-block-
column slabs of static shape, so the loop carry is O(n band), every
window is assembled from a handful of static slices, and the program is
O(1) in n.  (A dense (n, n) carry would force XLA to copy the whole
matrix per step — measured 7x slower than dense potrf on-chip; the slab
carry updates in place.)

Bandwidths are rounded up to multiples of the block size internally
(a superset band is still exact).  Band LU pivoting follows LAPACK gbtrf:
partial pivoting within the kl window, multipliers stay in place, and the
solve replays the per-window permutations — the packed factor is NOT
globally row-permuted like the dense getrf path.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.matmul import matmul
from .lu import _apply_bounded_perm, _panel_lu_masked, _swaps_to_perm

Array = jax.Array


def band_worthwhile(n: int, band: int) -> bool:
    """Windowed O(n band^2) beats the dense MXU path once the band is a
    small fraction of n (crossover measured in tests/test_band.py)."""
    return 4 * max(band, 1) <= n


def _pick_nb(band: int) -> int:
    return max(8, min(64, 1 << max(3, (max(band, 1) - 1).bit_length() - 1)))


def _round_up(x: int, mult: int) -> int:
    return ((max(x, 0) + mult - 1) // mult) * mult


def _pack_slabs(ap: Array, ns: int, nb: int, height: int, row_off: int) -> Array:
    """slabs[k] = ap[k*nb - row_off : +height, k*nb : +nb] via one gather."""
    ks = jnp.arange(ns)
    rows = ks[:, None, None] * nb - row_off + jnp.arange(height)[None, :, None]
    cols = ks[:, None, None] * nb + jnp.arange(nb)[None, None, :]
    return ap[rows, cols]


def _unpack_slabs(slabs: Array, npad: int, nb: int, row_off: int) -> Array:
    """Scatter slabs back into a zeroed (npad, npad) dense array."""
    ns, height, _ = slabs.shape
    ks = jnp.arange(ns)
    rows = ks[:, None, None] * nb - row_off + jnp.arange(height)[None, :, None]
    cols = ks[:, None, None] * nb + jnp.arange(nb)[None, None, :]
    out = jnp.zeros((npad, npad), slabs.dtype)
    return out.at[rows, cols].set(slabs, mode="drop")


# ---------------------------------------------------------------------------
# SPD band Cholesky (pbtrf / pbtrs)
# ---------------------------------------------------------------------------


class BandChol(NamedTuple):
    """Lower band Cholesky factor in dense storage + bandwidth."""

    l: Array
    kd: int
    nb: int
    info: Array


def pbtrf_band(a: Array, kd: int, nb: int = 0) -> BandChol:
    """Windowed lower band Cholesky (src/pbtrf.cc): per nb-block, factor
    the diagonal block, trsm the band-row panel under it, update only the
    (kd, kd) trailing window.  O(n kd^2) flops, O(n kd) loop state."""
    n = a.shape[0]
    nb = nb or _pick_nb(kd)
    kdr = _round_up(max(kd, 1), nb)  # rounded band; superset is exact
    c = kdr // nb
    w = kdr + nb
    nsteps = -(-n // nb)
    ns = nsteps + c  # extra slabs so window assembly never runs off the end
    npad = ns * nb + w
    # slabs hold LOWER-triangular content only; assemble() mirrors.
    # Project to the DECLARED band first: entries between kd and the
    # internally rounded band must not change the result (the dense
    # fallback path band-projects too)
    a = jnp.where(jnp.arange(n)[:, None] - jnp.arange(n)[None, :] <= kd, a, 0)
    ap = jnp.pad(jnp.tril(a), ((0, npad - n), (0, npad - n)))
    dpad = jnp.arange(n, npad)
    ap = ap.at[dpad, dpad].set(1)
    slabs = _pack_slabs(ap, ns, nb, w, 0)  # (ns, w, nb), rows kk..kk+w

    def assemble(slabs, k):
        """Full Hermitian (w, w) window rows/cols kk..kk+w."""
        win = jnp.zeros((w, w), slabs.dtype)
        for j in range(c + 1):
            piece = slabs[k + j]  # rows (k+j)nb .. +w
            win = win.at[j * nb :, j * nb : (j + 1) * nb].set(
                piece[: w - j * nb]
            )
        return win + jnp.conj(jnp.tril(win, -1)).T

    def scatter(slabs, k, win):
        win = jnp.tril(win)  # slabs keep the lower-only convention
        for j in range(c + 1):
            blk = win[j * nb :, j * nb : (j + 1) * nb]
            slabs = slabs.at[k + j, : w - j * nb, :].set(blk)
        return slabs

    def step(k, slabs):
        win = assemble(slabs, k)
        ld = lax.linalg.cholesky(win[:nb, :nb])
        pan = lax.linalg.triangular_solve(
            jnp.conj(ld).T[None], win[nb:, :nb][None],
            left_side=False, lower=False, transpose_a=False,
        )[0]
        trail = win[nb:, nb:] - matmul(pan, jnp.conj(pan).T).astype(win.dtype)
        win = win.at[:nb, :nb].set(jnp.tril(ld))
        win = win.at[nb:, :nb].set(pan)
        win = win.at[nb:, nb:].set(trail)
        return scatter(slabs, k, win)

    slabs = lax.fori_loop(0, nsteps, step, slabs)
    l = jnp.tril(_unpack_slabs(slabs, npad, nb, 0)[:n, :n])
    d = jnp.real(jnp.diagonal(l))
    bad = ~jnp.isfinite(d) | (d <= 0)
    info = jnp.where(jnp.any(bad), jnp.argmax(bad) + 1, 0).astype(jnp.int32)
    return BandChol(l, kd, nb, info)


def pbtrs_band(f: BandChol, b: Array) -> Array:
    """Banded forward + backward substitution, O(n kd nrhs); the RHS is
    the only O(n) loop state."""
    squeeze = b.ndim == 1
    bd = b[:, None] if squeeze else b
    n, nrhs = bd.shape
    nb = f.nb
    kdr = _round_up(max(f.kd, 1), nb)
    w = kdr + nb
    nsteps = -(-n // nb)
    ns = nsteps + kdr // nb
    npad = ns * nb + w
    lp = jnp.pad(f.l, ((0, npad - n), (0, npad - n)))
    dpad = jnp.arange(n, npad)
    lp = lp.at[dpad, dpad].set(1)
    slabs = _pack_slabs(lp, ns, nb, w, 0)
    yp = jnp.pad(bd.astype(f.l.dtype), ((0, npad - n), (0, 0)))

    def fwd(k, yp):
        kk = k * nb
        lw = slabs[k]  # (w, nb): diag block + kd rows below
        yw = lax.dynamic_slice(yp, (kk, 0), (w, nrhs))
        top = lax.linalg.triangular_solve(
            lw[:nb][None], yw[:nb][None], left_side=True, lower=True,
            transpose_a=False,
        )[0]
        bot = yw[nb:] - matmul(lw[nb:], top).astype(yp.dtype)
        return lax.dynamic_update_slice(yp, jnp.concatenate([top, bot]), (kk, 0))

    yp = lax.fori_loop(0, nsteps, fwd, yp)

    def bwd(s, yp):
        k = nsteps - 1 - s
        kk = k * nb
        lw = slabs[k]
        yw = lax.dynamic_slice(yp, (kk, 0), (w, nrhs))
        rhs = yw[:nb] - matmul(jnp.conj(lw[nb:]).T, yw[nb:]).astype(yp.dtype)
        top = lax.linalg.triangular_solve(
            jnp.conj(lw[:nb]).T[None], rhs[None], left_side=True, lower=False,
            transpose_a=False,
        )[0]
        return lax.dynamic_update_slice(yp, top, (kk, 0))

    yp = lax.fori_loop(0, nsteps, bwd, yp)
    x = yp[:n]
    return x[:, 0] if squeeze else x


def pbsv_band(a: Array, b: Array, kd: int):
    f = pbtrf_band(a, kd)
    return pbtrs_band(f, b), f, f.info


# ---------------------------------------------------------------------------
# General band LU with partial pivoting (gbtrf / gbtrs)
# ---------------------------------------------------------------------------


class BandLU(NamedTuple):
    """Windowed band LU: packed factors in dense storage, per-window
    permutations (LAPACK gbtrf pivot semantics), bandwidths."""

    lu: Array
    perms: Array  # (nsteps, wr): window-local row permutation per block
    kl: int
    ku: int
    nb: int
    info: Array


def _gb_geometry(kl: int, ku: int, nb: int):
    klr = _round_up(max(kl, 1), nb)
    kur = _round_up(max(ku, 1), nb)
    wr = nb + klr  # rows a block's elimination touches
    wc = nb + klr + kur  # cols (panel + fill-in reach)
    # pivoting can pull a row from klr below, carrying entries kur right of
    # ITS diagonal: U in column c reaches up to row c - klr - kur
    upoff = klr + kur
    hg = upoff + wr  # slab height: fill-in rows above + reach below
    return klr, kur, wr, wc, upoff, hg


def gbtrf_band(a: Array, kl: int, ku: int, nb: int = 0) -> BandLU:
    """Windowed band LU with partial pivoting (src/gbtrf.cc): per nb-block,
    pivoted panel LU of the (nb + kl)-row window (pivots stay within the
    kl reach), trailing update confined to the (nb + kl, kl + ku + nb)
    window; fill-in widens U to kl + ku as in LAPACK.  O(n kl (kl+ku))
    flops, O(n band) loop state."""
    n = a.shape[0]
    nb = nb or _pick_nb(max(kl, 1))
    klr, kur, wr, wc, upoff, hg = _gb_geometry(kl, ku, nb)
    cg = wc // nb  # column blocks a window spans
    nsteps = -(-n // nb)
    ns = nsteps + cg
    npad = ns * nb + hg + upoff
    # project to the declared (kl, ku) band (parity with the dense path)
    ij = jnp.arange(n)[:, None] - jnp.arange(n)[None, :]
    a = jnp.where((ij <= kl) & (-ij <= ku), a, 0)
    ap = jnp.pad(a, ((0, npad - n), (0, npad - n)))
    dpad = jnp.arange(n, npad)
    ap = ap.at[dpad, dpad].set(1)
    # slab k: rows kk-upoff .. kk+wr of column block k (negative rows of
    # the first slabs read zero padding via an offset copy)
    ap2 = jnp.pad(ap, ((upoff, 0), (0, 0)))
    slabs = _pack_slabs(ap2, ns, nb, hg, 0)  # offset folded into ap2's pad

    def assemble(slabs, k):
        """(wr, wc) window rows kk..kk+wr, cols kk..kk+wc."""
        win = jnp.zeros((wr, wc), slabs.dtype)
        for j in range(cg):
            # window rows t map to slab k+j local rows t + upoff - j*nb
            lo = max(0, j * nb - upoff)  # first window row in the slab
            s0 = lo + upoff - j * nb
            ln = min(wr - lo, hg - s0)
            piece = slabs[k + j][s0 : s0 + ln]
            win = win.at[lo : lo + ln, j * nb : (j + 1) * nb].set(piece)
        return win

    def scatter(slabs, k, win):
        for j in range(cg):
            lo = max(0, j * nb - upoff)
            s0 = lo + upoff - j * nb
            ln = min(wr - lo, hg - s0)
            blk = win[lo : lo + ln, j * nb : (j + 1) * nb]
            slabs = slabs.at[k + j, s0 : s0 + ln, :].set(blk)
        return slabs

    def step(k, carry):
        slabs, perms = carry
        win = assemble(slabs, k)
        pan, piv = _panel_lu_masked(win[:, :nb], 0, nb, wr)
        pv = _swaps_to_perm(piv, 0, wr, nb)
        targets = jnp.concatenate([jnp.arange(nb), piv])
        rest = _apply_bounded_perm(win[:, nb:], pv, targets)
        l11 = jnp.tril(pan[:nb], -1) + jnp.eye(nb, dtype=win.dtype)
        u12 = lax.linalg.triangular_solve(
            l11[None], rest[:nb][None], left_side=True, lower=True,
            transpose_a=False, unit_diagonal=True,
        )[0]
        trail = rest[nb:] - matmul(pan[nb:, :nb], u12).astype(win.dtype)
        win = jnp.concatenate(
            [pan, jnp.concatenate([u12, trail], axis=0)], axis=1
        )
        return scatter(slabs, k, win), perms.at[k].set(pv)

    perms0 = jnp.zeros((nsteps, wr), jnp.arange(1).dtype)
    slabs, perms = lax.fori_loop(0, nsteps, step, (slabs, perms0))
    lu = _unpack_slabs(slabs, npad + upoff, nb, 0)[upoff:, :][:n, :n]
    d = jnp.diagonal(lu)
    bad = (d == 0) | ~jnp.isfinite(jnp.abs(d))
    info = jnp.where(jnp.any(bad), jnp.argmax(bad) + 1, 0).astype(jnp.int32)
    return BandLU(lu, perms.astype(jnp.int32), kl, ku, nb, info)


def gbtrs_band(f: BandLU, b: Array) -> Array:
    """Solve from windowed band-LU factors: forward sweep replays each
    window's permutation + elimination, backward sweep solves the banded
    U.  O(n (kl + ku) nrhs)."""
    squeeze = b.ndim == 1
    bd = b[:, None] if squeeze else b
    n, nrhs = bd.shape
    nsteps, wr = f.perms.shape
    nb = f.nb
    klr, kur, wr2, wc, upoff, hg = _gb_geometry(f.kl, f.ku, nb)
    assert wr2 == wr, (wr2, wr)
    npad = (nsteps + wc // nb) * nb + hg + upoff
    lup = jnp.pad(f.lu, ((0, npad - n), (0, npad - n)))
    dpad = jnp.arange(n, npad)
    lup = lup.at[dpad, dpad].set(1)
    yp = jnp.pad(bd.astype(f.lu.dtype), ((0, npad - n), (0, 0)))

    def fwd(k, yp):
        kk = k * nb
        yw = lax.dynamic_slice(yp, (kk, 0), (wr, nrhs))
        yw = yw[f.perms[k]]
        lw = lax.dynamic_slice(lup, (kk, kk), (wr, nb))
        l11 = jnp.tril(lw[:nb], -1) + jnp.eye(nb, dtype=f.lu.dtype)
        top = lax.linalg.triangular_solve(
            l11[None], yw[:nb][None], left_side=True, lower=True,
            transpose_a=False, unit_diagonal=True,
        )[0]
        bot = yw[nb:] - matmul(lw[nb:], top).astype(yp.dtype)
        return lax.dynamic_update_slice(yp, jnp.concatenate([top, bot]), (kk, 0))

    yp = lax.fori_loop(0, nsteps, fwd, yp)

    def bwd(s, yp):
        k = nsteps - 1 - s
        kk = k * nb
        uw = lax.dynamic_slice(lup, (kk, kk), (nb, wc))
        yw = lax.dynamic_slice(yp, (kk, 0), (wc, nrhs))
        rhs = yw[:nb] - matmul(uw[:, nb:], yw[nb:]).astype(yp.dtype)
        top = lax.linalg.triangular_solve(
            jnp.triu(uw[:nb, :nb])[None], rhs[None], left_side=True,
            lower=False, transpose_a=False,
        )[0]
        return lax.dynamic_update_slice(yp, top, (kk, 0))

    yp = lax.fori_loop(0, nsteps, bwd, yp)
    x = yp[:n]
    return x[:, 0] if squeeze else x


def gbsv_band(a: Array, b: Array, kl: int, ku: int):
    f = gbtrf_band(a, kl, ku)
    return gbtrs_band(f, b), f, f.info
