"""Cholesky family: potrf / potrs / posv / potri + band pbtrf / pbtrs / pbsv.

Analogue of reference drivers ``src/{potrf,potrs,posv,potri,pbtrf,pbtrs,
pbsv}.cc`` and ``src/internal/internal_potrf.cc``.

Design inversion: the reference potrf is an OpenMP task DAG — per-k panel
factor of the diagonal tile, column trsm, listBcastMT of the panel, herk
trailing update with lookahead queues (src/potrf.cc:91-196).  The TPU-native
form is a *recursive blocked* factorization: split at a power-of-two
boundary, factor the leading block, one big trsm, one big herk, recurse on
the trailing block.  Same flops (n^3/3), O(log n) distinct subproblem shapes
(static shapes for XLA), and the lookahead/broadcast pipeline is recovered by
XLA's scheduler + GSPMD collectives instead of a runtime.  The nb x nb base
case delegates to XLA's Cholesky op exactly as the reference delegates the
diagonal-tile factor to vendor LAPACK (internal_potrf.cc -> lapack::potrf).
"""

from __future__ import annotations

from ..obs import instrument

import functools
from dataclasses import replace
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ..blas3.blas3 import _NB, _split, trsm_array
from ..core.matrix import (
    TriangularBandMatrix,
    BaseMatrix,
    HermitianBandMatrix,
    HermitianMatrix,
    TriangularMatrix,
    band_project,
    symmetrize,
    tri_project,
)
from ..ops.matmul import matmul
from ..ops.pallas_ops import chol_diag_inv_pallas, panel_engaged
from ..types import Diag, Op, Options, Side, Uplo

ArrayLike = Union[jax.Array, BaseMatrix]


def _potrf_lower(a: jax.Array) -> jax.Array:
    """Recursive lower Cholesky of a full Hermitian array; NaN-poisons on
    non-SPD input (converted to an info code by the driver)."""
    n = a.shape[0]
    if n <= _NB:
        return jax.lax.linalg.cholesky(a)
    h = _split(n)
    a11, a21, a22 = a[:h, :h], a[h:, :h], a[h:, h:]
    l11 = _potrf_lower(a11)
    # L21 = A21 * L11^-H  (solve X L11^H = A21)
    l21 = trsm_array(Side.Right, Uplo.Lower, Op.ConjTrans, Diag.NonUnit, 1.0, l11, a21)
    # trailing update: A22 - L21 L21^H (herk)
    upd = matmul(l21, jnp.conj(l21).T)
    l22 = _potrf_lower(a22 - upd.astype(a.dtype))
    z = jnp.zeros((h, n - h), a.dtype)
    return jnp.block([[l11, z], [l21, l22]])


def _potrf_scan(a: jax.Array, nb: int = 256, nbuckets: int = 4) -> jax.Array:
    """Single-program scanned lower Cholesky: lax.fori_loop over panels
    with static shapes, O(1) HLO size in n (the recursive trace explodes
    at north-star sizes — cf. lu.getrf_scan_array).  The k-range is
    segmented into ``nbuckets`` statically-shrinking trailing views (cf.
    parallel.dist_chol), cutting the HBM-bound masked trailing traffic to
    ~0.47x of the full-width form at 4 buckets; every flop is an MXU
    gemm.  Input must be full Hermitian."""
    n = a.shape[0]
    nsteps = -(-n // nb)
    np_ = nsteps * nb
    ap = jnp.pad(a, ((0, np_ - n), (0, np_ - n)))
    dpad = jnp.arange(n, np_)
    ap = ap.at[dpad, dpad].set(1)
    cplx = jnp.issubdtype(a.dtype, jnp.complexfloating)

    bounds = [nsteps * g // nbuckets for g in range(nbuckets)] + [nsteps]
    for g in range(nbuckets):
        k0, k1 = bounds[g], bounds[g + 1]
        if k0 == k1:
            continue
        off = k0 * nb
        view = ap[off:, off:]
        nv = np_ - off
        rows = jnp.arange(nv)

        def step(k, view, off=off, nv=nv, rows=rows):
            kk = k * nb - off  # view-local panel head
            dblk = jax.lax.dynamic_slice(view, (kk, kk), (nb, nb))
            col = jax.lax.dynamic_slice(view, (0, kk), (nv, nb))
            # panel solve as explicit-inverse gemm (MAGMA-style trtri+gemm):
            # XLA's big-rhs triangular_solve runs at ~1/10 the MXU matmul
            # rate at (32768, 256) (measured 46 vs 4 ms), and inverting only
            # the nb x nb diag block keeps the backward error at the same
            # O(eps * cond(L_kk)) class.  Under Option.PanelImpl=pallas the
            # factor + inverse pair is ONE fused on-chip kernel instead of
            # the per-column cholesky + triangular_solve dispatch chain.
            if panel_engaged(view.dtype, nb * nb * view.dtype.itemsize):
                ld, linv = chol_diag_inv_pallas(dblk)
            else:
                ld = jax.lax.linalg.cholesky(dblk)
                eye_nb = jnp.eye(nb, dtype=view.dtype)
                linv = jax.lax.linalg.triangular_solve(
                    ld[None], eye_nb[None], left_side=True, lower=True,
                    transpose_a=False,
                )[0]
            linv_h = jnp.conj(linv).T if cplx else linv.T
            sol = matmul(col, linv_h).astype(view.dtype)
            below = (rows >= kk + nb)[:, None]
            ondiag = ((rows >= kk) & (rows < kk + nb))[:, None]
            dpat = jax.lax.dynamic_update_slice(
                jnp.zeros((nv, nb), view.dtype), jnp.tril(ld), (kk, 0)
            )
            newcol = jnp.where(below, sol, jnp.where(ondiag, dpat, col))
            view = jax.lax.dynamic_update_slice(view, newcol, (0, kk))
            l21 = newcol * below.astype(view.dtype)
            upd = matmul(l21, jnp.conj(l21).T if cplx else l21.T)
            return view - upd.astype(view.dtype)

        view = jax.lax.fori_loop(k0, k1, step, view)
        ap = ap.at[off:, off:].set(view)
    return ap[:n, :n]


def _potrf_and_inv(a: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(L, L^-1) of a full Hermitian block, jointly, ALL-GEMM.

    The plain recursive factor (_potrf_lower) spends its time in f64
    triangular solves (XLA's emulated trsm crawls: measured 52 GF/s for
    the whole 4096 diag factor while the surrounding Ozaki updates run
    2-3 TF/s-eq).  Computing the inverse ALONGSIDE the factor removes
    every solve: l21 = a21 inv11^H and inv21 = -inv22 l21 inv11 are
    gemms, so the recursion's O(n^3) all rides the matmul dispatch
    (Ozaki above the win gate, tuned f32-pair emulation below), and the
    panel solve gets L^-1 for free — no separate trtri recursion.
    Error class is the explicit-inverse O(eps cond) trade already used by
    the scan panels (ADVICE r3: bounded by the ill-conditioned fixture
    tests)."""
    n = a.shape[0]
    cplx = jnp.issubdtype(a.dtype, jnp.complexfloating)
    if n <= _NB:
        if panel_engaged(a.dtype, n * n * a.dtype.itemsize):
            # fused on-chip factor + inverse: one kernel dispatch for the
            # whole leaf instead of the unrolled cholesky/trsm micro-op
            # chains (exact column-loop math for every engaged dtype, so
            # the f32-seeded f64 refinement below is not needed)
            return chol_diag_inv_pallas(a)
        if a.dtype == jnp.dtype(jnp.float64):
            return _potrf_inv_base_f64(a)
        l = jax.lax.linalg.cholesky(a)
        eye = jnp.eye(n, dtype=a.dtype)
        linv = jax.lax.linalg.triangular_solve(
            l[None], eye[None], left_side=True, lower=True, transpose_a=False
        )[0]
        return l, linv
    h = _split(n)
    l11, i11 = _potrf_and_inv(a[:h, :h])
    l21 = matmul(a[h:, :h], jnp.conj(i11).T if cplx else i11.T).astype(a.dtype)
    upd = matmul(l21, jnp.conj(l21).T if cplx else l21.T)
    l22, i22 = _potrf_and_inv(a[h:, h:] - upd.astype(a.dtype))
    i21 = -matmul(i22, matmul(l21, i11).astype(a.dtype)).astype(a.dtype)
    z = jnp.zeros((h, n - h), a.dtype)
    l = jnp.block([[l11, z], [l21, l22]])
    linv = jnp.block([[i11, z], [i21, i22]])
    return l, linv


def _potrf_inv_base_f64(a: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """f32-seeded, f64-refined (L, L^-1) of a small f64 block.

    TPU has no native f64 LAPACK ops: lax.linalg.cholesky/triangular_solve
    under the x64 rewriter unroll into ~16k serialized micro-ops per
    256-block (profiled: the leaf chains were 1.8s of the 2.0s n = 16384
    f64 factorization — the MXU gemms around them are ~0.4s).  Here the
    leaf runs the NATIVE f32 cholesky + inverse (fast, few ops), then
    three coupled refinement sweeps in f64 — each a handful of vectorized
    small gemms:

        E = X (A - L L^T) X^T          (backward error in L-coordinates)
        L <- L (I + low(E)),  low = strict lower + half diagonal
        X <- X (2 I - L X)             (Newton resync of the inverse)

    ||E|| starts at ~eps32 * cond(block) and squares per sweep, so three
    sweeps reach the eps64 * cond floor for cond(block) up to ~1e4; a
    residual-gated lax.cond falls back to the exact (slow) f64 path for
    blocks where the seed failed or refinement stalled — correctness never
    depends on the block's conditioning, only speed does."""
    n = a.shape[0]
    dt = a.dtype
    a32 = a.astype(jnp.float32)
    l32 = jax.lax.linalg.cholesky(a32)
    x32 = jax.lax.linalg.triangular_solve(
        l32[None], jnp.eye(n, dtype=jnp.float32)[None], left_side=True, lower=True
    )[0]
    seed_ok = jnp.all(jnp.isfinite(l32))
    l = jnp.tril(jnp.where(jnp.isfinite(l32), l32, 0)).astype(dt)
    x = jnp.tril(jnp.where(jnp.isfinite(x32), x32, 0)).astype(dt)
    eye = jnp.eye(n, dtype=dt)
    half_low = jnp.tril(jnp.ones((n, n), dt), -1) + 0.5 * eye
    for _ in range(3):
        r = a - l @ l.T
        e = x @ r @ x.T
        l = l + l @ (e * half_low)
        x = x @ (2.0 * eye - l @ x)
    resid = jnp.linalg.norm(a - l @ l.T)
    tol = 1e3 * n * jnp.finfo(dt).eps * jnp.linalg.norm(a)
    # gate the INVERSE too (ADVICE r4): X feeds every panel solve via
    # below = panel @ linv.T, and a stalled Newton resync can leave X
    # several digits behind while L alone passes its residual gate
    resid_x = jnp.linalg.norm(eye - l @ x)
    tol_x = 1e3 * n * jnp.finfo(dt).eps * jnp.linalg.norm(x) * jnp.linalg.norm(l)
    good = (
        seed_ok
        & jnp.isfinite(resid)
        & (resid <= tol)
        & jnp.isfinite(resid_x)
        & (resid_x <= tol_x)
    )

    def exact():
        le = jax.lax.linalg.cholesky(a)
        xe = jax.lax.linalg.triangular_solve(
            le[None], eye[None], left_side=True, lower=True
        )[0]
        return le, xe

    return jax.lax.cond(good, lambda: (jnp.tril(l), jnp.tril(x)), exact)


def _potrf_left_looking(a: jax.Array, nb: Optional[int] = None) -> jax.Array:
    """Left-looking blocked lower Cholesky with STATIC per-panel shapes.

    Built for f64 on TPU (VERDICT r4 item 1): every O(n^3) flop lands in a
    large-k gemm — panel update ``A[k:,k] -= L[k:,:k] L[k,:k]^H`` has
    k = j*nb contraction and an nb-wide output, exactly the shapes where
    the int8-MXU Ozaki dispatch (ops/matmul.py gate) wins — while the
    right-looking forms spend the same flops at rank-nb thin-k shapes
    where f64 pays ~5x.  The Python panel loop unrolls n/nb static
    steps (no masking waste, exact n^3/3 flops); only the nb x nb
    diagonal factor recurses.  Same math as the reference's potrf task
    graph read column-wise (src/potrf.cc:91-196)."""
    n = a.shape[0]
    if nb is None:
        # measured on v5e (round 4, n=16384 f64): nb=4096 -> 724 GF/s,
        # nb=2048 -> 569; the bigger panel amortizes the recursive diag
        # factor against far larger Ozaki updates
        nb = 4096 if n >= 16384 else 2048
    if n <= nb:
        return _potrf_lower(a)
    nsteps = -(-n // nb)
    ap, _ = _potrf_ll_pad(a, nsteps, nb)
    for j in range(nsteps):
        ap = _potrf_ll_panel_step(ap, j * nb, nb)
    return tri_project(ap[:n, :n], Uplo.Lower)


def _potrf_ll_pad(a: jax.Array, nsteps: int, nb: int):
    """Shared left-looking prelude: pad to a panel multiple with a unit
    diagonal in the pad block (exact: diag(A, I) factors to diag(L, I)).
    Returns (padded matrix, fresh_buffer) — fresh_buffer False means the
    result IS the caller's array."""
    n = a.shape[0]
    np_ = nsteps * nb
    if np_ == n:
        return a, False
    ap = jnp.pad(a, ((0, np_ - n), (0, np_ - n)))
    dpad = jnp.arange(n, np_)
    return ap.at[dpad, dpad].set(1), True


def _potrf_ll_panel_step(ap: jax.Array, r0: int, nb: int) -> jax.Array:
    """One left-looking panel step on the padded in-place matrix: subtract
    the factored history's contribution (a large-k gemm), factor the
    diagonal block jointly with its inverse, solve the below-panel rows
    as a gemm, write back."""
    cplx = jnp.issubdtype(ap.dtype, jnp.complexfloating)
    panel = ap[r0:, r0 : r0 + nb]
    if r0:
        left = ap[r0:, :r0]  # factored L[r0:, :r0]
        lrow = left[:nb]  # rows r0..r0+nb of L's first r0 columns
        upd = matmul(left, jnp.conj(lrow).T if cplx else lrow.T)
        panel = panel - upd.astype(ap.dtype)
    dblk, linv = _potrf_and_inv(panel[:nb])
    if panel.shape[0] > nb:
        below = matmul(panel[nb:], jnp.conj(linv).T if cplx else linv.T)
        panel = jnp.concatenate([dblk, below.astype(ap.dtype)], axis=0)
    else:
        panel = dblk
    return jax.lax.dynamic_update_slice(ap, panel, (r0, r0))


@functools.partial(jax.jit, static_argnames=("r0", "nb"), donate_argnums=0)
def _potrf_ll_step_jit(ap, r0: int, nb: int):
    return _potrf_ll_panel_step(ap, r0, nb)


@functools.partial(jax.jit, static_argnames=("n",), donate_argnums=0)
def _potrf_ll_finale_jit(ap, n: int):
    # donated: an EAGER tri_project here would allocate a second full
    # matrix next to ap, breaking the staged form's one-matrix peak
    return tri_project(ap[:n, :n], Uplo.Lower)


@functools.partial(jax.jit, static_argnames=("n",))
def _potrf_ll_finale_pad_jit(ap, n: int):
    # padded runs: the (n, n) output cannot alias the larger padded buffer,
    # so donating ap would only trip XLA's unusable-donation warning; the
    # output here is strictly smaller than ap, keeping peak < 2 matrices
    return tri_project(ap[:n, :n], Uplo.Lower)


def potrf_left_looking_staged(
    a: jax.Array, nb: Optional[int] = None, donate: bool = False
) -> jax.Array:
    """Left-looking f64 Cholesky with ONE DONATED XLA PROGRAM PER PANEL.

    The fused single-program form keeps ~7 live copies of the matrix
    (XLA's buffer assignment across the unrolled panel chain: measured
    14.4 GB peak for the 2 GB n = 16384 problem — the calibration point
    of ``obs.memmodel.FUSED_LL_COPIES``), which OOMs v5e at n = 32768
    (8 GB matrix).  Dispatching each panel as its own jit with the
    matrix donated caps peak HBM at one matrix + one panel's transients
    (``memmodel.potrf_staged_peak``).  Call EAGERLY (under an outer jit
    the stages inline and the fused-liveness problem returns) — cf.
    eig.heev_staged.

    ``donate=True`` CONSUMES the caller's array (required at n = 32768 on
    v5e: a defensive copy next to the 8 GB input would itself OOM; the
    caller must not reuse ``a``).  The default keeps the input intact by
    copying when the padding step would not already produce a fresh
    buffer."""
    n = a.shape[0]
    if nb is None:
        nb = 4096 if n >= 16384 else 2048
    if n <= nb:
        return _potrf_lower(a)
    nsteps = -(-n // nb)
    ap, fresh = _potrf_ll_pad(a, nsteps, nb)
    if not fresh and not donate:
        ap = jnp.array(ap, copy=True)  # first step's donation eats a copy
    for j in range(nsteps):
        ap = _potrf_ll_step_jit(ap, r0=j * nb, nb=nb)
    if ap.shape[0] == n:  # donation aliasable only when shapes match
        return _potrf_ll_finale_jit(ap, n=n)
    return _potrf_ll_finale_pad_jit(ap, n=n)


def _potrf_ll_ozaki(a: jax.Array, nb: Optional[int] = None, n_slices: Optional[int] = None) -> jax.Array:
    """Left-looking f64 lower Cholesky with a persistent Ozaki digit cache.

    The plain left-looking form (above) re-splits the factored history into
    int8 digit planes inside every panel-update GEMM (ops/ozaki.py splits
    per call).  Cholesky admits an exact a-priori row bound that makes the
    splits cacheable: sum_j L[i,j]^2 = A[i,i], so |L[i,j]| <= sqrt(A[i,i])
    for every j — fixing each row's digit grid at 2^e[i] > sqrt(A[i,i])
    BEFORE factoring means every panel's planes share the row scaling and
    concatenate exactly along the contraction axis.  Each factored panel is
    split ONCE into a (S, n, n) int8 cache; each panel update is then ONE
    plane-level GEMM (ops/ozaki.matmul_planes) over the full history with a
    single epilogue — no per-use splits, no per-panel partial sums.

    The bound is looser than the true row max by at most sqrt(row length)
    (mass-spread worst case), i.e. <= 7 lost top bits at n = 16384.  S = 9
    matches the split-per-call path's measured accuracy on well- AND
    ill-conditioned fixtures (the residual floor is the explicit-inverse
    panel solve, not the digit tail; test_chol.py gates both); above
    n = 8192 the default is S = 10 (+22% MXU work), which covers even the
    mass-spread worst case where the bound's slack exceeds one 6-bit plane.
    Peak HBM is modeled by ``obs.memmodel.potrf_ozaki_cache_peak``: the
    S n^2 int8 plane cache next to ~4 full f64 buffers — the dispatch in
    potrf_array gates this path to sizes where
    ``memmodel.potrf_f64_form`` says cache + matrix fit the HBM budget
    and falls back to the split-per-call form above.

    Same math as the reference potrf task graph read column-wise
    (src/potrf.cc:91-196); the digit cache is the TPU-native analogue of
    keeping the factored panels resident on-device for the trailing herk.
    """
    from ..ops.ozaki import _row_exp, matmul_planes, split_rows

    n = a.shape[0]
    if n_slices is None:
        # ADVICE r4: the sqrt(diag) row bound can be loose by up to
        # log2(sqrt(n)) bits in the mass-spread worst case — more than one
        # 6-bit plane past n ~ 8192 — so S = 10 there (+22% MXU work)
        # keeps the worst case inside the digit tail; S = 9 matches the
        # split-per-call accuracy below that
        n_slices = 10 if n > 8192 else 9
    if nb is None:
        nb = 4096 if n >= 16384 else 2048
    if n <= nb:
        return _potrf_lower(a)
    nsteps = -(-n // nb)
    np_ = nsteps * nb
    if np_ != n:
        ap = jnp.pad(a, ((0, np_ - n), (0, np_ - n)))
        dpad = jnp.arange(n, np_)
        ap = ap.at[dpad, dpad].set(1)
    else:
        ap = a
    # fixed per-row digit grid from the exact row bound sqrt(diag)
    e = _row_exp(jnp.sqrt(jnp.maximum(jnp.real(jnp.diagonal(ap)), 0)).astype(jnp.float32))[:, None]
    q = jnp.zeros((n_slices, np_, np_), jnp.int8)
    for j in range(nsteps):
        r0 = j * nb
        panel = ap[r0:, r0 : r0 + nb]
        if j:
            upd = matmul_planes(q[:, r0:, :r0], e[r0:], q[:, r0 : r0 + nb, :r0], e[r0 : r0 + nb])
            panel = panel - upd
        dblk, linv = _potrf_and_inv(panel[:nb])
        dblk = jnp.tril(dblk)
        if panel.shape[0] > nb:
            below = matmul(panel[nb:], linv.T)
            cpanel = jnp.concatenate([dblk, below.astype(ap.dtype)], axis=0)
        else:
            cpanel = dblk
        if j + 1 < nsteps:  # the last panel is never read back
            qc, _ = split_rows(cpanel, n_slices, e[r0:])
            q = jax.lax.dynamic_update_slice(q, qc, (0, r0, r0))
        ap = jax.lax.dynamic_update_slice(ap, cpanel, (r0, r0))
    return tri_project(ap[:n, :n], Uplo.Lower)


_POTRF_SCAN_MIN_N = 16384  # above this the recursive trace is too large
_POTRF_LL_MIN_N = 4096  # f64/c128: left-looking beats recursion from here


def _is_f64(dtype) -> bool:
    return dtype in (jnp.dtype(jnp.float64), jnp.dtype(jnp.complex128))


def _potrf_f64_form(n: int, concrete: bool, ozaki_dispatch: bool,
                    itemsize: int = 8) -> str:
    """ozaki | staged | fused for one big-f64/c128 factorization, by
    MODELED peak HBM against the live budget — the hand-computed
    digit-cache / staged ceilings this module used to hard-code.  The
    routing rules and their on-chip calibration points are documented at
    the single source, ``obs.memmodel.potrf_f64_form``."""
    from ..obs import memmodel

    return memmodel.potrf_f64_form(n, concrete, ozaki_dispatch,
                                   itemsize=itemsize)


@instrument("potrf_array")
def potrf_array(a: jax.Array, uplo: Uplo = Uplo.Lower) -> Tuple[jax.Array, jax.Array]:
    """Factor A = L L^H (or U^H U). ``a`` holds the uplo triangle (other
    triangle ignored). Returns (factor triangle, info); info = 0 on success
    else 1 + index of first non-positive pivot (src/potrf.cc:253-256)."""
    full = symmetrize(a, uplo, conj=jnp.issubdtype(a.dtype, jnp.complexfloating))
    if _is_f64(a.dtype) and a.shape[0] >= _POTRF_LL_MIN_N:
        # f64 rides the left-looking form: large-k updates hit the Ozaki
        # dispatch win region (measured 235 vs 211 GF/s at n=8192, 569
        # GF/s at 16384 vs 82 for the right-looking scan, v5e round 4).
        # Which left-looking variant is a MEMORY decision, made by the
        # analytic model against the HBM budget (_potrf_f64_form).
        from ..ops.matmul import _F64_DISPATCH, _tpu_is_default

        ozaki_ok = (
            a.dtype == jnp.dtype(jnp.float64)
            and _F64_DISPATCH["ozaki"]
            and _tpu_is_default()
        )
        form = _potrf_f64_form(
            a.shape[0], not isinstance(full, jax.core.Tracer), ozaki_ok,
            itemsize=jnp.dtype(a.dtype).itemsize,  # c128 peaks 2x f64
        )
        if form == "ozaki":
            l = _potrf_ll_ozaki(full)
        elif form == "staged":
            # ``full`` is the symmetrize intermediate owned here, so
            # donating it never touches the caller's array.
            l = potrf_left_looking_staged(full, donate=True)
        else:
            l = _potrf_left_looking(full)
    elif a.shape[0] > _POTRF_SCAN_MIN_N:
        l = _potrf_scan(full)
    else:
        l = _potrf_lower(full)
    d = jnp.real(jnp.diagonal(l))
    bad = ~(jnp.isfinite(d) & (d > 0))
    info = jnp.where(jnp.any(bad), jnp.argmax(bad) + 1, 0).astype(jnp.int32)
    l = tri_project(l, Uplo.Lower)
    if uplo == Uplo.Upper:
        return jnp.conj(l).T, info
    return l, info


def potrf(a: ArrayLike, opts: Optional[Options] = None):
    """slate::potrf driver (src/potrf.cc:261)."""
    if isinstance(a, BaseMatrix):
        f, info = potrf_array(a.data, a.uplo)
        return TriangularMatrix(data=f, uplo=a.uplo), info
    f, info = potrf_array(jnp.asarray(a), Uplo.Lower)
    return TriangularMatrix(data=f, uplo=Uplo.Lower), info


def potrs_array(l: jax.Array, b: jax.Array, uplo: Uplo = Uplo.Lower) -> jax.Array:
    """Solve A X = B given the Cholesky factor (src/potrs.cc)."""
    if uplo == Uplo.Lower:
        y = trsm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, 1.0, l, b)
        return trsm_array(Side.Left, Uplo.Lower, Op.ConjTrans, Diag.NonUnit, 1.0, l, y)
    y = trsm_array(Side.Left, Uplo.Upper, Op.ConjTrans, Diag.NonUnit, 1.0, l, b)
    return trsm_array(Side.Left, Uplo.Upper, Op.NoTrans, Diag.NonUnit, 1.0, l, y)


def potrs(factor: TriangularMatrix, b: ArrayLike):
    out = potrs_array(factor.data, b.array if isinstance(b, BaseMatrix) else jnp.asarray(b), factor.uplo)
    if isinstance(b, BaseMatrix):
        return replace(b, data=out)
    return out


@instrument("posv_array")
def posv_array(a: jax.Array, b: jax.Array, uplo: Uplo = Uplo.Lower):
    """Factor + solve (src/posv.cc). Returns (x, factor, info)."""
    f, info = potrf_array(a, uplo)
    x = potrs_array(f, b, uplo)
    return x, f, info


def posv(a: ArrayLike, b: ArrayLike, opts: Optional[Options] = None):
    uplo = a.uplo if isinstance(a, BaseMatrix) else Uplo.Lower
    ad = a.data if isinstance(a, BaseMatrix) else jnp.asarray(a)
    bd = b.array if isinstance(b, BaseMatrix) else jnp.asarray(b)
    x, f, info = posv_array(ad, bd, uplo)
    if isinstance(b, BaseMatrix):
        x = replace(b, data=x)
    return x, TriangularMatrix(data=f, uplo=uplo), info


def potri_array(l: jax.Array, uplo: Uplo = Uplo.Lower) -> jax.Array:
    """A^-1 from the Cholesky factor (src/potri.cc): trtri then trtrm
    (lauum-style triangle product)."""
    from .tri import trtri_array, trtrm_array

    linv = trtri_array(l, uplo, Diag.NonUnit)
    if uplo == Uplo.Lower:
        # A^-1 = L^-H L^-1: lower-stored result
        return trtrm_array(linv, Uplo.Lower)
    return trtrm_array(linv, Uplo.Upper)


def potri(factor: TriangularMatrix):
    inv = potri_array(factor.data, factor.uplo)
    return HermitianMatrix(data=inv, uplo=factor.uplo)


# ---------------------------------------------------------------------------
# Band Cholesky (src/pbtrf.cc, pbtrs.cc, pbsv.cc)
# ---------------------------------------------------------------------------


def _band_worthwhile(n: int, band: int) -> bool:
    from .band import band_worthwhile

    return band_worthwhile(n, band)


def pbtrf_array(a: jax.Array, kd: int, uplo: Uplo = Uplo.Lower) -> Tuple[jax.Array, jax.Array]:
    """Band Cholesky (src/pbtrf.cc).  Narrow bands take the windowed
    O(n kd^2) path (linalg.band.pbtrf_band); wide bands ride the dense
    recursive MXU factorization + band projection (exact either way)."""
    kl, ku = (kd, 0) if uplo == Uplo.Lower else (0, kd)
    if uplo == Uplo.Lower and _band_worthwhile(a.shape[0], kd):
        from .band import pbtrf_band

        f = pbtrf_band(a, kd)
        return f.l, f.info
    f, info = potrf_array(band_project(a, kl, ku), uplo)
    return band_project(f, kl, ku), info


def pbtrs_array(f: jax.Array, b: jax.Array, kd: int, uplo: Uplo = Uplo.Lower) -> jax.Array:
    if uplo == Uplo.Lower and _band_worthwhile(f.shape[0], kd):
        from .band import BandChol, pbtrs_band, _pick_nb

        fb = BandChol(f, kd, _pick_nb(kd), jnp.zeros((), jnp.int32))
        return pbtrs_band(fb, b)
    return potrs_array(f, b, uplo)


def pbsv_array(a: jax.Array, b: jax.Array, kd: int, uplo: Uplo = Uplo.Lower):
    f, info = pbtrf_array(a, kd, uplo)
    return pbtrs_array(f, b, kd, uplo), f, info


def pbsv(a: HermitianBandMatrix, b: ArrayLike, opts: Optional[Options] = None):
    bd = b.array if isinstance(b, BaseMatrix) else jnp.asarray(b)
    x, f, info = pbsv_array(a.data, bd, a.kd, a.uplo)
    if isinstance(b, BaseMatrix):
        x = replace(b, data=x)
    return x, TriangularBandMatrix.from_array(f, a.uplo, a.kd), info
