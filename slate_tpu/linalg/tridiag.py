"""Symmetric tridiagonal eigensolvers: sterf (values), steqr (QR iteration
with vectors), stedc (divide & conquer).

Analogues of the reference's tridiag tier (SURVEY §2.4): ``src/sterf.cc``
(LAPACK dsterf passthrough), ``src/steqr2.cc`` + ``src/{s,d,c,z}steqr2.f``
(modified LAPACK QR iteration updating a distributed Z), and ``src/stedc*.cc``
(divide & conquer: split / solve / merge via secular equation, ~1,700 LoC).

TPU design notes:
- sterf/steqr are inherently sequential Givens recurrences; they run as
  ``lax.while_loop``s with masked fixed-shape updates (the reference runs
  them single-node on the host, heev.cc:115-148 — same locality story).
- steqr's Z update applies each rotation to two length-n columns — the
  vectorizable part, exactly what SLATE_DSTEQR2 distributes over ranks.
- stedc is the TPU-native fast path for vectors: the merge's eigenvector
  assembly is one big matmul per level (MXU), and the secular-equation Newton
  iteration vectorizes over all roots at once.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


PRECISE = lax.Precision.HIGHEST


def _wilkinson_shift(a, b, c):
    """Eigenvalue of [[a, b], [b, c]] closest to c (LAPACK convention)."""
    d = (a - c) / 2
    sgn = jnp.where(d >= 0, 1.0, -1.0)
    denom = d + sgn * jnp.sqrt(d * d + b * b)
    denom = jnp.where(denom == 0, jnp.finfo(a.dtype).tiny, denom)
    return c - b * b / denom


def _steqr_impl(d, e, z: Optional[jax.Array], max_sweeps: int):
    """Shared implicit-shift QR iteration on (d, e); rotates z's columns if
    given.  Fixed-shape masked formulation: each outer iteration finds the
    active unreduced window [lo, hi] (smallest split containing the first
    unconverged off-diagonal) and runs one bulge-chase sweep across it."""
    n = d.shape[0]
    dtype = d.dtype
    eps = jnp.finfo(dtype).eps
    idx = jnp.arange(n - 1) if n > 1 else jnp.arange(0)
    has_z = z is not None
    zz = z if has_z else jnp.zeros((1, 1), dtype)

    def negligible(d, e):
        # |e_i| <= eps * sqrt(|d_i| |d_i+1|) -> treat as zero (dsteqr test)
        thresh = eps * jnp.sqrt(jnp.abs(d[:-1]) * jnp.abs(d[1:])) + jnp.finfo(dtype).tiny
        return jnp.abs(e) <= thresh

    def cond(state):
        d, e, zz, it = state
        return (it < max_sweeps) & ~jnp.all(negligible(d, e))

    def sweep(state):
        d, e, zz, it = state
        negl = negligible(d, e)
        e = jnp.where(negl, 0.0, e)
        # active window: first non-negligible off-diagonal lo, extend to the
        # next negligible one after it
        active = ~negl
        lo = jnp.argmax(active)  # first True (there is one, else cond ended)
        after = negl & (idx > lo)
        hi = jnp.where(jnp.any(after), jnp.argmax(after), n - 1)
        # hi = last index of window (inclusive, in d-space)

        shift = _wilkinson_shift(d[hi - 1], e[hi - 1], d[hi])

        # one implicit QR sweep lo..hi: sequential Givens recurrence
        def rot_body(k, carry):
            d, e, zz, x, zbulge = carry
            inside = (k >= lo) & (k < hi)
            # rotation annihilating zbulge against x at position k
            r = jnp.hypot(x, zbulge)
            r = jnp.where(r == 0, jnp.finfo(dtype).tiny, r)
            cs = jnp.where(inside, x / r, 1.0)
            sn = jnp.where(inside, zbulge / r, 0.0)

            dk = d[k]
            dk1 = d[jnp.minimum(k + 1, n - 1)]
            ek = e[k]
            # apply G^T [ [dk, ek], [ek, dk1] ] G
            new_dk = cs * cs * dk + 2 * cs * sn * ek + sn * sn * dk1
            new_dk1 = sn * sn * dk - 2 * cs * sn * ek + cs * cs * dk1
            new_ek = cs * sn * (dk1 - dk) + (cs * cs - sn * sn) * ek
            # previous off-diagonal e[k-1] gets length r
            ekm1 = jnp.where((k > lo) & inside, r, e[jnp.maximum(k - 1, 0)])

            d = d.at[k].set(jnp.where(inside, new_dk, dk))
            d = d.at[jnp.minimum(k + 1, n - 1)].set(
                jnp.where(inside, new_dk1, dk1)
            )
            e = e.at[jnp.maximum(k - 1, 0)].set(ekm1)
            e = e.at[k].set(jnp.where(inside, new_ek, e[k]))
            # bulge for next step: G rotates (e[k+1]) into position
            ek1 = e[jnp.minimum(k + 1, n - 2)]
            new_ek1 = jnp.where(inside & (k + 1 < hi), cs * ek1, ek1)
            # preserve the seeded bulge while k < lo (outside the window the
            # carry must pass through untouched, else the lo-th rotation
            # sees zbulge = 0 and the sweep silently does nothing)
            zb_next = jnp.where(
                inside, jnp.where(k + 1 < hi, sn * ek1, 0.0), zbulge
            )
            e = e.at[jnp.minimum(k + 1, n - 2)].set(new_ek1)

            if has_z:
                c0 = lax.dynamic_slice_in_dim(zz, k, 1, axis=1)[:, 0]
                c1 = lax.dynamic_slice_in_dim(zz, jnp.minimum(k + 1, n - 1), 1, axis=1)[:, 0]
                nc0 = jnp.where(inside, cs * c0 + sn * c1, c0)
                nc1 = jnp.where(inside, -sn * c0 + cs * c1, c1)
                zz = lax.dynamic_update_slice_in_dim(zz, nc0[:, None], k, axis=1)
                zz = lax.dynamic_update_slice_in_dim(
                    zz, nc1[:, None], jnp.minimum(k + 1, n - 1), axis=1
                )

            # first-step seeding handled by initial x, zbulge
            x_next = jnp.where(inside, e[k], x)
            return d, e, zz, x_next, zb_next

        x0 = d[lo] - shift
        zb0 = e[lo]
        d, e, zz, _, _ = lax.fori_loop(0, n - 1, rot_body, (d, e, zz, x0, zb0))
        return d, e, zz, it + 1

    if n == 1:
        return d, zz, jnp.zeros((), jnp.int32)
    d, e, zz, iters = lax.while_loop(cond, sweep, (d, e, zz, jnp.zeros((), jnp.int32)))
    return d, zz, iters

_STERF_QR_MAX = 256  # above this, QR iteration's serial rotations lose


def sterf(d: jax.Array, e: jax.Array, max_sweeps: Optional[int] = None) -> jax.Array:
    """Eigenvalues of the symmetric tridiagonal (d, e) — slate::sterf
    (no vectors). Returns ascending eigenvalues.

    Algorithm choice is a TPU design inversion: small problems run the
    classic implicit-shift QR iteration (the reference's Pal-Walker-Kahan
    path); past _STERF_QR_MAX the O(n^2) sequential scalar rotations are
    latency-bound on the accelerator, so values route to the boundary-row
    divide & conquer (stedc_vals) whose work is batched.

    Passing ``max_sweeps`` FORCES the QR-iteration path at any n (D&C has
    no sweep budget to bound) — it selects the algorithm, not just the
    iteration cap.  Leave it None unless you specifically want bounded QR
    iteration."""
    n = d.shape[0]
    if n > _STERF_QR_MAX and max_sweeps is None:
        return stedc_vals(d, e)
    ms = max_sweeps if max_sweeps is not None else 30 * n
    w, _, _ = _steqr_impl(d, e, None, ms)
    return jnp.sort(w)


def steqr(
    d: jax.Array,
    e: jax.Array,
    z: Optional[jax.Array] = None,
    max_sweeps: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Eigen-decomposition of tridiagonal (d, e) by implicit QR with
    accumulation into ``z`` (defaults to I): slate::steqr2 (steqr2.cc:74,
    the Fortran SLATE_DSTEQR2 core).  Returns (w ascending, z columns)."""
    n = d.shape[0]
    if z is None:
        z = jnp.eye(n, dtype=d.dtype)
    ms = max_sweeps if max_sweeps is not None else 30 * n
    w, zz, _ = _steqr_impl(d, e, z, ms)
    order = jnp.argsort(w)
    return w[order], zz[:, order]


# ---------------------------------------------------------------------------
# Divide & conquer (src/stedc.cc + stedc_{deflate,merge,secular,solve,...}.cc)
# ---------------------------------------------------------------------------


def _suffix_next(vals: jax.Array, active: jax.Array, fill) -> jax.Array:
    """nxt[i] = vals[j] of the nearest active j > i (else ``fill``)."""
    masked = jnp.where(active, vals, fill)
    rev = jnp.flip(masked)
    m = lax.associative_scan(jnp.minimum, rev)
    nxt_incl = jnp.flip(m)  # min over j >= i
    return jnp.concatenate([nxt_incl[1:], jnp.full((1,), fill, vals.dtype)])


def _prefix_prev(vals: jax.Array, active: jax.Array, fill) -> jax.Array:
    """prv[i] = vals[j] of the nearest active j < i (else ``fill``)."""
    masked = jnp.where(active, vals, fill)
    m = lax.associative_scan(jnp.maximum, masked)
    return jnp.concatenate([jnp.full((1,), fill, vals.dtype), m[:-1]])


def _secular_merge(d: jax.Array, z: jax.Array, rho, bisect_iters: int = 70):
    """Eigen-decomposition of diag(d) + rho z z^T, d ascending (stedc merge:
    stedc_secular.cc + stedc_deflate.cc).

    Vectorized and cancellation-safe: every root is bisected in its own gap
    variable mu_k = lambda_k - d_k (the LAPACK laed4 anchoring), so the
    eigenvector denominators (d_i - lambda_k) = (d_i - d_k) - mu_k never
    cancel; z is recomputed from the converged roots by the Gu-Eisenstat
    inverse-eigenvalue formula so eigenvectors stay numerically orthogonal.

    Deflation (stedc_deflate.cc): (a) negligible rho*z_k^2 -> eigenpair
    (d_k, e_k) passes through; (b) near-equal poles d_i ~ d_i+1 are merged by
    a Givens rotation that zeroes z_i+1 (applied to the returned V so the
    caller's single assembly matmul still works)."""
    n = d.shape[0]
    dtype = d.dtype
    eps = jnp.finfo(dtype).eps
    tiny = jnp.finfo(dtype).tiny
    absrho = jnp.abs(rho)
    znorm2 = jnp.sum(z * z)
    scale = absrho * znorm2 + jnp.max(jnp.abs(d)) + tiny
    tol = 8.0 * eps * scale

    # --- deflation (shared with the chunked/sharded merges): (b) Givens
    # near-equal poles + (a) negligible-z mask (dlaed2's LINEAR criterion;
    # a squared test would deflate z up to sqrt(eps) and leave
    # O(sqrt(eps)) residuals) ---
    z, cs_arr, sn_arr, active = _deflate_z(d, z, rho)
    pos = rho >= 0
    big = jnp.asarray(jnp.finfo(dtype).max / 4, dtype)

    # pairwise pole differences D[k, j] = d_j - d_k (exact in each entry)
    D = d[None, :] - d[:, None]
    zz2 = jnp.where(active, z * z, 0.0)
    idxs = jnp.arange(n)

    # interval of root k: (d_k, next active d) for rho>0, (prev, d_k) rho<0;
    # outermost root capped by the |rho|*||z||^2 bound.  rho's sign is a
    # traced value (it is an off-diagonal of the tridiagonal), so both
    # orientations are computed and selected with where — keeps the whole
    # merge jittable (stedc under jit; northstar_sweep heev driver).
    nxt_i = jnp.int32(
        _suffix_next(idxs.astype(dtype), active, jnp.asarray(n - 1, dtype))
    )
    has_nxt = _suffix_next(d, active, big) < big
    gap_p = jnp.where(has_nxt, d[nxt_i] - d, absrho * znorm2 + tol)
    prv_i = jnp.int32(
        _prefix_prev(idxs.astype(dtype), active, jnp.asarray(0, dtype))
    )
    has_prv = _prefix_prev(d, active, -big) > -big
    gap_m = jnp.where(has_prv, d[prv_i] - d, -(absrho * znorm2 + tol))
    has_nbr = jnp.where(pos, has_nxt, has_prv)
    gap = jnp.where(pos, gap_p, gap_m)
    nbr_i = jnp.where(pos, nxt_i, prv_i)

    # --- nearest-pole anchoring (laed4): decide the root's half-interval by
    # the secular sign at the midpoint, anchor mu at the closer pole so the
    # eigenvector denominators (d_i - lambda_k) never cancel ---
    def f_at(anchor_idx, mu):
        dan = d[None, :] - d[anchor_idx][:, None]  # d_j - anchor_k
        den = dan - mu[:, None]
        den = jnp.where(den == 0, tiny, den)
        return 1.0 + rho * jnp.sum(zz2[None, :] / den, axis=1)

    self_i = idxs
    fmid = f_at(self_i, gap * 0.5)
    # root in far half (toward the neighbor pole): for rho>0, f increasing,
    # interval (d_k, nxt): root > mid iff f(mid) < 0; for rho<0, f
    # decreasing, interval (prv, d_k): root < mid iff f(mid) < 0 too.
    far = fmid < 0
    use_nbr = far & has_nbr
    aidx = jnp.where(use_nbr, nbr_i, self_i)
    # mu bracket in anchored coordinates (mu = lambda - d[aidx])
    half = gap * 0.5
    lo0_p = jnp.where(use_nbr, half - gap, 0.0)  # (-gap/2, 0)
    hi0_p = jnp.where(use_nbr, 0.0, jnp.where(has_nbr, half, gap))
    lo0_m = jnp.where(use_nbr, 0.0, jnp.where(has_nbr, half, gap))
    hi0_m = jnp.where(use_nbr, half - gap, 0.0)
    lo0_m, hi0_m = jnp.minimum(lo0_m, hi0_m), jnp.maximum(lo0_m, hi0_m)
    lo0 = jnp.where(pos, lo0_p, lo0_m)
    hi0 = jnp.where(pos, hi0_p, hi0_m)

    def bis_body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        fm = f_at(aidx, mid)
        go_right = jnp.where(pos, fm < 0, fm > 0)
        lo = jnp.where(go_right, mid, lo)
        hi = jnp.where(go_right, hi, mid)
        return lo, hi

    lo, hi = lax.fori_loop(0, bisect_iters, bis_body, (lo0, hi0))
    mu = 0.5 * (lo + hi)

    # Fixed-point polish (laed4 inner iteration): bisection floors at
    # gap*2^-iters, but a root hugging its anchor sits at mu ~ rho z_a^2 —
    # as small as eps^2*gap.  The exact pole rearrangement
    #   mu = rho z_a^2 / (1 + rho * sum_{j != a} z_j^2 / (Dan_kj - mu))
    # is strongly attractive there; candidates outside the bisection bracket
    # are rejected, so the root is never lost.
    dan_full = d[None, :] - d[aidx][:, None]
    not_anchor = idxs[None, :] != aidx[:, None]
    zz2_anch = zz2[aidx]

    def fp_body(_, mu):
        den = dan_full - mu[:, None]
        den = jnp.where(den == 0, tiny, den)
        other = jnp.sum(jnp.where(not_anchor, zz2[None, :] / den, 0.0), axis=1)
        g = rho * zz2_anch / (1.0 + rho * other)
        ok = jnp.isfinite(g) & (g > lo) & (g < hi)
        return jnp.where(ok, g, mu)

    mu = lax.fori_loop(0, 25, fp_body, mu)
    mu = jnp.where(active, mu, 0.0)
    aidx = jnp.where(active, aidx, self_i)
    lam = d[aidx] + mu

    # --- Gu-Eisenstat z-hat from the converged roots ---
    # |zhat_k|^2 = prod_{j act} (lam_j - d_k) / (|rho| prod_{j!=k act} (d_j - d_k))
    # with lam_j - d_k = (d[aidx_j] - d_k) + mu_j (anchored, cancellation-free)
    offk = ~jnp.eye(n, dtype=bool)
    act_j = active[None, :] & offk
    Dsafe = jnp.where(D == 0, 1.0, D)
    lamd = (d[aidx][None, :] - d[:, None]) + mu[None, :]  # (k, j): lam_j - d_k
    ratio = jnp.where(act_j, lamd / Dsafe, 1.0)
    prod = jnp.prod(jnp.abs(ratio), axis=1)
    lamk_dk = jnp.take_along_axis(lamd, idxs[:, None], axis=1)[:, 0]
    zhat = jnp.sign(z) * jnp.sqrt(prod * jnp.abs(lamk_dk) / jnp.maximum(absrho, tiny))
    zhat = jnp.where(active, zhat, 0.0)

    # --- eigenvectors: v[i,k] = zhat_i / (d_i - lam_k), anchored form ---
    den = (d[:, None] - d[aidx][None, :]) - mu[None, :]
    den = jnp.where(den == 0, tiny, den)
    v = zhat[:, None] / den
    v = jnp.where(active[None, :], v, 0.0)
    nrm = jnp.sqrt(jnp.sum(v * v, axis=0))
    v = v / jnp.where(nrm == 0, 1.0, nrm)[None, :]
    v = v + jnp.where(active, 0.0, 1.0)[None, :] * jnp.eye(n, dtype=dtype)

    v = _undo_deflation_rows(v, cs_arr, sn_arr)
    return lam, v


def _secular_roots_shard(dd, zf, rho, active, kidx, bisect_iters=70):
    """Converged roots for MY root indices ``kidx`` of diag(dd) + rho z z^T
    (dd ascending, full length nn = 2s; zf the deflation-rotated z).
    Sharded restriction of linalg.tridiag._secular_merge's root finder:
    every (nn x nn) tensor becomes (kloc x nn).  Returns (mu, aidx) for my
    roots."""
    nn = dd.shape[0]
    dtype = dd.dtype
    tiny = jnp.finfo(dtype).tiny
    absrho = jnp.abs(rho)
    zz2 = jnp.where(active, zf * zf, 0.0)
    znorm2 = jnp.sum(zf * zf)
    eps = jnp.finfo(dtype).eps
    tol = 8.0 * eps * (absrho * znorm2 + jnp.max(jnp.abs(dd)) + tiny)
    pos = rho >= 0
    big = jnp.asarray(jnp.finfo(dtype).max / 4, dtype)
    idxs = jnp.arange(nn)

    nxt_i = jnp.int32(_suffix_next(idxs.astype(dtype), active, jnp.asarray(nn - 1, dtype)))
    has_nxt = _suffix_next(dd, active, big) < big
    gap_p = jnp.where(has_nxt, dd[nxt_i] - dd, absrho * znorm2 + tol)
    prv_i = jnp.int32(_prefix_prev(idxs.astype(dtype), active, jnp.asarray(0, dtype)))
    has_prv = _prefix_prev(dd, active, -big) > -big
    gap_m = jnp.where(has_prv, dd[prv_i] - dd, -(absrho * znorm2 + tol))
    has_nbr = jnp.where(pos, has_nxt, has_prv)
    gap_full = jnp.where(pos, gap_p, gap_m)
    nbr_full = jnp.where(pos, nxt_i, prv_i)

    # restrict to my roots
    gap = gap_full[kidx]
    nbr_i = nbr_full[kidx]
    has_nbr_k = has_nbr[kidx]
    self_i = kidx

    def f_at(anchor_idx, mu):
        dan = dd[None, :] - dd[anchor_idx][:, None]  # (kloc, nn)
        den = dan - mu[:, None]
        den = jnp.where(den == 0, tiny, den)
        return 1.0 + rho * jnp.sum(zz2[None, :] / den, axis=1)

    fmid = f_at(self_i, gap * 0.5)
    far = fmid < 0
    use_nbr = far & has_nbr_k
    aidx = jnp.where(use_nbr, nbr_i, self_i)
    half = gap * 0.5
    lo0_p = jnp.where(use_nbr, half - gap, 0.0)
    hi0_p = jnp.where(use_nbr, 0.0, jnp.where(has_nbr_k, half, gap))
    lo0_m = jnp.where(use_nbr, 0.0, jnp.where(has_nbr_k, half, gap))
    hi0_m = jnp.where(use_nbr, half - gap, 0.0)
    lo0_m, hi0_m = jnp.minimum(lo0_m, hi0_m), jnp.maximum(lo0_m, hi0_m)
    lo0 = jnp.where(pos, lo0_p, lo0_m)
    hi0 = jnp.where(pos, hi0_p, hi0_m)

    def bis_body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        fm = f_at(aidx, mid)
        go_right = jnp.where(pos, fm < 0, fm > 0)
        lo = jnp.where(go_right, mid, lo)
        hi = jnp.where(go_right, hi, mid)
        return lo, hi

    lo, hi = lax.fori_loop(0, bisect_iters, bis_body, (lo0, hi0))
    mu = 0.5 * (lo + hi)

    dan_full = dd[None, :] - dd[aidx][:, None]
    not_anchor = idxs[None, :] != aidx[:, None]
    zz2_anch = zz2[aidx]

    def fp_body(_, mu):
        den = dan_full - mu[:, None]
        den = jnp.where(den == 0, tiny, den)
        other = jnp.sum(jnp.where(not_anchor, zz2[None, :] / den, 0.0), axis=1)
        g = rho * zz2_anch / (1.0 + rho * other)
        ok = jnp.isfinite(g) & (g > lo) & (g < hi)
        return jnp.where(ok, g, mu)

    mu = lax.fori_loop(0, 25, fp_body, mu)
    act_k = active[kidx]
    mu = jnp.where(act_k, mu, 0.0)
    aidx = jnp.where(act_k, aidx, self_i)
    return mu, aidx


def _zhat_shard(dd, zf, rho, active, lam_anch_d, mu_all, kidx):
    """|zhat| for MY pole indices kidx (Gu-Eisenstat inverse-eigenvalue
    formula), using the replicated converged roots.  lam_anch_d[j] =
    dd[aidx_j] (anchor pole value of root j)."""
    nn = dd.shape[0]
    dtype = dd.dtype
    tiny = jnp.finfo(dtype).tiny
    absrho = jnp.abs(rho)
    idxs = jnp.arange(nn)
    dk = dd[kidx]  # (kloc,)
    D = dd[None, :] - dk[:, None]  # (kloc, nn): d_j - d_k
    Dsafe = jnp.where(D == 0, 1.0, D)
    lamd = (lam_anch_d[None, :] - dk[:, None]) + mu_all[None, :]  # lam_j - d_k
    offk = idxs[None, :] != kidx[:, None]
    act_j = active[None, :] & offk
    ratio = jnp.where(act_j, lamd / Dsafe, 1.0)
    prod = jnp.prod(jnp.abs(ratio), axis=1)
    lamk_dk = lamd[jnp.arange(kidx.shape[0]), kidx]  # lam_k - d_k per my pole
    zhat = jnp.sign(zf[kidx]) * jnp.sqrt(prod * jnp.abs(lamk_dk) / jnp.maximum(absrho, tiny))
    return jnp.where(active[kidx], zhat, 0.0)


def _vmap1(fn):
    """vmap that bypasses batching when the leading dim is 1.

    Round-3 chip finding: jax.vmap over the merge internals (deflation
    fori + dynamic updates + the big gathers) lowers to a kernel that
    faults the TPU worker at nn = 16384 even for batch size 1, while the
    identical unbatched program runs fine — the top merge level always has
    m = 1, so bypassing there is both the fix and free."""
    batched = jax.vmap(fn)

    def call(*args):
        if args[0].shape[0] == 1:
            out = fn(*(a[0] for a in args))
            if isinstance(out, tuple):
                return tuple(o[None] for o in out)
            return out[None]
        return batched(*args)

    return call


def _deflate_z(d: jax.Array, z: jax.Array, rho):
    """Deflation pre-pass shared by the chunked/sharded merges: Givens-
    rotate near-equal poles (zeroing the second z entry) and mask
    negligible-z components.  Returns (z_rotated, cs, sn, active)."""
    n = d.shape[0]
    dtype = d.dtype
    eps = jnp.finfo(dtype).eps
    tiny = jnp.finfo(dtype).tiny
    absrho = jnp.abs(rho)
    tol = 8.0 * eps * (absrho * jnp.sum(z * z) + jnp.max(jnp.abs(d)) + tiny)

    def body(t, carry):
        z, cs_a, sn_a = carry
        i = n - 2 - t
        close = jnp.abs(d[i + 1] - d[i]) <= tol
        zi, zi1 = z[i], z[i + 1]
        both = (jnp.abs(zi1) > 0) & close
        r = jnp.hypot(zi, zi1)
        rs = jnp.where(r == 0, 1.0, r)
        c = jnp.where(both, zi / rs, 1.0)
        s = jnp.where(both, zi1 / rs, 0.0)
        z = z.at[i].set(jnp.where(both, r, zi))
        z = z.at[i + 1].set(jnp.where(both, 0.0, zi1))
        return z, cs_a.at[i].set(c), sn_a.at[i].set(s)

    z, cs_a, sn_a = lax.fori_loop(
        0, n - 1, body, (z, jnp.ones((n - 1,), dtype), jnp.zeros((n - 1,), dtype))
    )
    active = absrho * jnp.abs(z) > tol
    return z, cs_a, sn_a, active


def _undo_deflation_rows(v: jax.Array, cs_arr: jax.Array, sn_arr: jax.Array) -> jax.Array:
    """Undo the deflation Givens rotations on V's ROWS (ascending order =
    reverse of the descending deflation scan): V <- R_i^T V on rows
    (i, i+1).  Shared by the monolithic, chunked, and mesh merges."""

    def rb(i, v):
        c, s = cs_arr[i], sn_arr[i]
        r0 = lax.dynamic_slice_in_dim(v, i, 1, axis=0)[0]
        r1 = lax.dynamic_slice_in_dim(v, i + 1, 1, axis=0)[0]
        n0 = c * r0 - s * r1
        n1 = s * r0 + c * r1
        v = lax.dynamic_update_slice_in_dim(v, n0[None], i, axis=0)
        return lax.dynamic_update_slice_in_dim(v, n1[None], i + 1, axis=0)

    if v.shape[0] > 1:
        return lax.fori_loop(0, v.shape[0] - 1, rb, v)
    return v


# Above this merge width, the single-program merge runs in root-column
# chunks: the monolithic form keeps several (2s)^2 tensors live at once and
# exhausts device memory near 2s = 16384 (round-3 chip finding — every
# piece passes in isolation, the fused whole kills the worker).
_CHUNK_AT = 16384
_CHUNK_COLS = 2048


def _merge_chunk_prep(dd_s, z_s, rho):
    """Shared chunked-merge prelude: deflation, chunked secular roots, and
    chunked zhat, all with (2s/chunks x 2s) peak tensors.  Returns
    (zf, cs_a, sn_a, active, lam, lam_anch_d, mu_all, zhat, nch, cols)."""
    nn = dd_s.shape[1]
    zf, cs_a, sn_a, active = _vmap1(_deflate_z)(dd_s, z_s, rho)
    nch = max(1, nn // _CHUNK_COLS)
    cols = nn // nch
    mus, aidxs = [], []
    for ci in range(nch):
        kidx = ci * cols + jnp.arange(cols)
        mu_c, aidx_c = _vmap1(
            lambda d1, z1, r1, a1: _secular_roots_shard(d1, z1, r1, a1, kidx)
        )(dd_s, zf, rho, active)
        mus.append(mu_c)
        aidxs.append(aidx_c)
    mu_all = jnp.concatenate(mus, axis=1)
    aidx_all = jnp.concatenate(aidxs, axis=1)
    lam_anch_d = jnp.take_along_axis(dd_s, aidx_all, axis=1)
    lam = lam_anch_d + mu_all
    zhs = []
    for ci in range(nch):
        kidx = ci * cols + jnp.arange(cols)
        zh_c = _vmap1(
            lambda d1, z1, r1, a1, la1, mu1: _zhat_shard(d1, z1, r1, a1, la1, mu1, kidx)
        )(dd_s, zf, rho, active, lam_anch_d, mu_all)
        zhs.append(zh_c)
    zhat = jnp.concatenate(zhs, axis=1)
    return zf, cs_a, sn_a, active, lam, lam_anch_d, mu_all, zhat, nch, cols


def _merge_chunk_v(dd_s, lam_anch_d, mu_all, zhat, active, cs_a, sn_a, inv, kidx):
    """Eigenvector slab for root columns ``kidx`` (child row order)."""
    dtype = dd_s.dtype
    nn = dd_s.shape[1]
    tiny = jnp.finfo(dtype).tiny
    den = (dd_s[:, :, None] - lam_anch_d[:, None, kidx]) - mu_all[:, None, kidx]
    den = jnp.where(den == 0, tiny, den)
    v = zhat[:, :, None] / den
    act_k = active[:, kidx]
    v = jnp.where(act_k[:, None, :], v, 0.0)
    nrm = jnp.sqrt(jnp.sum(v * v, axis=1))
    v = v / jnp.where(nrm == 0, 1.0, nrm)[:, None, :]
    ek = (jnp.arange(nn)[None, :, None] == kidx[None, None, :]).astype(dtype)
    v = v + jnp.where(act_k[:, None, :], 0.0, 1.0) * ek
    v = _vmap1(_undo_deflation_rows)(v, cs_a, sn_a)
    return _vmap1(lambda vm, im: vm[im])(v, inv)  # child row order


def _merge_chunked(dd_s, z_s, rho, s, q_pair, inv):
    """One merge level evaluated in root-column chunks with bounded peak
    memory: the shared prelude (_merge_chunk_prep) runs deflation + root
    finding + zhat as vector passes, then per chunk the (2s x cols)
    eigenvector slab is built (_merge_chunk_v) and consumed by the
    block-diagonal assembly write.  Shapes: dd_s/z_s (m, 2s) sorted-pole;
    q_pair (m, 2, s_rows, s); inv (m, 2s).  Returns (lam, q_new)."""
    m, nn = dd_s.shape
    dtype = dd_s.dtype
    zf, cs_a, sn_a, active, lam, lam_anch_d, mu_all, zhat, nch, cols = (
        _merge_chunk_prep(dd_s, z_s, rho)
    )
    srows = q_pair.shape[2]
    q_new = jnp.zeros((m, 2 * srows, nn), dtype)
    for ci in range(nch):
        kidx = ci * cols + jnp.arange(cols)
        v = _merge_chunk_v(dd_s, lam_anch_d, mu_all, zhat, active, cs_a, sn_a, inv, kidx)
        qt = jnp.einsum("mrj,mjk->mrk", q_pair[:, 0], v[:, :s, :], precision=PRECISE)
        qb = jnp.einsum("mrj,mjk->mrk", q_pair[:, 1], v[:, s:, :], precision=PRECISE)
        q_new = lax.dynamic_update_slice(
            q_new, jnp.concatenate([qt, qb], axis=1).astype(dtype), (0, 0, ci * cols)
        )
    return lam, q_new


def _merge_chunked_vals(dd_s, z_s, rho, s, top, bot, inv):
    """Values-only wide merge with bounded memory: same prelude and slab
    builder as _merge_chunked, but each chunk is reduced straight to its
    top/bot boundary-row contribution and freed — no O((2s)^2) tensor is
    ever live.  ``top``/``bot`` are the child boundary rows (m*2, s)."""
    dtype = dd_s.dtype
    zf, cs_a, sn_a, active, lam, lam_anch_d, mu_all, zhat, nch, cols = (
        _merge_chunk_prep(dd_s, z_s, rho)
    )
    tops, bots = [], []
    for ci in range(nch):
        kidx = ci * cols + jnp.arange(cols)
        v = _merge_chunk_v(dd_s, lam_anch_d, mu_all, zhat, active, cs_a, sn_a, inv, kidx)
        tops.append(jnp.einsum("mj,mjk->mk", top[0::2], v[:, :s, :], precision=PRECISE))
        bots.append(jnp.einsum("mj,mjk->mk", bot[1::2], v[:, s:, :], precision=PRECISE))
    return lam, jnp.concatenate(tops, axis=1).astype(dtype), jnp.concatenate(bots, axis=1).astype(dtype)


_DC_SMALL = 32  # base-case size (reference stedc small-problem cutoff)


def stedc(d: jax.Array, e: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Divide & conquer tridiagonal eigensolver (src/stedc.cc chain).
    Returns (w ascending, Z).

    Level-wise batched tree: the input is padded to N = 2^L * _DC_SMALL
    with a decoupled block of pad eigenvalues (4 * ||T|| on the diagonal,
    zero coupling — exact, sorts after every real eigenvalue), the 2^L
    base problems are one vmapped steqr, and every merge LEVEL is one
    vmapped secular solve + one batched assembly matmul on the MXU.  The
    compiled program is O(log n) kernels — the reference's recursive task
    tree (stedc.cc) would otherwise inline O(n/nb) distinct merges, whose
    program size is what crashed the TPU runtime at n = 8192 in round 2's
    first sweep."""
    w, q, _, _ = _stedc_levels(d, e, want_q=True)
    return w, q


def stedc_vals(d: jax.Array, e: jax.Array) -> jax.Array:
    """Values-only divide & conquer: the same batched merge tree as stedc,
    but each subproblem carries only (w, Q[0, :], Q[-1, :]) — the boundary
    rows are all a parent merge consumes (its z-vector) or produces.  The
    per-merge cost drops from the O(n^3) assembly matmul to the O(n^2)
    secular solve + two row-vector products — unlike the QR-iteration
    sterf, whose O(n^2) SEQUENTIAL scalar rotations are latency-bound on
    the accelerator."""
    w, _, _, _ = _stedc_levels(d, e, want_q=False)
    return w


def _stedc_levels(d, e, want_q: bool):
    n = d.shape[0]
    dtype = d.dtype
    if n <= _DC_SMALL:
        w, q = steqr(d, e)
        return w, q, q[0, :], q[-1, :]
    levels = max(1, -(-n // _DC_SMALL) - 1).bit_length()
    N = (1 << levels) * _DC_SMALL
    w, q, ep = _stedc_base(d, e, N)

    if want_q:
        # vectors path: shared per-level body (_merge_level_q) — the same
        # function stedc_staged dispatches one level at a time
        s = _DC_SMALL
        while s < N:
            w, q = _merge_level_q(w, q, ep, s, N)
            s *= 2
        wv = w.reshape(N)
        order = jnp.argsort(wv)
        return wv[order][:n], q[0][:, order[:n]][:n, :], None, None

    # boundary-row path: each subproblem carries only (w, top, bot)
    top = q[:, 0, :]
    bot = q[:, -1, :]
    s = _DC_SMALL
    while s < N:
        m = N // (2 * s)
        rho = ep[(2 * jnp.arange(m) + 1) * s - 1]
        dd = w.reshape(m, 2 * s)
        z = jnp.concatenate([bot[0::2], top[1::2]], axis=1)
        order = jnp.argsort(dd, axis=1)
        dd_s = jnp.take_along_axis(dd, order, axis=1)
        z_s = jnp.take_along_axis(z, order, axis=1)
        inv = jnp.argsort(order, axis=1)
        if 2 * s >= _CHUNK_AT:
            # wide merges: never materialize the O((2s)^2) eigenvector
            # matrix the boundary rows contract against (faulted the
            # worker at 2s = 32768 inside the n=16384 SVD's GK solve)
            w, top, bot = _merge_chunked_vals(dd_s, z_s, rho, s, top, bot, inv)
            s *= 2
            continue
        lam, v_s = _vmap1(_secular_merge)(dd_s, z_s, rho)
        v = _vmap1(lambda vm, im: vm[im])(v_s, inv)  # child row order
        # eigencolumns stay in sorted-pole root order (parents re-sort
        # their poles; one global argsort at the end)
        top = jnp.einsum(
            "mj,mjk->mk", top[0::2], v[:, :s, :], precision=PRECISE
        ).astype(dtype)
        bot = jnp.einsum(
            "mj,mjk->mk", bot[1::2], v[:, s:, :], precision=PRECISE
        ).astype(dtype)
        w = lam
        s *= 2

    wv = w.reshape(N)
    order = jnp.argsort(wv)
    return wv[order][:n], None, None, None


# Fused stedc-with-vectors is validated on chip up to N = 8192; at
# N = 16384 the single program kills the TPU worker even though every
# level runs fine as its own dispatch (round-3 finding) — so large
# problems run the level loop staged, one XLA program per merge level.
_STEDC_STAGE_ABOVE = 8192


def _stedc_base(d, e, N):
    n = d.shape[0]
    dtype = d.dtype
    nblk = N // _DC_SMALL
    scale = jnp.max(jnp.abs(d)) + 2 * (jnp.max(jnp.abs(e)) if n > 1 else 0) + 1
    big = 4 * scale
    dp = jnp.concatenate([d, jnp.full((N - n,), 1.0, dtype) * big])
    ep = jnp.concatenate([e, jnp.zeros((N - 1 - (n - 1),), dtype)])
    seams = _DC_SMALL * jnp.arange(1, nblk) - 1
    dp = dp.at[seams].add(-ep[seams]).at[seams + 1].add(-ep[seams])
    db = dp.reshape(nblk, _DC_SMALL)
    eb = jnp.concatenate([ep, jnp.zeros((1,), dtype)]).reshape(nblk, _DC_SMALL)
    eb = eb[:, : _DC_SMALL - 1]
    w, q = jax.vmap(steqr)(db, eb)
    return w, q, ep


def _merge_level_q(w, q, ep, s, N):
    """One merge level with the eigenvector stack carried — the single
    source of truth for the vectors path: _stedc_levels inlines it into
    the fused program and stedc_staged dispatches it per level."""
    dtype = q.dtype
    m = N // (2 * s)
    rho = ep[(2 * jnp.arange(m) + 1) * s - 1]
    dd = w.reshape(m, 2 * s)
    top = q[:, 0, :]
    bot = q[:, -1, :]
    z = jnp.concatenate([bot[0::2], top[1::2]], axis=1)
    order = jnp.argsort(dd, axis=1)
    dd_s = jnp.take_along_axis(dd, order, axis=1)
    z_s = jnp.take_along_axis(z, order, axis=1)
    inv = jnp.argsort(order, axis=1)
    if 2 * s >= _CHUNK_AT:
        lam, qn = _merge_chunked(
            dd_s, z_s, rho, s, q.reshape(m, 2, q.shape[1], q.shape[2]), inv
        )
        return lam, qn.astype(dtype)
    lam, v_s = _vmap1(_secular_merge)(dd_s, z_s, rho)
    v = _vmap1(lambda vm, im: vm[im])(v_s, inv)
    q_top = jnp.einsum("mij,mjk->mik", q[0::2], v[:, :s, :], precision=PRECISE)
    q_bot = jnp.einsum("mij,mjk->mik", q[1::2], v[:, s:, :], precision=PRECISE)
    return lam, jnp.concatenate([q_top, q_bot], axis=1).astype(dtype)


_stedc_base_jit = jax.jit(_stedc_base, static_argnames=("N",))
_stedc_level_jit = jax.jit(_merge_level_q, static_argnames=("s", "N"))


def stedc_staged(d: jax.Array, e: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """stedc with each merge level as its own XLA dispatch — numerically
    identical to stedc; the large-n driver path (cf. eig.heev_staged)."""
    n = d.shape[0]
    if n <= _STEDC_STAGE_ABOVE:
        return stedc(d, e)
    levels = max(1, -(-n // _DC_SMALL) - 1).bit_length()
    N = (1 << levels) * _DC_SMALL
    w, q, ep = _stedc_base_jit(d, e, N)
    s = _DC_SMALL
    while s < N:
        w, q = _stedc_level_jit(w, q, ep, s, N)
        s *= 2
    wv = w.reshape(N)
    order = jnp.argsort(wv)
    return wv[order][:n], q[0][:, order[:n]][:n, :]
