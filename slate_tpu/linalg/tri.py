"""Triangular inverse (trtri) and triangle-triangle multiply (trtrm).

Analogues of ``src/trtri.cc`` / ``src/internal/internal_trtri.cc`` and
``src/trtrm.cc`` / ``internal_trtrm.cc`` (LAPACK lauum-style).  Recursive
blocked, exact flops, O(log n) shapes — same scheme as chol.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..blas3.blas3 import _NB, _split, trsm_array
from ..core.matrix import tri_project
from ..ops.matmul import matmul
from ..types import Diag, Op, Side, Uplo


def _trtri_lower(a: jax.Array, diag: Diag) -> jax.Array:
    """Invert lower triangle recursively:
    inv([[A11, 0], [A21, A22]]) = [[A11^-1, 0], [-A22^-1 A21 A11^-1, A22^-1]]."""
    n = a.shape[0]
    if n <= _NB:
        eye = jnp.eye(n, dtype=a.dtype)
        return jax.lax.linalg.triangular_solve(
            a, eye, left_side=True, lower=True, unit_diagonal=(diag == Diag.Unit)
        )
    h = _split(n)
    a11, a21, a22 = a[:h, :h], a[h:, :h], a[h:, h:]
    i11 = _trtri_lower(a11, diag)
    i22 = _trtri_lower(a22, diag)
    i21 = -matmul(matmul(i22, a21), i11).astype(a.dtype)
    z = jnp.zeros((h, n - h), a.dtype)
    return jnp.block([[i11, z], [i21, i22]])


def trtri_array(a: jax.Array, uplo: Uplo = Uplo.Lower, diag: Diag = Diag.NonUnit) -> jax.Array:
    """slate::trtri (src/trtri.cc)."""
    if uplo == Uplo.Upper:
        return _trtri_lower(a.T, diag).T
    return _trtri_lower(a, diag)


def trtrm_array(t: jax.Array, uplo: Uplo = Uplo.Lower) -> jax.Array:
    """slate::trtrm (src/trtrm.cc): compute T^H T (lower) or T T^H (upper)
    where T is the uplo triangle — the lauum step of potri. Result is
    Hermitian; the uplo triangle of the product is returned."""
    tt = tri_project(t, uplo)
    if uplo == Uplo.Lower:
        prod = matmul(jnp.conj(tt).T, tt)
    else:
        prod = matmul(tt, jnp.conj(tt).T)
    return tri_project(prod.astype(t.dtype), uplo)
