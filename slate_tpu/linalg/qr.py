"""QR / LQ factorization and least squares.

Analogues of ``src/{geqrf,gelqf,unmqr,unmlq,cholqr,gels,gels_qr,
gels_cholqr}.cc`` and internal panels ``internal_geqrf.cc`` /
``Tile_geqrf.hh`` / the CAQR tree ``internal_ttqrt.cc``.

Design inversion: the reference does CAQR — each rank factors its tile stack
(geqrf panel), then a binary tree of triangle-triangle QRs (ttqrt) merges the
per-rank R factors over MPI (geqrf.cc:191-230, SURVEY.md P6).  The TPU form
is recursive compact-WY (Elmroth-Gustavson): factor the left half, apply
``I - Y T Y^H`` to the right half with three matmuls, recurse, and merge
T blocks — the same communication-avoiding tree, but the "tree" is the
recursion and the merges are matmuls XLA schedules over the mesh (sharded
runs get their collectives from GSPMD; the explicit mesh-axis ttqrt tree
lives in slate_tpu.parallel.dist_qr).  The unblocked base panel is a masked
``lax.fori_loop`` of Householder reflections (LAPACK larfg/larf semantics,
complex-safe).

Factors are packed LAPACK-style: V below the diagonal (unit first element
implicit), R on/above; plus the n x n upper-triangular WY accumulator T such
that Q = I - V T V^H.
"""

from __future__ import annotations

from ..obs import instrument

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..blas3.blas3 import trsm_array
from ..core.matrix import tri_project
from ..ops.matmul import matmul
from ..ops.pallas_ops import (
    panel_engaged,
    qr_panel_offset_pallas,
    qr_panel_pallas,
)
from ..types import Diag, MethodGels, Op, Option, Options, Side, SlateError, Uplo, get_option

Array = jax.Array

_QR_PANEL = 64


class QRFactors(NamedTuple):
    """Packed QR: ``vr`` has V below diag / R above; ``t`` is the WY
    accumulator, upper triangular (n, n): Q = I - V T V^H."""

    vr: Array
    t: Array


class LQFactors(NamedTuple):
    """Packed LQ: ``lv`` has L on/below diag, V^H above (rows are
    reflectors); ``t`` as in QR for the transposed problem."""

    lv: Array
    t: Array


def _sign_safe(x: Array) -> Array:
    """sign(x) with sign(0) = 1, complex-safe (LAPACK larfg convention)."""
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, jnp.ones_like(x), x / jnp.where(mag == 0, 1, mag))
    return jnp.where(x >= 0, jnp.ones_like(x), -jnp.ones_like(x))


def _panel_qr(a: Array) -> Tuple[Array, Array]:
    """Unblocked Householder QR of (m, w). Returns (packed VR, tau)."""
    m, w = a.shape
    rows = jnp.arange(m)
    cplx = jnp.issubdtype(a.dtype, jnp.complexfloating)

    def step(j, carry):
        a, tau = carry
        col = a[:, j]
        below = rows > j
        alpha = col[j]
        xnorm2 = jnp.sum(jnp.where(below, jnp.abs(col) ** 2, 0))
        anorm = jnp.sqrt(jnp.abs(alpha) ** 2 + xnorm2)
        s = _sign_safe(alpha if not cplx else jnp.where(jnp.real(alpha) == 0, jnp.asarray(1, a.dtype), alpha))
        beta = -s * anorm.astype(a.dtype)
        zero_col = (anorm == 0)
        beta = jnp.where(zero_col, jnp.ones_like(beta), beta)
        tj = (beta - alpha) / beta
        tj = jnp.where(zero_col, jnp.zeros_like(tj), tj)
        denom = alpha - beta
        denom = jnp.where(denom == 0, jnp.ones_like(denom), denom)
        v = jnp.where(below, col / denom, jnp.zeros_like(col))
        v = v.at[j].set(1)
        # apply H = I - tau v v^H to remaining columns (mask cols <= j)
        w_row = matmul(jnp.conj(v)[None, :], a)[0]  # v^H A
        cmask = (jnp.arange(w) > j).astype(a.dtype)
        a = a - jnp.outer(tj * v, w_row * cmask)
        # store: R entry at (j, j) = beta, v below diagonal
        newcol = jnp.where(below, v, a[:, j])
        newcol = newcol.at[j].set(jnp.where(zero_col, alpha, beta))
        a = a.at[:, j].set(newcol)
        tau = tau.at[j].set(tj)
        return a, tau

    tau0 = jnp.zeros(w, a.dtype)
    a, tau = jax.lax.fori_loop(0, min(m, w), step, (a, tau0))
    return a, tau


def _panel_qr_offset(a: Array, row0) -> Tuple[Array, Array, Array]:
    """Householder QR of a full-height column block whose pivot row for
    column j is the (traced) global row ``row0 + j``.

    Rows < row0 of ``a`` must be zero (caller masks its history out); the
    elimination never touches them, so the result can be scattered back
    into a larger matrix without disturbing already-factored content.
    Dead columns (no weight at or below the pivot) get tau = 0.

    Returns (r, v, tau): ``r`` is ``a`` with R at rows row0..row0+w and
    zeros below each pivot; ``v`` holds the explicit reflectors (unit
    pivot entries, zeros above); ``tau`` the w scalar factors.

    This is the fixed-shape panel the scanned two-stage reductions
    (he2hb / ge2tb) loop over — the reference runs the same panel QR per
    block column inside its task DAG (internal_geqrf.cc, he2hb.cc:207).
    """
    m, w = a.shape
    rows = jnp.arange(m)
    cplx = jnp.issubdtype(a.dtype, jnp.complexfloating)

    def step(j, carry):
        a, vmat, tau = carry
        gi = row0 + j
        col = jax.lax.dynamic_slice(a, (0, j), (m, 1))[:, 0]
        below = rows > gi
        alpha = col[gi]
        xnorm2 = jnp.sum(jnp.where(below, jnp.abs(col) ** 2, 0))
        anorm = jnp.sqrt(jnp.abs(alpha) ** 2 + xnorm2)
        s = _sign_safe(
            alpha if not cplx else jnp.where(jnp.real(alpha) == 0, jnp.asarray(1, a.dtype), alpha)
        )
        beta = -s * anorm.astype(a.dtype)
        zero_col = anorm == 0
        beta = jnp.where(zero_col, jnp.ones_like(beta), beta)
        tj = (beta - alpha) / beta
        tj = jnp.where(zero_col, jnp.zeros_like(tj), tj)
        denom = alpha - beta
        denom = jnp.where(denom == 0, jnp.ones_like(denom), denom)
        v = jnp.where(below, col / denom, jnp.zeros_like(col))
        v = v.at[gi].set(jnp.where(zero_col, jnp.zeros((), a.dtype), jnp.ones((), a.dtype)))
        w_row = matmul(jnp.conj(v)[None, :], a)[0]
        cmask = (jnp.arange(w) > j).astype(a.dtype)
        a = a - jnp.outer(tj * v, w_row * cmask)
        newcol = jnp.where(below, jnp.zeros_like(col), col)
        newcol = newcol.at[gi].set(jnp.where(zero_col, alpha, beta))
        a = jax.lax.dynamic_update_slice(a, newcol[:, None], (0, j))
        return a, vmat.at[:, j].set(v), tau.at[j].set(tj)

    r, v, tau = jax.lax.fori_loop(
        0, w, step, (a, jnp.zeros_like(a), jnp.zeros(w, a.dtype))
    )
    return r, v, tau


def _larft_v(v: Array, tau: Array) -> Array:
    """Compact-WY T from explicit reflectors (columns of ``v``)."""
    w = v.shape[1]
    vhv = matmul(jnp.conj(v).T, v)

    def step(j, t):
        tcol = -tau[j] * matmul(t, vhv[:, j][:, None])[:, 0]
        mask = (jnp.arange(w) < j).astype(v.dtype)
        t = t.at[:, j].set(tcol * mask)
        return t.at[j, j].set(tau[j])

    return jax.lax.fori_loop(0, w, step, jnp.zeros((w, w), v.dtype))


def _larft(vr: Array, tau: Array) -> Array:
    """Build the compact-WY T from packed reflectors (LAPACK larft forward
    columnwise): T[:j, j] = -tau_j * T[:j, :j] @ (V^H v_j)."""
    m, w = vr.shape
    rows = jnp.arange(m)[:, None]
    cols = jnp.arange(w)[None, :]
    v = jnp.where(rows > cols, vr, jnp.where(rows == cols, jnp.ones_like(vr), jnp.zeros_like(vr)))
    vhv = matmul(jnp.conj(v).T, v)  # (w, w)

    def step(j, t):
        tcol = -tau[j] * matmul(t, vhv[:, j][:, None])[:, 0]
        mask = (jnp.arange(w) < j).astype(vr.dtype)
        t = t.at[:, j].set(tcol * mask)
        return t.at[j, j].set(tau[j])

    t0 = jnp.zeros((w, w), vr.dtype)
    return jax.lax.fori_loop(0, w, step, t0)


def _panel_qr_t(a: Array) -> Tuple[Array, Array, Array]:
    """(packed VR, tau, T) of one panel — the ``_panel_qr`` + ``_larft``
    pair, fused into ONE Pallas dispatch (reflector generation and the
    compact-WY T accumulation run on the VMEM-resident panel) when
    ``Option.PanelImpl`` engages; the XLA pair is the reference and is
    bitwise-identical to the kernel under interpret mode (same op
    sequence)."""
    if panel_engaged(a.dtype, a.size * a.dtype.itemsize):
        return qr_panel_pallas(a)
    vr, tau = _panel_qr(a)
    return vr, tau, _larft(vr, tau)


def _panel_qr_offset_t(a: Array, row0) -> Tuple[Array, Array, Array, Array]:
    """(r, v, tau, T) of one offset-pivot panel — ``_panel_qr_offset`` +
    ``_larft_v`` as one fused dispatch when ``Option.PanelImpl``
    engages (``row0`` may be traced; it rides as a scalar operand)."""
    if panel_engaged(a.dtype, a.size * a.dtype.itemsize):
        return qr_panel_offset_pallas(a, row0)
    r, v, tau = _panel_qr_offset(a, row0)
    return r, v, tau, _larft_v(v, tau)


def _v_of(vr: Array, k: Optional[int] = None) -> Array:
    """Extract unit-lower V from packed storage (first k reflectors)."""
    m, n = vr.shape
    k = n if k is None else k
    rows = jnp.arange(m)[:, None]
    cols = jnp.arange(k)[None, :]
    block = vr[:, :k]
    return jnp.where(rows > cols, block, jnp.where(rows == cols, jnp.ones_like(block), jnp.zeros_like(block)))


def _split_qr(n: int) -> int:
    h = _QR_PANEL
    while h * 2 < n:
        h *= 2
    return h


def _geqrf_rec(a: Array) -> Tuple[Array, Array]:
    """Recursive blocked QR. Returns (packed VR, T)."""
    m, n = a.shape
    if n <= _QR_PANEL:
        vr, _, t = _panel_qr_t(a)
        return vr, t
    h = _split_qr(n)
    vr1, t1 = _geqrf_rec(a[:, :h])
    v1 = _v_of(vr1)
    # apply Q1^H to the right block: A2 -= V1 T1^H V1^H A2
    a2 = a[:, h:]
    w = matmul(jnp.conj(v1).T, a2)
    a2 = a2 - matmul(v1, matmul(jnp.conj(t1).T, w)).astype(a.dtype)
    r12, a2b = a2[:h], a2[h:]
    vr2, t2 = _geqrf_rec(a2b)
    v2 = jnp.concatenate([jnp.zeros((h, a2b.shape[1]), a.dtype), _v_of(vr2)], axis=0)
    # merged T: [[T1, -T1 (V1^H V2) T2], [0, T2]]
    t12 = -matmul(t1, matmul(matmul(jnp.conj(v1).T, v2), t2)).astype(a.dtype)
    nt = h + t2.shape[0]
    t = jnp.zeros((nt, nt), a.dtype)
    t = t.at[:h, :h].set(t1).at[:h, h:].set(t12).at[h:, h:].set(t2)
    top = jnp.concatenate([vr1[:h], r12], axis=1)
    bot = jnp.concatenate([vr1[h:], vr2], axis=1)
    return jnp.concatenate([top, bot], axis=0), t


@instrument("geqrf_array")
def geqrf_array(a: Array) -> QRFactors:
    """slate::geqrf (src/geqrf.cc) — A = Q R."""
    vr, t = _geqrf_rec(a)
    return QRFactors(vr, t)


class QRScanFactors(NamedTuple):
    """Scanned QR: R in ``r`` (upper), stacked per-panel global-coordinate
    reflectors ``v`` (K, mp, nb) + WY accumulators ``t`` (K, nb, nb) — the
    same storage the scanned two-stage reductions use (cf. eig.he2hb)."""

    r: Array
    v: Array
    t: Array
    nb: int


def geqrf_scan_array(a: Array, nb: int = _QR_PANEL) -> QRScanFactors:
    """Single-program scanned QR: one lax.fori_loop over panels with
    static shapes (O(1) HLO size in n) — the recursive trace explodes at
    north-star sizes.  Per panel: offset-pivot Householder QR of the
    masked full-height block column, then one global compact-WY update of
    the trailing columns."""
    from jax import lax

    m, n = a.shape
    if m < n:
        raise ValueError(f"geqrf_scan_array requires m >= n, got {a.shape}")
    nblocks = -(-n // nb)
    mp = max(m, (nblocks + 1) * nb)
    np_ = max(n, (nblocks + 1) * nb)
    ap = jnp.pad(a, ((0, mp - m), (0, np_ - n)))
    rows = jnp.arange(mp)
    cols = jnp.arange(np_)

    def body(k, carry):
        ap, vs, ts = carry
        j0 = k * nb
        j1 = j0 + nb
        colblk = lax.dynamic_slice(ap, (0, j0), (mp, nb))
        masked = jnp.where((rows >= j0)[:, None], colblk, 0)
        r_a, v, tau, t = _panel_qr_offset_t(masked, j0)
        w1 = matmul(jnp.conj(v).T, ap)
        upd = matmul(v, matmul(jnp.conj(t).T, w1)).astype(ap.dtype)
        ap = ap - upd * (cols >= j1)[None, :].astype(ap.dtype)
        newcols = jnp.where((rows >= j0)[:, None], r_a, colblk)
        ap = lax.dynamic_update_slice(ap, newcols, (0, j0))
        return ap, vs.at[k].set(v), ts.at[k].set(t)

    carry0 = (
        ap,
        jnp.zeros((nblocks, mp, nb), a.dtype),
        jnp.zeros((nblocks, nb, nb), a.dtype),
    )
    ap, vs, ts = lax.fori_loop(0, nblocks, body, carry0)
    return QRScanFactors(tri_project(ap[:m, :n], Uplo.Upper), vs, ts, nb)


def unmqr_scan_array(f: QRScanFactors, c: Array, op: Op = Op.NoTrans) -> Array:
    """Apply Q (or Q^H) from scanned factors: a fori_loop over the panel
    stack, each step three matmuls (cf. svd.unmbr_ge2tb_u)."""
    from jax import lax

    if op == Op.Trans and jnp.issubdtype(f.v.dtype, jnp.complexfloating):
        raise SlateError("unmqr_scan: Op.Trans unsupported for complex")
    nsteps, mp, _ = f.v.shape
    n0 = c.shape[0]
    cp = jnp.pad(c, ((0, mp - n0),) + ((0, 0),) * (c.ndim - 1))
    adjoint = op != Op.NoTrans

    def body(i, cp):
        k = i if adjoint else nsteps - 1 - i
        v, t = f.v[k], f.t[k]
        t = jnp.conj(t).T if adjoint else t
        return cp - matmul(v, matmul(t, matmul(jnp.conj(v).T, cp))).astype(cp.dtype)

    if nsteps:
        cp = lax.fori_loop(0, nsteps, body, cp)
    return cp[:n0]


def unmqr_array(side: Side, op: Op, f: QRFactors, c: Array) -> Array:
    """Apply Q / Q^H from geqrf factors (src/unmqr.cc): 3 matmuls.  Op.Trans
    on complex factors is undefined for compact-WY (LAPACK unmqr allows only
    'N'/'C' for complex) — rejected rather than silently computing Q^H."""
    if op == Op.Trans and jnp.issubdtype(f.vr.dtype, jnp.complexfloating):
        raise SlateError("unmqr: Op.Trans unsupported for complex; use ConjTrans")
    v = _v_of(f.vr, f.t.shape[0])
    t = f.t if op == Op.NoTrans else jnp.conj(f.t).T
    if side == Side.Left:
        w = matmul(jnp.conj(v).T, c)
        return c - matmul(v, matmul(t, w)).astype(c.dtype)
    w = matmul(c, v)
    return c - matmul(matmul(w, t), jnp.conj(v).T).astype(c.dtype)


def qr_multiply_by_q(f: QRFactors, c: Array, side: Side = Side.Left, op: Op = Op.NoTrans) -> Array:
    return unmqr_array(side, op, f, c)


def geqrf_r(f: QRFactors) -> Array:
    """Extract R (min(m,n) x n upper triangular)."""
    n = f.vr.shape[1]
    return tri_project(f.vr[: min(f.vr.shape[0], n)], Uplo.Upper)


def geqrf_q(f: QRFactors, full: bool = False) -> Array:
    """Materialize Q — thin (m, k) by default."""
    m = f.vr.shape[0]
    k = f.t.shape[0] if not full else m
    eye = jnp.eye(m, k, dtype=f.vr.dtype)
    return unmqr_array(Side.Left, Op.NoTrans, f, eye)


# ---------------------------------------------------------------------------
# LQ (src/gelqf.cc, unmlq.cc): A = L Q via QR of A^H
# ---------------------------------------------------------------------------


def gelqf_array(a: Array) -> LQFactors:
    """slate::gelqf — A = L Q.  Reduction: QR of A^H gives A^H = Qr R, so
    A = R^H Qr^H: L = R^H and the LQ reflectors are the QR reflectors
    conjugate-transposed (same V, applied from the right)."""
    f = geqrf_array(jnp.conj(a).T)
    lv = jnp.conj(f.vr).T
    return LQFactors(lv, f.t)


def unmlq_array(side: Side, op: Op, f: LQFactors, c: Array) -> Array:
    """Apply Q from gelqf: Q = (I - V T V^H)^H with V from the QR of A^H;
    i.e. Q_lq^H = Qr so multiply by Qr with flipped op.  Op.Trans on a
    complex factor would need conj(Qr), which compact-WY can't express by
    op-flipping; LAPACK unmlq likewise only defines 'N'/'C' for complex."""
    if op == Op.Trans and jnp.issubdtype(f.lv.dtype, jnp.complexfloating):
        raise SlateError("unmlq: Op.Trans unsupported for complex; use ConjTrans")
    qr_f = QRFactors(jnp.conj(f.lv).T, f.t)
    flip = {Op.NoTrans: Op.ConjTrans, Op.ConjTrans: Op.NoTrans, Op.Trans: Op.NoTrans}[op]
    return unmqr_array(side, flip, qr_f, c)


def gelqf_l(f: LQFactors) -> Array:
    m = f.lv.shape[0]
    return tri_project(f.lv[:, : min(m, f.lv.shape[1])], Uplo.Lower)


# ---------------------------------------------------------------------------
# CholeskyQR (src/cholqr.cc, MethodCholQR) — the TPU-favourite tall-skinny QR
# ---------------------------------------------------------------------------


def cholqr_array(a: Array) -> Tuple[Array, Array]:
    """Q, R with R from Cholesky of the Gram matrix (A^H A = R^H R):
    one herk + one chol + one trsm — minimal collectives, ideal on a mesh."""
    from .chol import potrf_array

    g = matmul(jnp.conj(a).T, a).astype(a.dtype)
    u, info = potrf_array(g, Uplo.Upper)
    q = trsm_array(Side.Right, Uplo.Upper, Op.NoTrans, Diag.NonUnit, 1.0, u, a)
    return q, u


# ---------------------------------------------------------------------------
# Least squares (src/gels.cc, gels_qr.cc, gels_cholqr.cc)
# ---------------------------------------------------------------------------


@instrument("gels_array")
def gels_array(
    a: Array, b: Array, opts: Optional[Options] = None
) -> Array:
    """Least-squares / minimum-norm solve of op(A) X ~= B (src/gels.cc).
    m >= n: QR; m < n: minimum-norm via LQ."""
    m, n = a.shape
    method = get_option(opts, Option.MethodGels, MethodGels.QR)
    if m >= n:
        if method == MethodGels.CholQR:
            q, r = cholqr_array(a)
            y = matmul(jnp.conj(q).T, b).astype(b.dtype)
            return trsm_array(Side.Left, Uplo.Upper, Op.NoTrans, Diag.NonUnit, 1.0, r, y)
        f = geqrf_array(a)
        qhb = unmqr_array(Side.Left, Op.ConjTrans, f, b)
        r = f.vr[:n]
        return trsm_array(Side.Left, Uplo.Upper, Op.NoTrans, Diag.NonUnit, 1.0, r, qhb[:n])
    # minimum norm: A = L Q, x = Q^H L^-1 b
    f = gelqf_array(a)
    l = f.lv[:, :m]
    y = trsm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, 1.0, l, b)
    ypad = jnp.concatenate([y, jnp.zeros((n - m,) + y.shape[1:], y.dtype)], axis=0)
    return unmlq_array(Side.Left, Op.ConjTrans, f, ypad)


def gels_qr_array(a: Array, b: Array) -> Array:
    return gels_array(a, b, {Option.MethodGels: MethodGels.QR})


def gels_cholqr_array(a: Array, b: Array) -> Array:
    return gels_array(a, b, {Option.MethodGels: MethodGels.CholQR})
