"""LU family: getrf (partial pivot / no-pivot / tournament), getrs, gesv,
getri, band gbtrf/gbtrs/gbsv.

Analogues of reference drivers ``src/{getrf,getrf_nopiv,getrf_tntpiv,getrs,
gesv,getri,gbtrf,gbtrs,gbsv}.cc`` and the panel kernels
``src/internal/internal_getrf.cc`` + ``Tile_getrf.hh:169-417``.

Design inversion (the hardest piece per SURVEY.md §7): the reference panel is
a multithreaded pipeline — per column: thread-local max, cross-thread
reduction, cross-rank MPI exchange, row swap, scale (Tile_getrf.hh) — and row
swaps move single rows between ranks over MPI (internal_swap.cc).  On TPU:

- the *panel* is an unblocked ``lax.fori_loop`` over columns with masked
  argmax pivot search and full-row dynamic swaps — one traced program, no
  latency-bound per-element dispatches;
- the *outer* factorization is recursive (Toledo-style): factor the left
  half, permute, triangular-solve for U12, one big gemm on the trailing
  block, recurse — exact 2n^3/3 flops with O(log n) distinct shapes;
- row swaps become gather/scatter permutations of whole row blocks (XLA
  lowers these to efficient collective permutes when sharded), replacing
  per-row MPI sends;
- tournament pivoting (getrf_tntpiv, CALU) reduces pivot candidates through
  a binary tree of small LUs — the communication-avoiding default for wide
  meshes, mirroring internal_getrf_tntpiv.cc.

Pivots are carried as a row-permutation vector ``perm`` (logical row i of
PA = LU is original row perm[i]) — the functional equivalent of the
reference's Pivots = vector<vector<Pivot>> (types.hh:64).
"""

from __future__ import annotations

from ..obs import instrument

from dataclasses import replace
from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from ..blas3.blas3 import _NB, _split, split_pow2, trsm_array
from ..core.matrix import BaseMatrix, Matrix, band_project, tri_project
from ..ops.matmul import matmul
from ..types import Diag, MethodLU, Op, Option, Options, Side, Uplo, get_option

ArrayLike = Union[jax.Array, BaseMatrix]

_PANEL_W = 64  # unblocked panel width (reference ib, enums InnerBlocking)


class LUFactors(NamedTuple):
    """Packed LU: unit-lower L below diagonal, U on/above; perm applied to
    rows (PA = LU); info = 1 + first zero pivot index, or 0."""

    lu: jax.Array
    perm: jax.Array
    info: jax.Array


# ---------------------------------------------------------------------------
# Unblocked panel (Tile_getrf.hh analogue)
# ---------------------------------------------------------------------------


def _panel_lu(a: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Partial-pivot LU of an (m, w) panel, w small. Returns (lu, perm)."""
    m, w = a.shape
    rows = jnp.arange(m)

    def step(j, carry):
        a, perm = carry
        col = jnp.abs(a[:, j])
        col = jnp.where(rows >= j, col, -jnp.inf)
        p = jnp.argmax(col)
        rj, rp = a[j], a[p]
        a = a.at[j].set(rp).at[p].set(rj)
        pj, pp = perm[j], perm[p]
        perm = perm.at[j].set(pp).at[p].set(pj)
        piv = a[j, j]
        denom = jnp.where(piv == 0, jnp.ones_like(piv), piv)
        below = (rows > j).astype(a.dtype)
        lcol = a[:, j] / denom * below
        a = a.at[:, j].set(a[:, j] * (1 - below) + lcol)
        cmask = (jnp.arange(w) > j).astype(a.dtype)
        a = a - jnp.outer(lcol, a[j] * cmask)
        return a, perm

    # wide panels (m < w): only min(m, w) elimination steps exist; looping
    # past m would argmax an all -inf column and corrupt row m-1
    a, perm = jax.lax.fori_loop(0, min(m, w), step, (a, jnp.arange(m)))
    return a, perm


# ---------------------------------------------------------------------------
# Recursive blocked LU (partial pivoting)
# ---------------------------------------------------------------------------


def _getrf_rec(a: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Recursive LU of (m, n), m >= n. Returns (lu, perm)."""
    m, n = a.shape
    if n <= _PANEL_W:
        return _panel_lu(a)
    h = _split_panel(n)
    lu1, p1 = _getrf_rec(a[:, :h])
    a2 = a[:, h:][p1]
    l11 = lu1[:h, :h]
    u12 = trsm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.Unit, 1.0, l11, a2[:h])
    s = a2[h:] - matmul(lu1[h:, :h], u12).astype(a.dtype)
    lu2, p2 = _getrf_rec(s)
    l21 = lu1[h:, :h][p2]
    top = jnp.concatenate([lu1[:h], u12.reshape(h, n - h)], axis=1)
    bot = jnp.concatenate([l21, lu2], axis=1)
    perm = jnp.concatenate([p1[:h], p1[h:][p2]])
    return jnp.concatenate([top, bot], axis=0), perm


def _split_panel(n: int) -> int:
    return split_pow2(n, _PANEL_W)


def _getrf_rec_inv(a: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Recursive LU of (m, w), m >= w, that ALSO returns inv(unit L11).

    The f64 analogue of _getrf_rec: the U12 triangular solve becomes a
    gemm against the child's unit-L inverse, and the combined inverse is
    assembled block-wise (inv([[L11,0],[L21,L22]]) has i21 = -i22 L21 i11)
    — so every O(m w^2) flop is a matmul riding the f64 dispatch (Ozaki /
    tuned emulation) instead of XLA's crawling emulated trsm (cf.
    chol._potrf_and_inv, same redesign).  Error class: explicit-inverse
    O(eps cond(L11)); partial pivoting keeps |L| <= 1 so unit-L blocks are
    well conditioned in practice (cond growth is the usual pivot-growth
    factor)."""
    m, w = a.shape
    if w <= _PANEL_W:
        lu, perm = _panel_lu(a)
        l11 = jnp.tril(lu[:w], -1) + jnp.eye(w, dtype=a.dtype)
        if a.dtype == jnp.dtype(jnp.float64):
            linv = _unit_linv_f64(l11)
        else:
            linv = jax.lax.linalg.triangular_solve(
                l11[None], jnp.eye(w, dtype=a.dtype)[None],
                left_side=True, lower=True, unit_diagonal=True,
            )[0]
        return lu, perm, linv
    h = _split_panel(w)
    lu1, p1, i1 = _getrf_rec_inv(a[:, :h])
    a2 = a[:, h:][p1]
    u12 = matmul(i1, a2[:h]).astype(a.dtype)
    s = a2[h:] - matmul(lu1[h:, :h], u12).astype(a.dtype)
    lu2, p2, i2 = _getrf_rec_inv(s)
    l21 = lu1[h:, :h][p2]
    i21 = -matmul(i2, matmul(l21[: w - h], i1).astype(a.dtype)).astype(a.dtype)
    top = jnp.concatenate([lu1[:h], u12.reshape(h, w - h)], axis=1)
    bot = jnp.concatenate([l21, lu2], axis=1)
    perm = jnp.concatenate([p1[:h], p1[h:][p2]])
    z = jnp.zeros((h, w - h), a.dtype)
    linv = jnp.block([[i1, z], [i21, i2]])
    return jnp.concatenate([top, bot], axis=0), perm, linv


def _unit_linv_f64(l11: jax.Array) -> jax.Array:
    """inv(unit-lower L) for a small f64 block, f32-seeded + Newton-refined
    (VERDICT r5 item 2, cf. chol._potrf_inv_base_f64): TPU has no native
    f64 triangular_solve — the x64 rewriter unrolls it into serialized
    micro-ops — so the leaf runs the NATIVE f32 solve and two coupled
    Newton sweeps X <- X (2I - L X) in f64 (each a pair of small gemms).
    Seed error ~eps32 * cond(L) squares per sweep; partial pivoting keeps
    |L| <= 1 so cond is modest.  A residual-gated fallback runs the exact
    path when the seed failed or the block is pathological."""
    w = l11.shape[0]
    dt = l11.dtype
    eye = jnp.eye(w, dtype=dt)
    x32 = jax.lax.linalg.triangular_solve(
        l11.astype(jnp.float32)[None], jnp.eye(w, dtype=jnp.float32)[None],
        left_side=True, lower=True, unit_diagonal=True,
    )[0]
    x = jnp.where(jnp.isfinite(x32), x32, 0).astype(dt)
    for _ in range(2):
        x = x @ (2.0 * eye - l11 @ x)
    resid = jnp.linalg.norm(eye - l11 @ x)
    tol = 1e3 * w * jnp.finfo(dt).eps * jnp.linalg.norm(x) * jnp.linalg.norm(l11)
    good = jnp.isfinite(resid) & (resid <= tol)

    def exact():
        return jax.lax.linalg.triangular_solve(
            l11[None], eye[None], left_side=True, lower=True,
            unit_diagonal=True,
        )[0]

    return jax.lax.cond(good, lambda: jnp.tril(x), exact)


def _getrf_left_looking(a: jax.Array, nb: Optional[int] = None) -> Tuple[jax.Array, jax.Array]:
    """Left-looking blocked partial-pivot LU for f64 on TPU (VERDICT r4
    item 1, cf. chol._potrf_left_looking).  Per panel: (1) U rows above
    the panel by blocked forward substitution — gemms against the CACHED
    unit-L diagonal-block inverses from _getrf_rec_inv; (2) one big Schur
    gemm  A[r0:, pj] -= L[r0:, :r0] U[:r0, pj]  whose k = r0 contraction
    is exactly the Ozaki-dispatch win shape; (3) recursive all-gemm panel
    LU with partial pivoting; (4) the panel's row permutation applied to
    the factored history (the permuteRows data motion, src/getrf.cc:161-178,
    as one row gather).  Same 2n^3/3 flops as the right-looking form, but
    the big-k products land where f64 is fast.  Returns (lu, perm)."""
    m, n = a.shape
    if nb is None:
        nb = 4096 if n >= 16384 else 2048
    if n <= nb or m != n:
        return _getrf_rec(a)
    nsteps = -(-n // nb)
    np_ = nsteps * nb
    if np_ != n:
        ap = jnp.pad(a, ((0, np_ - n), (0, np_ - n)))
        dpad = jnp.arange(n, np_)
        ap = ap.at[dpad, dpad].set(1)
    else:
        ap = a
    perm = jnp.arange(np_)
    linvs = []  # unit-L diagonal-block inverses, one per factored panel
    for j in range(nsteps):
        r0 = j * nb
        panel = ap[:, r0 : r0 + nb]
        if j:
            # U[:r0, pj]: blocked forward substitution through the factored
            # diagonal blocks (each step one small + one growing gemm)
            urows = []
            for k in range(j):
                k0 = k * nb
                bk = panel[k0 : k0 + nb]
                if k:
                    bk = bk - matmul(ap[k0 : k0 + nb, :k0], jnp.concatenate(urows, axis=0)).astype(ap.dtype)
                urows.append(matmul(linvs[k], bk).astype(ap.dtype))
            u_top = jnp.concatenate(urows, axis=0)  # (r0, nb)
            # Schur complement of the panel below r0: one big-k gemm
            sc = panel[r0:] - matmul(ap[r0:, :r0], u_top).astype(ap.dtype)
            panel = jnp.concatenate([u_top, sc], axis=0)
        lu_p, pv, linv = _getrf_rec_inv(panel[r0:])
        linvs.append(linv)
        # permute the history + trailing columns FIRST (lu_p is already in
        # pivoted row order), then write the factored panel.  Only the
        # trailing rows [r0:] move — gathering just them (instead of a
        # whole-matrix ap[gpv]) keeps the transient at (n - r0) rows,
        # which is what lets the 16384 f64 factorization fit v5e HBM.
        trail = ap[r0:][pv]
        ap = jax.lax.dynamic_update_slice(ap, trail, (r0, 0))
        perm = perm.at[r0:].set(perm[r0:][pv])
        ap = jax.lax.dynamic_update_slice(
            ap, jnp.concatenate([panel[:r0], lu_p], axis=0), (0, r0)
        )
    return ap[:n, :n], perm[:n]


def _lu_info(lu: jax.Array) -> jax.Array:
    d = jnp.diagonal(lu)
    bad = (d == 0) | ~jnp.isfinite(d)
    return jnp.where(jnp.any(bad), jnp.argmax(bad) + 1, 0).astype(jnp.int32)


_GETRF_LL_MIN_N = 4096  # f64 on TPU: left-looking from here
# Chip-validated ceiling (round 5): the full left-looking program is
# residual-correct on the real chip at 4096 (1.0e-11) and 8192 (3.7e-11),
# but the n = 16384 / nb = 4096 run factors WRONG (independent numpy
# residual 13.7) even though every component — the (12288, 4096) all-gemm
# panel, the f32-seeded unit-L leaf inverses, Ozaki products at the exact
# operand shapes/distributions, and the 4-panel driver at 8192 — passes
# in isolation at matching shapes.  The suspect is an XLA/x64-rewriter
# lowering issue at the full-program scale (e.g. the ~1.6 GB f64
# trailing-row gather); until it is root-caused the dispatch is gated to
# the validated sizes and larger f64 problems take the scanned form.
_GETRF_LL_MAX_N = 8192


@instrument("getrf_array")
def getrf_array(a: jax.Array) -> LUFactors:
    """Partial-pivot LU, PA = LU (src/getrf.cc)."""
    if (
        a.dtype in (jnp.dtype(jnp.float64), jnp.dtype(jnp.complex128))
        and a.ndim == 2
        and a.shape[0] == a.shape[1] >= _GETRF_LL_MIN_N
    ):
        from ..ops.matmul import _tpu_is_default

        if _tpu_is_default():
            if a.shape[0] <= _GETRF_LL_MAX_N:
                lu, perm = _getrf_left_looking(a)
                return LUFactors(lu, perm, _lu_info(lu))
            # past the validated ceiling: the scanned single-program form
            # (correct on chip; the recursive trace is too large here)
            return getrf_scan_array(a)
    lu, perm = _getrf_rec(a)
    return LUFactors(lu, perm, _lu_info(lu))


# ---------------------------------------------------------------------------
# Single-program scanned LU (north-star sizes)
#
# The recursive form above traces a full binary tree of panels — ~2n/w HLO
# node groups, which explodes compile time and program size at n = 65536
# (the reference hits the same wall differently: its task DAG is runtime-
# scheduled, getrf.cc:86-200).  The scanned form is ONE lax.fori_loop whose
# body works on full-size arrays with static shapes and row/col masks, so
# the program is O(1) in n.  Cost: the trailing update runs on the full
# matrix every step (~2.25x the optimal flop count for m = n) — the same
# trade the masked mesh kernels make (parallel/dist_chol.py) — but every
# flop is a big MXU gemm, and compile time stays flat.
# ---------------------------------------------------------------------------


def _swaps_to_perm(piv: jax.Array, kk, m: int, nb: int) -> jax.Array:
    """Permutation vector from a panel's pivot-swap sequence.

    piv[j] is the global row swapped with row kk+j at elimination step j
    (LAPACK ipiv semantics, 0-based).
    """

    def step(j, pv):
        gi = kk + j
        a_, b_ = pv[gi], pv[piv[j]]
        return pv.at[gi].set(b_).at[piv[j]].set(a_)

    return jax.lax.fori_loop(0, nb, step, jnp.arange(m))


def _panel_lu_masked(panel: jax.Array, kk, nmin: int, m_true: int, pivot: bool = True):
    """LU of full-height panel columns [kk, kk+nb) with rows < kk frozen.
    Returns (factored panel, pivot row per column).

    The panel is (mp, nb) with rows >= m_true zero padding; elimination
    step j operates on global row/col index kk+j and is masked off once
    kk+j >= nmin = min(m, n).  Padded and dead (all-zero) columns keep
    p = gi, matching LAPACK's keep-in-place zero-pivot behavior.  With
    ``pivot=False`` no row interchanges happen (pre-pivoted panels,
    tournament path).
    """
    mp, nb = panel.shape
    rows = jnp.arange(mp)
    cols = jnp.arange(nb)

    def step(j, carry):
        pan, piv = carry
        gi = kk + j
        active = gi < nmin
        if pivot:
            col = jax.lax.dynamic_slice(pan, (0, j), (mp, 1))[:, 0]
            mag = jnp.where(
                (rows >= gi) & (rows < m_true) & active, jnp.abs(col), -jnp.inf
            )
            p = jnp.argmax(mag)
            p = jnp.where(active & (mag[p] > 0), p, gi)
            # swap rows gi <-> p
            r_gi = jax.lax.dynamic_slice(pan, (gi, 0), (1, nb))
            r_p = jax.lax.dynamic_slice(pan, (p, 0), (1, nb))
            pan = jax.lax.dynamic_update_slice(pan, r_p, (gi, 0))
            pan = jax.lax.dynamic_update_slice(pan, r_gi, (p, 0))
            piv = piv.at[j].set(p)
        col = jax.lax.dynamic_slice(pan, (0, j), (mp, 1))[:, 0]
        pivval = col[gi]
        denom = jnp.where(pivval == 0, jnp.ones_like(pivval), pivval)
        below = ((rows > gi) & active).astype(pan.dtype)
        lcol = col / denom * below
        newcol = col * (1 - below) + lcol
        pan = jax.lax.dynamic_update_slice(pan, newcol[:, None], (0, j))
        urow = pan[gi] * (cols > j).astype(pan.dtype)
        pan = pan - jnp.outer(lcol, urow)
        return pan, piv

    piv0 = kk + jnp.arange(nb)  # identity swaps for masked-off columns
    return jax.lax.fori_loop(0, nb, step, (panel, piv0))


def _apply_bounded_perm(x: jax.Array, pv: jax.Array, targets: jax.Array):
    """x[pv] when pv differs from the identity only at ``targets``
    (static count): gather + scatter 2nb rows instead of all of x."""
    vals = x[pv[targets]]
    return x.at[targets].set(vals, mode="drop", unique_indices=False)


def _scan_step_update(out, pan, perm, piv, kk, nb: int, pv=None):
    """Shared tail of one scanned panel step: apply the panel's row swaps
    (bounded scatter — a panel moves at most 2nb rows), write the factored
    panel back, masked trsm for the U row block, masked trailing gemm."""
    mp, n = out.shape
    rows = jnp.arange(mp)
    cols = jnp.arange(n)

    if pv is None:
        pv = _swaps_to_perm(piv, kk, mp, nb)
    targets = jnp.concatenate([kk + jnp.arange(nb), piv])
    out = _apply_bounded_perm(out, pv, targets)
    perm = _apply_bounded_perm(perm, pv, targets)
    out = jax.lax.dynamic_update_slice(out, pan, (0, kk))
    l11 = tri_project(
        jax.lax.dynamic_slice(pan, (kk, 0), (nb, nb)), Uplo.Lower, Diag.Unit
    )
    rowblk = jax.lax.dynamic_slice(out, (kk, 0), (nb, n))
    # row solve as explicit-inverse gemm (cf. chol._potrf_scan): the
    # wide-rhs triangular_solve runs ~10x below the MXU matmul rate
    linv = jax.lax.linalg.triangular_solve(
        l11[None], jnp.eye(nb, dtype=out.dtype)[None], left_side=True,
        lower=True, transpose_a=False, unit_diagonal=True,
    )[0]
    u12 = matmul(linv, rowblk).astype(out.dtype)
    right = (cols >= kk + nb)[None, :]
    rowblk = jnp.where(right, u12, rowblk)
    out = jax.lax.dynamic_update_slice(out, rowblk, (kk, 0))
    l21 = pan * ((rows >= kk + nb)[:, None]).astype(pan.dtype)
    u12m = rowblk * right.astype(pan.dtype)
    out = out - matmul(l21, u12m).astype(out.dtype)
    return out, perm


def getrf_scan_array(
    a: jax.Array, nb: int = _PANEL_W, nbuckets: int = 4
) -> LUFactors:
    """Partial-pivot LU as one fixed-shape scanned program (PA = LU).

    Same math and pivot choices as ``getrf_array`` (src/getrf.cc
    semantics); built for north-star sizes where the recursive trace is
    too large to compile.  On exactly singular inputs the zero-pivot rows
    stay in place (info > 0 flags them) rather than swapping zero rows.

    The k-range is segmented into ``nbuckets`` statically-shrinking
    trailing views (cf. parallel.dist_chol bucketing): pivot search and
    swaps only ever touch rows >= k, so each bucket runs entirely on
    ``out[off:, off:]``, cutting the HBM-bound masked trailing traffic to
    ~0.47x of the full-width form at 4 buckets; finished L columns receive
    the bucket's composed row permutation in one gather at bucket end
    (LAPACK's deferred laswp on columns < k).
    """
    m, n = a.shape
    nmin = min(m, n)
    nsteps = -(-nmin // nb)
    # pad rows AND cols so the dynamic panel slices never clamp (a clamped
    # start silently reads the wrong window)
    mp = max(m, nsteps * nb)
    np_ = max(n, nsteps * nb)
    out = jnp.pad(a, ((0, mp - m), (0, np_ - n)))
    perm = jnp.arange(mp)

    bounds = [nsteps * g // nbuckets for g in range(nbuckets)] + [nsteps]
    for g in range(nbuckets):
        k0, k1 = bounds[g], bounds[g + 1]
        if k0 == k1:
            continue
        off = k0 * nb
        view = out[off:, off:]
        mv = mp - off

        def body(k, carry, off=off, mv=mv):
            view, pl = carry
            kk = k * nb - off  # view-local column/row of the panel head
            panel = jax.lax.dynamic_slice(view, (0, kk), (mv, nb))
            # global masks shift uniformly: local row r is global off + r
            pan, piv = _panel_lu_masked(panel, kk, nmin - off, m - off)
            # the factored panel is already in post-swap row order; swapping
            # `view` rows then overwriting columns [kk, kk+nb) reconciles both
            return _scan_step_update(view, pan, pl, piv, kk, nb)

        view, pl = jax.lax.fori_loop(
            k0, k1, body, (view, jnp.arange(mv))
        )
        out = out.at[off:, off:].set(view)
        if off:
            out = out.at[off:, :off].set(out[off:, :off][pl])
        perm = perm.at[off:].set(perm[off:][pl])
    return LUFactors(out[:m, :n], perm[:m], _lu_info(out[:m, :n]))


# ---------------------------------------------------------------------------
# No-pivot LU (src/getrf_nopiv.cc) — structurally potrf-like
# ---------------------------------------------------------------------------


def _getrf_nopiv_rec(a: jax.Array) -> jax.Array:
    n = min(a.shape)
    if n <= _NB:
        return _nopiv_base(a)
    h = _split(n)
    a11, a12, a21, a22 = a[:h, :h], a[:h, h:], a[h:, :h], a[h:, h:]
    lu11 = _nopiv_base(a11) if h <= _NB else _getrf_nopiv_rec(a11)
    u12 = trsm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.Unit, 1.0, lu11, a12)
    l21 = trsm_array(Side.Right, Uplo.Upper, Op.NoTrans, Diag.NonUnit, 1.0, lu11, a21)
    s = a22 - matmul(l21, u12).astype(a.dtype)
    lu22 = _getrf_nopiv_rec(s)
    return jnp.block([[lu11, u12], [l21, lu22]])


def _nopiv_base(a: jax.Array) -> jax.Array:
    m, n = a.shape
    rows = jnp.arange(m)

    def step(j, a):
        piv = a[j, j]
        denom = jnp.where(piv == 0, jnp.ones_like(piv), piv)
        below = (rows > j).astype(a.dtype)
        lcol = a[:, j] / denom * below
        a = a.at[:, j].set(a[:, j] * (1 - below) + lcol)
        cmask = (jnp.arange(n) > j).astype(a.dtype)
        return a - jnp.outer(lcol, a[j] * cmask)

    return jax.lax.fori_loop(0, min(m, n), step, a)


def getrf_nopiv_array(a: jax.Array) -> LUFactors:
    lu = _getrf_nopiv_rec(a)
    return LUFactors(lu, jnp.arange(a.shape[0]), _lu_info(lu))


# ---------------------------------------------------------------------------
# Tournament pivoting (CALU, src/getrf_tntpiv.cc + internal_getrf_tntpiv.cc)
# ---------------------------------------------------------------------------


def _tournament_reduce(ap: jax.Array, idx: jax.Array, w: int, sentinel: int):
    """Binary-tree reduction of pivot candidates: small partial-pivot LUs
    pick the w best rows per block, pairs of blocks merge until one block
    remains.  ``ap`` (rows, w) must have invalid rows zeroed and ``idx``
    (rows,) their ids set to ``sentinel``.  Returns (values, ids) of the w
    winners.  Shared by the single-chip scanned tntpiv and the mesh
    tournament (parallel/dist_lu.py)."""
    mp = ap.shape[0]
    block = max(2 * w, _PANEL_W)
    nblk = -(-mp // block)
    pad = nblk * block - mp
    ap = jnp.pad(ap, ((0, pad), (0, 0)))
    idx = jnp.pad(idx, (0, pad), constant_values=sentinel)
    cand_a = ap.reshape(nblk, block, w)
    cand_i = idx.reshape(nblk, block)

    def local_top(a_blk, i_blk):
        _, p = _panel_lu(a_blk)
        return a_blk[p][:w], i_blk[p][:w]

    tops_a, tops_i = jax.vmap(local_top)(cand_a, cand_i)
    while tops_a.shape[0] > 1:
        k = tops_a.shape[0]
        if k % 2 == 1:  # odd: pad a dead block
            tops_a = jnp.concatenate([tops_a, tops_a[-1:] * 0], axis=0)
            tops_i = jnp.concatenate(
                [tops_i, jnp.full_like(tops_i[-1:], sentinel)], axis=0
            )
            k += 1
        pa = tops_a.reshape(k // 2, 2 * w, w)
        pi = tops_i.reshape(k // 2, 2 * w)
        tops_a, tops_i = jax.vmap(local_top)(pa, pi)
    return tops_a[0], tops_i[0]


def _tournament_pivots_masked(panel: jax.Array, w: int, kk, m_true: int) -> jax.Array:
    """Tournament pivot selection over full-height panel rows with rows
    < kk (already factored) and >= m_true (padding) masked out.  Static
    shapes throughout: the block grid and tree depth depend only on the
    padded height.  Returns w global row indices (invalid slots carry the
    sentinel mp when fewer than w candidate rows remain)."""
    mp = panel.shape[0]
    rows = jnp.arange(mp)
    valid = (rows >= kk) & (rows < m_true)
    ap = jnp.where(valid[:, None], panel, 0)
    idx = jnp.where(valid, rows, mp)  # sentinel rows sort last in each LU
    _, tops_i = _tournament_reduce(ap, idx, w, mp)
    return tops_i


def _tournament_swap_seq(piv: jax.Array, kk, mp: int) -> jax.Array:
    """Convert tournament-selected global rows into a LAPACK-style
    sequential swap sequence (swap i brings selected row i to kk+i),
    tracking row positions as earlier swaps displace them."""
    w = piv.shape[0]

    def step(i, carry):
        seq, pos2row, row2pos = carry
        tgt = kk + i
        valid = piv[i] < mp
        cur = jnp.where(valid, row2pos[jnp.minimum(piv[i], mp - 1)], tgt)
        r1 = pos2row[tgt]
        r2 = pos2row[cur]
        pos2row = pos2row.at[tgt].set(r2).at[cur].set(r1)
        row2pos = row2pos.at[r2].set(tgt).at[r1].set(cur)
        return seq.at[i].set(cur), pos2row, row2pos

    seq0 = kk + jnp.arange(w)
    ident = jnp.arange(mp)
    seq, _, _ = jax.lax.fori_loop(0, w, step, (seq0, ident, ident))
    return seq


def getrf_tntpiv_array(a: jax.Array, nb: int = _PANEL_W) -> LUFactors:
    """Blocked LU with tournament pivoting (CALU) as one fixed-shape
    scanned program.  Per panel, the tournament tree picks nb pivot rows
    which are swapped to the top LAPACK-style, then the panel factors
    without further interchanges (getrf_tntpiv.cc:18-169,
    internal_getrf_tntpiv.cc)."""
    m, n = a.shape
    nmin = min(m, n)
    nb = min(nb, nmin)
    nsteps = -(-nmin // nb)
    mp = max(m, nsteps * nb)
    np_ = max(n, nsteps * nb)
    out = jnp.pad(a, ((0, mp - m), (0, np_ - n)))

    def body(k, carry):
        out, perm = carry
        kk = k * nb
        panel = jax.lax.dynamic_slice(out, (0, kk), (mp, nb))
        piv_rows = _tournament_pivots_masked(panel, nb, kk, m)
        piv = _tournament_swap_seq(piv_rows, kk, mp)
        pv = _swaps_to_perm(piv, kk, mp, nb)
        targets = jnp.concatenate([kk + jnp.arange(nb), piv])
        panel = _apply_bounded_perm(panel, pv, targets)
        pan, _ = _panel_lu_masked(panel, kk, nmin, m, pivot=False)
        out, perm = _scan_step_update(out, pan, perm, piv, kk, nb, pv=pv)
        return out, perm

    out, perm = jax.lax.fori_loop(0, nsteps, body, (out, jnp.arange(mp)))
    return LUFactors(out[:m, :n], perm[:m], _lu_info(out[:m, :n]))


# ---------------------------------------------------------------------------
# Solves / drivers
# ---------------------------------------------------------------------------


def getrs_array(f: LUFactors, b: jax.Array, op: Op = Op.NoTrans) -> jax.Array:
    """Solve op(A) X = B from factors (src/getrs.cc)."""
    lu, perm = f.lu, f.perm
    n = lu.shape[0]
    if op == Op.NoTrans:
        pb = b[perm]
        y = trsm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.Unit, 1.0, lu, pb)
        return trsm_array(Side.Left, Uplo.Upper, Op.NoTrans, Diag.NonUnit, 1.0, lu, y)
    # op(A) = A^T or A^H: solve U^op y = b; L^op z = y; x = P^T z
    y = trsm_array(Side.Left, Uplo.Upper, op, Diag.NonUnit, 1.0, lu, b)
    z = trsm_array(Side.Left, Uplo.Lower, op, Diag.Unit, 1.0, lu, y)
    inv = jnp.argsort(perm)
    return z[inv]


@instrument("gesv_array")
def gesv_array(a: jax.Array, b: jax.Array, method: MethodLU = MethodLU.PartialPiv):
    """Factor + solve (src/gesv.cc). Returns (x, factors)."""
    if method == MethodLU.PartialPiv:
        f = getrf_array(a)
    elif method == MethodLU.CALU:
        f = getrf_tntpiv_array(a)
    elif method == MethodLU.NoPiv:
        f = getrf_nopiv_array(a)
    elif method == MethodLU.RBT:
        from .rbt import gesv_rbt_array

        return gesv_rbt_array(a, b)
    else:
        raise ValueError(method)
    return getrs_array(f, b), f


def getri_array(f: LUFactors) -> jax.Array:
    """Matrix inverse from factors (src/getri.cc): A^-1 = U^-1 L^-1 P."""
    from .tri import trtri_array

    uinv = trtri_array(tri_project(f.lu, Uplo.Upper), Uplo.Upper, Diag.NonUnit)
    linv = trtri_array(tri_project(f.lu, Uplo.Lower, Diag.Unit), Uplo.Lower, Diag.Unit)
    x = matmul(uinv, linv).astype(f.lu.dtype)
    # A^-1 = (U^-1 L^-1) P; right-multiplying by P permutes columns by
    # perm^-1 since (X P)[i, j] = X[i, perm^-1(j)]
    return x[:, jnp.argsort(f.perm)]


def getri_oop_array(a: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Out-of-place inverse (src/getriOOP.cc): factor A and solve
    A X = I without forming triangular inverses — the reference's
    workspace-matrix variant.  Returns (A^-1, info)."""
    f = getrf_array(a)
    eye = jnp.eye(a.shape[0], dtype=a.dtype)
    return getrs_array(f, eye), f.info


# object-level drivers -------------------------------------------------------


def getrf(a: ArrayLike, opts: Optional[Options] = None) -> Tuple[Matrix, LUFactors]:
    ad = a.array if isinstance(a, BaseMatrix) else jnp.asarray(a)
    method = get_option(opts, Option.MethodLU, MethodLU.PartialPiv)
    if method == MethodLU.CALU:
        # MaxPanelThreads (reference: threads cooperating on one panel,
        # internal_getrf.cc) maps to the tournament panel-width
        # multiplier: wider panels amortize per-step latency against
        # bigger trailing updates, the same trade the reference makes by
        # adding panel threads (PartialPiv/NoPiv panels are recursive and
        # take no width knob).  NUMERICAL SIDE EFFECT — unlike the
        # reference, where the option is parallelism-only and bitwise
        # neutral, here it changes the CALU tournament width and hence
        # WHICH pivots win: a wider panel factors more columns without
        # interchanges between tournament rounds, so pivot quality (and
        # the element growth bound) degrades as the width grows.  Results
        # remain backward-stable in the CALU sense but are NOT invariant
        # under this option.  Clamped to 8x: past ~512-wide panels the
        # tournament factors without interchanges over too many columns
        # (pivot-growth risk) and the block LUs blow up compile time.
        threads = int(get_option(opts, Option.MaxPanelThreads, 1))
        f = getrf_tntpiv_array(ad, nb=_PANEL_W * min(max(1, threads), 8))
    elif method == MethodLU.NoPiv:
        f = getrf_nopiv_array(ad)
    else:
        f = getrf_array(ad)
    return Matrix(data=f.lu), f


def gesv(a: ArrayLike, b: ArrayLike, opts: Optional[Options] = None):
    ad = a.array if isinstance(a, BaseMatrix) else jnp.asarray(a)
    bd = b.array if isinstance(b, BaseMatrix) else jnp.asarray(b)
    method = get_option(opts, Option.MethodLU, MethodLU.PartialPiv)
    x, f = gesv_array(ad, bd, method)
    if isinstance(b, BaseMatrix):
        x = replace(b, data=x)
    return x, f


# ---------------------------------------------------------------------------
# Band LU (src/gbtrf.cc, gbtrs.cc, gbsv.cc)
# ---------------------------------------------------------------------------


def gbtrf_array(a: jax.Array, kl: int, ku: int) -> LUFactors:
    """Band LU with partial pivoting. Pivoting widens U's band to kl + ku
    (LAPACK gbtrf semantics), so U is projected to that band; L's multiplier
    columns have at most kl nonzeros each but pivoting scatters them to
    arbitrary rows (Golub & Van Loan band-LU), so the strictly-lower part is
    kept dense — projecting it would corrupt the factorization."""
    f = getrf_array(band_project(a, kl, ku))
    l_part = tri_project(f.lu, Uplo.Lower, Diag.Unit) - jnp.eye(*f.lu.shape, dtype=f.lu.dtype)
    u_part = band_project(tri_project(f.lu, Uplo.Upper), 0, kl + ku)
    return LUFactors(l_part + u_part, f.perm, f.info)


def gbtrs_array(f, b: jax.Array, kl: int, ku: int, op: Op = Op.NoTrans) -> jax.Array:
    from .band import BandLU, gbtrs_band

    if isinstance(f, BandLU):  # narrow-band factor from gbsv_array's routing
        if op != Op.NoTrans:
            raise ValueError("windowed band factors support op=NoTrans only")
        return gbtrs_band(f, b)
    return getrs_array(f, b, op)


def gbsv_array(a: jax.Array, b: jax.Array, kl: int, ku: int):
    """Band solve (src/gbsv.cc).  Narrow bands take the windowed
    O(n kl (kl+ku)) path (linalg.band, LAPACK gbtrf pivot semantics —
    its factor carries per-window permutations, not a global one); wide
    bands fall back to the dense partial-pivot factorization."""
    from .band import band_worthwhile

    if band_worthwhile(a.shape[0], max(kl, 1) + max(ku, 1)):
        from .band import gbsv_band

        x, f, info = gbsv_band(a, b, kl, ku)
        return x, f
    f = gbtrf_array(a, kl, ku)
    return gbtrs_array(f, b, kl, ku), f
