from .chol import (
    pbsv,
    pbsv_array,
    pbtrf_array,
    pbtrs_array,
    posv,
    posv_array,
    potrf,
    potrf_array,
    potri,
    potri_array,
    potrs,
    potrs_array,
)
from .lu import (
    LUFactors,
    gbsv_array,
    gbtrf_array,
    gbtrs_array,
    gesv,
    gesv_array,
    getrf,
    getrf_array,
    getrf_nopiv_array,
    getrf_tntpiv_array,
    getri_array,
    getrs_array,
)
from .refine import (
    gesv_mixed_array,
    gesv_mixed_gmres_array,
    posv_mixed_array,
    posv_mixed_gmres_array,
)
from .rbt import apply_butterfly, gerbt_array, gesv_rbt_array
from .tri import trtri_array, trtrm_array
