from .chol import (
    pbsv,
    pbsv_array,
    pbtrf_array,
    pbtrs_array,
    posv,
    posv_array,
    potrf,
    potrf_array,
    potri,
    potri_array,
    potrs,
    potrs_array,
)
from .lu import (
    LUFactors,
    gbsv_array,
    gbtrf_array,
    gbtrs_array,
    gesv,
    gesv_array,
    getrf,
    getrf_array,
    getrf_nopiv_array,
    getrf_scan_array,
    getrf_tntpiv_array,
    getri_array,
    getri_oop_array,
    getrs_array,
)
from .refine import (
    RefineResult,
    gesv_mixed_array,
    gesv_mixed_gmres_array,
    posv_mixed_array,
    posv_mixed_gmres_array,
)
from .rbt import apply_butterfly, gerbt_array, gesv_rbt_array
from .tri import trtri_array, trtrm_array
from .qr import (
    LQFactors,
    QRFactors,
    cholqr_array,
    gelqf_array,
    gels_array,
    gels_cholqr_array,
    gels_qr_array,
    geqrf_array,
    geqrf_q,
    geqrf_r,
    unmlq_array,
    unmqr_array,
)
from .norms import (
    col_norms,
    gecondest,
    norm,
    norm1est,
    pocondest,
    trcondest,
)
from .tridiag import stedc, stedc_vals, steqr, sterf
from .eig import (
    He2hbFactors,
    he2hb,
    heev_array,
    heev_staged,
    hegst_array,
    hegv_array,
    hb2st,
    unmtr_hb2st,
    unmtr_he2hb,
)
from .svd import (
    Ge2tbFactors,
    bdsqr,
    ge2tb,
    svd_array,
    svd_staged,
    tb2bd,
    unmbr_ge2tb_u,
    unmbr_ge2tb_v,
)
from .indefinite import (
    HetrfFactors,
    gtsv_array,
    hesv_array,
    hetrf_array,
    hetrs_array,
    sysv_array,
)
