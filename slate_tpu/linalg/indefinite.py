"""Hermitian/symmetric indefinite solvers: hetrf / hetrs / hesv (+ sysv).

Analogue of the reference's Aasen tier: ``src/hetrf.cc`` (633 LoC, Aasen's
LTL^H with a banded T and panel pivoting), ``src/hetrs.cc``, ``src/hesv.cc``.

Design inversion for TPU: Aasen's column-recurrence (H = T L^H bookkeeping,
per-column pivot exchanges) is latency-bound and pivot-heavy — a poor map to
the MXU.  This build factors the indefinite matrix by *unitary congruence*
instead: A = Q T Q^H via the same two-stage band reduction used by the
eigensolver (he2hb -> hb2st, all BLAS-3 + a fixed bulge chase), with T real
symmetric tridiagonal.  The solve is then Q (T^-1 (Q^H b)) with a
partial-pivot tridiagonal LU (gtsv).  Same capability and stability class
(unitary transforms are unconditionally stable; gtsv pivots), ~4x the
flops of Aasen but MXU-resident — the classic TPU trade (SURVEY §7).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.matmul import matmul
from .eig import He2hbFactors, Hb2stFactors, he2hb, hb2st, _EIG_NB


Array = jax.Array


# ---------------------------------------------------------------------------
# Tridiagonal solve with partial pivoting (LAPACK gtsv)
# ---------------------------------------------------------------------------


def gtsv_array(dl: Array, d: Array, du: Array, b: Array) -> Tuple[Array, Array]:
    """Solve tridiag(dl, d, du) X = B with partial pivoting (row swaps
    between adjacent rows only — gtsv's structure).  Returns (X, info)."""
    n = d.shape[0]
    if b.ndim == 1:
        x, info = gtsv_array(dl, d, du, b[:, None])
        return x[:, 0], info
    dtype = b.dtype
    # working diagonals: d (main), du1 (first super), du2 (second super,
    # created by swaps)
    du1 = jnp.concatenate([du, jnp.zeros((1,), du.dtype)])
    du2 = jnp.zeros((n,), d.dtype)
    dl_w = jnp.concatenate([dl, jnp.zeros((1,), dl.dtype)])

    def fwd(k, carry):
        d_, du1_, du2_, b_ = carry
        lk = dl_w[k]  # subdiagonal element A[k+1, k]
        swap = jnp.abs(lk) > jnp.abs(d_[k])
        k1 = jnp.minimum(k + 1, n - 1)
        # rows k and k+1 of the active 3-wide band
        r0 = jnp.stack([d_[k], du1_[k], du2_[k]])
        r1 = jnp.stack([lk, d_[k1], du1_[k1]])
        top = jnp.where(swap, r1, r0)
        bot = jnp.where(swap, r0, r1)
        piv = jnp.where(top[0] == 0, 1, top[0])
        m = bot[0] / piv
        bot = bot - m * top
        d_ = d_.at[k].set(top[0]).at[k1].set(jnp.where(k1 > k, bot[1], d_[k1]))
        du1_ = du1_.at[k].set(top[1]).at[k1].set(jnp.where(k1 > k, bot[2], du1_[k1]))
        du2_ = du2_.at[k].set(top[2])
        bk = b_[k]
        bk1 = b_[k1]
        btop = jnp.where(swap, bk1, bk)
        bbot = jnp.where(swap, bk, bk1) - m * btop
        b_ = b_.at[k].set(btop)
        b_ = b_.at[k1].set(jnp.where(k1 > k, bbot, b_[k1]))
        return d_, du1_, du2_, b_

    d_, du1_, du2_, b_ = lax.fori_loop(0, n - 1, fwd, (d.astype(dtype), du1.astype(dtype), du2, b))

    # back substitution with the 3-wide upper band
    def bwd(t, x):
        k = n - 1 - t
        k1 = jnp.minimum(k + 1, n - 1)
        k2 = jnp.minimum(k + 2, n - 1)
        upper = du1_[k] * jnp.where(k1 > k, x[k1], 0) + du2_[k] * jnp.where(k2 > k + 1, x[k2], 0)
        piv = jnp.where(d_[k] == 0, 1, d_[k])
        return x.at[k].set((b_[k] - upper) / piv)

    x = lax.fori_loop(0, n, bwd, jnp.zeros_like(b_))
    dd = jnp.abs(d_)
    bad = (dd == 0) | ~jnp.isfinite(dd)
    info = jnp.where(jnp.any(bad), jnp.argmax(bad) + 1, 0).astype(jnp.int32)
    return x, info


# ---------------------------------------------------------------------------
# hetrf / hetrs / hesv
# ---------------------------------------------------------------------------


class HetrfFactors(NamedTuple):
    """A = Q T Q^H: stage-1/2 transforms + real tridiagonal T."""

    stage1: He2hbFactors
    stage2: Hb2stFactors
    phases: Array
    d: Array  # T main diagonal (real)
    e: Array  # T off-diagonal (real)


def hetrf_array(a: Array, nb: int = _EIG_NB) -> Tuple[HetrfFactors, Array]:
    """Factor the Hermitian indefinite A = Q T Q^H (src/hetrf.cc capability;
    see module docstring for the design inversion).  info = 0 unless T is
    exactly singular (reported by the solve)."""
    f1 = he2hb(a, nb)
    d, e, f2, phases = hb2st(f1.band, nb)
    return HetrfFactors(f1, f2, phases, d, e), jnp.zeros((), jnp.int32)


def _apply_q(f: HetrfFactors, c: Array, adjoint: bool) -> Array:
    """c <- Q c (or Q^H c): Q = Q_he2hb * U_hb2st * P_phases."""
    from .eig import unmtr_hb2st, unmtr_he2hb

    cplx = jnp.issubdtype(c.dtype, jnp.complexfloating)
    if not adjoint:
        z = c
        if cplx:
            z = f.phases[:, None] * z
        z = unmtr_hb2st(f.stage2, z)
        return unmtr_he2hb(f.stage1, z)
    # Q^H c: reverse each factor, conj-transposed, in opposite order
    z = _unmtr_he2hb_adj(f.stage1, c)
    z = _unmtr_hb2st_adj(f.stage2, z)
    if cplx:
        z = jnp.conj(f.phases)[:, None] * z
    return z


def _unmtr_he2hb_adj(f1: He2hbFactors, c: Array) -> Array:
    """C <- Q^H C for the stage-1 Q (forward order, T^H).  V is stored in
    global row coordinates (zeros above each panel), so each update only
    touches the panel's trailing rows."""
    nsteps, np2, _ = f1.v.shape
    n = c.shape[0]
    cp = jnp.pad(c, ((0, np2 - n),) + ((0, 0),) * (c.ndim - 1))

    def body(k, cp):
        v, t = f1.v[k], f1.t[k]
        upd = matmul(v, matmul(jnp.conj(t).T, matmul(jnp.conj(v).T, cp))).astype(cp.dtype)
        return cp - upd

    if nsteps:  # zero-panel case: Q is the identity
        cp = jax.lax.fori_loop(0, nsteps, body, cp)
    return cp[:n]


def _unmtr_hb2st_adj(f2: Hb2stFactors, z: Array) -> Array:
    """Z <- U^H Z with U = H_1^H ... H_N^H: apply H_i chronologically
    (batched per sweep, eig._chase_sweep_apply adjoint path)."""
    from .eig import _chase_sweep_apply

    return _chase_sweep_apply(f2.vs, f2.taus, z, f2.n, f2.w, adjoint=True)


def hetrs_array(f: HetrfFactors, b: Array) -> Tuple[Array, Array]:
    """Solve A X = B from hetrf factors (src/hetrs.cc)."""
    squeeze = b.ndim == 1
    bd = b[:, None] if squeeze else b
    y = _apply_q(f, bd, adjoint=True)
    e = f.e.astype(bd.dtype)
    t, info = gtsv_array(e, f.d.astype(bd.dtype), e, y)
    x = _apply_q(f, t, adjoint=False)
    return (x[:, 0] if squeeze else x), info


def hesv_array(a: Array, b: Array, nb: int = _EIG_NB):
    """Factor + solve (src/hesv.cc). Returns (x, factors, info)."""
    f, _ = hetrf_array(a, nb)
    x, info = hetrs_array(f, b)
    return x, f, info


# symmetric aliases (src/sysv exposure; real symmetric == Hermitian path)
sytrf_array = hetrf_array
sytrs_array = hetrs_array
sysv_array = hesv_array
