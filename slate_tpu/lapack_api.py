"""LAPACK-signature API: drop-in named routines over numpy/JAX arrays.

Analogue of the reference's ``lapack_api/`` (23 files: slate_dgetrf etc.,
LAPACK-style shims for single-process callers) and the spirit of
``scalapack_api/`` (drop-in pdgemm_): in the TPU ecosystem the "drop-in"
surface is numpy/scipy-style Python, so each routine takes/returns arrays
with LAPACK naming and semantics.  Precision prefixes: s/d (f32/f64),
c/z (c64/c128) — the d/z versions require jax x64 to be enabled.
"""

from __future__ import annotations


import jax.numpy as jnp

from .blas3.blas3 import (
    gemm_array,
    hemm as _hemm_drv,
    her2k as _her2k_drv,
    herk as _herk_drv,
    symm as _symm_drv,
    syr2k as _syr2k_drv,
    syrk as _syrk_drv,
    trmm_array,
    trsm_array,
)
from .linalg import (
    gels_array,
    geqrf_array,
    gesv_array,
    gesv_mixed_array,
    getrf_array,
    getri_array,
    getrs_array,
    heev_array,
    hesv_array,
    norm,
    posv_array,
    posv_mixed_array,
    potrf_array,
    potri_array,
    potrs_array,
    svd_array,
)
from .linalg.norms import gecondest, pocondest
from .ops.tile_ops import genorm as _genorm, henorm as _henorm, trnorm as _trnorm
from .types import Diag, Norm, Op, Side, Uplo

_DTYPES = {"s": jnp.float32, "d": jnp.float64, "c": jnp.complex64, "z": jnp.complex128}


def _cast(dtype, a):
    return jnp.asarray(a).astype(dtype)


def _make(prefix):
    dt = _DTYPES[prefix]

    ns = {}

    def gemm(transa, transb, m, n, k, alpha, a, b, beta, c):
        opa = {"N": lambda x: x, "T": lambda x: x.T, "C": lambda x: jnp.conj(x).T}[transa.upper()]
        opb = {"N": lambda x: x, "T": lambda x: x.T, "C": lambda x: jnp.conj(x).T}[transb.upper()]
        return gemm_array(alpha, opa(_cast(dt, a)), opb(_cast(dt, b)), beta, _cast(dt, c))

    def gesv(a, b):
        x, f = gesv_array(_cast(dt, a), _cast(dt, b))
        return x, f, int(f.info)

    def getrf(a):
        return getrf_array(_cast(dt, a))

    def getrs(f, b, trans="N"):
        op = {"N": Op.NoTrans, "T": Op.Trans, "C": Op.ConjTrans}[trans.upper()]
        return getrs_array(f, _cast(dt, b), op)

    def getri(f):
        return getri_array(f)

    def posv(a, b, uplo="L"):
        x, l, info = posv_array(_cast(dt, a), _cast(dt, b), _uplo(uplo))
        return x, l, int(info)

    def potrf(a, uplo="L"):
        l, info = potrf_array(_cast(dt, a), _uplo(uplo))
        return l, int(info)

    def potrs(l, b, uplo="L"):
        return potrs_array(_cast(dt, l), _cast(dt, b), _uplo(uplo))

    def geqrf(a):
        return geqrf_array(_cast(dt, a))

    def gels(a, b):
        return gels_array(_cast(dt, a), _cast(dt, b))

    def gesvd(a):
        return svd_array(_cast(dt, a))

    def gecon(norm_char, a, anorm=None):
        ad = _cast(dt, a)
        f = getrf_array(ad)
        nt = Norm.One if norm_char.upper() in ("1", "O") else Norm.Inf
        if anorm is None:
            anorm = float(norm(nt, ad))
        return float(gecondest(nt, f, anorm))

    def trsm(side, uplo, trans, diag, alpha, a, b):
        return trsm_array(
            Side.Left if side.upper() == "L" else Side.Right,
            _uplo(uplo),
            {"N": Op.NoTrans, "T": Op.Trans, "C": Op.ConjTrans}[trans.upper()],
            Diag.Unit if diag.upper() == "U" else Diag.NonUnit,
            alpha, _cast(dt, a), _cast(dt, b),
        )

    def trmm(side, uplo, trans, diag, alpha, a, b):
        # lapack_api/lapack_trmm.cc
        return trmm_array(
            Side.Left if side.upper() == "L" else Side.Right,
            _uplo(uplo),
            {"N": Op.NoTrans, "T": Op.Trans, "C": Op.ConjTrans}[trans.upper()],
            Diag.Unit if diag.upper() == "U" else Diag.NonUnit,
            alpha, _cast(dt, a), _cast(dt, b),
        )

    def _side(s):
        return Side.Left if s.upper() == "L" else Side.Right

    def hemm(side, uplo, alpha, a, b, beta, c):
        # lapack_api/lapack_hemm.cc: C := alpha A B + beta C, A Hermitian
        from .core.matrix import HermitianMatrix

        am = HermitianMatrix.from_array(_cast(dt, a), _uplo(uplo))
        return _hemm_drv(_side(side), alpha, am, _cast(dt, b), beta, _cast(dt, c))

    def symm(side, uplo, alpha, a, b, beta, c):
        # lapack_api/lapack_symm.cc
        from .core.matrix import SymmetricMatrix

        am = SymmetricMatrix.from_array(_cast(dt, a), _uplo(uplo))
        return _symm_drv(_side(side), alpha, am, _cast(dt, b), beta, _cast(dt, c))

    def _rank_op(trans, a):
        # LAPACK herk/syrk trans: 'N' uses A (n x k); 'T'/'C' uses A^T/A^H
        ad = _cast(dt, a)
        t = trans.upper()
        if t == "N":
            return ad
        return jnp.conj(ad).T if t == "C" else ad.T

    def herk(uplo, trans, alpha, a, beta, c):
        # lapack_api/lapack_herk.cc: C := alpha op(A) op(A)^H + beta C
        return _herk_drv(alpha, _rank_op(trans, a), beta, _cast(dt, c), _uplo(uplo))

    def syrk(uplo, trans, alpha, a, beta, c):
        return _syrk_drv(alpha, _rank_op(trans, a), beta, _cast(dt, c), _uplo(uplo))

    def her2k(uplo, trans, alpha, a, b, beta, c):
        # lapack_api/lapack_her2k.cc
        return _her2k_drv(alpha, _rank_op(trans, a), _rank_op(trans, b), beta,
                          _cast(dt, c), _uplo(uplo))

    def syr2k(uplo, trans, alpha, a, b, beta, c):
        return _syr2k_drv(alpha, _rank_op(trans, a), _rank_op(trans, b), beta,
                          _cast(dt, c), _uplo(uplo))

    def potri(l, uplo="L"):
        # lapack_api/lapack_potri.cc: inverse from the Cholesky factor
        return potri_array(_cast(dt, l), _uplo(uplo))

    def gesv_mixed(a, b):
        # lapack_api/lapack_gesv_mixed.cc (slate_dsgesv): f32 factor +
        # f64 iterative refinement; returns (x, iters, info) with dsgesv
        # semantics: iters = -1 flags the full-precision fallback and info
        # is that factorization's first-zero-pivot index (0 on success)
        x, iters, _converged, info = gesv_mixed_array(_cast(dt, a), _cast(dt, b))
        return x, int(iters), int(info)

    def posv_mixed(a, b, uplo="L"):
        x, iters, _converged, info = posv_mixed_array(_cast(dt, a), _cast(dt, b), _uplo(uplo))
        return x, int(iters), int(info)

    _NORMC = {"M": Norm.Max, "1": Norm.One, "O": Norm.One, "I": Norm.Inf,
              "F": Norm.Fro, "E": Norm.Fro}

    def lange(norm_char, a):
        # lapack_api/lapack_lange.cc
        return float(_genorm(_NORMC[norm_char.upper()], _cast(dt, a)))

    def lanhe(norm_char, uplo, a):
        # lapack_api/lapack_lanhe.cc (Hermitian, one stored triangle)
        return float(_henorm(_NORMC[norm_char.upper()], _cast(dt, a), _uplo(uplo)))

    lansy = lanhe  # lapack_lansy.cc: same abs-value structure

    def lantr(norm_char, uplo, diag, a):
        # lapack_api/lapack_lantr.cc
        return float(_trnorm(
            _NORMC[norm_char.upper()], _cast(dt, a), _uplo(uplo),
            Diag.Unit if diag.upper() == "U" else Diag.NonUnit,
        ))

    ns.update(
        gemm=gemm, gesv=gesv, getrf=getrf, getrs=getrs, getri=getri,
        posv=posv, potrf=potrf, potrs=potrs, geqrf=geqrf, gels=gels,
        gesvd=gesvd, gecon=gecon, trsm=trsm, trmm=trmm, hemm=hemm,
        symm=symm, herk=herk, syrk=syrk, her2k=her2k, syr2k=syr2k,
        potri=potri, gesv_mixed=gesv_mixed, posv_mixed=posv_mixed,
        lange=lange, lanhe=lanhe, lansy=lansy, lantr=lantr,
    )

    if prefix in ("s", "d"):
        def syev(a):
            w, z = heev_array(_cast(dt, a))
            return w, z

        def sysv(a, b):
            x, f, info = hesv_array(_cast(dt, a), _cast(dt, b))
            return x, f, int(info)

        ns.update(syev=syev, sysv=sysv)
    else:
        def heev(a):
            w, z = heev_array(_cast(dt, a))
            return w, z

        def hesv(a, b):
            x, f, info = hesv_array(_cast(dt, a), _cast(dt, b))
            return x, f, int(info)

        ns.update(heev=heev, hesv=hesv)
    return ns


def _uplo(u):
    return Uplo.Lower if u.upper() == "L" else Uplo.Upper


# generate slate_dgesv-style names (reference lapack_api naming)
for _p in "sdcz":
    for _name, _fn in _make(_p).items():
        globals()[f"slate_{_p}{_name}"] = _fn
        globals()[f"{_p}{_name}"] = _fn  # bare LAPACK names too

del _p, _name, _fn
