"""LAPACK-signature API: drop-in named routines over numpy/JAX arrays.

Analogue of the reference's ``lapack_api/`` (23 files: slate_dgetrf etc.,
LAPACK-style shims for single-process callers) and the spirit of
``scalapack_api/`` (drop-in pdgemm_): in the TPU ecosystem the "drop-in"
surface is numpy/scipy-style Python, so each routine takes/returns arrays
with LAPACK naming and semantics.  Precision prefixes: s/d (f32/f64),
c/z (c64/c128) — the d/z versions require jax x64 to be enabled.
"""

from __future__ import annotations


import jax.numpy as jnp

from .blas3.blas3 import gemm_array, trsm_array
from .linalg import (
    gels_array,
    geqrf_array,
    gesv_array,
    getrf_array,
    getri_array,
    getrs_array,
    heev_array,
    hesv_array,
    norm,
    posv_array,
    potrf_array,
    potrs_array,
    svd_array,
)
from .linalg.norms import gecondest, pocondest
from .types import Diag, Norm, Op, Side, Uplo

_DTYPES = {"s": jnp.float32, "d": jnp.float64, "c": jnp.complex64, "z": jnp.complex128}


def _cast(dtype, a):
    return jnp.asarray(a).astype(dtype)


def _make(prefix):
    dt = _DTYPES[prefix]

    ns = {}

    def gemm(transa, transb, m, n, k, alpha, a, b, beta, c):
        opa = {"N": lambda x: x, "T": lambda x: x.T, "C": lambda x: jnp.conj(x).T}[transa.upper()]
        opb = {"N": lambda x: x, "T": lambda x: x.T, "C": lambda x: jnp.conj(x).T}[transb.upper()]
        return gemm_array(alpha, opa(_cast(dt, a)), opb(_cast(dt, b)), beta, _cast(dt, c))

    def gesv(a, b):
        x, f = gesv_array(_cast(dt, a), _cast(dt, b))
        return x, f, int(f.info)

    def getrf(a):
        return getrf_array(_cast(dt, a))

    def getrs(f, b, trans="N"):
        op = {"N": Op.NoTrans, "T": Op.Trans, "C": Op.ConjTrans}[trans.upper()]
        return getrs_array(f, _cast(dt, b), op)

    def getri(f):
        return getri_array(f)

    def posv(a, b, uplo="L"):
        x, l, info = posv_array(_cast(dt, a), _cast(dt, b), _uplo(uplo))
        return x, l, int(info)

    def potrf(a, uplo="L"):
        l, info = potrf_array(_cast(dt, a), _uplo(uplo))
        return l, int(info)

    def potrs(l, b, uplo="L"):
        return potrs_array(_cast(dt, l), _cast(dt, b), _uplo(uplo))

    def geqrf(a):
        return geqrf_array(_cast(dt, a))

    def gels(a, b):
        return gels_array(_cast(dt, a), _cast(dt, b))

    def gesvd(a):
        return svd_array(_cast(dt, a))

    def gecon(norm_char, a, anorm=None):
        ad = _cast(dt, a)
        f = getrf_array(ad)
        nt = Norm.One if norm_char.upper() in ("1", "O") else Norm.Inf
        if anorm is None:
            anorm = float(norm(nt, ad))
        return float(gecondest(nt, f, anorm))

    def trsm(side, uplo, trans, diag, alpha, a, b):
        return trsm_array(
            Side.Left if side.upper() == "L" else Side.Right,
            _uplo(uplo),
            {"N": Op.NoTrans, "T": Op.Trans, "C": Op.ConjTrans}[trans.upper()],
            Diag.Unit if diag.upper() == "U" else Diag.NonUnit,
            alpha, _cast(dt, a), _cast(dt, b),
        )

    ns.update(
        gemm=gemm, gesv=gesv, getrf=getrf, getrs=getrs, getri=getri,
        posv=posv, potrf=potrf, potrs=potrs, geqrf=geqrf, gels=gels,
        gesvd=gesvd, gecon=gecon, trsm=trsm,
    )

    if prefix in ("s", "d"):
        def syev(a):
            w, z = heev_array(_cast(dt, a))
            return w, z

        def sysv(a, b):
            x, f, info = hesv_array(_cast(dt, a), _cast(dt, b))
            return x, f, int(info)

        ns.update(syev=syev, sysv=sysv)
    else:
        def heev(a):
            w, z = heev_array(_cast(dt, a))
            return w, z

        def hesv(a, b):
            x, f, info = hesv_array(_cast(dt, a), _cast(dt, b))
            return x, f, int(info)

        ns.update(heev=heev, hesv=hesv)
    return ns


def _uplo(u):
    return Uplo.Lower if u.upper() == "L" else Uplo.Upper


# generate slate_dgesv-style names (reference lapack_api naming)
for _p in "sdcz":
    for _name, _fn in _make(_p).items():
        globals()[f"slate_{_p}{_name}"] = _fn
        globals()[f"{_p}{_name}"] = _fn  # bare LAPACK names too

del _p, _name, _fn
