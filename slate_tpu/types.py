"""Core enums, options, and types.

TPU-native analogue of the reference's ``include/slate/enums.hh`` and
``include/slate/types.hh`` (reference: enums.hh:33-143, types.hh:32-64).
Enums that only exist to drive the reference's CPU/GPU runtime (MOSI states,
TileKind, queue indices) are intentionally absent: under XLA/SPMD there is no
coherency protocol and no stream scheduler to configure.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Union


class Uplo(enum.Enum):
    """Which triangle of a matrix is stored/referenced (enums.hh analog)."""

    Upper = "U"
    Lower = "L"
    General = "G"


class Op(enum.Enum):
    """Logical transposition applied to a matrix view (Tile.hh op_)."""

    NoTrans = "N"
    Trans = "T"
    ConjTrans = "C"


class Diag(enum.Enum):
    Unit = "U"
    NonUnit = "N"


class Side(enum.Enum):
    Left = "L"
    Right = "R"


class Norm(enum.Enum):
    """Matrix norms (lapack convention; reference enums.hh Norm)."""

    One = "1"
    Inf = "I"
    Max = "M"
    Fro = "F"


class NormScope(enum.Enum):
    """Whole-matrix norm vs per-row / per-column norms (enums.hh:120)."""

    Matrix = "M"
    Columns = "C"
    Rows = "R"


class Target(enum.Enum):
    """Execution target.

    The reference dispatches HostTask/HostNest/HostBatch/Devices
    (enums.hh:33).  Here the only compute substrate is XLA, so targets
    select *where XLA runs*, not a hand-written scheduler:

    - ``TPU``: jit on the default accelerator backend.
    - ``Host``: jit on the CPU backend (reference Host* targets collapse to
      one — XLA:CPU already does the task/nest/batch scheduling internally).
    """

    TPU = "tpu"
    Host = "host"


class GridOrder(enum.Enum):
    """Process-grid ordering for 2D block-cyclic distributions (enums.hh:130)."""

    Col = "C"
    Row = "R"


class Layout(enum.Enum):
    """Tile storage layout. XLA manages physical layout; kept for API parity."""

    ColMajor = "C"
    RowMajor = "R"


# ---------------------------------------------------------------------------
# Method selection (reference include/slate/method.hh:25-319)
# ---------------------------------------------------------------------------


class MethodGemm(enum.Enum):
    Auto = "auto"
    GemmA = "A"  # stationary-A
    GemmC = "C"  # stationary-C (SUMMA-like)


class MethodTrsm(enum.Enum):
    Auto = "auto"
    TrsmA = "A"
    TrsmB = "B"


class MethodHemm(enum.Enum):
    Auto = "auto"
    HemmA = "A"
    HemmC = "C"


class MethodLU(enum.Enum):
    PartialPiv = "PPLU"
    CALU = "CALU"  # tournament pivoting (getrf_tntpiv analog)
    NoPiv = "NoPiv"
    RBT = "RBT"  # random butterfly transform + no-pivot LU


class MethodGels(enum.Enum):
    QR = "QR"
    CholQR = "CholQR"


class MethodEig(enum.Enum):
    QR = "QR"  # steqr: tridiagonal QR iteration
    DC = "DC"  # stedc: divide and conquer


class MethodSVD(enum.Enum):
    QR = "QR"  # bdsqr
    DC = "DC"


class Precision(enum.Enum):
    """Accumulation-precision tier for BLAS-3 (Option.Precision).

    The reference always runs vendor-native full-precision BLAS
    (internal_gemm.cc:634); on TPU the MXU offers a speed/accuracy ladder,
    so the tier is a first-class option.  Measured on v5e, n=1024 N(0,1)
    operands, max relative error vs f64:

    - ``Fast``: native MXU rate — single-pass bf16 for f32 data (~2^-8,
      78-103 TF/s), 6-slice Ozaki for f64 (~2^-33, 1.5x Highest's rate).
    - ``High``: 3-pass bf16x3 for f32 (~2^-16, ~43 TF/s); f64 unchanged
      (full Ozaki — there is no meaningful middle tier on the int8 path).
    - ``Highest``: full precision for the dtype — 6-pass bf16x9 for f32
      (~2^-22.5, ~25 TF/s), 9-slice int8 Ozaki for f64 (true f64, ~3e-15).
    - ``Emulated``: opt out of the int8 Ozaki f64 path entirely and use
      XLA's f32-pair f64 emulation (~1.3 TF/s; debugging escape hatch).

    Every driver defaults to Highest — matching the reference's
    always-full-precision vendor BLAS — and the reduced tiers are
    explicit opt-ins via Option.Precision.
    """

    Fast = "fast"
    High = "high"
    Highest = "highest"
    Emulated = "emulated"


def select_gemm_method(m: int, n: int, k: int) -> MethodGemm:
    """Heuristic from method.hh:35-45: tiny output panel -> stationary-A."""
    if n <= max(m, k) // 4:
        return MethodGemm.GemmA
    return MethodGemm.GemmC


def select_trsm_method(side: Side, m: int, n: int) -> MethodTrsm:
    """method.hh:88-99: solve-side-dominant shapes favour TrsmA."""
    if (side == Side.Left and n <= m // 4) or (side == Side.Right and m <= n // 4):
        return MethodTrsm.TrsmA
    return MethodTrsm.TrsmB


def select_hemm_method(m: int, n: int) -> MethodHemm:
    """Shape heuristic in the SPIRIT of method.hh MethodHemm::select_algo
    (a thin B/C panel next to a big Hermitian A favours the stationary-A
    schedule, hemmA.cc) but NOT its exact rule: the reference switches on
    ``n < 2 * nb`` (panel thinner than two tiles); here the threshold is
    the TPU-tuned aspect ratio n <= m / 4, where hemmA's |B|-replication
    + p|C|-reduction ICI volume undercuts the k-loop's row-panel gathers
    on the meshes we measure.  Callers pinning the reference's exact
    dispatch should pass Option.MethodHemm explicitly."""
    if n <= m // 4:
        return MethodHemm.HemmA
    return MethodHemm.HemmC


# ---------------------------------------------------------------------------
# Options (reference types.hh:60 Options = map<Option, OptionValue>)
# ---------------------------------------------------------------------------


class Option(enum.Enum):
    ChunkSize = "chunk_size"
    Lookahead = "lookahead"
    BlockSize = "block_size"  # nb (reference Option::TileSize analog)
    InnerBlocking = "inner_blocking"  # ib
    # Reference: threads cooperating on one LU panel (internal_getrf.cc),
    # a parallelism-only knob there.  TPU analogue: the CALU tournament
    # panel is ib * MaxPanelThreads columns wide, trading per-step latency
    # against update size as panel threads do — but with a NUMERICAL side
    # effect the reference doesn't have: the tournament width changes
    # which pivots win, so pivot quality varies with this option
    # (linalg/lu.py getrf, MethodLU.CALU, has the full note).
    MaxPanelThreads = "max_panel_threads"
    Tolerance = "tolerance"
    Target = "target"
    MaxIterations = "max_iterations"
    UseFallbackSolver = "use_fallback_solver"
    PivotThreshold = "pivot_threshold"
    MethodCholQR = "method_cholqr"
    MethodEig = "method_eig"
    MethodGels = "method_gels"
    MethodGemm = "method_gemm"
    MethodHemm = "method_hemm"
    MethodLU = "method_lu"
    MethodTrsm = "method_trsm"
    MethodSVD = "method_svd"
    PrintVerbose = "print_verbose"
    PrintPrecision = "print_precision"
    Depth = "depth"  # RBT butterfly depth
    Precision = "precision"  # BLAS-3 accumulation tier (Precision enum)
    # ABFT policy for the distributed kernels (ft.FtPolicy: off | detect |
    # correct | recompute).  Off (the default) routes to the plain kernels
    # untouched; any other value runs the checksum-carrying variants in
    # slate_tpu/ft/abft.py.  No reference analogue: SLATE delegates
    # resilience to the MPI/ULFM layer, while under XLA/SPMD the natural
    # unit of protection is the tile algebra itself.
    FaultTolerance = "fault_tolerance"
    # Broadcast lowering for the mesh k-loops' tileBcast verbs
    # (parallel/comm.py engine): "psum" (legacy masked all-reduce, ~2x the
    # bytes a broadcast needs), "ring" (pipelined collective_permute ring,
    # (s-1)/s * B per link), "doubling" (log2(s)-hop recursive doubling on
    # power-of-two axes), or "auto" (the default: doubling on power-of-two
    # axes, ring otherwise).  All lowerings are bitwise-identical in
    # results; they differ only in wire bytes and hop latency.  Resolution
    # order: explicit option > comm.use_bcast_impl context >
    # SLATE_TPU_BCAST_IMPL environment > auto.
    BcastImpl = "bcast_impl"
    # Panel-factorization lowering for the fused Pallas panel kernels
    # (ops/pallas_ops.py): "xla" (the reference semantics — today's
    # cholesky/triangular_solve/Householder dispatch chains, bitwise),
    # "pallas" (one fused on-chip kernel per panel phase: MAGMA-style
    # blocked panels; f64/complex panels fall back to xla on a real TPU,
    # and on CPU the kernels run under the Pallas interpreter), or
    # "auto" (the default: pallas on a real TPU backend for MXU dtypes,
    # xla elsewhere — CPU tier-1 stays bitwise today's results).
    # Resolution order: explicit option > pallas_ops.use_panel_impl
    # context > SLATE_TPU_PANEL_IMPL environment > auto (the
    # Option.BcastImpl pattern).  The pallas forms match the XLA
    # references to the documented O(eps cond) explicit-inverse class
    # (QR panels are bitwise); parity is gated by
    # tests/test_pallas_panels.py under interpret mode.
    PanelImpl = "panel_impl"
    # Trailing-update lowering for the mesh k-loops' bulk phase
    # (ops/pallas_ops.py, ISSUE 20): "xla" (the reference semantics —
    # today's einsum bulk chains, jaxpr-IDENTICAL by construction),
    # "pallas" (one fused grid dispatch over the local trailing tile
    # stack per k-step — summa_update_pallas / chol_trailing_update_pallas
    # / lu_trailing_update_pallas, with the broadcast panels riding VMEM
    # blocks; bitwise vs the xla bulk under interpret mode), or "auto"
    # (the default: pallas on a real TPU backend for MXU dtypes, xla
    # elsewhere).  Fusion changes compute scheduling, never comm — the
    # broadcast schedule and comm-audit wire bytes are invariant across
    # lowerings (asserted).  Resolution order: explicit option >
    # pallas_ops.use_update_impl context > SLATE_TPU_UPDATE_IMPL
    # environment > auto (the Option.PanelImpl pattern).  Scope: the
    # summa / potrf / LU-nopiv bulk phases; the pivoted/band LU kernels
    # pin xla (their trailing sweeps interleave with pivot application).
    UpdateImpl = "update_impl"
    # Mixed-precision routing for the distributed f64 solves
    # (parallel/dist_refine.py): "off" (factor at the data dtype — trace-
    # identical to the direct gesv_mesh/posv_mesh path), "ir" (f32 mesh
    # factor + fused on-device f64 iterative refinement, then the full-f64
    # fallback on non-convergence), "gmres" (f32 factor preconditioning
    # distributed restarted GMRES, then fallback), or "auto" (the default:
    # the escalation ladder IR -> GMRES-IR -> full-f64 fallback for real
    # f64 inputs — the reference's gesv_mixed/posv_mixed stance made the
    # DEFAULT because on TPU the f32:f64 factor gap is ~40x, not ~2x).
    # Resolution order: explicit option > dist_refine.use_mixed context >
    # SLATE_TPU_MIXED environment > auto.
    MixedPrecision = "mixed_precision"
    # Numerical-health monitoring for the mesh factorization k-loops and
    # the mixed-precision refinement loop (obs/numerics.py): "off" (the
    # plain kernels, jaxpr-IDENTICAL — the PanelImpl/MixedPrecision
    # pattern), "on" (the loop carry accumulates running element-growth /
    # diagonal-margin gauges and the refinement while_loop keeps a
    # fixed-size (||r||, ||x||) history buffer — zero extra collectives:
    # the gauges ride the carry and reduce once at loop exit through the
    # same unaudited pmax the info computation already uses, so comm-audit
    # wire bytes are unchanged), or "auto" (the default: on when the obs
    # layer is enabled — SLATE_TPU_OBS=1 / obs.enable() — off otherwise).
    # Resolution order: explicit option > numerics.use_num_monitor
    # context > SLATE_TPU_NUM environment > auto.  When monitoring is on,
    # Option.MixedPrecision=auto additionally consults the measured
    # f32-factor growth and a Hager-Higham condition estimate to pick its
    # ladder entry tier (pathological inputs skip straight to GMRES-IR).
    NumMonitor = "num_monitor"
    # Tuned-schedule-table consultation for the serving request path
    # (serve/table.py): "on" (unset schedule options — BcastImpl,
    # Lookahead, BlockSize, MethodGemm — resolve through the committed
    # autotuned table, artifacts/serve/tuned.json, BEFORE falling back
    # to auto; the resolution chain becomes explicit > context > env >
    # tuned > auto) or "off" (the pre-serve chain, tuned tier skipped).
    # Resolution order for the switch itself: explicit option >
    # SLATE_TPU_AUTOTUNE environment > on (serving exists to consume its
    # own measurements).  Only the serve dispatch path consults this —
    # direct driver calls never read the table.
    AutoTune = "auto_tune"
    # Checkpoint interval for the mesh factorization k-loops (ft/ckpt.py):
    # an int K snapshots the k-loop carry to host every K steps, so a
    # preempted multi-minute factorization resumes from the last
    # snapshot instead of restarting from zero.  Covered loops: potrf /
    # LU-nopiv / partial-pivot LU (single tile-stack carry + NumMonitor
    # gauges + pivot permutation; resume bitwise on the SAME mesh or a
    # RESHAPED p' x q' mesh via block-cyclic redistribution,
    # ft/elastic.py) and — ISSUE 13 — the distributed CAQR (geqrf) and
    # two-stage eig stage-1 reduction (he2hb), whose MULTI-ARRAY carries
    # (tile stack + T-factor / reflector / tree stacks) resume bitwise
    # on the same (p, q) grid shape only: the auxiliary arrays are
    # grid-locked and a reshaped resume is refused with a structured
    # error.  Snapshots are sync by default; SLATE_TPU_CKPT_ASYNC=1 (or
    # the drivers' async_snapshots=True) overlaps the device->host carry
    # copy with the next segment's dispatch, bitwise-equal either way.
    # Off / absent / 0 (the default) routes to the plain fused kernels
    # untouched: trace-identical, zero overhead.  Resolution order:
    # explicit option > SLATE_TPU_CKPT environment > off.  No reference
    # analogue: SLATE delegates preemption survival to the MPI
    # checkpoint layer; under XLA/SPMD the natural snapshot unit is the
    # k-loop carry itself.
    Checkpoint = "checkpoint"
    # Residual lowering for the mixed-precision refinement loop: "f64"
    # (plain SUMMA at the data dtype — XLA's emulated-f64 pairs on TPU),
    # "ozaki" (the int8 split-integer SUMMA: digit planes of A and X ride
    # the unchanged broadcast schedule at slice_count/8 x the f64 panel
    # bytes and the MXU integer rate), or "auto" (ozaki on a real TPU
    # backend, f64 elsewhere).  Both are f64-grade accurate; ozaki is
    # bitwise-reproducible across mesh shapes (fixed split + summation
    # order).  Resolution order: explicit option >
    # SLATE_TPU_RESIDUAL_IMPL environment > auto.
    ResidualImpl = "residual_impl"


Options = Mapping[Union[Option, str], Any]

_DEFAULTS = {
    Option.Lookahead: 1,
    Option.BlockSize: 256,
    Option.InnerBlocking: 32,
    Option.Tolerance: None,
    Option.Target: Target.TPU,
    Option.MaxIterations: 30,
    Option.UseFallbackSolver: True,
    Option.PivotThreshold: 1.0,
    Option.Depth: 2,
}


def get_option(opts: Optional[Options], key: Option, default: Any = None) -> Any:
    """Typed option lookup (types.hh get_option analog)."""
    if opts:
        if key in opts:
            return opts[key]
        if key.value in opts:
            return opts[key.value]
    if default is not None:
        return default
    return _DEFAULTS.get(key)


@dataclass(frozen=True)
class Pivot:
    """One pivot entry: which tile row / element within it (types.hh:64)."""

    tile_index: int
    element_offset: int


class SlateError(Exception):
    """slate::Exception analog (include/slate/Exception.hh)."""


def slate_assert(cond: bool, msg: str) -> None:
    if not cond:
        raise SlateError(msg)
