#!/usr/bin/env python
"""Parameter-sweep tester: the testsweeper/`test/tester` analogue.

Usage (mirrors `test/tester <routine> --dim ... --type ...`, SURVEY §4):

    python tester.py gemm --dim 256:1024:256 --type s,d
    python tester.py potrf --dim 1024 --type d --check y
    python tester.py heev svd --dim 200 --type d
    python tester.py --help

Per combination prints: routine, type, dims, error, status, time, gflops —
the reference tester's output row (docs/usage.md:36-44).  Gflop formulas
follow blas::Gflop (gemm 2mnk; potrf n^3/3; getrf 2n^3/3; geqrf 4mn^2-4n^3/3;
heev ~4n^3/3; svd ~8n^3/3).  Residual gates follow test/*.cc (3-eps style).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

_DTYPES = {"s": np.float32, "d": np.float64, "c": np.complex64, "z": np.complex128}


def _parse_dims(spec: str):
    for part in spec.split(","):
        if ":" in part:
            bits = [int(x) for x in part.split(":")]
            start, stop = bits[0], bits[1]
            step = bits[2] if len(bits) > 2 else start
            yield from range(start, stop + 1, step)
        else:
            yield int(part)


def _eps(dtype):
    return np.finfo(np.float32 if dtype in (np.float32, np.complex64) else np.float64).eps


def _rand(rng, m, n, dtype):
    a = rng.standard_normal((m, n))
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((m, n))
    return a.astype(dtype)


def _sync(out):
    """Force REAL execution: the axon tunnel defers programs and
    block_until_ready does not block through it — only a host transfer
    proves the work ran (one element is enough)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "ndim"):
            jax.device_get(leaf[(0,) * leaf.ndim])
            break
    return out


def _time(fn, *args, label: str = ""):
    from slate_tpu.utils.trace import Trace

    _sync(fn(*args))  # warm/compile (and drain the dispatch queue)
    t0 = time.perf_counter()
    out = _sync(fn(*args))
    t1 = time.perf_counter()
    if Trace.enabled():
        Trace.add(label or getattr(fn, "__name__", "op"), 0, t0, t1)
    return out, t1 - t0


def _ref_solve(routine, a, extra=None):
    """--ref mode: run the same problem through scipy/LAPACK and compare
    (the reference tester's ScaLAPACK `ref` comparison, test_gemm.cc:310,
    with scipy as the single-process reference library)."""
    import scipy.linalg as sla

    if routine == "gesv":
        return sla.solve(a, extra)
    if routine == "heev":
        return np.linalg.eigvalsh(a)
    if routine == "svd":
        return np.linalg.svd(a, compute_uv=False)
    return None


def _make_mesh_from_grid(grid: str):
    import jax

    from slate_tpu.parallel.mesh import make_mesh

    p, q = (int(x) for x in grid.lower().split("x"))
    devs = jax.devices()
    if len(devs) < p * q:
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            pass
    if len(devs) < p * q:
        raise SystemExit(
            f"--grid {grid} needs {p * q} devices but only {len(devs)} are "
            f"visible; for a virtual mesh set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={p * q} "
            f"JAX_PLATFORMS=cpu"
        )
    return make_mesh(p, q, devices=devs[: p * q])


def run_gemm_mesh(n, dtype, rng, check, grid):
    import jax.numpy as jnp

    from slate_tpu.parallel import gemm_mesh

    mesh = _make_mesh_from_grid(grid)
    a, b = _rand(rng, n, n, dtype), _rand(rng, n, n, dtype)
    nb = max(8, min(64, n // max(*_make_grid_dims(grid))))
    c, t = _time(lambda x, y: gemm_mesh(1.0, x, y, mesh, nb=nb),
                 jnp.asarray(a), jnp.asarray(b))
    err = 0.0
    if check:
        ref = a @ b
        err = np.abs(np.asarray(c) - ref).max() / (np.abs(ref).max() + 1e-30)
    return err, t, 2 * n**3 / t / 1e9, err < 100 * n * _eps(dtype)


def _make_grid_dims(grid):
    return tuple(int(x) for x in grid.lower().split("x"))


def run_posv_mesh(n, dtype, rng, check, grid):
    import jax.numpy as jnp

    from slate_tpu.parallel import posv_mesh

    mesh = _make_mesh_from_grid(grid)
    g = _rand(rng, n, n, dtype)
    a = g @ g.conj().T + n * np.eye(n, dtype=dtype)
    b = _rand(rng, n, 2, dtype)
    (x, info), t = _time(lambda aa, bb: posv_mesh(aa, bb, mesh, nb=16),
                         jnp.asarray(a), jnp.asarray(b))
    err = np.abs(a @ np.asarray(x) - b).max() / np.abs(b).max() if check else 0.0
    return err, t, n**3 / 3 / t / 1e9, int(info) == 0 and err < 100 * n * _eps(dtype)


def run_gesv_mesh(n, dtype, rng, check, grid):
    import jax.numpy as jnp

    from slate_tpu.parallel import gesv_tntpiv_mesh

    mesh = _make_mesh_from_grid(grid)
    a = _rand(rng, n, n, dtype)
    b = _rand(rng, n, 2, dtype)
    (x, info), t = _time(lambda aa, bb: gesv_tntpiv_mesh(aa, bb, mesh, nb=16),
                         jnp.asarray(a), jnp.asarray(b))
    x = np.asarray(x)
    err = (np.abs(a @ x - b).max() / (np.abs(a).max() * max(1, np.abs(x).max()) * n)
           if check else 0.0)
    return err, t, 2 * n**3 / 3 / t / 1e9, int(info) == 0 and err < 100 * _eps(dtype)


def run_gemm(n, dtype, rng, check, precision=None):
    """Times the gemm driver at its default tier (Highest for every dtype,
    matching the reference's full-precision vendor BLAS), or at an explicit
    --precision tier.  The --check gate uses a tier-aware tolerance: Fast
    is single-pass bf16 (~2^-8 relative on N(0,1) data), High is bf16x3
    (~2^-16), Highest is ~f32 (3-eps style)."""
    import jax.numpy as jnp
    from slate_tpu.blas3.blas3 import _mul_prec
    from slate_tpu.ops.matmul import matmul
    from slate_tpu.types import Precision

    a, b = _rand(rng, n, n, dtype), _rand(rng, n, n, dtype)
    aj, bj = jnp.asarray(a), jnp.asarray(b)
    prec = precision or _mul_prec(None)
    c, t = _time(lambda x, y: matmul(x, y, precision=prec), aj, bj)
    gflops = 2 * n**3 / t / 1e9
    err = 0.0
    if check:
        x = _rand(rng, n, 1, dtype)
        lhs = np.asarray(c) @ x
        rhs = a @ (b @ x)
        err = np.abs(lhs - rhs).max() / (np.abs(rhs).max() + 1e-30)
    # documented tier tolerances (measured v5e, types.Precision docstring):
    # input-rounding dominated for Fast/High, 3-eps style for Highest
    tier_eps = {Precision.Fast: 2.0**-8, Precision.High: 2.0**-16}
    if dtype in (np.float64, np.complex128):  # Ozaki dispatch dtypes only
        tier_eps[Precision.Fast] = 2.0**-33  # 6-slice Ozaki
        tier_eps[Precision.High] = 0.0
    tol = max(100 * n * _eps(dtype), 16 * tier_eps.get(prec, 0.0))
    return err, t, gflops, err < tol


def run_potrf(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import potrf_array

    g = _rand(rng, n, n, dtype)
    a = g @ g.conj().T + n * np.eye(n, dtype=dtype)
    (l, info), t = _time(potrf_array, jnp.asarray(a))
    gflops = n**3 / 3 / t / 1e9
    ld = np.tril(np.asarray(l))
    err = np.linalg.norm(ld @ ld.conj().T - a) / np.linalg.norm(a) if check else 0.0
    return err, t, gflops, int(info) == 0 and err < 30 * n * _eps(dtype)


def run_getrf(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import getrf_array

    a = _rand(rng, n, n, dtype)
    f, t = _time(getrf_array, jnp.asarray(a))
    gflops = 2 * n**3 / 3 / t / 1e9
    err = 0.0
    if check:
        lu, perm = np.asarray(f.lu), np.asarray(f.perm)
        l = np.tril(lu, -1) + np.eye(n, dtype=dtype)
        u = np.triu(lu)
        err = np.linalg.norm(l @ u - a[perm]) / np.linalg.norm(a)
    return err, t, gflops, err < 30 * n * _eps(dtype)


def run_gesv(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import gesv_array

    a = _rand(rng, n, n, dtype)
    b = _rand(rng, n, 8, dtype)
    (x, f), t = _time(lambda aa, bb: gesv_array(aa, bb), jnp.asarray(a), jnp.asarray(b))
    gflops = (2 * n**3 / 3 + 2 * n**2 * 8) / t / 1e9
    err = np.abs(a @ np.asarray(x) - b).max() / (np.abs(b).max() * np.abs(a).sum(1).max()) if check else 0.0
    return err, t, gflops, err < 30 * n * _eps(dtype)


def run_geqrf(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import geqrf_array
    from slate_tpu.linalg.qr import geqrf_q, geqrf_r

    m = n
    a = _rand(rng, m, n, dtype)
    f, t = _time(geqrf_array, jnp.asarray(a))
    gflops = (4 * m * n**2 - 4 * n**3 / 3) / t / 1e9
    err = 0.0
    if check:
        q = np.asarray(geqrf_q(f))
        r = np.asarray(geqrf_r(f))
        err = np.linalg.norm(q @ r - a) / np.linalg.norm(a)
    return err, t, gflops, err < 30 * n * _eps(dtype)


def run_gels(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import gels_array

    m = 2 * n
    a = _rand(rng, m, n, dtype)
    b = _rand(rng, m, 4, dtype)
    x, t = _time(gels_array, jnp.asarray(a), jnp.asarray(b))
    gflops = (2 * m * n**2) / t / 1e9
    err = 0.0
    if check:  # normal-equations residual: A^H (A x - b) ~ 0
        r = a @ np.asarray(x) - b
        err = np.abs(a.conj().T @ r).max() / (np.abs(a).max() ** 2 * np.abs(x).max() * m)
    return err, t, gflops, err < 100 * n * _eps(dtype)


def run_heev(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import heev_array

    a = _rand(rng, n, n, dtype)
    a = (a + a.conj().T) / 2
    (w, z), t = _time(lambda x: heev_array(x, nb=32), jnp.asarray(a))
    gflops = 4 * n**3 / 3 / t / 1e9
    err = 0.0
    if check:
        w, z = np.asarray(w), np.asarray(z)
        err = np.abs(a @ z - z * w).max() / (np.abs(w).max() + 1e-30) / n
    return err, t, gflops, err < 100 * _eps(dtype)


def run_svd(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import svd_array

    a = _rand(rng, n, n, dtype)
    (u, s, vh), t = _time(lambda x: svd_array(x, nb=32), jnp.asarray(a))
    gflops = 8 * n**3 / 3 / t / 1e9
    err = 0.0
    if check:
        u, s, vh = np.asarray(u), np.asarray(s), np.asarray(vh)
        err = np.abs(a - (u * s) @ vh).max() / (s[0] + 1e-30) / n
    return err, t, gflops, err < 100 * _eps(dtype)


def run_trsm(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.blas3.blas3 import trsm_array
    from slate_tpu.types import Diag, Op, Side, Uplo

    t_mat = np.tril(_rand(rng, n, n, dtype)) + n * np.eye(n, dtype=dtype)
    b = _rand(rng, n, n, dtype)
    x, t = _time(
        lambda a_, b_: trsm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, 1.0, a_, b_),
        jnp.asarray(t_mat), jnp.asarray(b),
    )
    gflops = n**3 / t / 1e9
    err = np.abs(t_mat @ np.asarray(x) - b).max() / (np.abs(b).max() * n) if check else 0.0
    return err, t, gflops, err < 30 * _eps(dtype)


ROUTINES = {
    "gemm": run_gemm,
    "potrf": run_potrf,
    "getrf": run_getrf,
    "gesv": run_gesv,
    "geqrf": run_geqrf,
    "gels": run_gels,
    "heev": run_heev,
    "svd": run_svd,
    "trsm": run_trsm,
}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("routines", nargs="+", choices=sorted(ROUTINES), help="routines to sweep")
    ap.add_argument("--dim", default="256", help="sizes: N | start:stop[:step] | comma list")
    ap.add_argument("--type", default="d", help="precisions from s,d,c,z")
    ap.add_argument("--check", default="y", choices=["y", "n"])
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--grid", default="",
                    help="PxQ mesh grid: run the distributed variants "
                         "(gemm/posv/gesv) over a device mesh")
    ap.add_argument("--precision", default="",
                    choices=["", "fast", "high", "highest", "emulated"],
                    help="BLAS-3 accumulation tier for gemm (types.Precision); "
                         "empty = driver default (fast for s, highest for d/z)")
    ap.add_argument("--ref", default="n", choices=["y", "n"],
                    help="also run scipy/LAPACK and report the comparison "
                         "(reference tester's ScaLAPACK ref mode)")
    ap.add_argument("--trace", default="",
                    help="write a timeline of the sweep via "
                         "slate_tpu.utils.trace to this path (SVG, or "
                         "Chrome-trace/Perfetto JSON for a .json path)")
    ap.add_argument("--report", default="",
                    help="write a slate_tpu.obs RunReport JSON of the sweep "
                         "(also enables observability: driver spans + comm "
                         "bytes ride along)")
    ap.add_argument("--flight", default="",
                    help="also write a step-level FlightReport JSON "
                         "(slate_tpu.obs.flight) for the first requested "
                         "routine that has a flight driver (gemm / potrf / "
                         "getrf / trsm); needs the 8-device CPU mesh")
    ap.add_argument("--mem", default="",
                    help="also write a mem.* RunReport JSON "
                         "(slate_tpu.obs.memwatch: AOT memory analysis + "
                         "MemoryModel + donation aliasing) for the first "
                         "requested routine with a mem driver (gemm / "
                         "potrf / getrf); needs the 8-device CPU mesh")
    ap.add_argument("--num", default="",
                    help="also write a num.* RunReport JSON "
                         "(slate_tpu.obs.numwatch: monitored growth/margin "
                         "gauges + distributed condest + mixed-ladder "
                         "health routing on seeded inputs) for the first "
                         "requested routine with a num driver (getrf / "
                         "gesv -> lu, potrf / posv -> potrf, else mixed); "
                         "needs the 8-device CPU mesh")
    args = ap.parse_args(argv)

    import jax

    if any(p in args.type for p in "dz"):
        jax.config.update("jax_enable_x64", True)

    rng = np.random.default_rng(args.seed)
    check = args.check == "y"
    tracer = None
    if args.trace:
        from slate_tpu.utils.trace import Trace

        Trace.on()
        tracer = Trace
    if args.report:
        from slate_tpu import obs

        obs.enable()
    report_values = {}
    hdr = (f"{'routine':<10} {'type':<4} {'n':>7} {'error':>10} {'status':>6} "
           f"{'time(s)':>9} {'gflops':>10}")
    print(hdr + ("  ref_diff" if args.ref == "y" else ""))
    failures = 0
    for routine in args.routines:
        for prefix in args.type.split(","):
            for n in _parse_dims(args.dim):
                dtype = _DTYPES[prefix]
                if args.grid and routine in MESH_ROUTINES:
                    if args.precision:
                        print(f"note: --precision {args.precision} ignored for "
                              f"mesh routine {routine}@{args.grid} (mesh kernels "
                              f"run their documented fixed tiers)", file=sys.stderr)
                    if args.trace:
                        # collective-volume audit rides the trace flag
                        # (VERDICT r4 item 7; full table: tools/comm_audit.py)
                        import jax as _jax

                        from slate_tpu.parallel.comm import comm_audit

                        _jax.clear_caches()
                        with comm_audit() as _comm_recs:
                            err, t, gflops, ok = MESH_ROUTINES[routine](
                                n, dtype, rng, check, args.grid)
                        payload = sum(b * m for _, b, m in _comm_recs)
                        execs = sum(m for _, _, m in _comm_recs)
                        print(f"  comm: {payload:,} payload B/dev over "
                              f"{execs:,} collective execs", file=sys.stderr)
                    else:
                        err, t, gflops, ok = MESH_ROUTINES[routine](
                            n, dtype, rng, check, args.grid)
                    rname = routine + "@" + args.grid
                elif routine == "gemm" and args.precision:
                    from slate_tpu.types import Precision

                    err, t, gflops, ok = run_gemm(
                        n, dtype, rng, check, Precision(args.precision))
                    rname = routine + ":" + args.precision
                else:
                    err, t, gflops, ok = ROUTINES[routine](n, dtype, rng, check)
                    rname = routine
                refcol = ""
                if args.ref == "y":
                    import scipy  # noqa: F401  (fail loudly if missing)

                    refcol = "  " + _ref_compare(routine, n, dtype, args.seed)
                status = "pass" if ok else "FAILED"
                failures += 0 if ok else 1
                key = f"{rname.replace('@', '_').replace(':', '_')}_{prefix}_n{n}"
                report_values[f"{key}_gflops"] = round(gflops, 2)
                report_values[f"{key}_seconds"] = round(t, 6)
                print(f"{rname:<10} {prefix:<4} {n:>7} {err:>10.2e} {status:>6} "
                      f"{t:>9.4f} {gflops:>10.1f}{refcol}")
    if tracer is not None:
        out = tracer.finish(args.trace)
        tracer.off()
        print(f"trace written to {out}")
    if args.report:
        import os

        from slate_tpu.obs.report import write_report

        d = os.path.dirname(os.path.abspath(args.report))
        os.makedirs(d, exist_ok=True)
        write_report(
            args.report, name="tester",
            config={"routines": ",".join(args.routines), "dim": args.dim,
                    "type": args.type, "grid": args.grid or "single"},
            values=report_values,
        )
        print(f"report written to {args.report}")
    if args.flight:
        from slate_tpu.obs import flight as _flight

        fl_ops = {"gemm": "summa", "potrf": "potrf",
                  "getrf": "getrf_nopiv", "trsm": "trsm"}
        op = next((fl_ops[r] for r in args.routines if r in fl_ops), None)
        if op is None:
            print(f"flight: none of {args.routines} has a flight driver "
                  f"({sorted(fl_ops)})")
        else:
            try:
                n_fl = max(_parse_dims(args.dim))
                rep = _flight.run_flight(op, n=n_fl, nb=max(8, n_fl // 12))
                _flight.write_flight_report(args.flight, rep)
                print(f"flight report written to {args.flight} (overlap_eff "
                      f"{rep['sched']['overlap_eff']:.3f})")
            except Exception as e:
                # obs must never flip a passed sweep's exit code (e.g.
                # <8 CPU devices without the forced-device XLA_FLAGS)
                print(f"flight report failed: {e!r}")
    if args.mem:
        from slate_tpu.obs import memwatch as _memwatch

        mem_ops = {"gemm": "summa", "potrf": "potrf",
                   "getrf": "getrf_nopiv"}
        op = next((mem_ops[r] for r in args.routines if r in mem_ops), None)
        if op is None:
            print(f"mem: none of {args.routines} has a mem driver "
                  f"({sorted(mem_ops)})")
        else:
            try:
                n_m = max(_parse_dims(args.dim))
                rep = _memwatch.run_memwatch(op, n=n_m,
                                             nb=max(8, n_m // 12))
                _memwatch.write_mem_report(args.mem, rep)
                v = rep["values"]
                print(f"mem report written to {args.mem} (temp "
                      f"{v['mem.temp_bytes']:,.0f} B/dev, model err "
                      f"{v['mem.model_err_frac']:.1%})")
            except Exception as e:
                # obs must never flip a passed sweep's exit code
                print(f"mem report failed: {e!r}")
    if args.num:
        from slate_tpu.obs import numwatch as _numwatch

        num_ops = {"getrf": "lu", "gesv": "lu", "potrf": "potrf",
                   "posv": "potrf"}
        op = next((num_ops[r] for r in args.routines if r in num_ops),
                  "mixed")
        try:
            rep = _numwatch.run_numwatch(op)
            _numwatch.write_num_report(args.num, rep)
            keys = [k for k in sorted(rep["values"]) if "_runtime_" not in k]
            print(f"num report written to {args.num} ("
                  + ", ".join(f"{k.split('num.', 1)[1]}="
                              f"{rep['values'][k]:.3g}" for k in keys[:3])
                  + ")")
        except Exception as e:
            # obs must never flip a passed sweep's exit code
            print(f"num report failed: {e!r}")
    return 1 if failures else 0


def _ref_compare(routine, n, dtype, seed) -> str:
    """Re-run the same seeded problem through scipy and diff the results
    (seeded identically so 'random matrices are the same regardless of
    distribution', CHANGELOG.md:25-26)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed + n)
    if routine == "gesv":
        from slate_tpu.linalg import gesv_array

        a = _rand(rng, n, n, dtype)
        b = _rand(rng, n, 2, dtype)
        x, _ = gesv_array(jnp.asarray(a), jnp.asarray(b))
        ref = _ref_solve("gesv", a, b)
        return f"|x-ref|={np.abs(np.asarray(x) - ref).max():.2e}"
    if routine == "heev":
        from slate_tpu.linalg import heev_array

        g = _rand(rng, n, n, dtype)
        a = (g + g.conj().T) / 2
        w = heev_array(jnp.asarray(a), want_vectors=False)
        ref = _ref_solve("heev", a)
        return f"|w-ref|={np.abs(np.asarray(w) - ref).max():.2e}"
    if routine == "svd":
        from slate_tpu.linalg import svd_array

        a = _rand(rng, n, n, dtype)
        sv = svd_array(jnp.asarray(a), want_vectors=False)
        ref = _ref_solve("svd", a)
        return f"|s-ref|={np.abs(np.sort(np.asarray(sv))[::-1] - ref).max():.2e}"
    return "(no ref)"


MESH_ROUTINES = {
    "gemm": run_gemm_mesh,
    "potrf": run_posv_mesh,
    "gesv": run_gesv_mesh,
}


if __name__ == "__main__":
    sys.exit(main())
