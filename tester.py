#!/usr/bin/env python
"""Parameter-sweep tester: the testsweeper/`test/tester` analogue.

Usage (mirrors `test/tester <routine> --dim ... --type ...`, SURVEY §4):

    python tester.py gemm --dim 256:1024:256 --type s,d
    python tester.py potrf --dim 1024 --type d --check y
    python tester.py heev svd --dim 200 --type d
    python tester.py --help

Per combination prints: routine, type, dims, error, status, time, gflops —
the reference tester's output row (docs/usage.md:36-44).  Gflop formulas
follow blas::Gflop (gemm 2mnk; potrf n^3/3; getrf 2n^3/3; geqrf 4mn^2-4n^3/3;
heev ~4n^3/3; svd ~8n^3/3).  Residual gates follow test/*.cc (3-eps style).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

_DTYPES = {"s": np.float32, "d": np.float64, "c": np.complex64, "z": np.complex128}


def _parse_dims(spec: str):
    for part in spec.split(","):
        if ":" in part:
            bits = [int(x) for x in part.split(":")]
            start, stop = bits[0], bits[1]
            step = bits[2] if len(bits) > 2 else start
            yield from range(start, stop + 1, step)
        else:
            yield int(part)


def _eps(dtype):
    return np.finfo(np.float32 if dtype in (np.float32, np.complex64) else np.float64).eps


def _rand(rng, m, n, dtype):
    a = rng.standard_normal((m, n))
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((m, n))
    return a.astype(dtype)


def _time(fn, *args):
    import jax

    out = fn(*args)  # warm/compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def run_gemm(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.ops.matmul import matmul

    a, b = _rand(rng, n, n, dtype), _rand(rng, n, n, dtype)
    c, t = _time(matmul, jnp.asarray(a), jnp.asarray(b))
    gflops = 2 * n**3 / t / 1e9
    err = 0.0
    if check:
        x = _rand(rng, n, 1, dtype)
        lhs = np.asarray(c) @ x
        rhs = a @ (b @ x)
        err = np.abs(lhs - rhs).max() / (np.abs(rhs).max() + 1e-30)
    return err, t, gflops, err < 100 * n * _eps(dtype)


def run_potrf(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import potrf_array

    g = _rand(rng, n, n, dtype)
    a = g @ g.conj().T + n * np.eye(n, dtype=dtype)
    (l, info), t = _time(potrf_array, jnp.asarray(a))
    gflops = n**3 / 3 / t / 1e9
    ld = np.tril(np.asarray(l))
    err = np.linalg.norm(ld @ ld.conj().T - a) / np.linalg.norm(a) if check else 0.0
    return err, t, gflops, int(info) == 0 and err < 30 * n * _eps(dtype)


def run_getrf(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import getrf_array

    a = _rand(rng, n, n, dtype)
    f, t = _time(getrf_array, jnp.asarray(a))
    gflops = 2 * n**3 / 3 / t / 1e9
    err = 0.0
    if check:
        lu, perm = np.asarray(f.lu), np.asarray(f.perm)
        l = np.tril(lu, -1) + np.eye(n, dtype=dtype)
        u = np.triu(lu)
        err = np.linalg.norm(l @ u - a[perm]) / np.linalg.norm(a)
    return err, t, gflops, err < 30 * n * _eps(dtype)


def run_gesv(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import gesv_array

    a = _rand(rng, n, n, dtype)
    b = _rand(rng, n, 8, dtype)
    (x, f), t = _time(lambda aa, bb: gesv_array(aa, bb), jnp.asarray(a), jnp.asarray(b))
    gflops = (2 * n**3 / 3 + 2 * n**2 * 8) / t / 1e9
    err = np.abs(a @ np.asarray(x) - b).max() / (np.abs(b).max() * np.abs(a).sum(1).max()) if check else 0.0
    return err, t, gflops, err < 30 * n * _eps(dtype)


def run_geqrf(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import geqrf_array
    from slate_tpu.linalg.qr import geqrf_q, geqrf_r

    m = n
    a = _rand(rng, m, n, dtype)
    f, t = _time(geqrf_array, jnp.asarray(a))
    gflops = (4 * m * n**2 - 4 * n**3 / 3) / t / 1e9
    err = 0.0
    if check:
        q = np.asarray(geqrf_q(f))
        r = np.asarray(geqrf_r(f))
        err = np.linalg.norm(q @ r - a) / np.linalg.norm(a)
    return err, t, gflops, err < 30 * n * _eps(dtype)


def run_gels(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import gels_array

    m = 2 * n
    a = _rand(rng, m, n, dtype)
    b = _rand(rng, m, 4, dtype)
    x, t = _time(gels_array, jnp.asarray(a), jnp.asarray(b))
    gflops = (2 * m * n**2) / t / 1e9
    err = 0.0
    if check:  # normal-equations residual: A^H (A x - b) ~ 0
        r = a @ np.asarray(x) - b
        err = np.abs(a.conj().T @ r).max() / (np.abs(a).max() ** 2 * np.abs(x).max() * m)
    return err, t, gflops, err < 100 * n * _eps(dtype)


def run_heev(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import heev_array

    a = _rand(rng, n, n, dtype)
    a = (a + a.conj().T) / 2
    (w, z), t = _time(lambda x: heev_array(x, nb=32), jnp.asarray(a))
    gflops = 4 * n**3 / 3 / t / 1e9
    err = 0.0
    if check:
        w, z = np.asarray(w), np.asarray(z)
        err = np.abs(a @ z - z * w).max() / (np.abs(w).max() + 1e-30) / n
    return err, t, gflops, err < 100 * _eps(dtype)


def run_svd(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.linalg import svd_array

    a = _rand(rng, n, n, dtype)
    (u, s, vh), t = _time(lambda x: svd_array(x, nb=32), jnp.asarray(a))
    gflops = 8 * n**3 / 3 / t / 1e9
    err = 0.0
    if check:
        u, s, vh = np.asarray(u), np.asarray(s), np.asarray(vh)
        err = np.abs(a - (u * s) @ vh).max() / (s[0] + 1e-30) / n
    return err, t, gflops, err < 100 * _eps(dtype)


def run_trsm(n, dtype, rng, check):
    import jax.numpy as jnp
    from slate_tpu.blas3.blas3 import trsm_array
    from slate_tpu.types import Diag, Op, Side, Uplo

    t_mat = np.tril(_rand(rng, n, n, dtype)) + n * np.eye(n, dtype=dtype)
    b = _rand(rng, n, n, dtype)
    x, t = _time(
        lambda a_, b_: trsm_array(Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, 1.0, a_, b_),
        jnp.asarray(t_mat), jnp.asarray(b),
    )
    gflops = n**3 / t / 1e9
    err = np.abs(t_mat @ np.asarray(x) - b).max() / (np.abs(b).max() * n) if check else 0.0
    return err, t, gflops, err < 30 * _eps(dtype)


ROUTINES = {
    "gemm": run_gemm,
    "potrf": run_potrf,
    "getrf": run_getrf,
    "gesv": run_gesv,
    "geqrf": run_geqrf,
    "gels": run_gels,
    "heev": run_heev,
    "svd": run_svd,
    "trsm": run_trsm,
}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("routines", nargs="+", choices=sorted(ROUTINES), help="routines to sweep")
    ap.add_argument("--dim", default="256", help="sizes: N | start:stop[:step] | comma list")
    ap.add_argument("--type", default="d", help="precisions from s,d,c,z")
    ap.add_argument("--check", default="y", choices=["y", "n"])
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args(argv)

    import jax

    if any(p in args.type for p in "dz"):
        jax.config.update("jax_enable_x64", True)

    rng = np.random.default_rng(args.seed)
    check = args.check == "y"
    print(f"{'routine':<8} {'type':<4} {'n':>7} {'error':>10} {'status':>6} "
          f"{'time(s)':>9} {'gflops':>10}")
    failures = 0
    for routine in args.routines:
        for prefix in args.type.split(","):
            for n in _parse_dims(args.dim):
                err, t, gflops, ok = ROUTINES[routine](n, _DTYPES[prefix], rng, check)
                status = "pass" if ok else "FAILED"
                failures += 0 if ok else 1
                print(f"{routine:<8} {prefix:<4} {n:>7} {err:>10.2e} {status:>6} "
                      f"{t:>9.4f} {gflops:>10.1f}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
