"""Headline benchmark: DGEMM (f64) GFLOP/s per chip, Ozaki-split int8 path.

Mirrors the reference tester's gemm benchmark (test/test_gemm.cc:217-245,
gflop formula blas::Gflop<double>::gemm = 2mnk / time) on the driver's
north-star config (BASELINE.json: DGEMM FP64 GFLOPS/chip).  The f64 product
runs on the int8 MXU via the Ozaki error-free split scheme
(slate_tpu/ops/ozaki.py) — TPU v5e has no native f64 path, and XLA's
f32-pair emulation measures ~1.3 TF/s; the split scheme reaches ~4.7 TF/s
at true f64 accuracy (residual-gated below).

Prints the driver-facing JSON line {"metric", "value", "unit",
"vs_baseline", "extras"} INCREMENTALLY: a complete line is re-emitted
after the headline and after every finished extra (the last parsable line
wins, which is what the driver's tail-parser and obs.report's legacy
loader read), and the same line is atomically rewritten to
``bench_partial.json`` next to this file (override:
``SLATE_TPU_BENCH_PARTIAL``) — so a timeout kill (rc=124,
BENCH_r05.json's failure mode) never loses already-measured numbers.
An atexit hook re-emits the last complete line on EVERY exit path
(SIGTERM handler, unhandled exception, SystemExit), so only an outright
SIGKILL can end stdout without a parseable line — and the partial file
covers that (unit-tested: tests/test_bench_kill.py).
``SLATE_TPU_BENCH_TIMEOUT`` (seconds; unset = 600, an explicit 0 = off)
is a wall-clock budget: extras that would start past it are skipped with a
reason, and a SIGALRM guard aborts a mid-flight extra at the deadline
instead of letting it eat the whole run.  Extras run cheapest-first, so
the f64 n=8192 factorizations (the BENCH_r05 rc=124 culprits: unrolled
f64 programs with O(10 min) cold compiles) land LAST — a budget kill
costs the expensive tail, never an already-cheap middle.  A SIGTERM
(what ``timeout`` sends before SIGKILL) re-emits the current full result
line on the way out, so the driver's tail parser sees a complete line
even on the kill path.

vs_baseline: ratio to 19,500 GFLOP/s — the FP64 tensor-core peak of the
A100 GPUs SLATE-CUDA runs on (its large-n DGEMM approaches peak), since the
reference repo publishes no numbers (BASELINE.md).

Ceiling analysis (the honest cross-ISA story): v5e int8 peak is 394 TOPS
(measured dense attainable: ~278 TOPS).  Full-f64 accuracy needs 9 digit
slices = 45 unit-GEMMs per product, so the hardware ceiling for f64-via-
int8 on this chip is 394/45 = 8.8 TF/s (attainable ~6.2); the headline
number is ~76% of attainable ceiling.  A100 FP64 TC peak (19.5 TF/s) is a
dedicated-f64-silicon number — "extras" records the native-precision MFU
story (bf16/int8/f32) where this chip actually competes.

Timing notes: iterations run inside one jitted lax.fori_loop with per-iter
input perturbation, full-size accumulators, and a forced host transfer at
the end — the execution tunnel caches identical dispatches, per-call host
round-trips cost ~0.1 s, XLA DCEs any result that is only partially
consumed, and block_until_ready does not block through the tunnel.
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)
# Persistent XLA compilation cache: the unrolled f64 factorizations take
# O(10 min) to compile through the tunnel helper; the on-disk cache makes
# driver re-runs start in seconds (validated against the axon backend).
import os as _os
jax.config.update("jax_compilation_cache_dir",
                  _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)

_T0 = time.time()


def _progress(msg):
    """Progress to stderr; stdout stays the single driver-facing JSON line."""
    print(f"[bench {time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)

BASELINE_GFLOPS = 19500.0  # A100 FP64 TC peak ~ SLATE-CUDA DGEMM/device
N = 8192  # v5e: 16G HBM; the Ozaki digit planes cap the size
V5E_BF16_PEAK = 197_000.0  # GFLOP/s, published v5e peak
V5E_INT8_PEAK = 394_000.0  # GOP/s


def _timeit(fn, *args, reps=3):
    """Best wall time over reps; forces a scalar host transfer."""
    float(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_dgemm_ozaki(a64, b64, iters=4):
    from slate_tpu.ops.ozaki import matmul_f64

    @jax.jit
    def run(a, b):
        # b must come in as an argument — closing over the device array
        # would embed a 512MB constant in the program and stall compile
        def body(i, carry):
            acc, aa = carry
            return acc + matmul_f64(aa, b), aa + 1e-6

        acc, _ = jax.lax.fori_loop(0, iters, body, (jnp.zeros((N, N), jnp.float64), a))
        return jnp.sum(acc[:1])

    t = _timeit(run, a64, b64)
    return 2.0 * N**3 * iters / t / 1e9


def bench_gemm(dtype, iters, pet=None):
    a = jax.random.normal(jax.random.PRNGKey(0), (N, N)).astype(dtype)
    b = jax.random.normal(jax.random.PRNGKey(1), (N, N)).astype(dtype)
    acc_dt = pet or dtype

    @jax.jit
    def run(a, b):
        def body(i, carry):
            acc, aa = carry
            c = jax.lax.dot_general(
                aa, b, (((1,), (0,)), ((), ())), preferred_element_type=pet
            )
            return acc + c, aa + jnp.ones((), dtype)

        acc, _ = jax.lax.fori_loop(0, iters, body, (jnp.zeros((N, N), acc_dt), a))
        return jnp.sum(acc[:1].astype(jnp.float32))

    t = _timeit(run, a, b)
    return 2.0 * N**3 * iters / t / 1e9


def bench_potrf():
    # recursive path, single call (the scanned variant pays ~3x masked
    # flops and only wins above the recursion's program-size ceiling;
    # SWEEP_r02.json carries the scanned 16384/32768 numbers)
    from slate_tpu.linalg.chol import potrf_array

    g = jax.random.normal(jax.random.PRNGKey(0), (N, N), jnp.float32)
    a = (g @ g.T) / N + 2 * jnp.eye(N, dtype=jnp.float32)
    run = jax.jit(lambda x: jnp.sum(jnp.abs(jnp.diagonal(potrf_array(x)[0]))))
    t = _timeit(run, a)
    return N**3 / 3.0 / t / 1e9


def bench_getrf():
    # recursive path: fastest at n=8192 (the scanned form trades ~2.25x
    # flops for O(1) compile and only wins beyond the recursion's
    # program-size ceiling)
    from slate_tpu.linalg.lu import getrf_array

    m = jax.random.normal(jax.random.PRNGKey(1), (N, N), jnp.float32) / 64
    run = jax.jit(lambda x: jnp.sum(jnp.abs(jnp.diagonal(getrf_array(x).lu))))
    t = _timeit(run, m)
    return 2.0 * N**3 / 3.0 / t / 1e9


# ---------------------------------------------------------------------------
# panel microbenches (ISSUE 6): the fused Pallas panel kernels vs their XLA
# reference chains, at the mesh kernels' panel shape (nb = 256, 63 below
# tiles = one n = 16384 panel column).  These isolate exactly the latency
# story the fused kernels target — SURVEY "Hard parts": potrf f32 runs at
# ~2.4 TF/s while gemm f32 hits ~101 TF/s because the panel phase is nb
# tiny dispatches; the kernel collapses it to ONE.
# ---------------------------------------------------------------------------

NB_PANEL = 256
L_PANEL = 63


def _panel_operands(kind):
    rng = np.random.default_rng(7)
    d = rng.standard_normal((NB_PANEL, NB_PANEL)).astype(np.float32)
    if kind == "potrf":
        d = d @ d.T / NB_PANEL + 2 * np.eye(NB_PANEL, dtype=np.float32)
    else:
        d = d + NB_PANEL * np.eye(NB_PANEL, dtype=np.float32)
    tiles = rng.standard_normal((L_PANEL, NB_PANEL, NB_PANEL)).astype(np.float32)
    return jnp.asarray(d), jnp.asarray(tiles)


def bench_panel_potrf(impl):
    """One potrf panel phase: diag factor (+inverse) then 63 tile solves.
    xla = today's cholesky + batched-trsm chain; pallas = the fused
    chol_panel_tiles kernel."""
    from slate_tpu.ops.pallas_ops import chol_panel_tiles_pallas

    d, tiles = _panel_operands("potrf")
    if impl == "pallas":

        @jax.jit
        def run(d, t):
            lkk, solved = chol_panel_tiles_pallas(d, t)
            return jnp.sum(jnp.abs(lkk)) + jnp.sum(solved[:, :1, :1])

    else:

        @jax.jit
        def run(d, t):
            lkk = jax.lax.linalg.cholesky(d)
            solved = jax.lax.linalg.triangular_solve(
                jnp.broadcast_to(lkk.T, t.shape), t,
                left_side=False, lower=False, transpose_a=False,
            )
            return jnp.sum(jnp.abs(lkk)) + jnp.sum(solved[:, :1, :1])

    t = _timeit(run, d, tiles)
    flops = NB_PANEL**3 / 3.0 + L_PANEL * NB_PANEL**3
    return flops / t / 1e9


def bench_panel_getrf(impl):
    """One LU-nopiv panel-column phase (diag L\\U + 63 right-solves)."""
    from slate_tpu.linalg.lu import _getrf_nopiv_rec
    from slate_tpu.ops.pallas_ops import lu_panel_tiles_pallas

    d, tiles = _panel_operands("getrf")
    if impl == "pallas":

        @jax.jit
        def run(d, t):
            lu, solved = lu_panel_tiles_pallas(d, t)
            return jnp.sum(jnp.abs(lu)) + jnp.sum(solved[:, :1, :1])

    else:

        @jax.jit
        def run(d, t):
            lu = _getrf_nopiv_rec(d)
            solved = jax.lax.linalg.triangular_solve(
                jnp.broadcast_to(jnp.triu(lu), t.shape), t,
                left_side=False, lower=False, transpose_a=False,
            )
            return jnp.sum(jnp.abs(lu)) + jnp.sum(solved[:, :1, :1])

    t = _timeit(run, d, tiles)
    flops = 2.0 * NB_PANEL**3 / 3.0 + L_PANEL * NB_PANEL**3
    return flops / t / 1e9


# ---------------------------------------------------------------------------
# trailing-update microbenches (PR 20): the fused one-dispatch Pallas
# trailing-update kernels vs the XLA einsum bulk forms, at a mesh
# kernel's local trailing shape (an 8 x 8 local tile grid of nb = 256
# tiles — one device's share of a step's trailing update).  The panel
# benches above isolate the panel phase's dispatch latency; these
# isolate the OTHER side of every k-step — the grid-wide consume — where
# the fused kernel keeps the broadcast panels VMEM-resident across the
# whole tile stack instead of re-streaming them per XLA fusion.
# ---------------------------------------------------------------------------

MTL_UPD = NTL_UPD = 8
NB_UPD = 256


def _update_operands(masked):
    rng = np.random.default_rng(9)
    acc = rng.standard_normal(
        (MTL_UPD, NTL_UPD, NB_UPD, NB_UPD)).astype(np.float32)
    pan = rng.standard_normal((MTL_UPD, NB_UPD, NB_UPD)).astype(np.float32)
    urow = rng.standard_normal((NTL_UPD, NB_UPD, NB_UPD)).astype(np.float32)
    mask = (np.arange(MTL_UPD)[:, None] >= np.arange(NTL_UPD)[None, :]
            if masked else np.ones((MTL_UPD, NTL_UPD), bool))
    return (jnp.asarray(acc), jnp.asarray(pan), jnp.asarray(urow),
            jnp.asarray(mask))


def bench_update_summa(impl):
    """One SUMMA stationary-C consume over the local tile grid: xla =
    today's einsum + add; pallas = the fused one-dispatch grid kernel
    (summa_update_pallas, panels broadcast in VMEM)."""
    from slate_tpu.ops.pallas_ops import summa_update_pallas

    acc, pan, urow, _ = _update_operands(masked=False)
    if impl == "pallas":

        @jax.jit
        def run(acc, p, u):
            out = summa_update_pallas(acc, p, u)
            return jnp.sum(out[:, :, :1, :1])

    else:

        @jax.jit
        def run(acc, p, u):
            upd = jnp.einsum("iab,jbc->ijac", p, u,
                             precision=jax.lax.Precision.HIGHEST)
            return jnp.sum((acc + upd.astype(acc.dtype))[:, :, :1, :1])

    t = _timeit(run, acc, pan, urow)
    return 2.0 * MTL_UPD * NTL_UPD * NB_UPD**3 / t / 1e9


def bench_update_potrf(impl):
    """One potrf trailing herk (lower-masked rank-nb update of the local
    trailing stack) — dist_chol._chol_bulk's two lowerings."""
    from slate_tpu.ops.pallas_ops import chol_trailing_update_pallas

    view, pan, _, mask = _update_operands(masked=True)
    pan_t = pan  # the mesh kernel broadcasts the panel twice (row + col)
    if impl == "pallas":

        @jax.jit
        def run(v, p, pt, m):
            out = chol_trailing_update_pallas(v, p, pt, m)
            return jnp.sum(out[:, :, :1, :1])

    else:

        @jax.jit
        def run(v, p, pt, m):
            upd = jnp.einsum("iab,jcb->ijac", p, pt,
                             precision=jax.lax.Precision.HIGHEST
                             ).astype(v.dtype)
            out = v - jnp.where(m[:, :, None, None], upd, 0)
            return jnp.sum(out[:, :, :1, :1])

    t = _timeit(run, view, pan, pan_t, mask)
    flops = 2.0 * int(mask.sum()) * NB_UPD**3
    return flops / t / 1e9


def bench_update_getrf(impl):
    """One LU trailing gemm (full local stack, the strict-schedule
    _nopiv_bulk) — einsum + subtract vs the fused kernel."""
    from slate_tpu.ops.pallas_ops import lu_trailing_update_pallas

    t_loc, pan, urow, mask = _update_operands(masked=False)
    if impl == "pallas":

        @jax.jit
        def run(t, p, u, m):
            out = lu_trailing_update_pallas(t, p, u, m)
            return jnp.sum(out[:, :, :1, :1])

    else:

        @jax.jit
        def run(t, p, u, m):
            upd = jnp.einsum("iab,jbc->ijac", p, u,
                             precision=jax.lax.Precision.HIGHEST)
            return jnp.sum((t - upd.astype(t.dtype))[:, :, :1, :1])

    t = _timeit(run, t_loc, pan, urow, mask)
    return 2.0 * MTL_UPD * NTL_UPD * NB_UPD**3 / t / 1e9


def bench_panel_qr(impl):
    """One tall-skinny Householder panel (m = 16384, w = 64) WITH the
    compact-WY T accumulation — the CAQR / two-stage building block."""
    from slate_tpu.linalg.qr import _larft, _panel_qr
    from slate_tpu.ops.pallas_ops import qr_panel_pallas

    m, w = L_PANEL * NB_PANEL + NB_PANEL, 64
    a = jnp.asarray(
        np.random.default_rng(8).standard_normal((m, w)).astype(np.float32)
    )
    if impl == "pallas":

        @jax.jit
        def run(a):
            vr, tau, t = qr_panel_pallas(a)
            return jnp.sum(jnp.abs(tau)) + jnp.sum(t[:1])

    else:

        @jax.jit
        def run(a):
            vr, tau = _panel_qr(a)
            t = _larft(vr, tau)
            return jnp.sum(jnp.abs(tau)) + jnp.sum(t[:1])

    t = _timeit(run, a)
    return 2.0 * m * w * w / t / 1e9


# f64 factorizations: the shipped dispatch routes f64 (n >= 4096) to the
# LEFT-LOOKING forms (round 4) whose panel updates are large-k gemms — the
# shape where the Ozaki int8-MXU path wins — with digit-plane caching for
# potrf and f32-seeded all-gemm panels; these benches time exactly that
# dispatch (potrf_array / getrf_array), not the superseded scan paths.
N_F64 = 8192


def bench_potrf_f64():
    # the SHIPPED dispatch (potrf_array): f64 at this size routes to the
    # left-looking digit-cached Ozaki form, whose big-k panel updates ride
    # the int8 MXU (chol.py _potrf_ll_ozaki) — the path users actually get
    from slate_tpu.linalg.chol import potrf_array

    n = N_F64
    g = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float64)
    a = (g + g.T) / (2.0 * jnp.sqrt(float(n))) + 3 * jnp.eye(n, dtype=jnp.float64)
    run = jax.jit(lambda x: jnp.sum(jnp.abs(jnp.diagonal(potrf_array(x)[0]))))
    t = _timeit_perturbed(run, a)
    return n**3 / 3.0 / t / 1e9


def bench_gemm_f64_emulated():
    # XLA f32-pair emulated DGEMM at the headline size: the denominator of
    # the honest Ozaki speedup (ozaki wins only in this huge-square
    # regime; see ops/matmul.py gate comment).  The f64_emulation context
    # ENFORCES the emulated path even if this is later switched to the
    # library matmul; outer reps perturb the input so no rep can be served
    # from the tunnel's identical-dispatch cache.
    from slate_tpu.ops.matmul import f64_emulation

    a = jax.random.normal(jax.random.PRNGKey(0), (N, N), jnp.float64)
    b = jax.random.normal(jax.random.PRNGKey(1), (N, N), jnp.float64)

    with f64_emulation():

        @jax.jit
        def run(a, b):
            # b as an argument — a 512MB closure constant stalls compile
            def body(i, carry):
                acc, aa = carry
                return acc + jnp.matmul(aa, b), aa + 1e-9
            acc, _ = jax.lax.fori_loop(0, 2, body, (jnp.zeros((N, N), jnp.float64), a))
            return jnp.sum(acc[:1])

        t = _timeit_perturbed(run, a, b)
    return 2.0 * N**3 * 2 / t / 1e9


def bench_getrf_f64():
    # the SHIPPED dispatch (getrf_array): f64 at this size routes to the
    # left-looking form whose big-k Schur gemms ride the f64 dispatch
    from slate_tpu.linalg.lu import getrf_array

    n = N_F64
    m = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float64) / 64
    run = jax.jit(lambda x: jnp.sum(jnp.abs(jnp.diagonal(getrf_array(x).lu))))
    t = _timeit_perturbed(run, m)
    return 2.0 * n**3 / 3.0 / t / 1e9


# Mixed-precision mesh solve (ISSUE 8): the DEFAULT f64 gesv/posv now
# routes through the f32-factor + fused-refinement ladder
# (Option.MixedPrecision=auto, parallel/dist_refine.py).  These extras
# time the shipped driver against the same driver pinned to the direct
# f64 path — the mixed/f64 ratio IS the headline the routing change buys
# (f32 getrf runs ~40x the emulated-f64 rate, so the solve should
# approach factor-bound f32 time + a few refinement sweeps).
N_SOLVE = 4096


def _bench_mesh_solve(kind: str, mode: str):
    from slate_tpu.parallel import make_mesh
    from slate_tpu.parallel.drivers import gesv_mesh, posv_mesh
    from slate_tpu.types import Option

    n = N_SOLVE
    g = jax.random.normal(jax.random.PRNGKey(2), (n, n), jnp.float64)
    if kind == "posv":
        a = (g + g.T) / (2.0 * jnp.sqrt(float(n))) + 3 * jnp.eye(n, dtype=jnp.float64)
        drv, flops = posv_mesh, n**3 / 3.0
    else:
        # diagonally shifted so the f32 factor's condition stays well
        # inside the IR tier (no GMRES/fallback escalation in the timing)
        a = g + jnp.sqrt(float(n)) * jnp.eye(n, dtype=jnp.float64)
        drv, flops = gesv_mesh, 2.0 * n**3 / 3.0
    b = jax.random.normal(jax.random.PRNGKey(3), (n, 8), jnp.float64)
    mesh = make_mesh()  # near-square grid over every local device
    opts = {Option.MixedPrecision: mode}

    def run(a_):
        x, info = drv(a_, b, mesh, 256, opts=opts)
        jax.block_until_ready(x)
        return x

    run(a)  # compile + warm (the drivers are host-driven multi-program)
    best = float("inf")
    for i in range(2):
        ai = a + (i + 1) * 1e-9 * jnp.eye(n, dtype=jnp.float64)
        jax.block_until_ready(ai)
        t0 = time.perf_counter()
        run(ai)
        best = min(best, time.perf_counter() - t0)
    return flops / best / 1e9


# Serving runtime (ISSUE 11): solves/s of the stacked batch driver vs
# the one-at-a-time loop through the mesh driver at the canonical small
# serving shape.  The ratio IS the headline the serving layer buys —
# small problems can't fill the machine one at a time, batched ones can.
def _bench_serve_batched():
    from slate_tpu.parallel import make_mesh
    from slate_tpu.serve.smoke import measure_throughput

    thr = measure_throughput(make_mesh(), n=512, batch=8, reps=2,
                             loop_reps=1)
    if not thr["bitwise"]:
        raise RuntimeError("serve batched parity broke under bench")
    # stash both rates; the caller derives the speedup ratio
    _bench_serve_batched.last = thr
    return thr["batched_solves_per_s"]


# Service layer (ISSUE 19): end-to-end requests/s through the batch-
# window queue — submit-side binning, budget reservation and DRR dequeue
# included, so the number prices the scheduler itself, not just the
# stacked program it dispatches.
def _bench_serve_queue():
    import numpy as np
    import jax.numpy as jnp

    from slate_tpu.serve.cache import ExecutableCache
    from slate_tpu.serve.queue import BatchQueue, ManualClock
    from slate_tpu.serve.router import Router

    n, reqs, batch = 256, 32, 8
    rng = np.random.default_rng(7)
    g = rng.standard_normal((n, n))
    a = jnp.asarray(g @ g.T / n + 2 * np.eye(n))
    b = jnp.asarray(rng.standard_normal(n))
    router = Router(bins=(n,), cache=ExecutableCache())
    q = BatchQueue(router, max_batch=batch, window_s=0.001,
                   clock=ManualClock(), name="bench")
    try:
        for tenant in ("warm",):  # compile outside the timed stream
            q.submit("posv", a, b, tenant=tenant)
            q.drain()
        t0 = time.perf_counter()
        for i in range(reqs):
            q.submit("posv", a, b, tenant=("acme", "zeta")[i % 2])
        q.drain()
        dt = time.perf_counter() - t0
    finally:
        q.close()
    return reqs / dt


def _timeit_perturbed(fn, a, *rest, reps=2):
    """Best wall time with a PERTURBED first input per rep (identical
    dispatches are cached by the tunnel) and a queue drain per timing."""
    float(fn(a, *rest))  # compile + warm
    best = float("inf")
    for i in range(reps):
        ai = a + (i + 1) * 1e-9
        _ = float(jnp.sum(ai[:1, :4]))  # drain
        t0 = time.perf_counter()
        float(fn(ai, *rest))
        best = min(best, time.perf_counter() - t0)
    return best


import atexit
import contextlib
import signal


_PARTIAL_PATH = _os.environ.get("SLATE_TPU_BENCH_PARTIAL") or _os.path.join(
    _os.path.dirname(_os.path.abspath(__file__)), "bench_partial.json"
)

# the last complete result line, re-emitted by the atexit hook so ANY
# exit path after the headline (SIGTERM handler, an unhandled exception,
# a SystemExit from a failed extra) still ends stdout with a parseable
# line — BENCH_r05 died rc=124 with parsed=null because the kill landed
# where no line had been flushed.  A SIGKILL (timeout -k's second shot)
# skips atexit by definition; the atomically-rewritten partial file from
# the last _emit is the survivor there.
_LAST_LINE = [None]
_ATEXIT_ARMED = [False]


def _atexit_reemit():
    if _LAST_LINE[0]:
        print(_LAST_LINE[0], flush=True)


def _arm_atexit():
    if not _ATEXIT_ARMED[0]:
        atexit.register(_atexit_reemit)
        _ATEXIT_ARMED[0] = True


def _bench_line(gflops, extras):
    return json.dumps(
        {
            "metric": f"dgemm_f64_ozaki_int8_gflops_n{N}",
            "value": round(gflops, 1),
            "unit": "GFLOP/s",
            "vs_baseline": round(gflops / BASELINE_GFLOPS, 4),
            "extras": extras,
        }
    )


def _emit(gflops, extras):
    """Emit the CURRENT full result line: stdout (last line wins for the
    driver's tail parser) + an atomic rewrite of bench_partial.json, so
    every completed metric survives a timeout kill.  Also arms the
    atexit re-emit so any exit path flushes a final parseable line."""
    line = _bench_line(gflops, extras)
    _LAST_LINE[0] = line
    _arm_atexit()
    print(line, flush=True)
    try:
        tmp = _PARTIAL_PATH + ".tmp"
        with open(tmp, "w") as f:
            f.write(line + "\n")
        _os.replace(tmp, _PARTIAL_PATH)
    except OSError as e:  # partial-file trouble must not kill the bench
        _progress(f"partial write failed: {e!r}")


@contextlib.contextmanager
def _alarm(seconds):
    """SIGALRM guard: abort one extra at the budget deadline (raises
    TimeoutError into the caller's except) instead of letting the driver's
    outer ``timeout`` SIGKILL the whole run mid-metric.  Best-effort:
    Python delivers the handler only at a bytecode boundary, so a single
    blocked XLA compile/execute call cannot be interrupted — the
    incremental ``_emit`` checkpoints are what actually preserve the
    already-measured numbers in that case."""
    if seconds is None or seconds <= 0 or not hasattr(signal, "SIGALRM"):
        yield
        return

    def handler(signum, frame):
        raise TimeoutError(f"extra exceeded the {seconds:.0f}s budget")

    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(max(1, int(seconds)))
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def main():
    from slate_tpu.ops.ozaki import matmul_f64

    # unset = a sane 600 s default (BENCH_r05 died rc=124 with the guard
    # off); an explicit SLATE_TPU_BENCH_TIMEOUT=0 still disables it
    budget = float(_os.environ.get("SLATE_TPU_BENCH_TIMEOUT", "600") or 0)
    deadline = _T0 + budget if budget > 0 else None

    # correctness gate: Ozaki f64 product vs numpy f64, 3-eps style
    m = 512
    rng = np.random.default_rng(0)
    am, bm = rng.standard_normal((m, m)), rng.standard_normal((m, m))
    chk = np.asarray(matmul_f64(jnp.asarray(am), jnp.asarray(bm)))
    ref = am @ bm
    rel = np.abs(chk - ref).max() / np.abs(ref).max()
    assert rel < 50 * m * np.finfo(np.float64).eps, f"ozaki residual {rel}"
    _progress(f"accuracy gate passed rel={rel:.2e}")

    a64 = jnp.asarray(rng.standard_normal((N, N)))
    b64 = jnp.asarray(rng.standard_normal((N, N)))
    _progress("operands transferred; timing ozaki dgemm")
    gflops = bench_dgemm_ozaki(a64, b64)
    _progress(f"headline {gflops:.0f} GFLOP/s")

    extras = {"ozaki_check_rel_err": float(rel)}
    _emit(gflops, extras)  # the headline survives even if every extra dies

    def _reemit_on_term(signum, frame):
        # timeout(1) sends SIGTERM before SIGKILL: flush the current full
        # line + partial file so the driver's tail parser wins either way
        _progress("SIGTERM: re-emitting final line and exiting")
        _emit(gflops, extras)
        raise SystemExit(124)

    if hasattr(signal, "SIGTERM"):
        signal.signal(signal.SIGTERM, _reemit_on_term)

    # cheapest-first: the f64 n=8192 factorizations (cold compiles alone
    # can eat several minutes each) run at the very end, after every
    # cheap metric has checkpointed
    for name, fn in [
        ("gemm_bf16_gflops", lambda: bench_gemm(jnp.bfloat16, 64, jnp.float32)),
        ("gemm_int8_gops", lambda: bench_gemm(jnp.int8, 64, jnp.int32)),
        ("gemm_f32_gflops", lambda: bench_gemm(jnp.float32, 32)),
        # fused-panel story (ISSUE 6): the same panel phase under both
        # lowerings — the pallas/xla ratio IS the panel speedup headline
        ("panel_potrf_xla_gflops", lambda: bench_panel_potrf("xla")),
        ("panel_potrf_pallas_gflops", lambda: bench_panel_potrf("pallas")),
        ("panel_getrf_xla_gflops", lambda: bench_panel_getrf("xla")),
        ("panel_getrf_pallas_gflops", lambda: bench_panel_getrf("pallas")),
        ("panel_qr_xla_gflops", lambda: bench_panel_qr("xla")),
        ("panel_qr_pallas_gflops", lambda: bench_panel_qr("pallas")),
        # fused trailing-update story (PR 20): the k-step's OTHER side —
        # the grid-wide consume — under both Option.UpdateImpl lowerings
        ("update_summa_xla_gflops", lambda: bench_update_summa("xla")),
        ("update_summa_pallas_gflops", lambda: bench_update_summa("pallas")),
        ("update_potrf_xla_gflops", lambda: bench_update_potrf("xla")),
        ("update_potrf_pallas_gflops", lambda: bench_update_potrf("pallas")),
        ("update_getrf_xla_gflops", lambda: bench_update_getrf("xla")),
        ("update_getrf_pallas_gflops", lambda: bench_update_getrf("pallas")),
        ("potrf_f32_gflops", bench_potrf),
        ("getrf_f32_gflops", bench_getrf),
        ("gemm_f64_emulated_gflops", bench_gemm_f64_emulated),
        # mixed-precision mesh solve (ISSUE 8): the shipped auto ladder
        # vs the same driver pinned to the direct f64 path — mixed first
        # (cheap), the f64 baselines just before the n=8192 heavyweights
        # serving runtime (ISSUE 11): batched small-problem throughput
        ("serve_batched_solves_per_s", _bench_serve_batched),
        # service layer (ISSUE 19): queue-scheduled end-to-end requests/s
        ("serve_queue_reqs_per_s", _bench_serve_queue),
        ("gesv_mixed_gflops", lambda: _bench_mesh_solve("gesv", "auto")),
        ("posv_mixed_gflops", lambda: _bench_mesh_solve("posv", "auto")),
        ("gesv_f64_direct_gflops", lambda: _bench_mesh_solve("gesv", "off")),
        ("posv_f64_direct_gflops", lambda: _bench_mesh_solve("posv", "off")),
        (f"potrf_f64_gflops_n{N_F64}", bench_potrf_f64),
        (f"getrf_f64_gflops_n{N_F64}", bench_getrf_f64),
    ]:
        remaining = None if deadline is None else deadline - time.time()
        if remaining is not None and remaining <= 0:
            extras[name] = "skipped: SLATE_TPU_BENCH_TIMEOUT budget exhausted"
            _progress(f"extra: {name} skipped (budget exhausted)")
            continue
        _progress(f"extra: {name}")
        try:
            with _alarm(remaining):
                extras[name] = round(fn(), 1)
            _progress(f"extra: {name} = {extras[name]}")
        except Exception as e:  # one failed extra must not kill the headline
            extras[name] = f"failed: {type(e).__name__}"
            _progress(f"extra: {name} failed: {e!r:.200}")
        _emit(gflops, extras)  # atomic checkpoint after every metric
    for kind in ("potrf", "getrf", "qr"):
        px = extras.get(f"panel_{kind}_xla_gflops")
        pp = extras.get(f"panel_{kind}_pallas_gflops")
        if isinstance(px, float) and isinstance(pp, float) and px > 0:
            extras[f"panel_{kind}_pallas_speedup"] = round(pp / px, 2)
    for kind in ("summa", "potrf", "getrf"):
        ux = extras.get(f"update_{kind}_xla_gflops")
        up = extras.get(f"update_{kind}_pallas_gflops")
        if isinstance(ux, float) and isinstance(up, float) and ux > 0:
            extras[f"update_{kind}_pallas_speedup"] = round(up / ux, 2)
    for kind in ("gesv", "posv"):
        mx = extras.get(f"{kind}_mixed_gflops")
        fx = extras.get(f"{kind}_f64_direct_gflops")
        if isinstance(mx, float) and isinstance(fx, float) and fx > 0:
            extras[f"{kind}_mixed_vs_f64_speedup"] = round(mx / fx, 2)
    thr = getattr(_bench_serve_batched, "last", None)
    if thr is not None and thr["loop_solves_per_s"] > 0:
        extras["serve_vs_loop_speedup"] = round(thr["speedup"], 2)
    if isinstance(extras.get("gemm_bf16_gflops"), float):
        extras["bf16_mfu_vs_peak"] = round(extras["gemm_bf16_gflops"] / V5E_BF16_PEAK, 3)
    ge = extras.get("gemm_f64_emulated_gflops")
    if isinstance(ge, float) and ge > 0:
        extras["gemm_f64_ozaki_vs_emulated"] = round(gflops / ge, 2)
    if isinstance(extras.get("gemm_int8_gops"), float):
        extras["int8_mfu_vs_peak"] = round(extras["gemm_int8_gops"] / V5E_INT8_PEAK, 3)
        # f64-via-int8 hardware ceiling: int8 attainable / 45 unit-GEMMs
        extras["ozaki_frac_of_int8_ceiling"] = round(
            gflops / (extras["gemm_int8_gops"] / 45.0), 3
        )

    _emit(gflops, extras)  # final line carries the derived ratios too
    _emit_obs_report(gflops, extras)
    _emit_flight_report()
    _emit_mem_report()
    _emit_num_report()


def _emit_obs_report(gflops, extras):
    """RunReport twin of the driver-facing JSON line (slate_tpu.obs):
    written when SLATE_TPU_OBS=1 or SLATE_TPU_OBS_REPORT=<path> is set,
    diffable against any prior report (or this BENCH line itself) with
    ``python -m slate_tpu.obs.report --check``.  stdout stays untouched."""
    path = _os.environ.get("SLATE_TPU_OBS_REPORT")
    if not path and _os.environ.get("SLATE_TPU_OBS", "") in ("", "0"):
        return
    try:
        from slate_tpu.obs.report import write_report

        if not path:
            path = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                                 "artifacts", "obs", "bench_report.json")
        _os.makedirs(_os.path.dirname(_os.path.abspath(path)), exist_ok=True)
        values = {f"dgemm_f64_ozaki_int8_gflops_n{N}": float(gflops)}
        values.update({k: float(v) for k, v in extras.items()
                       if isinstance(v, (int, float))})
        write_report(path, name="bench",
                     config={"n": N, "n_f64": N_F64}, values=values)
        _progress(f"obs report written to {path}")
    except Exception as e:  # the headline line must never die on obs
        _progress(f"obs report failed: {e!r}")


def _emit_flight_report():
    """Flight-recorder twin (ISSUE 7): when SLATE_TPU_OBS_FLIGHT=<path>
    is set, run a small per-step potrf flight on the available devices
    and write the FlightReport there — the per-k-step schedule timeline
    (critical path, overlap efficiency, exposed comm) next to the
    headline numbers.  Step dispatch fences every phase, so this runs
    AFTER the headline measurements and never touches them."""
    path = _os.environ.get("SLATE_TPU_OBS_FLIGHT")
    if not path:
        return
    try:
        import jax as _jax

        from slate_tpu.obs import flight as _flight
        from slate_tpu.parallel import make_mesh as _make_mesh

        devs = _jax.devices()
        if len(devs) >= 8:
            mesh = _make_mesh(2, 4, devices=devs[:8])
        else:
            mesh = _make_mesh(1, len(devs), devices=devs)
        rep = _flight.run_flight("potrf", n=256, nb=32, depth=1, mesh=mesh)
        _flight.write_flight_report(path, rep)
        _progress(
            f"flight report written to {path} (overlap_eff "
            f"{rep['sched']['overlap_eff']:.3f}, critical_path "
            f"{rep['sched']['critical_path_s']:.4f}s)")
    except Exception as e:  # the headline line must never die on obs
        _progress(f"flight report failed: {e!r}")


def _emit_mem_report():
    """Memory-observability twin (ISSUE 9): when SLATE_TPU_OBS_MEM=<path>
    is set, run the memwatch pass (AOT memory analysis + MemoryModel
    comparison + donation-alias verification) for a small mesh potrf on
    the available devices and write the mem.* RunReport there — the
    compile-analysis keys are the machine-independent regression surface
    next to the headline numbers."""
    path = _os.environ.get("SLATE_TPU_OBS_MEM")
    if not path:
        return
    try:
        import jax as _jax

        from slate_tpu.obs import memwatch as _memwatch
        from slate_tpu.parallel import make_mesh as _make_mesh

        devs = _jax.devices()
        if len(devs) >= 8:
            mesh = _make_mesh(2, 4, devices=devs[:8])
        else:
            mesh = _make_mesh(1, len(devs), devices=devs)
        rep = _memwatch.run_memwatch("potrf", n=256, nb=32, mesh=mesh,
                                     with_donations=False)
        _memwatch.write_mem_report(path, rep)
        v = rep["values"]
        _progress(
            f"mem report written to {path} (temp "
            f"{v['mem.temp_bytes']:,.0f} B/dev, model err "
            f"{v['mem.model_err_frac']:.1%})")
    except Exception as e:  # the headline line must never die on obs
        _progress(f"mem report failed: {e!r}")


def _emit_num_report():
    """Numerics-observability twin (ISSUE 10): when SLATE_TPU_OBS_NUM=
    <path> is set, run the numwatch pass (monitored-factor growth/margin
    gauges + distributed Hager-Higham condest + mixed-ladder health
    routing on seeded adversarial inputs) and write the num.* RunReport
    there — the accuracy report shipping next to the perf numbers, so a
    bench artifact records not just how fast the kernels ran but whether
    the answers they produce are numerically healthy."""
    path = _os.environ.get("SLATE_TPU_OBS_NUM")
    if not path:
        return
    try:
        import jax as _jax

        from slate_tpu.obs import numwatch as _numwatch
        from slate_tpu.parallel import make_mesh as _make_mesh

        devs = _jax.devices()
        if len(devs) >= 8:
            mesh = _make_mesh(2, 4, devices=devs[:8])
        else:
            mesh = _make_mesh(1, len(devs), devices=devs)
        rep = _numwatch.run_numwatch("mixed", n=96, nb=16, mesh=mesh)
        _numwatch.write_num_report(path, rep)
        v = rep["values"]
        _progress(
            f"num report written to {path} (condest "
            f"{v.get('num.condest_cond', 0):.3g}, routed_gmres "
            f"{v.get('num.routed_gmres', 0):.0f}, ir_iters_well "
            f"{v.get('num.ir_iters_well', 0):.0f})")
    except Exception as e:  # the headline line must never die on obs
        _progress(f"num report failed: {e!r}")


def _selftest_kill():
    """Hidden harness for tests/test_bench_kill.py: emit a headline,
    register the SIGTERM/atexit emission machinery exactly as main()
    does, then block mid-'extra' until the test delivers SIGTERM — the
    rc=124 kill path must still end stdout with a parseable line and a
    parseable partial file."""
    gflops = 1.0
    extras = {"selftest": 1}
    _emit(gflops, extras)

    def _reemit_on_term(signum, frame):
        _progress("SIGTERM: re-emitting final line and exiting")
        _emit(gflops, extras)
        raise SystemExit(124)

    signal.signal(signal.SIGTERM, _reemit_on_term)
    print("SELFTEST_READY", file=sys.stderr, flush=True)
    while True:  # mid-extra: blocked until the kill arrives
        time.sleep(0.05)


if __name__ == "__main__":
    if "--selftest-kill" in sys.argv:
        _selftest_kill()
    else:
        main()
