"""Headline benchmark: DGEMM (f64) GFLOP/s per chip.

Mirrors the reference tester's gemm benchmark (test/test_gemm.cc:217-245,
gflop formula blas::Gflop<double>::gemm = 2mnk / time) on the driver's
north-star config (BASELINE.json: DGEMM FP64 GFLOPS/chip).  Residual-checked
before timing, like the tester's `check` mode (test_gemm.cc:248-260).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline: ratio to 19,500 GFLOP/s — the FP64 tensor-core peak of the
A100 GPUs SLATE-CUDA runs on (its large-n DGEMM approaches peak), since the
reference repo publishes no numbers (BASELINE.md).  TPU f64 is software-
emulated (no native f64 MXU path), so this ratio is the honest cross-ISA
comparison the driver asks for.

Timing notes: iterations run inside one jitted lax.fori_loop with per-iter
input perturbation — the execution tunnel caches identical dispatches and
per-call host round-trips cost ~0.5 s, so naive per-call timing is wrong.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

BASELINE_GFLOPS = 19500.0  # A100 FP64 TC peak ~ SLATE-CUDA DGEMM/device
N = 8192  # v5e: 16G HBM; f64 emulation temporaries cap the size
ITERS = 3


def main():
    from slate_tpu.ops.matmul import matmul

    dtype = jnp.float64
    metric = f"dgemm_f64_gflops_n{N}"
    try:
        jnp.zeros((2, 2), dtype) @ jnp.zeros((2, 2), dtype)
    except Exception:
        dtype = jnp.float32  # platform without x64: report f32 instead
        metric = f"gemm_f32_gflops_n{N}"

    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (N, N), jnp.float32).astype(dtype)
    b = jax.random.normal(jax.random.PRNGKey(1), (N, N), jnp.float32).astype(dtype)

    # correctness gate (small block residual vs numpy, 3-eps style)
    m = 256
    chk = np.asarray(matmul(a[:m, :m], b[:m, :m]))
    ref = np.asarray(a[:m, :m], np.float64) @ np.asarray(b[:m, :m], np.float64)
    rel = np.abs(chk - ref).max() / max(np.abs(ref).max(), 1e-30)
    eps = np.finfo(np.asarray(chk).dtype).eps
    assert rel < 50 * m * eps, f"gemm residual {rel} too large"

    @jax.jit
    def run(a, b):
        def body(i, acc):
            # perturb input per iteration so no two dots share operands
            c = matmul(a + i * 1e-6, b)
            return acc + jnp.sum(c)  # consume ALL of C so nothing is DCE'd

        return jax.lax.fori_loop(0, ITERS, body, jnp.zeros((), dtype))

    run(a, b).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    np.asarray(run(a + 0.5, b))  # distinct input: tunnel caches executions
    t1 = time.perf_counter()
    gflops = 2.0 * N**3 * ITERS / (t1 - t0) / 1e9

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(gflops, 1),
                "unit": "GFLOP/s",
                "vs_baseline": round(gflops / BASELINE_GFLOPS, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
