"""Zero-copy views: slice / transpose (ex03_submatrix.cc)."""
import numpy as np, jax.numpy as jnp
import slate_tpu as st

a = st.Matrix.from_array(jnp.asarray(np.arange(36.0).reshape(6, 6)))
sub = a.slice(1, 4, 2, 6)
print("slice:", sub.shape, "conj-transposed:", a.conj_transposed().shape)
