#!/usr/bin/env python
"""Run every example as an installation smoke test (examples/run_tests.py)."""
import glob, os, subprocess, sys

here = os.path.dirname(os.path.abspath(__file__))
env = dict(os.environ, PYTHONPATH=os.path.dirname(here) + os.pathsep + os.environ.get("PYTHONPATH", ""))


def _libpython_available():
    # the C-API example builds native/ which links the -lpythonX.Y named
    # in native/build.sh; containers without that shared libpython cannot
    # build it — soft-skip with the reason instead of failing the smoke run
    import ctypes.util
    import re
    import sysconfig

    build = open(os.path.join(os.path.dirname(here), "native", "build.sh")).read()
    needed = set(re.findall(r"-l(python[\w.]+)", build)) or {"python3"}
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    for lib in needed:
        if not (ctypes.util.find_library(lib)
                or glob.glob(os.path.join(libdir, f"lib{lib}.so*"))):
            return False
    return True


_has_libpython = _libpython_available()
fails = []
for ex in sorted(glob.glob(os.path.join(here, "ex*.py"))):
    name = os.path.basename(ex)
    if "c_api" in name and not _has_libpython and not os.path.exists(
        os.path.join(os.path.dirname(here), "native", "lib", "libslatetpu_c.so")
    ):
        print(f"{name:<36} SKIP (libpython shared library unavailable)")
        continue
    r = subprocess.run([sys.executable, ex], env=env, capture_output=True, text=True, timeout=900)
    status = "ok" if r.returncode == 0 else "FAIL"
    print(f"{name:<36} {status}")
    if r.returncode != 0:
        print(r.stdout[-500:], r.stderr[-800:])
        fails.append(ex)
sys.exit(1 if fails else 0)
