#!/usr/bin/env python
"""Run every example as an installation smoke test (examples/run_tests.py)."""
import glob, os, subprocess, sys

here = os.path.dirname(os.path.abspath(__file__))
env = dict(os.environ, PYTHONPATH=os.path.dirname(here) + os.pathsep + os.environ.get("PYTHONPATH", ""))
fails = []
for ex in sorted(glob.glob(os.path.join(here, "ex*.py"))):
    r = subprocess.run([sys.executable, ex], env=env, capture_output=True, text=True, timeout=900)
    status = "ok" if r.returncode == 0 else "FAIL"
    print(f"{os.path.basename(ex):<36} {status}")
    if r.returncode != 0:
        print(r.stdout[-500:], r.stderr[-800:])
        fails.append(ex)
sys.exit(1 if fails else 0)
