"""Norms over matrix types (ex04_norm.cc)."""
import numpy as np, jax.numpy as jnp
import slate_tpu as st
from slate_tpu.linalg import norm
from slate_tpu.types import Norm

a = jnp.asarray(np.random.default_rng(0).standard_normal((50, 50)))
for nt in (Norm.One, Norm.Inf, Norm.Max, Norm.Fro):
    print(nt.name, float(norm(nt, a)))
