"""Level-3 BLAS (ex05_blas.cc: the 8-line gemm usage)."""
import numpy as np, jax.numpy as jnp
import slate_tpu as st

rng = np.random.default_rng(0)
a = jnp.asarray(rng.standard_normal((512, 256)).astype(np.float32))
b = jnp.asarray(rng.standard_normal((256, 384)).astype(np.float32))
c = jnp.zeros((512, 384), jnp.float32)
c = st.gemm(1.0, a, b, 0.0, c)
print("C = A B:", c.shape, "ok:", np.allclose(np.asarray(c), np.asarray(a) @ np.asarray(b), atol=1e-3))
