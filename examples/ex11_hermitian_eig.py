"""Hermitian eigenvalues (ex11_hermitian_eig.cc)."""
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from slate_tpu.linalg import heev_array

a = np.random.default_rng(0).standard_normal((100, 100)); a = (a + a.T) / 2
w, z = heev_array(jnp.asarray(a), nb=16)
print("eig err:", np.abs(np.asarray(w) - np.linalg.eigvalsh(a)).max())
