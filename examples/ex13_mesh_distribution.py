"""2D block-cyclic mesh distribution (ex13 non-uniform-grid analog):
distributed SUMMA gemm + Cholesky on a virtual device mesh."""
import os
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from slate_tpu.parallel import make_mesh, posv_mesh

devs = jax.devices("cpu")[:8] if len(jax.devices()) < 8 else jax.devices()[:8]
mesh = make_mesh(2, 4, devices=devs)
rng = np.random.default_rng(0)
n = 96
g = rng.standard_normal((n, n)); a = jnp.asarray(g @ g.T + n * np.eye(n))
xt = rng.standard_normal((n, 4))
x, info = posv_mesh(a, jnp.asarray(np.asarray(a) @ xt), mesh, nb=16)
print("mesh:", dict(mesh.shape), "info:", int(info), "err:", np.abs(np.asarray(x) - xt).max())
