"""2D block-cyclic mesh distribution (ex13 non-uniform-grid analog):
distributed SUMMA gemm + Cholesky on a virtual device mesh."""
import os
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from slate_tpu.parallel import make_mesh, posv_mesh

devs = jax.devices("cpu")[:8] if len(jax.devices()) < 8 else jax.devices()[:8]
mesh = make_mesh(2, 4, devices=devs)
rng = np.random.default_rng(0)
n = 96
g = rng.standard_normal((n, n)); a = jnp.asarray(g @ g.T + n * np.eye(n))
xt = rng.standard_normal((n, 4))
x, info = posv_mesh(a, jnp.asarray(np.asarray(a) @ xt), mesh, nb=16)
print("mesh:", dict(mesh.shape), "info:", int(info), "err:", np.abs(np.asarray(x) - xt).max())

# --- non-uniform block sizes + GridOrder (reference ex13 proper) ---
from slate_tpu.parallel import (
    from_dense_nonuniform, gemm_summa, to_dense_nonuniform, from_dense, to_dense,
)
from slate_tpu.types import GridOrder

rowsz = [16, 8, 24, 16, 8, 24]      # ragged row tiling (sums to 96)
colsz = [8, 24, 16, 8, 24, 16]
a2 = jnp.asarray(rng.standard_normal((96, 96)))
b2 = jnp.asarray(rng.standard_normal((96, 96)))
ad = from_dense_nonuniform(a2, mesh, rowsz, colsz)
bd = from_dense_nonuniform(b2, mesh, colsz, rowsz)  # B tiled by A's col sizes
cd = gemm_summa(1.0, ad, bd)
c = to_dense_nonuniform(cd, rowsz, rowsz)
print("non-uniform gemm err:", float(jnp.abs(c - a2 @ b2).max()))

mesh_col = make_mesh(2, 4, devices=devs, order=GridOrder.Col)
x2 = to_dense(from_dense(a2, mesh_col, 16))
print("GridOrder.Col roundtrip exact:", bool(jnp.all(x2 == a2)))
