"""Generalized Hermitian eig (ex12 analog; hegv)."""
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from slate_tpu.linalg import hegv_array

rng = np.random.default_rng(0)
n = 80
a = rng.standard_normal((n, n)); a = (a + a.T) / 2
g = rng.standard_normal((n, n)); b = g @ g.T + n * np.eye(n)
w, x, info = hegv_array(jnp.asarray(a), jnp.asarray(b))
print("info:", int(info), "first eigs:", np.asarray(w)[:3])
