"""Tile-stack and block-cyclic layout transforms (ex02_conversion.cc)."""
import numpy as np, jax.numpy as jnp
from slate_tpu.core.tiling import to_tiles, from_tiles, to_cyclic, from_cyclic

a = jnp.asarray(np.arange(64.0).reshape(8, 8))
t = to_tiles(a, 4)
print("tile stack:", t.shape)
c = to_cyclic(t, 2, 2)
back = from_tiles(from_cyclic(c, 2, 2), 8, 8)
assert (np.asarray(back) == np.asarray(a)).all()
print("round trip exact")
