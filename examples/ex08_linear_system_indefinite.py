"""Hermitian-indefinite solve (ex08_linear_system_indefinite.cc)."""
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from slate_tpu.linalg import hesv_array

rng = np.random.default_rng(0)
n = 100
a = rng.standard_normal((n, n)); a = (a + a.T) / 2
xt = rng.standard_normal((n, 1))
x, f, info = hesv_array(jnp.asarray(a), jnp.asarray(a @ xt), nb=16)
print("info:", int(info), "err:", np.abs(np.asarray(x) - xt).max())
