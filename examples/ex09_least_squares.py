"""Least squares (ex09_least_squares.cc): QR and CholQR paths."""
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from slate_tpu.linalg import gels_array
from slate_tpu.linalg.qr import gels_cholqr_array

rng = np.random.default_rng(0)
a = rng.standard_normal((400, 150))
b = rng.standard_normal((400, 3))
for name, fn in [("qr", gels_array), ("cholqr", gels_cholqr_array)]:
    x = np.asarray(fn(jnp.asarray(a), jnp.asarray(b)))
    print(name, "normal-eq resid:", np.abs(a.T @ (a @ x - b)).max())
