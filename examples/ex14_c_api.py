"""ex14: the C API surface (ex14_scalapack_gemm.cc analogue).

Loads libslatetpu_c.so via ctypes the way a C application links it, and
exercises 20+ generated s/d/c/z routines plus a ScaLAPACK-descriptor
entry point (slate_tpu_pdgesv).
"""

import ctypes
import os
import subprocess

import numpy as np

root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
lib_path = os.path.join(root, "native", "lib", "libslatetpu_c.so")
if not os.path.exists(lib_path):
    subprocess.run(["bash", os.path.join(root, "native", "build.sh")], check=True)
lib = ctypes.CDLL(lib_path)

rng = np.random.default_rng(0)
n, nrhs = 24, 2
i64, f64 = ctypes.c_int64, ctypes.c_double
P = ctypes.c_void_p
calls = 0


def c(fn, *args):
    global calls
    getattr(lib, fn).restype = ctypes.c_int
    info = getattr(lib, fn)(*args)
    assert info >= 0, (fn, info)
    calls += 1
    return info


def ptr(a):
    return P(a.ctypes.data)


for t, dt in [("s", np.float32), ("d", np.float64)]:
    tol = 1e-3 if t == "s" else 1e-9
    a = rng.standard_normal((n, n)).astype(dt)
    xt = rng.standard_normal((n, nrhs)).astype(dt)
    b = (a @ xt).astype(dt)
    x = np.zeros_like(b)
    c(f"slate_tpu_{t}gesv", i64(n), i64(nrhs), ptr(a), ptr(b), ptr(x))
    assert np.abs(x - xt).max() < tol * 100

    spd = (a @ a.T + n * np.eye(n)).astype(dt)
    c(f"slate_tpu_{t}posv", i64(n), i64(nrhs), ptr(spd), ptr(b), ptr(x))
    l = np.zeros_like(spd)
    c(f"slate_tpu_{t}potrf", i64(n), i64(0), ptr(spd), ptr(l))
    assert np.abs(np.tril(l) @ np.tril(l).T - spd).max() < tol * n
    c(f"slate_tpu_{t}potrs", i64(n), i64(nrhs), i64(0), ptr(l), ptr(b), ptr(x))

    lu = np.zeros_like(a)
    piv = np.zeros(n, np.int64)
    c(f"slate_tpu_{t}getrf", i64(n), i64(n), ptr(a), ptr(lu), ptr(piv))
    c(f"slate_tpu_{t}getrs", i64(n), i64(nrhs), i64(0), ptr(lu), ptr(piv),
      ptr(b), ptr(x))
    inv = np.zeros_like(a)
    c(f"slate_tpu_{t}getri", i64(n), ptr(lu), ptr(piv), ptr(inv))
    assert np.abs(inv @ a - np.eye(n)).max() < tol * 1000

    cmat = np.zeros((n, n), dt)
    c(f"slate_tpu_{t}gemm", i64(n), i64(n), i64(n), f64(1.0), f64(0.0),
      ptr(a), ptr(inv), ptr(cmat))
    assert np.abs(cmat - np.eye(n)).max() < tol * 1000

    w = np.zeros(n, dt)
    z = np.zeros((n, n), dt)
    sym = ((a + a.T) / 2).astype(dt)
    c(f"slate_tpu_{t}heev", i64(n), i64(1), ptr(sym), ptr(w), ptr(z))
    assert np.abs(sym @ z - z * w).max() < tol * n

    s_ = np.zeros(n, dt)
    u = np.zeros((n, n), dt)
    vt = np.zeros((n, n), dt)
    c(f"slate_tpu_{t}gesvd", i64(n), i64(n), ptr(a), ptr(s_), ptr(u), ptr(vt))
    assert np.abs((u * s_) @ vt - a).max() < tol * n

    val = np.zeros((), dt)
    c(f"slate_tpu_{t}norm", i64(3), i64(n), i64(n), ptr(a), ptr(val))
    assert abs(float(val) - np.linalg.norm(a)) < tol * 10

# complex: zgesv + zheev
az = (rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n)))
xz = rng.standard_normal((n, 1)) + 1j * rng.standard_normal((n, 1))
bz = az @ xz
outz = np.zeros_like(bz)
c("slate_tpu_zgesv", i64(n), i64(1), ptr(az), ptr(bz), ptr(outz))
assert np.abs(outz - xz).max() < 1e-8
herm = (az + az.conj().T) / 2
wz = np.zeros(n, np.float64)
zz = np.zeros((n, n), np.complex128)
c("slate_tpu_zheev", i64(n), i64(1), ptr(herm), ptr(wz), ptr(zz))
assert np.abs(herm @ zz - zz * wz).max() < 1e-8

# ScaLAPACK descriptor entry: column-major A/B/X with lld = n
ad = rng.standard_normal((n, n))
xd = rng.standard_normal((n, nrhs))
bd = ad @ xd
desc = np.asarray([1, 0, n, n, n, n, 0, 0, n], np.int32)
a_cm = ad.T.copy()  # row-major buffer holding A column-major
b_cm = bd.T.copy()
x_cm = np.zeros((nrhs, n))
c("slate_tpu_pdgesv", i64(n), i64(nrhs), ptr(a_cm), P(desc.ctypes.data),
  ptr(b_cm), P(desc.ctypes.data), ptr(x_cm))
assert np.abs(x_cm.T - xd).max() < 1e-8

print(f"C-API ok: {calls} routine calls across s/d/z + descriptor entry")
