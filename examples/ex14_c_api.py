"""C API demo (ex14_scalapack_gemm analog): call the native shared library
from ctypes the way a C application would."""
import ctypes, os, subprocess, numpy as np

root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
lib_path = os.path.join(root, "native", "lib", "libslatetpu_c.so")
if not os.path.exists(lib_path):
    subprocess.run(["bash", os.path.join(root, "native", "build.sh")], check=True)
lib = ctypes.CDLL(lib_path)
lib.slate_tpu_dgesv.argtypes = [ctypes.c_int64] * 2 + [ctypes.c_void_p] * 3
n = 32
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)) + n * np.eye(n)
xt = rng.standard_normal((n, 1)); b = a @ xt
x = np.zeros_like(xt)
info = lib.slate_tpu_dgesv(n, 1, a.ctypes.data, b.ctypes.data, x.ctypes.data)
print("C-API dgesv info:", info, "err:", np.abs(x - xt).max())
