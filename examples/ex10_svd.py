"""Singular value decomposition (ex10_svd.cc)."""
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from slate_tpu.linalg import svd_array

a = np.random.default_rng(0).standard_normal((120, 80))
u, s, vh = svd_array(jnp.asarray(a), nb=16)
print("sigma_max err:", abs(float(np.asarray(s)[0]) - np.linalg.svd(a, compute_uv=False)[0]))
