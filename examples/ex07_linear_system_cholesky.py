"""SPD solve (ex07_linear_system_cholesky.cc)."""
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from slate_tpu.linalg import posv_array

rng = np.random.default_rng(0)
n = 300
g = rng.standard_normal((n, n))
a = g @ g.T + n * np.eye(n)
xt = rng.standard_normal((n, 1))
x, l, info = posv_array(jnp.asarray(a), jnp.asarray(a @ xt))
print("info:", int(info), "err:", np.abs(np.asarray(x) - xt).max())
