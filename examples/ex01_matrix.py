"""Matrix types (reference examples/ex01_matrix.cc): typed views over arrays."""
import numpy as np, jax.numpy as jnp
import slate_tpu as st

a = jnp.asarray(np.arange(16.0).reshape(4, 4))
m = st.Matrix.from_array(a)
h = st.HermitianMatrix.from_array(a, st.Uplo.Lower)
t = st.TriangularMatrix.from_array(a, st.Uplo.Upper, st.Diag.Unit)
print(m, h, t, sep="\n")
print("transposed view:", m.transposed().shape)
