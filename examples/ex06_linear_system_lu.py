"""LU solve, 4 pivoting strategies (ex06_linear_system_lu.cc)."""
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_enable_x64", True)
from slate_tpu.linalg import gesv_array
from slate_tpu.types import MethodLU

rng = np.random.default_rng(0)
n = 200
a = rng.standard_normal((n, n))
xt = rng.standard_normal((n, 2))
b = a @ xt
for method in (MethodLU.PartialPiv, MethodLU.CALU, MethodLU.RBT):
    x, f = gesv_array(jnp.asarray(a), jnp.asarray(b), method)
    print(method.name, "err:", np.abs(np.asarray(x) - xt).max())
