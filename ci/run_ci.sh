#!/usr/bin/env bash
# One-command CI: static analysis first (fails fast, no kernels run), then
# the unit/numerical suite on the 8-device virtual CPU mesh, then the
# example smoke tests (the reference's Jenkins matrix runs
# test/run_tests.py + examples/run_tests.py the same way, Jenkinsfile:16-26).
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"

# ---- static gates -------------------------------------------------------
# slate_lint: jaxpr + AST invariants over every registered distributed
# driver (see slate_tpu/analysis/).  A lint failure is a CI failure.
python -m slate_tpu.analysis.lint

# contract-matrix autoprover (ISSUE 16): every registry entry's declared
# option contracts (off_jaxpr_identical / zero_extra_collectives /
# bytes_invariant) proved by abstract trace + comm audit, plus the
# registry-completeness and naming-convention checks.  The ring re-run
# proves the matrix holds under the non-default broadcast lowering too
# (the hop schedules move the same bytes, so every cell must re-prove).
python -m slate_tpu.analysis.contracts
SLATE_TPU_BCAST_IMPL=ring python -m slate_tpu.analysis.contracts

# self-checks: each gate must actually trip on its seeded violation,
# otherwise a silent analysis regression would wave everything through.
# Exit code must be EXACTLY 1 (findings) — 2 means the seeded path
# itself crashed.  The three ISSUE 16 SPMD passes (branch-divergent
# collectives, broken ppermute pair, read-after-donate) and the two
# contract seeds (undeclared / broken declaration) gate beside the
# original donation seed.
check_seed() {  # check_seed <module> <args...>
  set +e
  python -m "$@" > /dev/null 2>&1
  seed_rc=$?
  set -e
  if [ "$seed_rc" -ne 1 ]; then
    echo "static-analysis self-check FAILED: '$*' exited $seed_rc" \
         "(want 1)" >&2
    exit 1
  fi
}
check_seed slate_tpu.analysis.lint --skip-trace --seed-violation donation
check_seed slate_tpu.analysis.lint --only seeded \
    --seed-violation branch-divergence
check_seed slate_tpu.analysis.lint --only seeded --seed-violation ppermute-pair
check_seed slate_tpu.analysis.lint --only seeded \
    --seed-violation read-after-donate
check_seed slate_tpu.analysis.contracts --only seeded \
    --seed-violation undeclared-contract
check_seed slate_tpu.analysis.contracts --only seeded \
    --seed-violation broken-contract

# obs smoke: a tiny instrumented potrf_dist on the 8-device mesh must
# emit a schema-valid RunReport (wall/compile time, flop estimate, comm
# bytes) + a Perfetto-loadable trace with nested spans, and the
# `obs.report --check` gate must pass an unchanged report while flagging
# a synthetic 2x regression (slate_tpu/obs/smoke.py validates all of it)
python -m slate_tpu.obs.smoke --out artifacts/obs

# flight smoke (ISSUE 7): the step-level flight recorder — tiny summa +
# potrf re-run as per-step fenced dispatches under BOTH broadcast
# lowerings (psum + ring).  Gates: schema-valid FlightReports, per-device
# Perfetto Gantt with broadcast hop flow events, overlap_eff == 0 at
# lookahead depth 0 and > 0 at depth 1 (the number that proves the
# Option.Lookahead overlap), results numerically correct.  The fresh ring
# reports then gate against the committed references on the
# machine-independent keys only (modeled/measured bytes, resid): the
# millisecond wall-clock keys AND overlap_eff (a ratio of measured
# durations) depend on the runner's per-dispatch host round-trip, so
# they are --ignore'd rather than gated against another machine's
# numbers — the smoke itself asserts the depth-1-vs-0 overlap contrast
# on THIS machine.
python -m slate_tpu.obs.flight --smoke --out artifacts/obs_flight
python -m slate_tpu.obs.report --check \
    artifacts/obs_flight/flight_summa.flight.json \
    artifacts/obs/flight_summa.flight.json --threshold 4 \
    --ignore 'sched.*_s' --ignore 'sched.overlap_eff'
python -m slate_tpu.obs.report --check \
    artifacts/obs_flight/flight_potrf.flight.json \
    artifacts/obs/flight_potrf.flight.json --threshold 4 \
    --ignore 'sched.*_s' --ignore 'sched.overlap_eff'
# ISSUE 15: the QR/eig chains' flights (strict schedules — the smoke
# asserts overlap_eff == 0 by construction; the byte surface gates here)
python -m slate_tpu.obs.report --check \
    artifacts/obs_flight/flight_geqrf.flight.json \
    artifacts/obs/flight_geqrf.flight.json --threshold 4 \
    --ignore 'sched.*_s' --ignore 'sched.overlap_eff'
python -m slate_tpu.obs.report --check \
    artifacts/obs_flight/flight_he2hb.flight.json \
    artifacts/obs/flight_he2hb.flight.json --threshold 4 \
    --ignore 'sched.*_s' --ignore 'sched.overlap_eff'

# memwatch smoke (ISSUE 9): the HBM memory observability layer — AOT
# compile memory analysis of summa + potrf on the 8-device mesh must
# match the analytic MemoryModel within 10%, every donation-registry
# entry must MEASURABLY alias in its compiled executable, and the mem
# gate must trip on a seeded donation loss.  The fresh reports then gate
# against the committed references on the compile-analysis keys only
# (arg/out/temp/alias bytes + model + donation fracs are
# machine-independent at fixed shape); the runtime live/allocator keys
# depend on what else the runner holds live, so they are --ignore'd —
# as is model_err_frac, a near-zero ratio the smoke already bounds at
# 10% absolute (ratio-gating 0.008 vs 0.015 would flake on benign XLA
# buffer-assignment shifts while the byte keys catch any real change).
python -m slate_tpu.obs.memwatch --smoke --out artifacts/obs_mem
python -m slate_tpu.obs.report --check \
    artifacts/obs_mem/mem_summa.report.json \
    artifacts/obs/mem_summa.report.json \
    --ignore 'mem.*_runtime_*' --ignore 'mem.model_err_frac'
python -m slate_tpu.obs.report --check \
    artifacts/obs_mem/mem_potrf.report.json \
    artifacts/obs/mem_potrf.report.json \
    --ignore 'mem.*_runtime_*' --ignore 'mem.model_err_frac'

# numwatch smoke (ISSUE 10): the numerics observability layer — seeded
# adversarial inputs (Wilkinson growth, prescribed-spectrum
# ill-conditioned, near-singular-diagonal SPD) through the monitored
# kernels must trip the num.* gauges exactly (the Wilkinson growth is
# the CLOSED-FORM 2^{n-1}), the distributed Hager-Higham condest must
# match the single-chip estimators to rtol, the mixed ladder must
# health-route the pathological input to the GMRES tier, and every
# non-runtime gauge must be BITWISE-invariant across psum/ring (asserted
# inside the smoke).  The fresh reports then gate against the committed
# references: growth factors, condition estimates and iteration counts
# are bitwise-reproducible at fixed shape, so only the wall-clock keys
# are --ignore'd — the accuracy surface gates tight.
python -m slate_tpu.obs.numwatch --smoke --out artifacts/obs_num
for op in lu potrf mixed qr; do
  python -m slate_tpu.obs.report --check \
      "artifacts/obs_num/num_${op}.report.json" \
      "artifacts/obs/num_${op}.report.json" \
      --ignore 'num.*_runtime_*'
done
# the acceptance bound "gate green under both psum and ring": the smoke
# artifacts above ran ring; re-derive the lu gauges under the explicit
# legacy psum lowering and gate them against the SAME committed ring
# reference — they pass because the values are equal, not merely close
python -m slate_tpu.obs.numwatch lu --impl psum \
    --out artifacts/obs_num/num_lu_psum.report.json
python -m slate_tpu.obs.report --check \
    artifacts/obs_num/num_lu_psum.report.json \
    artifacts/obs/num_lu.report.json \
    --ignore 'num.*_runtime_*'

# serve smoke (ISSUE 11): the serving runtime — the stacked batch driver
# must beat the one-at-a-time mesh-dispatch loop >= 3x in solves/s at
# n = 512 with bitwise per-problem parity, the executable cache must
# perform ZERO retraces after warm-up (trace-counter asserted), ragged
# block-diagonal packing must unpack exactly (non-interaction bitwise),
# and the committed autotuned table (artifacts/serve/tuned.json, written
# by `python -m slate_tpu.serve.tune` from measured sched.* flights)
# must load and resolve with the explicit > context > env > tuned > auto
# precedence.  The ring re-run proves the env tier keeps outranking the
# tuned tier end-to-end.  The fresh report gates against the committed
# reference on the deterministic cache-hygiene keys; machine-dependent
# rates carry the _runtime_ infix and are --ignore'd.
python -m slate_tpu.serve.smoke --out artifacts/serve_ci
SLATE_TPU_BCAST_IMPL=ring python -m slate_tpu.serve.smoke \
    --out artifacts/serve_ci_ring
# (the serve section now carries the SLA latency quantiles too — wall
# clock, so this gate ignores them exactly like the SLA gate below and
# keeps only the machine-independent counts tight)
python -m slate_tpu.obs.report --check \
    artifacts/serve_ci/serve.report.json \
    artifacts/obs/serve.report.json \
    --ignore 'serve.*_runtime_*' --ignore '*latency*_s'

# request-level SLA gate (ISSUE 14): the smoke's SLA phase drove a
# deterministic meshless request stream through the Router; its
# serve_sla.report.json carries the latency histogram reductions +
# outcome-attribution totals/rates.  The quantiles are wall clock
# (--ignore '*latency*_s'); the shape/count/rate keys — per-class
# histogram counts, outcome counts, outcome rates — are
# machine-independent under the fixed stream and gate tight against the
# committed reference under BOTH lowerings (the stream is meshless, so
# ring must reproduce the counts exactly).  serve.stats then formats
# the fresh artifact as Prometheus text — the export-surface smoke.
python -m slate_tpu.obs.report --check \
    artifacts/serve_ci/serve_sla.report.json \
    artifacts/obs/serve_sla.report.json \
    --ignore '*latency*_s'
python -m slate_tpu.obs.report --check \
    artifacts/serve_ci_ring/serve_sla.report.json \
    artifacts/obs/serve_sla.report.json \
    --ignore '*latency*_s'
python -m slate_tpu.serve.stats artifacts/serve_ci/serve_sla.report.json \
    > /dev/null

# service-layer queue smoke (ISSUE 19): the async batch-window queue —
# a deterministic 64-request two-tenant ManualClock stream must coalesce
# into <= ceil(N/B) dispatched programs with ZERO steady-state retraces
# and bitwise parity to one-at-a-time Router dispatch, the weighted-DRR
# dequeue must keep every tenant within one max-weight round (no
# starvation, FIFO within tenant), per-tenant budget overruns must
# terminate as counted reject_budget outcomes with headroom restored on
# drain, the admission memo must evaluate each MemoryModel key exactly
# once over 100 admissions, the SLA controller must trip EXACTLY once on
# a seeded p95 spike (hysteresis — no flapping), and a ragged packed
# window must dispatch as one block-diagonal program.  The stream is
# meshless, so the ring re-run must reproduce every gated count exactly;
# only the wall-clock latency quantiles are --ignore'd.
python -m slate_tpu.serve.queue_smoke --out artifacts/serve_queue_ci
SLATE_TPU_BCAST_IMPL=ring python -m slate_tpu.serve.queue_smoke \
    --out artifacts/serve_queue_ci_ring
python -m slate_tpu.obs.report --check \
    artifacts/serve_queue_ci/serve_queue.report.json \
    artifacts/obs/serve_queue.report.json \
    --ignore '*latency*_s'
python -m slate_tpu.obs.report --check \
    artifacts/serve_queue_ci_ring/serve_queue.report.json \
    artifacts/obs/serve_queue.report.json \
    --ignore '*latency*_s'
# the export surface's new families (ISSUE 15): one scrape carries the
# num.* accuracy gauges and the sched.* schedule keys next to serve.* —
# format the fresh numwatch + flight artifacts and assert both appear
python -m slate_tpu.serve.stats artifacts/obs_num/num_qr.report.json \
    | grep -q 'slate_tpu_num_qr_orth_margin_fused'
python -m slate_tpu.serve.stats \
    artifacts/obs_flight/flight_geqrf.flight.json \
    | grep -q 'slate_tpu_sched_model_bytes'

# telemetry spine (ISSUE 17): start the live scrape endpoint, drive a
# tiny two-tenant Router workload (meshless rounds + one checkpointed/
# monitored mesh solve), scrape it over HTTP mid-process, and require
# validator-clean Prometheus text carrying ALL FOUR families (serve.*,
# sched.*, mem.*, num.*), a validator-clean unified Perfetto trace with
# >= 3 track types correlated by one request's trace_id, and a fresh
# ledger entry — obs.live --ci asserts all of it and exits nonzero
# otherwise.  The ring re-run proves the spine under the non-default
# broadcast lowering (the sched.link_bytes hop records come from the
# ring ppermute schedule there).  The ledger seeded from the committed
# entries then gates the fresh run against the N-run median
# (--trend); latency quantiles are wall clock and stay ignored.
python -m slate_tpu.obs.live --ci --out artifacts/obs_live
SLATE_TPU_BCAST_IMPL=ring python -m slate_tpu.obs.live --ci \
    --out artifacts/obs_live_ring
python -m slate_tpu.obs.report --trend artifacts/obs_live/ledger \
    --ignore '*latency*_s'
python -m slate_tpu.obs.report --trend artifacts/obs_live_ring/ledger \
    --ignore '*latency*_s'

# scaling-curve artifact (ISSUE 7 satellite): fold the MULTICHIP round
# artifacts into one RunReport-schema curve and schema-validate it
# through the standard CLI (the committed twin lives at
# artifacts/obs/scaling.report.json)
python tools/scaling_report.py --out artifacts/obs_flight/scaling.report.json
python -m slate_tpu.obs.report artifacts/obs_flight/scaling.report.json > /dev/null

# ft smoke: the ABFT acceptance run — one injected single-tile fault per
# op class (SUMMA gemm / mesh potrf / LU-nopiv / trsm / her2k) must be detected
# and corrected on the 8-device mesh, the recompute + FtError escalations
# must fire, and the ft.* counters must land in a schema-valid RunReport
# so detection-coverage regressions gate like perf (slate_tpu/ft/smoke.py)
python -m slate_tpu.ft.smoke --out artifacts/ft

# checkpoint/restart smoke (ISSUE 12 + 13): the elastic-reliability
# acceptance run — seeded kill -> resume on the SAME mesh must be
# BITWISE-identical to the uninterrupted factorization for potrf,
# LU-nopiv, partial-pivot LU, the distributed CAQR, and the two-stage
# eig stage-1 reduction (the last two over MULTI-ARRAY carries);
# kill -> resume on a RESHAPED 4x2 mesh must land the bitwise-same
# solution for the tile-stack ops through the shard_map block-cyclic
# redistribution (itself asserted bitwise vs the eager path) while the
# grid-locked multi-array carries REFUSE the reshaped grid with a
# structured error; snapshots survive a disk round trip; an in-segment
# kill loses exactly the steps since the last snapshot; async snapshots
# are bitwise-equal to sync; and the ft.ckpt_* recovery-cost counters
# land in a schema-valid RunReport.  The ring re-run proves the segment
# chains thread Option.BcastImpl end-to-end; the fresh report gates
# against the committed reference on the deterministic keys (snapshot /
# redistribute bytes, lost steps, bitwise-diff zeros) — resume wall time
# and the async-copy overlap are machine-dependent and carry the
# *_runtime_* / *_overlap_s infixes.
python -m slate_tpu.ft.ckpt_smoke --out artifacts/ft_ckpt
SLATE_TPU_BCAST_IMPL=ring python -m slate_tpu.ft.ckpt_smoke \
    --out artifacts/ft_ckpt_ring
python -m slate_tpu.obs.report --check \
    artifacts/ft_ckpt/ft_ckpt.report.json \
    artifacts/obs/ft_ckpt.report.json --ignore '*_runtime_*' \
    --ignore '*_overlap_s'

# broadcast-engine cross-impl pass (ISSUE 5): re-run both smokes under the
# explicit ring lowering so the non-default Option.BcastImpl path is
# exercised end-to-end on every commit (the default runs above already
# cover auto -> doubling on the 2x4 grid; slate_lint covers psum via the
# *_psum registry variants).  Two gates on the ring report vs the
# default-lowering report: `obs.report --check` at threshold 3 keeps the
# TIMING metrics from flaking a shared CI runner, and a dedicated exact
# comparison enforces the byte invariant the loose threshold cannot —
# ring and doubling move the SAME (s-1)-payload link bytes per rooted
# broadcast, so the absorbed comm_bytes must be equal to the byte.
SLATE_TPU_BCAST_IMPL=ring python -m slate_tpu.obs.smoke --out artifacts/obs_ring
SLATE_TPU_BCAST_IMPL=ring python -m slate_tpu.ft.smoke --out artifacts/ft_ring
python -m slate_tpu.obs.report --check \
    artifacts/obs_ring/smoke_report.json artifacts/obs/smoke_report.json \
    --threshold 3
python - <<'PY'
import json
ring = json.load(open("artifacts/obs_ring/smoke_report.json"))["values"]
base = json.load(open("artifacts/obs/smoke_report.json"))["values"]
if ring["comm_bytes"] != base["comm_bytes"]:
    raise SystemExit(
        f"cross-impl comm-byte gate: ring smoke absorbed "
        f"{ring['comm_bytes']:.0f} B/dev but the default lowering "
        f"{base['comm_bytes']:.0f} — the engine hop schedules must move "
        "identical link bytes"
    )
print(f"ci: cross-impl comm bytes equal ({ring['comm_bytes']:.0f} B/dev)")
PY

# fused-panel cross-impl pass (ISSUE 6): re-run both smokes under the
# explicit Pallas panel lowering — on this CPU harness every fused panel
# kernel runs under the Pallas interpreter, so Option.PanelImpl=pallas is
# exercised end-to-end (dist potrf / LU-nopiv panels, the ABFT fused
# trailing-update+checksum consume) on every commit.  The default runs
# above cover auto -> xla (bitwise today's schedules); slate_lint covers
# the pallas jaxprs via the *_panel_pallas registry variants.
SLATE_TPU_PANEL_IMPL=pallas python -m slate_tpu.obs.smoke --out artifacts/obs_panel
SLATE_TPU_PANEL_IMPL=pallas python -m slate_tpu.ft.smoke --out artifacts/ft_panel

# panel parity artifact: regenerate the fused-kernel vs XLA-reference
# RunReports and gate the backward-error parity (QR must be bitwise; the
# explicit-inverse panels must stay within the threshold class).  The
# tool gates internally; the obs.report --check pass re-validates the
# COMMITTED artifact shape through the standard CLI (the acceptance
# gate) — one threshold source for both.
PANEL_PARITY_THRESHOLD=3
python tools/panel_report.py --out artifacts/obs \
    --threshold "$PANEL_PARITY_THRESHOLD"
python -m slate_tpu.obs.report --check \
    artifacts/obs/panel_pallas.report.json artifacts/obs/panel_xla.report.json \
    --threshold "$PANEL_PARITY_THRESHOLD"

# fused trailing-update cross-impl pass (PR 20): re-run the smokes under
# the explicit Pallas trailing-update lowering — on this CPU harness the
# one-kernel fused updates (SUMMA stationary-C consume, potrf trailing
# herk, LU-nopiv trailing gemm) run under the Pallas interpreter, so
# Option.UpdateImpl=pallas is exercised end-to-end on every commit.  The
# default runs above cover auto -> xla (bitwise today's update loops),
# and the contracts runs at the top already prove BOTH lowerings of
# every *_upd_* matrix cell — the xla-side cells are jaxpr-identity
# proofs against the default trace, the pallas-side cells are
# bytes_invariant proofs against their xla twins, each under psum AND
# ring.  (No contracts re-run under this env: the off-pole cells
# compare pinned-xla against the ambient default, which the env itself
# would move.)  The flight re-run gates the byte surface: the fused
# update sits strictly inside the compute half of each k-step, so the
# modeled/measured bytes must equal the committed default-lowering
# references exactly (wall-clock keys and overlap_eff stay
# machine-dependent and --ignore'd, as above).
SLATE_TPU_UPDATE_IMPL=pallas python -m slate_tpu.obs.smoke --out artifacts/obs_upd
SLATE_TPU_UPDATE_IMPL=pallas python -m slate_tpu.ft.smoke --out artifacts/ft_upd
SLATE_TPU_UPDATE_IMPL=pallas python -m slate_tpu.obs.flight --smoke \
    --out artifacts/obs_flight_upd
for op in summa potrf; do
  python -m slate_tpu.obs.report --check \
      "artifacts/obs_flight_upd/flight_${op}.flight.json" \
      "artifacts/obs/flight_${op}.flight.json" --threshold 4 \
      --ignore 'sched.*_s' --ignore 'sched.overlap_eff'
done

# fused-update parity artifact: regenerate the fused trailing-update vs
# XLA-reference RunReports and gate the parity — the update kernels
# replicate the XLA op sequence exactly (contraction at HIGHEST →
# astype → select → add), so the tool requires BITWISE equality under
# the interpreter, a stronger contract than the panel threshold class.
# The obs.report --check pass re-validates the committed artifact pair
# through the standard CLI.
python tools/update_report.py --out artifacts/obs
python -m slate_tpu.obs.report --check \
    artifacts/obs/update_pallas.report.json artifacts/obs/update_xla.report.json \
    --threshold 3

# mixed-precision solve smoke (ISSUE 8): the default f64 gesv/posv route
# through the Option.MixedPrecision=auto ladder (f32 mesh factor + fused
# on-device refinement, GMRES-IR escalation, full-f64 fallback).  The
# smoke asserts the acceptance surface — off is jaxpr-identical to the
# direct path, auto and the Ozaki int8 residual meet the refine.py gate,
# the GMRES tier converges, the ir.* counters land in a schema-valid
# RunReport — then re-runs under the ring broadcast and Pallas panel
# lowerings to prove opts thread end-to-end into the f32 factor AND the
# refinement loop's residual SUMMA.
python -m slate_tpu.parallel.mixed_smoke --out artifacts/mixed
SLATE_TPU_BCAST_IMPL=ring python -m slate_tpu.parallel.mixed_smoke \
    --out artifacts/mixed_ring
SLATE_TPU_PANEL_IMPL=pallas python -m slate_tpu.parallel.mixed_smoke \
    --out artifacts/mixed_panel

# mixed accuracy artifact: regenerate the off-vs-auto RunReports and gate
# the residual-gate parity (the mixed ladder may not be numerically worse
# than the direct f64 solve); the obs.report --check pass re-validates
# the COMMITTED artifact pair through the standard CLI.
python tools/mixed_report.py --out artifacts/obs --threshold 3
python -m slate_tpu.obs.report --check \
    artifacts/obs/mixed_auto.report.json artifacts/obs/mixed_off.report.json \
    --threshold 3

# ruff / mypy: configured in pyproject.toml; the container image may not
# ship them, so gate on availability rather than skipping silently
if command -v ruff > /dev/null 2>&1; then
  ruff check slate_tpu tools tests
else
  echo "ci: ruff not installed; skipping (config lives in pyproject.toml)"
fi
if command -v mypy > /dev/null 2>&1; then
  mypy --config-file pyproject.toml
else
  echo "ci: mypy not installed; skipping (config lives in pyproject.toml)"
fi

# ---- dynamic suites -----------------------------------------------------
# tests/ includes test_lookahead.py in the default tier: the Option.Lookahead
# pipelined schedules must stay BITWISE identical to the strict depth-0
# schedule on the 8-device mesh, and the comm-audit byte totals must be
# depth-invariant (lookahead moves when bytes travel, never how many).
python -m pytest tests/ -q
python examples/run_tests.py
