#!/usr/bin/env bash
# One-command CI: unit/numerical suite on the 8-device virtual CPU mesh,
# then the example smoke tests (the reference's Jenkins matrix runs
# test/run_tests.py + examples/run_tests.py the same way, Jenkinsfile:16-26).
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
python -m pytest tests/ -q
python examples/run_tests.py
