/* C API for slate_tpu — analogue of include/slate/c_api/slate.h.
 *
 * Link against libslatetpu_c.so (native/build.sh).  All matrices are
 * row-major contiguous float64.  Functions return LAPACK-style info codes
 * (0 = success; >0 numerical failure index; <=-100 bridge error).
 *
 * The first call initializes an embedded Python/JAX runtime unless the
 * host process is already Python.  Set PYTHONPATH to include the
 * slate_tpu package root.
 */
#ifndef SLATE_TPU_C_H
#define SLATE_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Solve A X = B, general A (n x n), partial-pivot LU. */
int slate_tpu_dgesv(int64_t n, int64_t nrhs, const double* a,
                    const double* b, double* x);

/* Solve A X = B, A symmetric positive definite. */
int slate_tpu_dposv(int64_t n, int64_t nrhs, const double* a,
                    const double* b, double* x);

/* Least squares min |A X - B|, A (m x n), X (n x nrhs). */
int slate_tpu_dgels(int64_t m, int64_t n, int64_t nrhs, const double* a,
                    const double* b, double* x);

/* C = alpha A B + beta C. */
int slate_tpu_dgemm(int64_t m, int64_t n, int64_t k, double alpha,
                    const double* a, const double* b, double beta, double* c);

/* Symmetric eigen-decomposition: w (n), z (n x n) column eigvecs. */
int slate_tpu_dsyev(int64_t n, const double* a, double* w, double* z);

/* Thin SVD: s (min(m,n)), u (m x k), vt (k x n). */
int slate_tpu_dgesvd(int64_t m, int64_t n, const double* a, double* s,
                     double* u, double* vt);

#ifdef __cplusplus
}
#endif
#endif /* SLATE_TPU_C_H */
