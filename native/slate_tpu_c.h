/* C API for slate_tpu — analogue of include/slate/c_api/slate.h.
 *
 * Link against libslatetpu_c.so (native/build.sh).  The s/d/c/z routine
 * surface is generated (tools/gen_c_api.py) and declared in
 * slate_tpu_c_generated.h — 80 symbols over 20 routines; all buffers are
 * row-major contiguous; LAPACK-style info returns (0 success, >0
 * numerical failure index, <=-100 bridge error).
 *
 * The first call initializes an embedded Python/JAX runtime unless the
 * host process is already Python; the library locates the slate_tpu
 * package relative to its own path (PYTHONPATH override also honored).
 *
 * ScaLAPACK-descriptor entries below accept descinit-style descriptors
 * [dtype, ctxt, M, N, MB, NB, RSRC, CSRC, LLD] over COLUMN-major local
 * arrays (single-process: the grid collapses to one rank and the device
 * mesh provides the actual distribution).
 */
#ifndef SLATE_TPU_C_H
#define SLATE_TPU_C_H

#include <stdint.h>

#include "slate_tpu_c_generated.h"

#ifdef __cplusplus
extern "C" {
#endif

/* Solve A X = B from descriptor arrays; B/X column-major with lld = n. */
int slate_tpu_pdgesv(int64_t n, int64_t nrhs, double* a, const int* desca,
                     double* b, const int* descb, double* x);

/* In-place Cholesky of the descriptor-described column-major A. */
int slate_tpu_pdpotrf(int64_t n, double* a, const int* desca);

/* C = alpha A B + beta C over descriptor-described column-major arrays. */
int slate_tpu_pdgemm(int64_t m, int64_t n, int64_t k, double alpha,
                     const double* a, const int* desca, const double* b,
                     const int* descb, double beta, double* c,
                     const int* descc);

#ifdef __cplusplus
}
#endif
#endif /* SLATE_TPU_C_H */
