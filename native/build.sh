#!/bin/sh
# Build the native runtime pieces into native/lib/.
set -e
cd "$(dirname "$0")"
mkdir -p lib
CXX=${CXX:-g++}
PYINC=$(python3 -c "import sysconfig; print(sysconfig.get_paths()['include'])")
PYLIB=$(python3 -c "import sysconfig; print(sysconfig.get_config_var('LIBDIR'))")
python3 ../tools/gen_c_api.py
python3 ../tools/gen_scalapack_api.py
$CXX -O2 -fPIC -shared -o lib/libslatetpu_trace.so trace_svg.cc
$CXX -O2 -fPIC -shared -I"$PYINC" -o lib/libslatetpu_c.so c_api.cc c_api_generated.cc -L"$PYLIB" -lpython3.12
$CXX -O2 -fPIC -shared -I"$PYINC" -o lib/libslatetpu_scalapack.so c_api.cc c_api_generated.cc scalapack_api_generated.cc -L"$PYLIB" -lpython3.12
echo "built: $(ls lib)"
