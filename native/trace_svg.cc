// SVG timeline trace writer — native analogue of the reference's
// src/auxiliary/Trace.cc (trace::Trace::finish emits a standalone SVG with
// per-thread rows, color legend and time ticks, Trace.cc:330-600).
//
// C ABI consumed from Python via ctypes (slate_tpu/utils/trace.py).  Events
// are appended from the host side; write_svg lays them out one row per lane
// with a microsecond ruler, matching the reference's viewer-free output.

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

struct Event {
    std::string name;
    int lane;
    double t0, t1;
    std::string color;
};

struct Trace {
    std::vector<Event> events;
};

const char* kPalette[] = {
    "#4e79a7", "#f28e2b", "#e15759", "#76b7b2", "#59a14f",
    "#edc948", "#b07aa1", "#ff9da7", "#9c755f", "#bab0ac",
};

}  // namespace

extern "C" {

void* slate_trace_new() { return new Trace(); }

void slate_trace_free(void* t) { delete static_cast<Trace*>(t); }

void slate_trace_event(void* t, const char* name, int lane, double t0,
                       double t1, const char* color) {
    auto* tr = static_cast<Trace*>(t);
    tr->events.push_back(
        {name ? name : "", lane, t0, t1, color ? color : ""});
}

int slate_trace_count(void* t) {
    return static_cast<int>(static_cast<Trace*>(t)->events.size());
}

// Returns 0 on success. scale = pixels per second (reference trace_scale).
int slate_trace_write_svg(void* t, const char* path, double scale) {
    auto* tr = static_cast<Trace*>(t);
    if (tr->events.empty()) return 1;
    FILE* f = std::fopen(path, "w");
    if (!f) return 2;

    double tmin = 1e300, tmax = -1e300;
    int max_lane = 0;
    std::map<std::string, std::string> legend;
    int next_color = 0;
    for (auto& e : tr->events) {
        tmin = std::min(tmin, e.t0);
        tmax = std::max(tmax, e.t1);
        max_lane = std::max(max_lane, e.lane);
        if (legend.find(e.name) == legend.end()) {
            legend[e.name] = e.color.empty()
                ? kPalette[next_color++ % 10]
                : e.color;
        }
    }
    const double row_h = 24.0, pad = 40.0, legend_h = 22.0;
    double span = std::max(tmax - tmin, 1e-9);
    double width = span * scale + 2 * pad;
    double height = (max_lane + 1) * row_h + 2 * pad +
                    legend_h * ((legend.size() + 3) / 4) + 20;

    std::fprintf(f,
        "<svg xmlns='http://www.w3.org/2000/svg' width='%.0f' height='%.0f' "
        "font-family='monospace' font-size='11'>\n", width, height);
    std::fprintf(f, "<rect width='100%%' height='100%%' fill='white'/>\n");

    // time ruler: ~10 ticks
    double tick = span / 10.0;
    for (int i = 0; i <= 10; i++) {
        double x = pad + i * tick * scale;
        std::fprintf(f,
            "<line x1='%.1f' y1='%.0f' x2='%.1f' y2='%.1f' stroke='#ddd'/>\n",
            x, pad - 6, x, pad + (max_lane + 1) * row_h);
        std::fprintf(f,
            "<text x='%.1f' y='%.0f' fill='#666'>%.3fs</text>\n",
            x - 14, pad - 10, i * tick);
    }

    for (auto& e : tr->events) {
        double x = pad + (e.t0 - tmin) * scale;
        double w = std::max((e.t1 - e.t0) * scale, 0.5);
        double y = pad + e.lane * row_h;
        std::fprintf(f,
            "<rect x='%.2f' y='%.1f' width='%.2f' height='%.1f' fill='%s' "
            "stroke='#333' stroke-width='0.3'><title>%s [%.6f, %.6f]s"
            "</title></rect>\n",
            x, y + 2, w, row_h - 4, legend[e.name].c_str(), e.name.c_str(),
            e.t0 - tmin, e.t1 - tmin);
    }

    // legend rows (reference's X11-color legend, Trace.cc:489-)
    int i = 0;
    double ly0 = pad + (max_lane + 1) * row_h + 18;
    for (auto& kv : legend) {
        double lx = pad + (i % 4) * 180.0;
        double ly = ly0 + (i / 4) * legend_h;
        std::fprintf(f,
            "<rect x='%.1f' y='%.1f' width='14' height='14' fill='%s'/>"
            "<text x='%.1f' y='%.1f'>%s</text>\n",
            lx, ly, kv.second.c_str(), lx + 18, ly + 11, kv.first.c_str());
        i++;
    }
    std::fprintf(f, "</svg>\n");
    std::fclose(f);
    return 0;
}

}  // extern "C"
