"""Fused-panel RunReport evidence (ISSUE 6 acceptance artifact).

Runs every fused Pallas panel kernel against its XLA reference chain —
chol diag+inv, the potrf/LU panel-tile phases, the Householder panel
(+T), and the fused ABFT trailing-update+checksum step — and writes one
RunReport per lowering plus a diff summary:

- each side's values are its BACKWARD-ERROR residuals against an f64
  numpy ground truth (``*_resid_err``: lower-is-better names, so the
  ``python -m slate_tpu.obs.report --check PALLAS XLA`` gate enforces
  the parity contract: the fused kernels may not be numerically worse
  than the XLA chains beyond the threshold), and ``*_bitwise`` = 1.0
  for the QR kernels, which must reproduce the XLA pair exactly;
- on this CPU harness the kernels run under the Pallas interpreter, so
  the artifact certifies PARITY (the numerics shipped to the MXU), not
  speed — the on-chip speed story is bench.py's ``panel_*`` extras.

Usage:
  JAX_PLATFORMS=cpu python tools/panel_report.py [--out artifacts/obs]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

NB = 32
L = 7


def _operands():
    rng = np.random.default_rng(0)
    g = rng.standard_normal((NB, NB)).astype(np.float32)
    spd = jnp.asarray(g @ g.T + NB * np.eye(NB, dtype=np.float32))
    dd = jnp.asarray(g + NB * np.eye(NB, dtype=np.float32))
    tiles = jnp.asarray(rng.standard_normal((L, NB, NB)).astype(np.float32))
    qpanel = jnp.asarray(rng.standard_normal((8 * NB, 16)).astype(np.float32))
    return spd, dd, tiles, qpanel


def run(impl: str) -> dict:
    """Residuals of one lowering's panel phases vs f64 numpy truth."""
    from slate_tpu.linalg.lu import _getrf_nopiv_rec
    from slate_tpu.linalg.qr import _larft, _panel_qr
    from slate_tpu.ops import pallas_ops as po

    spd, dd, tiles, qpanel = _operands()
    spd64 = np.asarray(spd, np.float64)
    dd64 = np.asarray(dd, np.float64)
    t64 = np.asarray(tiles, np.float64)
    vals = {}

    # --- potrf panel: diag factor + tile solves ---
    if impl == "pallas":
        lkk, solved = po.chol_panel_tiles_pallas(spd, tiles)
    else:
        lkk = jax.lax.linalg.cholesky(spd)
        solved = jax.lax.linalg.triangular_solve(
            jnp.broadcast_to(lkk.T, tiles.shape), tiles,
            left_side=False, lower=False, transpose_a=False,
        )
    lref = np.linalg.cholesky(spd64)
    sref = t64 @ np.linalg.inv(lref).T
    scale = np.abs(spd64).max()
    vals["panel_potrf_factor_resid_err"] = float(
        np.abs(np.asarray(lkk, np.float64) - lref).max() / scale
    )
    vals["panel_potrf_solve_resid_err"] = float(
        np.abs(np.asarray(solved, np.float64) - sref).max() / np.abs(sref).max()
    )

    # --- LU-nopiv panel: diag L\U + column/row tile solves ---
    if impl == "pallas":
        lu, csolved = po.lu_panel_tiles_pallas(dd, tiles)
        rsolved = po.lu_rowsolve_tiles_pallas(lu, tiles)
    else:
        lu = _getrf_nopiv_rec(dd)
        csolved = jax.lax.linalg.triangular_solve(
            jnp.broadcast_to(jnp.triu(lu), tiles.shape), tiles,
            left_side=False, lower=False, transpose_a=False,
        )
        rsolved = jax.lax.linalg.triangular_solve(
            jnp.broadcast_to(jnp.tril(lu, -1) + jnp.eye(NB, dtype=lu.dtype),
                             tiles.shape),
            tiles, left_side=True, lower=True, transpose_a=False,
            unit_diagonal=True,
        )
    lun = np.asarray(lu, np.float64)
    Lf = np.tril(lun, -1) + np.eye(NB)
    Uf = np.triu(lun)
    vals["panel_getrf_factor_resid_err"] = float(
        np.abs(Lf @ Uf - dd64).max() / np.abs(dd64).max()
    )
    cref = t64 @ np.linalg.inv(Uf)
    rref = np.linalg.inv(Lf) @ t64
    vals["panel_getrf_colsolve_resid_err"] = float(
        np.abs(np.asarray(csolved, np.float64) - cref).max() / np.abs(cref).max()
    )
    vals["panel_getrf_rowsolve_resid_err"] = float(
        np.abs(np.asarray(rsolved, np.float64) - rref).max() / np.abs(rref).max()
    )

    # --- Householder panel (+T): pallas must be BITWISE vs the XLA pair ---
    vr_ref, tau_ref = _panel_qr(qpanel)
    t_ref = _larft(vr_ref, tau_ref)
    if impl == "pallas":
        vr, tau, t = po.qr_panel_pallas(qpanel)
        bitwise = (
            np.array_equal(np.asarray(vr), np.asarray(vr_ref))
            and np.array_equal(np.asarray(tau), np.asarray(tau_ref))
            and np.array_equal(np.asarray(t), np.asarray(t_ref))
        )
    else:
        vr, tau, t = vr_ref, tau_ref, t_ref
        bitwise = True
    vals["panel_qr_bitwise"] = float(bitwise)
    qv = np.asarray(vr, np.float64)
    rq = np.triu(qv[:16])
    qref = np.linalg.qr(np.asarray(qpanel, np.float64))[1]
    vals["panel_qr_factor_resid_err"] = float(
        np.abs(np.abs(rq) - np.abs(qref)).max() / np.abs(qref).max()
    )

    # --- fused ABFT trailing update + Huang-Abraham partial sums ---
    acc = jnp.zeros((L, 3, NB, NB), jnp.float32)
    urow = tiles[:3]
    w1 = jnp.ones((L,), jnp.float32)
    w2 = jnp.arange(1.0, L + 1.0, dtype=jnp.float32)
    part0 = jnp.zeros((2, 3, NB, NB), jnp.float32)
    if impl == "pallas":
        out, part = po.ft_summa_update_pallas(acc, tiles, urow, w1, w2, part0)
    else:
        upd = jnp.einsum("iab,jbc->ijac", tiles, urow,
                         precision=jax.lax.Precision.HIGHEST)
        out = acc + upd
        part = part0 + jnp.stack([
            jnp.einsum("i,ijab->jab", w1, upd,
                       precision=jax.lax.Precision.HIGHEST),
            jnp.einsum("i,ijab->jab", w2, upd,
                       precision=jax.lax.Precision.HIGHEST),
        ])
    upd64 = np.einsum("iab,jbc->ijac", t64, t64[:3])
    p64 = np.stack([
        np.einsum("i,ijab->jab", np.asarray(w1, np.float64), upd64),
        np.einsum("i,ijab->jab", np.asarray(w2, np.float64), upd64),
    ])
    vals["panel_ft_update_resid_err"] = float(
        np.abs(np.asarray(out, np.float64) - upd64).max() / np.abs(upd64).max()
    )
    vals["panel_ft_checksum_resid_err"] = float(
        np.abs(np.asarray(part, np.float64) - p64).max() / np.abs(p64).max()
    )
    vals["panel_kernels_checked"] = 5.0
    return vals


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "artifacts", "obs"))
    ap.add_argument("--threshold", type=float, default=3.0)
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    from slate_tpu.obs.report import check_regression, write_report
    from slate_tpu.ops.pallas_ops import use_panel_impl

    reports = {}
    for impl in ("xla", "pallas"):
        with use_panel_impl(impl):
            jax.clear_caches()
            vals = run(impl)
        path = os.path.join(args.out, f"panel_{impl}.report.json")
        write_report(path, name=f"panel_{impl}",
                     config={"nb": NB, "tiles": L, "impl": impl}, values=vals)
        reports[impl] = vals
        print(f"panel_report: wrote {path}")

    if reports["pallas"].get("panel_qr_bitwise") != 1.0:
        raise SystemExit("panel_report: QR kernel is not bitwise vs XLA")
    failures, compared = check_regression(
        reports["pallas"], reports["xla"], threshold=args.threshold
    )
    diff = {
        "threshold": args.threshold,
        "compared": compared,
        "failures": failures,
        "xla": reports["xla"],
        "pallas": reports["pallas"],
    }
    dpath = os.path.join(args.out, "panel_diff.json")
    with open(dpath, "w") as f:
        json.dump(diff, f, indent=1)
    print(f"panel_report: wrote {dpath} ({compared} metrics compared)")
    if failures:
        for msg in failures:
            print(f"panel_report: REGRESSION {msg}", file=sys.stderr)
        raise SystemExit(1)
    print("panel_report: OK — fused kernels within parity threshold")


if __name__ == "__main__":
    main()
