#!/usr/bin/env python3
"""Thin compatibility shim: the collective-volume audit now lives in
``slate_tpu.obs.comm_audit`` (ISSUE 2 — one audit entry point inside the
observability subsystem).  This wrapper keeps the historical CLI

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/comm_audit.py [--n 256] [--nb 16] [--report R.json]

and pins the virtual-mesh environment before JAX initializes a backend
(which a ``python -m slate_tpu.obs.comm_audit`` invocation cannot do,
since importing the package may already touch JAX).
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from slate_tpu.obs.comm_audit import (  # noqa: E402,F401  (re-exported API)
    main,
    render,
    run_audit,
    summarize,
)

if __name__ == "__main__":
    sys.exit(main())
