#!/usr/bin/env python3
"""Fold the MULTICHIP round artifacts into ONE committed scaling artifact
(ISSUE 7 satellite; ROADMAP "publish the scaling curve" item).

Each driver round leaves a ``MULTICHIP_r0N.json`` wrapper whose ``tail``
holds the harness' incremental JSON lines (``__graft_entry__._dryrun_impl``:
one line per completed phase, the last parsable line wins — the bench.py
contract).  This tool parses every round, extracts the per-phase wall
seconds / residuals / sched metrics, attaches the documented flop models
to estimate GF/s per phase, and writes a single RunReport-schema JSON
(``artifacts/obs/scaling.report.json``) so the scaling trajectory is a
first-class, diffable artifact: ``python -m slate_tpu.obs.report`` prints
it, ``--check`` gates a new sweep against it.

Rounds whose tail is empty or unparsable (e.g. the r01 libtpu-mismatch
crash, the r02-r05 empty tails) are recorded under ``config.missing``
with their rc — absence of data is part of the trajectory, not silently
dropped.

The single-chip BENCH_r0N.json partials fold in too (ISSUE 17): r04's
fully-parsed headline + extras, and the ``[bench ...s] extra: k = v``
progress lines recovered from r05's rc=124 timeout tail — a killed run's
completed phases are data, not garbage.  The report's config is stamped
with the emitting trace_id (the RunReport-meta convention the obs.live
ledger uses), so this artifact is joinable against traces and ledger
entries.

Usage::

    python tools/scaling_report.py [--out artifacts/obs/scaling.report.json]
        [--glob 'MULTICHIP_r*.json'] [--bench-glob 'BENCH_r*.json']
        [--partial multichip_partial.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# harness problem sizes (__graft_entry__._dryrun_impl)
N, NRHS, STEDC_N = 64, 16, 96

# documented flop models per harness phase (None = no meaningful GF/s)
PHASE_FLOPS = {
    # potrf + 2 trsm + SUMMA residual gemm
    "posv_chain": N**3 / 3 + 2 * N * N * NRHS + 2 * N**3,
    "gesv_pp": 2 * N**3 / 3 + 2 * N * N * NRHS,
    "hemm_summa": 2 * N * N * NRHS,
    "stedc_dist": None,
    "heev_chain": 4 * N**3 / 3,
    # potrf + LU-nopiv through the fused panel path
    "panel_pallas": N**3 / 3 + 2 * N**3 / 3,
    "flight_timeline": None,
}


def parse_round(path: str):
    """(round_tag, phases_dict | None, rc): phases from the tail's last
    parsable JSON line carrying a ``phases`` key."""
    tag = re.sub(r"\.json$", "", os.path.basename(path))
    with open(path) as f:
        doc = json.load(f)
    rc = doc.get("rc")
    if isinstance(doc.get("phases"), dict):  # a bare harness line (partial)
        return tag, doc["phases"], rc
    tail = doc.get("tail") or ""
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            inner = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(inner, dict) and isinstance(inner.get("phases"), dict):
            return tag, inner["phases"], rc
    return tag, None, rc


# a completed incremental metric in a bench run's progress log:
# "[bench  653.7s] extra: potrf_f64_gflops_n8192 = 700.8"
_BENCH_EXTRA_RE = re.compile(
    r"\[bench\s+[\d.]+s\]\s+extra:\s+(\w+)\s*=\s*([-+\d.eE]+)")


def parse_bench_round(path: str):
    """(round_tag, values_dict, rc, recovered): the headline + extras of
    a parsed BENCH wrapper, or — when the run died before the headline
    (r05's rc=124 timeout) — every completed ``extra: k = v`` progress
    line recovered from the tail."""
    tag = re.sub(r"\.json$", "", os.path.basename(path))
    with open(path) as f:
        doc = json.load(f)
    rc = doc.get("rc")
    vals = {}
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        metric = parsed.get("metric")
        if metric and isinstance(parsed.get("value"), (int, float)):
            vals[str(metric)] = float(parsed["value"])
        for k, v in (parsed.get("extras") or {}).items():
            if isinstance(v, (int, float)):
                vals[str(k)] = float(v)
        return tag, vals, rc, False
    for m in _BENCH_EXTRA_RE.finditer(doc.get("tail") or ""):
        try:
            vals[m.group(1)] = float(m.group(2))
        except ValueError:
            continue
    return tag, vals, rc, bool(vals)


def _rows_for(tag, phases):
    rows = []
    for name, vals in phases.items():
        if not isinstance(vals, dict):
            continue
        row = {"round": tag, "phase": name}
        if "skipped" in vals or "error" in vals:
            row["status"] = vals.get("skipped") or vals.get("error")
            rows.append(row)
            continue
        secs = vals.get("seconds")
        row["seconds"] = secs
        flops = PHASE_FLOPS.get(name)
        if flops and isinstance(secs, (int, float)) and secs > 0:
            row["gflops"] = flops / secs / 1e9
        for k, v in vals.items():
            if k != "seconds" and isinstance(v, (int, float)):
                row[k] = v
        rows.append(row)
    return rows


def build(paths, partial=None, bench_paths=()) -> dict:
    rows, missing = [], []
    for path in paths:
        tag, phases, rc = parse_round(path)
        if phases is None:
            missing.append({"round": tag, "rc": rc})
            continue
        rows.extend(_rows_for(tag, phases))
    if partial and os.path.exists(partial):
        tag, phases, _ = parse_round(partial)
        if phases is not None:
            rows.extend(_rows_for("partial", phases))

    values = {}
    for row in rows:
        key = f"{row['phase']}_{row['round'].lower()}"
        if isinstance(row.get("seconds"), (int, float)):
            values[f"{key}_seconds"] = float(row["seconds"])
        if isinstance(row.get("gflops"), (int, float)):
            values[f"{key}_gflops"] = float(row["gflops"])

    # single-chip bench partials: headline + extras per round, recovered
    # progress lines for rounds that died mid-run
    bench_rounds = []
    for path in bench_paths:
        tag, bvals, rc, recovered = parse_bench_round(path)
        if not bvals:
            missing.append({"round": tag, "rc": rc})
            continue
        bench_rounds.append({"round": tag, "rc": rc,
                             "recovered_from_tail": recovered,
                             "n_metrics": len(bvals)})
        low = tag.lower()
        for k, v in bvals.items():
            values[f"{low}_{k}"] = v

    from slate_tpu.obs.context import current as _ctx_current, new_trace_id
    from slate_tpu.obs.report import SCHEMA, VERSION, _env_info

    import time

    ctx = _ctx_current()
    return {
        "schema": SCHEMA,
        "version": VERSION,
        "name": "multichip_scaling",
        "created_unix": time.time(),
        "env": _env_info(),
        "config": {
            "n": N, "nrhs": NRHS, "harness": "__graft_entry__.dryrun_multichip",
            "rounds": sorted({r["round"] for r in rows}),
            "bench_rounds": bench_rounds,
            "missing": missing,
            # the emitting trace_id (RunReport-meta convention, ISSUE
            # 17): joinable against the obs.live ledger and traces
            "trace_id": ctx.trace_id if ctx is not None else new_trace_id(),
        },
        "values": values,
        # the curve proper: phase x n_devices x GF/s (every harness round
        # so far runs the 8-device virtual mesh; real-pod rounds will add
        # more n_devices points to the same artifact)
        "curve": rows,
        "metrics": {"counters": [], "gauges": [], "histograms": []},
        "spans": [],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python tools/scaling_report.py",
                                 description=__doc__)
    ap.add_argument("--out",
                    default=os.path.join(REPO, "artifacts", "obs",
                                         "scaling.report.json"))
    ap.add_argument("--glob", default=os.path.join(REPO, "MULTICHIP_r*.json"))
    ap.add_argument("--bench-glob", default=os.path.join(REPO,
                                                         "BENCH_r*.json"))
    ap.add_argument("--partial",
                    default=os.path.join(REPO, "multichip_partial.json"))
    args = ap.parse_args(argv)

    paths = sorted(glob.glob(args.glob))
    if not paths:
        print(f"scaling_report: no artifacts match {args.glob}")
        return 2
    bench_paths = sorted(glob.glob(args.bench_glob)) if args.bench_glob else []
    rep = build(paths, args.partial, bench_paths)

    from slate_tpu.obs.report import validate_report

    errs = validate_report(rep)
    if errs:
        print(f"scaling_report: built report fails schema: {errs}")
        return 1
    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(rep, f, indent=1)
    n_rows = len(rep["curve"])
    n_missing = len(rep["config"]["missing"])
    n_bench = len(rep["config"]["bench_rounds"])
    print(f"scaling_report: {len(paths)} round artifact(s) -> {n_rows} "
          f"phase row(s), {n_bench} bench round(s) folded, "
          f"{n_missing} round(s) without data; wrote {args.out}")
    for br in rep["config"]["bench_rounds"]:
        how = ("recovered from rc=%s tail" % br["rc"]
               if br["recovered_from_tail"] else "parsed headline")
        print(f"  {br['round']}: {br['n_metrics']} metric(s), {how}")
    for row in rep["curve"]:
        bits = [f"{row['phase']:<16} {row['round']}"]
        if "seconds" in row:
            bits.append(f"{row['seconds']:.3f}s")
        if "gflops" in row:
            bits.append(f"{row['gflops']:.3f} GF/s")
        if "status" in row:
            bits.append(f"[{row['status']}]")
        print("  " + "  ".join(bits))
    return 0


if __name__ == "__main__":
    sys.exit(main())
