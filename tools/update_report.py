"""Fused trailing-update RunReport evidence (PR 20 acceptance artifact).

Runs every fused Pallas trailing-update kernel against its XLA einsum
bulk form — the SUMMA stationary-C consume, the potrf trailing herk
(masked), and the LU-nopiv trailing gemm (masked) — and writes one
RunReport per Option.UpdateImpl lowering plus a diff summary:

- each side's values are its residuals against an f64 numpy ground
  truth (``*_resid_err``: lower-is-better names, so the ``python -m
  slate_tpu.obs.report --check PALLAS XLA`` gate enforces the parity
  contract), and ``update_*_bitwise`` = 1.0 — unlike the panel factor
  kernels, the update kernels replicate the XLA op sequence exactly
  (contraction at HIGHEST → astype → select → add/subtract), so under
  the interpreter they must match the einsum forms BIT FOR BIT;
- on this CPU harness the kernels run under the Pallas interpreter, so
  the artifact certifies PARITY (the numerics shipped to the MXU), not
  speed — the on-chip speed story is bench.py's ``update_*`` extras.

Usage:
  JAX_PLATFORMS=cpu python tools/update_report.py [--out artifacts/obs]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

MTL, NTL, NB = 3, 4, 32


def _operands():
    rng = np.random.default_rng(1)
    acc = rng.standard_normal((MTL, NTL, NB, NB)).astype(np.float32)
    pan = rng.standard_normal((MTL, NB, NB)).astype(np.float32)
    pan_t = rng.standard_normal((NTL, NB, NB)).astype(np.float32)
    urow = rng.standard_normal((NTL, NB, NB)).astype(np.float32)
    lower = np.arange(MTL)[:, None] >= np.arange(NTL)[None, :]
    return (jnp.asarray(acc), jnp.asarray(pan), jnp.asarray(pan_t),
            jnp.asarray(urow), jnp.asarray(lower))


def run(impl: str) -> dict:
    """Residuals of one lowering's trailing updates vs f64 numpy truth,
    plus bitwise-vs-XLA flags for the pallas side."""
    from slate_tpu.ops import pallas_ops as po

    acc, pan, pan_t, urow, lower = _operands()
    hi = jax.lax.Precision.HIGHEST
    a64 = np.asarray(acc, np.float64)
    p64 = np.asarray(pan, np.float64)
    pt64 = np.asarray(pan_t, np.float64)
    u64 = np.asarray(urow, np.float64)
    m64 = np.asarray(lower)
    vals = {}

    def xla_summa():
        upd = jnp.einsum("iab,jbc->ijac", pan, urow, precision=hi)
        return acc + upd.astype(acc.dtype)

    def xla_potrf():
        upd = jnp.einsum("iab,jcb->ijac", pan, pan_t,
                         precision=hi).astype(acc.dtype)
        return acc - jnp.where(lower[:, :, None, None], upd, 0)

    def xla_getrf():
        upd = jnp.einsum("iab,jbc->ijac", pan, urow, precision=hi)
        return acc - jnp.where(lower[:, :, None, None],
                               upd.astype(acc.dtype), 0)

    cases = {
        "summa": (
            xla_summa,
            lambda: po.summa_update_pallas(acc, pan, urow),
            a64 + np.einsum("iab,jbc->ijac", p64, u64),
        ),
        "potrf": (
            xla_potrf,
            lambda: po.chol_trailing_update_pallas(acc, pan, pan_t, lower),
            a64 - np.where(m64[:, :, None, None],
                           np.einsum("iab,jcb->ijac", p64, pt64), 0),
        ),
        "getrf": (
            xla_getrf,
            lambda: po.lu_trailing_update_pallas(acc, pan, urow, lower),
            a64 - np.where(m64[:, :, None, None],
                           np.einsum("iab,jbc->ijac", p64, u64), 0),
        ),
    }
    for name, (xla_fn, pallas_fn, truth) in cases.items():
        ref = np.asarray(xla_fn())
        out = np.asarray(pallas_fn()) if impl == "pallas" else ref
        vals[f"update_{name}_resid_err"] = float(
            np.abs(out.astype(np.float64) - truth).max() / np.abs(truth).max()
        )
        vals[f"update_{name}_bitwise"] = float(np.array_equal(out, ref))
    vals["update_kernels_checked"] = float(len(cases))
    return vals


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "artifacts", "obs"))
    ap.add_argument("--threshold", type=float, default=3.0)
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    from slate_tpu.obs.report import check_regression, write_report
    from slate_tpu.ops.pallas_ops import use_update_impl

    reports = {}
    for impl in ("xla", "pallas"):
        with use_update_impl(impl):
            jax.clear_caches()
            vals = run(impl)
        path = os.path.join(args.out, f"update_{impl}.report.json")
        write_report(path, name=f"update_{impl}",
                     config={"mtl": MTL, "ntl": NTL, "nb": NB, "impl": impl},
                     values=vals)
        reports[impl] = vals
        print(f"update_report: wrote {path}")

    not_bitwise = [k for k, v in reports["pallas"].items()
                   if k.endswith("_bitwise") and v != 1.0]
    if not_bitwise:
        raise SystemExit(
            f"update_report: kernels not bitwise vs XLA: {not_bitwise}")
    failures, compared = check_regression(
        reports["pallas"], reports["xla"], threshold=args.threshold
    )
    diff = {
        "threshold": args.threshold,
        "compared": compared,
        "failures": failures,
        "xla": reports["xla"],
        "pallas": reports["pallas"],
    }
    dpath = os.path.join(args.out, "update_diff.json")
    with open(dpath, "w") as f:
        json.dump(diff, f, indent=1)
    print(f"update_report: wrote {dpath} ({compared} metrics compared)")
    if failures:
        for msg in failures:
            print(f"update_report: REGRESSION {msg}", file=sys.stderr)
        raise SystemExit(1)
    print("update_report: OK — fused updates bitwise + within parity "
          "threshold")


if __name__ == "__main__":
    main()
