"""Mixed-precision RunReport evidence (ISSUE 8 acceptance artifact).

Solves the same f64 systems through ``gesv_mesh``/``posv_mesh`` under
``Option.MixedPrecision=off`` (the direct f64 path) and ``auto`` (the
f32-factor + fused-refinement ladder) on the 8-device CPU mesh and
writes one RunReport per mode plus a diff summary:

- each side's values are normalized residual-gate ratios against the
  refine.py contract (``*_gate_ratio``: ||r|| / (||x|| ||A|| eps
  sqrt(n)) — lower-is-better "resid"-free names would not gate, so the
  key carries ``resid``), so the committed
  ``python -m slate_tpu.obs.report --check AUTO OFF`` diff certifies
  the accuracy contract: the mixed ladder may not be numerically worse
  than the direct f64 solve beyond the threshold;
- the ``auto`` report additionally carries the ``ir`` section (solve /
  convergence / iteration / escalation counters) that a pre-mixed
  report lacks — ``--check`` reports those keys as per-key
  INCONCLUSIVE, the sectioned-schema behavior of obs.report;
- on this CPU harness both modes run the same XLA kernels, so the
  artifact certifies ACCURACY (the contract shipped with the routing
  default), not speed — the on-chip speed story is bench.py's
  ``gesv_mixed_gflops`` / ``*_vs_f64_speedup`` extras.

Usage:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/mixed_report.py [--out artifacts/obs]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

N, NB = 96, 16


def _operands():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((N, N)) + N * np.eye(N))
    g = rng.standard_normal((N, N))
    spd = jnp.asarray(g @ g.T / N + 2 * np.eye(N))
    b = jnp.asarray(rng.standard_normal((N, 2)))
    return a, spd, b


def run(mode: str, mesh) -> dict:
    """Residual-gate ratios of one MixedPrecision mode (off | auto)."""
    from slate_tpu.parallel.drivers import gesv_mesh, posv_mesh
    from slate_tpu.types import Option

    a, spd, b = _operands()
    opts = {Option.MixedPrecision: mode}
    eps = np.finfo(np.float64).eps

    def ratio(a_, x_, b_):
        a_, x_, b_ = map(np.asarray, (a_, x_, b_))
        r = b_ - a_ @ x_
        gate = (np.abs(x_).sum(axis=1).max() * np.abs(a_).sum(axis=1).max()
                * eps * np.sqrt(N))
        return float(np.abs(r).sum(axis=1).max() / gate)

    vals = {}
    x, info = gesv_mesh(a, b, mesh, NB, opts=opts)
    assert int(info) == 0, f"gesv info={int(info)} under mode={mode}"
    vals["gesv_gate_resid_ratio"] = ratio(a, x, b)
    x, info = posv_mesh(spd, b, mesh, NB, opts=opts)
    assert int(info) == 0, f"posv info={int(info)} under mode={mode}"
    vals["posv_gate_resid_ratio"] = ratio(spd, x, b)
    vals["solves_checked"] = 2.0
    return vals


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "artifacts", "obs"))
    ap.add_argument("--threshold", type=float, default=3.0)
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    from slate_tpu import obs
    from slate_tpu.obs.report import check_regression, write_report
    from slate_tpu.parallel import make_mesh

    devs = jax.devices("cpu")
    if len(devs) < 8:
        raise SystemExit("mixed_report: need 8 CPU devices — set "
                         "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    mesh = make_mesh(2, 4, devices=devs[:8])

    reports = {}
    for mode in ("off", "auto"):
        obs.reset()
        jax.clear_caches()
        vals = run(mode, mesh)
        path = os.path.join(args.out, f"mixed_{mode}.report.json")
        write_report(path, name=f"mixed_{mode}",
                     config={"n": N, "nb": NB, "grid": "2x4", "mode": mode},
                     values=vals)
        reports[mode] = vals
        print(f"mixed_report: wrote {path}")

    # the accuracy contract: auto's gate ratios may not regress past off's
    # by more than the threshold (both must sit at <= 1.0 — converged —
    # anyway; the assert in run() already enforced info == 0)
    for mode, vals in reports.items():
        for k, v in vals.items():
            if k.endswith("_gate_resid_ratio") and v > 1.0:
                raise SystemExit(
                    f"mixed_report: {mode} {k} = {v:.3g} exceeds the "
                    "residual gate — the solve did not converge"
                )
    failures, compared = check_regression(
        reports["auto"], reports["off"], threshold=args.threshold
    )
    diff = {
        "threshold": args.threshold,
        "compared": compared,
        "failures": failures,
        "off": reports["off"],
        "auto": reports["auto"],
    }
    dpath = os.path.join(args.out, "mixed_diff.json")
    with open(dpath, "w") as f:
        json.dump(diff, f, indent=1)
    print(f"mixed_report: wrote {dpath} ({compared} metrics compared)")
    if failures:
        for msg in failures:
            print(f"mixed_report: REGRESSION {msg}", file=sys.stderr)
        raise SystemExit(1)
    print("mixed_report: OK — mixed ladder within the f64 accuracy contract")


if __name__ == "__main__":
    main()
