#!/usr/bin/env python3
"""North-star size sweep on the real chip (VERDICT round-1 item 2).

Runs each routine in its own subprocess (OOM/timeout isolation), one JSON
line per result; the driver-facing artifact is SWEEP_r02.json.  Usage:

    PYTHONPATH=/root/repo:/root/.axon_site python tools/northstar_sweep.py

Timing note: single timed execution after a warm-up compile; the tunnel
adds ~0.1 s dispatch latency per call, included (i.e. numbers are a lower
bound on throughput).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

CASES = [
    # round 5: artifact-first ordering — the rows that verify round 4's
    # claims (VERDICT r5 item 1) run before the f32 refreshes
    ("heev_vec", 8192, 3600),
    ("getrf_f64", 16384, 7200),
    ("heev_vec", 16384, 7200),
    ("svd", 16384, 7200),
    ("svd_vec", 16384, 9000),
    ("potrf_f64", 16384, 7200),
    # 32768 runs the STAGED per-panel-program form with a donated carry:
    # the fused program's ~5 live matrix copies OOM v5e at 8 GB/matrix
    # (measured r5); per-panel programs cap peak at ~one matrix.
    ("potrf_f64", 32768, 9000),
    ("getrf_scan", 32768, 900),
    ("getrf_scan", 16384, 600),
    ("potrf_scan", 32768, 900),
    ("potrf_scan", 16384, 600),
    ("geqrf", 32768, 900),
    ("geqrf", 16384, 600),
    ("gemm_f32", 16384, 600),
    # no-vector eig/SVD chains + remaining driver families
    ("heev", 8192, 3600),
    ("svd", 8192, 3600),
    ("svd_vec", 8192, 3600),
    ("heev", 16384, 5400),
    ("heev", 4096, 1800),
    ("svd", 4096, 1800),
    ("hesv", 4096, 1800),
    ("pbsv", 16384, 900),
    ("gbsv", 16384, 900),
]

CHILD = r"""
import json, time, sys, os
import numpy as np, jax, jax.numpy as jnp
sys.path.insert(0, {root!r})
# persistent compile cache shared with bench.py (big programs compile once)
jax.config.update("jax_compilation_cache_dir", os.path.join({root!r}, ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 10.0)
routine, n = {routine!r}, {n}
key = jax.random.PRNGKey(0)

def emit(secs, gflops, check, ok):
    print("RESULT " + json.dumps({{
        "routine": routine, "n": n,
        "dtype": "f64" if routine.endswith("_f64") else "f32",
        "seconds": round(secs, 2), "gflops": round(gflops, 1),
        "check": check, "ok": bool(ok)}}), flush=True)

if routine == "getrf_scan":
    from slate_tpu.linalg.lu import getrf_scan_array
    a = jax.random.normal(key, (n, n), jnp.float32) / 64
    f = jax.jit(lambda x: getrf_scan_array(x))
    out = f(a); info = int(out.info)
    d0 = float(jnp.abs(jnp.diagonal(out.lu)).min())
    del out
    _ = float(jnp.sum(a[:1, :4]))  # drain the queue before timing
    t0 = time.perf_counter()
    out = f(a)
    info2 = int(out.info)  # host sync
    t1 = time.perf_counter()
    ok = info == 0 and np.isfinite(d0) and d0 > 0
    emit(t1 - t0, 2 / 3 * n**3 / (t1 - t0) / 1e9, f"info={{info}} dmin={{d0:.2e}}", ok)
elif routine == "potrf_scan":
    from slate_tpu.linalg.chol import _potrf_scan
    # Wigner shift: spectrum of sym/sqrt(n) is in [-2, 2], so 3I + W is
    # SPD without materializing a Gram product; input is donated and the
    # program AOT-compiled so peak HBM stays ~2 matrices (n = 32768 = 4GB)
    f = jax.jit(_potrf_scan, donate_argnums=0)
    comp = f.lower(jax.ShapeDtypeStruct((n, n), jnp.float32)).compile()
    build = jax.jit(
        lambda x: (x + x.T) / (2.0 * np.sqrt(n))
        + 3.0 * jnp.eye(n, dtype=jnp.float32),
        donate_argnums=0,
    )
    # warm run first: the tunnel's AOT .compile() is itself lazy and a
    # cold first execution swallows it (measured 69s cold vs 0.45s warm
    # at n=16384)
    aw = build(jax.random.normal(jax.random.PRNGKey(7), (n, n), jnp.float32))
    lw = comp(aw)
    _ = float(jnp.real(jnp.diagonal(lw)).min())
    del lw
    a = build(jax.random.normal(key, (n, n), jnp.float32))
    _ = float(jnp.sum(a[:1, :4]))  # drain the queue before timing
    t0 = time.perf_counter()
    l = comp(a)
    dmin = float(jnp.real(jnp.diagonal(l)).min())
    t1 = time.perf_counter()
    emit(t1 - t0, n**3 / 3 / (t1 - t0) / 1e9, f"dmin={{dmin:.2e}}",
         np.isfinite(dmin) and dmin > 0)
elif routine == "geqrf":
    from slate_tpu.linalg.qr import geqrf_scan_array
    f = jax.jit(lambda x: geqrf_scan_array(x).r, donate_argnums=0)
    comp = f.lower(jax.ShapeDtypeStruct((n, n), jnp.float32)).compile()
    aw = jax.random.normal(jax.random.PRNGKey(7), (n, n), jnp.float32)
    rw = comp(aw)
    _ = float(jnp.abs(jnp.diagonal(rw)).min())  # warm (lazy tunnel compile)
    del rw
    a = jax.random.normal(key, (n, n), jnp.float32)
    _ = float(jnp.sum(a[:1, :4]))  # drain the queue before timing
    t0 = time.perf_counter()
    r = comp(a)
    dmin = float(jnp.abs(jnp.diagonal(r)).min())
    t1 = time.perf_counter()
    emit(t1 - t0, 4 / 3 * n**3 / (t1 - t0) / 1e9, f"rmin={{dmin:.2e}}",
         np.isfinite(dmin) and dmin > 0)
elif routine == "gemm_f32":
    from slate_tpu.ops.matmul import matmul
    a = jax.random.normal(key, (n, n), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
    f = jax.jit(lambda a, b: jnp.sum(jnp.abs(matmul(a, b)[:1])))
    float(f(a, b))
    t0 = time.perf_counter()
    v = float(f(a + 1e-6, b))
    t1 = time.perf_counter()
    emit(t1 - t0, 2 * n**3 / (t1 - t0) / 1e9, f"sum={{v:.3e}}", np.isfinite(v))
elif routine == "heev":
    # staged driver: one XLA program per phase (a single fused program
    # for all phases faults the TPU runtime near n = 8192)
    from slate_tpu.linalg.eig import heev_staged
    g = jax.random.normal(key, (n, n), jnp.float32)
    a = (g + g.T) / 2
    del g
    f = lambda x: heev_staged(x, want_vectors=False)
    t0 = time.perf_counter()
    w = f(a)
    wmax = float(jnp.abs(w).max())
    t1 = time.perf_counter()
    # Weyl sanity: spectral radius of a Wigner matrix ~ 2 sqrt(n) * sigma
    ok = np.isfinite(wmax) and abs(wmax / (2 * np.sqrt(n) * np.sqrt(0.5)) - 1) < 0.2
    emit(t1 - t0, 4 / 3 * n**3 / (t1 - t0) / 1e9, f"wmax={{wmax:.3e}}", ok)
elif routine == "svd":
    from slate_tpu.linalg.svd import svd_staged
    a = jax.random.normal(key, (n, n), jnp.float32)
    f = lambda x: svd_staged(x, want_vectors=False)
    t0 = time.perf_counter()
    s = f(a)
    smax = float(s.max())
    t1 = time.perf_counter()
    ok = np.isfinite(smax) and abs(smax / (2 * np.sqrt(n)) - 1) < 0.2
    emit(t1 - t0, 8 / 3 * n**3 / (t1 - t0) / 1e9, f"smax={{smax:.3e}}", ok)
elif routine == "heev_vec":
    from slate_tpu.linalg.eig import heev_staged
    g = jax.random.normal(key, (n, n), jnp.float32)
    a = (g + g.T) / 2
    del g
    t0 = time.perf_counter()
    w, z = heev_staged(a, want_vectors=True)
    wmax = float(jnp.abs(w).max())
    t1 = time.perf_counter()
    idx = np.arange(0, n, max(1, n // 64))
    zc = np.asarray(z[:, idx]); wc = np.asarray(w)[idx]
    an = np.asarray(a)
    resid = float(np.abs(an @ zc - zc * wc).max() / max(wmax, 1e-30))
    orth = float(np.abs(zc.T @ zc - np.eye(len(idx))).max())
    ok = resid < 5e-5 and orth < 5e-4
    emit(t1 - t0, 4 / 3 * n**3 / (t1 - t0) / 1e9,
         f"resid={{resid:.2e}} orth={{orth:.2e}}", ok)
elif routine == "svd_vec":
    from slate_tpu.linalg.svd import svd_staged
    a = jax.random.normal(key, (n, n), jnp.float32)
    t0 = time.perf_counter()
    u, s, vh = svd_staged(a)
    smax = float(s.max())
    t1 = time.perf_counter()
    idx = np.arange(0, n, max(1, n // 64))
    un = np.asarray(u[:, idx]); vn = np.asarray(vh[idx, :]); sn = np.asarray(s)[idx]
    an = np.asarray(a)
    resid = float(np.abs(an @ vn.conj().T - un * sn).max() / smax)
    orth = float(np.abs(un.T @ un - np.eye(len(idx))).max())
    ok = resid < 5e-5 and orth < 5e-4
    emit(t1 - t0, 8 / 3 * n**3 / (t1 - t0) / 1e9,
         f"resid={{resid:.2e}} orth={{orth:.2e}}", ok)
elif routine == "hesv":
    # symmetric-indefinite solve (unitary-congruence Q T Q^H + pivoted
    # gtsv, linalg/indefinite.py) — first on-chip datapoint (VERDICT r4
    # item 9); flop formula matches the driver's documented ~4x Aasen cost
    from slate_tpu.linalg import hesv_array
    g = jax.random.normal(key, (n, n), jnp.float32)
    a = (g + g.T) / 2
    del g
    b = jax.random.normal(jax.random.PRNGKey(1), (n, 2), jnp.float32)
    x, fac, info = hesv_array(a, b)
    _ = float(jnp.sum(jnp.abs(x[:1])))  # warm + sync
    _ = float(jnp.sum(a[:1, :4]))
    t0 = time.perf_counter()
    x, fac, info = hesv_array(a + 1e-6, b)
    _ = float(jnp.sum(jnp.abs(x[:1])))
    t1 = time.perf_counter()
    an, xn, bn = np.asarray(a + 1e-6), np.asarray(x), np.asarray(b)
    resid = float(np.abs(an @ xn - bn).max()
                  / (np.abs(an).max() * np.abs(xn).max() * n + np.abs(bn).max()))
    ok = int(info) == 0 and resid < 100 * n * 1.2e-7
    emit(t1 - t0, 4 * n**3 / 3 / (t1 - t0) / 1e9, f"resid={{resid:.2e}}", ok)
elif routine == "pbsv":
    # SPD band solve, windowed O(n kd^2) path (VERDICT r4 item 9)
    from slate_tpu.linalg import pbsv_array
    kd = 512
    i = jnp.arange(n)
    band = (jnp.abs(i[:, None] - i[None, :]) <= kd)
    g = jax.random.normal(key, (n, n), jnp.float32)
    a = jnp.where(band, (g + g.T) / 2, 0) + 3 * kd * jnp.eye(n, dtype=jnp.float32)
    del g, band
    b = jax.random.normal(jax.random.PRNGKey(1), (n, 2), jnp.float32)
    x, fac, info = pbsv_array(a, b, kd)
    _ = float(jnp.sum(jnp.abs(x[:1])))
    _ = float(jnp.sum(a[:1, :4]))
    t0 = time.perf_counter()
    x, fac, info = pbsv_array(a + 1e-6 * jnp.eye(n, dtype=jnp.float32), b, kd)
    _ = float(jnp.sum(jnp.abs(x[:1])))
    t1 = time.perf_counter()
    an = np.asarray(a) + 1e-6 * np.eye(n, dtype=np.float32)
    xn, bn = np.asarray(x), np.asarray(b)
    resid = float(np.abs(an @ xn - bn).max()
                  / (np.abs(an).max() * np.abs(xn).max() * n + np.abs(bn).max()))
    ok = int(info) == 0 and resid < 100 * n * 1.2e-7
    # ~n kd^2 factor flops + 4 n kd nrhs solve flops (windowed band path)
    emit(t1 - t0, n * kd * (kd + 8.0) / (t1 - t0) / 1e9,
         f"kd={{kd}} resid={{resid:.2e}}", ok)
elif routine == "gbsv":
    # general band solve, windowed partial-pivot path (VERDICT r4 item 9)
    from slate_tpu.linalg import gbsv_array
    kl = ku = 512
    i = jnp.arange(n)
    band = (i[:, None] - i[None, :] <= kl) & (i[None, :] - i[:, None] <= ku)
    a = jnp.where(band, jax.random.normal(key, (n, n), jnp.float32), 0)
    a = a + 3 * kl * jnp.eye(n, dtype=jnp.float32)
    del band
    b = jax.random.normal(jax.random.PRNGKey(1), (n, 2), jnp.float32)
    x, fac = gbsv_array(a, b, kl, ku)
    _ = float(jnp.sum(jnp.abs(x[:1])))
    _ = float(jnp.sum(a[:1, :4]))
    t0 = time.perf_counter()
    x, fac = gbsv_array(a + 1e-6 * jnp.eye(n, dtype=jnp.float32), b, kl, ku)
    _ = float(jnp.sum(jnp.abs(x[:1])))
    t1 = time.perf_counter()
    an = np.asarray(a) + 1e-6 * np.eye(n, dtype=np.float32)
    xn, bn = np.asarray(x), np.asarray(b)
    resid = float(np.abs(an @ xn - bn).max()
                  / (np.abs(an).max() * np.abs(xn).max() * n + np.abs(bn).max()))
    ok = resid < 100 * n * 1.2e-7
    emit(t1 - t0, 2.0 * n * kl * (kl + ku) / (t1 - t0) / 1e9,
         f"kl=ku={{kl}} resid={{resid:.2e}}", ok)
elif routine == "potrf_f64":
    # f64 left-looking Cholesky: digit-cached Ozaki updates at 16384
    # (potrf_array dispatch), in-place split-per-call at 32768 (cache +
    # matrix exceed HBM) — VERDICT r4 item 1
    jax.config.update("jax_enable_x64", True)
    import numpy as _np
    from slate_tpu.linalg.chol import potrf_array
    rng = _np.random.default_rng(0)
    ah = rng.standard_normal((n, n))
    ah = (ah + ah.T) / (2.0 * _np.sqrt(n)) + 3.0 * _np.eye(n)
    a = jax.device_put(ah); del ah
    _ = float(jnp.sum(a[:1, :4]))
    if n <= 20480:
        f = jax.jit(lambda x: potrf_array(x)[0])
        l = f(a)
        dmin = float(jnp.min(jnp.real(jnp.diagonal(l))))  # sync (real run)
        del l
        a2 = jax.block_until_ready(a + 1e-9)
        _ = float(jnp.sum(a2[:1, :4]))
        t0 = time.perf_counter()
        l = f(a2)
        dmin = float(jnp.min(jnp.real(jnp.diagonal(l))))
        t1 = time.perf_counter()
        # residual via matvec columns, CHUNKED: XLA's f64 emulation
        # materializes ~8 f32 copies of the big operand per dot, so a
        # whole-matrix f64 matvec OOMs next to the factor at 16384
        xv = jax.device_put(rng.standard_normal((n, 4)))
        def mv(mat_rows, x, c=2048):
            return jnp.concatenate([mat_rows[i:i+c] @ x for i in range(0, n, c)])
        lty = mv(l.T, xv)
        num = jnp.linalg.norm(mv(l, lty) - mv(a2, xv))
        den = jnp.linalg.norm(mv(a2, xv))
        resid = float(num / den)
    else:
        # STAGED per-panel programs with donation (the fused form keeps
        # ~5 live matrix copies and OOMs at 32768); input pre-symmetrized
        from slate_tpu.linalg.chol import potrf_left_looking_staged
        l = potrf_left_looking_staged(a, donate=True)
        dmin = float(jnp.min(jnp.real(jnp.diagonal(l))))
        del l, a
        ah = rng.standard_normal((n, n))
        ah = (ah + ah.T) / (2.0 * _np.sqrt(n)) + 3.0 * _np.eye(n)
        a2 = jax.device_put(ah); del ah
        _ = float(jnp.sum(a2[:1, :4]))
        t0 = time.perf_counter()
        l = potrf_left_looking_staged(a2, donate=True)
        dmin = float(jnp.min(jnp.real(jnp.diagonal(l))))
        t1 = time.perf_counter()
        resid = float("nan")  # input donated; dmin + 16384-run gate accuracy
    ok = _np.isfinite(dmin) and dmin > 0 and (not _np.isfinite(resid) or resid < 1e-12)
    emit(t1 - t0, n**3 / 3 / (t1 - t0) / 1e9,
         f"dmin={{dmin:.2e}} resid={{resid:.2e}}", ok)
elif routine == "getrf_f64":
    # f64 partial-pivot LU through the shipped dispatch: left-looking at
    # the chip-validated sizes (<= 8192), the scanned single-program form
    # past the _GETRF_LL_MAX_N gate (see lu.py — the 16384 left-looking
    # program factors wrong on chip despite every component passing)
    jax.config.update("jax_enable_x64", True)
    import numpy as _np
    from slate_tpu.linalg.lu import getrf_array
    rng = _np.random.default_rng(0)
    a = jax.device_put(rng.standard_normal((n, n)) / 64)
    _ = float(jnp.sum(a[:1, :4]))
    # donate the input: the 16384 f64 program peaks ~14.4 GB un-donated
    # (memory_analysis) — aliasing the 2 GB input is what fits v5e HBM
    f = jax.jit(lambda x: getrf_array(x), donate_argnums=0)
    out = f(a)
    dmin = float(jnp.min(jnp.abs(jnp.diagonal(out.lu))))
    del out, a
    # timed run on a donated input; the matrix is rebuilt from its seed
    # AFTER the factorization for the residual check (nothing but the
    # program's own buffers is resident while it runs)
    a2_in = jax.device_put(_np.random.default_rng(7).standard_normal((n, n)) / 64)
    _ = float(jnp.sum(a2_in[:1, :4]))
    t0 = time.perf_counter()
    out = f(a2_in)
    dmin = float(jnp.min(jnp.abs(jnp.diagonal(out.lu))))
    t1 = time.perf_counter()
    info = int(out.info)
    a2 = jax.device_put(_np.random.default_rng(7).standard_normal((n, n)) / 64)
    # residual via matvec columns, CHUNKED (see potrf_f64 note): P A x vs
    # L (U x) with triangles taken per row chunk
    xv = jax.device_put(rng.standard_normal((n, 4)))
    lu = out.lu
    cols = jnp.arange(n)
    def tri_mv(low):
        outs = []
        for i in range(0, n, 2048):
            blk = lu[i:i+2048]
            r = (cols[i:i+2048, None] > cols[None, :]) if low else (cols[i:i+2048, None] <= cols[None, :])
            outs.append(jnp.where(r, blk, 0) @ (xv if not low else ux))
        return jnp.concatenate(outs)
    ux = tri_mv(False)
    lv = ux + tri_mv(True)  # L (U x), unit diagonal
    pax = jnp.concatenate([a2[out.perm[i:i+2048]] @ xv for i in range(0, n, 2048)])
    resid = float(jnp.linalg.norm(lv - pax) / jnp.linalg.norm(pax))
    ok = info == 0 and resid < 1e-12
    emit(t1 - t0, 2.0 * n**3 / 3 / (t1 - t0) / 1e9,
         f"info={{info}} dmin={{dmin:.2e}} resid={{resid:.2e}}", ok)
"""


def main():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    only = None
    if len(sys.argv) > 2 and sys.argv[1] == "--only":
        only = set(sys.argv[2].split(","))
    out = os.path.join(root, "SWEEP_r05.json")
    results = []
    if only and os.path.exists(out):
        with open(out) as f:  # keep other routines' existing rows
            results = [
                r for r in json.load(f)["results"] if r["routine"] not in only
            ]
    for routine, n, tmo in CASES:
        if only and routine not in only:
            continue
        code = CHILD.format(root=root, routine=routine, n=n)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True,
                timeout=tmo,
            )
            line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
            if line:
                results.append(json.loads(line[-1][7:]))
            else:
                tail = (proc.stderr or "")[-300:]
                results.append({"routine": routine, "n": n, "ok": False,
                                "error": f"rc={proc.returncode} {tail}"})
        except subprocess.TimeoutExpired:
            results.append({"routine": routine, "n": n, "ok": False,
                            "error": f"timeout>{tmo}s"})
        print(json.dumps(results[-1]), flush=True)
        with open(out, "w") as f:
            json.dump(
                {"chip": "TPU v5e (1 chip, via tunnel)", "results": results},
                f, indent=1,
            )
    print(f"wrote {out}")
    _emit_obs_report(root, out, results)


def _emit_obs_report(root, out, results):
    """RunReport twin of the sweep file (slate_tpu.obs): schema-versioned,
    diffable against any prior sweep with
    ``python -m slate_tpu.obs.report --check`` (which also reads the
    legacy SWEEP_*.json shape directly)."""
    try:
        sys.path.insert(0, root)
        from slate_tpu.obs.report import write_report

        values = {
            f"{r['routine']}_n{r['n']}_gflops": float(r["gflops"])
            for r in results
            if r.get("ok") and isinstance(r.get("gflops"), (int, float))
        }
        rpath = out[:-5] + ".report.json" if out.endswith(".json") else out + ".report.json"
        write_report(rpath, name="northstar_sweep",
                     config={"chip": "TPU v5e (1 chip, via tunnel)"},
                     values=values)
        print(f"wrote {rpath}")
    except Exception as e:  # sweep results must never die on obs
        print(f"obs report failed: {e!r}")


if __name__ == "__main__":
    main()
