"""Lookahead RunReport evidence (ISSUE 3 acceptance artifact).

Runs the pipelined mesh kernels at Option.Lookahead depth 0 (strict
broadcast→update) and at the shipped default depth, through the
``slate_tpu.obs`` layer, and writes one RunReport per schedule plus a
verification summary:

- comm-audit BYTE totals per kernel must be identical across depths
  (lookahead moves when bytes travel, never how many) — hard-asserted;
- results must be bitwise identical — hard-asserted;
- wall/execute timings land in the reports for the
  ``python -m slate_tpu.obs.report --check NEW OLD`` regression gate
  (improved-or-neutral on the CPU mesh; the ICI overlap win needs a
  real multi-chip ring).

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/lookahead_report.py [--out artifacts/obs] [--n 256] [--nb 16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)


def run(depth: int, n: int, nb: int):
    """One full pass (gemm + potrf + trsm + pp-LU) at one depth; returns
    (values, outputs, comm_totals)."""
    from slate_tpu import obs
    from slate_tpu.parallel import from_dense, gemm_summa, make_mesh, to_dense
    from slate_tpu.parallel.comm import comm_audit
    from slate_tpu.parallel.dist_chol import potrf_dist
    from slate_tpu.parallel.dist_lu import getrf_pp_dist
    from slate_tpu.parallel.dist_trsm import trsm_dist
    from slate_tpu.types import MethodGemm, MethodTrsm, Op, Uplo

    mesh = make_mesh(2, 4, devices=jax.devices("cpu")[:8])
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n, n)))
    b = jnp.asarray(rng.standard_normal((n, n)))
    spd = a @ a.T + n * jnp.eye(n)
    ad = from_dense(a, mesh, nb)
    bd = from_dense(b, mesh, nb)
    spdd = from_dense(spd, mesh, nb, diag_pad_one=True)
    tril = from_dense(jnp.tril(a) + n * jnp.eye(n), mesh, nb, diag_pad_one=True)
    rhs = from_dense(b[:, : 2 * nb], mesh, nb)

    kernels = {
        "gemm_summa": lambda: gemm_summa(
            1.0, ad, bd, method=MethodGemm.GemmC, lookahead=depth
        ).tiles,
        "potrf_dist": lambda: potrf_dist(spdd, lookahead=depth)[0].tiles,
        "trsm_dist": lambda: trsm_dist(
            tril, rhs, Uplo.Lower, Op.NoTrans, method=MethodTrsm.TrsmB,
            lookahead=depth,
        ).tiles,
        "getrf_pp_dist": lambda: getrf_pp_dist(spdd, lookahead=depth)[0].tiles,
    }

    values, outputs, comm = {}, {}, {}
    with obs.force_enabled():
        for name, fn in kernels.items():
            jax.clear_caches()  # fresh trace: audit + compile both counted
            with comm_audit() as recs:
                t0 = time.perf_counter()
                out = fn()
                out.block_until_ready()
                wall_cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            out = fn()
            out.block_until_ready()
            execute = time.perf_counter() - t0  # warm: execute-only
            outputs[name] = np.asarray(out)
            comm[name] = int(sum(nb_ * m for _, nb_, m in recs))
            values[f"{name}_comm_bytes"] = comm[name]
            values[f"{name}_wall_cold_s"] = round(wall_cold, 4)
            values[f"{name}_execute_s"] = round(execute, 4)
    return values, outputs, comm


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="artifacts/obs")
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--nb", type=int, default=16)
    ap.add_argument("--depth", type=int, default=1,
                    help="lookahead depth to diff against strict (default 1)")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)

    from slate_tpu.obs.report import check_regression, write_report

    paths = {}
    results = {}
    for depth in (0, args.depth):
        values, outputs, comm = run(depth, args.n, args.nb)
        results[depth] = (values, outputs, comm)
        path = os.path.join(args.out, f"lookahead_la{depth}.report.json")
        write_report(
            path, name=f"lookahead_la{depth}",
            config={"n": args.n, "nb": args.nb, "grid": "2x4",
                    "lookahead": depth},
            values=values,
        )
        paths[depth] = path
        print(f"wrote {path}")

    v0, out0, comm0 = results[0]
    vd, outd, commd = results[args.depth]

    # hard gates: bytes identical, results bitwise identical
    assert comm0 == commd, f"comm bytes changed under lookahead: {comm0} vs {commd}"
    for name in out0:
        assert (out0[name] == outd[name]).all(), f"{name}: not bitwise equal"
    print(f"comm-audit bytes identical across depths: {comm0}")
    print("outputs bitwise identical across depths")

    # timing diff through the shipped regression gate (timings only:
    # comm bytes are asserted equal above, so they can never fail it)
    timing = lambda v: {k: x for k, x in v.items() if k.endswith("_s")}
    failures, compared = check_regression(timing(vd), timing(v0), threshold=1.5)
    print(f"obs.report gate: {compared} timing metrics compared, "
          f"{len(failures)} regression(s)")
    for f in failures:
        print("  " + f)
    summary = {
        "depths": [0, args.depth],
        "comm_bytes": comm0,
        "bitwise_identical": True,
        "timings_la0": timing(v0),
        f"timings_la{args.depth}": timing(vd),
        "regressions": failures,
    }
    spath = os.path.join(args.out, "lookahead_diff.json")
    with open(spath, "w") as f:
        json.dump(summary, f, indent=1)
    print(f"wrote {spath}")
    print(f"gate command: python -m slate_tpu.obs.report --check "
          f"{paths[args.depth]} {paths[0]}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
