"""ScaLAPACK drop-in symbol surface (native/scalapack_api_generated.cc ->
scalapack_bridge): call the Fortran-convention pd* symbols via ctypes the
way a re-linked ScaLAPACK application would (reference scalapack_api/)."""

import ctypes
import os

import numpy as np
import pytest

_LIB = os.path.join(os.path.dirname(__file__), "..", "native", "lib",
                    "libslatetpu_scalapack.so")


@pytest.fixture(scope="module")
def lib():
    if not os.path.exists(_LIB):
        pytest.skip("native scalapack shim not built")
    return ctypes.CDLL(_LIB)


def _iref(v):
    return ctypes.byref(ctypes.c_int32(v))


def _cref(ch):
    return ctypes.c_char_p(ch.encode())


def _desc(m, n, mb=32):
    # [dtype=1, ctxt, M, N, MB, NB, RSRC, CSRC, LLD] — single-rank grid
    d = np.array([1, 0, m, n, mb, mb, 0, 0, m], dtype=np.int32)
    return d, d.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _fptr(a):
    return a.ctypes.data_as(ctypes.c_void_p)


def test_pdgemm(lib):
    rng = np.random.default_rng(0)
    m, n, k = 48, 40, 56
    a = np.asfortranarray(rng.standard_normal((m, k)))
    b = np.asfortranarray(rng.standard_normal((k, n)))
    c = np.asfortranarray(np.zeros((m, n)))
    da, pda = _desc(m, k)
    db, pdb = _desc(k, n)
    dc, pdc = _desc(m, n)
    alpha = ctypes.byref(ctypes.c_double(2.0))
    beta = ctypes.byref(ctypes.c_double(0.0))
    lib.pdgemm_(_cref("N"), _cref("N"), _iref(m), _iref(n), _iref(k),
                alpha, _fptr(a), _iref(1), _iref(1), pda,
                _fptr(b), _iref(1), _iref(1), pdb,
                beta, _fptr(c), _iref(1), _iref(1), pdc)
    ref = 2.0 * (np.asarray(a) @ np.asarray(b))
    assert np.abs(c - ref).max() < 1e-11


def test_pdgemm_transposed_window(lib):
    rng = np.random.default_rng(1)
    # multiply a sub-window with op(A) = A^T (ia/ja offsets exercised)
    M, K = 64, 64
    abig = np.asfortranarray(rng.standard_normal((M, K)))
    m, n, k = 24, 16, 32
    b = np.asfortranarray(rng.standard_normal((k, n)))
    c = np.asfortranarray(np.zeros((m, n)))
    da, pda = _desc(M, K)
    db, pdb = _desc(k, n)
    dc, pdc = _desc(m, n)
    lib.pdgemm_(_cref("T"), _cref("N"), _iref(m), _iref(n), _iref(k),
                ctypes.byref(ctypes.c_double(1.0)),
                _fptr(abig), _iref(3), _iref(5), pda,
                _fptr(b), _iref(1), _iref(1), pdb,
                ctypes.byref(ctypes.c_double(0.0)),
                _fptr(c), _iref(1), _iref(1), pdc)
    sub = np.asarray(abig)[2 : 2 + k, 4 : 4 + m]  # (k, m), then transposed
    ref = sub.T @ np.asarray(b)
    assert np.abs(c - ref).max() < 1e-11


def test_pdgesv_and_pdgetrs(lib):
    rng = np.random.default_rng(2)
    n, nrhs = 64, 3
    a0 = rng.standard_normal((n, n))
    x_true = rng.standard_normal((n, nrhs))
    b0 = a0 @ x_true
    a = np.asfortranarray(a0)
    b = np.asfortranarray(b0)
    ipiv = np.zeros(n, np.int32)
    info = ctypes.c_int32(-7)
    da, pda = _desc(n, n)
    db, pdb = _desc(n, nrhs)
    lib.pdgesv_(_iref(n), _iref(nrhs), _fptr(a), _iref(1), _iref(1), pda,
                _fptr(ipiv), _fptr(b), _iref(1), _iref(1), pdb,
                ctypes.byref(info))
    assert info.value == 0
    assert np.abs(b - x_true).max() < 1e-9
    # LU + ipiv written in place: replay the solve through pdgetrs_
    b2 = np.asfortranarray(b0.copy())
    info2 = ctypes.c_int32(-7)
    lib.pdgetrs_(_cref("N"), _iref(n), _iref(nrhs),
                 _fptr(a), _iref(1), _iref(1), pda, _fptr(ipiv),
                 _fptr(b2), _iref(1), _iref(1), pdb, ctypes.byref(info2))
    assert info2.value == 0
    assert np.abs(b2 - x_true).max() < 1e-9


def test_pdpotrf_pdpotrs(lib):
    rng = np.random.default_rng(3)
    n = 48
    g = rng.standard_normal((n, n))
    a0 = g @ g.T + n * np.eye(n)
    a = np.asfortranarray(a0)
    info = ctypes.c_int32(-7)
    da, pda = _desc(n, n)
    lib.pdpotrf_(_cref("L"), _iref(n), _fptr(a), _iref(1), _iref(1), pda,
                 ctypes.byref(info))
    assert info.value == 0
    l = np.tril(np.asarray(a))
    assert np.abs(l @ l.T - a0).max() < 1e-10 * n
    x_true = rng.standard_normal((n, 2))
    b = np.asfortranarray(a0 @ x_true)
    db, pdb = _desc(n, 2)
    info2 = ctypes.c_int32(-7)
    lib.pdpotrs_(_cref("L"), _iref(n), _iref(2), _fptr(a), _iref(1), _iref(1),
                 pda, _fptr(b), _iref(1), _iref(1), pdb, ctypes.byref(info2))
    assert info2.value == 0
    assert np.abs(b - x_true).max() < 1e-9


def test_pdsyev_and_pzheev(lib):
    rng = np.random.default_rng(4)
    n = 40
    g = rng.standard_normal((n, n))
    a0 = (g + g.T) / 2
    a = np.asfortranarray(a0)
    w = np.zeros(n)
    z = np.asfortranarray(np.zeros((n, n)))
    da, pda = _desc(n, n)
    dz, pdz = _desc(n, n)
    work = np.zeros(4)
    info = ctypes.c_int32(-7)
    # standard two-call pattern: lwork=-1 is a workspace query
    lib.pdsyev_(_cref("V"), _cref("L"), _iref(n), _fptr(a), _iref(1), _iref(1),
                pda, _fptr(w), _fptr(z), _iref(1), _iref(1), pdz,
                _fptr(work), _iref(-1), ctypes.byref(info))
    assert info.value == 0
    lwork = int(work[0])
    assert lwork >= 1
    lib.pdsyev_(_cref("V"), _cref("L"), _iref(n), _fptr(a), _iref(1), _iref(1),
                pda, _fptr(w), _fptr(z), _iref(1), _iref(1), pdz,
                _fptr(work), _iref(lwork), ctypes.byref(info))
    assert info.value == 0
    assert np.abs(np.sort(w) - np.linalg.eigvalsh(a0)).max() < 1e-10
    zn = np.asarray(z)
    assert np.abs(a0 @ zn - zn * w).max() < 1e-9
    # complex drop-in (pzheev_ has the extra rwork/lrwork slots)
    ac0 = g + 1j * rng.standard_normal((n, n))
    ac0 = (ac0 + ac0.conj().T) / 2
    ac = np.asfortranarray(ac0.astype(np.complex128))
    wz = np.zeros(n)
    zz = np.asfortranarray(np.zeros((n, n), np.complex128))
    rwork = np.zeros(4)
    infoz = ctypes.c_int32(-7)
    lib.pzheev_(_cref("V"), _cref("L"), _iref(n), _fptr(ac), _iref(1), _iref(1),
                pda, _fptr(wz), _fptr(zz), _iref(1), _iref(1), pdz,
                _fptr(work), _iref(4), _fptr(rwork), _iref(4),
                ctypes.byref(infoz))
    assert infoz.value == 0
    assert np.abs(np.sort(wz) - np.linalg.eigvalsh(ac0)).max() < 1e-10


def test_pdlange(lib):
    rng = np.random.default_rng(5)
    m, n = 32, 24
    a = np.asfortranarray(rng.standard_normal((m, n)))
    da, pda = _desc(m, n)
    work = np.zeros(1)
    lib.pdlange_.restype = ctypes.c_double
    v = lib.pdlange_(_cref("I"), _iref(m), _iref(n), _fptr(a), _iref(1),
                     _iref(1), pda, _fptr(work))
    assert abs(v - np.abs(np.asarray(a)).sum(axis=1).max()) < 1e-12


def test_pstrsm_f32(lib):
    rng = np.random.default_rng(6)
    n, nrhs = 32, 4
    t = np.tril(rng.standard_normal((n, n))).astype(np.float32) + n * np.eye(n, dtype=np.float32)
    b0 = rng.standard_normal((n, nrhs)).astype(np.float32)
    b = np.asfortranarray(b0.copy())
    ta = np.asfortranarray(t)
    da, pda = _desc(n, n)
    db, pdb = _desc(n, nrhs)
    alpha = ctypes.byref(ctypes.c_float(1.0))
    lib.pstrsm_(_cref("L"), _cref("L"), _cref("N"), _cref("N"),
                _iref(n), _iref(nrhs), alpha,
                _fptr(ta), _iref(1), _iref(1), pda,
                _fptr(b), _iref(1), _iref(1), pdb)
    assert np.abs(t @ b - b0).max() < 1e-3


def test_pdgesvd_pdgels_pdsyrk(lib):
    rng = np.random.default_rng(7)
    m, n = 40, 32
    a0 = rng.standard_normal((m, n))
    a = np.asfortranarray(a0)
    s = np.zeros(min(m, n))
    u = np.asfortranarray(np.zeros((m, min(m, n))))
    vt = np.asfortranarray(np.zeros((min(m, n), n)))
    da, pda = _desc(m, n)
    du, pdu = _desc(m, min(m, n))
    dv, pdv = _desc(min(m, n), n)
    work = np.zeros(4)
    info = ctypes.c_int32(-7)
    lib.pdgesvd_(_cref("V"), _cref("V"), _iref(m), _iref(n),
                 _fptr(a), _iref(1), _iref(1), pda, _fptr(s),
                 _fptr(u), _iref(1), _iref(1), pdu,
                 _fptr(vt), _iref(1), _iref(1), pdv,
                 _fptr(work), _iref(4), ctypes.byref(info))
    assert info.value == 0
    sref = np.linalg.svd(a0, compute_uv=False)
    assert np.abs(s - sref).max() < 1e-10
    rec = (np.asarray(u) * s) @ np.asarray(vt)
    assert np.abs(rec - a0).max() < 1e-9

    # least squares: m > n overdetermined
    b0 = rng.standard_normal((m, 2))
    b = np.asfortranarray(b0.copy())
    db, pdb = _desc(m, 2)
    info2 = ctypes.c_int32(-7)
    lib.pdgels_(_cref("N"), _iref(m), _iref(n), _iref(2),
                _fptr(a := np.asfortranarray(a0)), _iref(1), _iref(1), pda,
                _fptr(b), _iref(1), _iref(1), pdb,
                _fptr(work), _iref(4), ctypes.byref(info2))
    assert info2.value == 0
    xref, *_ = np.linalg.lstsq(a0, b0, rcond=None)
    assert np.abs(np.asarray(b)[:n] - xref).max() < 1e-9

    # syrk: C = alpha A A^T (lower)
    k = 24
    aa = np.asfortranarray(rng.standard_normal((n, k)))
    c = np.asfortranarray(np.zeros((n, n)))
    dA, pdA = _desc(n, k)
    dC, pdC = _desc(n, n)
    lib.pdsyrk_(_cref("L"), _cref("N"), _iref(n), _iref(k),
                ctypes.byref(ctypes.c_double(1.5)),
                _fptr(aa), _iref(1), _iref(1), pdA,
                ctypes.byref(ctypes.c_double(0.0)),
                _fptr(c), _iref(1), _iref(1), pdC)
    ref = 1.5 * np.asarray(aa) @ np.asarray(aa).T
    assert np.abs(np.tril(c) - np.tril(ref)).max() < 1e-11


def test_pdsymm_pzhemm(lib):
    # scalapack_symm.cc / scalapack_hemm.cc drop-ins
    rng = np.random.default_rng(8)
    n = 32
    g = rng.standard_normal((n, n))
    sy = (g + g.T) / 2
    b0 = rng.standard_normal((n, n))
    c = np.asfortranarray(rng.standard_normal((n, n)))
    c0 = np.asarray(c).copy()
    da, pda = _desc(n, n)
    lib.pdsymm_(_cref("L"), _cref("L"), _iref(n), _iref(n),
                ctypes.byref(ctypes.c_double(2.0)),
                _fptr(a := np.asfortranarray(np.tril(sy))), _iref(1), _iref(1), pda,
                _fptr(b := np.asfortranarray(b0)), _iref(1), _iref(1), pda,
                ctypes.byref(ctypes.c_double(-1.0)),
                _fptr(c), _iref(1), _iref(1), pda)
    assert np.abs(np.asarray(c) - (2 * sy @ b0 - c0)).max() < 1e-11
    # hemm, complex, right side, upper triangle stored
    he = (g + 1j * rng.standard_normal((n, n)))
    he = (he + he.conj().T) / 2
    cz = np.asfortranarray(np.zeros((n, n), np.complex128))
    ab = np.array([2.0 + 0j])
    bz = np.array([0.0 + 0j])
    lib.pzhemm_(_cref("R"), _cref("U"), _iref(n), _iref(n),
                _fptr(ab),
                _fptr(az := np.asfortranarray(np.triu(he))), _iref(1), _iref(1), pda,
                _fptr(bzm := np.asfortranarray(b0.astype(np.complex128))), _iref(1), _iref(1), pda,
                _fptr(bz),
                _fptr(cz), _iref(1), _iref(1), pda)
    assert np.abs(np.asarray(cz) - 2 * b0 @ he).max() < 1e-11


def test_pdtrmm(lib):
    rng = np.random.default_rng(9)
    n, nrhs = 32, 5
    t = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    b0 = rng.standard_normal((n, nrhs))
    b = np.asfortranarray(b0.copy())
    da, pda = _desc(n, n)
    db, pdb = _desc(n, nrhs)
    lib.pdtrmm_(_cref("L"), _cref("L"), _cref("N"), _cref("N"),
                _iref(n), _iref(nrhs), ctypes.byref(ctypes.c_double(0.5)),
                _fptr(ta := np.asfortranarray(t)), _iref(1), _iref(1), pda,
                _fptr(b), _iref(1), _iref(1), pdb)
    assert np.abs(np.asarray(b) - 0.5 * t @ b0).max() < 1e-11


def test_pdsyr2k_pzher2k(lib):
    rng = np.random.default_rng(10)
    n, k = 32, 24
    a0 = rng.standard_normal((n, k))
    b0 = rng.standard_normal((n, k))
    c = np.asfortranarray(np.zeros((n, n)))
    dA, pdA = _desc(n, k)
    dC, pdC = _desc(n, n)
    lib.pdsyr2k_(_cref("L"), _cref("N"), _iref(n), _iref(k),
                 ctypes.byref(ctypes.c_double(1.0)),
                 _fptr(a := np.asfortranarray(a0)), _iref(1), _iref(1), pdA,
                 _fptr(b := np.asfortranarray(b0)), _iref(1), _iref(1), pdA,
                 ctypes.byref(ctypes.c_double(0.0)),
                 _fptr(c), _iref(1), _iref(1), pdC)
    ref = a0 @ b0.T + b0 @ a0.T
    assert np.abs(np.tril(c) - np.tril(ref)).max() < 1e-11
    # her2k: complex, alpha complex, beta REAL (zher2k signature)
    az = (a0 + 1j * rng.standard_normal((n, k))).astype(np.complex128)
    bz = (b0 + 1j * rng.standard_normal((n, k))).astype(np.complex128)
    cz = np.asfortranarray(np.zeros((n, n), np.complex128))
    alpha = np.array([1.0 + 0j])
    lib.pzher2k_(_cref("L"), _cref("N"), _iref(n), _iref(k),
                 _fptr(alpha),
                 _fptr(azf := np.asfortranarray(az)), _iref(1), _iref(1), pdA,
                 _fptr(bzf := np.asfortranarray(bz)), _iref(1), _iref(1), pdA,
                 ctypes.byref(ctypes.c_double(0.0)),
                 _fptr(cz), _iref(1), _iref(1), pdC)
    refz = az @ bz.conj().T + bz @ az.conj().T
    assert np.abs(np.tril(cz) - np.tril(refz)).max() < 1e-10


def test_pdposv_pdpotri(lib):
    rng = np.random.default_rng(11)
    n = 32
    g = rng.standard_normal((n, n))
    a0 = g @ g.T + n * np.eye(n)
    x_true = rng.standard_normal((n, 2))
    a = np.asfortranarray(a0)
    b = np.asfortranarray(a0 @ x_true)
    da, pda = _desc(n, n)
    db, pdb = _desc(n, 2)
    info = ctypes.c_int32(-7)
    lib.pdposv_(_cref("L"), _iref(n), _iref(2), _fptr(a), _iref(1), _iref(1),
                pda, _fptr(b), _iref(1), _iref(1), pdb, ctypes.byref(info))
    assert info.value == 0
    assert np.abs(np.asarray(b) - x_true).max() < 1e-9
    l = np.tril(np.asarray(a))  # factor written in place
    assert np.abs(l @ l.T - a0).max() < 1e-10 * n
    # potri from the factor: uplo triangle of A^-1
    info2 = ctypes.c_int32(-7)
    lib.pdpotri_(_cref("L"), _iref(n), _fptr(a), _iref(1), _iref(1), pda,
                 ctypes.byref(info2))
    assert info2.value == 0
    inv = np.tril(np.asarray(a))
    full = inv + np.tril(inv, -1).T
    assert np.abs(full @ a0 - np.eye(n)).max() < 1e-8


def test_pdgetri(lib):
    rng = np.random.default_rng(12)
    n = 32
    a0 = rng.standard_normal((n, n)) + n * np.eye(n)
    a = np.asfortranarray(a0)
    ipiv = np.zeros(n, np.int32)
    info = ctypes.c_int32(-7)
    da, pda = _desc(n, n)
    lib.pdgetrf_(_iref(n), _iref(n), _fptr(a), _iref(1), _iref(1), pda,
                 _fptr(ipiv), ctypes.byref(info))
    assert info.value == 0
    work = np.zeros(2)
    iwork = np.zeros(2, np.int32)
    # workspace query then real call (ScaLAPACK two-step contract)
    lib.pdgetri_(_iref(n), _fptr(a), _iref(1), _iref(1), pda, _fptr(ipiv),
                 _fptr(work), _iref(-1), _fptr(iwork), _iref(-1),
                 ctypes.byref(info))
    assert info.value == 0
    lib.pdgetri_(_iref(n), _fptr(a), _iref(1), _iref(1), pda, _fptr(ipiv),
                 _fptr(work), _iref(int(work[0])), _fptr(iwork),
                 _iref(int(iwork[0])), ctypes.byref(info))
    assert info.value == 0
    assert np.abs(np.asarray(a) @ a0 - np.eye(n)).max() < 1e-9


def test_pdsgesv_mixed(lib):
    # scalapack_gesv_mixed.cc drop-in: f32 factor + f64 refinement
    rng = np.random.default_rng(13)
    n, nrhs = 48, 2
    a0 = rng.standard_normal((n, n)) + n * np.eye(n)
    x_true = rng.standard_normal((n, nrhs))
    a = np.asfortranarray(a0)
    b = np.asfortranarray(a0 @ x_true)
    x = np.asfortranarray(np.zeros((n, nrhs)))
    ipiv = np.zeros(n, np.int32)
    it = ctypes.c_int32(-99)
    info = ctypes.c_int32(-7)
    da, pda = _desc(n, n)
    db, pdb = _desc(n, nrhs)
    lib.pdsgesv_(_iref(n), _iref(nrhs), _fptr(a), _iref(1), _iref(1), pda,
                 _fptr(ipiv), _fptr(b), _iref(1), _iref(1), pdb,
                 _fptr(x), _iref(1), _iref(1), pdb,
                 ctypes.byref(it), ctypes.byref(info))
    assert info.value == 0
    assert it.value != -99  # iteration count written (>=0, or <0 = fallback)
    assert np.abs(np.asarray(x) - x_true).max() < 1e-9
    # ipiv holds real pivots from the f32 factor
    assert ipiv.min() >= 1 and ipiv.max() <= n


def test_pdlansy_pzlanhe_pdlantr(lib):
    rng = np.random.default_rng(14)
    n = 32
    g = rng.standard_normal((n, n))
    sy = (g + g.T) / 2
    da, pda = _desc(n, n)
    work = np.zeros(1)
    lib.pdlansy_.restype = ctypes.c_double
    v = lib.pdlansy_(_cref("1"), _cref("L"), _iref(n),
                     _fptr(a := np.asfortranarray(np.tril(sy))), _iref(1),
                     _iref(1), pda, _fptr(work))
    assert abs(v - np.abs(sy).sum(axis=0).max()) < 1e-12
    he = g + 1j * rng.standard_normal((n, n))
    he = (he + he.conj().T) / 2
    lib.pzlanhe_.restype = ctypes.c_double
    v = lib.pzlanhe_(_cref("M"), _cref("U"), _iref(n),
                     _fptr(az := np.asfortranarray(np.triu(he))), _iref(1),
                     _iref(1), pda, _fptr(work))
    assert abs(v - np.abs(he).max()) < 1e-12
    t = np.tril(g)
    lib.pdlantr_.restype = ctypes.c_double
    v = lib.pdlantr_(_cref("I"), _cref("L"), _cref("N"), _iref(n), _iref(n),
                     _fptr(tf := np.asfortranarray(t)), _iref(1), _iref(1),
                     pda, _fptr(work))
    assert abs(v - np.abs(t).sum(axis=1).max()) < 1e-12
