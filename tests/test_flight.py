"""Flight recorder (ISSUE 7): disabled-mode trace identity, StepEvent
completeness + lookahead issue-order shifts, ScheduleModel bytes against
the analytic comm-audit volumes, FlightReport schema, and the overlap
metric's depth-0 / depth-1 contract on the 8-device CPU mesh."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu.obs import flight, schedule
from slate_tpu.parallel import from_dense, make_mesh, to_dense
from slate_tpu.parallel.comm import comm_audit, sched_audit
from slate_tpu.parallel.dist_chol import potrf_dist
from slate_tpu.parallel.dist_lu import getrf_nopiv_dist
from slate_tpu.parallel.dist_trsm import trsm_dist
from slate_tpu.parallel.summa import gemm_summa
from slate_tpu.types import MethodGemm, MethodTrsm, Op, Uplo

P_, Q_, N_, NB_ = 2, 4, 64, 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(P_, Q_, devices=jax.devices("cpu")[:8])


@pytest.fixture(scope="module")
def ops(mesh):
    rng = np.random.default_rng(7)
    a = rng.standard_normal((N_, N_)).astype(np.float32)
    b = rng.standard_normal((N_, N_)).astype(np.float32)
    spd = (a @ a.T / N_ + 2 * np.eye(N_)).astype(np.float32)
    dd = (np.tril(a) + N_ * np.eye(N_)
          + np.triu(rng.standard_normal((N_, N_)), 1)).astype(np.float32)
    tl = (np.tril(a) + N_ * np.eye(N_)).astype(np.float32)
    return {
        "a": from_dense(jnp.asarray(a), mesh, NB_),
        "b": from_dense(jnp.asarray(b), mesh, NB_),
        "spd": from_dense(jnp.asarray(spd), mesh, NB_, diag_pad_one=True),
        "lu": from_dense(jnp.asarray(dd), mesh, NB_, diag_pad_one=True),
        "tril": from_dense(jnp.asarray(tl), mesh, NB_, diag_pad_one=True),
    }


# ---------------------------------------------------------------------------
# Disabled mode: trace identity + activation contract
# ---------------------------------------------------------------------------


def _kernel_jaxprs(ops):
    """Jaxprs of every opted-in fused kernel, traced fresh."""
    from slate_tpu.parallel.dist_qr import geqrf_dist
    from slate_tpu.parallel.dist_twostage import he2hb_dist

    jax.clear_caches()
    out = {}
    out["summa"] = str(jax.make_jaxpr(
        lambda x, y: gemm_summa(1.0, x, y, method=MethodGemm.GemmC).tiles
    )(ops["a"], ops["b"]))
    out["potrf"] = str(jax.make_jaxpr(
        lambda x: potrf_dist(x)[0].tiles)(ops["spd"]))
    out["lu"] = str(jax.make_jaxpr(
        lambda x: getrf_nopiv_dist(x)[0].tiles)(ops["lu"]))
    out["trsm"] = str(jax.make_jaxpr(
        lambda x, y: trsm_dist(x, y, Uplo.Lower, Op.NoTrans,
                               method=MethodTrsm.TrsmB).tiles
    )(ops["tril"], ops["b"]))
    # the ISSUE 15 ops: the flight routing branch + the phase_scope
    # markers inside the shared step helpers must not change a jaxpr
    out["geqrf"] = str(jax.make_jaxpr(
        lambda x: geqrf_dist(x).fact.tiles)(ops["a"]))
    out["he2hb"] = str(jax.make_jaxpr(
        lambda x: he2hb_dist(x).band.tiles)(ops["spd"]))
    return out


def test_disabled_mode_is_trace_identical(ops):
    """With SLATE_TPU_OBS_DEEP unset and no scope open, the mesh kernels
    trace exactly as before: the routing branch and the phase_scope
    markers in comm.py must not change a single jaxpr — asserted by
    re-tracing after a full flight run exercised the whole machinery."""
    assert not flight.step_dispatch_active()
    before = _kernel_jaxprs(ops)
    with flight.flight_scope():
        potrf_dist(ops["spd"])  # exercise step dispatch end to end
    assert not flight.step_dispatch_active()
    after = _kernel_jaxprs(ops)
    assert before == after


def test_env_and_scope_activation(monkeypatch):
    monkeypatch.delenv(flight.DEEP_ENV, raising=False)
    assert not flight.step_dispatch_active()
    monkeypatch.setenv(flight.DEEP_ENV, "1")
    assert flight.step_dispatch_active()
    with flight.no_flight():
        assert not flight.step_dispatch_active()
    monkeypatch.setenv(flight.DEEP_ENV, "0")
    assert not flight.step_dispatch_active()
    with flight.flight_scope() as rec:
        assert flight.active_recorder() is rec


# ---------------------------------------------------------------------------
# Step-dispatch results are bitwise-identical to the fused kernels
# ---------------------------------------------------------------------------


def test_flight_results_bitwise(ops):
    ref_g = to_dense(gemm_summa(1.0, ops["a"], ops["b"],
                                method=MethodGemm.GemmC, lookahead=0))
    ref_p = to_dense(potrf_dist(ops["spd"], lookahead=0)[0])
    ref_l = to_dense(getrf_nopiv_dist(ops["lu"], lookahead=0)[0])
    ref_t = to_dense(trsm_dist(ops["tril"], ops["b"], Uplo.Lower,
                               Op.NoTrans, method=MethodTrsm.TrsmB,
                               lookahead=0))
    with flight.flight_scope():
        fl_g = to_dense(gemm_summa(1.0, ops["a"], ops["b"],
                                   method=MethodGemm.GemmC, lookahead=1))
        fl_p = to_dense(potrf_dist(ops["spd"], lookahead=1)[0])
        fl_l = to_dense(getrf_nopiv_dist(ops["lu"], lookahead=1)[0])
        fl_t = to_dense(trsm_dist(ops["tril"], ops["b"], Uplo.Lower,
                                  Op.NoTrans, method=MethodTrsm.TrsmB,
                                  lookahead=1))
    np.testing.assert_array_equal(np.asarray(fl_g), np.asarray(ref_g))
    np.testing.assert_array_equal(np.asarray(fl_p), np.asarray(ref_p))
    np.testing.assert_array_equal(np.asarray(fl_l), np.asarray(ref_l))
    np.testing.assert_array_equal(np.asarray(fl_t), np.asarray(ref_t))


# ---------------------------------------------------------------------------
# StepEvent completeness + the lookahead issue-order shift
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("depth", [0, 1, 2])
def test_summa_events_complete_and_issue_shifted(ops, depth):
    """Every k records its bcast and bulk phase, and depth d issues the
    step-(k+d) broadcast immediately before step k's update — the exact
    prefetch_bcast order, reproduced by the dispatch loop."""
    kt = ops["a"].nt
    with flight.flight_scope() as rec:
        gemm_summa(1.0, ops["a"], ops["b"], method=MethodGemm.GemmC,
                   lookahead=depth, bcast_impl="ring")
    rows = schedule.rows_from_events(rec.events)
    order = [(r["phase"], r["k"]) for r in rows]
    d = min(depth, kt)
    expected = [("bcast", j) for j in range(d)]
    for k in range(kt):
        if d and k + d < kt:
            expected.append(("bcast", k + d))
        if not d:
            expected.append(("bcast", k))
        expected.append(("bulk", k))
    assert order == expected
    # per-device events: one StepEvent per mesh coordinate per dispatch
    coords = {e.device_coord for e in rec.events}
    assert coords == {(r, c) for r in range(P_) for c in range(Q_)}


def test_potrf_events_every_k_has_all_three_phases(ops):
    nt = ops["spd"].nt
    with flight.flight_scope() as rec:
        potrf_dist(ops["spd"], lookahead=1)
    rows = schedule.rows_from_events(rec.events)
    by_phase = {}
    for r in rows:
        by_phase.setdefault(r["phase"], set()).add(r["k"])
    assert by_phase["panel"] == set(range(nt))
    assert by_phase["bcast"] == set(range(nt))
    assert by_phase["bulk"] == set(range(nt))
    # depth 1 issues step k's broadcast BEFORE step k-1's deferred bulk
    # (the LAST bulk event of step k-1: its narrow half legitimately runs
    # first, refreshing the column panel k reads)
    order = [(r["phase"], r["k"]) for r in rows]
    for k in range(1, nt):
        last_bulk = len(order) - 1 - order[::-1].index(("bulk", k - 1))
        assert order.index(("bcast", k)) < last_bulk


# ---------------------------------------------------------------------------
# ScheduleModel bytes == the analytic comm-audit volumes, per impl
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ["psum", "ring", "doubling"])
def test_schedule_model_summa_bytes_analytic(ops, impl):
    """The model's totals are the closed-form SUMMA broadcast volumes of
    tests/test_comm_audit.py — psum: kt*(mtl+ntl)*nb^2*itemsize; engine:
    kt*((q-1)*mtl + (p-1)*ntl)*nb^2*itemsize — and every byte lands in
    the bcast phase (SUMMA's only collectives are the panel fetches)."""
    a, b = ops["a"], ops["b"]
    kt, mtl, ntl = a.nt, a.mt // P_, b.nt // Q_
    itemsize = 4
    a_bytes, b_bytes = mtl * NB_ * NB_ * itemsize, ntl * NB_ * NB_ * itemsize
    if impl == "psum":
        expect = kt * (a_bytes + b_bytes)
    else:
        expect = kt * ((Q_ - 1) * a_bytes + (P_ - 1) * b_bytes)
    jax.clear_caches()
    with sched_audit() as recs:
        gemm_summa(1.0, a, b, method=MethodGemm.GemmC, lookahead=1,
                   bcast_impl=impl)
    model = schedule.ScheduleModel("summa", kt, P_, Q_, impl, list(recs))
    assert model.total_bytes == expect
    assert model.phase_bytes == {"bcast": expect}
    if impl != "psum":
        assert model.hop_records, "engine lowering must carry hop pairs"
        for _op, _nb, _m, _ph, _st, pairs in model.hop_records:
            assert all(isinstance(s, int) and isinstance(d, int)
                       for s, d in pairs)


@pytest.mark.parametrize("op", ["potrf", "lu"])
def test_schedule_model_matches_comm_audit_exactly(ops, op):
    """For the factor loops the model's grand total must equal the
    comm-audit channel's byte-for-byte (same trace, two channels), with
    the phase split covering every record."""
    mat = ops["spd"] if op == "potrf" else ops["lu"]
    run = potrf_dist if op == "potrf" else getrf_nopiv_dist
    jax.clear_caches()
    with comm_audit() as plain, sched_audit() as tagged:
        run(mat, lookahead=1, bcast_impl="ring")
    model = schedule.ScheduleModel(op, mat.nt, P_, Q_, "ring", list(tagged))
    audit_total = sum(nb * m for _, nb, m in plain)
    assert model.total_bytes == audit_total
    assert sum(model.phase_bytes.values()) == audit_total
    assert set(model.phase_bytes) <= {"panel", "bcast", "bulk"}
    # the broadcast half of the panel phase is tagged "bcast" (the
    # phase_scope marker inside _chol_panel / _nopiv_panel)
    assert model.phase_bytes.get("bcast", 0) > 0
    assert model.phase_bytes.get("panel", 0) > 0  # the diag-tile hops


def test_flight_measured_bytes_match_phase_audit(ops):
    """The recorder's per-event byte shares sum back to the per-phase
    audited totals: kt * per-step phase bytes (the unbucketed per-step
    programs repeat the same shapes every step)."""
    a, b = ops["a"], ops["b"]
    kt, mtl, ntl = a.nt, a.mt // P_, b.nt // Q_
    with flight.flight_scope() as rec:
        gemm_summa(1.0, a, b, method=MethodGemm.GemmC, lookahead=1,
                   bcast_impl="ring")
    rows = schedule.rows_from_events(rec.events)
    got = sum(r["bytes"] for r in rows if r["phase"] == "bcast")
    expect = kt * ((Q_ - 1) * mtl + (P_ - 1) * ntl) * NB_ * NB_ * 4
    assert got == expect


# ---------------------------------------------------------------------------
# FlightReport schema + the overlap metric contract
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def potrf_report(mesh):
    return flight.run_flight("potrf", n=N_, nb=NB_, depth=1,
                             bcast_impl="ring", mesh=mesh)


def test_flight_report_schema(potrf_report, tmp_path):
    rep = potrf_report
    assert flight.validate_flight_report(rep) == []
    # round-trips through JSON
    path = str(tmp_path / "f.flight.json")
    flight.write_flight_report(path, rep)
    with open(path) as f:
        assert flight.validate_flight_report(json.load(f)) == []
    # mutations are caught
    bad = dict(rep, events=[])
    assert flight.validate_flight_report(bad)
    bad = json.loads(json.dumps(rep))
    bad["sched"]["overlap_eff"] = 1.5
    assert any("overlap_eff" in e for e in flight.validate_flight_report(bad))
    bad2 = json.loads(json.dumps(rep))
    bad2["events"][0]["phase"] = "mystery"
    assert flight.validate_flight_report(bad2)


def test_overlap_eff_bounds_and_depth_contrast(potrf_report):
    sched = potrf_report["sched"]
    assert 0.0 <= sched["overlap_eff"] <= 1.0
    assert sched["overlap_eff"] > 0.0  # depth 1 hides some broadcast
    # strict schedule: overlap 0, every comm second exposed
    assert sched["overlap_eff_la0"] == 0.0
    assert sched["exposed_comm_s"] <= sched["total_comm_s"]
    assert sched["critical_path_s"] == pytest.approx(
        sched["total_compute_s"] + sched["exposed_comm_s"])


def test_depth0_exposes_all_comm(ops):
    with flight.flight_scope() as rec:
        potrf_dist(ops["spd"], lookahead=0)
    sched = schedule.analyze(schedule.rows_from_events(rec.events), 0)
    assert sched["overlap_eff"] == 0.0
    assert sched["exposed_comm_s"] == pytest.approx(sched["total_comm_s"])


def test_report_check_gates_flight_reports(potrf_report, tmp_path):
    """obs.report --check reads FlightReports: identical pair passes; a
    halved overlap_eff (higher-is-better) fails."""
    from slate_tpu.obs import report

    new = str(tmp_path / "new.flight.json")
    old = str(tmp_path / "old.flight.json")
    flight.write_flight_report(new, potrf_report)
    worse = json.loads(json.dumps(potrf_report))
    worse["values"]["sched.overlap_eff"] = (
        potrf_report["values"]["sched.overlap_eff"] / 4)
    flight.write_flight_report(old, potrf_report)
    assert report.main(["--check", new, old, "--threshold", "3"]) == 0
    flight.write_flight_report(new, worse)
    # worse as NEW against good OLD: overlap_eff fell 4x beyond 3x
    assert report.main(["--check", new, old, "--threshold", "3"]) == 1


def test_flight_perfetto_gantt(potrf_report):
    """Per-device tracks + broadcast hop flow arrows validate."""
    from slate_tpu.obs import perfetto

    tr = perfetto.flight_chrome_trace(potrf_report["events"],
                                      potrf_report["hop_events"],
                                      grid=(P_, Q_))
    assert perfetto.validate_chrome_trace(tr) == []
    evs = tr["traceEvents"]
    tids = {e["tid"] for e in evs if e.get("ph") == "X"}
    assert len(tids) == P_ * Q_
    names = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert f"mesh(0,0)" in names and f"mesh({P_-1},{Q_-1})" in names
    starts = [e for e in evs if e.get("ph") == "s"]
    ends = [e for e in evs if e.get("ph") == "f"]
    assert starts and len(starts) == len(ends)
    # flow arrows join distinct device tracks
    assert any(s["tid"] != t["tid"] for s, t in zip(starts, ends))


def test_analyze_depth2_never_double_counts_hiding():
    """Each second of bulk work hides at most one second of broadcast:
    at depth 2 the hide windows of bcast k and k+1 overlap on bulk k-1,
    and the shared capacity must be consumed, not credited twice."""
    # two broadcasts of 1.0 s each, one eligible bulk of 1.0 s issued
    # after both — naive per-broadcast summing would hide 2.0 s
    rows = [
        {"op": "x", "k": 2, "phase": "bcast", "t0": 0.0, "t1": 1.0,
         "dur": 1.0, "bytes": 0.0, "flops": 0.0},
        {"op": "x", "k": 3, "phase": "bcast", "t0": 1.0, "t1": 2.0,
         "dur": 1.0, "bytes": 0.0, "flops": 0.0},
        {"op": "x", "k": 1, "phase": "bulk", "t0": 2.0, "t1": 3.0,
         "dur": 1.0, "bytes": 0.0, "flops": 0.0},
    ]
    out = schedule.analyze(rows, 2)
    # bulk k=1 lies in both windows ([0,2) and [1,3)) but its 1.0 s can
    # only cover one of the 2.0 comm seconds
    assert out["exposed_comm_s"] == pytest.approx(1.0)
    assert out["overlap_eff"] == pytest.approx(0.5)
    assert out["critical_path_s"] == pytest.approx(2.0)


def test_backward_trsm_hop_rotation_uses_logical_root(ops):
    """Backward solves (Upper/NoTrans) walk panels last-to-first: hop
    events must carry root_k = nt-1-s so the Perfetto arrows rotate by
    the true broadcast owner, not the dispatch index."""
    up = ops["tril"]
    upper = from_dense(to_dense(up).T, up.mesh, NB_, diag_pad_one=True)
    with flight.flight_scope() as rec:
        trsm_dist(upper, ops["b"], uplo=Uplo.Upper, op=Op.NoTrans,
                  method=MethodTrsm.TrsmB, lookahead=1, bcast_impl="ring")
    nt = up.nt
    hops = [h for h in rec.hop_events if h["op"] == "trsm"]
    assert hops, "ring trsm flight must record hop events"
    assert all(h["root_k"] == nt - 1 - h["k"] for h in hops), hops[:4]
    # forward solve: logical root == dispatch index
    with flight.flight_scope() as rec_f:
        trsm_dist(ops["tril"], ops["b"], uplo=Uplo.Lower, op=Op.NoTrans,
                  method=MethodTrsm.TrsmB, lookahead=1, bcast_impl="ring")
    assert all(h["root_k"] == h["k"] for h in rec_f.hop_events
               if h["op"] == "trsm")


def test_report_check_ignore_glob(potrf_report, tmp_path):
    """--ignore GLOB excludes machine-dependent wall-clock keys from the
    gate while the byte/eff keys still compare (the CI flight gate)."""
    from slate_tpu.obs import report

    new = str(tmp_path / "new.flight.json")
    old = str(tmp_path / "old.flight.json")
    slow = json.loads(json.dumps(potrf_report))
    for key in list(slow["values"]):
        if key.endswith("_s"):
            slow["values"][key] *= 100.0  # a 100x slower runner
    flight.write_flight_report(new, slow)
    flight.write_flight_report(old, potrf_report)
    # gated bare: the timing keys fail
    assert report.main(["--check", new, old, "--threshold", "4"]) == 1
    # gated as CI does: timings ignored, deterministic keys still pass
    assert report.main(["--check", new, old, "--threshold", "4",
                        "--ignore", "sched.*_s"]) == 0
    # but a byte regression is NOT maskable by the timing ignore
    slow["values"]["sched.model_bytes"] *= 100.0
    flight.write_flight_report(new, slow)
    assert report.main(["--check", new, old, "--threshold", "4",
                        "--ignore", "sched.*_s"]) == 1


# ---------------------------------------------------------------------------
# QR / eig chains (ISSUE 15): flight coverage for geqrf + he2hb
# ---------------------------------------------------------------------------


def test_geqrf_flight_bitwise_and_bytes(ops):
    """The per-step CAQR dispatch (panel -> three rooted column
    broadcasts -> trailing/tree update) is bitwise-identical to the
    fused kernel across the WHOLE multi-array result, and the recorded
    bcast-phase bytes equal the closed-form broadcast volume: per step
    three column broadcasts of (mfl, nb) + (mfl, nb) + (nb, nb), at
    (q-1)x the payload under the ring engine."""
    from slate_tpu.parallel.dist_qr import geqrf_dist

    ref = geqrf_dist(ops["a"], bcast_impl="ring")
    with flight.flight_scope() as rec:
        fl = geqrf_dist(ops["a"], bcast_impl="ring")
    for name in ("tloc", "treev", "treet"):
        np.testing.assert_array_equal(np.asarray(getattr(ref, name)),
                                      np.asarray(getattr(fl, name)),
                                      err_msg=name)
    np.testing.assert_array_equal(np.asarray(to_dense(ref.fact)),
                                  np.asarray(to_dense(fl.fact)))
    rows = schedule.rows_from_events(rec.events)
    nt, mtl = ops["a"].nt, ops["a"].mt // P_
    mfl = mtl * NB_
    got = sum(r["bytes"] for r in rows if r["phase"] == "bcast")
    expect = nt * (Q_ - 1) * (2 * mfl * NB_ + NB_ * NB_) * 4
    assert got == expect
    # strict schedule: every phase present per step, overlap reads 0
    by_phase = {}
    for r in rows:
        by_phase.setdefault(r["phase"], set()).add(r["k"])
    assert by_phase["panel"] >= set(range(nt))
    assert by_phase["bcast"] == set(range(nt))
    assert by_phase["bulk"] == set(range(nt))
    assert schedule.analyze(rows, 0)["overlap_eff"] == 0.0


@pytest.mark.parametrize("impl", ["psum", "ring"])
def test_schedule_model_qr_he2hb_bytes_analytic(mesh, impl):
    """The acceptance bound (ISSUE 15): the geqrf/he2hb ScheduleModel
    per-step wire bytes equal the comm-audit analytic volumes under
    psum AND ring.  Pure make_jaxpr traces at a shape no other test
    compiles — no clear_caches, no execution."""
    from slate_tpu.parallel.dist_qr import geqrf_dist
    from slate_tpu.parallel.dist_twostage import he2hb_dist

    n, nb = 80, 8  # pads to nt = 12 — unique in this suite
    rng = np.random.default_rng(11)
    a = rng.standard_normal((n, n)).astype(np.float32)
    ad = from_dense(jnp.asarray(a), mesh, nb)
    nt, mtl, ntl = ad.nt, ad.mt // P_, ad.nt // Q_
    mfl, isz = mtl * nb, 4
    eng = (Q_ - 1) if impl != "psum" else 1

    with comm_audit() as plain, sched_audit() as tagged:
        jax.make_jaxpr(
            lambda t: geqrf_dist(
                from_dense_like(t, ad), bcast_impl=impl).fact.tiles
        )(ad.tiles)
    model = schedule.ScheduleModel("geqrf", nt, P_, Q_, impl, list(tagged))
    audit_total = sum(b * m for _, b, m in plain)
    assert model.total_bytes == audit_total
    # closed forms: bcast = 3 rooted column broadcasts (r_a, V, T);
    # bulk = the tree all_gathers (R block + the gathered C row slices)
    bcast = nt * eng * (2 * mfl * nb + nb * nb) * isz
    bulk = nt * (nb * nb + nb * ntl * nb) * isz
    assert model.phase_bytes["bcast"] == bcast
    assert model.phase_bytes["bulk"] == bulk

    spd = (a @ a.T / n + 2 * np.eye(n)).astype(np.float32)
    sd = from_dense(jnp.asarray(spd), mesh, nb)
    nsteps = 9  # _he2hb_panel_count(80, 8)
    with comm_audit() as hplain, sched_audit() as htagged:
        jax.make_jaxpr(
            lambda t: he2hb_dist(
                from_dense_like(t, sd), bcast_impl=impl).band.tiles
        )(sd.tiles)
    hmodel = schedule.ScheduleModel("he2hb", nsteps, P_, Q_, impl,
                                    list(htagged))
    haudit = sum(b * m for _, b, m in hplain)
    assert hmodel.total_bytes == haudit
    # bcast = the rooted panel-column broadcast + the row gather into
    # global order; bulk = the Y psum over 'q' + the Y row gather
    pan = mfl * nb * isz
    assert hmodel.phase_bytes["bcast"] == nsteps * (eng * pan + pan)
    assert hmodel.phase_bytes["bulk"] == nsteps * 2 * pan


def from_dense_like(tiles, like):
    from slate_tpu.parallel.dist import DistMatrix

    return DistMatrix(tiles=tiles, m=like.m, n=like.n, nb=like.nb,
                      mesh=like.mesh, diag_pad=like.diag_pad)


@pytest.mark.slow
def test_qr_he2hb_flight_reports_full():
    """The full QR/he2hb flight sweep (ISSUE 15, -m slow): run_flight
    under psum and ring — schema-valid FlightReports, model bytes ==
    measured bytes, residuals clean, and the he2hb per-step dispatch
    bitwise vs the fused kernel."""
    from slate_tpu.parallel.dist_twostage import he2hb_dist

    mesh = make_mesh(P_, Q_, devices=jax.devices("cpu")[:8])
    for op in ("geqrf", "he2hb"):
        for impl in ("psum", "ring"):
            rep = flight.run_flight(op, n=N_, nb=NB_, depth=1,
                                    bcast_impl=impl, mesh=mesh)
            assert flight.validate_flight_report(rep) == []
            assert rep["config"]["lookahead"] == 0  # strict schedule
            assert rep["sched"]["overlap_eff"] == 0.0
            assert rep["values"]["resid"] < 1e-3
            assert (rep["sched"]["measured_bytes"]
                    == rep["model"]["total_bytes"])
    rng = np.random.default_rng(3)
    g = rng.standard_normal((N_, N_)).astype(np.float32)
    spd = (g @ g.T / N_ + 2 * np.eye(N_)).astype(np.float32)
    sd = from_dense(jnp.asarray(spd), mesh, NB_)
    ref = he2hb_dist(sd, bcast_impl="ring")
    with flight.flight_scope():
        fl = he2hb_dist(sd, bcast_impl="ring")
    for name in ("vq", "tq"):
        np.testing.assert_array_equal(np.asarray(getattr(ref, name)),
                                      np.asarray(getattr(fl, name)),
                                      err_msg=name)
    np.testing.assert_array_equal(np.asarray(to_dense(ref.band)),
                                  np.asarray(to_dense(fl.band)))


@pytest.mark.parametrize("trans_op", [Op.Trans, Op.ConjTrans])
def test_flight_trsm_trans_path_bitwise(ops, trans_op):
    """The flight trsm driver re-implements _trsm_jit's transpose-gather
    fetch (op != NoTrans reads a ROW of A and transposes); pin it
    bitwise against the fused kernel so a future dist_trsm fix can't
    silently drift the step-dispatch twin."""
    ref = to_dense(trsm_dist(ops["tril"], ops["b"], Uplo.Lower, trans_op,
                             method=MethodTrsm.TrsmB, lookahead=0))
    with flight.flight_scope() as rec:
        fl = to_dense(trsm_dist(ops["tril"], ops["b"], Uplo.Lower, trans_op,
                                method=MethodTrsm.TrsmB, lookahead=1))
    np.testing.assert_array_equal(np.asarray(fl), np.asarray(ref))
    # Lower/Trans is an effective-upper BACKWARD solve: logical roots
    # must run last-to-first
    trsm_hops = [h for h in rec.hop_events if h["op"] == "trsm"]
    nt = ops["tril"].nt
    assert all(h["root_k"] == nt - 1 - h["k"] for h in trsm_hops)
