"""Distributed-layer tests on the forced 8-device CPU mesh.

Mirrors the reference's oversubscribed single-node MPI CI (Jenkinsfile-mpi):
shard_map kernels run over a real (p, q) Mesh of XLA:CPU devices, so every
psum/all_gather in the SUMMA/potrf/LU/trsm kernels executes as an actual
collective; numerical gates are the 3-eps style residuals of test/ (§4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.parallel import (
    DistMatrix,
    from_dense,
    gemm_mesh,
    gemm_summa,
    gesv_nopiv_mesh,
    make_mesh,
    posv_mesh,
    potrf_dist,
    potrf_mesh,
    to_dense,
    trsm_dist,
)
from slate_tpu.types import Diag, Op, Uplo

from conftest import cpu_devices


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def mesh22():
    return make_mesh(2, 2, devices=cpu_devices(4))


def _rand(rng, m, n, dtype=np.float64):
    a = rng.standard_normal((m, n))
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal((m, n))
    return jnp.asarray(a.astype(dtype))


def _spd(rng, n, dtype=np.float64):
    a = _rand(rng, n, n, dtype)
    return a @ jnp.conj(a).T + n * jnp.eye(n, dtype=dtype)


def test_roundtrip(rng):
    mesh = mesh24()
    a = _rand(rng, 100, 68)
    d = from_dense(a, mesh, nb=16)
    assert d.mt % 4 == 0 and d.nt % 4 == 0  # lcm(2,4) padding
    np.testing.assert_array_equal(np.asarray(to_dense(d)), np.asarray(a))


def test_roundtrip_diag_pad(rng):
    mesh = mesh24()
    a = _spd(rng, 50)
    d = from_dense(a, mesh, nb=16, diag_pad_one=True)
    back = to_dense(d)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(a))


@pytest.mark.parametrize("dims", [(96, 96, 96), (100, 52, 68), (32, 96, 16)])
def test_gemm_summa(rng, dims):
    m, n, k = dims
    mesh = mesh24()
    a, b = _rand(rng, m, k), _rand(rng, k, n)
    c = gemm_mesh(1.0, a, b, mesh, nb=16)
    ref = np.asarray(a) @ np.asarray(b)
    np.testing.assert_allclose(np.asarray(c), ref, rtol=1e-12, atol=1e-10)


def test_gemm_summa_beta(rng):
    mesh = mesh22()
    a, b, c0 = _rand(rng, 64, 32), _rand(rng, 32, 48), _rand(rng, 64, 48)
    c = gemm_mesh(2.0, a, b, mesh, nb=16, beta=-1.0, c=c0)
    ref = 2.0 * np.asarray(a) @ np.asarray(b) - np.asarray(c0)
    np.testing.assert_allclose(np.asarray(c), ref, rtol=1e-12, atol=1e-10)


def test_gemm_summa_stationary_a(rng):
    # GemmA (src/gemmA.cc): stationary-A schedule must agree with GemmC
    # and numpy on thin-C shapes, where select_gemm_method auto-picks it
    from slate_tpu.types import MethodGemm, select_gemm_method

    mesh = mesh24()
    m, k, n = 96, 128, 16
    a, b, c0 = _rand(rng, m, k), _rand(rng, k, n), _rand(rng, m, n)
    ad, bd = from_dense(a, mesh, 8), from_dense(b, mesh, 8)
    cd = from_dense(c0, mesh, 8)
    ref = 2.0 * np.asarray(a) @ np.asarray(b) - np.asarray(c0)
    outs = {
        meth: np.asarray(to_dense(gemm_summa(2.0, ad, bd, -1.0, cd, method=meth)))
        for meth in (MethodGemm.GemmA, MethodGemm.GemmC)
    }
    for meth, out in outs.items():
        np.testing.assert_allclose(out, ref, rtol=1e-12, atol=1e-10, err_msg=str(meth))
    # thin output panel auto-selects the stationary-A path (method.hh:35-45)
    assert select_gemm_method(m // 8, n // 8, k // 8) == MethodGemm.GemmA


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("op", [Op.NoTrans, Op.Trans, Op.ConjTrans])
def test_trsm_dist_stationary_a(rng, uplo, op):
    # TrsmA (src/trsmA.cc): stationary-A schedule, thin RHS, ALL ops —
    # the transposed ops route partials across mesh rows (r5 item 7)
    from slate_tpu.types import MethodTrsm, Side, select_trsm_method

    mesh = mesh24()
    n, nrhs = 96, 8
    # complex operands so ConjTrans is distinguishable from Trans
    t = np.tril(np.asarray(_rand(rng, n, n, np.complex128))) + n * np.eye(n)
    if uplo == Uplo.Upper:
        t = t.T
    b = _rand(rng, n, nrhs, np.complex128)
    ad = from_dense(jnp.asarray(t), mesh, nb=8, diag_pad_one=True)
    bd = from_dense(b, mesh, nb=8)
    x = to_dense(trsm_dist(ad, bd, uplo, op, method=MethodTrsm.TrsmA))
    opt = {Op.NoTrans: t, Op.Trans: t.T, Op.ConjTrans: t.conj().T}[op]
    err = np.linalg.norm(opt @ np.asarray(x) - np.asarray(b)) / np.linalg.norm(np.asarray(b))
    assert err < 1e-12
    assert select_trsm_method(Side.Left, n // 8, nrhs // 8) == MethodTrsm.TrsmA


@pytest.mark.parametrize("n", [64, 100])
def test_potrf_dist(rng, n):
    mesh = mesh24()
    a = _spd(rng, n)
    l, info = potrf_mesh(a, mesh, nb=16)
    assert int(info) == 0
    ld = np.tril(np.asarray(to_dense(l)))
    resid = np.linalg.norm(ld @ ld.T - np.asarray(a)) / np.linalg.norm(np.asarray(a))
    assert resid < 1e-13


def test_potrf_dist_complex(rng):
    mesh = mesh22()
    a = _spd(rng, 48, np.complex128)
    l, info = potrf_mesh(a, mesh, nb=16)
    assert int(info) == 0
    ld = np.tril(np.asarray(to_dense(l)))
    resid = np.linalg.norm(ld @ ld.conj().T - np.asarray(a)) / np.linalg.norm(np.asarray(a))
    assert resid < 1e-13


def test_potrf_dist_not_spd(rng):
    mesh = mesh22()
    a = jnp.eye(32, dtype=jnp.float64)
    a = a.at[10, 10].set(-1.0)
    _, info = potrf_mesh(a, mesh, nb=8)
    # failure is in tile 1 (global rows 8..15, bad pivot at 10): info lands
    # in (8, 11] — tile-start granularity, see dist_chol.py info note
    assert 8 < int(info) <= 11


def test_posv_mesh(rng):
    mesh = mesh24()
    n, nrhs = 80, 24
    a = _spd(rng, n)
    x_true = _rand(rng, n, nrhs)
    b = jnp.asarray(np.asarray(a) @ np.asarray(x_true))
    x, info = posv_mesh(a, b, mesh, nb=16)
    assert int(info) == 0
    err = np.linalg.norm(np.asarray(x) - np.asarray(x_true)) / np.linalg.norm(np.asarray(x_true))
    assert err < 1e-10


def test_gesv_nopiv_mesh(rng):
    mesh = mesh24()
    n, nrhs = 96, 8
    # diagonally dominant => no-pivot LU is stable (gesv_nopiv contract)
    a = _rand(rng, n, n) + n * jnp.eye(n, dtype=jnp.float64)
    x_true = _rand(rng, n, nrhs)
    b = jnp.asarray(np.asarray(a) @ np.asarray(x_true))
    x, info = gesv_nopiv_mesh(a, b, mesh, nb=16)
    assert int(info) == 0
    err = np.linalg.norm(np.asarray(x) - np.asarray(x_true)) / np.linalg.norm(np.asarray(x_true))
    assert err < 1e-10


@pytest.mark.parametrize("uplo,op", [
    (Uplo.Lower, Op.NoTrans),
    (Uplo.Lower, Op.ConjTrans),
    (Uplo.Upper, Op.NoTrans),
    (Uplo.Upper, Op.Trans),
])
def test_trsm_dist(rng, uplo, op):
    mesh = mesh22()
    n, nrhs = 64, 16
    t = np.tril(np.asarray(_rand(rng, n, n))) + n * np.eye(n)
    if uplo == Uplo.Upper:
        t = t.T
    b = _rand(rng, n, nrhs)
    ad = from_dense(jnp.asarray(t), mesh, nb=16, diag_pad_one=True)
    bd = from_dense(b, mesh, nb=16)
    x = to_dense(trsm_dist(ad, bd, uplo, op))
    opt = t.T if op != Op.NoTrans else t
    err = np.linalg.norm(opt @ np.asarray(x) - np.asarray(b)) / np.linalg.norm(np.asarray(b))
    assert err < 1e-12


def test_gesv_tntpiv_mesh(rng):
    # general NON-diagonally-dominant matrix: real pivoting must happen
    from slate_tpu.parallel import gesv_tntpiv_mesh

    mesh = mesh24()
    for n, nb in [(96, 16), (130, 16)]:
        a = np.asarray(_rand(rng, n, n))
        b = np.asarray(_rand(rng, n, 3))
        x, info = gesv_tntpiv_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb=nb)
        x = np.asarray(x)
        resid = np.abs(a @ x - b).max() / (np.abs(a).max() * np.abs(x).max() * n)
        assert int(info) == 0
        assert resid < 1e-13, (n, nb, resid)


def test_getrf_tntpiv_dist_factor(rng):
    # PA = LU at the factor level, incl. cross-shard row motion
    from slate_tpu.parallel import getrf_tntpiv_mesh

    mesh = mesh24()
    n, nb = 64, 16
    a = np.asarray(_rand(rng, n, n))
    lu, perm, info = getrf_tntpiv_mesh(jnp.asarray(a), mesh, nb=nb)
    lud, perm = np.asarray(to_dense(lu)), np.asarray(perm)
    l = np.tril(lud, -1) + np.eye(n)
    u = np.triu(lud)
    ap = np.pad(a, ((0, perm.shape[0] - n), (0, 0)))[perm][:n]
    assert int(info) == 0
    assert np.abs(ap - l @ u).max() < 1e-12
    assert sorted(perm.tolist()) == list(range(perm.shape[0]))


def test_permute_rows_dist(rng):
    from slate_tpu.parallel import permute_rows_dist

    mesh = mesh22()
    n = 64
    b = np.asarray(_rand(rng, n, 5))
    bd = from_dense(jnp.asarray(b), mesh, nb=16)
    mglob = bd.mt * bd.nb
    perm = np.random.default_rng(3).permutation(mglob)
    out = np.asarray(to_dense(permute_rows_dist(bd, jnp.asarray(perm))))
    bp = np.pad(b, ((0, mglob - n), (0, 0)))[perm][:n]
    np.testing.assert_allclose(out, bp, atol=0)


def test_gesv_tntpiv_mesh_zero_leading_pivot(rng):
    # review-found bug class: winners already inside block k must be
    # position-tracked through earlier swaps; a[0,0]=0 makes the tournament
    # reorder within the leading block (win=[1,0]-style), which the naive
    # original-position swap sim cancelled out, leaving the zero pivot
    from slate_tpu.parallel import gesv_tntpiv_mesh

    mesh = mesh24()
    n, nb = 64, 16
    a = np.asarray(_rand(rng, n, n)).copy()
    a[0, 0] = 0.0
    a[1, 0] = 5.0
    b = np.asarray(_rand(rng, n, 2))
    x, info = gesv_tntpiv_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb=nb)
    x = np.asarray(x)
    assert int(info) == 0
    assert np.isfinite(x).all()
    resid = np.abs(a @ x - b).max() / (np.abs(a).max() * np.abs(x).max() * n)
    assert resid < 1e-13, resid


def test_gesv_tntpiv_mesh_near_singular_column(rng):
    # column 0 mostly zeros: pivot quality must not silently degrade
    from slate_tpu.parallel import gesv_tntpiv_mesh

    mesh = mesh24()
    n, nb = 64, 16
    a = np.asarray(_rand(rng, n, n)).copy()
    a[:, 0] = 0.0
    a[40, 0] = 3.0  # the single viable pivot lives deep in another shard
    b = np.asarray(_rand(rng, n, 2))
    x, info = gesv_tntpiv_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb=nb)
    x = np.asarray(x)
    assert int(info) == 0
    resid = np.abs(a @ x - b).max() / (np.abs(a).max() * np.abs(x).max() * n)
    assert resid < 1e-13, resid


def test_caqr_orthogonality_and_reconstruction(rng):
    # Q Q^H b = b (implicit-Q orthogonality) and A = Q R via unmqr replay
    from slate_tpu.parallel import geqrf_dist, unmqr_dist

    mesh = mesh24()
    m, n, nb = 96, 64, 16
    a = np.asarray(_rand(rng, m, n))
    f = geqrf_dist(from_dense(jnp.asarray(a), mesh, nb))
    b = np.asarray(_rand(rng, m, 3))
    bd = from_dense(jnp.asarray(b), mesh, nb)
    qhb = unmqr_dist(f, bd, Op.ConjTrans)
    back = np.asarray(to_dense(unmqr_dist(f, qhb, Op.NoTrans)))
    assert np.abs(back - b).max() < 1e-12
    r_up = np.triu(np.asarray(to_dense(f.fact))[:n, :n])
    r_ext = np.zeros((m, n))
    r_ext[:n] = r_up
    rd = from_dense(jnp.asarray(r_ext), mesh, nb)
    qr = np.asarray(to_dense(unmqr_dist(f, rd, Op.NoTrans)))
    assert np.abs(qr - a).max() / np.abs(a).max() < 1e-13


def test_gels_mesh(rng):
    from slate_tpu.parallel import gels_mesh

    mesh = mesh24()
    # least-squares optimality on an overdetermined system
    m, n, nb = 96, 64, 16
    a = np.asarray(_rand(rng, m, n))
    b = np.asarray(_rand(rng, m, 3))
    x, info = gels_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb=nb)
    x = np.asarray(x)
    opt = np.abs(a.T @ (a @ x - b)).max() / (np.abs(a).max() ** 2 * np.abs(b).max())
    assert int(info) == 0 and opt < 1e-12
    # consistent system at a non-multiple size solves exactly
    m, n = 130, 70
    a = np.asarray(_rand(rng, m, n))
    xt = np.asarray(_rand(rng, n, 2))
    x, info = gels_mesh(jnp.asarray(a), jnp.asarray(a @ xt), mesh, nb=nb)
    assert int(info) == 0
    assert np.abs(np.asarray(x) - xt).max() < 1e-10


def test_caqr_single_tile_rows(rng):
    # mtl == 1 (one tile per mesh row): rowless devices must not clobber
    # their clamped tile slot with the zeroed gather copy (review/debug
    # found the replay wiping rows at panels they do not participate in)
    from slate_tpu.parallel import geqrf_dist, unmqr_dist
    from slate_tpu.parallel.mesh import make_mesh
    from conftest import cpu_devices

    mesh = make_mesh(2, 1, devices=cpu_devices(2))
    m = n = 32
    a = np.asarray(_rand(rng, m, n))
    f = geqrf_dist(from_dense(jnp.asarray(a), mesh, 16))
    b = np.asarray(_rand(rng, m, 2))
    bd = from_dense(jnp.asarray(b), mesh, 16)
    rt = np.asarray(to_dense(unmqr_dist(f, unmqr_dist(f, bd, Op.ConjTrans), Op.NoTrans)))
    assert np.abs(rt - b).max() < 1e-12
    qa = np.asarray(to_dense(unmqr_dist(f, from_dense(jnp.asarray(a), mesh, 16), Op.ConjTrans)))
    r_up = np.triu(np.asarray(to_dense(f.fact))[:n, :n])
    assert np.abs(qa[:n] - r_up).max() < 1e-12


def test_norm_dist(rng):
    from slate_tpu.parallel import norm_dist
    from slate_tpu.types import Norm

    mesh = mesh24()
    m, n, nb = 90, 70, 16  # non-multiples: pad masking matters
    a = np.asarray(_rand(rng, m, n))
    # diag_pad_one writes 1s into the pad region; norms must mask them out
    ad = from_dense(jnp.asarray(a), mesh, nb, diag_pad_one=True)
    for nt, ref in [
        (Norm.Max, np.abs(a).max()),
        (Norm.Fro, np.linalg.norm(a)),
        (Norm.One, np.abs(a).sum(0).max()),
        (Norm.Inf, np.abs(a).sum(1).max()),
    ]:
        assert abs(float(norm_dist(nt, ad)) - ref) < 1e-10 * max(1, ref)


def test_herk_dist(rng):
    from slate_tpu.parallel import herk_dist

    mesh = mesh24()
    a = np.asarray(_rand(rng, 90, 70))
    ad = from_dense(jnp.asarray(a), mesh, 16, diag_pad_one=True)
    ref = a @ a.T
    cd = np.asarray(to_dense(herk_dist(1.0, ad, full=True)))
    assert np.abs(cd - ref).max() < 1e-11
    cl = np.asarray(to_dense(herk_dist(1.0, ad, uplo=Uplo.Lower)))
    assert np.abs(np.tril(cl) - np.tril(ref)).max() < 1e-11
    assert np.abs(np.triu(cl, 1)).max() == 0


@pytest.mark.parametrize("uplo,op", [
    (Uplo.Lower, Op.NoTrans), (Uplo.Lower, Op.Trans),
    (Uplo.Upper, Op.NoTrans), (Uplo.Upper, Op.ConjTrans),
])
def test_trsm_dist_right(rng, uplo, op):
    from slate_tpu.parallel import trsm_dist_right

    mesh = mesh24()
    m, n, nb = 90, 70, 16
    t = np.tril(np.asarray(_rand(rng, n, n))) + n * np.eye(n)
    if uplo == Uplo.Upper:
        t = t.T
    b = np.asarray(_rand(rng, m, n))
    td = from_dense(jnp.asarray(t), mesh, nb, diag_pad_one=True)
    bd = from_dense(jnp.asarray(b), mesh, nb)
    x = np.asarray(to_dense(trsm_dist_right(td, bd, uplo, op)))
    opa = t.T if op != Op.NoTrans else t
    assert np.abs(x @ opa - b).max() / np.abs(b).max() < 1e-11


def test_redistribute_device_side(rng):
    from slate_tpu.parallel import redistribute

    mesh = mesh24()
    a = np.asarray(_rand(rng, 90, 70))
    ad = from_dense(jnp.asarray(a), mesh, 16)
    d2 = redistribute(ad, make_mesh(4, 2, devices=cpu_devices(8)))
    assert np.abs(np.asarray(to_dense(d2)) - a).max() == 0
    d3 = redistribute(ad, mesh22(), nb=32)  # mesh AND nb change
    assert np.abs(np.asarray(to_dense(d3)) - a).max() == 0


@pytest.mark.parametrize("grid2", [(4, 2), (1, 8)])
def test_redistribute_shardmap_matches_eager(rng, grid2):
    """ISSUE 12: the shard_map ppermute redistribution is BITWISE the
    eager path on a ragged-tail operand, non-square grids included."""
    from slate_tpu.parallel import redistribute

    mesh = mesh24()
    a = np.asarray(_rand(rng, 90, 70))
    ad = from_dense(jnp.asarray(a), mesh, 16)
    m2 = make_mesh(*grid2, devices=cpu_devices(8))
    ea = redistribute(ad, m2, impl="eager")
    sm = redistribute(ad, m2, impl="shardmap")
    assert (ea.m, ea.n, ea.nb, ea.diag_pad) == (sm.m, sm.n, sm.nb, sm.diag_pad)
    np.testing.assert_array_equal(np.asarray(ea.tiles), np.asarray(sm.tiles))
    assert np.abs(np.asarray(to_dense(sm)) - a).max() == 0


def test_redistribute_shardmap_psum_era_grid(rng):
    """The 4-device 2x2 grid (the psum-era harness shape) through the
    shardmap exchange, including a reshape to a degenerate 4x1 ring."""
    from slate_tpu.parallel import redistribute

    mesh = mesh22()
    a = np.asarray(_rand(rng, 52, 52))
    ad = from_dense(jnp.asarray(a), mesh, 16)
    m2 = make_mesh(4, 1, devices=cpu_devices(4))
    ea = redistribute(ad, m2, impl="eager")
    sm = redistribute(ad, m2, impl="shardmap")
    np.testing.assert_array_equal(np.asarray(ea.tiles), np.asarray(sm.tiles))
    assert np.abs(np.asarray(to_dense(sm)) - a).max() == 0


def test_redistribute_roundtrip_bitwise(rng):
    """ISSUE 12 satellite (the pad-tile diagonal bug class): a
    redistribute → redistribute round trip with mesh reshape AND nb
    change is bitwise, and a diag-padded factorization operand KEEPS its
    identity pad (flag and bytes) through every reshape."""
    from slate_tpu.core.tiling import from_cyclic
    from slate_tpu.parallel import redistribute

    mesh = mesh24()
    a = _spd(rng, 90)
    d = from_dense(a, mesh, 16, diag_pad_one=True)
    m42 = make_mesh(4, 2, devices=cpu_devices(8))
    d2 = redistribute(d, m42, nb=32)  # mesh + nb change (eager retile)
    assert d2.diag_pad  # pre-fix this flag was dropped by the retile
    d2.require_diag_pad("roundtrip")  # i.e. factorizations accept it
    d3 = redistribute(d2, mesh, nb=16)  # round-trip back
    assert d3.diag_pad
    np.testing.assert_array_equal(np.asarray(d3.tiles), np.asarray(d.tiles))
    # a GROWN tile grid gets fresh identity pad tiles (both lowerings):
    # 40/16 -> 3 data tiles, lcm(2,4)=4 grid -> lcm(1,8)=8 grid
    small = from_dense(a[:40, :40], mesh, 16, diag_pad_one=True)
    m18 = make_mesh(1, 8, devices=cpu_devices(8))
    for impl in ("eager", "shardmap"):
        g = redistribute(small, m18, impl=impl)
        assert g.diag_pad, impl
        logi = np.asarray(from_cyclic(g.tiles, 1, 8))
        for t in range(3, 8):
            np.testing.assert_array_equal(
                logi[t, t], np.eye(16), err_msg=f"{impl} pad tile {t}")


def test_posv_self_check_fully_distributed(rng):
    # the residual pipeline never gathers to one host: potrf + trsm + SUMMA
    # + distributed Fro norms (VERDICT round-1 item 7)
    from slate_tpu.parallel import norm_dist, potrf_dist
    from slate_tpu.types import Norm

    mesh = mesh24()
    n, nb = 96, 16
    spd = np.asarray(_spd(rng, n))
    b = np.asarray(_rand(rng, n, 8))
    ad = from_dense(jnp.asarray(spd), mesh, nb, diag_pad_one=True)
    bd = from_dense(jnp.asarray(b), mesh, nb)
    l, info = potrf_dist(ad)
    y = trsm_dist(l, bd, Uplo.Lower, Op.NoTrans)
    xd = trsm_dist(l, y, Uplo.Lower, Op.ConjTrans)
    rd = gemm_summa(1.0, from_dense(jnp.asarray(spd), mesh, nb), xd, -1.0, bd)
    resid = float(norm_dist(Norm.Fro, rd)) / float(norm_dist(Norm.Fro, bd))
    assert int(info) == 0
    assert resid < 1e-12


def test_heev_mesh(rng):
    from slate_tpu.parallel import heev_mesh

    n = 96
    a = _rand(rng, n, n)
    a = (a + a.T) / 2
    w, z = heev_mesh(a, mesh24(), nb=16)
    an, zn, wn = np.asarray(a), np.asarray(z), np.asarray(w)
    wref = np.linalg.eigvalsh(an)
    eps = np.finfo(np.float64).eps
    assert np.abs(np.sort(wn) - wref).max() < 50 * n * eps * max(1, np.abs(wref).max())
    assert np.abs(an @ zn - zn * wn).max() < 50 * n * eps * max(1, np.abs(wref).max())
    assert np.abs(zn.T @ zn - np.eye(n)).max() < 50 * n * eps
    # values-only path
    w2 = heev_mesh(a, mesh24(), nb=16, want_vectors=False)
    assert np.abs(np.sort(np.asarray(w2)) - wref).max() < 50 * n * eps * max(
        1, np.abs(wref).max()
    )


def test_heev_mesh_complex(rng):
    from slate_tpu.parallel import heev_mesh

    n = 64
    a = _rand(rng, n, n, np.complex128)
    a = (a + jnp.conj(a).T) / 2
    w, z = heev_mesh(a, mesh22(), nb=16)
    an, zn, wn = np.asarray(a), np.asarray(z), np.asarray(w)
    wref = np.linalg.eigvalsh(an)
    eps = np.finfo(np.float64).eps
    scale = max(1, np.abs(wref).max())
    assert np.abs(np.sort(wn) - wref).max() < 50 * n * eps * scale
    assert np.abs(an @ zn - zn * wn).max() < 50 * n * eps * scale
    assert np.abs(zn.conj().T @ zn - np.eye(n)).max() < 50 * n * eps


@pytest.mark.slow  # tier-1 budget relief (ISSUE 11): 44 s of accuracy
# sweeps; distributed SVD stays tier-1-covered by test_svd_mesh_complex,
# and the full CI pytest pass still runs these
@pytest.mark.parametrize("shape", [(80, 64), (64, 96), (100, 100)])
def test_svd_mesh(rng, shape):
    from slate_tpu.parallel import svd_mesh

    m, n = shape
    a = _rand(rng, m, n)
    u, s, vh = svd_mesh(a, mesh24(), nb=16)
    an, un, sn, vn = np.asarray(a), np.asarray(u), np.asarray(s), np.asarray(vh)
    sref = np.linalg.svd(an, compute_uv=False)
    k = min(m, n)
    eps = np.finfo(np.float64).eps
    scale = max(1, sref.max())
    assert np.abs(sn - sref).max() < 50 * k * eps * scale
    assert np.abs(an - (un * sn) @ vn).max() < 50 * k * eps * scale
    assert np.abs(un.conj().T @ un - np.eye(un.shape[1])).max() < 50 * k * eps
    assert np.abs(vn @ vn.conj().T - np.eye(vn.shape[0])).max() < 50 * k * eps
    svals = svd_mesh(a, mesh24(), nb=16, want_vectors=False)
    assert np.abs(np.asarray(svals) - sref).max() < 50 * k * eps * scale


def test_he2hb_dist_band_structure(rng):
    """Stage-1 output really is banded and orthogonally similar to A."""
    from slate_tpu.parallel import from_dense, he2hb_dist, to_dense

    n, nb = 64, 16
    a = _rand(rng, n, n)
    a = (a + a.T) / 2
    f = he2hb_dist(from_dense(a, mesh24(), nb))
    band = np.asarray(to_dense(f.band))
    # outside the band: zero
    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    out = np.abs(ii - jj) > nb
    assert np.abs(band[out]).max() < 1e-12
    # same spectrum
    wref = np.linalg.eigvalsh(np.asarray(a))
    wband = np.linalg.eigvalsh(0.5 * (band + band.T))
    assert np.abs(wref - wband).max() < 1e-11


# ---------------------------------------------------------------------------
# partial-pivot mesh LU (src/getrf.cc default; VERDICT r2 missing item 1)
# ---------------------------------------------------------------------------


def _check_pp_factor(a, lu, perm, n):
    lud, perm = np.asarray(to_dense(lu)), np.asarray(perm)
    l = np.tril(lud, -1) + np.eye(n)
    u = np.triu(lud)
    ap = np.pad(np.asarray(a), ((0, perm.shape[0] - n), (0, 0)))[perm][:n]
    assert np.abs(ap - l @ u).max() < 1e-12
    assert sorted(perm.tolist()) == list(range(perm.shape[0]))
    # partial pivoting invariant: |L| <= 1 everywhere
    assert np.abs(l).max() <= 1.0 + 1e-14


def test_getrf_pp_mesh_factor(rng):
    from slate_tpu.parallel import getrf_mesh

    mesh = mesh24()
    n, nb = 64, 16
    a = _rand(rng, n, n)
    lu, perm, info = getrf_mesh(a, mesh, nb=nb)
    assert int(info) == 0
    _check_pp_factor(a, lu, perm, n)


def test_getrf_pp_mesh_matches_lapack_pivots(rng):
    # same pivot choices as scipy's LAPACK getrf on a matrix with distinct
    # column maxima (no ties): the mesh partial pivot IS partial pivoting
    import scipy.linalg as sla
    from slate_tpu.parallel import getrf_mesh

    mesh = mesh22()
    n, nb = 48, 16
    a = np.asarray(_rand(rng, n, n))
    lu, perm, info = getrf_mesh(jnp.asarray(a), mesh, nb=nb)
    assert int(info) == 0
    lud = np.asarray(to_dense(lu))
    lu_ref, piv = sla.lu_factor(a)
    np.testing.assert_allclose(lud[:n, :n], lu_ref, rtol=0, atol=1e-11)


def test_gesv_pp_mesh_zero_leading_pivot(rng):
    from slate_tpu.parallel import gesv_mesh

    mesh = mesh24()
    n, nb = 64, 16
    a = np.asarray(_rand(rng, n, n)).copy()
    a[0, 0] = 0.0
    a[1, 0] = 5.0
    b = np.asarray(_rand(rng, n, 2))
    x, info = gesv_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb=nb)
    x = np.asarray(x)
    assert int(info) == 0
    assert np.isfinite(x).all()
    resid = np.abs(a @ x - b).max() / (np.abs(a).max() * np.abs(x).max() * n)
    assert resid < 1e-13, resid


def test_gesv_pp_mesh_near_singular_column(rng):
    from slate_tpu.parallel import gesv_mesh

    mesh = mesh24()
    n, nb = 64, 16
    a = np.asarray(_rand(rng, n, n)).copy()
    a[:, 0] = 0.0
    a[40, 0] = 3.0  # the single viable pivot lives deep in another shard
    b = np.asarray(_rand(rng, n, 2))
    x, info = gesv_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb=nb)
    x = np.asarray(x)
    assert int(info) == 0
    resid = np.abs(a @ x - b).max() / (np.abs(a).max() * np.abs(x).max() * n)
    assert resid < 1e-13, resid


def test_getrf_pp_mesh_singular_info(rng):
    from slate_tpu.parallel import getrf_mesh

    mesh = mesh22()
    n, nb = 32, 16
    a = np.asarray(_rand(rng, n, n)).copy()
    a[:, 5] = 0.0  # exactly singular: U[5,5] = 0 after elimination
    lu, perm, info = getrf_mesh(jnp.asarray(a), mesh, nb=nb)
    assert int(info) == 6  # 1-based first zero pivot


# ---------------------------------------------------------------------------
# mesh BLAS-3 fill: hemm/symm, trmm, her2k/syr2k (VERDICT r2 missing item 3)
# ---------------------------------------------------------------------------


def test_transpose_dist(rng):
    from slate_tpu.parallel.dist_blas3 import transpose_dist

    mesh = mesh24()
    a = _rand(rng, 80, 48, np.complex128)
    d = from_dense(a, mesh, nb=16)
    out = np.asarray(to_dense(transpose_dist(d, conj=True)))
    np.testing.assert_allclose(out, np.asarray(a).conj().T, atol=0)


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("conj", [True, False])
def test_hemm_symm_dist_left(rng, uplo, conj):
    from slate_tpu.parallel.dist_blas3 import hemm_summa
    from slate_tpu.types import Side

    mesh = mesh24()
    n, nrhs, nb = 64, 32, 16
    g = np.asarray(_rand(rng, n, n, np.complex128))
    herm = (g + g.conj().T) / 2 if conj else (g + g.T) / 2
    b = np.asarray(_rand(rng, n, nrhs, np.complex128))
    # poison the dead triangle: the kernel must never read it
    stored = herm.copy()
    dead = np.triu(np.ones((n, n), bool), 1) if uplo == Uplo.Lower else np.tril(np.ones((n, n), bool), -1)
    stored[dead] = 1e6
    ad = from_dense(jnp.asarray(stored), mesh, nb)
    bd = from_dense(jnp.asarray(b), mesh, nb)
    out = np.asarray(to_dense(hemm_summa(Side.Left, 2.0, ad, bd, uplo=uplo, conj=conj)))
    ref = 2.0 * herm @ b
    assert np.abs(out - ref).max() / np.abs(ref).max() < 1e-12


@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("conj", [True, False])
def test_hemm_stationary_a(rng, uplo, conj):
    # hemmA (src/hemmA.cc): stationary-A schedule, thin B/C (r5 item 7);
    # the auto-selector must pick it for a thin panel
    from slate_tpu.parallel.dist_blas3 import hemm_summa
    from slate_tpu.types import MethodHemm, Side, select_hemm_method

    mesh = mesh24()
    n, nrhs, nb = 96, 8, 8
    g = np.asarray(_rand(rng, n, n, np.complex128))
    herm = (g + g.conj().T) / 2 if conj else (g + g.T) / 2
    b = np.asarray(_rand(rng, n, nrhs, np.complex128))
    stored = herm.copy()
    dead = np.triu(np.ones((n, n), bool), 1) if uplo == Uplo.Lower else np.tril(np.ones((n, n), bool), -1)
    stored[dead] = 1e6  # the kernel must never read the dead triangle
    ad = from_dense(jnp.asarray(stored), mesh, nb)
    bd = from_dense(jnp.asarray(b), mesh, nb)
    out = np.asarray(to_dense(hemm_summa(
        Side.Left, 2.0, ad, bd, uplo=uplo, conj=conj, method=MethodHemm.HemmA
    )))
    ref = 2.0 * herm @ b
    assert np.abs(out - ref).max() / np.abs(ref).max() < 1e-12
    assert select_hemm_method(n // nb, nrhs // nb) == MethodHemm.HemmA


def test_hemm_dist_right(rng):
    from slate_tpu.parallel.dist_blas3 import hemm_summa
    from slate_tpu.types import Side

    mesh = mesh22()
    n, mr, nb = 48, 32, 16
    g = np.asarray(_rand(rng, n, n, np.complex128))
    herm = (g + g.conj().T) / 2
    b = np.asarray(_rand(rng, mr, n, np.complex128))
    ad = from_dense(jnp.asarray(herm), mesh, nb)
    bd = from_dense(jnp.asarray(b), mesh, nb)
    out = np.asarray(to_dense(hemm_summa(Side.Right, 1.5, ad, bd)))
    ref = 1.5 * b @ herm
    assert np.abs(out - ref).max() / np.abs(ref).max() < 1e-12


@pytest.mark.parametrize("op", [Op.NoTrans, Op.Trans, Op.ConjTrans])
@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
def test_trmm_dist_left(rng, op, uplo):
    from slate_tpu.parallel.dist_blas3 import trmm_dist
    from slate_tpu.types import Side

    mesh = mesh24()
    n, nrhs, nb = 64, 16, 16
    a = np.asarray(_rand(rng, n, n, np.complex128))
    t = np.tril(a) if uplo == Uplo.Lower else np.triu(a)
    b = np.asarray(_rand(rng, n, nrhs, np.complex128))
    ad = from_dense(jnp.asarray(a), mesh, nb)  # full stored; kernel masks
    bd = from_dense(jnp.asarray(b), mesh, nb)
    out = np.asarray(to_dense(trmm_dist(Side.Left, uplo, op, Diag.NonUnit, 1.0, ad, bd)))
    opt = {Op.NoTrans: t, Op.Trans: t.T, Op.ConjTrans: t.conj().T}[op]
    ref = opt @ b
    assert np.abs(out - ref).max() / np.abs(ref).max() < 1e-12


def test_trmm_dist_unit_and_right(rng):
    from slate_tpu.parallel.dist_blas3 import trmm_dist
    from slate_tpu.types import Side

    mesh = mesh22()
    n, mr, nb = 48, 32, 16
    a = np.asarray(_rand(rng, n, n))
    t = np.tril(a, -1) + np.eye(n)
    b = np.asarray(_rand(rng, mr, n))
    ad = from_dense(jnp.asarray(a), mesh, nb)
    bd = from_dense(jnp.asarray(b), mesh, nb)
    out = np.asarray(to_dense(trmm_dist(Side.Right, Uplo.Lower, Op.NoTrans, Diag.Unit, 1.0, ad, bd)))
    ref = b @ t
    assert np.abs(out - ref).max() / np.abs(ref).max() < 1e-12


@pytest.mark.parametrize("conj", [True, False])
def test_her2k_syr2k_dist(rng, conj):
    from slate_tpu.parallel.dist_blas3 import her2k_dist
    from slate_tpu.parallel import norm_dist

    mesh = mesh24()
    n, k, nb = 64, 48, 16
    a = np.asarray(_rand(rng, n, k, np.complex128))
    b = np.asarray(_rand(rng, n, k, np.complex128))
    ad = from_dense(jnp.asarray(a), mesh, nb)
    bd = from_dense(jnp.asarray(b), mesh, nb)
    alpha = 1.0 + (0.5j if conj else 0.0)
    out = np.asarray(to_dense(her2k_dist(alpha, ad, bd, conj=conj, full=True)))
    if conj:
        ref = alpha * a @ b.conj().T + np.conj(alpha) * b @ a.conj().T
    else:
        ref = alpha * a @ b.T + alpha * b @ a.T
    assert np.abs(out - ref).max() / np.abs(ref).max() < 1e-12


def test_svd_mesh_complex(rng):
    # ADVICE r2: the complex path through ge2tb_dist's LQ conjugation and
    # the pu/pv phase handling in the mesh driver was untested
    from slate_tpu.parallel import svd_mesh

    m, n = 72, 56
    a = _rand(rng, m, n, np.complex128)
    u, s, vh = svd_mesh(a, mesh22(), nb=16)
    an, un, sn, vn = np.asarray(a), np.asarray(u), np.asarray(s), np.asarray(vh)
    sref = np.linalg.svd(an, compute_uv=False)
    k = min(m, n)
    eps = np.finfo(np.float64).eps
    scale = max(1, sref.max())
    assert np.abs(sn - sref).max() < 50 * k * eps * scale
    assert np.abs(an - (un * sn) @ vn).max() < 50 * k * eps * scale
    assert np.abs(un.conj().T @ un - np.eye(un.shape[1])).max() < 50 * k * eps
    assert np.abs(vn @ vn.conj().T - np.eye(vn.shape[0])).max() < 50 * k * eps


def test_stedc_dist(rng):
    # VERDICT r2 item 6: the D&C merge tree sharded over the mesh — secular
    # roots over the column axis, eigenvector rows over the row axis
    from slate_tpu.parallel.dist_stedc import stedc_dist

    n = 200  # pads to N=256: exercises pad-block merges too
    d = np.asarray(_rand(rng, n, 1))[:, 0]
    e = np.asarray(_rand(rng, n - 1, 1))[:, 0]
    w, z = stedc_dist(jnp.asarray(d), jnp.asarray(e), mesh24())
    w, z = np.asarray(w), np.asarray(z)
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    wref = np.linalg.eigvalsh(T)
    eps = np.finfo(np.float64).eps
    scale = max(1, np.abs(wref).max())
    assert np.abs(w - wref).max() < 50 * n * eps * scale
    assert np.abs(T @ z - z * w).max() < 50 * n * eps * scale
    assert np.abs(z.T @ z - np.eye(n)).max() < 50 * n * eps


def test_stedc_dist_deflation_heavy(rng):
    # repeated eigenvalues force the Givens-deflation path across shards
    from slate_tpu.parallel.dist_stedc import stedc_dist

    n = 128
    d = np.repeat(np.arange(n // 4), 4).astype(np.float64)
    e = np.full(n - 1, 1e-3)
    w, z = stedc_dist(jnp.asarray(d), jnp.asarray(e), mesh24())
    w, z = np.asarray(w), np.asarray(z)
    T = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    wref = np.linalg.eigvalsh(T)
    eps = np.finfo(np.float64).eps
    assert np.abs(w - wref).max() < 100 * n * eps * max(1, np.abs(wref).max())
    assert np.abs(T @ z - z * w).max() < 100 * n * eps * max(1, np.abs(wref).max())
    assert np.abs(z.T @ z - np.eye(n)).max() < 100 * n * eps


def test_heev_mesh_distributed_solver(rng):
    from slate_tpu.parallel import heev_mesh

    n = 96
    a = _rand(rng, n, n)
    a = (a + a.T) / 2
    w, z = heev_mesh(a, mesh24(), nb=16)
    an, zn, wn = np.asarray(a), np.asarray(z), np.asarray(w)
    wref = np.linalg.eigvalsh(an)
    eps = np.finfo(np.float64).eps
    scale = max(1, np.abs(wref).max())
    assert np.abs(np.sort(wn) - wref).max() < 50 * n * eps * scale
    assert np.abs(an @ zn - zn * wn).max() < 50 * n * eps * scale
    assert np.abs(zn.T @ zn - np.eye(n)).max() < 50 * n * eps


# ---------------------------------------------------------------------------
# mixed-precision mesh solvers + distributed inverses (VERDICT r2 items 4/8)
# ---------------------------------------------------------------------------


def test_posv_mixed_mesh(rng):
    from slate_tpu.parallel import posv_mixed_mesh

    mesh = mesh24()
    n = 96
    a = np.asarray(_spd(rng, n))
    b = np.asarray(_rand(rng, n, 3))
    x, iters, info = posv_mixed_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb=16)
    assert int(info) == 0
    assert 0 <= int(iters) <= 3  # well-conditioned: converges in <= 3
    resid = np.abs(a @ np.asarray(x) - b).max() / (np.abs(a).max() * np.abs(np.asarray(x)).max() * n)
    assert resid < 1e-14, resid  # f64-grade answer from an f32 factor


def test_gesv_mixed_mesh(rng):
    from slate_tpu.parallel import gesv_mixed_mesh

    mesh = mesh24()
    n = 96
    a = np.asarray(_rand(rng, n, n)) + n * np.eye(n)
    b = np.asarray(_rand(rng, n, 2))
    x, iters, info = gesv_mixed_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb=16)
    assert int(info) == 0
    assert 0 <= int(iters) <= 3
    resid = np.abs(a @ np.asarray(x) - b).max() / (np.abs(a).max() * np.abs(np.asarray(x)).max() * n)
    assert resid < 1e-14, resid


def test_posv_mixed_mesh_failed_factor_returns_nan(rng):
    # non-SPD input: info != 0 and x is NaN-filled — a caller that skips
    # the info check cannot mistake the RHS for a solution (ADVICE r3)
    from slate_tpu.parallel import posv_mixed_mesh

    mesh = mesh24()
    n = 96
    a = -np.eye(n)  # negative definite: f32 potrf must fail
    b = np.asarray(_rand(rng, n, 2))
    x, iters, info = posv_mixed_mesh(jnp.asarray(a), jnp.asarray(b), mesh, nb=16)
    assert int(info) != 0
    assert int(iters) == -1
    assert np.all(np.isnan(np.asarray(x)))


def test_getri_potri_mesh(rng):
    from slate_tpu.parallel import getri_mesh, potri_mesh

    mesh = mesh22()
    n = 64
    a = np.asarray(_rand(rng, n, n))
    inv, info = getri_mesh(jnp.asarray(a), mesh, nb=16)
    assert int(info) == 0
    assert np.abs(a @ np.asarray(inv) - np.eye(n)).max() < 1e-10
    s = np.asarray(_spd(rng, n))
    sinv, info2 = potri_mesh(jnp.asarray(s), mesh, nb=16)
    assert int(info2) == 0
    assert np.abs(s @ np.asarray(sinv) - np.eye(n)).max() < 1e-9


# ---------------------------------------------------------------------------
# non-uniform block sizes + GridOrder (func.hh:39-203 parity, ref ex13)
# ---------------------------------------------------------------------------


def test_nonuniform_roundtrip_and_gemm(rng):
    from slate_tpu.parallel import (
        from_dense_nonuniform, gemm_summa, to_dense_nonuniform,
    )

    mesh = mesh24()
    rowsz = [16, 8, 24, 16, 8, 24]
    colsz = [8, 24, 16, 8, 24, 16]
    a = _rand(rng, 96, 96)
    b = _rand(rng, 96, 96)
    ad = from_dense_nonuniform(a, mesh, rowsz, colsz)
    assert ad.nb == 24  # max block size
    back = to_dense_nonuniform(ad, rowsz, colsz)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(a))
    bd = from_dense_nonuniform(b, mesh, colsz, rowsz)
    c = to_dense_nonuniform(gemm_summa(1.0, ad, bd), rowsz, rowsz)
    ref = np.asarray(a) @ np.asarray(b)
    assert np.abs(np.asarray(c) - ref).max() < 1e-12


def test_nonuniform_size_mismatch_raises(rng):
    from slate_tpu.parallel import from_dense_nonuniform

    with pytest.raises(ValueError):
        from_dense_nonuniform(_rand(rng, 64, 64), mesh22(), [32, 16], [32, 32])


def test_nonuniform_factorizations(rng):
    # ex13 parity (VERDICT r5 item 6): real algorithms on non-uniformly
    # tiled input — Cholesky and pivoted LU end-to-end through the
    # device-resident non-uniform -> uniform redistribution
    from slate_tpu.parallel import (
        from_dense_nonuniform, redistribute_nonuniform, to_dense,
        trsm_dist, from_dense,
    )
    from slate_tpu.parallel.dist_chol import potrf_dist
    from slate_tpu.parallel.dist_lu import getrf_pp_dist, permute_rows_dist

    mesh = mesh24()
    n = 96
    rowsz = [16, 8, 24, 16, 8, 24]
    a = _spd(rng, n)
    ad_nu = from_dense_nonuniform(a, mesh, rowsz, rowsz)
    ad = redistribute_nonuniform(ad_nu, rowsz, rowsz, nb=16, diag_pad_one=True)
    l, info = potrf_dist(ad)
    assert int(info) == 0
    ld = np.tril(np.asarray(to_dense(l)))
    assert np.abs(ld @ ld.T - np.asarray(a)).max() / np.abs(np.asarray(a)).max() < 1e-12

    g = _rand(rng, n, n)
    gd_nu = from_dense_nonuniform(g, mesh, rowsz, rowsz)
    gd = redistribute_nonuniform(gd_nu, rowsz, rowsz, nb=16, diag_pad_one=True)
    lu, perm, info2 = getrf_pp_dist(gd)
    assert int(info2) == 0
    b = _rand(rng, n, 4)
    bd = permute_rows_dist(from_dense(b, mesh, 16), perm)
    y = trsm_dist(lu, bd, Uplo.Lower, Op.NoTrans, Diag.Unit)
    x = to_dense(trsm_dist(lu, y, Uplo.Upper, Op.NoTrans))
    resid = np.abs(np.asarray(g) @ np.asarray(x) - np.asarray(b)).max()
    assert resid / np.abs(np.asarray(b)).max() < 1e-10


def test_grid_order_col(rng):
    from slate_tpu.parallel import gemm_mesh
    from slate_tpu.types import GridOrder

    from slate_tpu.parallel import make_mesh as mk
    mesh = mk(2, 4, devices=cpu_devices(8), order=GridOrder.Col)
    a, b = _rand(rng, 64, 48), _rand(rng, 48, 32)
    c = gemm_mesh(1.0, a, b, mesh, nb=16)
    ref = np.asarray(a) @ np.asarray(b)
    np.testing.assert_allclose(np.asarray(c), ref, rtol=1e-12, atol=1e-10)
    # Col vs Row order place device k at transposed grid coordinates
    mrow = mk(2, 4, devices=cpu_devices(8), order=GridOrder.Row)
    dcol = np.asarray(mesh.devices)
    drow = np.asarray(mrow.devices)
    assert dcol[1, 0] == drow[0, 1]  # device k=1: (1,0) in Col vs (0,1) in Row


# ---------------------------------------------------------------------------
# mesh band drivers (src/gbmm.cc, hbmm.cc, tbsm.cc, gbsv, pbsv on the mesh)
# ---------------------------------------------------------------------------


def _band(rng, n, kl, ku):
    a = np.asarray(_rand(rng, n, n)).copy()
    for i in range(n):
        for j in range(n):
            if j < i - kl or j > i + ku:
                a[i, j] = 0.0
    return a


def test_gbmm_hbmm_mesh(rng):
    from slate_tpu.parallel import gbmm_mesh, hbmm_mesh
    from slate_tpu.types import Side

    mesh = mesh22()
    n, kl, ku = 64, 5, 3
    ab = _band(rng, n, kl, ku)
    b = np.asarray(_rand(rng, n, 8))
    c = np.asarray(gbmm_mesh(1.0, jnp.asarray(ab), kl, ku, jnp.asarray(b), mesh, nb=16))
    assert np.abs(c - ab @ b).max() < 1e-12
    hb = _band(rng, n, 4, 4)
    hb = (hb + hb.T) / 2
    c2 = np.asarray(hbmm_mesh(Side.Left, 1.0, jnp.asarray(hb), 4, jnp.asarray(b), mesh, nb=16))
    assert np.abs(c2 - hb @ b).max() < 1e-12


def test_tbsm_pbsv_gbsv_mesh(rng):
    from slate_tpu.parallel import gbsv_mesh, pbsv_mesh, tbsm_mesh

    mesh = mesh22()
    n, kd = 64, 6
    t = np.tril(_band(rng, n, kd, 0)) + n * np.eye(n)
    b = np.asarray(_rand(rng, n, 4))
    x = np.asarray(tbsm_mesh(jnp.asarray(t), kd, jnp.asarray(b), mesh, nb=16))
    assert np.abs(t @ x - b).max() / np.abs(b).max() < 1e-12
    hb = _band(rng, n, kd, kd)
    spd = hb @ hb.T + n * np.eye(n)
    spd_band = np.where(np.abs(np.subtract.outer(np.arange(n), np.arange(n))) <= 2 * kd, spd, 0)
    xs, info = pbsv_mesh(jnp.asarray(spd_band), jnp.asarray(b), 2 * kd, mesh, nb=16)
    assert int(info) == 0
    assert np.abs(spd_band @ np.asarray(xs) - b).max() / np.abs(b).max() < 1e-10
    gb = _band(rng, n, 4, 7) + n * np.eye(n)
    xg, info2 = gbsv_mesh(jnp.asarray(gb), jnp.asarray(b), 4, 7, mesh, nb=16)
    assert int(info2) == 0
    assert np.abs(gb @ np.asarray(xg) - b).max() / np.abs(b).max() < 1e-12


def test_band_mesh_kernels_band_cost(rng):
    # VERDICT r5 item 8 gate: the windowed band kernels do O(n k^2)-class
    # work — their compiled flop count must sit far below the dense mesh
    # factorization's O(n^3)-class count at the same size
    from slate_tpu.parallel.dist_chol import _pbtrf_band_jit, _potrf_jit
    from slate_tpu.parallel.dist_lu import _gb_pp_jit, _pp_jit
    from slate_tpu.parallel import from_dense

    mesh = mesh24()
    n, nb, kd = 512, 16, 32
    tiles = from_dense(jnp.eye(n), mesh, nb, diag_pad_one=True).tiles
    nt = n // nb
    wd = ((nb - 1) + kd) // nb + 1

    def flops(compiled):
        # cost_analysis returns one dict on newer JAX, a per-device list
        # of dicts on 0.4.x
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return ca["flops"]

    # lowering pinned to psum + the xla panel/update forms: the
    # flop-class gate is impl-independent (ppermute adds bytes
    # bookkeeping, not flops; the fused panel/update kernels change
    # dispatch count, not flop class) but the jits now take the
    # bcast-impl / panel-impl / update-impl static args
    dense = _potrf_jit.lower(
        tiles, mesh, 2, 4, nt, 1, "psum", "xla", "xla"
    ).compile()
    band = _pbtrf_band_jit.lower(tiles, mesh, 2, 4, nt, wd, 1, "psum").compile()
    assert flops(band) < flops(dense) / 4, (flops(band), flops(dense))

    dense_lu = _pp_jit.lower(
        tiles, mesh, 2, 4, nt, n, 1, "psum", "xla"
    ).compile()
    wd_u = ((nb - 1) + 2 * kd) // nb + 1
    wd_usw = ((nb - 1) + 3 * kd) // nb + 1
    band_lu = _gb_pp_jit.lower(
        tiles, mesh, 2, 4, nt, n, wd, wd_u, wd_usw, "psum"
    ).compile()
    assert flops(band_lu) < flops(dense_lu) / 4, (flops(band_lu), flops(dense_lu))


def test_band_mesh_wide_band(rng):
    # windowed kernels with kd wide enough that the window IS the grid:
    # degenerates to the dense schedule, stays correct
    from slate_tpu.parallel import pbsv_mesh

    mesh = mesh22()
    n, kd = 64, 60
    hb = _band(rng, n, kd, kd)
    spd = hb @ hb.T + n * np.eye(n)
    spd = np.where(np.abs(np.subtract.outer(np.arange(n), np.arange(n))) <= kd, spd, 0)
    b = np.asarray(_rand(rng, n, 3))
    x, info = pbsv_mesh(jnp.asarray(spd), jnp.asarray(b), kd, mesh, nb=16)
    assert int(info) == 0
    assert np.abs(spd @ np.asarray(x) - b).max() / np.abs(b).max() < 1e-9


def test_chase_apply_dist_matches_replicated(rng):
    # streamed sharded stage-2 back-transform == the single-program apply
    from slate_tpu.linalg.eig import _chase_sweep_apply, hb2st
    from slate_tpu.parallel.dist_twostage import chase_apply_dist

    n, w = 96, 8
    g = _rand(rng, n, n)
    band = np.tril(np.triu(g + g.T, -w), w)
    d, e, f2, _ = hb2st(jnp.asarray(band), w)
    z = jnp.asarray(_rand(rng, n, n))
    ref = np.asarray(_chase_sweep_apply(f2.vs, f2.taus, z, n, w, False))
    got = np.asarray(chase_apply_dist(f2.vs, f2.taus, z, n, w, mesh24()))
    assert np.abs(got - ref).max() < 1e-12


def test_chase_apply_dist_memory():
    # VERDICT r3 item 4 gate: peak per-device memory of the distributed
    # stage-2 back-transform is O(n^2/p), not the O(n^2) of replication.
    # memory_analysis reports PER-DEVICE sizes for the partitioned program.
    from slate_tpu.parallel.dist_twostage import _chase_apply_dist_jit

    mesh = mesh24()
    n, w = 512, 8
    nparts = 8
    max_hops = -(-(n - 1) // w)
    nsweeps = n - 2
    blk = -(-nsweeps // nparts)
    vs = jnp.zeros((blk * nparts, max_hops, w), jnp.float64)
    taus = jnp.zeros((blk * nparts, max_hops), jnp.float64)
    z = jnp.zeros((n, n), jnp.float64)
    c = _chase_apply_dist_jit.lower(
        vs, taus, z, mesh, 2, 4, n, w, blk, "auto"
    ).compile()
    ma = c.memory_analysis()
    per_dev = ma.argument_size_in_bytes + ma.temp_size_in_bytes + ma.output_size_in_bytes
    repl = (vs.size + taus.size + 2 * z.size) * 8  # replicated footprint
    # sharded run must stay well under half the replicated footprint
    # (measures: z/8 + vs/8 + one streamed block + slack)
    assert per_dev < 0.45 * repl, (per_dev, repl)


@pytest.mark.parametrize("p,q", [(2, 4), (4, 2)])
def test_stedc_finale_memory(p, q):
    # VERDICT r4 item 6 gate: the stedc -> chase handoff is sharded, so
    # the whole heev_mesh stage-2 chain (merge tree out-spec, finale,
    # chase) keeps per-device peak O(n^2/min(p, q)) — no replicated
    # (n, n) Z at the driver boundary.  Both mesh aspect ratios are
    # gated (the gather buffer is O(n^2/q), the input shard O(n^2/p)).
    # memory_analysis reports PER-DEVICE sizes.
    from slate_tpu.parallel.dist_stedc import _stedc_finale_jit

    mesh = make_mesh(p, q, devices=cpu_devices(8))
    n, N = 960, 1024
    z = jnp.zeros((N, N), jnp.float64)
    inv = jnp.arange(N)
    order = jnp.arange(n)
    c = _stedc_finale_jit.lower(z, inv, order, mesh, p, q, n).compile()
    ma = c.memory_analysis()
    per_dev = ma.argument_size_in_bytes + ma.temp_size_in_bytes + ma.output_size_in_bytes
    repl = 2 * N * N * 8  # replicated in+out footprint
    # input shard N^2/p + one N*(n/q) gather buffer + small temps: the
    # per-device peak must stay well under the replicated footprint and
    # within the O(n^2/p + 2 n^2/q) design bound
    assert per_dev < 0.5 * repl, (p, q, per_dev, repl)
    bound = (N * N / p + 2.5 * N * N / q + 4 * N * n / (p * q)) * 8
    assert per_dev < bound, (p, q, per_dev, bound)
