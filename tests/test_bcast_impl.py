"""Broadcast engine (ISSUE 5): Option.BcastImpl consumed end-to-end.

Contracts under test, on the forced 8-device CPU mesh:

1. Every lowering of a rooted broadcast moves the owner's exact bytes —
   results are BITWISE identical across ``psum`` / ``ring`` /
   ``doubling`` for every driver that consumes the engine, including the
   checksum-carrying ABFT variants (the psum path only ever adds exact
   zeros, so equality is bit-for-bit up to the sign of zero, which
   ``assert_array_equal`` treats as equal).
2. The lookahead-depth bitwise invariance (test_lookahead.py's contract)
   holds under EACH lowering, and across lowerings at every depth.
3. The option plumbs through driver ``opts``, the ``use_bcast_impl``
   context, and the ``SLATE_TPU_BCAST_IMPL`` environment default, with
   explicit-argument > context > environment precedence (the audit
   record ops are the fingerprint: ppermute hops vs masked psums).
4. The owner-rooted ``reduce_to_row``/``reduce_to_col`` counterpart
   delivers a deterministic sum on the owner and zeros elsewhere.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import cpu_devices

from slate_tpu.parallel import from_dense, gemm_summa, make_mesh, to_dense
from slate_tpu.parallel import comm
from slate_tpu.parallel.comm import comm_audit, use_bcast_impl
from slate_tpu.parallel.dist_chol import potrf_dist
from slate_tpu.types import MethodGemm, Option

IMPLS = ("psum", "ring", "doubling")
N, NB = 64, 8


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def _leaves(x):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(x)]


def _run_driver_under(fn, args, impl):
    with use_bcast_impl(impl):
        return _leaves(jax.block_until_ready(fn(*args)))


def _registry_case(name):
    from slate_tpu.analysis.registry import REGISTRY, make_ctx

    ctx = make_ctx()
    return REGISTRY[name].build(ctx)


def _assert_driver_bitwise(name):
    """Trace under psum vs ring first: identical jaxprs mean the driver
    has no engine broadcasts (its outputs cannot depend on the impl) and
    execution is skipped; different jaxprs are executed under all three
    lowerings and compared bytes-for-bytes."""
    fn, args = _registry_case(name)
    with use_bcast_impl("psum"):
        jx_psum = str(jax.make_jaxpr(fn)(*args))
    with use_bcast_impl("ring"):
        jx_ring = str(jax.make_jaxpr(fn)(*args))
    if jx_psum == jx_ring:
        return  # no rooted broadcasts anywhere in the trace
    ref = _run_driver_under(fn, args, "psum")
    for impl in ("ring", "doubling"):
        got = _run_driver_under(fn, args, impl)
        assert len(got) == len(ref), (name, impl)
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(a, b, err_msg=f"{name}/{impl}")


# the issue's core ops stay in the default tier; the exhaustive sweep over
# the full registry (including the heavyweight QR/two-stage/eig chains,
# which carry no engine broadcasts and shortcut to the jaxpr comparison)
# runs in CI's full pytest pass
CORE = [
    "gemm_summa_c",
    "potrf_dist",
    "getrf_nopiv_dist",
    "getrf_pp_dist",
    "trsm_dist_lower",
    "gemm_abft_correct",
    "potrf_abft_detect",
    "getrf_nopiv_abft_correct",
]


@pytest.mark.parametrize("name", CORE)
def test_core_driver_bitwise_across_impls(name):
    _assert_driver_bitwise(name)


def _all_registry_names():
    from slate_tpu.analysis import registry  # populates REGISTRY on import

    return sorted(registry.REGISTRY)


@pytest.mark.slow
@pytest.mark.parametrize("name", _all_registry_names())
def test_every_registered_driver_bitwise_across_impls(name):
    if name in CORE:
        pytest.skip("covered by the default-tier core sweep")
    _assert_driver_bitwise(name)


# ---------------------------------------------------------------------------
# lookahead x impl: depth invariance holds under each lowering
# ---------------------------------------------------------------------------


def test_lookahead_invariance_under_each_impl(rng):
    mesh = mesh24()
    a = from_dense(jnp.asarray(rng.standard_normal((N, N))), mesh, NB)
    b = from_dense(jnp.asarray(rng.standard_normal((N, N))), mesh, NB)
    g = rng.standard_normal((N, N))
    sd = from_dense(jnp.asarray(g @ g.T + N * np.eye(N)), mesh, NB,
                    diag_pad_one=True)

    ref_gemm = ref_potrf = None
    for impl in IMPLS:
        for la in (0, 1, 2):
            out = np.asarray(to_dense(gemm_summa(
                1.0, a, b, method=MethodGemm.GemmC, lookahead=la,
                bcast_impl=impl)))
            if ref_gemm is None:
                ref_gemm = out
            np.testing.assert_array_equal(out, ref_gemm, err_msg=(impl, la))
            l, info = potrf_dist(sd, lookahead=la, bcast_impl=impl)
            assert int(info) == 0
            outp = np.asarray(to_dense(l))
            if ref_potrf is None:
                ref_potrf = outp
            np.testing.assert_array_equal(outp, ref_potrf, err_msg=(impl, la))


# ---------------------------------------------------------------------------
# option plumbing: opts / context / environment, with precedence
# ---------------------------------------------------------------------------


def _bcast_ops(run):
    jax.clear_caches()  # audit hooks record at trace time only
    with comm_audit() as recs:
        run()
    return {op.split("[")[0] for op, _, _ in recs}


def test_bcast_impl_plumbs_through_driver_opts(rng):
    from slate_tpu.parallel import gemm_mesh

    mesh = mesh24()
    a = jnp.asarray(rng.standard_normal((N, N)))
    b = jnp.asarray(rng.standard_normal((N, N)))

    run = lambda impl: gemm_mesh(
        1.0, a, b, mesh, nb=NB, opts={Option.BcastImpl: impl}
    ).block_until_ready()
    assert _bcast_ops(lambda: run("psum")) == {"psum"}
    assert _bcast_ops(lambda: run("ring")) == {"ppermute"}
    assert _bcast_ops(lambda: run("auto")) == {"ppermute"}  # 2x4: pow-2 axes


def test_bcast_impl_context_and_env_defaults(rng, monkeypatch):
    mesh = mesh24()
    a = from_dense(jnp.asarray(rng.standard_normal((N, N))), mesh, NB)
    b = from_dense(jnp.asarray(rng.standard_normal((N, N))), mesh, NB)
    run = lambda **kw: gemm_summa(
        1.0, a, b, method=MethodGemm.GemmC, **kw
    ).tiles.block_until_ready()

    # environment default
    monkeypatch.setenv(comm.BCAST_IMPL_ENV, "psum")
    assert _bcast_ops(run) == {"psum"}
    # context beats environment
    with use_bcast_impl("ring"):
        assert _bcast_ops(run) == {"ppermute"}
        # explicit argument beats context
        assert _bcast_ops(lambda: run(bcast_impl="psum")) == {"psum"}
    # unknown values fail loudly, at resolve time
    with pytest.raises(ValueError, match="unknown bcast impl"):
        run(bcast_impl="carrier-pigeon")
    monkeypatch.setenv(comm.BCAST_IMPL_ENV, "telepathy")
    with pytest.raises(ValueError, match="unknown bcast impl"):
        run()


def test_resolve_default_is_auto(monkeypatch):
    monkeypatch.delenv(comm.BCAST_IMPL_ENV, raising=False)
    assert comm.resolve_bcast_impl() == "auto"
    assert comm.resolve_bcast_impl("ring") == "ring"


# ---------------------------------------------------------------------------
# owner-rooted reduce: the tileReduce counterpart
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl", IMPLS)
def test_reduce_to_owner_sums_deterministically(impl):
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from slate_tpu.parallel.comm import (
        bcast_impl_scope, reduce_to_col, reduce_to_row, shard_map_compat,
    )
    from slate_tpu.parallel.mesh import COL_AXIS, ROW_AXIS

    p, q = 2, 4
    mesh = mesh24()
    spec = P(ROW_AXIS, COL_AXIS)
    # integer-valued payloads: sums are exact, so ALL lowerings (psum's
    # backend order included) must agree bitwise
    x = (jnp.arange(8.0).reshape(p, q)[..., None] + 1) * jnp.ones((1, 1, 4))

    def kernel(v):
        rc = reduce_to_col(v, 2)
        rr = reduce_to_row(v, 1)
        return rc, rr

    with bcast_impl_scope(impl):
        rc, rr = shard_map_compat(
            kernel, mesh=mesh, in_specs=(spec,), out_specs=(spec, spec),
            check_vma=False,
        )(x)
    rc, rr = np.asarray(rc)[..., 0], np.asarray(rr)[..., 0]
    xs = np.asarray(x)[..., 0]
    # column 2 holds the row sums; every other column is zeros
    expect_c = np.zeros_like(xs)
    expect_c[:, 2] = xs.sum(axis=1)
    np.testing.assert_array_equal(rc, expect_c)
    expect_r = np.zeros_like(xs)
    expect_r[1, :] = xs.sum(axis=0)
    np.testing.assert_array_equal(rr, expect_r)
