"""Telemetry-spine tests (ISSUE 17): one TraceContext from socket to
step — trace_id constancy across the degradation ladder, batch-abort
bystander identity, tenant-tagged attribution isolation, the live
telemetry bus + HTTP scrape endpoint, the unified Perfetto export, the
rotating RunReport ledger, and ``obs.report --trend`` exit codes.

Budget notes: the mesh cases reuse test_serve's exact router opts and
shapes (n = 64, nb = 8 on the 2x4 mesh — programs already compiled by
the degradation-ladder suite); everything else is meshless n = 32 or
pure-host (bus/ledger/trend).  The flight StepEvent case re-runs step
dispatch and rides at ``-m slow``.
"""

import json
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu import obs
from slate_tpu.obs import live
from slate_tpu.obs import report as obs_report
from slate_tpu.obs.metrics import REGISTRY
from slate_tpu.parallel.mesh import make_mesh
from slate_tpu.serve import trace as rtrace
from slate_tpu.serve.router import Router
from slate_tpu.types import Option, SlateError

from conftest import cpu_devices


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def _resilient_router(opts):
    return Router(mesh=mesh24(), nb=8, bins=(64,), opts=opts)


def _spd_one(rng, n=64):
    g = rng.standard_normal((n, n))
    return jnp.asarray(g @ g.T / n + 2 * np.eye(n))


def _hex16(s):
    return isinstance(s, str) and len(s) == 16 and all(
        c in "0123456789abcdef" for c in s)


# ---------------------------------------------------------------------------
# trace_id constancy: the degradation ladder keeps ONE id
# ---------------------------------------------------------------------------


def test_ladder_resume_keeps_one_trace_id(rng):
    """A preempted-then-resumed request re-dispatches under the SAME
    RequestTrace, so every driver span recorded across both dispatches
    carries the one trace_id (and the submitting tenant) — the resume
    is one request's story, not two."""
    from slate_tpu.ft import inject

    router = _resilient_router({Option.Checkpoint: 3,
                                Option.NumMonitor: "off"})
    a = _spd_one(rng)
    b = jnp.asarray(rng.standard_normal((64, 2)))
    with obs.force_enabled(True):
        before_tr = len(rtrace.finished_traces())
        before_sp = len(obs.FINISHED)
        with inject.fault_scope(
            inject.FaultPlan([inject.KillFault("potrf", 4)])
        ):
            router.solve("posv", a, b, tenant="acme")
        traces = rtrace.finished_traces()[before_tr:]
        spans = obs.FINISHED[before_sp:]
    assert len(traces) == 1
    tr = traces[0]
    assert tr.outcome == "served_resume"
    assert _hex16(tr.trace_id)
    tagged = [s for s in spans if s["tags"].get("trace_id")]
    # both dispatches (pre-kill + resume) record spans under the request
    assert len(tagged) >= 2
    assert {s["tags"]["trace_id"] for s in tagged} == {tr.trace_id}
    assert {s["tags"].get("tenant") for s in tagged} == {"acme"}


def test_batch_abort_bystander_gets_own_trace_id(rng):
    """The failing request and its batch-abort bystander are DIFFERENT
    requests: distinct trace_ids, each cause attributed to its own."""
    n = 32
    router = Router(bins=(32,), hbm_budget=1 << 30)
    g = rng.standard_normal((n, n))
    good = jnp.asarray(g @ g.T / n + 2 * np.eye(n))
    b = jnp.asarray(rng.standard_normal((n, 2)))
    with obs.force_enabled(True):
        before = len(rtrace.finished_traces())
        with pytest.raises(SlateError, match="nonzero info"):
            router.solve_batch([("posv", good, b),
                                ("posv", jnp.asarray(-np.eye(n)), b)],
                               tenants=["acme", "zeta"])
        traces = rtrace.finished_traces()[before:]
    assert sorted(t.outcome for t in traces) \
        == ["failed_info", "reject_batch_abort"]
    ids = {t.trace_id for t in traces}
    assert len(ids) == 2 and all(_hex16(i) for i in ids)
    by_outcome = {t.outcome: t for t in traces}
    assert by_outcome["failed_info"].tenant != \
        by_outcome["reject_batch_abort"].tenant


# ---------------------------------------------------------------------------
# tenant attribution: isolated registry series, tenant-free SLA pools
# ---------------------------------------------------------------------------


def test_tenant_histogram_isolation(rng):
    """Each tenant's served latency lands in its OWN
    (op, klass, outcome, tenant) series; a tenant-less request keeps the
    exact historical tag set (no tenant key); and the pooled SLA
    reduction stays tenant-free."""
    n = 32
    router = Router(bins=(32,), hbm_budget=1 << 30)
    good = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n))
    b = jnp.asarray(rng.standard_normal((n, 2)))
    with obs.force_enabled(True):
        router.solve("gesv", good, b, tenant="acme")
        router.solve("gesv", good, b, tenant="zeta")
        router.solve("gesv", good, b)  # tenant-less
    series = REGISTRY.histogram_series("serve.latency_s")
    served = [h for h in series if h["tags"].get("op") == "gesv"
              and h["tags"].get("outcome") == "served"]
    tenants = {h["tags"].get("tenant") for h in served}
    assert {"acme", "zeta"} <= tenants
    # the tenant-less stream keeps its historical tag set exactly
    bare = [h for h in served if "tenant" not in h["tags"]]
    assert bare and all(set(h["tags"]) == {"op", "klass", "outcome"}
                        for h in bare)
    # per-tenant series are isolated: distinct series objects, each with
    # its own count
    for t in ("acme", "zeta"):
        own = [h for h in served if h["tags"].get("tenant") == t]
        assert own and own[-1]["count"] >= 1
    # pooled SLA keys never grow a tenant dimension
    assert not any("acme" in k or "zeta" in k for k in rtrace.sla_values())


# ---------------------------------------------------------------------------
# the live bus + scrape endpoint
# ---------------------------------------------------------------------------


def test_bus_carries_span_request_mem_events(rng):
    """With obs.live imported, span exits / trace finishes / memory
    samples publish onto the bus, all carrying the request's
    trace_id."""
    from slate_tpu.obs import memory

    n = 32
    router = Router(bins=(32,), hbm_budget=1 << 30)
    good = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n))
    b = jnp.asarray(rng.standard_normal((n, 2)))
    since = live.BUS.last_seq()
    with obs.force_enabled(True), memory.force_sampling(True):
        before = len(rtrace.finished_traces())
        router.solve("gesv", good, b, tenant="acme")
        tr = rtrace.finished_traces()[before:][0]
    evs = live.BUS.events(since=since)
    kinds = {e["kind"] for e in evs}
    assert {"span", "request", "mem"} <= kinds
    req = [e for e in evs if e["kind"] == "request"]
    assert any(e["data"].get("trace_id") == tr.trace_id for e in req)
    sp = [e for e in evs if e["kind"] == "span"
          and e["data"]["tags"].get("trace_id") == tr.trace_id]
    assert sp
    mem_evs = [e for e in evs if e["kind"] == "mem"]
    assert any(e["data"].get("trace_id") == tr.trace_id for e in mem_evs)


def test_scrape_endpoint_serves_validated_text(rng):
    """The stdlib-http endpoint scrapes the LIVE registry: /metrics is
    validator-clean Prometheus text, /snapshot.json and /events.json
    parse, /healthz answers."""
    n = 32
    router = Router(bins=(32,), hbm_budget=1 << 30)
    good = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n))
    b = jnp.asarray(rng.standard_normal((n, 2)))
    since = live.BUS.last_seq()
    with obs.force_enabled(True):
        router.solve("gesv", good, b, tenant="acme")
    srv, _thread, port = live.start_server(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert live.validate_prometheus_text(text) == []
        assert "slate_tpu_serve_requests" in text
        assert 'tenant="acme"' in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/snapshot.json", timeout=10) as r:
            snap = json.loads(r.read().decode())
        assert snap["finished_requests"] >= 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/events.json?since={since}",
                timeout=10) as r:
            page = json.loads(r.read().decode())
        assert page["events"] and page["last_seq"] > since
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            body = r.read().decode()
        # queue-aware liveness since ISSUE 19: first line stays "ok",
        # the second reports the service layer's live queues
        assert body.splitlines()[0] == "ok"
        assert "queues" in body and "open_windows" in body
    finally:
        srv.shutdown()


def test_bus_bounded_ring_semantics():
    """The bus is a bounded ring: capped length, dropped counter,
    monotonic seq, since-filtering."""
    bus = live.TelemetryBus(cap=8)
    seqs = [bus.publish("t", {"i": i}) for i in range(12)]
    assert seqs == sorted(seqs) and len(set(seqs)) == 12
    assert len(bus) == 8
    assert bus.dropped == 4
    evs = bus.events()
    assert [e["data"]["i"] for e in evs] == list(range(4, 12))
    tail = bus.events(since=seqs[-3])
    assert [e["data"]["i"] for e in tail] == [10, 11]
    assert bus.events(since=bus.last_seq()) == []


# ---------------------------------------------------------------------------
# the unified Perfetto export
# ---------------------------------------------------------------------------


def test_unified_trace_correlates_tracks(rng):
    """ONE trace: request track + driver spans + mem counters, tied by
    trace_id flow arrows — >= 3 track categories correlated by the one
    request's id, validator-clean."""
    from slate_tpu.obs import memory, perfetto

    n = 32
    router = Router(bins=(32,), hbm_budget=1 << 30)
    good = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n))
    b = jnp.asarray(rng.standard_normal((n, 2)))
    with obs.force_enabled(True), memory.force_sampling(True):
        before = len(rtrace.finished_traces())
        router.solve("gesv", good, b, tenant="acme")
        traces = rtrace.finished_traces()[before:]
    tr = traces[0]
    doc = perfetto.unified_chrome_trace(traces)
    assert perfetto.validate_chrome_trace(doc) == []
    cats = {e.get("cat") for e in doc["traceEvents"]
            if (e.get("args") or {}).get("trace_id") == tr.trace_id}
    assert len(cats) >= 3, cats
    assert "traceflow" in cats  # the flow arrows that tie it together
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "traceflow"]
    assert {e["ph"] for e in flows} == {"s", "f"}


# ---------------------------------------------------------------------------
# ledger rotation + --trend exit codes
# ---------------------------------------------------------------------------


def _mini_report(i, values):
    return {
        "schema": obs_report.SCHEMA, "version": obs_report.VERSION,
        "name": "spine_t", "created_unix": 1000.0 + i, "env": {},
        "config": {}, "values": dict(values),
        "metrics": {"counters": [], "gauges": [], "histograms": []},
        "spans": [],
    }


def test_ledger_rotation_and_trace_id_stamp(tmp_path):
    d = str(tmp_path / "ledger")
    for i in range(5):
        live.ledger_append(_mini_report(i, {"x": float(i)}), d, cap=3)
    paths = live.ledger_paths(d)
    assert len(paths) == 3  # rotated: oldest two pruned
    docs = live.ledger_load(d)
    assert [doc["values"]["x"] for doc in docs] == [2.0, 3.0, 4.0]
    for doc in docs:
        assert _hex16(doc["config"]["trace_id"])
        # the filename embeds the stamped id's prefix (joinability)
        assert doc["config"]["trace_id"][:8] in doc["_ledger_path"]


def test_trend_gate_exit_codes(tmp_path, capsys):
    """--trend: < 3 usable entries => 2 (inconclusive); stable history
    => 0; a regressed newest entry => 1."""
    d = str(tmp_path / "ledger")
    vals = {"spine_seconds": 1.0, "spine_gflops": 10.0}
    live.ledger_append(_mini_report(0, vals), d)
    live.ledger_append(_mini_report(1, vals), d)
    assert obs_report.main(["--trend", d]) == 2  # too thin to gate
    live.ledger_append(_mini_report(2, vals), d)
    live.ledger_append(_mini_report(3, vals), d)
    assert obs_report.main(["--trend", d]) == 0  # stable vs median
    live.ledger_append(
        _mini_report(4, {"spine_seconds": 10.0, "spine_gflops": 10.0}), d)
    assert obs_report.main(["--trend", d]) == 1  # 10x slower than median
    out = capsys.readouterr().out
    assert "spine_seconds" in out and "regression" in out


def test_trend_new_key_inconclusive_not_fatal(tmp_path, capsys):
    """A key present only in the newest entry cannot have a trend — it
    reports INCONCLUSIVE, it does not fail the gate."""
    d = str(tmp_path / "ledger")
    for i in range(3):
        live.ledger_append(_mini_report(i, {"spine_seconds": 1.0}), d)
    live.ledger_append(
        _mini_report(3, {"spine_seconds": 1.0, "fresh_bytes": 5.0}), d)
    assert obs_report.main(["--trend", d]) == 0
    assert "fresh_bytes" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# one formatter + off-mode honesty
# ---------------------------------------------------------------------------


def test_stats_shim_delegates_to_live():
    """serve.stats is a delegating shim: ONE Prometheus formatter lives
    in obs.live (identity, not copies)."""
    from slate_tpu.serve import stats

    assert stats.prometheus_text is live.prometheus_text
    assert stats.stats_snapshot is live.stats_snapshot
    assert stats.validate_prometheus_text is live.validate_prometheus_text
    assert stats.snapshot_from_report is live.snapshot_from_report


def test_context_off_mode_costs_nothing(rng):
    """Obs off: no trace, no ambient context, use_context(None) is a
    pass-through, and driver spans record nothing — the spine is
    host-side only and fully dark when disabled."""
    from slate_tpu.obs import context as obs_context

    with obs.force_enabled(False):
        assert rtrace.new_trace("gesv", 32, 8, "float64") is None
        assert obs_context.current() is None
        with obs_context.use_context(None) as ctx:
            assert ctx is None and obs_context.current() is None
        assert obs_context.event_tags() == {}
        before = len(obs.FINISHED)
        with obs.driver_span("spine_off_probe"):
            pass
        assert len(obs.FINISHED) == before


# ---------------------------------------------------------------------------
# flight StepEvents join the spine (step dispatch re-run: slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_flight_step_events_carry_trace_id(rng):
    """StepEvents recorded while a TraceContext is ambient stamp the
    request's trace_id + tenant — the flight Gantt joins the unified
    trace by id."""
    from slate_tpu.obs import flight
    from slate_tpu.parallel import from_dense
    from slate_tpu.parallel.dist_chol import potrf_dist

    a = from_dense(_spd_one(rng), mesh24(), 8, diag_pad_one=True)
    ctx = obs.TraceContext(obs.new_trace_id(), tenant="acme",
                           klass="friendly", rid=0, op="potrf")
    with obs.force_enabled(True), obs.use_context(ctx):
        with flight.flight_scope() as rec:
            potrf_dist(a)
    assert rec.events
    assert {e.trace_id for e in rec.events} == {ctx.trace_id}
    assert {e.tenant for e in rec.events} == {"acme"}
