"""Fused Pallas trailing-update kernels (PR 20): Option.UpdateImpl
end-to-end, plus the pivoted-panel fusion riding the same PR.

Contracts under test, on CPU with every kernel running under the Pallas
interpreter (the tier-1 parity story — the same kernels compile for the
MXU on a real TPU backend):

1. Every fused trailing-update kernel matches its XLA einsum bulk form
   BITWISE: unlike the panel factor kernels, the update kernels
   replicate the XLA op sequence exactly (contraction at HIGHEST →
   astype → select → add/subtract), so the interpreter must reproduce
   the einsum forms bit for bit — at kernel level AND through the mesh
   drivers (gemm_summa consume, potrf trailing herk, LU-nopiv trailing
   gemm), aligned and ragged, at every lookahead depth.
2. ``Option.UpdateImpl = xla`` IS today's trace (identical jaxpr), and
   ``auto`` resolves to xla off-TPU — the default tier-1 schedules are
   untouched.
3. The option plumbs through driver ``update_impl=``, the
   ``use_update_impl`` context, and the ``SLATE_TPU_UPDATE_IMPL``
   environment default, with explicit > context > environment
   precedence; complex dtypes fall back to xla even when pallas is
   requested.
4. The comm-audit byte totals are UpdateImpl-invariant: the fused
   dispatch sits strictly inside the compute half of each k-step.
5. The pivoted panels unlocked this PR dispatch Pallas under
   Option.PanelImpl: the tntpiv/pp panel factor+rowsolve and the
   dist-QR offset panels (tntpiv to the documented tolerance class with
   BITWISE pivot decisions; pp and QR bitwise).
6. The serving tier's ``gels`` route polices the recorded QR
   orthogonality-loss gauge: a factor past ``ORTH_THRESHOLD`` costs one
   counted re-orthogonalization retry, not a bad solution.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import cpu_devices

from slate_tpu.ops import pallas_ops as po
from slate_tpu.parallel import from_dense, make_mesh, to_dense
from slate_tpu.parallel.dist_chol import potrf_dist
from slate_tpu.parallel.dist_lu import (
    getrf_nopiv_dist,
    getrf_pp_dist,
    getrf_tntpiv_dist,
)
from slate_tpu.parallel.summa import MethodGemm, gemm_summa
from slate_tpu.types import Option

N, NB = 64, 8
DTYPES = [jnp.float32, jnp.float64]


def mesh24():
    return make_mesh(2, 4, devices=cpu_devices(8))


def _spd(rng, n, dtype):
    g = rng.standard_normal((n, n))
    return jnp.asarray(g @ g.T + n * np.eye(n), dtype)


def _diag_dom(rng, n, dtype):
    return jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n), dtype)


# ---------------------------------------------------------------------------
# kernel-level parity vs the XLA bulk forms: BITWISE under interpret
# ---------------------------------------------------------------------------

_HI = jax.lax.Precision.HIGHEST


def _update_operands(rng, dtype, mtl=3, ntl=4, nb=NB):
    acc = jnp.asarray(rng.standard_normal((mtl, ntl, nb, nb)), dtype)
    pan = jnp.asarray(rng.standard_normal((mtl, nb, nb)), dtype)
    pan_t = jnp.asarray(rng.standard_normal((ntl, nb, nb)), dtype)
    urow = jnp.asarray(rng.standard_normal((ntl, nb, nb)), dtype)
    lower = jnp.asarray(
        np.arange(mtl)[:, None] >= np.arange(ntl)[None, :]
    )
    return acc, pan, pan_t, urow, lower


@pytest.mark.parametrize("dtype", DTYPES)
def test_summa_update_kernel_bitwise(rng, dtype):
    acc, pan, _, urow, _ = _update_operands(rng, dtype)
    out = po.summa_update_pallas(acc, pan, urow)
    upd = jnp.einsum("iab,jbc->ijac", pan, urow, precision=_HI)
    ref = acc + upd.astype(acc.dtype)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("dtype", DTYPES)
def test_chol_trailing_kernel_bitwise(rng, dtype):
    acc, pan, pan_t, _, lower = _update_operands(rng, dtype)
    out = po.chol_trailing_update_pallas(acc, pan, pan_t, lower)
    upd = jnp.einsum(
        "iab,jcb->ijac", pan, pan_t, precision=_HI
    ).astype(acc.dtype)
    ref = acc - jnp.where(lower[:, :, None, None], upd, 0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("dtype", DTYPES)
def test_lu_trailing_kernel_bitwise(rng, dtype):
    acc, pan, _, urow, lower = _update_operands(rng, dtype)
    out = po.lu_trailing_update_pallas(acc, pan, urow, lower)
    upd = jnp.einsum("iab,jbc->ijac", pan, urow, precision=_HI)
    ref = acc - jnp.where(lower[:, :, None, None], upd.astype(acc.dtype), 0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


# ---------------------------------------------------------------------------
# driver-level parity: mesh kernels bitwise across lowerings
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [N, N - 4], ids=["aligned", "ragged-tail"])
@pytest.mark.parametrize("dtype", DTYPES)
def test_gemm_summa_update_pallas_bitwise(rng, n, dtype):
    mesh = mesh24()
    a = jnp.asarray(rng.standard_normal((n, n)), dtype)
    b = jnp.asarray(rng.standard_normal((n, n)), dtype)
    outs = {}
    for impl in ("xla", "pallas"):
        c = gemm_summa(
            1.0, from_dense(a, mesh, NB), from_dense(b, mesh, NB),
            method=MethodGemm.GemmC, update_impl=impl,
        )
        outs[impl] = np.asarray(to_dense(c))
    np.testing.assert_array_equal(outs["pallas"], outs["xla"])


@pytest.mark.parametrize("n", [N, N - 4], ids=["aligned", "ragged-tail"])
@pytest.mark.parametrize("dtype", DTYPES)
def test_potrf_dist_update_pallas_bitwise(rng, n, dtype):
    mesh = mesh24()
    ad = from_dense(_spd(rng, n, dtype), mesh, NB, diag_pad_one=True)
    l_x, info_x = potrf_dist(ad, update_impl="xla")
    l_p, info_p = potrf_dist(ad, update_impl="pallas")
    assert int(info_x) == 0 and int(info_p) == int(info_x)
    np.testing.assert_array_equal(
        np.asarray(to_dense(l_p)), np.asarray(to_dense(l_x))
    )


@pytest.mark.parametrize("n", [N, N - 4], ids=["aligned", "ragged-tail"])
@pytest.mark.parametrize("dtype", DTYPES)
def test_getrf_nopiv_dist_update_pallas_bitwise(rng, n, dtype):
    mesh = mesh24()
    ad = from_dense(_diag_dom(rng, n, dtype), mesh, NB, diag_pad_one=True)
    lu_x, info_x = getrf_nopiv_dist(ad, update_impl="xla")
    lu_p, info_p = getrf_nopiv_dist(ad, update_impl="pallas")
    assert int(info_x) == 0 and int(info_p) == int(info_x)
    np.testing.assert_array_equal(
        np.asarray(to_dense(lu_p)), np.asarray(to_dense(lu_x))
    )


@pytest.mark.parametrize("depth", [0, 1, 2])
def test_lookahead_depth_invariant_under_pallas(rng, depth):
    """Lookahead moves WHEN the fused update runs, never what it
    computes: every depth must land the depth-0 bits under pallas."""
    mesh = mesh24()
    ad = from_dense(_spd(rng, N, jnp.float64), mesh, NB, diag_pad_one=True)
    l0, _ = potrf_dist(ad, lookahead=0, update_impl="pallas")
    ld, info = potrf_dist(ad, lookahead=depth, update_impl="pallas")
    assert int(info) == 0
    np.testing.assert_array_equal(
        np.asarray(to_dense(ld)), np.asarray(to_dense(l0))
    )


# ---------------------------------------------------------------------------
# UpdateImpl=xla is today's trace; plumbing and precedence
# ---------------------------------------------------------------------------


def test_update_impl_xla_is_todays_trace(rng):
    """``xla`` and off-TPU ``auto`` must produce the IDENTICAL jaxpr for
    every routed driver — the acceptance bar that UpdateImpl=xla
    reproduces today's results bitwise."""
    mesh = mesh24()
    spd = from_dense(_spd(rng, N, jnp.float64), mesh, NB, diag_pad_one=True)
    dd = from_dense(_diag_dom(rng, N, jnp.float64), mesh, NB,
                    diag_pad_one=True)
    g = from_dense(jnp.asarray(rng.standard_normal((N, N))), mesh, NB)
    runs = {
        "summa": lambda impl: (lambda x: gemm_summa(
            1.0, x, g, method=MethodGemm.GemmC, update_impl=impl)),
        "potrf": lambda impl: (lambda x: potrf_dist(x, update_impl=impl)),
        "getrf": lambda impl: (
            lambda x: getrf_nopiv_dist(x, update_impl=impl)),
    }
    operands = {"summa": g, "potrf": spd, "getrf": dd}
    for name, mk in runs.items():
        jx = {impl: str(jax.make_jaxpr(mk(impl))(operands[name]))
              for impl in ("xla", "auto")}
        assert jx["auto"] == jx["xla"], name
        assert "pallas_call" not in jx["xla"], name


def _uses_pallas(run):
    jax.clear_caches()  # trace-time dispatch (cf. the panel-impl tests)
    return "pallas_call" in str(jax.make_jaxpr(run)())


def test_update_impl_context_and_env_defaults(rng, monkeypatch):
    mesh = mesh24()
    ad = from_dense(_spd(rng, N, jnp.float64), mesh, NB, diag_pad_one=True)

    def run(**kw):
        return lambda: potrf_dist(ad, **kw)

    # environment default
    monkeypatch.setenv(po.UPDATE_IMPL_ENV, "pallas")
    assert _uses_pallas(run())
    # context beats environment
    with po.use_update_impl("xla"):
        assert not _uses_pallas(run())
        # explicit argument beats context
        assert _uses_pallas(run(update_impl="pallas"))
    # unknown values fail loudly, at resolve time
    with pytest.raises(ValueError, match="unknown update impl"):
        potrf_dist(ad, update_impl="fpga")
    monkeypatch.setenv(po.UPDATE_IMPL_ENV, "abacus")
    with pytest.raises(ValueError, match="unknown update impl"):
        potrf_dist(ad)


def test_update_impl_plumbs_through_driver_opts(rng):
    from slate_tpu.parallel import potrf_mesh

    mesh = mesh24()
    a = _spd(rng, N, jnp.float64)
    run = lambda impl: (lambda: potrf_mesh(a, mesh, nb=NB,
                                           opts={Option.UpdateImpl: impl}))
    assert not _uses_pallas(run("xla"))
    assert _uses_pallas(run("pallas"))
    assert not _uses_pallas(run("auto"))  # off-TPU auto -> xla


def test_resolve_update_default_is_auto(monkeypatch):
    monkeypatch.delenv(po.UPDATE_IMPL_ENV, raising=False)
    assert po.resolve_update_impl() == "auto"
    assert po.resolve_update_impl("pallas") == "pallas"


def test_complex_update_falls_back_to_xla(rng):
    """Complex trailing updates have no fused kernel: requesting pallas
    must trace the XLA einsum forms rather than fail."""
    mesh = mesh24()
    g = rng.standard_normal((N, N)) + 1j * rng.standard_normal((N, N))
    a = jnp.asarray(g @ g.conj().T + N * np.eye(N), jnp.complex128)
    ad = from_dense(a, mesh, NB, diag_pad_one=True)
    jx = str(jax.make_jaxpr(
        lambda x: potrf_dist(x, update_impl="pallas")
    )(ad))
    assert "pallas_call" not in jx
    l, info = potrf_dist(ad, update_impl="pallas")
    assert int(info) == 0


def test_update_bytes_invariant_across_impls(rng):
    """The fused dispatch sits strictly inside the compute half of each
    k-step: the audited collective schedule (ops, payloads, multiplier
    totals) must be IDENTICAL across UpdateImpl."""
    from slate_tpu.parallel.comm import comm_audit

    mesh = mesh24()
    spd = from_dense(_spd(rng, N, jnp.float64), mesh, NB, diag_pad_one=True)
    g = from_dense(jnp.asarray(rng.standard_normal((N, N))), mesh, NB)
    runs = {
        "potrf": (lambda x, impl: potrf_dist(x, update_impl=impl), spd),
        "summa": (lambda x, impl: gemm_summa(
            1.0, x, g, method=MethodGemm.GemmC, update_impl=impl), g),
    }
    for name, (fn, operand) in runs.items():
        recs = {}
        for impl in ("xla", "pallas"):
            jax.clear_caches()
            with comm_audit() as r:
                jax.make_jaxpr(lambda x: fn(x, impl))(operand)
            recs[impl] = sorted((op, nb, m) for op, nb, m in r)
        assert recs["pallas"] == recs["xla"], name


def test_flight_on_bitwise_and_bytes_unchanged(rng):
    """Under the flight recorder's per-step fenced dispatch the fused
    update keeps the SAME phase events and byte attribution as the xla
    loop, and the results stay bitwise — the ScheduleModel sees one
    schedule regardless of UpdateImpl."""
    from slate_tpu.obs import flight, schedule

    mesh = mesh24()
    ad = from_dense(_spd(rng, N, jnp.float64), mesh, NB, diag_pad_one=True)
    outs, rows = {}, {}
    for impl in ("xla", "pallas"):
        with flight.flight_scope() as rec:
            l, info = potrf_dist(ad, lookahead=1, update_impl=impl)
        assert int(info) == 0
        outs[impl] = np.asarray(to_dense(l))
        rows[impl] = [
            (r["phase"], r["k"], r["bytes"])
            for r in schedule.rows_from_events(rec.events)
        ]
    np.testing.assert_array_equal(outs["pallas"], outs["xla"])
    assert rows["pallas"] == rows["xla"]
    # the fenced pallas dispatch matches the plain (unfenced) kernel too
    l_plain, _ = potrf_dist(ad, lookahead=1, update_impl="pallas")
    np.testing.assert_array_equal(
        outs["pallas"], np.asarray(to_dense(l_plain))
    )


# ---------------------------------------------------------------------------
# pivoted-panel fusion: tntpiv / pp / dist-QR panels under PanelImpl
# ---------------------------------------------------------------------------


def test_getrf_tntpiv_dist_panel_pallas(rng):
    """Tournament-pivot LU under the fused panel kernels: the PIVOT
    DECISIONS are bitwise (the tournament itself stays XLA) and the
    factors land the documented-tolerance parity class of
    ``lu_panel_tiles_pallas`` (explicit-inverse solve)."""
    mesh = mesh24()
    a = jnp.asarray(rng.standard_normal((N, N)))
    ad = from_dense(a, mesh, NB, diag_pad_one=True)
    outs = {}
    for impl in ("xla", "pallas"):
        lu, perm, info = getrf_tntpiv_dist(ad, panel_impl=impl)
        assert int(info) == 0, impl
        outs[impl] = (np.asarray(to_dense(lu), np.float64)[:N, :N],
                      np.asarray(perm))
    np.testing.assert_array_equal(outs["pallas"][1], outs["xla"][1])
    an = np.asarray(a, np.float64)
    for impl, (lun, perm) in outs.items():
        rec = (np.tril(lun, -1) + np.eye(N)) @ np.triu(lun)
        err = np.abs(rec - an[perm]).max()
        assert err < 1e-10 * N * np.abs(an).max(), (impl, err)


def test_getrf_pp_dist_panel_pallas_bitwise(rng):
    """Partial-pivot LU's panel rowsolve is the same op sequence inside
    and outside the kernel — bitwise, pivots included."""
    mesh = mesh24()
    ad = from_dense(jnp.asarray(rng.standard_normal((N, N))), mesh, NB,
                    diag_pad_one=True)
    lu_x, perm_x, info_x = getrf_pp_dist(ad, panel_impl="xla")
    lu_p, perm_p, info_p = getrf_pp_dist(ad, panel_impl="pallas")
    assert int(info_x) == 0 and int(info_p) == int(info_x)
    np.testing.assert_array_equal(np.asarray(perm_p), np.asarray(perm_x))
    np.testing.assert_array_equal(
        np.asarray(to_dense(lu_p)), np.asarray(to_dense(lu_x))
    )


def test_geqrf_dist_panel_pallas_bitwise(rng):
    """The CAQR offset panels ride ``qr_panel_offset_pallas`` — same
    Householder op sequence, so every factor array is bitwise."""
    from slate_tpu.parallel.dist_qr import geqrf_dist

    mesh = mesh24()
    a = jnp.asarray(rng.standard_normal((N, N // 2)))
    f_x = geqrf_dist(from_dense(a, mesh, NB), panel_impl="xla")
    f_p = geqrf_dist(from_dense(a, mesh, NB), panel_impl="pallas")
    np.testing.assert_array_equal(
        np.asarray(to_dense(f_p.fact)), np.asarray(to_dense(f_x.fact))
    )
    for got, ref in ((f_p.tloc, f_x.tloc), (f_p.treev, f_x.treev),
                     (f_p.treet, f_x.treet)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


# ---------------------------------------------------------------------------
# serving tier: the gels route polices the QR orthogonality gauge
# ---------------------------------------------------------------------------


def _ls_router(opts=None):
    from slate_tpu.serve.router import Router

    return Router(mesh=mesh24(), nb=NB, bins=(64,), opts=opts or {})


def test_router_gels_serves_least_squares(rng):
    router = _ls_router()
    a = jnp.asarray(rng.standard_normal((N, N // 2)))
    b = jnp.asarray(rng.standard_normal(N))
    x = router.gels(a, b)
    assert x.shape == (N // 2,)
    an, bn = np.asarray(a), np.asarray(b)
    # least-squares optimality: the residual is normal to range(A)
    grad = an.T @ (an @ np.asarray(x) - bn)
    assert np.abs(grad).max() < 1e-8


def test_router_gels_orth_retry(rng, monkeypatch):
    """A monitored factor past ORTH_THRESHOLD costs exactly one counted
    re-orthogonalization retry — and the served solution is still the
    least-squares optimum (the two-factor solve folds R2 R1)."""
    from slate_tpu.obs import numerics as _num
    from slate_tpu.serve import metrics as serve_metrics

    router = _ls_router({Option.NumMonitor: "on"})
    a = jnp.asarray(rng.standard_normal((N, N // 2)))
    b = jnp.asarray(rng.standard_normal((N, 2)))
    # a healthy panel records ~eps loss: force the police to trip
    monkeypatch.setattr(_num, "ORTH_THRESHOLD", 0.0)
    before = serve_metrics.serve_counter_values()["retries"]
    x = router.gels(a, b)
    after = serve_metrics.serve_counter_values()["retries"]
    assert after == before + 1
    an, bn = np.asarray(a), np.asarray(b)
    grad = an.T @ (an @ np.asarray(x) - bn)
    assert np.abs(grad).max() < 1e-8


def test_router_gels_unmonitored_keeps_single_pass(rng, monkeypatch):
    """No gauge, no degradation action: an unmonitored request never
    pays the retry even when the threshold would trip."""
    from slate_tpu.obs import numerics as _num
    from slate_tpu.serve import metrics as serve_metrics

    router = _ls_router()
    monkeypatch.setattr(_num, "ORTH_THRESHOLD", 0.0)
    a = jnp.asarray(rng.standard_normal((N, N // 2)))
    b = jnp.asarray(rng.standard_normal(N))
    before = serve_metrics.serve_counter_values()["retries"]
    router.gels(a, b)
    after = serve_metrics.serve_counter_values()["retries"]
    assert after == before
