"""Windowed band tier (linalg/band.py) — correctness vs dense/LAPACK and
the O(n band^2) speed advantage (VERDICT round-1 item 8)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.linalg.band import gbsv_band, gbtrf_band, pbsv_band, pbtrf_band
from slate_tpu.linalg.chol import pbsv_array, potrf_array
from slate_tpu.linalg.lu import gbsv_array


def _band_matrix(rng, n, kl, ku, spd=False):
    a = np.zeros((n, n))
    for d in range(-kl, ku + 1):
        a += np.diag(rng.standard_normal(n - abs(d)), d)
    if spd:
        a = a @ a.T + n * np.eye(n)  # bandwidth kl + ku
    return a


@pytest.mark.parametrize("n,kd", [(100, 5), (257, 16), (64, 32)])
def test_pbsv_band(rng, n, kd):
    a = _band_matrix(rng, n, kd // 2, kd // 2, spd=True)
    b = np.asarray(rng.standard_normal((n, 3)))
    x, f, info = pbsv_band(jnp.asarray(a), jnp.asarray(b), kd)
    resid = np.abs(a @ np.asarray(x) - b).max() / np.abs(b).max()
    assert int(info) == 0 and resid < 1e-10
    # the factor matches the dense Cholesky
    ref = np.linalg.cholesky(a)
    assert np.abs(np.asarray(f.l) - ref).max() < 1e-10


@pytest.mark.parametrize("n,kl,ku", [(100, 4, 3), (257, 16, 8), (90, 1, 1)])
def test_gbsv_band(rng, n, kl, ku):
    a = _band_matrix(rng, n, kl, ku)  # non-dominant: real pivoting
    b = np.asarray(rng.standard_normal((n, 2)))
    x, f, info = gbsv_band(jnp.asarray(a), jnp.asarray(b), kl, ku)
    x = np.asarray(x)
    resid = np.abs(a @ x - b).max() / (np.abs(a).max() * max(1, np.abs(x).max()))
    assert int(info) == 0 and resid < 1e-11


def test_gbtrf_band_not_dominant_pivots(rng):
    # tiny leading diagonal forces within-window pivoting
    n, kl, ku = 64, 3, 2
    a = _band_matrix(rng, n, kl, ku)
    a[0, 0] = 1e-14
    b = np.asarray(rng.standard_normal((n, 1)))
    x, f, info = gbsv_band(jnp.asarray(a), jnp.asarray(b), kl, ku)
    assert int(info) == 0
    assert np.abs(a @ np.asarray(x) - b).max() / np.abs(b).max() < 1e-9


def test_public_band_routes_to_windowed(rng):
    # pbsv_array / gbsv_array pick the windowed path for narrow bands
    n, kd = 200, 6
    a = _band_matrix(rng, n, kd // 2, kd // 2, spd=True)
    b = np.asarray(rng.standard_normal((n, 2)))
    x, f, info = pbsv_array(jnp.asarray(a), jnp.asarray(b), kd)
    assert int(info) == 0
    assert np.abs(a @ np.asarray(x) - b).max() / np.abs(b).max() < 1e-10
    ag = _band_matrix(rng, n, 2, 2)
    xg, fg = gbsv_array(jnp.asarray(ag), jnp.asarray(b), 2, 2)
    assert np.abs(ag @ np.asarray(xg) - b).max() / np.abs(b).max() < 1e-9


def test_band_speed_advantage(rng):
    # the windowed path must beat dense by a wide margin at n >> kd
    n, kd = 2048, 32
    a = _band_matrix(rng, n, kd // 2, kd // 2, spd=True)
    aj = jnp.asarray(a)
    fb = jax.jit(lambda x: pbtrf_band(x, kd).l)
    fd = jax.jit(lambda x: potrf_array(x)[0])
    fb(aj).block_until_ready()
    fd(aj).block_until_ready()

    def best_of(fn, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn(aj).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best

    tb = best_of(fb)
    td = best_of(fd)
    # best-of-3 to damp scheduler noise; 1.5x is a wide margin for a path
    # that is asymptotically O(n kd^2) vs O(n^3)
    assert tb < td / 1.5, (tb, td)
