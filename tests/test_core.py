"""Unit tests for the matrix core (analog of unit_test/test_Matrix.cc,
test_Tile.cc, test_func.cc)."""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.core import grid, tiling
from slate_tpu.core.matrix import symmetrize, tri_project
from slate_tpu.types import Diag, GridOrder, Op, Uplo


def test_matrix_views(rng):
    a = rng.standard_normal((6, 4))
    m = st.Matrix.from_array(a)
    assert m.shape == (6, 4)
    t = m.transposed()
    assert t.shape == (4, 6)
    np.testing.assert_allclose(np.asarray(t.array), a.T)
    h = m.conj_transposed()
    np.testing.assert_allclose(np.asarray(h.array), a.T)  # real: H == T
    # double transpose round-trips
    np.testing.assert_allclose(np.asarray(t.transposed().array), a)


def test_complex_conj_transpose(rng):
    a = rng.standard_normal((3, 5)) + 1j * rng.standard_normal((3, 5))
    m = st.Matrix.from_array(a)
    np.testing.assert_allclose(np.asarray(m.conj_transposed().array), a.conj().T)
    np.testing.assert_allclose(np.asarray(m.conj_transposed().conj_transposed().array), a)
    np.testing.assert_allclose(np.asarray(m.transposed().conj_transposed().array), a.conj())


def test_slice(rng):
    a = rng.standard_normal((8, 8))
    m = st.Matrix.from_array(a)
    s = m.slice(2, 6, 1, 5)
    np.testing.assert_allclose(np.asarray(s.array), a[2:6, 1:5])
    # slicing a transposed view works in logical coordinates
    st_ = m.transposed().slice(1, 3, 2, 4)
    np.testing.assert_allclose(np.asarray(st_.array), a.T[1:3, 2:4])


def test_symmetrize(rng):
    a = rng.standard_normal((5, 5)) + 1j * rng.standard_normal((5, 5))
    full = np.asarray(symmetrize(jnp.asarray(a), Uplo.Lower, conj=True))
    np.testing.assert_allclose(full, full.conj().T)
    np.testing.assert_allclose(np.tril(full, -1), np.tril(a, -1))
    assert np.allclose(np.imag(np.diag(full)), 0)


def test_tri_project(rng):
    a = rng.standard_normal((4, 4))
    lo = np.asarray(tri_project(jnp.asarray(a), Uplo.Lower))
    np.testing.assert_allclose(lo, np.tril(a))
    un = np.asarray(tri_project(jnp.asarray(a), Uplo.Upper, Diag.Unit))
    np.testing.assert_allclose(un, np.triu(a, 1) + np.eye(4))


def test_band_matrix(rng):
    a = rng.standard_normal((6, 6))
    b = st.BandMatrix.from_array(a, kl=1, ku=2)
    d = np.asarray(b.data)
    assert d[3, 0] == 0 and d[0, 3] == 0
    assert d[2, 1] != 0 and d[1, 3] != 0


def test_grid_maps():
    f = grid.process_2d_grid(GridOrder.Col, 2, 3)
    assert f((0, 0)) == 0
    assert f((1, 0)) == 1
    assert f((0, 1)) == 2
    assert f((2, 3)) == f((0, 0))  # cyclic wrap
    bs = grid.uniform_blocksize(10, 4)
    assert [bs(i) for i in range(3)] == [4, 4, 2]
    assert grid.grid_2d_factor(8) == (2, 4)


def test_tiling_roundtrip(rng):
    a = jnp.asarray(rng.standard_normal((10, 7)))
    t = tiling.to_tiles(a, 4)
    assert t.shape == (3, 2, 4, 4)
    back = tiling.from_tiles(t, 10, 7)
    np.testing.assert_allclose(np.asarray(back), np.asarray(a))


def test_cyclic_roundtrip(rng):
    a = jnp.asarray(rng.standard_normal((16, 16)))
    t = tiling.to_tiles(a, 2)  # 8x8 tiles
    c = tiling.to_cyclic(t, 2, 4)
    back = tiling.from_cyclic(c, 2, 4)
    np.testing.assert_allclose(np.asarray(back), np.asarray(t))
    # row permutation alone: first half of storage rows are even logical rows
    c2 = tiling.to_cyclic(t, 2, 1)
    np.testing.assert_allclose(np.asarray(c2[0]), np.asarray(t[0]))
    np.testing.assert_allclose(np.asarray(c2[1]), np.asarray(t[2]))
    np.testing.assert_allclose(np.asarray(c2[4]), np.asarray(t[1]))
    assert list(tiling.cyclic_perm(8, 2)) == [0, 2, 4, 6, 1, 3, 5, 7]


def test_print_matrix_formats():
    from slate_tpu.utils.printing import sprint_matrix, sprint_ownership
    from slate_tpu.types import Uplo

    a = np.arange(36, dtype=np.float64).reshape(6, 6)
    s = sprint_matrix("A", a, nb=2)
    assert "A = [" in s and "6-by-6" in s
    s = sprint_matrix("L", a, uplo=Uplo.Lower)
    assert "." in s  # masked upper entries
    big = np.zeros((64, 64))
    s = sprint_matrix("B", big, edgeitems=4)
    assert "..." in s  # center elision


def test_print_ownership_and_debug_checks():
    import jax.numpy as jnp

    from conftest import cpu_devices
    from slate_tpu.parallel import from_dense
    from slate_tpu.parallel.mesh import make_mesh
    from slate_tpu.utils.debug import Debug, DebugError, check_dist, check_finite
    from slate_tpu.utils.printing import sprint_ownership

    mesh = make_mesh(2, 2, devices=cpu_devices(4))
    d = from_dense(jnp.eye(40), mesh, 8, diag_pad_one=True)
    assert "(0,0)" in sprint_ownership("A", d)
    check_dist(d)  # no-op while off
    Debug.on()
    try:
        check_dist(d)
        check_finite("x", np.ones(3))
        try:
            check_finite("bad", np.asarray([1.0, np.nan]))
            raise AssertionError("expected DebugError")
        except DebugError:
            pass
    finally:
        Debug.off()
