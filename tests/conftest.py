"""Test configuration.

Mirrors the reference's test strategy (SURVEY.md §4): run everything on
XLA:CPU with a forced 8-device host platform — the "multi-node without a
cluster" fake backend (analogue of the reference's MPI-stub serial builds and
oversubscribed single-node MPI CI, Jenkinsfile-mpi) — with float64 enabled so
numerical checks use the same 3-eps style gates as the reference tester.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_enable_x64", True)
# persistent compile cache: the suite re-compiles hundreds of CPU programs
# per run (33 min wall on one core); the disk cache cuts warm reruns
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

# The axon TPU plugin registers itself as default backend even under
# JAX_PLATFORMS=cpu; pin default placement to CPU explicitly so tests are
# hermetic and fast (the real chip is exercised by bench.py, not pytest).
try:
    _cpu0 = jax.devices("cpu")[0]
    jax.config.update("jax_default_device", _cpu0)
except RuntimeError:
    pass

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def cpu_devices(n=8):
    return jax.devices("cpu")[:n]
