"""slate_lint (ISSUE 1 tentpole) tests: each invariant check flags its
seeded violation, the shipped tree is clean, and the CLI wires exit codes
correctly.  The full driver trace runs in CI (ci/run_ci.sh); here we lint
a fast subset in-process plus the pure passes."""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import cpu_devices

from slate_tpu.analysis.jaxpr_checks import (
    check_collective_axes,
    check_comm_upcast,
    check_donation,
    check_dot_precision,
)


def _mesh_psum_jaxpr(axes):
    """Trace a psum-over-first-axis kernel on a 2x2 mesh named ``axes``."""
    from jax.sharding import Mesh, PartitionSpec as P

    from slate_tpu.parallel.comm import shard_map_compat

    mesh = Mesh(np.asarray(cpu_devices(4)).reshape(2, 2), axes)
    spec = P(*axes)
    fn = shard_map_compat(
        lambda x: jax.lax.psum(x, axes[0]),
        mesh=mesh,
        in_specs=(spec,),
        out_specs=P(None, axes[1]),
        check_vma=False,
    )
    return jax.make_jaxpr(fn)(jnp.zeros((4, 4)))


def test_flags_bad_axis_name():
    closed = _mesh_psum_jaxpr(("row", "col"))
    found = check_collective_axes(closed, ("p", "q"), "driver:toy")
    assert len(found) == 1
    assert found[0].rule == "axis-name" and "row" in found[0].message


def test_accepts_declared_axes():
    closed = _mesh_psum_jaxpr(("p", "q"))
    assert check_collective_axes(closed, ("p", "q"), "driver:toy") == []


def test_flags_missing_precision():
    closed = jax.make_jaxpr(lambda a: a @ a)(jnp.zeros((4, 4)))
    found = check_dot_precision(closed, "driver:toy")
    assert len(found) == 1 and found[0].rule == "precision"


def test_accepts_highest_precision_and_int_dots():
    closed = jax.make_jaxpr(
        lambda a: jnp.einsum("ij,jk->ik", a, a, precision=jax.lax.Precision.HIGHEST)
    )(jnp.zeros((4, 4)))
    assert check_dot_precision(closed, "driver:toy") == []
    # integer dots have no precision semantics
    closed_i = jax.make_jaxpr(lambda a: a @ a)(jnp.zeros((4, 4), jnp.int32))
    assert check_dot_precision(closed_i, "driver:toy") == []


def test_flags_silent_f64_upcast_of_comm_payload():
    def fn(x):
        return jax.lax.psum(x.astype(jnp.float64), "i")

    closed = jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(
        jnp.zeros((2, 4), jnp.float32)
    )
    found = check_comm_upcast(closed, "driver:toy")
    assert len(found) == 1 and found[0].rule == "comm-upcast"
    # an all-f64 driver psumming f64 is fine
    closed64 = jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(
        jnp.zeros((2, 4), jnp.float64)
    )
    assert check_comm_upcast(closed64, "driver:toy") == []


def test_flags_unusable_donation():
    found = check_donation(
        lambda x: x[:300, :300], (jnp.zeros((320, 320)),), (0,), "donation:toy"
    )
    assert len(found) == 1 and found[0].rule == "donation"
    # shape-preserving donation is aliasable
    assert (
        check_donation(lambda x: x * 2, (jnp.zeros((320, 320)),), (0,), "d:ok")
        == []
    )


def test_flags_second_donation_with_single_output():
    """Two same-aval donations can alias only one output buffer: the
    shared-pool matching must flag the second one."""

    def fn(x, y):
        return x + y  # one (n, n) output

    args = (jnp.zeros((16, 16)), jnp.zeros((16, 16)))
    found = check_donation(fn, args, (0, 1), "donation:toy2")
    assert len(found) == 1 and found[0].rule == "donation"


def test_shard_map_compat_rejects_unknown_kwarg():
    import pytest as _pytest

    from jax.sharding import Mesh, PartitionSpec as P

    from slate_tpu.parallel.comm import shard_map_compat

    mesh = Mesh(np.asarray(cpu_devices(4)).reshape(2, 2), ("p", "q"))
    with _pytest.raises(TypeError, match="check_vm"):
        shard_map_compat(
            lambda x: x,
            mesh=mesh,
            in_specs=(P("p", "q"),),
            out_specs=P("p", "q"),
            check_vm=False,  # typo: must fail fast, not silently drop
        )


def test_loop_audit_one_scope_does_not_mask_second_loop():
    """A properly scoped loop must not hide a second, unscoped loop."""
    from slate_tpu.analysis.jaxpr_checks import check_loop_audit
    from slate_tpu.parallel.comm import audit_scope, comm_audit, psum_a

    def two_loops(x):
        with audit_scope(3):
            x = jax.lax.fori_loop(0, 3, lambda i, a: a + psum_a(a, "i"), x)
        # second loop: audited wrapper but NO scope
        return jax.lax.fori_loop(0, 5, lambda i, a: a + psum_a(a, "i"), x)

    with comm_audit() as recs:
        closed = jax.make_jaxpr(jax.vmap(two_loops, axis_name="i"))(
            jnp.zeros((2, 4))
        )
    found = check_loop_audit(closed, list(recs), "driver:toy")
    assert len(found) == 1 and found[0].rule == "loop-audit"


def test_staged_potrf_donation_contract_clean():
    """The (fixed) staged left-looking potrf path: both its donating jit
    stages must be aliasable (the float64[320,320] warning regression)."""
    from slate_tpu.analysis.registry import DONATIONS, make_ctx

    ctx = make_ctx()
    for name in ("potrf_ll_staged_step", "potrf_ll_staged_finale"):
        fn, args, donate = DONATIONS[name].build(ctx)
        assert check_donation(fn, args, donate, name) == [], name


def test_grid_invariants_clean():
    from slate_tpu.analysis.grid_checks import run_grid_checks

    assert run_grid_checks() == []


def test_ast_pass_clean_or_waived():
    from slate_tpu.analysis.ast_checks import check_tree
    from slate_tpu.analysis.waivers import load_waivers

    waivers = load_waivers()
    unwaived = [f for f in check_tree() if waivers.match(f) is None]
    assert unwaived == [], [f.render() for f in unwaived]


def test_ast_pass_flags_bad_kwarg(tmp_path):
    from slate_tpu.analysis.ast_checks import _installed_signatures, check_file

    bad = tmp_path / "bad_kernel.py"
    bad.write_text(
        "from jax.experimental.shard_map import shard_map\n"
        "import jax.lax as lax\n"
        "def k(f, mesh, spec, x):\n"
        "    y = lax.psum(x, 'p')\n"
        "    return shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec,\n"
        "                     totally_bogus_kwarg=False)(y)\n"
    )
    found = check_file(str(bad), "toy/bad_kernel.py", _installed_signatures())
    rules = sorted(f.rule for f in found)
    assert rules == ["ast-kwargs", "ast-raw-collective", "ast-shard-map-import"]
    kw = [f for f in found if f.rule == "ast-kwargs"][0]
    assert "totally_bogus_kwarg" in kw.message


def test_ast_pass_rep_aliases_only_via_compat(tmp_path):
    """check_vma/check_rep are valid ONLY through shard_map_compat; a raw
    shard_map call with either spelling is the API-drift bug itself, and a
    comm re-import of raw shard_map is flagged too."""
    from slate_tpu.analysis.ast_checks import _installed_signatures, check_file

    ok = tmp_path / "ok_kernel.py"
    ok.write_text(
        "def k(shard_map_compat, f, mesh, spec, x):\n"
        "    a = shard_map_compat(f, mesh=mesh, in_specs=spec, out_specs=spec,\n"
        "                         check_vma=False)(x)\n"
        "    return shard_map_compat(f, mesh=mesh, in_specs=spec, out_specs=spec,\n"
        "                            check_rep=False)(a)\n"
    )
    assert check_file(str(ok), "toy/ok_kernel.py", _installed_signatures()) == []

    bad = tmp_path / "bad_kernel2.py"
    bad.write_text(
        "from slate_tpu.parallel.comm import shard_map\n"
        "def k(f, mesh, spec, x):\n"
        "    return shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec,\n"
        "                     check_vma=False)(x)\n"
    )
    found = check_file(str(bad), "toy/bad_kernel2.py", _installed_signatures())
    rules = sorted(f.rule for f in found)
    assert "ast-shard-map-import" in rules
    # on an installed JAX without check_vma, the raw call is kwarg drift
    from slate_tpu.parallel.comm import _SHARD_MAP_KW

    if "check_vma" not in _SHARD_MAP_KW:
        assert "ast-kwargs" in rules


def test_ast_pass_catches_aliased_collectives(tmp_path):
    """Aliased imports must not smuggle raw collectives past the rule."""
    from slate_tpu.analysis.ast_checks import _installed_signatures, check_file

    f = tmp_path / "sneaky.py"
    f.write_text(
        "from jax.lax import psum as p\n"
        "import jax.lax as L\n"
        "def k(x):\n"
        "    return p(x, 'p') + L.all_gather(x, 'q')\n"
    )
    found = check_file(str(f), "toy/sneaky.py", _installed_signatures())
    msgs = sorted(x.message for x in found if x.rule == "ast-raw-collective")
    assert len(msgs) == 2 and "psum" in msgs[1] and "all_gather" in msgs[0], msgs


def test_shard_map_compat_rejects_conflicting_aliases():
    import pytest as _pytest

    from jax.sharding import Mesh, PartitionSpec as P

    from slate_tpu.parallel.comm import shard_map_compat

    mesh = Mesh(np.asarray(cpu_devices(4)).reshape(2, 2), ("p", "q"))
    with _pytest.raises(TypeError, match="conflicting"):
        shard_map_compat(
            lambda x: x,
            mesh=mesh,
            in_specs=(P("p", "q"),),
            out_specs=P("p", "q"),
            check_vma=True,
            check_rep=False,
        )


def test_lint_traces_summa_clean():
    """One registered driver end-to-end in-process: trace + all jaxpr
    checks on the real SUMMA kernel come back clean."""
    from slate_tpu.analysis.jaxpr_checks import check_loop_audit
    from slate_tpu.analysis.registry import REGISTRY, make_ctx
    from slate_tpu.parallel.comm import comm_audit

    ctx = make_ctx()
    fn, args = REGISTRY["gemm_summa_c"].build(ctx)
    jax.clear_caches()
    with comm_audit() as recs:
        closed = jax.make_jaxpr(fn)(*args)
    findings = (
        check_collective_axes(closed, ("p", "q"), "driver:gemm_summa_c")
        + check_dot_precision(closed, "driver:gemm_summa_c")
        + check_comm_upcast(closed, "driver:gemm_summa_c")
        + check_loop_audit(closed, list(recs), "driver:gemm_summa_c")
    )
    assert findings == [], [f.render() for f in findings]


def test_cli_exit_codes():
    """CLI: clean (fast passes) exits 0; a seeded unusable donation exits 1.
    --skip-trace keeps this at import cost rather than 24 driver traces."""
    base = [sys.executable, "-m", "slate_tpu.analysis.lint", "--skip-trace"]
    r = subprocess.run(base, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    r2 = subprocess.run(
        base + ["--seed-violation", "donation"],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert r2.returncode == 1, r2.stdout + r2.stderr
    assert "donation" in r2.stdout


def test_ast_pass_flags_masked_psum_bcast(tmp_path):
    """ISSUE 5: the masked-psum broadcast idiom outside comm.py is a
    finding (it pays ~2x a rooted broadcast's bytes and bypasses
    Option.BcastImpl); routing through the engine wrappers is clean."""
    from slate_tpu.analysis.ast_checks import (
        _installed_signatures, check_file, check_source,
    )

    bad = tmp_path / "masked.py"
    bad.write_text(
        "from slate_tpu.parallel.comm import psum_a\n"
        "import jax.numpy as jnp\n"
        "def k(x, me, owner):\n"
        "    return psum_a(jnp.where(me == owner, x, 0), 'q')\n"
    )
    found = check_file(str(bad), "toy/masked.py", _installed_signatures())
    rules = [f.rule for f in found]
    assert rules == ["ast-masked-psum-bcast"], found

    ok = (
        "from slate_tpu.parallel.comm import bcast_from_col, psum_a\n"
        "import jax.numpy as jnp\n"
        "def k(x, me, owner, masked):\n"
        "    a = bcast_from_col(jnp.where(me == owner, x, 0), owner)\n"
        "    return a + psum_a(masked, 'q')\n"  # pre-masked var: a reduction
    )
    assert check_source(ok, "toy/ok.py", _installed_signatures()) == []

    # inside parallel/comm.py the idiom IS the psum lowering itself
    in_comm = (
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "def bcast(x, owner):\n"
        "    me = lax.axis_index('q')\n"
        "    return lax.psum(jnp.where(me == owner, x, 0), 'q')\n"
    )
    assert check_source(in_comm, "slate_tpu/parallel/comm.py",
                        _installed_signatures()) == []


def test_loop_audit_counts_switch_branches_once():
    """The broadcast engine dispatches rooted hop schedules through
    lax.switch: exactly one branch executes per trip, so the loop-audit
    eqn count must take the max over cond branches, not their sum —
    otherwise every engine-lowered driver would need q x the audit
    records it can honestly emit."""
    from slate_tpu.analysis.jaxpr_checks import (
        check_loop_audit, count_loop_collectives,
    )
    from slate_tpu.parallel.comm import audit_scope, comm_audit, psum_a

    def body(i, acc):
        # 3 branches, each with ONE collective; one audited record is
        # emitted per loop step by the shared recording below
        def br(k):
            return lambda a: a + jax.lax.psum(a * k, "i")

        return acc + jax.lax.switch(i % 3, [br(0), br(1), br(2)], acc)

    def fn(x):
        with audit_scope(3):
            # the engine's pattern: record once per hop, outside the switch
            _ = psum_a(x, "i")  # stands in for the per-hop _rec call
            return jax.lax.fori_loop(0, 3, body, x)

    with comm_audit() as recs:
        closed = jax.make_jaxpr(jax.vmap(fn, axis_name="i"))(jnp.zeros((2, 4)))
    # 3 branches x 1 collective counts as ONE executed collective
    assert count_loop_collectives(closed) == 1
    assert check_loop_audit(closed, list(recs), "driver:toy") == []


def test_lint_cli_masked_psum_seed():
    """--seed-violation masked-psum works with --skip-trace and exits 1."""
    base = [sys.executable, "-m", "slate_tpu.analysis.lint", "--skip-trace"]
    r = subprocess.run(
        base + ["--seed-violation", "masked-psum"],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 1, r.stdout + r.stderr
    assert "ast-masked-psum-bcast" in r.stdout
