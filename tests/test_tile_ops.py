"""Tile kernel tests (analog of unit_test/test_Tile_kernels.cc) — each TPU
kernel vs the numpy semantics of the reference CUDA kernel."""

import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu import ops
from slate_tpu.types import Diag, Norm, NormScope, Uplo


def test_geadd(rng):
    a, b = rng.standard_normal((5, 4)), rng.standard_normal((5, 4))
    out = ops.geadd(2.0, jnp.asarray(a), 3.0, jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), 2 * a + 3 * b)


def test_tzadd(rng):
    a, b = rng.standard_normal((4, 4)), rng.standard_normal((4, 4))
    out = np.asarray(ops.tzadd(Uplo.Lower, 2.0, jnp.asarray(a), 1.0, jnp.asarray(b)))
    exp = np.where(np.tril(np.ones((4, 4), bool)), 2 * a + b, b)
    np.testing.assert_allclose(out, exp)


def test_gecopy_convert(rng):
    a = rng.standard_normal((3, 3))
    out = ops.gecopy(jnp.asarray(a), jnp.float32)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), a.astype(np.float32))


def test_gescale_row_col(rng):
    a = rng.standard_normal((3, 4))
    r, c = rng.random(3), rng.random(4)
    out = ops.gescale_row_col(jnp.asarray(r), jnp.asarray(c), jnp.asarray(a))
    np.testing.assert_allclose(np.asarray(out), np.diag(r) @ a @ np.diag(c))


def test_geset_tzset():
    out = np.asarray(ops.geset(1.0, 5.0, (3, 4), jnp.float64))
    assert out[0, 0] == 5 and out[0, 1] == 1
    a = jnp.zeros((3, 3))
    out2 = np.asarray(ops.tzset(Uplo.Upper, 2.0, 7.0, a))
    assert out2[0, 0] == 7 and out2[0, 2] == 2 and out2[2, 0] == 0


def test_genorm(rng):
    a = rng.standard_normal((6, 4))
    aj = jnp.asarray(a)
    assert np.isclose(float(ops.genorm(Norm.Max, aj)), np.abs(a).max())
    assert np.isclose(float(ops.genorm(Norm.One, aj)), np.abs(a).sum(0).max())
    assert np.isclose(float(ops.genorm(Norm.Inf, aj)), np.abs(a).sum(1).max())
    assert np.isclose(float(ops.genorm(Norm.Fro, aj)), np.linalg.norm(a))
    np.testing.assert_allclose(
        np.asarray(ops.genorm(Norm.One, aj, NormScope.Columns)), np.abs(a).sum(0)
    )


def test_henorm(rng):
    a = rng.standard_normal((5, 5)) + 1j * rng.standard_normal((5, 5))
    full = np.tril(a) + np.tril(a, -1).conj().T
    got = float(ops.henorm(Norm.One, jnp.asarray(a), Uplo.Lower))
    assert np.isclose(got, np.abs(full).sum(0).max())
    got_f = float(ops.henorm(Norm.Fro, jnp.asarray(a), Uplo.Lower))
    assert np.isclose(got_f, np.linalg.norm(full))


def test_trnorm(rng):
    a = rng.standard_normal((4, 4))
    got = float(ops.trnorm(Norm.Inf, jnp.asarray(a), Uplo.Upper))
    assert np.isclose(got, np.abs(np.triu(a)).sum(1).max())


def test_transpose(rng):
    a = rng.standard_normal((3, 5)) + 1j * rng.standard_normal((3, 5))
    np.testing.assert_allclose(np.asarray(ops.transpose(jnp.asarray(a), conj=True)), a.conj().T)


def test_matmul_fallback(rng):
    # CPU path goes through dot_general with HIGHEST precision
    a, b = rng.standard_normal((64, 32)), rng.standard_normal((32, 48))
    out = ops.matmul(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-12)


def test_pallas_ops_gate_and_fallback():
    # on CPU the Pallas twins must gate off and tile_ops falls back to XLA
    import jax
    import jax.numpy as jnp

    from slate_tpu.ops.pallas_ops import use_pallas_tiles
    from slate_tpu.ops.tile_ops import transpose

    a = jnp.arange(2 * 4 * 4, dtype=jnp.float32).reshape(2, 4, 4)
    if jax.default_backend() != "tpu":
        assert not use_pallas_tiles(a)
    out = np.asarray(transpose(a))
    assert (out == np.swapaxes(np.asarray(a), -1, -2)).all()
