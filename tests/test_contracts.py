"""Contract-matrix autoprover (ISSUE 16 tentpole): one proven cell per
contract class at unique shapes, the prover's failure modes, structural
completeness of the real registry, and waiver hygiene/staleness.

The full 54-cell matrix over the real registry runs in CI
(``python -m slate_tpu.analysis.contracts`` in ci/run_ci.sh); here we
drive the prover over tiny vmap kernels.  Shapes are UNIQUE within the
suite so every audited trace is fresh — the prover's ``clear_caches``
(needed for full-registry runs) is skipped to keep the shared tier-1
compile cache warm."""

import jax
import jax.numpy as jnp

from slate_tpu.analysis.contracts import (
    _Prover,
    check_registry_completeness,
)
from slate_tpu.analysis.registry import Contract, DriverSpec
from slate_tpu.types import Option


class _LeanProver(_Prover):
    """Trace WITHOUT jax.clear_caches(): test kernels use unique shapes,
    so their traces (and the comm-audit records) are fresh anyway."""

    def trace(self, name):
        if name not in self._traced:
            from slate_tpu.parallel.comm import comm_audit

            fn, args = self._build(name)
            with comm_audit() as records:
                closed = jax.make_jaxpr(fn)(*args)
            self._traced[name] = (str(closed.jaxpr), list(records))
        return self._traced[name]


def _vmap_driver(shape, kernel):
    """A registry-shaped build fn: vmap the kernel over a named axis."""

    def build(ctx):
        x = jnp.zeros((2,) + shape)
        return jax.vmap(kernel, axis_name="i"), (x,)

    return build


def _spec(name, build, contracts=()):
    return DriverSpec(name, build, (), tuple(contracts))


def _prover(registry):
    return _LeanProver(ctx=None, registry=registry)


def test_proves_off_jaxpr_identical_with_base():
    from slate_tpu.parallel.comm import psum_a

    k = lambda t: psum_a(t, "i") * 2.0  # noqa: E731
    reg = {
        "base": _spec("base", _vmap_driver((3, 38), k)),
        "twin": _spec("twin", _vmap_driver((3, 38), k), (
            Contract(Option.Checkpoint, "off_jaxpr_identical", "base"),)),
    }
    p = _prover(reg)
    assert p.prove("twin", reg["twin"].contracts[0]) == []


def test_flags_off_jaxpr_divergence():
    from slate_tpu.parallel.comm import psum_a

    reg = {
        "base": _spec("base", _vmap_driver(
            (3, 42), lambda t: psum_a(t, "i"))),
        "notwin": _spec("notwin", _vmap_driver(
            (3, 42), lambda t: psum_a(t, "i") + 1.0), (
            Contract(Option.Checkpoint, "off_jaxpr_identical", "base"),)),
    }
    p = _prover(reg)
    found = p.prove("notwin", reg["notwin"].contracts[0])
    assert len(found) == 1 and found[0].rule == "contract-off-jaxpr"


def test_proves_off_jaxpr_self_under_off_context():
    # no base: the cell re-traces under the option's off-forcing context
    # (NumMonitor off) and the jaxpr must be untouched
    from slate_tpu.parallel.comm import psum_a

    reg = {
        "plain": _spec("plain", _vmap_driver(
            (3, 46), lambda t: psum_a(t, "i")), (
            Contract(Option.NumMonitor, "off_jaxpr_identical"),)),
    }
    p = _prover(reg)
    assert p.prove("plain", reg["plain"].contracts[0]) == []


def test_proves_zero_extra_collectives_and_flags_extra():
    from slate_tpu.parallel.comm import psum_a

    reg = {
        "base": _spec("base", _vmap_driver(
            (3, 50), lambda t: psum_a(t, "i"))),
        "samecomm": _spec("samecomm", _vmap_driver(
            (3, 50), lambda t: psum_a(t * 3.0, "i") - 1.0), (
            Contract(Option.NumMonitor, "zero_extra_collectives", "base"),)),
        "extracomm": _spec("extracomm", _vmap_driver(
            (3, 50), lambda t: psum_a(psum_a(t, "i"), "i")), (
            Contract(Option.NumMonitor, "zero_extra_collectives", "base"),)),
    }
    p = _prover(reg)
    assert p.prove("samecomm", reg["samecomm"].contracts[0]) == []
    # the audit actually recorded something — the proof is not vacuous
    assert p.trace("samecomm")[1] and p.trace("base")[1]
    found = p.prove("extracomm", reg["extracomm"].contracts[0])
    assert len(found) == 1
    assert found[0].rule == "contract-extra-collectives"
    assert "1 extra" in found[0].message


def test_proves_bytes_invariant_across_different_record_shapes():
    # the variant moves the SAME total volume in two half-size hops:
    # bytes_invariant proves, zero_extra (rightly) would not
    from slate_tpu.parallel.comm import psum_a

    def whole(t):
        return psum_a(t, "i")

    def halves(t):
        lo = psum_a(t[:, :27], "i")
        hi = psum_a(t[:, 27:], "i")
        return jnp.concatenate([lo, hi], axis=1)

    reg = {
        "whole": _spec("whole", _vmap_driver((3, 54), whole)),
        "halves": _spec("halves", _vmap_driver((3, 54), halves), (
            Contract(Option.Lookahead, "bytes_invariant", "whole"),
            Contract(Option.Lookahead, "zero_extra_collectives", "whole"),)),
    }
    p = _prover(reg)
    assert p.prove("halves", reg["halves"].contracts[0]) == []
    assert p.trace("halves")[1] and p.trace("whole")[1]
    found = p.prove("halves", reg["halves"].contracts[1])
    assert len(found) == 1 and found[0].rule == "contract-extra-collectives"


def test_flags_bytes_divergence():
    from slate_tpu.parallel.comm import psum_a

    reg = {
        "small": _spec("small", _vmap_driver(
            (3, 58), lambda t: psum_a(t[:, :29], "i"))),
        "big": _spec("big", _vmap_driver(
            (3, 58), lambda t: psum_a(t, "i")[:, :29]), (
            Contract(Option.BcastImpl, "bytes_invariant", "small"),)),
    }
    p = _prover(reg)
    found = p.prove("big", reg["big"].contracts[0])
    assert len(found) == 1 and found[0].rule == "contract-bytes"


def test_broken_build_is_a_trace_error_finding_not_a_crash():
    def boom(ctx):
        raise RuntimeError("no such driver")

    reg = {"bad": _spec("bad", boom, (
        Contract(Option.NumMonitor, "off_jaxpr_identical"),))}
    found = _prover(reg).prove("bad", reg["bad"].contracts[0])
    assert len(found) == 1 and found[0].rule == "contract-trace-error"


# ---------------------------------------------------------------- registry


def test_real_registry_structurally_complete():
    """Every contract option consumed, every base exists, every
    naming-convention variant covered — on the SHIPPED registry."""
    from slate_tpu.analysis.registry import REGISTRY

    assert check_registry_completeness(REGISTRY) == []


def test_completeness_flags_undeclared_num_variant():
    reg = {"foo_num": _spec("foo_num", lambda ctx: None)}
    found = [f for f in check_registry_completeness(reg)
             if f.rule == "contract-undeclared"]
    assert len(found) == 1 and "NumMonitor" in found[0].message


def test_completeness_accepts_family_scoped_ckpt_declaration():
    # the *_ckpt_off entry carries the family's Checkpoint proof; the
    # *_ckpt_seg sibling is covered by family scope, a *_num sibling of
    # ANOTHER family is not
    ck = Contract(Option.Checkpoint, "off_jaxpr_identical", "bar")
    reg = {
        "bar": _spec("bar", lambda ctx: None),
        "bar_ckpt_off": _spec("bar_ckpt_off", lambda ctx: None, (ck,)),
        "bar_ckpt_seg": _spec("bar_ckpt_seg", lambda ctx: None),
    }
    assert [f for f in check_registry_completeness(reg)
            if f.rule == "contract-undeclared"] == []


def test_completeness_flags_missing_base():
    reg = {"foo": _spec("foo", lambda ctx: None, (
        Contract(Option.Lookahead, "bytes_invariant", "ghost"),))}
    found = [f for f in check_registry_completeness(reg)
             if f.rule == "contract-undeclared"]
    assert len(found) == 1 and "ghost" in found[0].message


def test_completeness_flags_unconsumed_option():
    found = check_registry_completeness({})
    assert any(f.rule == "contract-option-unconsumed"
               and "Checkpoint" in f.message for f in found)


def test_register_rejects_unknown_contract_class():
    import pytest

    from slate_tpu.analysis.registry import register

    with pytest.raises(ValueError, match="unknown contract class"):
        register("toy_bad_class", contracts=(
            Contract(Option.NumMonitor, "always_faster"),))


# ------------------------------------------------------------------ waivers


def _mk_waivers(*rows):
    from slate_tpu.analysis.waivers import Waiver, Waivers

    return Waivers([Waiver(r, p, "reason", i + 1)
                    for i, (r, p) in enumerate(rows)])


def test_waiver_hygiene_flags_unknown_rule_and_dead_driver():
    from slate_tpu.analysis.waivers import check_hygiene

    ws = _mk_waivers(
        ("spmd-divergent-collectives", "driver:real"),
        ("no-such-rule", "*"),
        ("contract-bytes", "contract:deleted_driver"),
    )
    found = check_hygiene(ws, {"real"}, set(), "w.cfg")
    assert [f.rule for f in found] == ["waiver-hygiene", "waiver-hygiene"]
    assert "no-such-rule" in found[0].message
    assert "deleted_driver" in found[1].message


def test_waiver_staleness_scoped_to_the_running_cli():
    from slate_tpu.analysis.waivers import check_stale

    ws = _mk_waivers(
        ("spmd-divergent-collectives", "driver:a"),  # lint-scope, unused
        ("contract-bytes", "contract:b"),            # contracts-scope
    )
    # a full LINT run must fail the unused lint-scope waiver only: the
    # contracts-scope waiver can legitimately go unmatched there
    found = check_stale(ws, {"spmd-divergent-collectives"}, "w.cfg")
    assert len(found) == 1 and found[0].rule == "waiver-stale"
    assert "spmd-divergent-collectives" in found[0].message


def test_used_waiver_is_not_stale():
    from slate_tpu.analysis.findings import Finding
    from slate_tpu.analysis.waivers import check_stale

    ws = _mk_waivers(("spmd-divergent-collectives", "driver:a"))
    assert ws.match(Finding(
        "spmd-divergent-collectives", "driver:a", "msg")) is not None
    assert check_stale(ws, {"spmd-divergent-collectives"}, "w.cfg") == []


def test_shipped_waiver_file_is_hygienic():
    from slate_tpu.analysis.registry import DONATIONS, REGISTRY
    from slate_tpu.analysis.waivers import check_hygiene, load_waivers

    ws = load_waivers()
    assert check_hygiene(ws, set(REGISTRY), set(DONATIONS),
                         "waivers.cfg") == []
