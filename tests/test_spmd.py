"""SPMD safety passes (ISSUE 16 tentpole): branch-divergent collectives,
ppermute bijection, donation liveness, and the broadcast engine's
hop-schedule relay proof.  Each pass flags its seeded violation
in-process and stays clean on well-formed kernels; the CLI-level
``--seed-violation`` gates (which exercise the same seeds through
``python -m slate_tpu.analysis.lint``) run in ci/run_ci.sh."""

import jax
import jax.numpy as jnp
import numpy as np

from conftest import cpu_devices

from slate_tpu.analysis.spmd import (
    _verify_schedule,
    check_branch_collectives,
    check_donation_liveness,
    check_hop_schedules,
    check_ppermute_bijection,
)


def _mesh22():
    from jax.sharding import Mesh

    return Mesh(np.asarray(cpu_devices(4)).reshape(2, 2), ("p", "q"))


def _cond_jaxpr(true_fn, false_fn, shape):
    """Trace a shard_map'd cond whose branches are the given kernels."""
    from jax.sharding import PartitionSpec as P

    from slate_tpu.parallel.comm import shard_map_compat

    def fn(x):
        def kernel(t):
            return jax.lax.cond(t.sum() > 0, true_fn, false_fn, t)

        return shard_map_compat(
            kernel,
            mesh=_mesh22(),
            in_specs=(P("p", "q"),),
            out_specs=P("p", "q"),
            check_vma=False,
        )(x)

    return jax.make_jaxpr(fn)(jnp.zeros(shape))


def test_flags_divergent_branch_collectives():
    closed = _cond_jaxpr(
        lambda t: jax.lax.psum(t, "p"),
        lambda t: jax.lax.psum(jax.lax.psum(t, "p"), "p"),
        (4, 6),
    )
    found = check_branch_collectives(closed, "driver:toy")
    assert len(found) == 1
    assert found[0].rule == "spmd-divergent-collectives"
    assert "deadlock" in found[0].message


def test_flags_branch_axis_divergence():
    # same collective COUNT but a different axis: still a divergent
    # ordered (op, axes) sequence — devices on "q" would wait forever
    closed = _cond_jaxpr(
        lambda t: jax.lax.psum(t, "p"),
        lambda t: jax.lax.psum(t, "q"),
        (4, 10),
    )
    found = check_branch_collectives(closed, "driver:toy")
    assert len(found) == 1 and found[0].rule == "spmd-divergent-collectives"


def test_accepts_uniform_branches():
    # different arithmetic, identical collective sequence: safe by
    # construction whatever the predicate does
    closed = _cond_jaxpr(
        lambda t: jax.lax.psum(t * 2.0, "p"),
        lambda t: jax.lax.psum(t, "p") + 1.0,
        (4, 14),
    )
    assert check_branch_collectives(closed, "driver:toy") == []


def _ppermute_jaxpr(perm, shape):
    from jax.sharding import PartitionSpec as P

    from slate_tpu.parallel.comm import shard_map_compat

    def fn(x):
        return shard_map_compat(
            lambda t: jax.lax.ppermute(t, "q", perm),
            mesh=_mesh22(),
            in_specs=(P("p", "q"),),
            out_specs=P("p", "q"),
            check_vma=False,
        )(x)

    return jax.make_jaxpr(fn)(jnp.zeros(shape))


def test_flags_duplicate_ppermute_destination():
    # JAX traces this silently; XLA keeps one payload and drops the rest
    closed = _ppermute_jaxpr([(0, 1), (1, 1)], (4, 18))
    found = check_ppermute_bijection(closed, {"p": 2, "q": 2}, "driver:toy")
    assert len(found) == 1
    assert found[0].rule == "spmd-ppermute-bijection"
    assert "destination" in found[0].message


def test_accepts_bijective_ppermute():
    closed = _ppermute_jaxpr([(0, 1), (1, 0)], (4, 22))
    assert check_ppermute_bijection(closed, {"p": 2, "q": 2}, "d:ok") == []


def test_engine_hop_schedules_all_valid():
    """Every ring/doubling schedule the broadcast engine can emit on the
    registry grid's axis sizes, for every root, is a proven relay."""
    assert check_hop_schedules() == []


def test_schedule_verifier_flags_dropped_device():
    # a ring that stops one hop short: device 3 never gets the payload
    hops = [[(0, 1)], [(1, 2)], [(2, 2)]]
    found = _verify_schedule("toy/broken_ring", 4, 0, hops)
    assert any("never delivers" in f.message and "[3]" in f.message
               for f in found)


def test_schedule_verifier_flags_stray_source():
    # hop 0 forwards from device 1, which does not hold the payload yet
    found = _verify_schedule("toy/stray", 4, 0, [[(1, 2)]])
    assert any("have not received the payload" in f.message for f in found)
    assert any("never delivers" in f.message for f in found)


def test_flags_read_after_donate():
    g = jax.jit(lambda t: t * 2.0, donate_argnums=(0,))

    def fn(x):
        y = g(x)
        return y + x  # x's buffer may already be reused by XLA

    closed = jax.make_jaxpr(fn)(jnp.zeros((6, 26)))
    found = check_donation_liveness(closed, "driver:toy")
    assert len(found) == 1
    assert found[0].rule == "spmd-donation-liveness"
    assert "use-after-donate" in found[0].message


def test_flags_donated_value_returned():
    g = jax.jit(lambda t: t + 1.0, donate_argnums=(0,))

    def fn(x):
        return g(x), x  # returning the donated operand to the caller

    closed = jax.make_jaxpr(fn)(jnp.zeros((6, 30)))
    found = check_donation_liveness(closed, "driver:toy")
    assert len(found) == 1 and "returned" in found[0].message


def test_accepts_dead_after_donate():
    g = jax.jit(lambda t: t * 3.0, donate_argnums=(0,))

    def fn(x):
        y = g(x)
        return y * 2.0  # x is dead after the donating call: fine

    closed = jax.make_jaxpr(fn)(jnp.zeros((6, 34)))
    assert check_donation_liveness(closed, "driver:toy") == []
